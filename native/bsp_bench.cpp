// bsp_bench: round-trip benchmark CLI for the oracle sidecar data plane.
//
// Generates a synthetic (nodes x groups) batch, ships it over the packed
// protocol, and reports per-batch latency from the native side — the number
// a Go control plane would see.
//
// Usage: bsp_bench <host> <port> [nodes] [groups] [lanes] [iters]

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <chrono>
#include <vector>

extern "C" {
struct BspClient;
BspClient* bsp_connect(const char* host, int port);
void bsp_close(BspClient*);
int bsp_ping(BspClient*);
const char* bsp_last_error(BspClient*);
int bsp_schedule(BspClient*, int32_t n, int32_t g, int32_t r,
                 const int32_t*, const int32_t*, const int32_t*,
                 const int32_t*, const uint8_t*, const uint8_t*,
                 const int32_t*, const int32_t*, const int32_t*,
                 const int32_t*, const uint8_t*, const int32_t*, uint8_t*,
                 uint8_t*, int32_t*, int32_t*, uint8_t*, int32_t*, int32_t*,
                 int32_t*, int32_t, uint32_t*);
}

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <host> <port> [nodes] [groups] [lanes] [iters]\n",
                 argv[0]);
    return 2;
  }
  const char* host = argv[1];
  int port = std::atoi(argv[2]);
  int32_t n = argc > 3 ? std::atoi(argv[3]) : 1024;
  int32_t g = argc > 4 ? std::atoi(argv[4]) : 256;
  int32_t r = argc > 5 ? std::atoi(argv[5]) : 5;
  int iters = argc > 6 ? std::atoi(argv[6]) : 10;

  BspClient* client = bsp_connect(host, port);
  if (!client) {
    std::fprintf(stderr, "connect failed\n");
    return 1;
  }
  if (bsp_ping(client) != 0) {
    std::fprintf(stderr, "ping failed: %s\n", bsp_last_error(client));
    return 1;
  }

  // synthetic cluster: uniform nodes, gangs of 4 x 1-cpu-ish members
  std::vector<int32_t> alloc(static_cast<size_t>(n) * r);
  std::vector<int32_t> requested(static_cast<size_t>(n) * r, 0);
  for (int32_t i = 0; i < n; ++i) {
    alloc[static_cast<size_t>(i) * r + 0] = 64000;   // cpu milli
    alloc[static_cast<size_t>(i) * r + 1] = 1 << 28; // mem KiB
    if (r > 3) alloc[static_cast<size_t>(i) * r + 3] = 110;  // pods
  }
  std::vector<int32_t> group_req(static_cast<size_t>(g) * r, 0);
  std::vector<int32_t> remaining(g, 4);
  for (int32_t j = 0; j < g; ++j) {
    group_req[static_cast<size_t>(j) * r + 0] = 4000;
    group_req[static_cast<size_t>(j) * r + 1] = 1 << 23;
    if (r > 3) group_req[static_cast<size_t>(j) * r + 3] = 1;
  }
  std::vector<uint8_t> fit_mask(static_cast<size_t>(g) * n, 1);
  std::vector<uint8_t> group_valid(g, 1);
  std::vector<int32_t> order(g), min_member(g, 4), scheduled(g, 0),
      matched(g, 0), creation_rank(g);
  std::vector<uint8_t> ineligible(g, 0);
  for (int32_t j = 0; j < g; ++j) order[j] = creation_rank[j] = j;

  const int32_t k_capacity = 128;
  std::vector<uint8_t> gang_feasible(g), placed(g);
  std::vector<int32_t> progress(g);
  std::vector<int32_t> assignment_nodes(static_cast<size_t>(g) * k_capacity);
  std::vector<int32_t> assignment_counts(static_cast<size_t>(g) * k_capacity);
  int32_t best = 0, k_out = 0;
  uint8_t best_exists = 0;
  uint32_t batch_seq = 0;

  double total_ms = 0, best_ms = 1e18;
  int placed_total = 0;
  for (int it = 0; it < iters + 1; ++it) {
    auto t0 = std::chrono::steady_clock::now();
    int rc = bsp_schedule(client, n, g, r, alloc.data(), requested.data(),
                          group_req.data(), remaining.data(), fit_mask.data(),
                          group_valid.data(), order.data(), min_member.data(),
                          scheduled.data(), matched.data(), ineligible.data(),
                          creation_rank.data(), gang_feasible.data(),
                          placed.data(), progress.data(), &best, &best_exists,
                          assignment_nodes.data(), assignment_counts.data(),
                          &k_out, k_capacity, &batch_seq);
    auto t1 = std::chrono::steady_clock::now();
    if (rc != 0) {
      std::fprintf(stderr, "schedule failed: %s\n", bsp_last_error(client));
      bsp_close(client);
      return 1;
    }
    double ms = std::chrono::duration<double, std::milli>(t1 - t0).count();
    if (it == 0) continue;  // first batch includes jit compile
    total_ms += ms;
    if (ms < best_ms) best_ms = ms;
    placed_total = 0;
    for (int32_t j = 0; j < g; ++j) placed_total += placed[j];
  }

  std::printf(
      "{\"nodes\": %d, \"groups\": %d, \"lanes\": %d, \"iters\": %d, "
      "\"avg_batch_ms\": %.2f, \"best_batch_ms\": %.2f, \"placed\": %d, "
      "\"k\": %d}\n",
      n, g, r, iters, total_ms / iters, best_ms, placed_total, k_out);
  bsp_close(client);
  return 0;
}
