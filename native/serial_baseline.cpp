// serial_baseline: the reference's serial per-pod PreFilter hot loop in
// C++, timed over a full 10k-pod admission — the defensible denominator for
// bench.py's vs_baseline (a Python stand-in plausibly understates a
// compiled Go loop by 10-50x).
//
// Models reference pkg/scheduler/core/core.go per scheduled pod:
//   1. findMaxPG          O(groups)  progress argmax        (core.go:701-739)
//   2. cluster feasibility O(nodes)  running left-resource sum with early
//                                    exit vs the gang's pre-allocation
//                                    (compareClusterResourceAndRequire,
//                                     core.go:595-632, getPreAllocatedResource
//                                     :774-793)
//   3. node selection      O(nodes)  first node whose leftover fits one
//                                    member (singleNodeResource +
//                                    compareResourceAndRequire, :634-699),
//                                    then commit the pod there
// The cluster FILLS as the loop runs, so scan depth grows exactly as it
// would for the reference scheduling the same workload serially.
//
// Two variants bracket the reference's cost:
//   map:   per-node unordered_map<string,int64> resource lists — the data
//          layout the Go code actually iterates (singleNodeResource builds
//          maps per node per pod). bench.py computes vs_baseline against
//          THIS one: it is the faithful model of the reference.
//   array: flat int64 lanes — an idealized lower bound no map-based
//          implementation reaches (it is this repo's oracle data layout,
//          minus the batching). Reported alongside for honesty.
//
// Usage: serial_baseline [nodes] [groups] [members] [lanes]
// Prints one JSON line.

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

using Clock = std::chrono::steady_clock;
using Map = std::unordered_map<std::string, int64_t>;

static const char* kLaneNames[] = {"cpu", "memory", "pods",
                                   "nvidia.com/gpu", "ephemeral-storage"};

struct Workload {
  int32_t n, g, m, r;
  std::vector<int64_t> alloc;    // [n][r]
  std::vector<int64_t> req;      // member request [r]
  std::vector<int32_t> min_member, scheduled, matched;
};

static Workload make_workload(int32_t n, int32_t g, int32_t m, int32_t r) {
  // mirrors bench.py build_inputs: 64-cpu/256Gi/110-pod/8-gpu nodes,
  // gangs of m members each needing 4 cpu / 8Gi / 1 gpu (+1 pod slot)
  Workload w{n, g, m, r, {}, {}, {}, {}, {}};
  const int64_t node_alloc[5] = {64000, 256LL << 30, 110, 8, 1LL << 40};
  const int64_t member_req[5] = {4000, 8LL << 30, 1, 1, 0};
  w.alloc.resize(size_t(n) * r);
  for (int32_t i = 0; i < n; ++i)
    for (int32_t l = 0; l < r; ++l) w.alloc[size_t(i) * r + l] = node_alloc[l];
  w.req.assign(member_req, member_req + r);
  w.min_member.assign(g, m);
  w.scheduled.assign(g, 0);
  w.matched.assign(g, 0);
  return w;
}

// ---------------------------------------------------------------- array --

static double run_array(Workload w) {
  const int32_t n = w.n, g = w.g, r = w.r;
  std::vector<int64_t> used(size_t(n) * r, 0);
  std::vector<int64_t> prealloc(r), running(r), left(r);
  const int64_t total_pods = int64_t(g) * w.m;
  auto t0 = Clock::now();
  for (int64_t pod = 0; pod < total_pods; ++pod) {
    // 1. findMaxPG
    int32_t best = 0, best_p = -1;
    for (int32_t gi = 0; gi < g; ++gi) {
      int32_t p =
          int32_t((int64_t(w.matched[gi] + w.scheduled[gi]) * 1000) /
                  w.min_member[gi]);
      if (w.scheduled[gi] < w.min_member[gi] && p > best_p) {
        best_p = p;
        best = gi;
      }
    }
    // gang to place this pod: round-robin through groups in order (the
    // workload arrives gang by gang); max-progress group gets percent=1.0
    int32_t gi = int32_t(pod / w.m);
    int32_t remaining = w.min_member[gi] - w.scheduled[gi];
    for (int32_t l = 0; l < r; ++l) prealloc[l] = w.req[l] * remaining;
    prealloc[2] = remaining;  // pods lane: one slot per member

    // 2. running cluster sum with early exit
    std::memset(running.data(), 0, sizeof(int64_t) * r);
    bool feasible = false;
    for (int32_t i = 0; i < n && !feasible; ++i) {
      const int64_t* a = &w.alloc[size_t(i) * r];
      const int64_t* u = &used[size_t(i) * r];
      feasible = true;
      for (int32_t l = 0; l < r; ++l) {
        int64_t lv = a[l] - u[l];
        running[l] += lv > 0 ? lv : 0;
        if (running[l] < prealloc[l]) feasible = false;
      }
    }
    (void)best;
    if (!feasible) continue;  // denied (never hits in this workload)

    // 3. first node fitting one member; commit
    for (int32_t i = 0; i < n; ++i) {
      int64_t* u = &used[size_t(i) * r];
      const int64_t* a = &w.alloc[size_t(i) * r];
      bool fits = true;
      for (int32_t l = 0; l < r; ++l)
        if (a[l] - u[l] < w.req[l]) fits = false;
      if (fits) {
        // req[] already carries the member's pod slot in the pods lane
        for (int32_t l = 0; l < r; ++l) u[l] += w.req[l];
        w.scheduled[gi]++;
        break;
      }
    }
  }
  return std::chrono::duration<double>(Clock::now() - t0).count();
}

// ------------------------------------------------------------------ map --

static double run_map(Workload w) {
  const int32_t n = w.n, g = w.g, r = w.r;
  std::vector<Map> used(n);
  Map member_req;
  for (int32_t l = 0; l < r; ++l) member_req[kLaneNames[l]] = w.req[l];
  const int64_t total_pods = int64_t(g) * w.m;
  auto t0 = Clock::now();
  for (int64_t pod = 0; pod < total_pods; ++pod) {
    int32_t best = 0, best_p = -1;
    for (int32_t gi = 0; gi < g; ++gi) {
      int32_t p =
          int32_t((int64_t(w.matched[gi] + w.scheduled[gi]) * 1000) /
                  w.min_member[gi]);
      if (w.scheduled[gi] < w.min_member[gi] && p > best_p) {
        best_p = p;
        best = gi;
      }
    }
    (void)best;
    int32_t gi = int32_t(pod / w.m);
    int32_t remaining = w.min_member[gi] - w.scheduled[gi];
    Map prealloc;
    for (auto& kv : member_req) prealloc[kv.first] = kv.second * remaining;
    prealloc["pods"] = remaining;

    // singleNodeResource builds a fresh map per node per pod in the
    // reference; mirror that allocation pattern
    Map running;
    bool feasible = false;
    for (int32_t i = 0; i < n && !feasible; ++i) {
      Map left;
      for (int32_t l = 0; l < r; ++l) {
        int64_t lv = w.alloc[size_t(i) * r + l];
        auto it = used[i].find(kLaneNames[l]);
        if (it != used[i].end()) lv -= it->second;
        left[kLaneNames[l]] = lv > 0 ? lv : 0;
      }
      for (auto& kv : left) running[kv.first] += kv.second;
      feasible = true;
      for (auto& kv : prealloc)
        if (running[kv.first] < kv.second) feasible = false;
    }
    if (!feasible) continue;

    for (int32_t i = 0; i < n; ++i) {
      bool fits = true;
      for (int32_t l = 0; l < r; ++l) {
        int64_t lv = w.alloc[size_t(i) * r + l];
        auto it = used[i].find(kLaneNames[l]);
        if (it != used[i].end()) lv -= it->second;
        if (lv < w.req[l]) fits = false;
      }
      if (fits) {
        // req[] already carries the member's pod slot in the pods lane
        for (int32_t l = 0; l < r; ++l)
          used[i][kLaneNames[l]] += w.req[l];
        w.scheduled[gi]++;
        break;
      }
    }
  }
  return std::chrono::duration<double>(Clock::now() - t0).count();
}

int main(int argc, char** argv) {
  int32_t n = argc > 1 ? std::atoi(argv[1]) : 5000;
  int32_t g = argc > 2 ? std::atoi(argv[2]) : 1000;
  int32_t m = argc > 3 ? std::atoi(argv[3]) : 10;
  int32_t r = argc > 4 ? std::atoi(argv[4]) : 5;
  if (r > 5) r = 5;
  Workload w = make_workload(n, g, m, r);
  double t_array = run_array(w);
  double t_map = run_map(w);
  int64_t pods = int64_t(g) * m;
  std::printf(
      "{\"serial_native_array_s\": %.4f, \"serial_native_map_s\": %.4f, "
      "\"pods\": %lld, \"nodes\": %d, \"per_pod_array_us\": %.2f, "
      "\"per_pod_map_us\": %.2f}\n",
      t_array, t_map, (long long)pods, n, t_array / pods * 1e6,
      t_map / pods * 1e6);
  return 0;
}
