// bsp_client: native client for the batch-scheduler oracle sidecar.
//
// Speaks the framed packed-array protocol of
// batch_scheduler_tpu/service/protocol.py:
//
//   frame := "BSO2" | u32 msg_type | u64 payload_len | payload  (LE)
//
// Exposed as a C API so it embeds anywhere the control plane lives: Go via
// cgo, C++ directly, Python via ctypes (service/native.py). This is the
// native half of the north star's data plane: the scheduler packs pod/node
// resource lanes into flat int32 buffers and ships one batch per frame —
// no per-pod marshalling anywhere on the hot path.
//
// Build: make -C native   (produces libbsp_client.so and bsp_bench)

#include <arpa/inet.h>
#include <netdb.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr char kMagic[4] = {'B', 'S', 'O', '2'};

enum MsgType : uint32_t {
  kScheduleReq = 1,
  kScheduleResp = 2,
  kRowReq = 3,
  kRowResp = 4,
  kPing = 5,
  kPong = 6,
  kError = 7,
};

struct Frame {
  uint32_t msg_type = 0;
  std::vector<uint8_t> payload;
};

class Conn {
 public:
  explicit Conn(int fd) : fd_(fd) {}
  ~Conn() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool send_all(const void* data, size_t len) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    while (len) {
      ssize_t n = ::send(fd_, p, len, 0);
      if (n <= 0) {
        if (n < 0 && errno == EINTR) continue;
        return false;
      }
      p += n;
      len -= static_cast<size_t>(n);
    }
    return true;
  }

  bool recv_all(void* data, size_t len) {
    uint8_t* p = static_cast<uint8_t*>(data);
    while (len) {
      ssize_t n = ::recv(fd_, p, len, 0);
      if (n <= 0) {
        if (n < 0 && errno == EINTR) continue;
        return false;
      }
      p += n;
      len -= static_cast<size_t>(n);
    }
    return true;
  }

 private:
  int fd_;
};

}  // namespace

extern "C" {

struct BspClient {
  Conn* conn = nullptr;
  std::string last_error;

  bool write_frame(uint32_t msg_type, const std::vector<uint8_t>& payload) {
    uint8_t header[16];
    std::memcpy(header, kMagic, 4);
    uint32_t type_le = msg_type;  // LE hosts only (TPU hosts are x86/ARM LE)
    uint64_t len_le = payload.size();
    std::memcpy(header + 4, &type_le, 4);
    std::memcpy(header + 8, &len_le, 8);
    if (!conn->send_all(header, sizeof(header))) {
      last_error = "send failed";
      return false;
    }
    if (!payload.empty() && !conn->send_all(payload.data(), payload.size())) {
      last_error = "send failed";
      return false;
    }
    return true;
  }

  bool read_frame(Frame* out) {
    uint8_t header[16];
    if (!conn->recv_all(header, sizeof(header))) {
      last_error = "recv failed";
      return false;
    }
    if (std::memcmp(header, kMagic, 4) != 0) {
      last_error = "bad frame magic";
      return false;
    }
    uint32_t msg_type;
    uint64_t length;
    std::memcpy(&msg_type, header + 4, 4);
    std::memcpy(&length, header + 8, 8);
    if (length > (256ull << 20)) {
      last_error = "oversized frame";
      return false;
    }
    out->msg_type = msg_type;
    out->payload.resize(length);
    if (length && !conn->recv_all(out->payload.data(), length)) {
      last_error = "recv failed";
      return false;
    }
    return true;
  }

  bool round_trip(uint32_t msg_type, const std::vector<uint8_t>& payload,
                  Frame* resp) {
    if (!write_frame(msg_type, payload) || !read_frame(resp)) return false;
    if (resp->msg_type == kError) {
      last_error.assign(resp->payload.begin(), resp->payload.end());
      return false;
    }
    return true;
  }
};

BspClient* bsp_connect(const char* host, int port) {
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  std::string port_str = std::to_string(port);
  if (getaddrinfo(host, port_str.c_str(), &hints, &res) != 0) return nullptr;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, 1 /*TCP_NODELAY*/, &one, sizeof(one));
  auto* client = new BspClient();
  client->conn = new Conn(fd);
  return client;
}

void bsp_close(BspClient* c) {
  if (!c) return;
  delete c->conn;
  delete c;
}

const char* bsp_last_error(BspClient* c) {
  return c ? c->last_error.c_str() : "null client";
}

int bsp_ping(BspClient* c) {
  Frame resp;
  if (!c->round_trip(kPing, {}, &resp)) return -1;
  return resp.msg_type == kPong ? 0 : -1;
}

static void append(std::vector<uint8_t>* buf, const void* data, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  buf->insert(buf->end(), p, p + len);
}

// One oracle batch. All arrays row-major little-endian; outputs sized by the
// caller: gang_feasible/placed/progress are [g]; assignment_* are
// [g * k_capacity] with the actual K written to k_out (K <= k_capacity
// required, server K is min(128, padded nodes)). fit_mask carries
// mask_rows rows of n (1 = broadcast row, the no-selector fast path that
// keeps the frame small; g = per-group selector masks).
int bsp_schedule(BspClient* c, int32_t n, int32_t g, int32_t r,
                 int32_t mask_rows,
                 const int32_t* alloc, const int32_t* requested,
                 const int32_t* group_req, const int32_t* remaining,
                 const uint8_t* fit_mask, const uint8_t* group_valid,
                 const int32_t* order, const int32_t* min_member,
                 const int32_t* scheduled, const int32_t* matched,
                 const uint8_t* ineligible, const int32_t* creation_rank,
                 uint8_t* gang_feasible, uint8_t* placed, int32_t* progress,
                 int32_t* best, uint8_t* best_exists,
                 int32_t* assignment_nodes, int32_t* assignment_counts,
                 int32_t* k_out, int32_t k_capacity, uint32_t* batch_seq) {
  if (mask_rows != 1 && mask_rows != g) {
    c->last_error = "mask_rows must be 1 or g";
    return -1;
  }
  std::vector<uint8_t> payload;
  payload.reserve(16 + static_cast<size_t>(n) * r * 8 +
                  static_cast<size_t>(g) * (r * 4 + 22) +
                  static_cast<size_t>(mask_rows) * n);
  uint32_t counts[4] = {static_cast<uint32_t>(n), static_cast<uint32_t>(g),
                        static_cast<uint32_t>(r),
                        static_cast<uint32_t>(mask_rows)};
  append(&payload, counts, sizeof(counts));
  append(&payload, alloc, static_cast<size_t>(n) * r * 4);
  append(&payload, requested, static_cast<size_t>(n) * r * 4);
  append(&payload, group_req, static_cast<size_t>(g) * r * 4);
  append(&payload, remaining, static_cast<size_t>(g) * 4);
  append(&payload, fit_mask, static_cast<size_t>(mask_rows) * n);
  append(&payload, group_valid, static_cast<size_t>(g));
  append(&payload, order, static_cast<size_t>(g) * 4);
  append(&payload, min_member, static_cast<size_t>(g) * 4);
  append(&payload, scheduled, static_cast<size_t>(g) * 4);
  append(&payload, matched, static_cast<size_t>(g) * 4);
  append(&payload, ineligible, static_cast<size_t>(g));
  append(&payload, creation_rank, static_cast<size_t>(g) * 4);

  Frame resp;
  if (!c->round_trip(kScheduleReq, payload, &resp)) return -1;
  if (resp.msg_type != kScheduleResp) {
    c->last_error = "unexpected response type";
    return -1;
  }
  const uint8_t* p = resp.payload.data();
  size_t avail = resp.payload.size();
  if (avail < 17) {
    c->last_error = "short response";
    return -1;
  }
  uint32_t resp_g, resp_k;
  std::memcpy(&resp_g, p, 4);
  std::memcpy(&resp_k, p + 4, 4);
  std::memcpy(best, p + 8, 4);
  *best_exists = p[12];
  std::memcpy(batch_seq, p + 13, 4);
  p += 17;
  avail -= 17;
  if (resp_g != static_cast<uint32_t>(g) ||
      resp_k > static_cast<uint32_t>(k_capacity)) {
    c->last_error = "response shape mismatch";
    return -1;
  }
  size_t need = static_cast<size_t>(g) * 2 + static_cast<size_t>(g) * 4 +
                static_cast<size_t>(g) * resp_k * 8;
  if (avail != need) {
    c->last_error = "response size mismatch";
    return -1;
  }
  std::memcpy(gang_feasible, p, g);
  p += g;
  std::memcpy(placed, p, g);
  p += g;
  std::memcpy(progress, p, static_cast<size_t>(g) * 4);
  p += static_cast<size_t>(g) * 4;
  std::memcpy(assignment_nodes, p, static_cast<size_t>(g) * resp_k * 4);
  p += static_cast<size_t>(g) * resp_k * 4;
  std::memcpy(assignment_counts, p, static_cast<size_t>(g) * resp_k * 4);
  *k_out = static_cast<int32_t>(resp_k);
  return 0;
}

// Fetch one (group) row of "capacity" (kind=0) or "scores" (kind=1) from the
// connection's last batch. Writes up to capacity int32s, count to n_out.
int bsp_row(BspClient* c, int32_t kind, int32_t group_index,
            uint32_t batch_seq, int32_t* out, int32_t capacity,
            int32_t* n_out) {
  std::vector<uint8_t> payload(9);
  payload[0] = static_cast<uint8_t>(kind);
  uint32_t g_le = static_cast<uint32_t>(group_index);
  std::memcpy(payload.data() + 1, &g_le, 4);
  std::memcpy(payload.data() + 5, &batch_seq, 4);
  Frame resp;
  if (!c->round_trip(kRowReq, payload, &resp)) return -1;
  if (resp.msg_type != kRowResp) {
    c->last_error = "unexpected response type";
    return -1;
  }
  size_t count = resp.payload.size() / 4;
  if (count > static_cast<size_t>(capacity)) {
    c->last_error = "row larger than buffer";
    return -1;
  }
  std::memcpy(out, resp.payload.data(), count * 4);
  *n_out = static_cast<int32_t>(count);
  return 0;
}

}  // extern "C"
