# Build/test/bench entry points — the analog of the reference's Makefile
# (vet + static build + image targets, reference Makefile:23-45). Python has
# no link step; "build" here means byte-compile + native client build, and
# "vet" is a strict syntax/import sweep.

PY ?= python

.PHONY: all build vet analyze stamp-coupling test test-cpu test-tier1 bench bench-scan bench-pipeline bench-delta bench-policy bench-whatif bench-capacity bench-slo bench-coalesce bench-failover bench-sharding bench-xl bench-regress validate-artifacts native ladder dryrun clean version tpu-artifacts http-e2e serial-e2e trace-demo replay-gate

all: vet analyze native test bench-regress bench-capacity bench-slo bench-coalesce bench-failover validate-artifacts

build: vet analyze native

# go-vet analog, part 1: byte-compile every module, fail on syntax errors
# (the semantic half is `analyze` below — together they are this repo's
# equivalent of the reference Makefile's vet line)
vet:
	$(PY) -m compileall -q batch_scheduler_tpu tests benchmarks bench.py __graft_entry__.py

# go-vet analog, part 2: the in-repo invariant analyzer suite
# (docs/static_analysis.md) — guarded-by lock discipline, jit purity +
# donation discipline, formula-coupling fingerprints, the BST_* knob
# registry, MsgType/metric exhaustiveness. Pure-AST, no jax import,
# budgeted well under 30s; exit 1 on any finding. The runtime half is
# BST_LOCKCHECK=1 (armed in the chaos/fuzz suites), not a make target.
analyze:
	$(PY) -m batch_scheduler_tpu.analysis

# after an INTENTIONAL change to a declared change-together formula group:
# verify bit-identity (bench-policy / bench-xl / replay-gate), then stamp
stamp-coupling:
	$(PY) -m batch_scheduler_tpu.analysis --stamp-coupling

# the native C++ sidecar client + bench harness
native:
	$(MAKE) -C native

# full suite (CPU-mesh conftest handles multi-device paths), slow
# widening matrices included
test:
	$(PY) -m pytest tests/ -q

test-cpu:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q

# the tier-1 gate filter: excludes @pytest.mark.slow (compile-heavy
# shard_map widening matrices) so the suite fits the CI wall-clock
# budget; run `pytest -m slow` for the excluded set
test-tier1:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# headline benchmark on the default platform (one JSON line)
bench:
	$(PY) bench.py

# scan-vs-scoring split + wavefront-scan stats (the SCAN_SPLIT artifact:
# scan fraction, waves-per-batch, sequential-step count) — tracks the
# scan-fraction trajectory per round; BST_SCAN_WAVE overrides the width
bench-scan:
	$(PY) benchmarks/scan_split.py

# overlapped-batch pipeline CI gate (CPU): window-2 pipelined vs steady
# (fails if pipelined exceeds steady by >5% — the BENCH_r05 regression),
# delta snapshot packing >= 2x + bit-identical, dispatch-ahead plan
# identity under mid-flight invalidation, compile-warmer hit on a bucket
# transition (docs/pipelining.md)
bench-pipeline:
	JAX_PLATFORMS=cpu $(PY) benchmarks/pipeline_gate.py

# device-resident state CI gate (CPU): a churned refresh via jit'd
# scatter-updates must beat the host full-repack refresh path at the
# 5k-node/10k-pod shape, plan digests bit-identical across
# delta-applied / keyframe-resynced / full-repack state (local AND over
# the wire), and a forced generation mismatch must resync from a
# keyframe (docs/pipelining.md "Device-resident state")
bench-delta:
	JAX_PLATFORMS=cpu $(PY) benchmarks/delta_gate.py

# BASELINE.json measurement ladder, configs 1-6 (asserts regressions)
ladder:
	$(PY) benchmarks/ladder.py

# pallas-kernel-on-hardware proof (skips with rc=1 off-TPU)
smoke-tpu:
	$(PY) benchmarks/tpu_smoke.py

# config-2-scale e2e over the HTTP control plane with a forced gateway
# restart mid-run (CPU-only: measures the wire, not the oracle)
http-e2e:
	$(PY) benchmarks/http_e2e.py

# the apples-to-apples denominator: the same framework on the serial
# (reference-parity) scorer at a scale where one run is ~1-2 minutes
serial-e2e:
	$(PY) benchmarks/serial_e2e.py

# schedule-trace pipeline CI gate: short sim with tracing against a real
# sidecar; validates the Chrome-trace JSON loads, client+server spans
# stitch under one trace ID, and /debug/decisions serves placed+denied
# blame records — fails on schema drift (docs/observability.md)
trace-demo:
	JAX_PLATFORMS=cpu $(PY) benchmarks/trace_demo.py

# policy-engine CI gate (CPU, 8-device virtual mesh): zero-policy plans
# bit-identical to the pre-policy scan on the steady/wavefront/sharded
# rungs, the vectorized preemption pass bounded at 10% of the
# [G=128, N=1024] steady batch, and a policy-rung audit record replaying
# bit-identically on steady + cpu-ladder (docs/policy.md)
bench-policy:
	$(PY) benchmarks/policy_gate.py

# explain/what-if observatory CI gate (CPU): each counterfactual kind's
# forked what-if plan bit-identical to a cluster that actually applied
# it; an interleaved what-if storm leaves the live device-resident
# holder's generation/digests untouched; explain's blame byte-matches
# the flight recorder on every denied gang of a recorded run; warm
# what-if query <= 2x one steady batch at the 5k-node/10k-pod bucket
# (docs/observability.md "Explain" / "What-if")
bench-whatif:
	JAX_PLATFORMS=cpu $(PY) benchmarks/whatif_gate.py

# capacity-observatory CI gate (CPU): the analytics hook's amortized
# cost <= 2% of the 5k-node/10k-pod steady stream, an offline `capacity`
# replay of a recorded sim bit-identical to the live series, per-tenant
# shares summing <= 1 on every lane of every sample, and a chaos latency
# storm flipping burn:batch to breach (recovery clears it) with the
# bst_slo_burn_rate gauges elevated (docs/observability.md "Capacity
# observatory & burn-rate alerts")
bench-capacity:
	JAX_PLATFORMS=cpu $(PY) benchmarks/capacity_gate.py

# gang-lifecycle / placement-SLO CI gate (CPU): the ledger hot path
# costing <=1% of the 5120-node steady batch under a worst-case deny
# storm (coalescing holding every gang to a bounded ring), the live
# /debug/gangs snapshot byte-identical to the offline audit-ring re-fold
# (the `timeline --audit-dir` path), and a real deny storm flipping
# burn:ttp to breach against a tightened BST_SLO_TTP_P99_S — recovery
# sliding the fast window clear (docs/observability.md "Gang lifecycle
# & placement SLOs")
bench-slo:
	JAX_PLATFORMS=cpu $(PY) benchmarks/slo_gate.py

# multi-tenant coalescer CI gate (CPU): 8 concurrent clients through one
# coalescing sidecar vs the 8-dedicated-sidecars time-sliced equivalent —
# per-tenant plan digests bit-identical on BOTH merge lowerings (span
# re-dispatch + block-diagonal mega-batch), a starved small tenant's p95
# queue wait bounded under a whale storm, and the aggregate-throughput
# floor (host-fingerprint-aware: a 1-core host has nothing to overlap
# with, so it demotes to a parity band and the measured speedup rides
# the envelope for the COALESCE_<tag> hardware capture)
# (docs/multitenancy.md)
bench-coalesce:
	JAX_PLATFORMS=cpu $(PY) benchmarks/coalesce_gate.py

# sidecar HA CI gate (CPU): crash-recovery drills — mid-storm graceful
# drain (zero client-visible errors, clean flush report, DRAINING
# promotions counted) and a ChaosProxy kill of the primary (clients trip
# the breaker, promote to the warm standby, finish with plan digests
# bit-identical to an uninterrupted control run: zero lost plans, zero
# double-applied plans), time-to-recovery bounded, breaker/failover
# metrics truthful, and warmth replication asserted (first post-failover
# shape is a compile-warmer HIT on the standby)
# (docs/resilience.md "High availability")
bench-failover:
	JAX_PLATFORMS=cpu $(PY) benchmarks/failover_gate.py

# audit/replay/health CI gate (CPU): records a short sim into an audit
# ring, replays every batch bit-identically (steady + cpu-ladder rungs),
# proves a tampered record yields a structured blame report, flips
# /debug/health ok -> breach under the chaos proxy's injected latency
# (with the bst_slo_breach_total increment), and bounds audit recording
# overhead at 5% of the steady batch (docs/observability.md)
replay-gate:
	JAX_PLATFORMS=cpu $(PY) benchmarks/replay_gate.py

# capture the full hardware-evidence suite (bench, smoke, ladder, scale)
# into the round's artifact files — aborts untouched if the TPU is away
tpu-artifacts:
	bash benchmarks/capture_tpu_artifacts.sh

# focused round-5 re-capture, ordered by missing evidence (ladder config
# 6 and 5, scan split, link diag, scale probe); merges per-config into
# LADDER_r05_tpu.json
tpu-refresh:
	bash benchmarks/capture_tpu_refresh_r05.sh

# sharded-scan scaling measurement (the SHARDING artifact): the
# node-sharded wavefront merge vs the replicated/partitioned layouts —
# wall-clock sweep over device counts, per-wave collective budget, and
# the winning (N, devices) point; fails if the partitioned scan cannot
# beat single-device on the virtual CPU mesh (the r05 regression).
# BST_SHARDING_PLATFORM=default runs on the real backend (TPU capture).
bench-sharding:
	$(PY) benchmarks/sharding_scaling.py

# back-compat alias (pre-r06 name)
sharding: bench-sharding

# hierarchical top-K CI gate (CPU): at a small XL bucket the top-K scan
# must be bit-identical to the dense wavefront scan at every K, clear a
# speedup floor, and a batch recorded on the top-K rung must replay
# bit-identically on the cpu-ladder rung through the audit log. The full
# XL measurement (the BENCH_XL artifact, [G=2048, N=65536] acceptance
# bucket) is `python benchmarks/xl_scaling.py` without --gate.
bench-xl:
	$(PY) benchmarks/xl_scaling.py --gate

# perf-regression tripwire (CPU): re-run the fixed probe set and compare
# median-of-k against the committed baseline envelope
# (benchmarks/perf_baseline.json, host-fingerprint-guarded); exits 1 with
# structured blame (metric, baseline, observed, ratio, knob diff) on
# regression. Runs land in PERF_LEDGER.jsonl. Re-baseline after an
# INTENTIONAL perf change: JAX_PLATFORMS=cpu python
# benchmarks/perf_regress.py --update-baseline
bench-regress:
	JAX_PLATFORMS=cpu $(PY) benchmarks/perf_regress.py

# schema-check every repo-root *_r*.json artifact (+ PERF_LEDGER.jsonl)
# against the unified bench envelope; pre-envelope artifacts pass via the
# frozen grandfather list (benchmarks/validate_artifacts.py) — future
# captures can't drift silently
validate-artifacts:
	$(PY) benchmarks/validate_artifacts.py

# the reference's serial hot loop in C++ — bench.py's vs_baseline denominator
serial-baseline:
	$(MAKE) -C native serial_baseline
	./native/serial_baseline

# driver-style entry checks: single-chip jit + 8-device sharded dry run.
# NB: this environment's sitecustomize registers the TPU plugin and overrides
# the jax_platforms config — env vars alone don't switch to CPU; the config
# update below is what makes the virtual 8-device CPU mesh take effect.
dryrun:
	$(PY) -c "from batch_scheduler_tpu.utils.backend import resolve_platform; \
		print('platform:', resolve_platform()); \
		import __graft_entry__ as g; fn, args = g.entry(); fn(*args); print('entry OK')"
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) -c "import jax; jax.config.update('jax_platforms', 'cpu'); \
		import __graft_entry__ as g; g.dryrun_multichip(8)"

version:
	$(PY) -m batch_scheduler_tpu version

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
