"""Unit tests for utils.retry: backoff bounds (full jitter on the first
draw, decorrelated jitter down the chain), the reusable RetryPolicy.call
driver, and the CircuitBreaker state machine (the pieces
ResilientOracleClient composes; docs/resilience.md)."""

import random

import pytest

from batch_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy


def test_backoff_full_jitter_bounds():
    policy = RetryPolicy(base_delay=0.1, max_delay=1.0, multiplier=2.0)
    rng = random.Random(42)
    for i in range(8):
        cap = min(1.0, 0.1 * 2.0 ** i)
        for _ in range(50):
            d = policy.backoff(i, rng=rng)
            assert 0.0 <= d <= cap, (i, d, cap)
    # the draw actually spreads (full jitter, not equal-jitter floor)
    draws = [policy.backoff(3, rng=rng) for _ in range(200)]
    assert min(draws) < 0.2 and max(draws) > 0.6


def test_backoff_decorrelated_bounds():
    policy = RetryPolicy(base_delay=0.1, max_delay=1.0, multiplier=2.0)
    rng = random.Random(7)
    prev = policy.backoff(0, rng=rng)
    for i in range(1, 12):
        d = policy.backoff(i, rng=rng, prev=prev)
        lo = policy.base_delay
        hi = min(policy.max_delay, max(3.0 * prev, lo))
        assert lo <= d <= hi or d == policy.max_delay, (i, d, prev)
        assert d <= policy.max_delay
        prev = d
    # a tiny prev never collapses the range below base_delay
    d = policy.backoff(1, rng=rng, prev=1e-6)
    assert policy.base_delay <= d <= policy.max_delay


def test_decorrelated_chains_desynchronize():
    """The HA stampede claim: two clients that start their retry chains
    at the same instant diverge on the first draw and STAY diverged —
    each delay feeds the next draw's range, so the chains' cumulative
    wakeup times separate instead of re-correlating around the shared
    exponential envelope."""
    policy = RetryPolicy(base_delay=0.05, max_delay=30.0)

    def chain(seed, n=8):
        rng = random.Random(seed)
        delays = []
        prev = None
        for i in range(n):
            d = policy.backoff(i, rng=rng, prev=prev)
            delays.append(d)
            prev = d
        return delays

    a, b = chain(1), chain(2)
    assert a != b
    # cumulative wakeup instants separate measurably, not by epsilon
    wake_a = sum(a)
    wake_b = sum(b)
    assert abs(wake_a - wake_b) > policy.base_delay
    # determinism: the same seed replays the same chain
    assert chain(1) == a


def test_call_threads_prev_through_chain():
    """RetryPolicy.call feeds each delay into the next draw (the
    decorrelated recurrence), so every observed sleep after the first
    lies in [base, min(max_delay, 3*prev)]."""
    sleeps = []
    policy = RetryPolicy(max_attempts=8, base_delay=0.01, max_delay=5.0)

    def always():
        raise OSError("down")

    with pytest.raises(OSError):
        policy.call(always, retry_on=(OSError,), sleep=sleeps.append)
    assert len(sleeps) == policy.max_attempts - 1
    for prev, d in zip(sleeps, sleeps[1:]):
        assert policy.base_delay <= d <= min(
            policy.max_delay, max(3.0 * prev, policy.base_delay)
        ), (prev, d)


def test_call_retries_then_succeeds():
    sleeps = []
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("boom")
        return "ok"

    policy = RetryPolicy(max_attempts=4, base_delay=0.01, max_delay=0.02)
    result = policy.call(flaky, retry_on=(OSError,), sleep=sleeps.append)
    assert result == "ok"
    assert len(attempts) == 3
    assert len(sleeps) == 2  # one sleep per retry, none after success


def test_call_exhaustion_reraises_last_error_unwrapped():
    def always():
        raise OSError("dead")

    policy = RetryPolicy(max_attempts=3, base_delay=0.01)
    with pytest.raises(OSError, match="dead"):
        policy.call(always, retry_on=(OSError,), sleep=lambda _d: None)


def test_call_no_retry_wins_over_retry_on():
    attempts = []

    def semantic():
        attempts.append(1)
        raise ValueError("semantic answer")

    policy = RetryPolicy(max_attempts=5, base_delay=0.01)
    with pytest.raises(ValueError):
        policy.call(
            semantic,
            retry_on=(Exception,),
            no_retry=(ValueError,),
            sleep=lambda _d: None,
        )
    assert len(attempts) == 1  # never retried


def test_call_on_retry_observes_each_retry():
    seen = []

    def flaky():
        if len(seen) < 2:
            raise OSError("x")
        return True

    policy = RetryPolicy(max_attempts=4, base_delay=0.01)
    assert policy.call(
        flaky,
        retry_on=(OSError,),
        sleep=lambda _d: None,
        on_retry=lambda i, e, d: seen.append((i, type(e).__name__)),
    )
    assert seen == [(0, "OSError"), (1, "OSError")]


def test_breaker_lifecycle_with_fake_clock():
    now = [0.0]
    transitions = []
    breaker = CircuitBreaker(
        failure_threshold=3,
        reset_timeout=5.0,
        clock=lambda: now[0],
        on_transition=transitions.append,
    )
    assert breaker.state == "closed"
    assert breaker.admit() == "attempt"

    # below threshold: still closed; a success resets the count
    breaker.record_failure()
    breaker.record_failure()
    breaker.record_success()
    breaker.record_failure()
    breaker.record_failure()
    assert breaker.state == "closed"

    breaker.record_failure()  # third consecutive -> open
    assert breaker.state == "open"
    assert breaker.admit() == "refuse"
    assert not breaker.would_attempt()

    now[0] = 4.9
    assert breaker.admit() == "refuse"  # cooldown not elapsed
    now[0] = 5.1
    assert breaker.would_attempt()
    assert breaker.admit() == "probe"  # half-open
    assert breaker.state == "half-open"

    # failed probe re-opens with a FRESH cooldown
    breaker.record_failure()
    assert breaker.state == "open"
    assert breaker.admit() == "refuse"
    now[0] = 10.2
    assert breaker.admit() == "probe"
    breaker.record_success()
    assert breaker.state == "closed"
    assert breaker.admit() == "attempt"
    assert transitions == ["open", "half-open", "open", "half-open", "closed"]
