"""PodGroup controller phase-machine tests, driven synchronously through
_sync_handler with a fake clientset (the fake-clientset controller-test
pattern the reference's generated fake enables but never uses —
SURVEY.md §4)."""

import pytest

from batch_scheduler_tpu.api import PodGroupPhase, PodPhase
from batch_scheduler_tpu.cache import PGStatusCache
from batch_scheduler_tpu.client import APIServer, Clientset, SharedInformerFactory
from batch_scheduler_tpu.controller import PodGroupController

from helpers import make_group, make_pod


class Harness:
    def __init__(self, max_schedule_seconds=None):
        self.api = APIServer()
        self.client = Clientset(self.api)
        self.cache = PGStatusCache()
        self.rejected = []
        self.backoffs = []
        factory = SharedInformerFactory(self.api)
        self.controller = PodGroupController(
            client=self.client,
            pg_informer=factory.pod_groups(),
            pg_cache=self.cache,
            reject_pod=self.rejected.append,
            add_to_backoff=self.backoffs.append,
            max_schedule_seconds=max_schedule_seconds,
        )

    def sync(self, name, namespace="default"):
        pg = self.client.podgroups(namespace).get(name)
        self.controller._sync_handler(pg, f"{namespace}/{name}")
        return self.client.podgroups(namespace).get(name)


def bind_and_phase(h, pod, node, phase):
    h.client.pods().create(pod)
    h.client.pods().bind(pod.metadata.name, node)
    h.client.pods().patch(pod.metadata.name, {"status": {"phase": phase.value}})


def test_empty_phase_normalized_to_pending():
    h = Harness()
    h.client.podgroups().create(make_group("g", 2))
    pg = h.sync("g")
    assert pg.status.phase == PodGroupPhase.PENDING
    assert h.cache.get("default/g") is not None


def test_scheduling_to_running_to_finished():
    h = Harness()
    h.client.podgroups().create(make_group("g", 2))
    h.sync("g")
    h.client.podgroups().patch(
        "g", {"status": {"phase": "Scheduling", "scheduled": 2}}
    )
    for i in range(2):
        bind_and_phase(h, make_pod(f"g-{i}", group="g"), "n1", PodPhase.RUNNING)
    pg = h.sync("g")
    assert pg.status.phase == PodGroupPhase.RUNNING
    assert pg.status.running == 2

    for i in range(2):
        h.client.pods().patch(f"g-{i}", {"status": {"phase": "Succeeded"}})
    pg = h.sync("g")
    assert pg.status.phase == PodGroupPhase.FINISHED
    assert pg.status.succeeded == 2
    # terminal groups leave the cache (reference controller.go:304-306)
    assert h.cache.get("default/g") is None


def test_failure_detection():
    h = Harness()
    h.client.podgroups().create(make_group("g", 2))
    h.sync("g")
    h.client.podgroups().patch(
        "g", {"status": {"phase": "Scheduling", "scheduled": 2}}
    )
    bind_and_phase(h, make_pod("g-0", group="g"), "n1", PodPhase.RUNNING)
    bind_and_phase(h, make_pod("g-1", group="g"), "n1", PodPhase.FAILED)
    pg = h.sync("g")
    assert pg.status.phase == PodGroupPhase.FAILED
    assert pg.status.failed == 1
    assert h.cache.get("default/g") is None


def test_crash_recovery_rederives_scheduled():
    # phase Pending but schedule_start_time set: re-derive Scheduled from
    # live member pods (reference controller.go:201-222)
    h = Harness()
    h.client.podgroups().create(make_group("g", 3))
    h.client.podgroups().patch(
        "g", {"status": {"phase": "Pending", "schedule_start_time": 123.0}}
    )
    for i in range(2):
        bind_and_phase(h, make_pod(f"g-{i}", group="g"), "n1", PodPhase.RUNNING)
    pg = h.sync("g")
    assert pg.status.scheduled == 2


def test_demotion_when_members_vanish():
    # Scheduled group whose live notPending < minMember goes back to
    # Scheduling (reference controller.go:276-279)
    h = Harness()
    h.client.podgroups().create(make_group("g", 3))
    h.sync("g")
    h.client.podgroups().patch(
        "g", {"status": {"phase": "Scheduled", "scheduled": 3}}
    )
    bind_and_phase(h, make_pod("g-0", group="g"), "n1", PodPhase.RUNNING)
    pg = h.sync("g")
    assert pg.status.phase == PodGroupPhase.SCHEDULING
    assert pg.status.scheduled == 1


def test_local_schedule_progress_not_clobbered():
    h = Harness()
    h.client.podgroups().create(make_group("g", 3))
    h.sync("g")
    pgs = h.cache.get("default/g")
    pgs.pod_group.status.phase = PodGroupPhase.PRE_SCHEDULING  # Permit advanced
    pg = h.sync("g")
    assert h.cache.get("default/g").pod_group.status.phase == PodGroupPhase.PRE_SCHEDULING


def test_ttl_eviction_aborts_gang():
    import time

    h = Harness(max_schedule_seconds=60)
    h.client.podgroups().create(make_group("g", 2))
    h.sync("g")
    pgs = h.cache.get("default/g")
    pgs.matched_pod_nodes.set("uid-1", object(), ttl=60.0)
    pgs.pod_name_uids.set("default/g-0", "uid-1", ttl=0.001)
    time.sleep(0.01)
    pgs.pod_name_uids.purge_expired()
    assert h.rejected == ["uid-1"]
    assert h.backoffs == ["default/g"]
    assert pgs.matched_pod_nodes.items() == {}

