"""Multi-device sharding tests on the 8-device virtual CPU mesh: the oracle
batch partitioned over ("groups", "nodes") must agree exactly with the
single-device result."""

import jax
import numpy as np
import pytest

from batch_scheduler_tpu.ops import ClusterSnapshot, GroupDemand, schedule_batch
from batch_scheduler_tpu.parallel import make_mesh, sharded_schedule_batch
from batch_scheduler_tpu.sim.scenarios import make_sim_node


def _snapshot(num_nodes=32, num_groups=16):
    nodes = [
        make_sim_node(f"n{i:03d}", {"cpu": "16", "memory": "64Gi", "pods": "32"})
        for i in range(num_nodes)
    ]
    groups = [
        GroupDemand(
            full_name=f"default/g{g:03d}",
            min_member=4 + (g % 3),
            member_request={"cpu": 2000, "memory": 4 * 1024**3},
            creation_ts=float(g),
        )
        for g in range(num_groups)
    ]
    return ClusterSnapshot(nodes, {}, groups)


def test_mesh_uses_all_devices():
    mesh = make_mesh(8)
    assert mesh.devices.size == 8
    assert set(mesh.axis_names) == {"groups", "nodes"}


def test_sharded_batch_matches_single_device():
    snap = _snapshot()
    single = jax.device_get(schedule_batch(*snap.device_args()))

    mesh = make_mesh(8)
    sharded = jax.device_get(sharded_schedule_batch(mesh, snap.device_args()))

    for key in ("gang_feasible", "placed", "capacity", "assignment"):
        np.testing.assert_array_equal(
            np.asarray(single[key]), np.asarray(sharded[key]), err_msg=key
        )


@pytest.mark.parametrize(
    "num_nodes,num_groups",
    [
        (64, 32),  # even tiny shards
        (100, 24),  # uneven node shards (100 pads to 128, splits 4-way)
    ],
)
def test_sharded_equivalence_across_shapes(num_nodes, num_groups):
    """GSPMD partitioning bugs are notoriously shape-dependent (tile
    boundaries, uneven shards): the sharded batch must match the
    single-device batch bit-for-bit across shard layouts. (The padded
    north-star production bucket gets its own combined test below.)"""
    snap = _snapshot(num_nodes=num_nodes, num_groups=num_groups)
    args = snap.device_args()
    single = jax.device_get(schedule_batch(*args))
    mesh = make_mesh(8)
    sharded = jax.device_get(sharded_schedule_batch(mesh, args))
    for key in ("gang_feasible", "placed", "capacity", "assignment"):
        np.testing.assert_array_equal(
            np.asarray(single[key]), np.asarray(sharded[key]), err_msg=key
        )


def test_north_star_bucket_equivalence_and_collectives():
    """The padded north-star production bucket (5k nodes / 1k groups ->
    [G=1024, N=8192]) compiled ONCE, then both checks against that one
    compiled object (VERDICT r4 item 4):

    - placements match the single-device batch bit-for-bit (GSPMD
      partitioning bugs are shape-dependent — tile boundaries, uneven
      shards — so the toy-shape equivalence above proves nothing here);
    - the compiled module carries only the one-time handful of
      collectives (scoring all-gathers + scan-input replication),
      nothing per scan step — a partitioning regression shows up as an
      op-count explosion (the fully-partitioned scan variant measures
      ~50 collective sites) before it shows up as wrong placements.

    Slow on the 8-way virtual CPU mesh (~1 min: eight replicas share one
    host) — correctness at the production shape is the point."""
    from batch_scheduler_tpu.ops import oracle as okern
    from batch_scheduler_tpu.parallel import shard_snapshot_args
    from batch_scheduler_tpu.parallel.mesh import (
        count_collective_instructions,
    )

    snap = _snapshot(num_nodes=5000, num_groups=1000)
    args = snap.device_args()
    single = jax.device_get(schedule_batch(*args))

    mesh = make_mesh(8)
    sharded_args = shard_snapshot_args(mesh, args)
    compiled = okern.schedule_batch.lower(
        *sharded_args, scan_mesh=mesh
    ).compile()

    counts = count_collective_instructions(compiled.as_text())
    total = sum(counts.values())
    assert 0 < total <= 16, counts

    sharded = jax.device_get(compiled(*sharded_args))
    for key in ("gang_feasible", "placed", "capacity", "assignment"):
        np.testing.assert_array_equal(
            np.asarray(single[key]), np.asarray(sharded[key]), err_msg=key
        )


def test_sharded_batch_on_subset_mesh():
    snap = _snapshot(num_nodes=16, num_groups=8)
    mesh = make_mesh(4)
    out = jax.device_get(sharded_schedule_batch(mesh, snap.device_args()))
    assert np.asarray(out["placed"])[:8].all()


def test_init_distributed_noop_without_coordinator(monkeypatch):
    """Single-process is a no-op: no coordinator configured -> False, and
    global_mesh still builds over the local (virtual) devices."""
    from batch_scheduler_tpu.parallel import global_mesh, init_distributed

    monkeypatch.delenv("BST_COORDINATOR", raising=False)
    assert init_distributed() is False
    mesh = global_mesh()
    assert mesh.size == len(jax.devices())
    assert set(mesh.axis_names) == {"groups", "nodes"}
