from fractions import Fraction

import pytest

from batch_scheduler_tpu.api.quantity import (
    canonicalize,
    format_quantity,
    parse_quantity,
    parse_resource_list,
)


def test_parse_plain_and_milli():
    assert parse_quantity("1") == 1
    assert parse_quantity("100m") == Fraction(1, 10)
    assert parse_quantity("1.5") == Fraction(3, 2)


def test_parse_binary_suffixes():
    assert parse_quantity("1Ki") == 1024
    assert parse_quantity("64Mi") == 64 * 1024**2
    assert parse_quantity("2Gi") == 2 * 1024**3


def test_parse_decimal_suffixes_and_exponent():
    assert parse_quantity("2k") == 2000
    assert parse_quantity("1M") == 10**6
    assert parse_quantity("1e3") == 1000
    assert parse_quantity("1.5G") == 1_500_000_000


def test_parse_invalid():
    for bad in ("", "abc", "1Q", "--3", "1..5"):
        with pytest.raises(ValueError):
            parse_quantity(bad)


def test_canonicalize_cpu_millicores():
    assert canonicalize("cpu", "1") == 1000
    assert canonicalize("cpu", "250m") == 250
    assert canonicalize("cpu", "1.5") == 1500


def test_canonicalize_rounding_direction():
    # requests round up, capacities round down
    assert canonicalize("memory", "1.5", floor=False) == 2
    assert canonicalize("memory", "1.5", floor=True) == 1
    assert canonicalize("cpu", "1m") == 1


def test_parse_resource_list():
    rl = parse_resource_list({"cpu": "2", "memory": "1Gi", "nvidia.com/gpu": 4})
    assert rl == {"cpu": 2000, "memory": 1024**3, "nvidia.com/gpu": 4}


def test_format_roundtrip():
    assert format_quantity("cpu", 1500) == "1500m"
    assert format_quantity("cpu", 2000) == "2"
    assert format_quantity("memory", 1024**3) == "1Gi"
