"""Framework unit tests: queue ordering, waiting pods, cluster state."""

import time

from batch_scheduler_tpu.framework import (
    ClusterState,
    PodInfo,
    SchedulingQueue,
    WaitingPod,
    WaitingPods,
)
from batch_scheduler_tpu.api import PodPhase

from helpers import make_node, make_pod


def test_queue_orders_by_less():
    q = SchedulingQueue(
        less_fn=lambda a, b: a.pod.spec.priority > b.pod.spec.priority
    )
    low = PodInfo(pod=make_pod("low", priority=1))
    high = PodInfo(pod=make_pod("high", priority=9))
    mid = PodInfo(pod=make_pod("mid", priority=5))
    for info in (low, high, mid):
        q.push(info)
    assert q.pop(1).pod.metadata.name == "high"
    assert q.pop(1).pod.metadata.name == "mid"
    assert q.pop(1).pod.metadata.name == "low"
    q.close()


def test_queue_backoff_promotion():
    q = SchedulingQueue(backoff_base=0.05, backoff_cap=0.2)
    info = PodInfo(pod=make_pod("p"))
    q.push_backoff(info)
    assert q.pop(0.01) is None  # still backing off
    got = q.pop(2.0)
    assert got is not None and got.pod.metadata.name == "p"
    assert got.attempts == 1
    q.close()


def test_waiting_pod_allow_reject_once():
    pods = WaitingPods()
    wp = WaitingPod(make_pod("w"), "n1", deadline=time.monotonic() + 60)
    pods.park(wp)
    assert pods.get(wp.pod.metadata.uid) is wp
    assert wp.allow("batch-scheduler")
    assert not wp.reject("too late")  # already resolved
    resolved, outcome, _ = pods.resolved.get(timeout=1)
    assert resolved is wp and outcome == "allow"
    assert pods.get(wp.pod.metadata.uid) is None
    pods.close()


def test_waiting_pod_timeout_fires():
    pods = WaitingPods()
    wp = WaitingPod(make_pod("t"), "n1", deadline=time.monotonic() + 0.1)
    pods.park(wp)
    resolved, outcome, msg = pods.resolved.get(timeout=2)
    assert resolved is wp and outcome == "timeout"
    pods.close()


def test_waiting_pods_iterate():
    pods = WaitingPods()
    for i in range(3):
        pods.park(WaitingPod(make_pod(f"w{i}"), "n", time.monotonic() + 60))
    names = []
    pods.iterate(lambda wp: names.append(wp.get_pod().metadata.name))
    assert sorted(names) == ["w0", "w1", "w2"]
    pods.close()


def test_cluster_state_assume_forget_observe():
    cs = ClusterState()
    cs.add_node(make_node("n1", {"cpu": "8", "pods": "10"}))
    v0 = cs.version()

    pod = make_pod("p", requests={"cpu": "2"})
    cs.assume(pod, "n1")
    assert cs.node_requested("n1") == {"cpu": 2000, "pods": 1}
    assert cs.version() > v0

    cs.forget(pod.metadata.uid)
    assert cs.node_requested("n1") == {}

    # observe a bound pod (informer path), then its terminal state frees it
    bound = make_pod("b", requests={"cpu": "1"})
    bound.spec.node_name = "n1"
    cs.observe_pod(bound)
    assert cs.node_requested("n1")["cpu"] == 1000
    bound.status.phase = PodPhase.SUCCEEDED
    cs.observe_pod(bound)
    assert cs.node_requested("n1") == {}


def test_cluster_state_assume_then_observe_no_double_count():
    cs = ClusterState()
    cs.add_node(make_node("n1", {"cpu": "8"}))
    pod = make_pod("p", requests={"cpu": "2"})
    cs.assume(pod, "n1")
    cs.finish_binding(pod.metadata.uid)
    pod.spec.node_name = "n1"
    cs.observe_pod(pod)  # informer catches up with the bind
    assert cs.node_requested("n1") == {"cpu": 2000, "pods": 1}


def test_cluster_state_raw_paths_match_typed():
    """observe_pod_raw's three branches (terminal release, same-placement
    no-op without quantity parsing, unseen-placement fallback) must leave
    ClusterState identical to the typed observe_pod path."""
    from batch_scheduler_tpu.api.types import to_dict

    cs = ClusterState()
    cs.add_node(make_node("n1"))
    pod = make_pod("p1", requests={"cpu": "2"})

    # unseen bound pod arrives raw -> full charge via the fallback
    d = to_dict(pod)
    d["spec"]["node_name"] = "n1"
    cs.observe_pod_raw(d)
    assert cs.node_requested("n1").get("cpu") == 2000
    assert not cs.is_assumed(pod.metadata.uid)

    # same placement again: no-op (version unchanged)
    v = cs.version()
    cs.observe_pod_raw(d)
    assert cs.version() == v

    # assumed pod's bind commit observed raw: assumed flag clears only
    p2 = make_pod("p2", requests={"cpu": "1"})
    cs.assume(p2, "n1")
    assert cs.is_assumed(p2.metadata.uid)
    d2 = to_dict(p2)
    d2["spec"]["node_name"] = "n1"
    cs.observe_pod_raw(d2)
    assert not cs.is_assumed(p2.metadata.uid)
    assert cs.node_requested("n1").get("cpu") == 3000

    # terminal phase releases by uid
    d["status"]["phase"] = PodPhase.SUCCEEDED.value
    cs.observe_pod_raw(d)
    assert cs.node_requested("n1").get("cpu", 0) == 1000

    # raw removal drops the remaining charge
    cs.remove_pod_raw(d2)
    assert cs.node_requested("n1").get("cpu", 0) == 0
