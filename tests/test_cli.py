"""CLI layer tests: manifest loading, scheduler-config parsing, the
extension-point gate, and the `sim` command end-to-end on the examples/
manifests (the reference's gang demo and README race demo)."""

import json
import os

import pytest

from batch_scheduler_tpu.api.manifest import (
    expand_workload,
    load_manifest_file,
    load_manifests,
)
from batch_scheduler_tpu.api.types import Node, Pod, PodGroup
from batch_scheduler_tpu.cmd.config import SchedulerConfiguration, load_scheduler_config
from batch_scheduler_tpu.cmd.main import main
from batch_scheduler_tpu.plugin.gate import (
    ALL_EXTENSION_POINTS,
    DEFAULT_ENABLED,
    ExtensionPointGate,
)
from batch_scheduler_tpu.framework.types import StatusCode
from batch_scheduler_tpu.utils.labels import POD_GROUP_LABEL

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- manifest loader ---------------------------------------------------------


def test_example1_manifest_expands_statefulset():
    objs = load_manifest_file(os.path.join(REPO, "examples", "example1.yaml"))
    groups = [o for o in objs if isinstance(o, PodGroup)]
    pods = [o for o in objs if isinstance(o, Pod)]
    assert len(groups) == 1 and groups[0].spec.min_member == 9
    assert len(pods) == 9
    names = {p.metadata.name for p in pods}
    assert "web-group-valid1-0" in names and "web-group-valid1-8" in names
    for p in pods:
        assert p.metadata.labels[POD_GROUP_LABEL] == "group1"
        # "1" cpu limit+request -> canonical 1000 milli
        assert p.resource_require() == {"cpu": 1000}


def test_race_manifest_node_quantities():
    objs = load_manifest_file(os.path.join(REPO, "examples", "race.yaml"))
    nodes = [o for o in objs if isinstance(o, Node)]
    assert len(nodes) == 1
    assert nodes[0].status.allocatable["cpu"] == 7100
    assert nodes[0].status.allocatable["memory"] == 32 * 1024**3
    assert nodes[0].status.allocatable["pods"] == 110


def test_duration_parsing():
    from batch_scheduler_tpu.api.manifest import _duration_seconds

    assert _duration_seconds(None) is None
    assert _duration_seconds(90) == 90.0
    assert _duration_seconds("30s") == 30.0
    assert _duration_seconds("1m30s") == 90.0
    assert _duration_seconds("500ms") == 0.5
    assert _duration_seconds("1h2m3s") == 3723.0
    assert _duration_seconds("2.5m") == 150.0
    with pytest.raises(ValueError, match="maxScheduleTime"):
        _duration_seconds("tomorrow")


def test_manifest_skips_unknown_kinds_and_parses_durations():
    text = """
apiVersion: v1
kind: Service
metadata: {name: svc}
---
apiVersion: batch.scheduler.tpu/v1
kind: PodGroup
metadata: {name: g}
spec:
  minMember: 3
  maxScheduleTime: 5m
  minResources: {cpu: "2", memory: 1Gi}
"""
    objs = load_manifests(text)
    assert len(objs) == 1
    pg = objs[0]
    assert pg.spec.max_schedule_time == 300.0
    assert pg.spec.min_resources == {"cpu": 2000, "memory": 1024**3}


def test_expand_job_uses_parallelism():
    pods = expand_workload(
        {
            "kind": "Job",
            "metadata": {"name": "j", "namespace": "ns1"},
            "spec": {
                "parallelism": 3,
                "template": {
                    "metadata": {"labels": {POD_GROUP_LABEL: "g"}},
                    "spec": {"containers": [{"name": "c", "resources": {"requests": {"cpu": "500m"}}}]},
                },
            },
        }
    )
    assert [p.metadata.name for p in pods] == ["j-0", "j-1", "j-2"]
    assert pods[0].metadata.namespace == "ns1"
    assert pods[0].resource_require() == {"cpu": 500}


# -- scheduler configuration -------------------------------------------------


def test_load_shipped_config():
    cfg = load_scheduler_config(
        os.path.join(REPO, "deploy", "scheduler", "config", "batch_scheduler_config.json")
    )
    assert cfg.plugin_config.scorer == "oracle"
    assert cfg.plugin_config.max_schedule_minutes == 10
    assert cfg.enabled_points == ALL_EXTENSION_POINTS


def test_load_reference_parity_config():
    """The reference's shipped KubeSchedulerConfiguration shape parses, with
    its four extension points and no filter/score (reference
    deploy/scheduler/config/batch_scheduler_config.json:7-36)."""
    cfg = load_scheduler_config(
        os.path.join(REPO, "deploy", "scheduler", "config", "reference_parity_config.json")
    )
    assert cfg.enabled_points == DEFAULT_ENABLED
    assert cfg.plugin_config.scorer == "serial"
    assert cfg.kubeconfig  # clientConnection surfaced


def test_config_scorer_batching_args():
    """pluginConfig.args carries the scorer batching knobs (the config-file
    analog of --oracle-background-refresh / batch coalescing)."""
    cfg = SchedulerConfiguration.from_dict(
        {
            "pluginConfig": [
                {
                    "name": "batch-scheduler",
                    "args": {
                        "min_batch_interval_seconds": 0.5,
                        "oracle_background_refresh": True,
                    },
                }
            ]
        }
    )
    assert cfg.plugin_config.min_batch_interval_seconds == 0.5
    assert cfg.plugin_config.oracle_background_refresh is True
    # defaults stay off
    dflt = load_scheduler_config(None)
    assert dflt.plugin_config.min_batch_interval_seconds == 0.0
    assert dflt.plugin_config.oracle_background_refresh is False
    # a string "false" must fail loudly, not silently mean True
    with pytest.raises(ValueError, match="JSON boolean"):
        SchedulerConfiguration.from_dict(
            {
                "pluginConfig": [
                    {
                        "name": "batch-scheduler",
                        "args": {"oracle_background_refresh": "false"},
                    }
                ]
            }
        )


def test_default_config_and_bad_kind():
    assert load_scheduler_config(None).enabled_points == DEFAULT_ENABLED
    with pytest.raises(ValueError):
        SchedulerConfiguration.from_dict({"kind": "Deployment"})
    with pytest.raises(ValueError):
        SchedulerConfiguration.from_dict(
            {"plugins": {"bogusPoint": {"enabled": [{"name": "batch-scheduler"}]}}}
        )


# -- extension-point gate ----------------------------------------------------


class _RecordingPlugin:
    def __init__(self):
        self.calls = []

    def less(self, a, b):
        self.calls.append("less")
        return True

    def pre_filter(self, pod):
        self.calls.append("pre_filter")

    def filter(self, pod, node):
        self.calls.append("filter")

    def score(self, pod, node):
        self.calls.append("score")
        return 7

    def permit(self, pod, node):
        self.calls.append("permit")
        return (StatusCode.WAIT, 1.0)

    def post_bind(self, pod, node):
        self.calls.append("post_bind")

    def reject_pod(self, uid):
        self.calls.append("reject_pod")


def test_gate_reference_default_disables_filter_and_score():
    base = _RecordingPlugin()
    gate = ExtensionPointGate(base, DEFAULT_ENABLED)
    gate.filter(None, "n")  # disabled -> no-op, no exception
    assert gate.score(None, "n") == 0
    gate.pre_filter(None)
    assert gate.permit(None, "n") == (StatusCode.WAIT, 1.0)
    gate.post_bind(None, "n")
    gate.reject_pod("u")  # non-extension-point methods always pass through
    assert base.calls == ["pre_filter", "permit", "post_bind", "reject_pod"]


def test_gate_disabled_queue_sort_falls_back_to_fifo():
    class Info:
        def __init__(self, ts):
            self.timestamp = ts

    gate = ExtensionPointGate(_RecordingPlugin(), frozenset())
    assert gate.less(Info(1.0), Info(2.0)) is True
    assert gate.less(Info(2.0), Info(1.0)) is False
    assert gate.permit(None, "n") == (StatusCode.SUCCESS, 0.0)


def test_gate_rejects_unknown_point():
    with pytest.raises(ValueError):
        ExtensionPointGate(_RecordingPlugin(), {"preFilter", "bogus"})


# -- sim command end-to-end --------------------------------------------------


def test_cli_check_config(capsys):
    rc = main(
        [
            "check-config",
            "--config",
            os.path.join(REPO, "deploy", "scheduler", "config", "batch_scheduler_config.json"),
        ]
    )
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["valid"] and out["scorer"] == "oracle"


def test_cli_version(capsys):
    assert main(["version"]) == 0
    assert "batch-scheduler-tpu v" in capsys.readouterr().out


@pytest.mark.parametrize("scorer", ["oracle", "serial"])
def test_cli_sim_race_manifest(scorer, capsys):
    """README race demo through the real CLI: exactly one gang wins."""
    rc = main(
        [
            "sim",
            "-f",
            os.path.join(REPO, "examples", "race.yaml"),
            "--scorer",
            scorer,
            "--timeout",
            "30",
            "--settle",
            "2",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    lines = {l.split()[0]: l.split() for l in out.splitlines() if l.startswith("default/")}
    winner = lines["default/web-group-race1"]
    loser = lines["default/web-group-race2"]
    assert winner[1] == "Running" and winner[3] == "5"
    assert loser[3] == "0"


def test_cli_sim_zones_manifest_pins_gang_to_selected_zone(capsys, monkeypatch):
    """examples/zones.yaml: the nodeSelector-pinned gang lands entirely in
    its zone (the per-group [G,N] fit-mask path at the user surface) even
    though the other zone has more room; the free gang also runs."""
    from batch_scheduler_tpu.sim import harness

    placements = {}
    orig_stop = harness.SimCluster.stop

    def capturing_stop(self):
        if not placements:
            for p in self.clientset.pods().list():
                if p.spec.node_name:
                    placements[p.metadata.name] = p.spec.node_name
        orig_stop(self)

    monkeypatch.setattr(harness.SimCluster, "stop", capturing_stop)
    rc = main(
        [
            "sim",
            "-f",
            os.path.join(REPO, "examples", "zones.yaml"),
            "--timeout",
            "30",
            "--settle",
            "2",
        ]
    )
    assert rc == 0
    out = capsys.readouterr().out
    lines = {l.split()[0]: l.split() for l in out.splitlines() if l.startswith("default/")}
    assert lines["default/pinned-east"][1] == "Running"
    assert lines["default/free-roam"][1] == "Running"
    pinned = {v for k, v in placements.items() if k.startswith("pinned-")}
    assert pinned == {"east-1"}  # never lands in the roomier west


def test_cli_sim_requires_nodes_and_groups(capsys):
    assert main(["sim", "--timeout", "1"]) == 2


def test_cli_sim_remote_scorer():
    """sim --oracle-addr scores through the sidecar service (the start.sh
    deployment shape: scheduler process + oracle sidecar).

    Both halves run in SUBPROCESSES: in-process, this test settled
    Pending whenever any single-device ``execute_batch_host`` test ran
    first in the same interpreter (an ad-hoc-ordering interaction
    through leaked process-global jit/gate state, pre-existing on seed
    HEAD and documented in CHANGES PR 13) — fresh processes make the
    deployment shape the test actually claims, with no inherited
    device/global state on either side."""
    import re
    import subprocess
    import sys
    import time

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("BST_BUCKET_COST", "0")
    env.setdefault("BST_COMPILE_LEDGER", "off")
    env.setdefault("BST_CAPACITY", "0")
    server = subprocess.Popen(
        [sys.executable, "-m", "batch_scheduler_tpu", "serve", "--port", "0"],
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
        cwd=REPO,
        env=env,
    )
    try:
        addr = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = server.stdout.readline()
            if not line:
                break
            m = re.search(r"listening on ([\d.]+:\d+)", line)
            if m:
                addr = m.group(1)
                break
        assert addr, "sidecar subprocess never reported its address"
        sim = subprocess.run(
            [
                sys.executable, "-m", "batch_scheduler_tpu", "sim",
                "-f", os.path.join(REPO, "examples", "example1.yaml"),
                "--nodes", "4",
                "--node-cpu", "4",
                "--oracle-addr", addr,
                "--timeout", "60",
                "--settle", "2",
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
            env=env,
            timeout=300,
        )
        assert sim.returncode == 0, sim.stdout + sim.stderr
        row = next(
            l.split()
            for l in sim.stdout.splitlines()
            if l.startswith("default/group1")
        )
        assert row[1] == "Running" and row[3] == "9", sim.stdout
    finally:
        server.terminate()
        try:
            server.wait(timeout=20)
        except subprocess.TimeoutExpired:
            server.kill()
            server.wait(timeout=10)
        server.stdout.close()


def test_sim_cluster_enabled_points_passthrough():
    """cfg.plugins gating reaches the runtime: with permit disabled the
    plugin never parks pods, binds go straight through."""
    from batch_scheduler_tpu.plugin.gate import ExtensionPointGate
    from batch_scheduler_tpu.sim import SimCluster

    cluster = SimCluster(enabled_points={"queueSort", "preFilter", "postBind"})
    try:
        assert isinstance(cluster.runtime.plugin, ExtensionPointGate)
        assert "permit" not in cluster.runtime.plugin.enabled
    finally:
        cluster.stop()
