"""Real-endpoint adapter tests: the control plane over an HTTP API server.

VERDICT r1 item 5: the reference can point at any real API server via
client-go (reference clientset.go:58-97); these tests prove the owned stack
does too — Clientset, informers, and the PodGroup controller all running
against a KWOK-shaped HTTP endpoint (client.http_gateway serving an
APIServer over the wire), with the in-memory path unchanged.
"""

import queue as _q

import pytest

from batch_scheduler_tpu.api.types import PodGroupPhase, to_dict
from batch_scheduler_tpu.cache.pg_cache import PGStatusCache
from batch_scheduler_tpu.client.apiserver import (
    APIServer,
    NotFoundError,
    WatchEvent,
)
from batch_scheduler_tpu.client.clientset import Clientset
from batch_scheduler_tpu.client.http_apiserver import HTTPAPIServer
from batch_scheduler_tpu.client.http_gateway import serve_gateway
from batch_scheduler_tpu.client.informers import SharedInformerFactory
from batch_scheduler_tpu.controller.controller import PodGroupController
from batch_scheduler_tpu.utils.labels import POD_GROUP_LABEL

from helpers import make_group, make_pod


@pytest.fixture
def remote():
    """(HTTPAPIServer client, backing APIServer); gateway torn down after."""
    backing = APIServer()
    server = serve_gateway(backing)
    host, port = server.server_address[:2]
    client = HTTPAPIServer(host, port)
    yield client, backing
    client.close()
    server.shutdown()
    server.server_close()


def _wait(predicate, timeout=5.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def test_crud_and_crd_over_http(remote):
    api, _ = remote
    # CRD auto-create semantics (reference batchscheduler.go:416-436)
    assert api.ensure_crd("podgroups.batch.scheduler.tpu", {"kind": "PodGroup"})
    assert not api.ensure_crd("podgroups.batch.scheduler.tpu")  # AlreadyExists
    assert "podgroups.batch.scheduler.tpu" in api.crds()

    cs = Clientset(api)
    pg = cs.podgroups().create(make_group("web", min_member=3))
    assert pg.metadata.uid  # server stamped
    got = cs.podgroups().get("web")
    assert got.spec.min_member == 3

    # merge-patch semantics survive the wire
    patched = cs.podgroups().patch("web", {"status": {"phase": "Pending"}})
    assert patched.status.phase == PodGroupPhase.PENDING
    assert patched.spec.min_member == 3  # untouched stanza intact

    with pytest.raises(NotFoundError):
        cs.podgroups().get("nope")

    # label-selector list (the controller's member listing) over the wire
    pod = make_pod("web-0", group="web")
    cs.pods().create(pod)
    loner = make_pod("loner")
    cs.pods().create(loner)
    members = cs.pods().list(label_selector={POD_GROUP_LABEL: "web"})
    assert [p.metadata.name for p in members] == ["web-0"]

    cs.podgroups().delete("web")
    with pytest.raises(NotFoundError):
        cs.podgroups().get("web")


def test_bind_many_batched_over_http(remote):
    """The pods:bindmany custom verb: one request binds many pods,
    missing pods are skipped, and Clientset.bind_many dispatches to it
    via the bind_pods duck type. The per-pod fallback path
    (batch_bind=False) must agree bit-for-bit."""
    api, _ = remote
    cs = Clientset(api)
    for name in ("bm-0", "bm-1", "bm-2"):
        cs.pods().create(make_pod(name))
    bound = cs.pods().bind_many(
        [("bm-0", "n1"), ("ghost", "n1"), ("bm-1", "n2")]
    )
    assert bound == ["bm-0", "bm-1"]
    assert cs.pods().get("bm-0").spec.node_name == "n1"
    assert cs.pods().get("bm-1").spec.node_name == "n2"
    assert not cs.pods().get("bm-2").spec.node_name
    # measurement-control path: same contract without the batch verb
    api._batch_bind = False
    try:
        assert api.bind_pods("default", [("bm-2", "n3"), ("ghost", "n3")]) == [
            "bm-2"
        ]
    finally:
        api._batch_bind = True
    assert cs.pods().get("bm-2").spec.node_name == "n3"


def test_gateway_restart_fences_zombie_binds(remote):
    """Regression guard for the churn/outage over-commit flake: a gateway
    handler thread that survives its server's death (severed socket, but
    already past the request read) must NOT be able to apply its bind
    against the shared backing store once a NEW gateway generation has
    started — otherwise the scheduler's kept-assume resolution ("unbound
    on a fresh read -> the lost bind never applied") is unsound and the
    replanned gang over-commits. The zombie is simulated deterministically:
    capture the old generation's epoch, restart, then bind with it."""
    api, backing = remote
    cs = Clientset(api)
    for name in ("fz-0", "fz-1"):
        cs.pods().create(make_pod(name))
    old_epoch = backing._bind_epoch
    assert old_epoch >= 1  # serve_gateway advanced it at startup
    # bind through the live generation works
    assert backing.bind_pods("default", [("fz-0", "n1")], epoch=old_epoch) \
        == ["fz-0"]
    # "restart": a new generation advances the fence (what serve_gateway
    # does at startup)
    backing.advance_bind_epoch()
    # the zombie's bind, stamped with the dead generation's epoch,
    # applies NOTHING — fz-1 stays unbound, exactly what the scheduler's
    # liveness read concluded
    assert backing.bind_pods(
        "default", [("fz-1", "n2")], epoch=old_epoch
    ) == []
    assert not cs.pods().get("fz-1").spec.node_name
    # epoch-less (in-process) callers and the new generation are unfenced
    assert backing.bind_pods("default", [("fz-1", "n2")]) == ["fz-1"]
    assert cs.pods().get("fz-1").spec.node_name == "n2"


def test_failed_gateway_restart_does_not_burn_the_fence(remote):
    """A restart attempt that cannot bind (port still held by the live
    gateway) must raise cleanly BEFORE advancing the bind epoch —
    advancing first would silently fence a gateway that never got
    replaced, and every later bind through it would apply nothing."""
    api, backing = remote
    cs = Clientset(api)
    cs.pods().create(make_pod("fb-0"))
    host, port = api.host, api.port
    epoch_before = backing._bind_epoch
    with pytest.raises(OSError):
        serve_gateway(backing, host, port)  # port busy
    assert backing._bind_epoch == epoch_before
    # the surviving generation still binds
    assert cs.pods().bind_many([("fb-0", "n1")]) == ["fb-0"]


def test_watch_streams_over_http(remote):
    api, _ = remote
    cs = Clientset(api)
    cs.podgroups().create(make_group("before", min_member=1))

    events = api.watch("PodGroup", replay=True)
    ev = events.get(timeout=5.0)
    assert (ev.type, ev.obj["metadata"]["name"]) == (WatchEvent.ADDED, "before")

    cs.podgroups().create(make_group("after", min_member=2))
    ev = events.get(timeout=5.0)
    assert (ev.type, ev.obj["metadata"]["name"]) == (WatchEvent.ADDED, "after")

    cs.podgroups().patch("after", {"status": {"phase": "Pending"}})
    ev = events.get(timeout=5.0)
    assert ev.type == WatchEvent.MODIFIED
    assert ev.obj["status"]["phase"] == "Pending"

    cs.podgroups().delete("after")
    ev = events.get(timeout=5.0)
    assert ev.type == WatchEvent.DELETED

    api.stop_watch("PodGroup", events)
    # a stopped watch must not receive later events
    cs.podgroups().create(make_group("silent", min_member=1))
    with pytest.raises(_q.Empty):
        events.get(timeout=0.5)


def test_controller_reconciles_over_http(remote):
    """Full e2e across the wire: informers list+watch the HTTP endpoint and
    the controller drives the phase machine on a PodGroup created remotely
    (the reference's controller-over-client-go shape, controller.go:61-108)."""
    api, _ = remote
    cs = Clientset(api)
    informers = SharedInformerFactory(api)
    pg_informer = informers.pod_groups()
    cache = PGStatusCache()
    controller = PodGroupController(
        client=cs,
        pg_informer=pg_informer,
        pg_cache=cache,
        reject_pod=lambda uid: None,
        add_to_backoff=lambda name: None,
        resync_seconds=0.1,
    )
    informers.start()
    assert informers.wait_for_cache_sync(10.0)
    controller.run(workers=2)
    try:
        cs.podgroups().create(make_group("remote-gang", min_member=2))
        # controller sees the remote create via the HTTP watch and initialises
        # the phase machine: "" -> Pending, status cache entry exists
        assert _wait(
            lambda: cs.podgroups().get("remote-gang").status.phase
            == PodGroupPhase.PENDING,
            timeout=10.0,
        )
        assert _wait(lambda: cache.get("default/remote-gang") is not None)
    finally:
        controller.stop()
        informers.stop()


def test_watch_reflector_survives_gateway_restart():
    """Reflector semantics (client-go relist, reference factory.go:117-133):
    kill the gateway mid-watch, mutate state while it is down, restart it on
    the same port — the informer reconnects, replays, and synthesizes
    DELETED for objects that vanished during the outage."""
    backing = APIServer()
    server = serve_gateway(backing)
    host, port = server.server_address[:2]
    client = HTTPAPIServer(host, port)
    try:
        backing.create("PodGroup", to_dict(make_group("keep", 2)))
        backing.create("PodGroup", to_dict(make_group("doomed", 2)))

        informers = SharedInformerFactory(client)
        inf = informers.informer("PodGroup")
        inf.start()
        assert inf.wait_for_sync(10.0)
        assert _wait(lambda: len(inf.list("default")) == 2)

        # gateway goes away (LB blip / restart); stream drops
        server.shutdown()
        server.server_close()

        # state changes while the watcher is blind
        backing.delete("PodGroup", "default", "doomed")
        backing.create("PodGroup", to_dict(make_group("fresh", 3)))

        # gateway returns on the SAME port
        server = serve_gateway(backing, host=host, port=port)

        def converged():
            names = {g.metadata.name for g in inf.list("default")}
            return names == {"keep", "fresh"}

        assert _wait(converged, timeout=15.0), {
            g.metadata.name for g in inf.list("default")
        }
    finally:
        client.close()
        server.shutdown()
        server.server_close()


def test_watch_namespace_and_selector_scoping(remote):
    """A namespaced, label-selected watch streams ONLY matching objects
    (ADVICE r2: the gateway previously streamed everything)."""
    api, backing = remote
    import http.client as hc
    import json as _json

    conn = hc.HTTPConnection(api.host, api.port)
    conn.request(
        "GET",
        "/api/v1/namespaces/nsa/pods?watch=1&replay=1&labelSelector=app%3Dweb",
    )
    resp = conn.getresponse()
    try:
        pa = to_dict(make_pod("in-scope", {"cpu": 100}))
        pa["metadata"]["namespace"] = "nsa"
        pa["metadata"]["labels"] = {"app": "web"}
        pb = to_dict(make_pod("wrong-ns", {"cpu": 100}))
        pb["metadata"]["namespace"] = "nsb"
        pb["metadata"]["labels"] = {"app": "web"}
        pc = to_dict(make_pod("wrong-label", {"cpu": 100}))
        pc["metadata"]["namespace"] = "nsa"
        pc["metadata"]["labels"] = {"app": "db"}
        for d in (pa, pb, pc):
            backing.create("Pod", d)

        # keep reading past the first match: leakage of the out-of-scope
        # objects (created after in-scope) would appear in later lines
        seen = []
        budget = 40  # ~8s of 0.2s heartbeats; plenty for all three events
        while budget > 0:
            line = resp.fp.readline()
            budget -= 1
            if not line:
                break
            if not line.strip():
                continue
            ev = _json.loads(line)
            if ev.get("type") in ("ADDED", "MODIFIED"):
                seen.append(ev["object"]["metadata"]["name"])
        # duplicates are fine (an object created between the stream's
        # subscribe and its LIST replays twice — level-based contract);
        # out-of-scope names are the regression this test exists to catch
        assert seen and set(seen) == {"in-scope"}
    finally:
        resp.close()
        conn.close()


def test_watch_scope_transitions_emit_added_and_deleted(remote):
    """Relabeling an object into/out of a scoped watch's selector reads as
    ADDED/DELETED to that watcher (k8s scoped-watch semantics)."""
    api, backing = remote
    import http.client as hc
    import json as _json

    d = to_dict(make_pod("mover", {"cpu": 100}))
    d["metadata"]["labels"] = {"app": "db"}
    backing.create("Pod", d)

    conn = hc.HTTPConnection(api.host, api.port)
    conn.request(
        "GET", "/api/v1/pods?watch=1&replay=1&labelSelector=app%3Dweb"
    )
    resp = conn.getresponse()
    try:
        def next_event(budget=40):
            while budget > 0:
                line = resp.fp.readline()
                budget -= 1
                if not line:
                    return None
                if not line.strip():
                    continue
                ev = _json.loads(line)
                if ev.get("type") != "BOOKMARK":
                    return ev
            return None

        # drain the replay up to its BOOKMARK before mutating, so the
        # patches below can't race the replay's LIST snapshot
        while True:
            line = resp.fp.readline()
            if line.strip() and _json.loads(line).get("type") == "BOOKMARK":
                break

        # into scope -> ADDED (even though the API event is MODIFIED)
        backing.patch("Pod", "default", "mover", {"metadata": {"labels": {"app": "web"}}})
        ev = next_event()
        assert ev and ev["type"] == "ADDED" and ev["object"]["metadata"]["name"] == "mover"

        # out of scope -> DELETED to this watcher
        backing.patch("Pod", "default", "mover", {"metadata": {"labels": {"app": "db"}}})
        ev = next_event()
        assert ev and ev["type"] == "DELETED" and ev["object"]["metadata"]["name"] == "mover"
    finally:
        resp.close()
        conn.close()


def test_full_stack_schedules_over_http():
    """The ENTIRE framework — scheduler loop, plugin runtime, controller,
    informers (reflector watches), sim kubelet — running against the HTTP
    gateway instead of the in-memory API server: the reference race demo
    must settle identically over the wire (client-go deployment shape,
    reference clientset.go:58-97)."""
    from batch_scheduler_tpu.api.types import PodGroupPhase
    from batch_scheduler_tpu.sim import SimCluster
    from batch_scheduler_tpu.sim.scenarios import race_scenario

    backing = APIServer()
    server = serve_gateway(backing)
    host, port = server.server_address[:2]
    # generous flow-control: the point here is correctness over the wire
    api = HTTPAPIServer(host, port, qps=500.0, burst=200)
    cluster = SimCluster(scorer="oracle", api=api)
    nodes, groups, pods_by_group = race_scenario()
    cluster.add_nodes(nodes)
    for pg in groups:
        cluster.create_group(pg)
    cluster.start()
    try:
        for pods in pods_by_group.values():
            cluster.create_pods(pods)
        assert cluster.wait_for(
            lambda: cluster.scheduler.stats["binds"] >= 5, timeout=60.0
        ), cluster.scheduler.stats
        # gang exclusivity holds across the wire: race1 fully bound,
        # race2 bound nothing
        assert cluster.wait_for_group_phase(
            "web-group-race1", PodGroupPhase.RUNNING, timeout=30.0
        )
        bound2 = [
            p for p in cluster.member_pods("web-group-race2") if p.spec.node_name
        ]
        assert bound2 == [], [p.metadata.name for p in bound2]
        assert cluster.scheduler.stats["binds"] == 5
    finally:
        cluster.stop()
        api.close()
        server.shutdown()
        server.server_close()
