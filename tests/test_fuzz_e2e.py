"""Property fuzz of the FULL framework: random node shapes, random gang
demands and priorities (a fraction deliberately infeasible), plus loose
non-gang pods, driven through the complete stack (API server, informers,
scheduler, plugin, controller, kubelet). The reference has nothing like
this (SURVEY.md §4: two unit files); a scheduler's core promises are
exactly the kind of thing randomized inputs break.

Invariants asserted once the cluster quiesces:

1. **No node over-commit** — per node, the lane-wise sum of every bound
   pod's requests (plus its implicit pod slot) fits inside allocatable,
   judged from the API server's truth, not the scheduler's own caches.
2. **Gang atomicity** — every gang ends fully admitted (bound members >=
   minMember) or with zero bound members.
3. **Feasibility honesty** — gangs the generator constructed to be
   trivially feasible in isolation AND collectively (total demand within
   total capacity with headroom) all run; generator-infeasible gangs
   (demand no node can hold) never bind a pod.
4. **Liveness** — the run settles inside the timeout (no deadlock between
   the queue, permit waits, TTL aborts, and re-batches).
"""

import time

import numpy as np
import pytest

from batch_scheduler_tpu.api.quantity import parse_quantity
from batch_scheduler_tpu.sim import (
    SimCluster,
    make_member_pods,
    make_sim_group,
    make_sim_node,
)


@pytest.fixture(scope="module", autouse=True)
def _lockcheck():
    """BST_LOCKCHECK: the full-stack fuzz (informers, scheduler, plugin,
    controller, kubelet — every thread in the system) runs as a genuine
    race detector over the guarded-by-annotated classes
    (docs/static_analysis.md)."""
    import os

    from batch_scheduler_tpu.analysis import lockcheck

    prev = os.environ.get("BST_LOCKCHECK")
    os.environ["BST_LOCKCHECK"] = "1"
    lockcheck.install()
    yield
    # restore the env so SUBPROCESSES spawned by later tests don't inherit
    # the knob (in-process instrumentation intentionally stays installed)
    if prev is None:
        os.environ.pop("BST_LOCKCHECK", None)
    else:
        os.environ["BST_LOCKCHECK"] = prev


@pytest.fixture
def sim(request):
    clusters = []

    def build(**kwargs):
        c = SimCluster(**kwargs)
        clusters.append(c)
        return c

    yield build
    for c in clusters:
        c.stop()


def _assert_no_overcommit(cluster):
    nodes = {
        n.metadata.name: n for n in cluster.clientset.nodes().list()
    }
    used = {name: {} for name in nodes}
    from batch_scheduler_tpu.api.types import PodPhase

    for pod in cluster.clientset.pods().list():
        node = pod.spec.node_name
        if not node:
            continue
        if pod.status.phase in (PodPhase.SUCCEEDED, PodPhase.FAILED):
            continue  # terminal pods release their requests (k8s semantics)
        assert node in nodes, f"pod {pod.metadata.name} bound to ghost {node}"
        req = pod.resource_require()
        u = used[node]
        for k, v in req.items():
            u[k] = u.get(k, 0) + v
        u["pods"] = u.get("pods", 0) + 1
    for name, u in used.items():
        alloc = nodes[name].status.allocatable
        for k, v in u.items():
            have = int(parse_quantity(alloc.get(k, 0)))
            assert v <= have, (
                f"node {name} over-committed on {k}: {v} > {have} "
                f"(bound pods exceed allocatable)"
            )


def _await_binds(cluster, expected, timeout=90.0):
    """Liveness: every expected bind lands. Denied/infeasible gangs retry
    forever (reference semantics — a pending pod never stops), so 'stats
    quiet' is not a reachable state; the settle condition is bind count."""
    return cluster.wait_for(
        lambda: cluster.scheduler.stats["binds"] >= expected,
        timeout=timeout,
        interval=0.2,
    )


def _fuzz_scenario(sim, seed, **cluster_kwargs):
    """Build + run one randomized scenario; returns cluster and the
    generator's feasible/infeasible gang lists."""
    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(8, 24))
    node_cpus = rng.choice([4, 8, 16], size=n_nodes)
    nodes = [
        make_sim_node(
            f"fz-n{i:03d}",
            {"cpu": str(int(c)), "memory": f"{int(c) * 4}Gi", "pods": "110"},
        )
        for i, c in enumerate(node_cpus)
    ]
    total_cpu = int(node_cpus.sum())
    max_node_cpu = int(node_cpus.max())

    # oracle by default; kwargs may select the serial (reference-parity)
    # scorer so the same invariants hammer both paths
    cluster_kwargs.setdefault("scorer", "oracle")
    cluster = sim(
        max_schedule_minutes=0.05,  # 3s gang TTL: abort paths exercised
        backoff_base=0.1,
        backoff_cap=0.5,
        **cluster_kwargs,
    )
    cluster.add_nodes(nodes)

    feasible, infeasible, pod_batches = [], [], []
    budget = total_cpu * 0.6  # collective headroom: feasible set must all fit
    n_gangs = int(rng.integers(10, 25))
    for g in range(n_gangs):
        members = int(rng.integers(2, 6))
        prio = int(rng.integers(0, 3))
        if rng.random() < 0.2:
            cpu = max_node_cpu + int(rng.integers(1, 4))  # fits NO node
            name = f"fz-bad-{g:03d}"
            infeasible.append((name, members))
        else:
            cpu = int(rng.integers(1, 4))
            if budget - members * cpu < 0:
                continue
            budget -= members * cpu
            name = f"fz-ok-{g:03d}"
            feasible.append((name, members))
        # recent stamps: epoch-scale creation_ts would trip the controller's
        # 48h GC horizon once scheduled and silence reconciliation
        cluster.create_group(
            make_sim_group(
                name, members, creation_ts=time.time() - (n_gangs - g) * 1e-3
            )
        )
        pod_batches.append(
            make_member_pods(name, members, {"cpu": str(cpu)}, priority=prio)
        )

    # loose (non-gang) pods riding the same queue
    loose = make_member_pods("fz-loose", int(rng.integers(3, 8)), {"cpu": "1"})
    for p in loose:
        p.metadata.labels = {}
    pod_batches.append(loose)

    cluster.start()
    order = rng.permutation(len(pod_batches))
    for i in order:
        cluster.create_pods(pod_batches[int(i)])
    return cluster, feasible, infeasible, len(loose)


@pytest.mark.parametrize(
    "seed,kwargs",
    [
        (101, {}),
        (202, {"oracle_background_refresh": True}),
        (303, {"min_batch_interval": 0.2}),
        # the serial (reference-parity) scorer under the same invariants:
        # its PreFilter may optimistically admit what Filter then rejects
        # per node, so infeasible gangs die by TTL abort instead of
        # up-front denial — the binding-level invariants must hold anyway
        (404, {"scorer": "serial"}),
    ],
)
def test_fuzz_full_framework_invariants(sim, seed, kwargs):
    cluster, feasible, infeasible, n_loose = _fuzz_scenario(sim, seed, **kwargs)
    expected = sum(m for _, m in feasible) + n_loose
    assert _await_binds(cluster, expected), (
        "feasible work never fully bound",
        expected,
        cluster.scheduler.stats,
    )
    time.sleep(2.0)  # window for any erroneous extra bind to surface
    assert cluster.scheduler.stats["binds"] == expected, (
        "more binds than the feasible set",
        expected,
        cluster.scheduler.stats,
    )

    _assert_no_overcommit(cluster)

    bound_uids = set()
    for name, members in feasible + infeasible:
        bound = [p for p in cluster.member_pods(name) if p.spec.node_name]
        for p in bound:
            assert p.metadata.uid not in bound_uids
            bound_uids.add(p.metadata.uid)
        # gang atomicity: all-in or all-out at quiescence
        assert len(bound) == 0 or len(bound) >= members, (
            f"{name}: partial gang bound {len(bound)}/{members}",
            cluster.scheduler.stats,
        )
    for name, members in infeasible:
        bound = [p for p in cluster.member_pods(name) if p.spec.node_name]
        assert bound == [], f"infeasible gang {name} bound {len(bound)} pods"
    for name, members in feasible:
        bound = [p for p in cluster.member_pods(name) if p.spec.node_name]
        assert len(bound) >= members, (
            f"feasible gang {name} never admitted ({len(bound)}/{members})",
            cluster.scheduler.stats,
        )
    # loose pods schedule independently of gang machinery
    loose_bound = [
        p
        for p in cluster.clientset.pods().list()
        if p.metadata.name.startswith("fz-loose") and p.spec.node_name
    ]
    assert len(loose_bound) > 0


def test_fuzz_churn_backfill_capacity_cycles(sim):
    """Churn fuzz: gangs RUN AND FINISH (short kubelet run_duration), so
    capacity cycles and an oversubscribed backlog (~2x cluster capacity in
    aggregate) must still fully drain through backfill re-batches. The
    over-commit invariant is sampled WHILE the cluster churns, not just at
    the end — a transient double-charge between release and re-admission
    is exactly what end-state checks miss."""
    rng = np.random.default_rng(77)
    nodes = [
        make_sim_node(f"ch-n{i:03d}", {"cpu": "8", "memory": "32Gi", "pods": "110"})
        for i in range(10)
    ]  # 80 cpus
    cluster = sim(
        scorer="oracle",
        oracle_background_refresh=True,
        kubelet_run_duration=1.0,  # gangs finish ~1s after starting
        backoff_base=0.1,
        backoff_cap=0.5,
        bind_workers=16,  # ladder config 6's concurrency level
    )
    cluster.add_nodes(nodes)

    gangs = []
    now = time.time()
    n_gangs = 30
    for g in range(n_gangs):  # ~2x capacity in aggregate
        members = int(rng.integers(2, 5))
        cpu = int(rng.integers(1, 4))
        name = f"ch-g{g:03d}"
        gangs.append((name, members, cpu))
        cluster.create_group(
            make_sim_group(name, members, creation_ts=now - (n_gangs - g) * 1e-3)
        )
    cluster.start()
    batches = []
    for name, members, cpu in gangs:
        batches.append(make_member_pods(name, members, {"cpu": str(cpu)}))
    for i in rng.permutation(len(batches)):
        cluster.create_pods(batches[int(i)])

    total = sum(m for _, m, _cpu in gangs)
    deadline = time.monotonic() + 120.0
    samples = 0
    while time.monotonic() < deadline:
        _assert_no_overcommit(cluster)  # sampled mid-churn
        samples += 1
        if cluster.scheduler.stats["binds"] >= total:
            break
        time.sleep(0.5)
    assert cluster.scheduler.stats["binds"] >= total, (
        "backlog never drained through capacity churn",
        cluster.scheduler.stats,
    )
    assert samples >= 3  # invariant actually sampled during churn
    _assert_no_overcommit(cluster)


def test_fuzz_full_framework_invariants_with_chaos_faults(sim):
    """The standing fuzz invariants with TRANSPORT FAULTS enabled: the
    oracle is remote (real sidecar server) behind a chaos proxy injecting
    delayed, reset, truncated and garbage frames throughout the run, with
    the resilient client + conservative local-CPU fallback absorbing them
    (docs/resilience.md). The scheduler must still fully bind the feasible
    set with gang atomicity and no over-commit — no scheduling cycle may
    die on an unhandled transport error."""
    from batch_scheduler_tpu.service import (
        RemoteScorer,
        ResilientOracleClient,
        serve_background,
    )
    from batch_scheduler_tpu.sim.chaos import ChaosProxy
    from batch_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

    srv = serve_background()
    proxy = ChaosProxy(*srv.address, seed=909)
    # a steady drizzle of every fault class (delay dominates, hard faults
    # rarer), never disarmed — the run must make progress THROUGH them
    proxy.set_fault(
        {"delay": 0.15, "reset": 0.04, "truncate": 0.03, "garbage": 0.03},
        delay_s=0.03,
        hang_s=1.0,
    )
    client = ResilientOracleClient(
        *proxy.address,
        timeout=5.0,
        retry_policy=RetryPolicy(max_attempts=4, base_delay=0.02, max_delay=0.2),
        breaker=CircuitBreaker(failure_threshold=4, reset_timeout=0.3),
    )
    scorer = RemoteScorer(client, fallback="local-cpu")
    try:
        cluster, feasible, infeasible, n_loose = _fuzz_scenario(
            sim, 909, scorer=scorer
        )
        expected = sum(m for _, m in feasible) + n_loose
        assert _await_binds(cluster, expected, timeout=120.0), (
            "feasible work never fully bound under chaos faults",
            expected,
            cluster.scheduler.stats,
            proxy.injected_counts(),
        )
        _assert_no_overcommit(cluster)
        for name, members in feasible:
            bound = [p for p in cluster.member_pods(name) if p.spec.node_name]
            assert len(bound) >= members, (name, len(bound), members)
        for name, members in infeasible:
            bound = [p for p in cluster.member_pods(name) if p.spec.node_name]
            assert bound == [], f"infeasible gang {name} bound {len(bound)} pods"
        # the run actually exercised the fault injector
        injected = proxy.injected_counts()
        assert sum(injected.values()) > 0, injected
    finally:
        scorer.close()
        proxy.stop()
        srv.shutdown()


def _fuzz_selector_scenario(sim, seed, **cluster_kwargs):
    """Randomized zones + taints + per-gang selectors/tolerations (VERDICT
    r3 item 6): forces the oracle's per-group [G,N] fit-mask path and the
    snapshot's quadratic mask walk under the same four invariants, plus
    placement validity. Feasible gangs are reserved member-by-member
    against the eligible-node capacity at generation time (0.6 headroom),
    so the feasible set is simultaneously satisfiable BY CONSTRUCTION
    even under zone pinning; infeasible gangs select a zone no node has."""
    from batch_scheduler_tpu.api.types import Taint, Toleration

    rng = np.random.default_rng(seed)
    zones = ["z0", "z1", "z2"]
    taint = Taint(key="dedicated", value="batch", effect="NoSchedule")
    toleration = Toleration(
        key="dedicated", operator="Equal", value="batch", effect="NoSchedule"
    )
    n_nodes = int(rng.integers(12, 24))
    nodes, node_info = [], []
    for i in range(n_nodes):
        cpu = int(rng.choice([4, 8, 16]))
        zone = zones[int(rng.integers(0, len(zones)))]
        tainted = bool(rng.random() < 0.25)
        nodes.append(
            make_sim_node(
                f"fzs-n{i:03d}",
                {"cpu": str(cpu), "memory": f"{cpu * 4}Gi", "pods": "110"},
                labels={"zone": zone},
                taints=[taint] if tainted else [],
            )
        )
        # reservation budget: 0.6 headroom against fragmentation
        node_info.append(
            {"zone": zone, "tainted": tainted, "budget": cpu * 0.6}
        )

    cluster = sim(
        scorer="oracle",
        max_schedule_minutes=0.05,
        backoff_base=0.1,
        backoff_cap=0.5,
        # capacity CYCLES: gangs finish ~1.5s after starting, so even if
        # greedy packing transiently strands a zone-pinned gang behind
        # unpinned load on its only eligible node, the backfill re-batch
        # eventually seats it — the joint-placement existence proof below
        # guarantees feasibility, not that greedy finds it first try
        kubelet_run_duration=1.5,
        **cluster_kwargs,
    )
    cluster.add_nodes(nodes)

    def reserve(members, cpu, zone, tolerant):
        """First-fit the gang's members onto eligible budget; False if the
        gang cannot be guaranteed feasible (caller skips it)."""
        taken = []
        for _ in range(members):
            for ni in node_info:
                if zone is not None and ni["zone"] != zone:
                    continue
                if ni["tainted"] and not tolerant:
                    continue
                if ni["budget"] >= cpu:
                    ni["budget"] -= cpu
                    taken.append(ni)
                    break
            else:
                for ni in taken:
                    ni["budget"] += cpu
                return False
        return True

    feasible, infeasible, pod_batches = [], [], []
    selector_gangs = {}
    n_gangs = int(rng.integers(12, 22))
    for g in range(n_gangs):
        members = int(rng.integers(2, 5))
        cpu = int(rng.integers(1, 4))
        prio = int(rng.integers(0, 3))
        zone = (
            zones[int(rng.integers(0, len(zones)))]
            if rng.random() < 0.6
            else None
        )
        tolerant = bool(rng.random() < 0.5)
        if rng.random() < 0.2:
            name = f"fzs-bad-{g:03d}"
            selector = {"zone": "nowhere"}  # matches NO node
            infeasible.append((name, members))
        else:
            if not reserve(members, cpu, zone, tolerant):
                continue
            name = f"fzs-ok-{g:03d}"
            selector = {"zone": zone} if zone else None
            feasible.append((name, members))
        if selector:
            selector_gangs[name] = (selector, tolerant)
        cluster.create_group(
            make_sim_group(
                name, members, creation_ts=time.time() - (n_gangs - g) * 1e-3
            )
        )
        pod_batches.append(
            make_member_pods(
                name,
                members,
                {"cpu": str(cpu)},
                priority=prio,
                node_selector=selector,
                tolerations=[toleration] if tolerant else None,
            )
        )

    cluster.start()
    for i in rng.permutation(len(pod_batches)):
        cluster.create_pods(pod_batches[int(i)])
    return cluster, feasible, infeasible, selector_gangs


@pytest.mark.parametrize("seed", [613, 724, 835])
def test_fuzz_combo_selector_churn_outage(sim, seed):
    """Adversarial COMPOSITION fuzz (VERDICT r4 item 7): randomized zone
    selectors + capacity churn (gangs finish and release) + gang-TTL
    aborts + a mid-run gateway outage that severs every persistent
    connection — all over the real HTTP stack. The lost-bind-response
    stall and the kept-assume livelock were exactly the bug class only
    composition finds. Asserts the four standing invariants (over-commit
    judged from the backing store's truth, gang atomicity, feasibility
    honesty, liveness) plus zone placement validity: a zone-pinned
    gang's members bind only inside its zone."""
    from batch_scheduler_tpu.client.apiserver import APIServer
    from batch_scheduler_tpu.client.http_apiserver import HTTPAPIServer
    from batch_scheduler_tpu.client.http_gateway import serve_gateway

    rng = np.random.default_rng(seed)
    zones = ["za", "zb"]
    backing = APIServer()
    server = serve_gateway(backing)
    host, port = server.server_address[:2]
    # throttles off: this test targets outage/churn composition, not flow
    # control (benchmarks/http_e2e.py owns the throttled measurement)
    api = HTTPAPIServer(host, port, qps=0)
    try:
        n_nodes = int(rng.integers(8, 14))
        node_zone = [
            zones[int(rng.integers(0, len(zones)))] for _ in range(n_nodes)
        ]
        zone_budget = {z: 0.0 for z in zones}
        nodes = []
        for i, z in enumerate(node_zone):
            cpu = int(rng.choice([4, 8]))
            zone_budget[z] += cpu * 0.6
            nodes.append(
                make_sim_node(
                    f"cb-n{i:03d}",
                    {"cpu": str(cpu), "memory": f"{cpu * 4}Gi", "pods": "110"},
                    labels={"zone": z},
                )
            )

        cluster = sim(
            scorer="oracle",
            api=api,
            max_schedule_minutes=0.05,  # 3s gang TTL: abort paths live
            kubelet_run_duration=1.5,  # churn: capacity cycles mid-run
            backoff_base=0.1,
            backoff_cap=0.5,
            oracle_background_refresh=True,
            min_batch_interval=0.2,
        )
        cluster.add_nodes(nodes)

        feasible, infeasible, pod_batches = [], [], []
        gang_zone = {}
        now = time.time()
        n_gangs = int(rng.integers(8, 14))
        for g in range(n_gangs):
            members = int(rng.integers(2, 5))
            cpu = int(rng.integers(1, 3))
            zone = (
                zones[int(rng.integers(0, len(zones)))]
                if rng.random() < 0.6
                else None
            )
            if rng.random() < 0.2:
                name = f"cb-bad-{g:03d}"
                selector = {"zone": "nowhere"}
                infeasible.append((name, members))
            else:
                if zone is not None:
                    if zone_budget[zone] < members * cpu:
                        continue
                    zone_budget[zone] -= members * cpu
                else:
                    best = max(zone_budget, key=zone_budget.get)
                    if zone_budget[best] < members * cpu:
                        continue
                    zone_budget[best] -= members * cpu
                name = f"cb-ok-{g:03d}"
                selector = {"zone": zone} if zone else None
                feasible.append((name, members))
            if selector and "nowhere" not in selector.values():
                gang_zone[name] = selector["zone"]
            cluster.create_group(
                make_sim_group(
                    name, members, creation_ts=now - (n_gangs - g) * 1e-3
                )
            )
            pod_batches.append(
                make_member_pods(
                    name, members, {"cpu": str(cpu)}, node_selector=selector
                )
            )
        assert feasible, "generator produced no feasible gangs"

        cluster.start()
        for i in rng.permutation(len(pod_batches)):
            cluster.create_pods(pod_batches[int(i)])

        expected = sum(m for _, m in feasible)
        # outage once a third of the work has bound: severs every
        # kept-alive connection mid-flight (bind ambiguity, reflector
        # resync, kept-assume release all engage)
        assert cluster.wait_for(
            lambda: cluster.scheduler.stats["binds"] >= max(1, expected // 3),
            timeout=60.0,
            interval=0.05,
        ), (
            "stalled BEFORE the outage — the mid-bind kill premise never "
            "engaged",
            cluster.scheduler.stats,
        )
        server.shutdown()
        server.server_close()
        time.sleep(0.3)
        server = serve_gateway(backing, host, port)

        # liveness judged from the BACKING STORE: a bind that applied
        # with only its response lost to the outage is real
        def feasible_bound_in_store() -> bool:
            bound = {
                d["metadata"]["name"]
                for d in backing.list("Pod")
                if (d.get("spec") or {}).get("node_name")
            }
            return all(
                sum(1 for b in bound if b.startswith(f"{name}-")) >= members
                for name, members in feasible
            )

        assert cluster.wait_for(
            feasible_bound_in_store, timeout=90.0, interval=0.25
        ), ("feasible work never fully bound", cluster.scheduler.stats)

        # over-commit from the store's truth (the clientset reads through
        # the HTTP API into the same backing store), terminal pods
        # excluded — the shared helper owns the invariant
        _assert_no_overcommit(cluster)
        nodes_by_name = {n.metadata.name: n for n in nodes}

        # atomicity + feasibility honesty + zone exclusivity
        bound_by_gang = {}
        for d in backing.list("Pod"):
            if not (d.get("spec") or {}).get("node_name"):
                continue
            pname = d["metadata"]["name"]
            gang = pname.rsplit("-", 1)[0]
            bound_by_gang.setdefault(gang, []).append(d)
        for name, members in infeasible:
            assert name not in bound_by_gang, (
                f"infeasible gang {name} bound pods"
            )
        for name, members in feasible:
            assert len(bound_by_gang.get(name, [])) >= members, (
                f"feasible gang {name} not fully admitted"
            )
        for name, docs in bound_by_gang.items():
            zone = gang_zone.get(name)
            if zone is None:
                continue
            for d in docs:
                node = nodes_by_name[(d["spec"]["node_name"])]
                assert node.metadata.labels.get("zone") == zone, (
                    f"{name} member on node outside its zone "
                    f"({node.metadata.name}, wanted {zone})"
                )
    finally:
        try:
            cluster.stop()
        except Exception:
            pass
        api.close()
        server.shutdown()
        server.server_close()


@pytest.mark.parametrize(
    "seed,kwargs",
    [
        (411, {}),
        (522, {"oracle_background_refresh": True, "bind_workers": 16}),
    ],
)
def test_fuzz_selector_mask_invariants(sim, seed, kwargs):
    cluster, feasible, infeasible, selector_gangs = _fuzz_selector_scenario(
        sim, seed, **kwargs
    )
    assert selector_gangs, "generator produced no selector gangs"
    assert any(
        name.startswith("fzs-ok") for name in selector_gangs
    ), "no FEASIBLE selector gang generated (mask path untested)"
    expected = sum(m for _, m in feasible)
    assert _await_binds(cluster, expected), (
        "feasible selector work never fully bound",
        expected,
        cluster.scheduler.stats,
    )
    time.sleep(2.0)
    assert cluster.scheduler.stats["binds"] == expected, (
        "more binds than the feasible set",
        expected,
        cluster.scheduler.stats,
    )

    _assert_no_overcommit(cluster)

    # the per-group [G,N] mask path must actually have engaged: selector
    # diversity makes the broadcast [1,N] fast path impossible
    snap = cluster.runtime.operation.oracle.snapshot
    assert snap is not None and snap.fit_mask.shape[0] > 1, (
        "selector fuzz never exercised the per-group fit-mask path",
        None if snap is None else snap.fit_mask.shape,
    )

    nodes = {n.metadata.name: n for n in cluster.clientset.nodes().list()}
    from batch_scheduler_tpu.core import resources as rmath

    for name, members in feasible + infeasible:
        bound = [p for p in cluster.member_pods(name) if p.spec.node_name]
        assert len(bound) == 0 or len(bound) >= members, (
            f"{name}: partial gang bound {len(bound)}/{members}",
            cluster.scheduler.stats,
        )
        # placement validity, judged against the GENERATOR's intent (the
        # stored selector/tolerance), not just the pod's own spec: every
        # bound member sits on a node matching the gang's selector, and a
        # non-tolerant gang never lands on a tainted node
        gen_selector, gen_tolerant = selector_gangs.get(name, (None, True))
        for p in bound:
            node = nodes[p.spec.node_name]
            assert rmath.check_fit(p, node), (
                f"{p.metadata.name} bound to {node.metadata.name} violating "
                f"selector {p.spec.node_selector} / taints {node.spec.taints}"
            )
            if gen_selector is not None:
                assert all(
                    node.metadata.labels.get(k) == v
                    for k, v in gen_selector.items()
                ), (name, gen_selector, node.metadata.labels)
            if not gen_tolerant:
                assert not node.spec.taints, (
                    f"non-tolerant gang {name} on tainted "
                    f"{node.metadata.name}"
                )
    for name, members in infeasible:
        bound = [p for p in cluster.member_pods(name) if p.spec.node_name]
        assert bound == [], f"infeasible gang {name} bound {len(bound)} pods"
    for name, members in feasible:
        bound = [p for p in cluster.member_pods(name) if p.spec.node_name]
        assert len(bound) >= members, (
            f"feasible gang {name} never admitted ({len(bound)}/{members})",
            cluster.scheduler.stats,
        )
