"""API server / clientset / informer / fake clientset tests
(the machinery of reference pkg/generated/, C3-C5 in SURVEY.md §2)."""

import time

import pytest

from batch_scheduler_tpu.api import PodGroupPhase, PodPhase
from batch_scheduler_tpu.client import (
    AlreadyExistsError,
    APIServer,
    Clientset,
    NotFoundError,
    SharedInformerFactory,
    new_simple_clientset,
)

from helpers import make_group, make_node, make_pod


def test_podgroup_crud_roundtrip():
    cs = Clientset(APIServer())
    pg = make_group("g1", 5)
    created = cs.podgroups().create(pg)
    assert created.spec.min_member == 5
    assert created.metadata.resource_version > 0

    got = cs.podgroups().get("g1")
    assert got.full_name() == "default/g1"

    with pytest.raises(AlreadyExistsError):
        cs.podgroups().create(pg)

    got.status.phase = PodGroupPhase.PENDING
    updated = cs.podgroups().update_status(got)
    assert updated.status.phase == PodGroupPhase.PENDING
    # update_status must not touch spec
    assert updated.spec.min_member == 5

    cs.podgroups().delete("g1")
    with pytest.raises(NotFoundError):
        cs.podgroups().get("g1")


def test_patch_merges_status_only():
    cs = Clientset(APIServer())
    cs.podgroups().create(make_group("g", 3))
    patched = cs.podgroups().patch(
        "g", {"status": {"phase": "Scheduling", "scheduled": 2}}
    )
    assert patched.status.phase == PodGroupPhase.SCHEDULING
    assert patched.status.scheduled == 2
    assert patched.spec.min_member == 3


def test_pod_list_by_label_selector():
    cs = Clientset(APIServer())
    for pod in (
        make_pod("a-0", group="a"),
        make_pod("a-1", group="a"),
        make_pod("b-0", group="b"),
        make_pod("solo"),
    ):
        cs.pods().create(pod)
    from batch_scheduler_tpu.utils.labels import POD_GROUP_LABEL

    a_pods = cs.pods().list(label_selector={POD_GROUP_LABEL: "a"})
    assert sorted(p.metadata.name for p in a_pods) == ["a-0", "a-1"]
    assert len(cs.pods().list()) == 4


def test_pod_bind_subresource():
    cs = Clientset(APIServer())
    cs.pods().create(make_pod("p"))
    bound = cs.pods().bind("p", "node-7")
    assert bound.spec.node_name == "node-7"
    assert bound.status.phase == PodPhase.PENDING


def test_nodes_cluster_scoped():
    cs = Clientset(APIServer())
    cs.nodes().create(make_node("n1", {"cpu": "4"}))
    assert cs.nodes().get("n1").status.allocatable["cpu"] == 4000


def test_watch_stream_order_and_replay():
    api = APIServer()
    cs = Clientset(api)
    cs.podgroups().create(make_group("early", 1))
    q = api.watch("PodGroup", replay=True)
    cs.podgroups().patch("early", {"status": {"phase": "Pending"}})
    cs.podgroups().delete("early")
    events = [q.get(timeout=1.0) for _ in range(3)]
    assert [e.type for e in events] == ["ADDED", "MODIFIED", "DELETED"]
    assert events[1].object().status.phase == PodGroupPhase.PENDING


def test_bulk_verbs_fan_out_chunked_batches_in_order():
    """The event-batching contract (round 5): bulk verbs put ONE list per
    commit chunk per watcher; single-object verbs stay single events;
    drain_queue flattens transparently with the flattened total bounded
    near max_batch; relative order is preserved across verb kinds."""
    import queue as _q

    from batch_scheduler_tpu.api.types import to_dict
    from batch_scheduler_tpu.utils.drain import drain_queue

    api = APIServer()
    q = api.watch("Pod", replay=False)
    docs = [to_dict(make_pod(f"b{i:04d}")) for i in range(600)]
    assert api.create_many("Pod", docs, assume_fresh=True) == 600
    api.patch("Pod", "default", "b0000", {"status": {"phase": "Running"}})

    raw_items, flat = [], []
    while True:
        try:
            item = q.get_nowait()
        except _q.Empty:
            break
        raw_items.append(item)
        flat.extend(item if isinstance(item, list) else [item])
    # 600 creates chunk at 256 -> 3 list puts, then ONE single event
    assert [len(i) if isinstance(i, list) else 1 for i in raw_items] == [
        256,
        256,
        88,
        1,
    ]
    assert [e.type for e in flat[:600]] == ["ADDED"] * 600
    assert flat[600].type == "MODIFIED"
    # creation order preserved through the chunked fanout
    assert [e.obj["metadata"]["name"] for e in flat[:3]] == [
        "b0000",
        "b0001",
        "b0002",
    ]

    # drain_queue flattening: bounded near max_batch, order kept
    q2 = api.watch("Pod", replay=False)
    api.bind_pods("default", [(f"b{i:04d}", "n1") for i in range(600)])
    batch = drain_queue(q2, timeout=1.0, max_batch=100)
    # bind chunks are 64: the drain stops once >= 100, overshooting by
    # at most one producer chunk
    assert 100 <= len(batch) <= 164, len(batch)
    assert batch[0].obj["metadata"]["name"] == "b0000"
    rest = drain_queue(q2, timeout=1.0, max_batch=4096)
    assert len(batch) + len(rest) == 600


def test_informer_sync_handlers_and_lister():
    api = APIServer()
    cs = Clientset(api)
    cs.podgroups().create(make_group("pre", 2))
    factory = SharedInformerFactory(api)
    informer = factory.pod_groups()
    seen = {"add": [], "update": [], "delete": []}
    informer.add_event_handler(
        on_add=lambda pg: seen["add"].append(pg.metadata.name),
        on_update=lambda old, new: seen["update"].append(new.metadata.name),
        on_delete=lambda pg: seen["delete"].append(pg.metadata.name),
    )
    factory.start()
    assert factory.wait_for_cache_sync(5.0)

    cs.podgroups().create(make_group("post", 2))
    cs.podgroups().patch("post", {"status": {"phase": "Pending"}})
    cs.podgroups().delete("pre")

    deadline = time.monotonic() + 3.0
    while time.monotonic() < deadline and (
        "post" not in seen["add"]
        or "post" not in seen["update"]
        or "pre" not in seen["delete"]
    ):
        time.sleep(0.02)
    assert "pre" in seen["add"] and "post" in seen["add"]
    assert "post" in seen["update"]
    assert seen["delete"] == ["pre"]

    lister = factory.pod_group_lister()
    assert lister.pod_groups("default").get("post").metadata.name == "post"
    assert lister.pod_groups("default").get("pre") is None
    factory.stop()


def test_fake_clientset_seeding():
    cs = new_simple_clientset(
        make_group("g", 4), make_pod("p", group="g"), make_node("n", {"cpu": "2"})
    )
    assert cs.podgroups().get("g").spec.min_member == 4
    assert cs.pods().get("p").metadata.name == "p"
    assert cs.nodes().get("n").status.allocatable["cpu"] == 2000
