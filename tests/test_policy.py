"""Policy engine tests (batch_scheduler_tpu.policy / docs/policy.md):
zero-policy bit-identity, term steering, preemption-pass invariants
(property-style randomized sweeps), and the end-to-end spot-vs-guaranteed
preemption transaction in the sim."""

import numpy as np
import pytest

from batch_scheduler_tpu.ops import oracle as ok
from batch_scheduler_tpu.policy import (
    DOMAIN_BUCKETS,
    HASH_LANES,
    PolicyConfig,
    PolicyEngine,
    label_hash,
    plan_victims,
)
from batch_scheduler_tpu.policy.engine import PolicyConfig as PC

# one shared small shape: every oracle-level test reuses it so the suite
# pays a handful of jit compiles, not one per test
N, G, R = 16, 8, 3
ALL_TERMS = ("affinity", "anti-affinity", "spread")
WEIGHTS = (32, 8, 3)


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 50, (N, R)).astype(np.int32)
    req = rng.integers(1, 5, (G, R)).astype(np.int32)
    rem = rng.integers(1, 6, G).astype(np.int32)
    mask = np.ones((1, N), np.int32)
    order = np.arange(G, dtype=np.int32)
    return left, req, rem, mask, order


def _zero_cols():
    return (
        np.zeros(G, np.int32),  # prio
        np.zeros(G, np.int32),  # aff
        np.zeros(G, np.int32),  # anti
        np.zeros((G, DOMAIN_BUCKETS), np.int32),
        np.zeros((N, HASH_LANES), np.int32),
        np.zeros(N, np.int32),
    )


# ---------------------------------------------------------------------------
# zero-policy identity (the bench-policy invariant)
# ---------------------------------------------------------------------------


def test_zero_policy_columns_bit_identical_to_base_scan():
    for seed in range(4):
        left, req, rem, mask, order = _batch(seed)
        base = ok.assign_gangs(left, req, rem, mask, order)
        pol = ok.assign_gangs_policy(
            left, req, rem, mask, order, *_zero_cols(),
            policy_terms=ALL_TERMS, policy_weights=WEIGHTS,
        )
        for a, b in zip(base, pol):
            assert np.array_equal(np.asarray(a), np.asarray(b))


def test_policy_off_schedule_batch_untouched():
    left, req, rem, mask, order = _batch(1)
    alloc = np.abs(left) + 10
    requested = np.zeros_like(alloc)
    gv = np.ones(G, bool)
    out0 = ok.schedule_batch(alloc, requested, req, rem, mask, gv, order)
    out1 = ok.schedule_batch(
        alloc, requested, req, rem, mask, gv, order,
        policy_cols=None, policy_terms=(), policy_weights=(),
    )
    for k in ("placed", "assignment", "left_after", "gang_feasible"):
        assert np.array_equal(np.asarray(out0[k]), np.asarray(out1[k]))


# ---------------------------------------------------------------------------
# term steering
# ---------------------------------------------------------------------------


def test_affinity_term_steers_but_never_starves():
    left, req, rem, mask, order = _batch(2)
    left[:, :] = 40  # uniform capacity so only the composite differs
    h = label_hash("zone", "a")
    cols = list(_zero_cols())
    cols[1] = np.full(G, h, np.int32)  # every gang prefers zone=a
    nhash = np.zeros((N, HASH_LANES), np.int32)
    nhash[:4, 0] = h  # nodes 0-3 match
    cols[4] = nhash
    allocp, placedp, _ = ok.assign_gangs_policy(
        left, req, rem, mask, order, *cols,
        policy_terms=ALL_TERMS, policy_weights=WEIGHTS,
    )
    allocp = np.asarray(allocp)
    # matching nodes have plenty of capacity: every member lands there
    assert allocp[:, 4:].sum() == 0
    assert np.asarray(placedp).all()
    # starvation check: matchers full -> gangs still place elsewhere
    left2 = left.copy()
    left2[:4] = 0
    alloc2, placed2, _ = ok.assign_gangs_policy(
        left2, req, rem, mask, order, *cols,
        policy_terms=ALL_TERMS, policy_weights=WEIGHTS,
    )
    assert np.asarray(placed2).all()
    assert np.asarray(alloc2)[:, :4].sum() == 0


def test_anti_affinity_is_a_hard_mask():
    left, req, rem, mask, order = _batch(3)
    h = label_hash("team", "red")
    cols = list(_zero_cols())
    anti = np.zeros(G, np.int32)
    anti[2] = h
    cols[2] = anti
    nhash = np.zeros((N, HASH_LANES), np.int32)
    nhash[5:9, 1] = h
    cols[4] = nhash
    allocp, _, _ = ok.assign_gangs_policy(
        left, req, rem, mask, order, *cols,
        policy_terms=ALL_TERMS, policy_weights=WEIGHTS,
    )
    assert np.asarray(allocp)[2, 5:9].sum() == 0


def test_spread_term_prefers_empty_domains():
    left, req, rem, mask, order = _batch(4)
    left[:, :] = 40
    rem[:] = 2
    cols = list(_zero_cols())
    node_dom = np.zeros(N, np.int32)
    node_dom[: N // 2] = 1  # first half = domain 1, rest = domain 0
    cols[5] = node_dom
    gdom = np.zeros((G, DOMAIN_BUCKETS), np.int32)
    gdom[:, 1] = 3  # every gang already crowds domain 1
    cols[3] = gdom
    allocp, _, _ = ok.assign_gangs_policy(
        left, req, rem, mask, order, *cols,
        policy_terms=ALL_TERMS, policy_weights=WEIGHTS,
    )
    allocp = np.asarray(allocp)
    # capacity is uniform, so the spread penalty decides: all members
    # land in the uncrowded domain 0 (second half of the node axis)
    assert allocp[:, : N // 2].sum() == 0


# ---------------------------------------------------------------------------
# engine config / env parsing
# ---------------------------------------------------------------------------


def test_policy_config_env_parse_guard(monkeypatch):
    monkeypatch.setenv("BST_POLICY", "affinity, bogus-term ,preempt")
    cfg = PC.from_env()
    assert cfg.terms == ("affinity", "preempt")
    assert cfg.preemption
    monkeypatch.setenv("BST_POLICY", "off")
    assert not PC.from_env().enabled
    monkeypatch.setenv("BST_POLICY", "all")
    assert set(PC.from_env().terms) >= {"affinity", "spread", "preempt"}
    monkeypatch.setenv("BST_POLICY_AFFINITY_WEIGHT", "not-a-number")
    assert PC.from_env().affinity_weight == 32  # degrade, never crash


def test_policy_fingerprint_names_knobs():
    a = PolicyConfig(terms=("affinity",)).fingerprint()
    b = PolicyConfig(terms=("affinity",), affinity_weight=64).fingerprint()
    assert a["fingerprint"] != b["fingerprint"]
    assert a["affinity_weight"] == 32 and b["affinity_weight"] == 64
    assert len(a["fingerprint"]) == 16


# ---------------------------------------------------------------------------
# preemption-pass invariants (property-style randomized sweeps)
# ---------------------------------------------------------------------------

VN, VR, VV = 8, 2, 8  # one (nodes, lanes, victims) bucket -> one compile


def _random_preempt_case(seed):
    rng = np.random.default_rng(seed)
    left = rng.integers(0, 3, (VN, VR)).astype(np.int32)
    fit = np.ones(VN, np.int32)
    req = np.array([2, 1], np.int32)
    need = int(rng.integers(1, 7))
    prio = int(rng.integers(1, 5))
    valloc = rng.integers(0, 3, (VV, VN)).astype(np.int32)
    vreq = np.stack(
        [np.array([int(rng.integers(1, 4)), 1], np.int32) for _ in range(VV)]
    )
    vprio = rng.integers(0, 6, VV).astype(np.int32)
    vvalid = (rng.random(VV) < 0.8).astype(np.int32)
    order = np.array(
        sorted(
            range(VV),
            key=lambda i: (-vvalid[i], int(vprio[i]), int(valloc[i].sum())),
        ),
        np.int32,
    )
    return left, fit, req, need, prio, valloc, vreq, vprio, vvalid, order


def _pooled(left, fit, req, need):
    safe = np.maximum(req, 1)
    per = np.where(req[None, :] > 0, np.clip(left, 0, None) // safe, 2**30)
    cap = per.min(axis=1) * fit
    return int(np.minimum(cap, need).sum())


@pytest.mark.parametrize("seed", range(25))
def test_preemption_invariants(seed):
    (left, fit, req, need, prio, valloc, vreq, vprio, vvalid,
     order) = _random_preempt_case(seed)
    taken, feasible, pooled_after = plan_victims(
        left, fit, req, np.int32(need), np.int32(prio),
        valloc, vreq, vprio, vvalid, order,
    )
    taken = np.asarray(taken)
    feasible = bool(feasible)

    # invariant 1: never evicts an equal-or-higher priority (or invalid) gang
    for v in range(VV):
        if taken[v]:
            assert vvalid[v] and vprio[v] < prio

    def freed(sel):
        out = left.astype(np.int64).copy()
        for v in range(VV):
            if sel[v]:
                out += valloc[v][:, None].astype(np.int64) * vreq[v][None, :]
        return out.astype(np.int32)

    if feasible and taken.any():
        # invariant 2: the plan frees sufficient capacity, re-verified
        # against the leftover with independent host math
        assert _pooled(freed(taken), fit, req, need) >= need
        # invariant 3: inclusion-minimality — dropping any single victim
        # leaves the preemptor uncovered
        for v in range(VV):
            if taken[v]:
                reduced = taken.copy()
                reduced[v] = False
                assert _pooled(freed(reduced), fit, req, need) < need
    if not feasible:
        # even evicting EVERY eligible victim cannot cover the need
        every = (vvalid > 0) & (vprio < prio)
        assert _pooled(freed(every), fit, req, need) < need
        assert not taken.any()  # an infeasible pass evicts nothing

    # determinism: same inputs, same plan
    taken2, feas2, _ = plan_victims(
        left, fit, req, np.int32(need), np.int32(prio),
        valloc, vreq, vprio, vvalid, order,
    )
    assert np.array_equal(taken, np.asarray(taken2))
    assert feasible == bool(feas2)


# ---------------------------------------------------------------------------
# snapshot packing
# ---------------------------------------------------------------------------


def test_snapshot_packs_policy_columns_and_delta_rewrites():
    from batch_scheduler_tpu.ops.snapshot import (
        DeltaSnapshotPacker,
        GroupDemand,
    )
    from batch_scheduler_tpu.sim.scenarios import make_sim_node

    engine = PolicyEngine(PolicyConfig(
        terms=ALL_TERMS, spread_node_key="zone"
    ))
    nodes = [
        make_sim_node(f"n{i}", {"cpu": "8", "pods": "20"},
                      labels={"zone": f"z{i % 2}", "team": "blue"})
        for i in range(4)
    ]
    groups = [
        GroupDemand(
            full_name="default/g0", min_member=2,
            member_request={"cpu": 1},
            affinity_hash=label_hash("team", "blue"),
            spread=True, placed_nodes={"n0": 1, "n1": 2},
            priority=7,
        )
    ]
    packer = DeltaSnapshotPacker(policy_engine=engine)
    snap = packer.pack(nodes, {}, groups)
    assert snap.policy_cols is not None
    prio, aff, anti, gdom, nhash, ndom = snap.policy_cols
    assert prio[0] == 7
    assert aff[0] == label_hash("team", "blue")
    assert (nhash[:4] > 0).any()
    # spread occupancy: n0 (z0) holds 1, n1 (z1) holds 2
    z0 = label_hash("zone", "z0") % DOMAIN_BUCKETS
    z1 = label_hash("zone", "z1") % DOMAIN_BUCKETS
    assert gdom[0, z0] == 1 and gdom[0, z1] == 2
    payload = snap.policy_payload()
    assert payload is not None and payload[1] == engine.config.scoring_terms

    # delta discipline: unchanged labels -> zero policy rows rewritten
    packer.pack(nodes, {}, groups)
    assert packer.policy_rows_rewritten == 0
    nodes[2].metadata.labels["zone"] = "z9"
    packer.pack(nodes, {}, groups)
    assert packer.policy_rows_rewritten == 1


def test_preemption_only_config_keeps_base_rungs():
    from batch_scheduler_tpu.ops.snapshot import (
        DeltaSnapshotPacker,
        GroupDemand,
    )
    from batch_scheduler_tpu.sim.scenarios import make_sim_node

    engine = PolicyEngine(PolicyConfig(terms=("preempt",)))
    packer = DeltaSnapshotPacker(policy_engine=engine)
    snap = packer.pack(
        [make_sim_node("n0", {"cpu": "8", "pods": "20"})], {},
        [GroupDemand(full_name="default/g0", min_member=1,
                     member_request={"cpu": 1})],
    )
    # columns packed (the planner reads priorities) but NO scoring terms:
    # the batch must ride the base scan rungs, not the policy rung
    assert snap.policy_cols is not None
    assert snap.policy_payload() is None


# ---------------------------------------------------------------------------
# audit replay with policies on
# ---------------------------------------------------------------------------


def test_policy_audit_record_replays_bit_identically(tmp_path):
    from batch_scheduler_tpu.core.oracle_scorer import replay_audit_record
    from batch_scheduler_tpu.utils import audit as audit_mod

    left, req, rem, mask, order = _batch(5)
    alloc = np.abs(left) + 10
    requested = np.zeros_like(alloc)
    gv = np.ones(G, bool)
    batch_args = (alloc, requested, req, rem, mask, gv, order)
    prog = (rem, np.zeros(G, np.int32), np.zeros(G, np.int32),
            np.zeros(G, bool), np.arange(G, dtype=np.int32))
    cols = list(_zero_cols())
    h = label_hash("zone", "a")
    cols[1][:] = h
    cols[4][: N // 2, 0] = h
    policy = (tuple(cols), ALL_TERMS, WEIGHTS)
    host, _ = ok.execute_batch_host(batch_args, prog, policy=policy)
    assert host["telemetry"]["scan_policy"] is True

    log = audit_mod.AuditLog(str(tmp_path / "ring"))
    log.record_batch(
        batch_args=batch_args, progress_args=prog, result=host,
        plan_digest=audit_mod.plan_digest(host), policy=policy,
    )
    assert log.stop()
    batches, skipped = audit_mod.AuditReader(str(tmp_path / "ring")).batches()
    assert not skipped and len(batches) == 1
    rec = batches[0]
    assert rec["policy_args"][1] == ALL_TERMS
    for rung in ("steady", "cpu-ladder"):
        rep = replay_audit_record(rec, against=rung)
        assert rep["identical"], rep.get("blame")
        assert rep["executed_rung"]["scan_policy"] is True


# ---------------------------------------------------------------------------
# wire: the POLICY_INFO fingerprint annotation
# ---------------------------------------------------------------------------


def test_policy_info_annotation_roundtrip_and_skew_counter():
    from batch_scheduler_tpu.service import protocol as proto
    from batch_scheduler_tpu.service.server import _Handler
    from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY

    fp = PolicyConfig(terms=("affinity",)).fingerprint()["fingerprint"]
    assert proto.unpack_policy_info(proto.pack_policy_info(fp)) == fp
    with pytest.raises(ValueError):
        proto.pack_policy_info("short")
    counter = DEFAULT_REGISTRY.counter(
        "bst_policy_fingerprint_mismatch_total", ""
    )
    before = counter.value()
    # this process's active engine (if any) cannot share a random peer fp
    _Handler._note_policy_skew("f" * 16)
    assert counter.value() == before + 1


# ---------------------------------------------------------------------------
# end-to-end: spot vs guaranteed through the sim
# ---------------------------------------------------------------------------


@pytest.mark.slow  # full SimCluster runs (~50s each on the CI box) ride
# the slow marker so tier-1 stays inside the 870s budget (the PR-7
# discipline); `make test` / `pytest -m slow` run them, and
# `make bench-policy` gates the identity claims deterministically
def test_spot_vs_guaranteed_preemption_e2e():
    from batch_scheduler_tpu.sim.harness import SimCluster
    from batch_scheduler_tpu.sim.scenarios import spot_vs_guaranteed_scenario
    from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY

    nodes, groups, pods = spot_vs_guaranteed_scenario()
    before = DEFAULT_REGISTRY.counter("bst_preemptions_total", "").value(
        reason="priority-tier"
    )
    sim = SimCluster(
        policy=PolicyConfig(terms=("preempt",)), kubelet_start_delay=0.01
    )
    try:
        sim.add_nodes(nodes)
        spot_names = [g.metadata.name for g in groups
                      if g.metadata.name.startswith("spot")]
        for g in groups:
            if g.metadata.name.startswith("spot"):
                sim.create_group(g)
        sim.start()
        for name in spot_names:
            sim.create_pods(pods[name])
        for name in spot_names:
            assert sim.wait_for_bound(name, 4, timeout=120), name
        uids_before = {
            name: {p.metadata.uid for p in sim.member_pods(name)}
            for name in spot_names
        }

        # guaranteed arrives into a FULL cluster: only preemption places it
        for g in groups:
            if g.metadata.name.startswith("guaranteed"):
                sim.create_group(g)
        sim.create_pods(pods["guaranteed-000"])

        # preemptor-side blame record names its victim count. Read it as
        # it LANDS (polling), not after binding: the respawn race can add
        # preempt/deny rounds whose records churn the 32-deep ring
        def preempt_blamed():
            recs = sim.decisions("guaranteed-000").get(
                "default/guaranteed-000", []
            )
            return [
                r for r in recs
                if r.get("verdict") == "placed-via-preemption"
            ]

        assert sim.wait_for(lambda: bool(preempt_blamed()), timeout=90)
        assert preempt_blamed()[0]["victims"] >= 1
        assert sim.wait_for_bound("guaranteed-000", 4, timeout=120)

        after = DEFAULT_REGISTRY.counter("bst_preemptions_total", "").value(
            reason="priority-tier"
        )
        assert after > before  # the new counter is visible end-to-end

        # evicted gangs re-entered the queue exactly once: each evicted
        # member was respawned as ONE fresh Pending pod (same name, NEW
        # uid) — member counts per spot gang stay exactly min_member and
        # at least one spot gang's uid set changed wholesale. (The
        # victim-side flight record exists too, but its 32-deep ring can
        # churn past it under respawn-retry denials — the uid evidence is
        # ring-independent.)
        respawned_gangs = 0
        for name in spot_names:
            members = sim.member_pods(name)
            assert len(members) == 4, name
            now_uids = {p.metadata.uid for p in members}
            if not (now_uids & uids_before[name]):
                respawned_gangs += 1
        assert respawned_gangs >= 1, "no spot gang was evicted+respawned"

        # lifecycle-clock regression: the eviction must not reset the
        # victim's story — its ledger record keeps the ORIGINAL arrival
        # anchor (TTP includes preemption churn) and the post-eviction
        # re-arrivals are relabeled `respawn`, ordered after `evicted`
        from batch_scheduler_tpu.utils.lifecycle import DEFAULT_LEDGER

        def evicted_with_respawn():
            out = []
            for g, tv in DEFAULT_LEDGER.snapshot()["gangs"].items():
                evs = [e["event"] for e in tv["events"]]
                if "evicted" in evs and "respawn" in evs:
                    out.append((g, evs, tv["anchors"]["arrival"]))
            return out

        assert sim.wait_for(
            lambda: len(evicted_with_respawn()) >= 1, timeout=30
        ), "no evicted+respawned gang reached the lifecycle ledger"
        for g, evs, arrival in evicted_with_respawn():
            assert evs.index("evicted") < evs.index("respawn"), (g, evs)
            assert arrival is not None, g
    finally:
        sim.stop()


@pytest.mark.slow  # waits out the 20s deny TTL; tier-1 keeps the
# spot-vs-guaranteed e2e (which proves eviction + respawn + blame)
def test_evicted_gang_requeues_and_reschedules():
    """After eviction the victim gang's respawned pods re-enter the queue
    and reschedule once capacity frees (the guaranteed workload
    departing)."""
    from batch_scheduler_tpu.sim.harness import SimCluster
    from batch_scheduler_tpu.sim.scenarios import (
        make_member_pods,
        make_sim_group,
        make_sim_node,
    )

    # one 8-cpu node; spot gang fills it; guaranteed gang evicts; the
    # guaranteed pods are then deleted (the workload departing), freeing
    # capacity for the respawned spot gang to reschedule
    node = make_sim_node("n0", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    spot = make_sim_group("spot-a", 4)
    guar = make_sim_group("guar-a", 4)
    sim = SimCluster(
        policy=PolicyConfig(terms=("preempt",)), kubelet_start_delay=0.01
    )
    try:
        sim.add_nodes([node])
        sim.create_group(spot)
        sim.start()
        sim.create_pods(make_member_pods("spot-a", 4, {"cpu": "2"}))
        assert sim.wait_for_bound("spot-a", 4, timeout=120)
        spot_uids = {p.metadata.uid for p in sim.member_pods("spot-a")}

        sim.create_group(guar)
        sim.create_pods(
            make_member_pods("guar-a", 4, {"cpu": "2"}, priority=10)
        )
        assert sim.wait_for_bound("guar-a", 4, timeout=120)

        # the spot gang was evicted and respawned exactly once: 4 member
        # pods exist again, ALL with fresh UIDs, all unbound
        respawned = sim.member_pods("spot-a")
        assert len(respawned) == 4
        assert not (spot_uids & {p.metadata.uid for p in respawned})

        # the guaranteed workload departs: its capacity frees and the
        # respawned spot gang reschedules (the deny-cache entry expires
        # within its 20s TTL)
        for p in sim.member_pods("guar-a"):
            sim.clientset.pods("default").delete(p.metadata.name)
        assert sim.wait_for_bound("spot-a", 4, timeout=120)
    finally:
        sim.stop()
