"""Hierarchical top-K scan (ops.oracle.assign_gangs_topk and its
node-sharded composition): bit-identity with the dense serial scan across
candidate widths and shard counts, demotion-backed exactness under
adversarial tight fits, padded-node safety, the dispatch ladder's gate
isolation, and cross-rung replay identity through the audit log
(docs/scan_parallelism.md "Hierarchical top-K").

Every distinct (shape, K, mesh) is a fresh shard_map compile (~30s on the
CPU-mesh host), so the tier-1 set keeps ONE compile per code path and the
widening matrices (extra Ks per mesh, sharded per-group/mega/adversarial
variants, the full-batch and budget lowers) ride `-m slow`."""

import numpy as np
import pytest

import jax.numpy as jnp

from batch_scheduler_tpu.core.oracle_scorer import (
    replay_audit_record,
)
from batch_scheduler_tpu.ops import oracle as okern
from batch_scheduler_tpu.ops.bucketing import topk_bucket
from batch_scheduler_tpu.ops.oracle import (
    assign_gangs,
    assign_gangs_topk,
    assign_gangs_topk_sharded,
    execute_batch_host,
    forced_scan_rung,
    scan_topk_active,
    schedule_batch,
)
from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot, GroupDemand
from batch_scheduler_tpu.parallel.mesh import (
    make_mesh,
    shard_snapshot_args,
    sharded_scan_collective_counts,
    sharded_schedule_batch,
)
from batch_scheduler_tpu.sim.scenarios import make_sim_node
from batch_scheduler_tpu.utils import audit as audit_mod
from batch_scheduler_tpu.utils.audit import AuditLog, AuditReader


def _scan_case(n=48, g=14, r=3, per_group=False, uniform=False, seed=7):
    """Raw assign_gangs inputs (unbucketed, so N can be shard-uneven)."""
    rng = np.random.RandomState(seed)
    left = jnp.asarray(rng.randint(0, 120, size=(n, r)), jnp.int32)
    if uniform:
        req = jnp.asarray(
            np.tile(rng.randint(1, 6, size=(1, r)), (g, 1)), jnp.int32
        )
    else:
        req = jnp.asarray(rng.randint(0, 6, size=(g, r)), jnp.int32)
    rem = jnp.asarray(rng.randint(0, 30, size=(g,)), jnp.int32)
    if per_group:
        mask = jnp.asarray(rng.randint(0, 2, size=(g, n)), jnp.int32)
    else:
        mask = jnp.ones((1, n), jnp.int32)
    order = jnp.asarray(rng.permutation(g), jnp.int32)
    return left, req, rem, mask, order


def _assert_identical(args, k, mesh=None, wave=4, want_dense=None):
    a0, p0, l0 = (np.asarray(x) for x in assign_gangs(*args))
    if mesh is None:
        a1, p1, l1, stats = assign_gangs_topk(
            *args, wave=wave, k=k, with_stats=True
        )
    else:
        a1, p1, l1, stats = assign_gangs_topk_sharded(
            *args, mesh=mesh, wave=wave, k=k, with_stats=True
        )
    np.testing.assert_array_equal(a0, np.asarray(a1))
    np.testing.assert_array_equal(p0, np.asarray(p1))
    np.testing.assert_array_equal(l0, np.asarray(l1))
    dense_n = int(np.asarray(stats[2]).sum())
    if want_dense is not None:
        assert (dense_n > 0) is want_dense, stats
    return dense_n


# ---------------------------------------------------------------------------
# bit-identity: candidate width and shard count are layout choices
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k", [4, 16, 64])
def test_bit_identical_across_candidate_widths(k):
    """K is a performance knob, never a semantic one: any width must
    reproduce the dense plan exactly (demotion fills the gap when K is
    too small to cover a gang)."""
    _assert_identical(_scan_case(per_group=False, uniform=False, seed=k), k)


@pytest.mark.parametrize("n_devices", [2, 4])
def test_bit_identical_across_shard_meshes(n_devices):
    """The sharded composition: each shard coarse-ranks only its slice
    and the merged global top-K drives the identical replicated
    selection on every shard."""
    _assert_identical(
        _scan_case(per_group=False, uniform=False, seed=31 + n_devices),
        16,
        mesh=make_mesh(n_devices),
    )


@pytest.mark.slow
def test_bit_identical_single_shard_mesh():
    """The degenerate 1-shard mesh: the shard_map plumbing with no real
    partitioning (the merge becomes local arithmetic)."""
    _assert_identical(
        _scan_case(per_group=False, uniform=False, seed=32),
        16,
        mesh=make_mesh(1),
    )


@pytest.mark.slow
@pytest.mark.parametrize("k", [4, 64])
def test_bit_identical_shard_mesh_k_sweep(k):
    """Widening matrix: candidate widths beyond the tier-1 K=16 on the
    4-shard mesh (each K is a fresh shard_map compile)."""
    _assert_identical(
        _scan_case(per_group=False, uniform=False, seed=40 + k),
        k,
        mesh=make_mesh(4),
    )


def test_per_group_masks_stay_identical():
    _assert_identical(
        _scan_case(n=32, g=10, per_group=True, uniform=False, seed=17), 8
    )


@pytest.mark.slow
def test_per_group_masks_sharded_stay_identical():
    _assert_identical(
        _scan_case(n=32, g=10, per_group=True, uniform=False, seed=18),
        8,
        mesh=make_mesh(4),
    )


def test_uniform_waves_use_candidate_stream_and_stay_identical():
    """Bulk-identical gangs ride the restricted aggregate member stream
    (the mega path) — boundary feasibilities recovered from pooled −
    candidate-entry + candidate-post sums must match the dense plan."""
    _assert_identical(
        _scan_case(n=64, g=16, per_group=False, uniform=True, seed=5), 16
    )


@pytest.mark.slow
def test_uniform_waves_sharded_stay_identical():
    _assert_identical(
        _scan_case(n=64, g=16, per_group=False, uniform=True, seed=6),
        16,
        mesh=make_mesh(4),
    )


def test_uneven_node_counts_padded_rows_never_win():
    """N not divisible by the shard count pads the node axis internally;
    identity with the serial scan proves a padded (capacity-0) row never
    ranks into any candidate set, and shapes stay in caller space."""
    n = 37
    mesh = make_mesh(4)
    args = _scan_case(n=n, g=9, uniform=False, seed=n)
    _assert_identical(args, 8, mesh=mesh)
    alloc, placed, left = assign_gangs_topk_sharded(
        *args, mesh=mesh, wave=4, k=8
    )
    assert alloc.shape == (9, n)
    assert left.shape == (n, args[0].shape[1])


@pytest.mark.slow
@pytest.mark.parametrize("n", [50, 61])
def test_uneven_node_counts_widening(n):
    _assert_identical(
        _scan_case(n=n, g=9, uniform=False, seed=n), 8, mesh=make_mesh(4)
    )


# ---------------------------------------------------------------------------
# demotion: exactness by construction, not by hoping K is big enough
# ---------------------------------------------------------------------------


def test_adversarial_tight_fit_forces_dense_demotion():
    """Capacity shredded one member per node: a gang needing 10 members
    cannot be covered by K=4 candidates while pooled capacity says
    placement exists, so the gang MUST demote to the dense-column replay
    (bst_topk_demotions) — and the plan must still be the dense plan."""
    n, g, r = 40, 3, 2
    left = jnp.full((n, r), 5, jnp.int32)       # one member per node
    req = jnp.full((g, r), 5, jnp.int32)
    rem = jnp.asarray([10, 10, 10], jnp.int32)  # spans 10 nodes >> K=4
    mask = jnp.ones((1, n), jnp.int32)
    order = jnp.asarray([0, 1, 2], jnp.int32)
    args = (left, req, rem, mask, order)
    dense_n = _assert_identical(args, 4, want_dense=True)
    assert dense_n >= 3  # every gang outran its candidate set
    # a covering K places the same gangs with zero demotions
    _assert_identical(args, 16, want_dense=False)


@pytest.mark.slow
def test_adversarial_tight_fit_sharded_demotes_identically():
    n, g, r = 40, 3, 2
    left = jnp.full((n, r), 5, jnp.int32)
    req = jnp.full((g, r), 5, jnp.int32)
    rem = jnp.asarray([10, 10, 10], jnp.int32)
    mask = jnp.ones((1, n), jnp.int32)
    order = jnp.asarray([0, 1, 2], jnp.int32)
    _assert_identical(
        (left, req, rem, mask, order), 4, mesh=make_mesh(4), want_dense=True
    )


def test_pooled_infeasible_gang_needs_no_demotion():
    """A gang the whole cluster cannot hold is exactly-infeasible from
    the wave-entry pooled bound alone (capacities only decrease within a
    batch): no dense replay, no placement, identical to dense."""
    n, g, r = 24, 2, 2
    left = jnp.full((n, r), 5, jnp.int32)
    req = jnp.full((g, r), 5, jnp.int32)
    rem = jnp.asarray([n + 10, 4], jnp.int32)   # gang 0 can never fit
    mask = jnp.ones((1, n), jnp.int32)
    order = jnp.asarray([0, 1], jnp.int32)
    dense_n = _assert_identical(
        (left, req, rem, mask, order), 4, want_dense=False
    )
    assert dense_n == 0


# ---------------------------------------------------------------------------
# knob bucketing
# ---------------------------------------------------------------------------


def test_topk_bucket_snaps_to_static_widths():
    assert topk_bucket(0) == 0
    assert topk_bucket(-3) == 0
    assert topk_bucket(1) == 4
    assert topk_bucket(5) == 8
    assert topk_bucket(16) == 16
    assert topk_bucket(200) == 128


# ---------------------------------------------------------------------------
# dispatch ladder: rung selection, gate isolation, telemetry
# ---------------------------------------------------------------------------


def _snapshot_args(num_nodes=48, num_groups=18):
    nodes = [
        make_sim_node(f"n{i:03d}", {"cpu": "16", "memory": "64Gi", "pods": "32"})
        for i in range(num_nodes)
    ]
    groups = [
        GroupDemand(
            full_name=f"default/g{x:03d}",
            min_member=4 + (x % 3),
            member_request={"cpu": 2000, "memory": 4 * 1024**3},
            creation_ts=float(x),
        )
        for x in range(num_groups)
    ]
    return ClusterSnapshot(nodes, {}, groups).device_args()


def _progress_args(g):
    return (
        jnp.full((g,), 4, jnp.int32),
        jnp.zeros((g,), jnp.int32),
        jnp.full((g,), 4, jnp.int32),
        jnp.zeros((g,), bool),
        jnp.arange(g, dtype=jnp.int32),
    )


def test_env_knob_selects_topk_rung(monkeypatch):
    monkeypatch.setenv("BST_SCAN_TOPK", "16")
    assert scan_topk_active()
    args = _snapshot_args(num_nodes=24, num_groups=8)
    host, _ = execute_batch_host(
        args, _progress_args(np.asarray(args[2]).shape[0])
    )
    tel = host["telemetry"]
    assert tel["scan_topk"] == 16
    assert "topk_demotions" in tel
    assert "waves_per_batch" in tel
    # the plan matches the dense rung bit-for-bit
    monkeypatch.delenv("BST_SCAN_TOPK")
    dense, _ = execute_batch_host(
        args, _progress_args(np.asarray(args[2]).shape[0])
    )
    for key in ("placed", "gang_feasible", "assignment_nodes"):
        np.testing.assert_array_equal(
            np.asarray(dense[key]), np.asarray(host[key]), err_msg=key
        )


def test_unparseable_env_knob_degrades_to_dense(monkeypatch):
    monkeypatch.setenv("BST_SCAN_TOPK", "many")
    assert not scan_topk_active()
    args = _snapshot_args(num_nodes=24, num_groups=8)
    host, _ = execute_batch_host(
        args, _progress_args(np.asarray(args[2]).shape[0])
    )
    assert host["telemetry"]["scan_topk"] == 0


def test_topk_composes_with_sharded_layout_on_mesh(monkeypatch):
    monkeypatch.setenv("BST_SCAN_TOPK", "8")
    args = _snapshot_args(num_nodes=24, num_groups=8)
    mesh = make_mesh(4)
    placed_args = shard_snapshot_args(mesh, args, flat_nodes=True)
    host, _ = execute_batch_host(
        placed_args, _progress_args(np.asarray(args[2]).shape[0]),
        scan_mesh=mesh,
    )
    tel = host["telemetry"]
    assert tel["scan_topk"] == 8
    assert "topk_demotions" in tel


def test_ladder_fallback_disables_only_the_topk_gate(monkeypatch):
    """A top-K rung failure demotes THIS batch to the dense ladder and
    flips only _topk_enabled — never the wave, pallas, or sharded gates
    (independent features must not poison each other). Uses a bucket
    shape no other test dispatches top-K, so the failure fires at trace
    time instead of hitting the jit cache."""
    monkeypatch.setenv("BST_SCAN_TOPK", "16")
    args = _snapshot_args(num_nodes=40, num_groups=12)
    g = np.asarray(args[2]).shape[0]
    monkeypatch.delenv("BST_SCAN_TOPK")
    single, _ = execute_batch_host(args, _progress_args(g))
    monkeypatch.setenv("BST_SCAN_TOPK", "16")

    def boom(*a, **kw):
        raise RuntimeError("top-K lowering exploded")

    monkeypatch.setattr(okern, "assign_gangs_topk", boom)
    wave_before = okern._wave_enabled[0]
    sharded_before = okern._sharded_enabled[0]
    pallas_before = dict(okern._pallas_enabled)
    try:
        with pytest.warns(UserWarning, match="top-K"):
            host, _ = execute_batch_host(args, _progress_args(g))
        assert host["telemetry"]["scan_topk"] == 0
        assert okern._topk_enabled[0] is False
        assert okern._wave_enabled[0] == wave_before
        assert okern._sharded_enabled[0] == sharded_before
        assert okern._pallas_enabled == pallas_before
        assert not scan_topk_active()
        np.testing.assert_array_equal(
            np.asarray(single["placed"]), np.asarray(host["placed"])
        )
    finally:
        okern._topk_enabled[0] = True


def test_forced_rung_pin_runs_local_topk_never_sharded():
    """A (pallas=False, wave, topk) pin on a mesh must run the LOCAL
    top-K variant — pinned replays are single-process by contract, and
    the sharded compositions are verified by cross-rung identity."""
    args = _snapshot_args(num_nodes=24, num_groups=8)
    mesh = make_mesh(4)
    with forced_scan_rung(False, 8, 16):
        host, _ = execute_batch_host(
            args, _progress_args(np.asarray(args[2]).shape[0]),
            scan_mesh=mesh,
        )
    tel = host["telemetry"]
    assert tel["scan_topk"] == 16
    assert tel["scan_sharded"] is False


@pytest.mark.slow
def test_full_batch_topk_matches_single_device():
    """The fused schedule_batch on the sharded top-K layout agrees with
    the plain single-device batch on every output field."""
    args = _snapshot_args()
    single = {
        k: np.asarray(v)
        for k, v in execute_batch_host(
            args, _progress_args(np.asarray(args[2]).shape[0])
        )[0].items()
        if k in ("placed", "gang_feasible", "assignment_nodes")
    }
    mesh = make_mesh(4)
    import jax

    sharded = jax.device_get(
        sharded_schedule_batch(mesh, args, sharded_scan=True, scan_topk=16)
    )
    for key in ("gang_feasible", "placed", "capacity", "assignment"):
        got = np.asarray(sharded[key])
        want = np.asarray(jax.device_get(schedule_batch(*args))[key])
        np.testing.assert_array_equal(want, got, err_msg=key)
    np.testing.assert_array_equal(
        single["placed"], np.asarray(sharded["placed"])
    )


@pytest.mark.slow
def test_scan_only_collective_budget_stays_summary_sized():
    """The sharded top-K module's collectives are all candidate-summary
    sized: no [N, R] node state ever rides a collective, and instruction
    sites do not grow with G."""
    mesh = make_mesh(4)
    small = sharded_scan_collective_counts(
        mesh, _snapshot_args(64, 8), topk=8
    )
    big = sharded_scan_collective_counts(
        mesh, _snapshot_args(64, 32), topk=8
    )
    assert small["counts"] == big["counts"], (small, big)
    assert big["waves"] > small["waves"]
    for rep in (small, big):
        assert rep["max_collective_bytes"] <= rep["summary_bytes"], rep
        assert rep["counts"]["collective-permute"] == 0, rep
        assert rep["counts"]["all-gather"] + rep["counts"]["all-reduce"] > 0


# ---------------------------------------------------------------------------
# cross-rung replay identity through the audit log
# ---------------------------------------------------------------------------


def _audited_batch(tmp_path, monkeypatch, topk_env=None):
    if topk_env is not None:
        monkeypatch.setenv("BST_SCAN_TOPK", str(topk_env))
    else:
        monkeypatch.delenv("BST_SCAN_TOPK", raising=False)
    snap_nodes = [
        make_sim_node(f"n{i}", {"cpu": "8", "memory": "32Gi", "pods": "64"})
        for i in range(6)
    ]
    groups = [
        GroupDemand(f"default/g{i}", 3, member_request={"cpu": 1000},
                    creation_ts=float(i))
        for i in range(4)
    ]
    snap = ClusterSnapshot(snap_nodes, {}, groups)
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    log = AuditLog(str(tmp_path))
    log.record_batch(
        batch_args=snap.device_args(),
        progress_args=snap.progress_args(),
        result=host,
        plan_digest=audit_mod.plan_digest(host),
        node_names=snap.node_names,
        group_names=snap.group_names,
    )
    assert log.flush()
    (rec,), _ = AuditReader(str(tmp_path)).batches()
    log.stop()
    return rec, host


def test_topk_recorded_batch_replays_identically_on_dense_rungs(
    tmp_path, monkeypatch
):
    """A batch RECORDED on the top-K rung replays bit-identically on the
    dense rungs — the demotion-backed identity claim, verified through
    the audit log's exact packed inputs."""
    rec, host = _audited_batch(tmp_path, monkeypatch, topk_env=16)
    assert host["telemetry"]["scan_topk"] == 16
    monkeypatch.delenv("BST_SCAN_TOPK")
    for rung in ("steady", "cpu-ladder", "wavefront"):
        rep = replay_audit_record(rec, against=rung)
        assert rep["identical"], (rung, rep)
        assert rep["replayed_digest"] == rec["plan_digest"]


def test_dense_recorded_batch_replays_identically_on_topk_rung(
    tmp_path, monkeypatch
):
    """And the other direction: a dense-recorded batch replayed AGAINST
    the top-K rung reproduces the digest, with the executed-rung
    evidence naming the candidate width."""
    rec, _ = _audited_batch(tmp_path, monkeypatch, topk_env=None)
    rep = replay_audit_record(rec, against="topk")
    assert rep["identical"], rep
    assert rep["executed_rung"]["scan_topk"] == 16
    assert not rep.get("rung_fell_back")
