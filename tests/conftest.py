"""Test configuration: force an 8-device virtual CPU platform so multi-chip
sharding paths are exercised without TPU hardware.

Note: this environment's sitecustomize registers the axon TPU plugin at
interpreter start and overrides the jax_platforms *config* (env vars alone
don't win); the config must be updated back to cpu before first device use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# The per-bucket compiled-cost analysis (ops.oracle._maybe_analyze_bucket)
# re-lowers every freshly-built blob signature on a daemon thread — pure
# background compile load across a suite that builds hundreds of tiny
# shapes. Tests that exercise it re-enable via monkeypatch.
os.environ.setdefault("BST_BUCKET_COST", "0")
# Same class of background side effect: every jit-cache miss in the suite
# would append a test-shape line to the user's persistent compile ledger
# (~/.cache/bst-compile-ledger.jsonl, utils/profiler.py), polluting the
# cross-run attribution data it exists for. Tests that exercise the
# ledger pass an explicit path.
os.environ.setdefault("BST_COMPILE_LEDGER", "off")
# The capacity observatory's analytics kernel (ops.capacity) compiles one
# jit signature per batch shape — across a suite that builds hundreds of
# tiny scorers that is pure compile load for samples nothing reads.
# Tests that exercise the observatory re-enable via monkeypatch/env.
os.environ.setdefault("BST_CAPACITY", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the suite itself — the serving
# entry points' BST_COMPILATION_CACHE_DIR discipline (cmd/main.py
# _enable_compilation_cache) applied to tests: the suite compiles
# hundreds of oracle bucket shapes and the unrolled assignment scan is
# expensive to BUILD, so re-runs on the same machine should pay XLA once.
# Results are bit-identical (the cache stores the compiled module keyed
# by HLO + flags); python-side compile accounting (jit cache-size deltas
# feeding the "compiled" telemetry flag, warmer hit/miss, the compile
# ledger) is unaffected — tracing still happens, only the XLA backend
# build is served from disk. Cached under /tmp, NOT the user's
# ~/.cache serving dir (the BST_COMPILE_LEDGER rule: tests must not
# pollute cross-run serving caches). Same opt-out values as the serving
# knob: BST_COMPILATION_CACHE_DIR=off/0/empty disables.
_test_cache = os.environ.get(
    "BST_COMPILATION_CACHE_DIR", "/tmp/bst-test-xla-cache"
)
if _test_cache.strip().lower() not in ("", "0", "off"):
    try:
        os.makedirs(_test_cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _test_cache)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # cache is an optimization only, never block tests
        pass


def pytest_configure(config):
    # the tier-1 gate runs `-m 'not slow'`: slow marks the compile-heavy
    # widening matrices (extra shard_map signatures) that re-prove paths
    # a cheaper sibling already covers — run them with `-m slow`
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (compile-heavy variants)",
    )

assert jax.devices()[0].platform == "cpu", jax.devices()
assert len(jax.devices()) == 8, jax.devices()
