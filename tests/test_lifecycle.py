"""Gang lifecycle observatory (docs/observability.md "Gang lifecycle").

The ledger's contract has three load-bearing edges this file pins down:
coalesce/respawn mechanics must be byte-reproducible from the flat
evidence chain (fold == live snapshot — the slo_gate invariant), the
streaming cursor must never silently skip or re-serve an occurrence,
and the burn:ttp SLO signal must stay quiet on no-traffic windows while
flipping decisively on a deny storm. Plus the satellite regression: a
preemption eviction must NOT reset the pending/TTP clock.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

import pytest

from batch_scheduler_tpu.utils.lifecycle import GangLifecycleLedger
from batch_scheduler_tpu.utils.metrics import Registry


def _drive(led: GangLifecycleLedger) -> None:
    """A canonical two-gang story: acme's gang waits through a deny
    streak, gets evicted once, respawns and binds; beta's gang sails."""
    led.note_arrival("acme/train", tier=2, pods=1)
    led.note_arrival("acme/train", tier=2, pods=1)
    led.note_admitted("acme/train")
    for _ in range(3):
        led.note_deny("acme/train", "lane cpu deficit")
    led.note_batch_context(
        "aid-1", {"coalesce": {"queue_wait_seconds": 0.02}}
    )
    led.note_deny("acme/train", "lane cpu deficit")
    led.note_evicted("acme/train", preemptor="beta/urgent")
    led.note_arrival("acme/train", tier=2, pods=1)  # the respawn
    led.note_permit("acme/train")
    led.note_bind("acme/train", members=1)
    led.note_bind("acme/train", members=1)
    led.note_arrival("beta/urgent", tier=3, pods=1)
    led.note_permit("beta/urgent")
    led.note_bind("beta/urgent", members=1)


class _FakeAudit:
    def __init__(self):
        self.records = []

    def record_event(self, event, **fields):
        self.records.append({"kind": "event", "event": event, **fields})


def test_coalesce_streaks_respawn_and_ttp():
    led = GangLifecycleLedger(registry=Registry())
    _drive(led)
    snap = led.snapshot()
    assert snap["count"] == 2
    tv = snap["gangs"]["acme/train"]
    events = [(e["event"], e.get("repeats", 1)) for e in tv["events"]]
    # member arrivals coalesce; denies coalesce per blame string; the
    # post-eviction arrival is relabeled respawn; binds coalesce
    assert events == [
        ("arrival", 2),
        ("admitted", 1),
        ("deny", 4),
        ("evicted", 1),
        ("respawn", 1),
        ("permit", 1),
        ("bind", 2),
    ]
    deny = tv["events"][2]
    assert deny["reason"] == "lane cpu deficit"
    assert deny["audit_id"] == "aid-1"  # cross-stamped mid-streak
    assert deny["sidecar_wait_s"] == pytest.approx(0.02)
    assert "first_ts" in deny and deny["first_ts"] <= deny["ts"]
    # phase decomposition: anchors ordered, sidecar wait attributed
    assert tv["phases"]["sidecar_wait"] == pytest.approx(0.02)
    assert tv["ttp_s"] >= 0
    a = tv["anchors"]
    assert a["arrival"] <= a["sched"] <= a["bind"]
    # TTP observed ONCE per bind streak, tagged tenant+tier
    rep = led.report()
    assert rep["tenants"]["acme"]["count"] == 1
    assert rep["tenants"]["beta"]["count"] == 1


def test_tenant_scope_and_limit():
    led = GangLifecycleLedger(registry=Registry())
    _drive(led)
    assert list(led.snapshot(tenant="beta")["gangs"]) == ["beta/urgent"]
    assert led.snapshot(gang="acme/train")["count"] == 1
    # limit keeps the MOST RECENTLY ACTIVE gangs; 0 is empty, not all
    assert list(led.snapshot(limit=1)["gangs"]) == ["beta/urgent"]
    assert led.snapshot(limit=0)["count"] == 0


def test_retry_ping_pong_compacts_to_two_ring_slots():
    """A parked gang alternates admitted<->deny every scheduling cycle;
    the ledger must fold that ping-pong into two entries, not churn the
    arrival/eviction story out of the bounded ring."""
    audit = _FakeAudit()
    led = GangLifecycleLedger(per_gang=8, registry=Registry())
    led.attach_audit(audit)
    led.note_arrival("ns/parked", tier=0, pods=1)
    for _ in range(50):
        led.note_admitted("ns/parked")
        led.note_deny("ns/parked", "cluster full")
    tv = led.snapshot()["gangs"]["ns/parked"]
    events = [(e["event"], e.get("repeats", 1)) for e in tv["events"]]
    assert events == [
        ("arrival", 1), ("admitted", 50), ("deny", 50),
    ]
    assert tv["dropped_events"] == 0
    # a terminal event is a hard boundary: denies after a bind are a NEW
    # streak, never merged back across it
    led.note_bind("ns/parked", members=1)
    led.note_deny("ns/parked", "cluster full")
    events = [e["event"] for e in led.snapshot()["gangs"]["ns/parked"]["events"]]
    assert events == ["arrival", "admitted", "deny", "bind", "deny"]
    # and the skip-merge is fold-reproducible from the flat records
    folded = GangLifecycleLedger.fold(audit.records, per_gang=8)
    assert json.dumps(
        GangLifecycleLedger.timeline_view(folded["ns/parked"]),
        sort_keys=True,
    ) == json.dumps(led.snapshot()["gangs"]["ns/parked"], sort_keys=True)


def test_per_gang_ring_bound_counts_drops():
    led = GangLifecycleLedger(per_gang=4, registry=Registry())
    led.note_arrival("ns/g", tier=0, pods=1)
    for i in range(10):
        led.note_deny("ns/g", f"reason-{i}")  # distinct: no coalesce
    tv = led.snapshot()["gangs"]["ns/g"]
    assert len(tv["events"]) == 4
    assert tv["dropped_events"] == 7
    # arrival_ts anchor survives the ring evicting the arrival event
    assert tv["anchors"]["arrival"] is not None


def test_fold_is_byte_identical_to_live_snapshot():
    """The offline half of every surface: re-folding the flat audit
    records must reproduce the live per-gang event lists byte-for-byte
    (same coalesce rule, same ring bound) — `timeline --audit-dir` and
    the slo_gate byte-consistency phase both stand on this."""
    audit = _FakeAudit()
    led = GangLifecycleLedger(registry=Registry())
    led.attach_audit(audit)
    _drive(led)
    assert all(r["event"] == "gang_lifecycle" for r in audit.records)
    folded = GangLifecycleLedger.fold(audit.records)
    live = led.snapshot()["gangs"]
    assert set(folded) == set(live)
    for gang, rec in folded.items():
        view = GangLifecycleLedger.timeline_view(rec)
        assert json.dumps(view, sort_keys=True) == json.dumps(
            live[gang], sort_keys=True
        ), gang


def test_fold_applies_ring_bound():
    audit = _FakeAudit()
    led = GangLifecycleLedger(per_gang=4, registry=Registry())
    led.attach_audit(audit)
    led.note_arrival("ns/g", tier=0, pods=1)
    for i in range(10):
        led.note_deny("ns/g", f"reason-{i}")
    folded = GangLifecycleLedger.fold(audit.records, per_gang=4)
    assert json.dumps(
        GangLifecycleLedger.timeline_view(folded["ns/g"]), sort_keys=True
    ) == json.dumps(led.snapshot()["gangs"]["ns/g"], sort_keys=True)


def test_export_jsonl_round_trips(tmp_path):
    led = GangLifecycleLedger(registry=Registry())
    led.set_export_dir(str(tmp_path))
    _drive(led)
    lines = [
        json.loads(line)
        for line in (tmp_path / "events.jsonl").read_text().splitlines()
    ]
    # export lines fold through the same rule as audit records
    folded = GangLifecycleLedger.fold(lines)
    assert json.dumps(
        GangLifecycleLedger.timeline_view(folded["acme/train"]),
        sort_keys=True,
    ) == json.dumps(led.snapshot()["gangs"]["acme/train"], sort_keys=True)


def test_export_rotation_bounds_disk(tmp_path, monkeypatch):
    monkeypatch.setenv("BST_LIFECYCLE_EXPORT_MAX_MB", "0.002")  # ~2 KB
    led = GangLifecycleLedger(registry=Registry())
    led.set_export_dir(str(tmp_path))
    for i in range(80):  # push well past the cap
        led.note_deny("ns/filler", f"r-{i}")
    main = tmp_path / "events.jsonl"
    rolled = tmp_path / "events.jsonl.1"
    assert main.exists() and rolled.exists()
    # at most the live file + ONE rotated generation survive, each capped
    assert not (tmp_path / "events.jsonl.2").exists()
    assert rolled.stat().st_size <= 3 * 1024
    # every surviving line is intact JSON (rotation never tears a line)
    for f in (rolled, main):
        for line in f.read_text().splitlines():
            assert json.loads(line)["gang"] == "ns/filler"


def test_events_since_cursor_semantics():
    led = GangLifecycleLedger(stream_capacity=8, registry=Registry())
    for i in range(5):
        led.note_deny("ns/g", f"r-{i}")
    out = led.events_since(0)
    assert [e["cursor"] for e in out["events"]] == [1, 2, 3, 4, 5]
    assert out["cursor"] == 5 and out["dropped"] == 0
    # resume from the returned cursor: nothing new, cursor unchanged
    again = led.events_since(out["cursor"])
    assert again["events"] == [] and again["cursor"] == 5
    # limit truncates but the cursor only advances past SERVED events
    page = led.events_since(0, limit=2)
    assert [e["cursor"] for e in page["events"]] == [1, 2]
    assert page["cursor"] == 2
    # limit=0 with events available must NOT advance (no silent skip)
    peek = led.events_since(0, limit=0)
    assert peek["events"] == [] and peek["cursor"] == 0
    # ring overflow reports the evicted span as dropped
    for i in range(10):
        led.note_deny("ns/h", f"s-{i}")
    tail = led.events_since(0)
    assert tail["dropped"] == 15 - 8
    assert len(tail["events"]) == 8
    # a coalesced repeat gets a NEW cursor but keeps its stable seq
    led.note_deny("ns/h", "s-9")
    bump = led.events_since(tail["cursor"])
    assert len(bump["events"]) == 1
    assert bump["events"][0]["seq"] == tail["events"][-1]["seq"]
    assert bump["events"][0]["cursor"] == tail["cursor"] + 1


def test_events_since_long_poll_times_out_quickly():
    led = GangLifecycleLedger(registry=Registry())
    t0 = time.monotonic()
    out = led.events_since(0, timeout_s=0.05)
    assert out["events"] == []
    assert time.monotonic() - t0 < 5.0


def test_debug_endpoints_serve_and_reject(tmp_path):
    """/debug/gangs, /debug/events, and the /debug/decisions filters —
    including the 400-on-malformed convention."""
    from batch_scheduler_tpu.utils import lifecycle as lifecycle_mod
    from batch_scheduler_tpu.utils.metrics import (
        DEFAULT_REGISTRY,
        serve_metrics,
    )
    from batch_scheduler_tpu.utils.trace import DEFAULT_FLIGHT_RECORDER

    led = lifecycle_mod.DEFAULT_LEDGER
    led.reset()
    DEFAULT_FLIGHT_RECORDER.clear()
    _drive(led)
    DEFAULT_FLIGHT_RECORDER.record(
        "acme/train", "prefilter", "deny", "lane cpu deficit"
    )
    DEFAULT_FLIGHT_RECORDER.record("beta/urgent", "bind", "ok")
    server = serve_metrics(DEFAULT_REGISTRY, port=0)
    try:
        port = server.server_address[1]

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5
            ) as r:
                return json.loads(r.read().decode()), r.status

        doc, status = get("/debug/gangs")
        assert status == 200 and doc["count"] == 2
        doc, _ = get("/debug/gangs?gang=acme/train")
        assert list(doc["gangs"]) == ["acme/train"]
        assert doc["gangs"]["acme/train"]["phases"]["sidecar_wait"] > 0
        doc, _ = get("/debug/gangs?tenant=beta&limit=5")
        assert list(doc["gangs"]) == ["beta/urgent"]
        doc, _ = get("/debug/events?since=0&limit=4")
        assert len(doc["events"]) == 4 and doc["cursor"] == 4
        doc, _ = get(f"/debug/events?since={doc['cursor']}")
        assert doc["events"][0]["cursor"] == 5
        doc, _ = get("/debug/decisions?tenant=acme")
        assert list(doc["decisions"]) == ["acme/train"]
        doc, _ = get("/debug/decisions?gang=beta/urgent&limit=1")
        assert list(doc["decisions"]) == ["beta/urgent"]
        for bad in (
            "/debug/gangs?limit=bogus",
            "/debug/gangs?limit=-1",
            "/debug/decisions?limit=1.5",
            "/debug/events?since=xyz",
            "/debug/events?limit=-3",
        ):
            with pytest.raises(urllib.error.HTTPError) as exc:
                get(bad)
            assert exc.value.code == 400, bad
            assert json.loads(exc.value.read().decode())["ok"] is False
    finally:
        server.shutdown()
        led.reset()
        DEFAULT_FLIGHT_RECORDER.clear()


def test_timeline_cli_offline_folds_audit_ring(tmp_path, capsys):
    """`timeline --audit-dir`: the explain/capacity offline pattern over
    the gang_lifecycle evidence chain."""
    from batch_scheduler_tpu.cmd.main import main
    from batch_scheduler_tpu.utils.audit import AuditLog

    log = AuditLog(str(tmp_path), cap_bytes=1 << 20)
    led = GangLifecycleLedger(registry=Registry())
    led.attach_audit(log)
    _drive(led)
    log.flush()
    log.stop()
    assert main(["timeline", "acme/train", "--audit-dir", str(tmp_path)]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert list(doc["gangs"]) == ["acme/train"]
    assert doc["gangs"]["acme/train"]["ttp_s"] >= 0
    # tenant scoping + the nothing-matches exit contract
    assert main(["timeline", "--audit-dir", str(tmp_path),
                 "--tenant", "beta"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert list(doc["gangs"]) == ["beta/urgent"]
    assert main(["timeline", "ns/ghost", "--audit-dir", str(tmp_path)]) == 2
    capsys.readouterr()


# -- the pending-clock eviction carry (satellite regression) ---------------


def test_pending_clock_survives_preemption_eviction():
    """An evicted-then-respawned gang (same name, new uids) must NOT
    reset its pending clock: the original first-seen is carried across
    note_placed -> note_evicted, so pending age and the next placement's
    observed span include the preemption churn."""
    from batch_scheduler_tpu.utils.health import PendingGangTracker

    reg = Registry()
    t = PendingGangTracker(registry=reg)
    t.note_deny("spot/victim")
    time.sleep(0.03)
    t.note_placed("spot/victim")
    first_span = reg.histogram("bst_gang_pending_seconds").snapshot()[1]
    assert first_span >= 0.03
    # a guaranteed gang preempts the spot gang; the spot gang respawns
    t.note_evicted("spot/victim")
    rep = t.report()
    assert rep["pending_gangs"] == 1
    assert rep["oldest_age_s"] >= 0.03, "eviction reset the pending clock"
    time.sleep(0.02)
    t.note_placed("spot/victim")
    total_span = reg.histogram("bst_gang_pending_seconds").snapshot()[1]
    # the second observation spans the ORIGINAL first-seen -> now
    assert total_span - first_span >= 0.05
    # an eviction while still pending leaves the running clock alone
    t.note_deny("ns/waiting")
    time.sleep(0.02)
    t.note_evicted("ns/waiting")
    assert t.report()["oldest_age_s"] >= 0.02
    # forget (gang deleted) drops the carry: no ghost re-arm later
    t.note_deny("ns/gone")
    t.note_placed("ns/gone")
    t.forget("ns/gone")
    t.note_evicted("ns/gone")
    assert t.report()["pending_gangs"] == 2  # re-armed at NOW, age ~0


def test_operation_eviction_rearms_pending_via_tracker():
    """The wiring end of the satellite: ScheduleOperation.note_gang_evicted
    must re-arm the pending tracker (operation -> tracker), not just flip
    group phase."""
    from batch_scheduler_tpu.core.operation import ScheduleOperation

    assert hasattr(ScheduleOperation, "note_gang_evicted")
    src = open(
        "batch_scheduler_tpu/core/operation.py", encoding="utf-8"
    ).read()
    assert "pending_tracker.note_evicted" in src


def test_ledger_arrival_anchor_survives_respawn():
    """The TTP half of the same regression: the ledger's arrival anchor
    (and so ttp_s) spans the eviction."""
    led = GangLifecycleLedger(registry=Registry())
    led.note_arrival("spot/victim", tier=1, pods=1)
    time.sleep(0.03)
    led.note_evicted("spot/victim", preemptor="guar/winner")
    led.note_arrival("spot/victim", tier=1, pods=1)  # respawn
    led.note_bind("spot/victim", members=1)
    tv = led.snapshot()["gangs"]["spot/victim"]
    assert [e["event"] for e in tv["events"]] == [
        "arrival", "evicted", "respawn", "bind",
    ]
    assert tv["ttp_s"] >= 0.03, "respawn reset the TTP anchor"


# -- burn:ttp windowed edge cases (satellite 3) ----------------------------


def _model_and_hist():
    from batch_scheduler_tpu.utils.health import HealthModel

    reg = Registry()
    model = HealthModel(registry=reg)
    model.reset()
    return model, reg.histogram("bst_gang_ttp_seconds")


def test_burn_ttp_quiet_on_no_traffic_windows():
    model, _ = _model_and_hist()
    for _ in range(3):
        sig = model.evaluate()["signals"]["burn:ttp"]
        assert sig["verdict"] == "ok"
        assert sig["observations"] == 0
        assert sig["burn_fast"] == 0.0 and sig["burn_slow"] == 0.0


def test_burn_ttp_deny_storm_breaches_and_reset_recovers(monkeypatch):
    monkeypatch.setenv("BST_SLO_TTP_P99_S", "0.5")
    model, hist = _model_and_hist()
    for _ in range(50):
        hist.observe(5.0, tenant="acme", tier="1")
    sig = model.evaluate()["signals"]["burn:ttp"]
    assert sig["verdict"] == "breach"
    assert sig["tiers"]["1"]["p99_s"] > 0.5
    assert sig["tiers"]["1"]["observations"] == 50
    # recovery: re-baselining scopes the next verdict to new traffic
    model.reset()
    for _ in range(50):
        hist.observe(0.01, tenant="acme", tier="1")
    sig = model.evaluate()["signals"]["burn:ttp"]
    assert sig["verdict"] == "ok"


def test_burn_ttp_per_tier_targets(monkeypatch):
    """Per-tier overrides: the same latency breaches the strict tier and
    passes the lax default; malformed overrides are ignored (the knobs
    parse-guard contract)."""
    from batch_scheduler_tpu.utils.health import _ttp_target_for_tier

    monkeypatch.setenv("BST_SLO_TTP_P99_S", "100")
    monkeypatch.setenv("BST_SLO_TTP_P99_T3_S", "0.05")
    monkeypatch.setenv("BST_SLO_TTP_P99_T7_S", "not-a-number")
    assert _ttp_target_for_tier("3") == 0.05
    assert _ttp_target_for_tier("7") == 100.0  # malformed -> base
    assert _ttp_target_for_tier("0") == 100.0
    monkeypatch.setenv("BST_SLO_TTP_P99_S", "")
    assert _ttp_target_for_tier("0") == 120.0  # baked-in default

    model, hist = _model_and_hist()
    monkeypatch.setenv("BST_SLO_TTP_P99_S", "100")
    for _ in range(90):
        hist.observe(1.0, tenant="acme", tier="3")  # breaches T3's 0.05
    for _ in range(10):
        hist.observe(1.0, tenant="acme", tier="0")  # well under 100
    sig = model.evaluate()["signals"]["burn:ttp"]
    # 90 of 100 observations violate THEIR tier's target -> burn 18x,
    # past both thresholds; the default tier contributes only its total
    assert sig["verdict"] == "breach"
    assert sig["observations"] == 100
    assert sig["tiers"]["3"]["target_p99_s"] == 0.05
    assert sig["tiers"]["0"]["target_p99_s"] == 100.0
    # the same latency on the LAX tier alone would not have breached:
    # tier 0's windowed p99 is far under its target
    assert sig["tiers"]["0"]["p99_s"] < 100.0


def test_burn_ttp_counter_reuse_never_goes_negative(monkeypatch):
    """A histogram epoch restarting under the model (registry swapped or
    series cleared — tests do this) must clamp to zero traffic, not
    produce negative burns."""
    monkeypatch.setenv("BST_SLO_TTP_P99_S", "0.5")
    model, hist = _model_and_hist()
    for _ in range(20):
        hist.observe(5.0, tenant="t", tier="0")
    assert model.evaluate()["signals"]["burn:ttp"]["verdict"] == "breach"
    hist._series.clear()  # the counter-reuse epoch break
    sig = model.evaluate()["signals"]["burn:ttp"]
    assert sig["observations"] == 0
    assert sig["burn_fast"] >= 0.0 and sig["burn_slow"] >= 0.0
    assert sig["verdict"] == "ok"


def test_burn_ttp_snapshot_deque_bounded_under_fast_polling(monkeypatch):
    """A 10Hz /debug/health poller must not grow the TTP history: the
    deque retains at most ~1k entries per slow window by construction."""
    model, hist = _model_and_hist()
    hist.observe(0.01, tenant="t", tier="0")
    for _ in range(200):
        model.evaluate()
    assert len(model._ttp_snaps) <= 1100


def test_burn_capacity_downsampled_span_overlap(monkeypatch):
    """The capacity burn admits downsampled entries by span OVERLAP and
    weights by merged count — a ring that has downsampled must not
    underweight the slow window (the same window math burn:ttp's deque
    granularity bound leans on)."""
    from batch_scheduler_tpu.ops import capacity as capacity_mod
    from batch_scheduler_tpu.utils.health import HealthModel

    class _FakeSampler:
        def series(self):
            now = time.time()
            return [
                # merged entry: 8 raw samples, half violating, whose span
                # STARTS outside the fast window but overlaps into it
                {"ts": now - 400, "span_s": 200.0, "merged": 8,
                 "data": {"capacity_violation": 0.5}},
                {"ts": now - 1, "merged": 1,
                 "data": {"capacity_violation": 1.0}},
            ]

    model = HealthModel(registry=Registry())
    model.reset()
    monkeypatch.setattr(capacity_mod, "active_sampler", _FakeSampler)
    monkeypatch.setenv("BST_SLO_WINDOW_S", "300")
    sig = model.evaluate()["signals"]["burn:capacity"]
    # both entries admitted: 8*0.5 + 1*1.0 = 5 bad of 9 -> fraction 5/9
    assert sig["observations"] == 9
    assert sig["burn_fast"] == pytest.approx((5 / 9) / 0.05, rel=1e-3)
