"""Merge-patch tests, modelled on the reference's table test
(reference pkg/util/k8s_test.go:31-78)."""

from batch_scheduler_tpu.utils.patch import apply_merge_patch, create_merge_patch


def test_no_change_empty_patch():
    doc = {"a": 1, "b": {"c": 2}}
    assert create_merge_patch(doc, doc) == {}


def test_scalar_change():
    assert create_merge_patch({"phase": "Pending"}, {"phase": "Running"}) == {
        "phase": "Running"
    }


def test_nested_status_change_only_diff():
    original = {
        "metadata": {"name": "g1"},
        "status": {"phase": "Pending", "scheduled": 0},
    }
    modified = {
        "metadata": {"name": "g1"},
        "status": {"phase": "Scheduling", "scheduled": 3},
    }
    patch = create_merge_patch(original, modified)
    assert patch == {"status": {"phase": "Scheduling", "scheduled": 3}}


def test_removed_key_becomes_null():
    patch = create_merge_patch({"a": 1, "b": 2}, {"a": 1})
    assert patch == {"b": None}


def test_added_key():
    patch = create_merge_patch({"a": 1}, {"a": 1, "b": {"x": 5}})
    assert patch == {"b": {"x": 5}}


def test_lists_replaced_wholesale():
    patch = create_merge_patch({"items": [1, 2]}, {"items": [1, 2, 3]})
    assert patch == {"items": [1, 2, 3]}


def test_apply_inverts_create():
    original = {
        "spec": {"minMember": 5},
        "status": {"phase": "Pending", "scheduled": 0, "occupiedBy": "x"},
    }
    modified = {
        "spec": {"minMember": 5},
        "status": {"phase": "Scheduled", "scheduled": 5},
    }
    patch = create_merge_patch(original, modified)
    assert apply_merge_patch(original, patch) == modified
