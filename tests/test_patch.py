"""Merge-patch tests, modelled on the reference's table test
(reference pkg/util/k8s_test.go:31-78)."""

from batch_scheduler_tpu.utils.patch import apply_merge_patch, create_merge_patch


def test_no_change_empty_patch():
    doc = {"a": 1, "b": {"c": 2}}
    assert create_merge_patch(doc, doc) == {}


def test_scalar_change():
    assert create_merge_patch({"phase": "Pending"}, {"phase": "Running"}) == {
        "phase": "Running"
    }


def test_nested_status_change_only_diff():
    original = {
        "metadata": {"name": "g1"},
        "status": {"phase": "Pending", "scheduled": 0},
    }
    modified = {
        "metadata": {"name": "g1"},
        "status": {"phase": "Scheduling", "scheduled": 3},
    }
    patch = create_merge_patch(original, modified)
    assert patch == {"status": {"phase": "Scheduling", "scheduled": 3}}


def test_removed_key_becomes_null():
    patch = create_merge_patch({"a": 1, "b": 2}, {"a": 1})
    assert patch == {"b": None}


def test_added_key():
    patch = create_merge_patch({"a": 1}, {"a": 1, "b": {"x": 5}})
    assert patch == {"b": {"x": 5}}


def test_lists_replaced_wholesale():
    patch = create_merge_patch({"items": [1, 2]}, {"items": [1, 2, 3]})
    assert patch == {"items": [1, 2, 3]}


def test_apply_inverts_create():
    original = {
        "spec": {"minMember": 5},
        "status": {"phase": "Pending", "scheduled": 0, "occupiedBy": "x"},
    }
    modified = {
        "spec": {"minMember": 5},
        "status": {"phase": "Scheduled", "scheduled": 5},
    }
    patch = create_merge_patch(original, modified)
    assert apply_merge_patch(original, patch) == modified


def test_fast_to_dict_matches_asdict():
    """The explicit per-kind encoders must stay field-for-field identical to
    the dataclasses.asdict fallback (api/types.to_dict fast path)."""
    import dataclasses
    import enum

    from batch_scheduler_tpu.api.types import (
        Container,
        Node,
        ObjectMeta,
        Pod,
        PodGroup,
        PodGroupPhase,
        PodGroupSpec,
        PodGroupStatus,
        PodPhase,
        PodSpec,
        PodStatus,
        Taint,
        Toleration,
        to_dict,
    )

    def slow(obj):
        def encode(v):
            return v.value if isinstance(v, enum.Enum) else v

        return dataclasses.asdict(
            obj, dict_factory=lambda items: {k: encode(v) for k, v in items}
        )

    meta = ObjectMeta(
        name="p1", namespace="ns", uid="u1", labels={"a": "b"},
        annotations={"x": "y"}, owner_references=["u0"],
        creation_timestamp=3.5, resource_version=7,
    )
    pod = Pod(
        metadata=meta,
        spec=PodSpec(
            containers=[Container("c", {"cpu": 100}, {"cpu": 200})],
            node_selector={"zone": "z1"},
            tolerations=[Toleration("k", "Exists", "", "NoSchedule")],
            priority=3,
            node_name="n1",
        ),
        status=PodStatus(phase=PodPhase.RUNNING),
    )
    node = Node(metadata=meta)
    node.spec.taints = [Taint("k", "v", "NoExecute")]
    node.spec.unschedulable = True
    node.status.allocatable = {"cpu": 8000}
    node.status.capacity = {"cpu": 8000}
    pg = PodGroup(
        metadata=meta,
        spec=PodGroupSpec(
            min_member=5, priority_class_name="high",
            min_resources={"cpu": 100}, max_schedule_time=60,
        ),
        status=PodGroupStatus(phase=PodGroupPhase.SCHEDULING, scheduled=2),
    )
    for obj in (pod, node, pg, pg.status, PodGroup()):
        assert to_dict(obj) == slow(obj)
    # fast output must not alias the source containers
    d = to_dict(pod)
    d["metadata"]["labels"]["a"] = "mutated"
    assert pod.metadata.labels["a"] == "b"
