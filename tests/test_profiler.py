"""The device profiler + perf observatory (utils/profiler.py): on-demand
jax.profiler capture, device-memory sampling, the compile ledger, the
perf report, and the teardown drain for the telemetry daemon threads.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.error
import urllib.request

from batch_scheduler_tpu.utils import profiler


def _small_snapshot(n_nodes: int = 16, resource: str = "cpu"):
    from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot, GroupDemand
    from batch_scheduler_tpu.sim.scenarios import make_sim_node

    nodes = [
        make_sim_node(
            f"n{i:03d}",
            {"cpu": "8", "memory": "32Gi", "pods": "110"},
        )
        for i in range(n_nodes)
    ]
    groups = [
        GroupDemand(
            "default/probe", 2, member_request={resource: 1000}
        )
    ]
    return ClusterSnapshot(nodes, {}, groups)


def test_capture_profile_writes_bounded_trace_dir(tmp_path):
    """/debug/profile's engine: a capture on CPU produces a loadable
    (non-empty) trace dir under the configured --profile-dir, the
    capture counter advances, and old captures are pruned oldest-first
    so the dir stays bounded."""
    profiler.configure(profile_dir=str(tmp_path))
    try:
        out = profiler.capture_profile(0.1)
        assert out["ok"], out
        assert out["trace_dir"].startswith(str(tmp_path))
        assert os.path.isdir(out["trace_dir"])
        assert out["files"] >= 1  # the profiler wrote real trace files
        state = profiler.profile_state()
        assert state["captures"] >= 1 and not state["busy"]
        assert state["last_capture"]["trace_dir"] == out["trace_dir"]

        # bounded-size dir: with keep=1 the first capture is pruned
        out2 = profiler.capture_profile(0.1)
        assert out2["ok"], out2
        profiler._prune_captures(str(tmp_path), keep=1)
        assert not os.path.exists(out["trace_dir"])
        assert os.path.isdir(out2["trace_dir"])
    finally:
        profiler.configure(profile_dir=None)


def test_capture_profile_rejects_concurrent_capture(tmp_path, monkeypatch):
    """The jax profiler is a global singleton: a second capture while one
    is in flight answers busy instead of corrupting it."""
    profiler.configure(profile_dir=str(tmp_path))
    try:
        started = threading.Event()
        release = threading.Event()
        real_sleep = profiler.time.sleep

        def slow_sleep(_s):
            started.set()
            release.wait(10)

        monkeypatch.setattr(profiler.time, "sleep", slow_sleep)
        results = []
        t = threading.Thread(
            target=lambda: results.append(profiler.capture_profile(0.1))
        )
        t.start()
        assert started.wait(10)
        second = profiler.capture_profile(0.1)
        assert second == {"ok": False, "error": "capture already in progress"}
        release.set()
        monkeypatch.setattr(profiler.time, "sleep", real_sleep)
        t.join(30)
        assert results and results[0]["ok"]
        # shutdown() with no capture in flight is immediate — and it
        # CLOSES the profiler: a capture starting after teardown would
        # re-create the exit-abort class, so it must be refused until
        # the next configure() (the bring-up call) reopens
        assert profiler.shutdown(timeout=5.0)
        assert profiler.capture_profile(0.1) == {
            "ok": False, "error": "profiler shut down"
        }
        assert profiler.profile_state()["closed"] is True
        profiler.configure(profile_dir=str(tmp_path))
        assert profiler.profile_state()["closed"] is False
    finally:
        profiler.configure(profile_dir=None)


def test_device_memory_sampler_is_cpu_noop():
    """On a backend with no memory_stats (CPU) the sampler thread exits
    after its first empty pass — a no-op, not a spinning daemon — and
    the bst_device_* gauges stay UNREGISTERED ("absent on CPU" means
    absent from /metrics too: a registered-but-never-set gauge renders
    as 0, which would read as bytes_limit==0 to the HBM-headroom
    consumers this sampler feeds)."""
    import jax

    from batch_scheduler_tpu.utils.metrics import Registry

    reg = Registry()
    sampler = profiler.DeviceMemorySampler(interval_s=0.5, registry=reg)
    assert sampler.stop(timeout=5.0)
    if jax.default_backend() == "cpu":
        assert sampler.sample_once() is None
        assert profiler.sample_device_memory() is None
        assert reg.get("bst_device_bytes_in_use") is None
        assert reg.get("bst_device_peak_bytes") is None
        assert reg.get("bst_device_bytes_limit") is None
        assert "bst_device" not in reg.render()


def test_compile_ledger_records_and_persists(tmp_path):
    ledger = profiler.CompileLedger(path=str(tmp_path / "ledger.jsonl"))
    ledger.record(64, 1024, "serial", False, 1.25, backend="cpu")
    ledger.record(64, 1024, "serial", False, 0.75, backend="cpu")
    ledger.record(64, 1024, "wavefront", True, 2.0, backend="cpu")
    rep = ledger.report()
    assert rep["totals"]["64x1024/serial"]["compiles"] == 2
    assert rep["totals"]["64x1024/serial"]["dispatch_seconds"] == 2.0
    assert rep["totals"]["64x1024/wavefront/donated"]["compiles"] == 1
    assert len(rep["recent"]) == 3
    # persisted JSONL: one parseable line per entry, cross-run evidence
    lines = [
        json.loads(line)
        for line in (tmp_path / "ledger.jsonl").read_text().splitlines()
    ]
    assert len(lines) == 3
    assert lines[0]["g_bucket"] == 64 and lines[0]["rung"] == "serial"
    assert lines[2]["donated"] is True
    assert all("ts" in e and "pid" in e for e in lines)


def test_compile_ledger_disabled_path(tmp_path, monkeypatch):
    monkeypatch.setenv("BST_COMPILE_LEDGER", "off")
    ledger = profiler.CompileLedger()
    ledger.record(8, 16, "serial", False, 0.5)
    assert ledger.entry_count() == 1  # in-memory view still works
    assert ledger.report()["jsonl"] is None


def test_dispatch_feeds_compile_ledger_and_drain(tmp_path, monkeypatch):
    """A jit-cache miss on the serving dispatch path lands one compile-
    ledger entry keyed by bucket shape + rung, and the telemetry daemon
    threads it spawns join cleanly (the teardown drain)."""
    from batch_scheduler_tpu.ops import oracle as oracle_mod

    fresh = profiler.CompileLedger(path=str(tmp_path / "cl.jsonl"))
    monkeypatch.setattr(profiler, "COMPILE_LEDGER", fresh)
    # an exotic resource name changes the lane schema -> a jit signature
    # this test process has never compiled -> a guaranteed cache miss
    snap = _small_snapshot(resource="example.com/profiler-probe")
    host, _ = oracle_mod.execute_batch_host(
        snap.device_args(), snap.progress_args()
    )
    telemetry = host["telemetry"]
    if telemetry.get("compiled"):
        assert fresh.entry_count() >= 1
        entry = fresh.report()["recent"][-1]
        assert entry["g_bucket"] == telemetry["g_bucket"]
        assert entry["n_bucket"] == telemetry["n_bucket"]
        assert entry["dispatch_seconds"] > 0
        assert (tmp_path / "cl.jsonl").exists()
    # the bucket-cost analysis thread the compile spawned must join
    assert oracle_mod.drain_telemetry_threads(timeout=120.0)


def test_perf_report_shape():
    from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY

    # ensure at least one phase histogram + the scan mix have data
    DEFAULT_REGISTRY.histogram(
        "bst_oracle_pack_seconds", "Host snapshot-pack time per batch"
    ).observe(0.01)
    DEFAULT_REGISTRY.counter(
        "bst_scan_batches_total", "Oracle batches by assignment-scan path"
    ).inc(path="serial")
    report = profiler.perf_report()
    assert set(report) >= {
        "phases", "scan_rung_mix", "device_memory", "compile_ledger",
        "profiler",
    }
    pack = report["phases"]["bst_oracle_pack_seconds"]
    assert pack["count"] >= 1 and pack["p95_s"] >= pack["p50_s"] >= 0
    assert report["scan_rung_mix"].get("serial", 0) >= 1
    assert "totals" in report["compile_ledger"]


def test_perf_and_profile_endpoints(tmp_path):
    """The acceptance wiring: /debug/perf serves the report and
    /debug/profile?seconds=N produces a loadable trace dir on CPU, over
    HTTP on the metrics endpoint."""
    from batch_scheduler_tpu.utils.metrics import Registry, serve_metrics

    profiler.configure(profile_dir=str(tmp_path))
    server = serve_metrics(Registry(), port=0)
    try:
        port = server.server_address[1]

        def get(path, timeout=120):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout
            ) as r:
                return r.headers["Content-Type"], json.loads(r.read())

        ctype, perf = get("/debug/perf")
        assert "application/json" in ctype
        assert "phases" in perf and "compile_ledger" in perf

        ctype, state = get("/debug/profile")
        assert "application/json" in ctype
        assert state["busy"] is False

        _, capture = get("/debug/profile?seconds=0.1")
        assert capture["ok"], capture
        assert os.path.isdir(capture["trace_dir"])
        assert capture["files"] >= 1

        # a malformed duration answers 400 and runs NO capture (it would
        # block a handler thread and consume the global profiler slot);
        # nan parses as a float but is junk — same treatment
        before = profiler.profile_state()["captures"]
        for bad in ("5s", "nan", "inf"):
            try:
                get(f"/debug/profile?seconds={bad}")
                assert False, f"expected 400 for {bad}"
            except urllib.error.HTTPError as e:
                assert e.code == 400, bad
                assert json.loads(e.read())["ok"] is False
        assert profiler.profile_state()["captures"] == before
    finally:
        server.shutdown()
        profiler.shutdown(timeout=60.0)
        profiler.configure(profile_dir=None)


def test_sim_dispatch_ahead_with_compile_warmer_exits_cleanly():
    """The README known-issue regression: ``sim --dispatch-ahead
    --compile-warmer`` used to abort at interpreter exit ("terminate
    called without an active exception") after a successful run — the
    warmer's precompiles each spawned an unjoined bucket-cost-analysis
    daemon thread that died inside XLA teardown. The combination must
    now exit 0 with no abort, in a real subprocess (the abort only
    fires at interpreter exit)."""
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable, "-m", "batch_scheduler_tpu", "sim",
            "--scenario", "synthetic", "--nodes", "8", "--groups", "2",
            "--members", "2", "--dispatch-ahead", "--compile-warmer",
            "--timeout", "90", "--settle", "2",
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "terminate called" not in proc.stderr
    assert "Aborted" not in proc.stderr


def test_scorer_drain_joins_warmer_and_telemetry_threads():
    """The --dispatch-ahead --compile-warmer exit-abort fix: a scorer
    draining with a live warmer stops the warmer FIRST and then joins
    the telemetry threads its precompiles spawned — drain must return
    True (nothing left racing XLA teardown)."""
    from batch_scheduler_tpu.core.oracle_scorer import OracleScorer
    from batch_scheduler_tpu.sim.harness import SimCluster
    from batch_scheduler_tpu.sim.scenarios import (
        make_member_pods,
        make_sim_group,
        make_sim_node,
    )

    cluster = SimCluster(
        scorer="oracle",
        oracle_dispatch_ahead=True,
        oracle_compile_warmer=True,
    )
    cluster.add_nodes(
        [make_sim_node(f"d{i}", {"cpu": "8", "memory": "32Gi",
                                 "pods": "110"}) for i in range(8)]
    )
    cluster.create_group(make_sim_group("drain-g", 2))
    cluster.start()
    try:
        cluster.create_pods(make_member_pods("drain-g", 2, {"cpu": "1"}))
        assert cluster.wait_for(
            lambda: cluster.scheduler.stats["binds"] >= 2, timeout=60.0
        )
        oracle = cluster.runtime.operation.oracle
        assert isinstance(oracle, OracleScorer)
        assert oracle._warmer is not None
        assert oracle.drain_background(timeout=120.0) is True
        # idempotent: a second drain (factory.stop calls it again) holds
        assert oracle.drain_background(timeout=30.0) is True
    finally:
        cluster.stop()


def test_serve_sigterm_drains_and_exits_cleanly():
    """The SIGTERM graceful-drain path (docs/resilience.md "High
    availability"): a live sidecar that has served traffic must, on
    SIGTERM, finish the in-flight window, flush warmer -> executor ->
    telemetry -> audit in producer-before-join order, print the drain
    report, and exit 0 with no interpreter-teardown abort — in a real
    subprocess, because both the signal handler and the exit-abort only
    exist there."""
    import re
    import signal
    import subprocess
    import sys
    import time

    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        BST_BUCKET_COST="0",
        BST_COMPILE_LEDGER="off",
        BST_CAPACITY="0",
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-u", "-m", "batch_scheduler_tpu", "serve",
            "--port", "0", "--compile-warmer",
        ],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )
    try:
        # wait for the bound port announcement
        line = ""
        deadline = time.time() + 120
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "listening on" in line:
                break
            assert proc.poll() is None, proc.stderr.read()[-2000:]
        m = re.search(r"listening on ([\d.]+):(\d+)", line)
        assert m, f"no listening line: {line!r}"
        host, port = m.group(1), int(m.group(2))

        # serve one real batch so drain has ledgers/threads to flush
        from batch_scheduler_tpu.service.client import OracleClient
        from batch_scheduler_tpu.sim.scenarios import tenant_oracle_stream

        req = tenant_oracle_stream(0, 1, nodes=16, gangs=4)[0]
        client = OracleClient(host, port, timeout=120.0)
        resp = client.schedule(req, tenant="drainer")
        assert resp.placed.shape[0] > 0
        client.close()

        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    except BaseException:
        proc.kill()
        proc.communicate(timeout=30)
        raise
    assert proc.returncode == 0, (out[-2000:], err[-2000:])
    assert "SIGTERM: draining oracle sidecar" in out
    m = re.search(r"drain complete: (\{.*\})", out)
    assert m, out[-2000:]
    report = json.loads(m.group(1))
    assert report["drained"] is True
    assert report["telemetry_joined"] is True
    assert report["audit_flushed"] is True
    assert "terminate called" not in err
    assert "Aborted" not in err
