"""Sidecar service tests: protocol roundtrip, Python client, RemoteScorer
inside ScheduleOperation, and the native C++ client against the same server
(wire compatibility proven end-to-end)."""

import numpy as np
import pytest

from batch_scheduler_tpu.service import (
    OracleClient,
    RemoteScorer,
    ResilientOracleClient,
    protocol as proto,
    serve_background,
)
from batch_scheduler_tpu.cache import PGStatusCache
from batch_scheduler_tpu.core import ScheduleOperation
from batch_scheduler_tpu.utils import errors as errs

from helpers import FakeCluster, make_node, make_pod, status_for, make_group


def _request(n=4, g=2, r=5, members=3):
    alloc = np.zeros((n, r), np.int32)
    alloc[:, 0] = 8000
    alloc[:, 3] = 20
    requested = np.zeros((n, r), np.int32)
    group_req = np.zeros((g, r), np.int32)
    group_req[:, 0] = 1000
    group_req[:, 3] = 1
    return proto.ScheduleRequest(
        alloc=alloc,
        requested=requested,
        group_req=group_req,
        remaining=np.full(g, members, np.int32),
        fit_mask=np.ones((g, n), bool),
        group_valid=np.ones(g, bool),
        order=np.arange(g, dtype=np.int32),
        min_member=np.full(g, members, np.int32),
        scheduled=np.zeros(g, np.int32),
        matched=np.zeros(g, np.int32),
        ineligible=np.zeros(g, bool),
        creation_rank=np.arange(g, dtype=np.int32),
    )


def test_protocol_roundtrip():
    req = _request()
    packed = proto.pack_schedule_request(req)
    back = proto.unpack_schedule_request(packed)
    np.testing.assert_array_equal(req.alloc, back.alloc)
    np.testing.assert_array_equal(req.fit_mask, back.fit_mask)
    np.testing.assert_array_equal(req.creation_rank, back.creation_rank)

    resp = proto.ScheduleResponse(
        gang_feasible=np.array([True, False]),
        placed=np.array([True, False]),
        progress=np.array([700, 0], np.int32),
        best=0,
        best_exists=True,
        assignment_nodes=np.arange(8, dtype=np.int32).reshape(2, 4),
        assignment_counts=np.ones((2, 4), np.int32),
    )
    back = proto.unpack_schedule_response(proto.pack_schedule_response(resp))
    assert back.best == 0 and back.best_exists
    np.testing.assert_array_equal(resp.assignment_nodes, back.assignment_nodes)


@pytest.fixture(scope="module")
def server():
    srv = serve_background()
    yield srv
    srv.shutdown()


def test_server_python_client(server):
    host, port = server.address
    client = OracleClient(host, port)
    assert client.ping()
    resp = client.schedule(_request())
    assert resp.gang_feasible.tolist() == [True, True]
    assert resp.placed.tolist() == [True, True]
    # rows from the last batch (presenting its batch token)
    row = client.row("capacity", 0, resp.batch_seq)
    assert row.shape[0] >= 4 and row[:4].min() >= 1
    client.close()


def test_server_rejects_bad_row_index_and_stale_batch(server):
    host, port = server.address
    client = OracleClient(host, port)
    resp = client.schedule(_request())
    with pytest.raises(RuntimeError):
        client.row("capacity", 99999, resp.batch_seq)
    # connection stays usable after an in-band error
    assert client.ping()
    # a stale batch token is refused: rows can never come from a newer batch
    resp2 = client.schedule(_request())
    assert resp2.batch_seq != resp.batch_seq
    with pytest.raises(RuntimeError, match="stale batch"):
        client.row("capacity", 0, resp.batch_seq)
    client.close()


def test_remote_scorer_race_scenario(server):
    """The full gang-race semantics through the sidecar: ScheduleOperation
    with a RemoteScorer must agree with the in-process oracle."""
    host, port = server.address
    node = make_node("n1", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    cluster = FakeCluster([node])
    cluster.bind(make_pod("sys", requests={"cpu": "900m"}), "n1")
    cache = PGStatusCache()
    pods = {}
    for gname, ts in (("race1", 1.0), ("race2", 2.0)):
        pg = make_group(gname, 5, creation_ts=ts)
        members = [
            make_pod(f"{gname}-{i}", group=gname, requests={"cpu": "1"})
            for i in range(5)
        ]
        status_for(pg, cache, rep_pod=members[0])
        pods[gname] = members

    client = OracleClient(host, port)
    op = ScheduleOperation(cache, cluster, scorer=RemoteScorer(client))
    for pod in pods["race1"]:
        op.pre_filter(pod)
        op.permit(pod, "n1")
    for pod in pods["race1"]:
        cluster.bind(pod, "n1")
        op.post_bind(pod, "n1")
    with pytest.raises(errs.ResourceNotEnoughError):
        op.pre_filter(pods["race2"][0])
    # filter/score go through remote rows
    assert op.score(pods["race1"][0], "n1") > -(2**30)
    client.close()


def test_multi_device_mesh_assignment_in_client_space(server):
    """On the conftest's 8-device virtual mesh the sidecar shards every
    batch (scan_mesh set). The response's assignment must come back in
    the CLIENT's node index space with exact counts — the PR-1 bug
    returned the packed blob scaled by the node-shard count (node
    indexes striding by 4, counts 4x), so plans stamped empty."""
    assert server.scan_mesh is not None, "conftest must provide >1 device"
    host, port = server.address
    client = OracleClient(host, port)
    n, g, r = 5, 3, 2
    alloc = np.full((n, r), 10, np.int32)
    req = proto.ScheduleRequest(
        alloc=alloc,
        requested=np.zeros((n, r), np.int32),
        group_req=np.ones((g, r), np.int32),
        remaining=np.array([4, 3, 2], np.int32),
        fit_mask=np.ones((1, n), bool),
        group_valid=np.ones(g, bool),
        order=np.arange(g, dtype=np.int32),
        min_member=np.array([4, 3, 2], np.int32),
        scheduled=np.zeros(g, np.int32),
        matched=np.zeros(g, np.int32),
        ineligible=np.zeros(g, bool),
        creation_rank=np.arange(g, dtype=np.int32),
    )
    resp = client.schedule(req)
    assert resp.placed.tolist() == [True, True, True]
    # tightest-first on uniform nodes: every gang packs node 0
    for gi, count in enumerate((4, 3, 2)):
        row = {
            int(nd): int(ct)
            for nd, ct in zip(resp.assignment_nodes[gi], resp.assignment_counts[gi])
            if ct > 0
        }
        assert row == {0: count}, (gi, row)
    # no index may escape the client's node space (pad rows are zeroed)
    assert int(resp.assignment_nodes.max()) < n
    client.close()


def test_multi_device_sidecar_e2e_plan_path():
    """Whole-gang admission THROUGH a sharded-mesh sidecar: the gang's
    plan stamps non-empty (assignment_path == "plan") and the members
    seat through it without a single per-pod Permit wait — the exact
    path the shard-index mapping fix reopens (before it, plans stamped
    empty and members degraded to the per-pod scan)."""
    from batch_scheduler_tpu.api.types import PodGroupPhase
    from batch_scheduler_tpu.sim import (
        SimCluster,
        make_member_pods,
        make_sim_group,
        make_sim_node,
    )

    srv = serve_background()
    assert srv.scan_mesh is not None, "conftest must provide >1 device"
    client = OracleClient(*srv.address)
    scorer = RemoteScorer(client)
    # spy on plan stamping: the plan is cleared once the gang completes,
    # so capture what the batches actually handed the control plane
    stamped_plans = []
    orig_assignment = scorer.assignment

    def spy_assignment(full_name):
        plan = orig_assignment(full_name)
        stamped_plans.append((full_name, dict(plan)))
        return plan

    scorer.assignment = spy_assignment
    cluster = SimCluster(scorer=scorer)
    try:
        cluster.add_nodes(
            [make_sim_node(f"n{i}", {"cpu": "16", "pods": "64"}) for i in range(3)]
        )
        pg = make_sim_group("mdgang", 6)
        pg.spec.min_resources = {"cpu": 1000}
        cluster.create_group(pg)
        cluster.start()
        cluster.create_pods(make_member_pods("mdgang", 6, {"cpu": "1"}))
        assert cluster.wait_for_bound("mdgang", 6, timeout=60.0), (
            cluster.scheduler.stats
        )
        assert cluster.wait_for_group_phase(
            "mdgang", (PodGroupPhase.SCHEDULED, PodGroupPhase.RUNNING),
            timeout=20.0,
        )
        stats = cluster.scheduler.stats
        # non-empty stamped plans + zero permit waits == the plan path
        gang_plans = [
            plan for name, plan in stamped_plans if name == "default/mdgang"
        ]
        assert gang_plans, "no plan was ever stamped through the mesh"
        full = [p for p in gang_plans if sum(p.values()) >= 6]
        assert full, ("plans stamped empty/partial through the mesh",
                      gang_plans)
        assignment_path = (
            "plan" if full and stats["permit_waits"] == 0 else "scan"
        )
        assert assignment_path == "plan", (stats, gang_plans)
    finally:
        cluster.stop()
        scorer.close()
        srv.shutdown()


def test_native_client_wire_compat(server):
    from batch_scheduler_tpu.service.native import NativeOracleClient, ensure_built

    if ensure_built() is None:
        pytest.skip("no C++ toolchain available")
    host, port = server.address
    native = NativeOracleClient(host, port)
    assert native.ping()
    req = _request()
    resp_native = native.schedule(req)

    py_client = OracleClient(host, port)
    resp_py = py_client.schedule(req)

    np.testing.assert_array_equal(resp_native.gang_feasible, resp_py.gang_feasible)
    np.testing.assert_array_equal(resp_native.placed, resp_py.placed)
    np.testing.assert_array_equal(
        resp_native.assignment_counts, resp_py.assignment_counts
    )
    # row fetch through the native path matches python
    row_native = native.row("scores", 0, resp_native.batch_seq)
    row_py = py_client.row("scores", 0, resp_py.batch_seq)
    np.testing.assert_array_equal(row_native, row_py)
    native.close()
    py_client.close()

def test_native_client_broadcast_mask_row(server):
    """The mask_rows=1 wire form through the NATIVE client: a broadcast
    [1,N] fit-mask row must produce the same schedule as the expanded
    [G,N] mask (the frame-size win lives or dies on this C++ encode
    path)."""
    from batch_scheduler_tpu.service.native import NativeOracleClient, ensure_built

    if ensure_built() is None:
        pytest.skip("no C++ toolchain available")
    host, port = server.address
    req_full = _request()
    g, n = req_full.fit_mask.shape
    import dataclasses

    req_bcast = dataclasses.replace(
        req_full, fit_mask=np.ones((1, n), bool)
    )
    native = NativeOracleClient(host, port)
    resp_bcast = native.schedule(req_bcast)
    resp_full = native.schedule(req_full)
    np.testing.assert_array_equal(resp_bcast.placed, resp_full.placed)
    np.testing.assert_array_equal(
        resp_bcast.assignment_counts, resp_full.assignment_counts
    )
    np.testing.assert_array_equal(
        resp_bcast.gang_feasible, resp_full.gang_feasible
    )
    native.close()


def test_native_client_protocol_constants_in_sync():
    """Drift check between the Python wire protocol and the native C++
    client — the analog of the reference's codegen drift gate
    (reference hack/verify-codegen.sh:36-45): the generated/duplicated
    artifact must match the source of truth or CI fails."""
    import os
    import re

    from batch_scheduler_tpu.service import protocol as proto

    src = open(
        os.path.join(os.path.dirname(__file__), "..", "native", "bsp_client.cpp")
    ).read()

    magic = re.search(
        r"kMagic\[4\]\s*=\s*\{'(.)',\s*'(.)',\s*'(.)',\s*'(.)'\}", src
    )
    assert magic, "kMagic not found in bsp_client.cpp"
    assert "".join(magic.groups()).encode() == proto.MAGIC

    want = {
        "kScheduleReq": proto.MsgType.SCHEDULE_REQ,
        "kScheduleResp": proto.MsgType.SCHEDULE_RESP,
        "kRowReq": proto.MsgType.ROW_REQ,
        "kRowResp": proto.MsgType.ROW_RESP,
        "kPing": proto.MsgType.PING,
        "kPong": proto.MsgType.PONG,
        "kError": proto.MsgType.ERROR,
    }
    for name, value in want.items():
        m = re.search(rf"{name}\s*=\s*(\d+)", src)
        assert m, f"{name} not found in bsp_client.cpp"
        assert int(m.group(1)) == value, f"{name} drifted: C++ {m.group(1)} != py {value}"


def test_resilient_client_stale_batch_is_semantic_not_transport(server):
    """StaleBatchError through the retry layer: a stale-batch answer is a
    SEMANTIC response over a live transport — never retried (retrying
    cannot un-stale it) and never counted against the circuit breaker
    (with threshold=1 any transport classification would open it)."""
    from batch_scheduler_tpu.utils.metrics import Registry
    from batch_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

    host, port = server.address
    reg = Registry()
    client = ResilientOracleClient(
        host,
        port,
        timeout=30.0,
        registry=reg,
        retry_policy=RetryPolicy(max_attempts=3, base_delay=0.01),
        breaker=CircuitBreaker(failure_threshold=1, reset_timeout=60.0),
    )
    label = f"{host}:{port}"
    resp1 = client.schedule(_request())
    resp2 = client.schedule(_request())
    assert resp2.batch_seq != resp1.batch_seq
    with pytest.raises(errs.StaleBatchError):
        client.row("capacity", 0, resp1.batch_seq)
    assert client.breaker.state == "closed"
    assert reg.counter("bst_oracle_retries_total").value(
        op="row", client=label
    ) == 0
    assert reg.counter("bst_oracle_transport_failures_total").value(
        op="row", client=label
    ) == 0

    # other in-band server errors are equally semantic: surfaced as-is,
    # unretried, breaker untouched, connection still usable
    with pytest.raises(RuntimeError, match="out of range"):
        client.row("capacity", 99999, resp2.batch_seq)
    assert client.breaker.state == "closed"
    assert client.ping()
    client.close()


def test_remote_scorer_dual_connection_background_refresh(server):
    """Two connections unlock background refresh remotely: batches
    alternate between the connections, each batch's rows answer from the
    connection that executed it (no stale-batch errors across the
    alternation), and the operation accepts background_refresh without the
    single-connection downgrade warning."""
    import warnings

    host, port = server.address
    c_fg, c_bg = OracleClient(host, port), OracleClient(host, port)
    scorer = RemoteScorer(c_fg, background_client=c_bg)
    assert scorer.supports_background_refresh

    node = make_node("n1", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    cluster = FakeCluster([node])
    cache = PGStatusCache()
    pg = make_group("dual", 2, creation_ts=1.0)
    members = [
        make_pod(f"dual-{i}", group="dual", requests={"cpu": "1"})
        for i in range(2)
    ]
    status_for(pg, cache, rep_pod=members[0])

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        op = ScheduleOperation(
            cache, cluster, scorer=scorer, background_refresh=True
        )
    assert not any("background_refresh" in str(x.message) for x in w)
    assert scorer.background_refresh is True

    import time as _time

    scorer.ensure_fresh(cluster, cache, group="default/dual")  # blocking: no state yet
    assert scorer.batches_run == 1

    # each round: a BACKGROUND batch runs on the alternate connection while
    # rows keep answering from the current batch's connection (a wrong
    # routing would answer stale-batch in-band)
    for round_no in range(3):
        scorer.mark_dirty()
        scorer.ensure_fresh(cluster, cache, group="default/dual")  # kicks bg
        with scorer._bg_lock:  # guarded state, read guarded (lockcheck)
            assert scorer._bg_thread is not None  # background path ran

        assert op.score(members[0], "n1") > -(2**30)  # stale rows still served
        deadline = _time.monotonic() + 10.0
        while (
            scorer.batches_run < round_no + 2
            and _time.monotonic() < deadline
        ):
            _time.sleep(0.01)
        assert scorer.batches_run == round_no + 2, scorer._bg_error
        assert op.score(members[0], "n1") > -(2**30)  # fresh batch's rows
    assert scorer._bg_error is None
    scorer.drain_background()
    scorer.close()


def test_draining_frame_roundtrip():
    """DRAINING (MsgType 18) shares the BUSY payload layout plus a UTF-8
    failover hint; the hint may be empty (no standby configured)."""
    ms, hint = proto.unpack_draining(
        proto.pack_draining(250, "10.0.0.2:9090")
    )
    assert ms == 250 and hint == "10.0.0.2:9090"
    ms, hint = proto.unpack_draining(proto.pack_draining(100))
    assert ms == 100 and hint == ""


def test_parse_oracle_addresses():
    from batch_scheduler_tpu.service.client import parse_oracle_addresses

    assert parse_oracle_addresses("h1:9090,h2:9191") == [
        ("h1", 9090), ("h2", 9191),
    ]
    # bare ports keep the historical --oracle-addr sugar
    assert parse_oracle_addresses("9090") == [("127.0.0.1", 9090)]
    assert parse_oracle_addresses(":9090, h2:91 ,") == [
        ("127.0.0.1", 9090), ("h2", 91),
    ]
    with pytest.raises(ValueError):
        parse_oracle_addresses("")
    with pytest.raises(ValueError):
        parse_oracle_addresses("h1:notaport")


def test_drain_refuses_work_keeps_ping_and_reports_flush():
    """A draining sidecar answers DRAINING (with the failover hint) to
    work requests, keeps PING flowing (half-open probes must succeed so
    clients can see the DRAINING answer), and reports a clean flush."""
    srv = serve_background()
    host, port = srv.address
    client = OracleClient(host, port)
    try:
        assert client.schedule(_request()).placed.all()
        report = srv.drain(timeout=5.0, failover_hint="standby:1234")
        assert report["drained"] is True
        assert report["telemetry_joined"] is True
        assert report["audit_flushed"] is True
        assert srv.draining() is True
        assert client.ping()  # probes still flow
        with pytest.raises(errs.OracleDrainingError) as ei:
            client.schedule(_request())
        assert ei.value.failover_hint == "standby:1234"
        assert ei.value.retry_after_ms > 0
        # idempotent: a second drain returns the same report
        assert srv.drain(timeout=5.0)["drained"] is True
    finally:
        client.close()
        srv.shutdown()
        srv.server_close()
