"""End-to-end simulated-cluster tests: the full framework (API server,
informers, scheduler, plugin, controller, leader gate, sim kubelet) running
the BASELINE config-1 race scenario and the gang timeout/abort path."""

import pytest

from batch_scheduler_tpu.api import PodGroupPhase
from batch_scheduler_tpu.sim import (
    SimCluster,
    make_member_pods,
    make_sim_group,
    make_sim_node,
    race_scenario,
)


@pytest.fixture
def sim(request):
    clusters = []

    def build(**kwargs):
        c = SimCluster(**kwargs)
        clusters.append(c)
        return c

    yield build
    for c in clusters:
        c.stop()


@pytest.mark.parametrize("scorer", ["oracle", "serial"])
def test_race_scenario_end_to_end(sim, scorer):
    """README race demo: exactly one of two gangs schedules and runs; the
    loser binds nothing."""
    cluster = sim(scorer=scorer)
    nodes, groups, pods = race_scenario()
    cluster.add_nodes(nodes)
    # ~0.9 cpu of system load, bound outside any group
    sysload = make_member_pods("sysload", 1, {"cpu": "900m"})[0]
    sysload.metadata.labels = {}
    sysload.spec.node_name = "node-1"
    cluster.clientset.pods().create(sysload)

    for pg in groups:
        cluster.create_group(pg)
    cluster.start()
    for group_pods in pods.values():
        cluster.create_pods(group_pods)

    assert cluster.wait_for_bound("web-group-race1", 5, timeout=30.0), (
        cluster.member_phase_counts("web-group-race1"),
        cluster.scheduler.stats,
    )
    assert cluster.wait_for_group_phase(
        "web-group-race1",
        (PodGroupPhase.SCHEDULED, PodGroupPhase.RUNNING),
        timeout=30.0,
    )
    # winner reaches Running once the sim kubelet starts its pods
    assert cluster.wait_for_group_phase(
        "web-group-race1", PodGroupPhase.RUNNING, timeout=30.0
    )

    # the loser must have bound nothing
    race2_bound = [
        p for p in cluster.member_pods("web-group-race2") if p.spec.node_name
    ]
    assert race2_bound == []
    assert cluster.group_phase("web-group-race2") in (
        PodGroupPhase.PENDING,
        PodGroupPhase.PRE_SCHEDULING,
    )


def test_multi_node_gang_spreads_and_runs(sim):
    cluster = sim(scorer="oracle")
    cluster.add_nodes(
        [make_sim_node(f"n{i}", {"cpu": "4", "memory": "16Gi", "pods": "20"}) for i in range(4)]
    )
    cluster.create_group(make_sim_group("wide", 12))
    cluster.start()
    cluster.create_pods(make_member_pods("wide", 12, {"cpu": "1"}))

    assert cluster.wait_for_bound("wide", 12, timeout=30.0), (
        cluster.member_phase_counts("wide"),
        cluster.scheduler.stats,
    )
    assert cluster.wait_for_group_phase("wide", PodGroupPhase.RUNNING, timeout=30.0)
    # 12 x 1cpu over 4 x 4cpu nodes: best-fit packs into exactly 3 nodes,
    # leaving one node entirely free for wide pods
    nodes_used = {p.spec.node_name for p in cluster.member_pods("wide")}
    assert len(nodes_used) == 3, nodes_used


def _fragmented_gang_setup(cluster):
    """Cluster-sum feasible but fragmentation-infeasible: 3 nodes x 2 cpu
    (6 cpu total) vs a 4-member gang of 1.5-cpu pods (6 cpu total) — each
    node fits only one member, so at most 3 of 4 can ever place."""
    cluster.add_nodes(
        [make_sim_node(f"n{i}", {"cpu": "2", "pods": "10"}) for i in range(3)]
    )
    cluster.create_group(make_sim_group("frag", 4, max_schedule_time=1.0))
    cluster.start()
    cluster.create_pods(make_member_pods("frag", 4, {"cpu": "1500m"}))


def test_gang_timeout_aborts_partial_gang_serial(sim):
    """The serial scorer's raw cluster-sum check admits a fragmentation-
    infeasible gang (reference semantics); the TTL abort path must then
    release its permitted pods and back the group off (reference §3.4)."""
    cluster = sim(scorer="serial")
    _fragmented_gang_setup(cluster)

    op = cluster.runtime.operation
    # some members get permitted and parked, but the gang can't complete
    assert cluster.wait_for(
        lambda: (pgs := op.status_cache.get("default/frag")) is not None
        and len(pgs.matched_pod_nodes.items()) > 0,
        timeout=15.0,
    ), cluster.scheduler.stats
    # after the 1s TTL: gang aborted -> deny backoff + all waits cleared
    assert cluster.wait_for(
        lambda: op.last_denied_pg.contains("default/frag"), timeout=15.0
    )
    assert cluster.wait_for(lambda: len(cluster.scheduler.waiting) == 0, timeout=15.0)
    assert all(not p.spec.node_name for p in cluster.member_pods("frag"))


def test_oracle_rejects_fragmented_gang_upfront(sim):
    """The capacity-based oracle sees through fragmentation and denies the
    gang before any pod is permitted — strictly better than the reference's
    cluster-sum heuristic (SURVEY.md §7 hard parts)."""
    cluster = sim(scorer="oracle")
    _fragmented_gang_setup(cluster)

    op = cluster.runtime.operation
    assert cluster.wait_for(
        lambda: op.last_denied_pg.contains("default/frag"), timeout=15.0
    ), cluster.scheduler.stats
    assert cluster.scheduler.stats["permit_waits"] == 0
    assert all(not p.spec.node_name for p in cluster.member_pods("frag"))


def test_non_group_pods_schedule_immediately(sim):
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "4", "pods": "10"})])
    cluster.start()
    solo = make_member_pods("solo", 2, {"cpu": "1"})
    for p in solo:
        p.metadata.labels = {}
    cluster.create_pods(solo)
    assert cluster.wait_for(
        lambda: all(
            cluster.clientset.pods().get(p.metadata.name).spec.node_name
            for p in solo
        ),
        timeout=15.0,
    ), cluster.scheduler.stats


def test_gang_granular_admission_batches_scale_with_gangs(sim):
    """VERDICT r1 item 3: once a batch places a gang, member pods must ride
    the stamped placement plan — oracle batches_run scales with gangs, not
    pods. 6 gangs x 8 pods = 48 pods must need far fewer than 48 batches."""
    n_gangs, members = 6, 8
    cluster = sim(scorer="oracle")
    cluster.add_nodes(
        [make_sim_node(f"n{i}", {"cpu": "16", "pods": "64"}) for i in range(4)]
    )
    for g in range(n_gangs):
        cluster.create_group(make_sim_group(f"gang{g}", members, creation_ts=float(g)))
    cluster.start()
    for g in range(n_gangs):
        cluster.create_pods(make_member_pods(f"gang{g}", members, {"cpu": "1"}))

    for g in range(n_gangs):
        assert cluster.wait_for_bound(f"gang{g}", members, timeout=30.0), (
            g,
            cluster.member_phase_counts(f"gang{g}"),
            cluster.scheduler.stats,
        )
    oracle = cluster.runtime.operation.oracle
    total_pods = n_gangs * members
    # budget: ~1 batch to plan + ~1 per gang completion + small slack for
    # informer-driven churn; anything near total_pods means per-pod re-batching
    assert oracle.batches_run <= 3 * n_gangs, (
        oracle.batches_run,
        total_pods,
        cluster.scheduler.stats,
    )
    assert oracle.batches_run < total_pods // 2
    # the plan fast path, not the O(nodes) scan, must have routed members:
    # every gang got a stamped plan (the whole-gang fast lane consumes the
    # plan on completion, so the stamp sequence is the surviving evidence)
    for g in range(n_gangs):
        pgs = cluster.runtime.operation.status_cache.get(f"default/gang{g}")
        assert pgs is not None and pgs.plan_batch_seq >= 1, g


def test_preemption_evicts_pending_gang_member_only(sim):
    """VERDICT r1 item 9 e2e: an online (non-group) pod preempts a pending
    gang's permitted member, but never touches a Running gang
    (reference policy core.go:203-260, hooks batchscheduler.go:116-144)."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "4", "pods": "10"})])
    # incomplete gang: 3 of 4 members exist, so they park in Permit wait
    cluster.create_group(make_sim_group("lowgang", 4))
    cluster.start()
    cluster.create_pods(make_member_pods("lowgang", 3, {"cpu": "1"}))

    op = cluster.runtime.operation
    assert cluster.wait_for(
        lambda: (pgs := op.status_cache.get("default/lowgang")) is not None
        and len(pgs.matched_pod_nodes.items()) == 3,
        timeout=15.0,
    ), cluster.scheduler.stats

    # online pod needs 2 cpu; only 1 is free -> must evict one member
    online = make_member_pods("online", 1, {"cpu": "2"}, priority=10)
    for p in online:
        p.metadata.labels = {}
    cluster.create_pods(online)

    assert cluster.wait_for(
        lambda: cluster.clientset.pods().get("online-0").spec.node_name,
        timeout=20.0,
    ), cluster.scheduler.stats
    assert cluster.scheduler.stats["preemptions"] >= 1
    # exactly one member was evicted (deleted), the others still pending
    remaining = cluster.member_pods("lowgang")
    assert len(remaining) == 2, [p.metadata.name for p in remaining]


def test_preemption_never_touches_running_gang(sim):
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "4", "pods": "10"})])
    cluster.create_group(make_sim_group("rungang", 3))
    cluster.start()
    cluster.create_pods(make_member_pods("rungang", 3, {"cpu": "1"}))
    assert cluster.wait_for_group_phase(
        "rungang", PodGroupPhase.RUNNING, timeout=30.0
    ), cluster.member_phase_counts("rungang")

    online = make_member_pods("online", 1, {"cpu": "2"}, priority=10)
    for p in online:
        p.metadata.labels = {}
    cluster.create_pods(online)

    # the online pod must stay unbound: Running gang members are protected
    import time as _time

    _time.sleep(2.0)
    assert not cluster.clientset.pods().get("online-0").spec.node_name
    assert cluster.scheduler.stats["preemptions"] == 0
    assert len([p for p in cluster.member_pods("rungang") if p.spec.node_name]) == 3


def test_preemption_picks_fewest_victims_node(sim):
    """kube-scheduler candidate selection (VERDICT r2 missing #2): when a
    single eviction on one node suffices, a node needing TWO victims must
    not be chosen even if it comes first in node order."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes(
        [
            make_sim_node("n1", {"cpu": "4", "pods": "10"}, labels={"zone": "a"}),
            make_sim_node("n2", {"cpu": "4", "pods": "10"}, labels={"zone": "b"}),
        ]
    )
    # gangA: three 1-cpu members pinned to n1 (1 cpu free there -> the
    # preemptor would need 2 victims); gangB: one 2-cpu member pinned to n2
    # (2 cpu free -> exactly 1 victim suffices)
    cluster.create_group(make_sim_group("ganga", 4))
    cluster.create_group(make_sim_group("gangb", 2))
    cluster.start()
    pods_a = make_member_pods("ganga", 3, {"cpu": "1"})
    for p in pods_a:
        p.spec.node_selector = {"zone": "a"}
    pods_b = make_member_pods("gangb", 1, {"cpu": "2"})
    for p in pods_b:
        p.spec.node_selector = {"zone": "b"}
    cluster.create_pods(pods_a)
    cluster.create_pods(pods_b)

    op = cluster.runtime.operation
    assert cluster.wait_for(
        lambda: (a := op.status_cache.get("default/ganga")) is not None
        and len(a.matched_pod_nodes.items()) == 3
        and (b := op.status_cache.get("default/gangb")) is not None
        and len(b.matched_pod_nodes.items()) == 1,
        timeout=20.0,
    ), cluster.scheduler.stats

    # needs 3 cpu: no node has it free; n2 frees it with ONE victim
    online = make_member_pods("online", 1, {"cpu": "3"}, priority=10)
    for p in online:
        p.metadata.labels = {}
    cluster.create_pods(online)

    assert cluster.wait_for(
        lambda: cluster.clientset.pods().get("online-0").spec.node_name,
        timeout=20.0,
    ), cluster.scheduler.stats
    assert cluster.clientset.pods().get("online-0").spec.node_name == "n2"
    # gangb's single member was the victim; ganga untouched
    assert len(cluster.member_pods("ganga")) == 3
    assert len(cluster.member_pods("gangb")) == 0
    assert cluster.scheduler.stats["preemptions"] >= 1


def test_preemption_prefers_low_priority_victims_over_fewest(sim):
    """Upstream pickOneNodeForPreemption precedence: lowest
    highest-victim-priority dominates victim count — two priority-0
    victims beat one priority-5 victim."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes(
        [
            make_sim_node("n1", {"cpu": "4", "pods": "10"}, labels={"zone": "a"}),
            make_sim_node("n2", {"cpu": "4", "pods": "10"}, labels={"zone": "b"}),
        ]
    )
    # n1: one 2-cpu priority-5 member (2 free); n2: two 1-cpu priority-0
    # members (2 free). Preemptor needs 3 cpu: n1 = 1 victim (prio 5),
    # n2 = 1.. no — 2 free + evict one 1-cpu = 3 -> ONE victim on n2 too,
    # but at priority 0. Fewest-victims ties; priority must decide n2.
    cluster.create_group(make_sim_group("highgang", 2))
    cluster.create_group(make_sim_group("lowgang", 3))
    cluster.start()
    pods_h = make_member_pods("highgang", 1, {"cpu": "2"}, priority=5)
    for p in pods_h:
        p.spec.node_selector = {"zone": "a"}
    pods_l = make_member_pods("lowgang", 2, {"cpu": "1"}, priority=0)
    for p in pods_l:
        p.spec.node_selector = {"zone": "b"}
    cluster.create_pods(pods_h)
    cluster.create_pods(pods_l)

    op = cluster.runtime.operation
    assert cluster.wait_for(
        lambda: (h := op.status_cache.get("default/highgang")) is not None
        and len(h.matched_pod_nodes.items()) == 1
        and (low := op.status_cache.get("default/lowgang")) is not None
        and len(low.matched_pod_nodes.items()) == 2,
        timeout=20.0,
    ), cluster.scheduler.stats

    online = make_member_pods("online", 1, {"cpu": "3"}, priority=10)
    for p in online:
        p.metadata.labels = {}
    cluster.create_pods(online)

    assert cluster.wait_for(
        lambda: cluster.clientset.pods().get("online-0").spec.node_name,
        timeout=20.0,
    ), cluster.scheduler.stats
    # the priority-0 victim on n2 was chosen; the priority-5 member survives
    assert cluster.clientset.pods().get("online-0").spec.node_name == "n2"
    assert len(cluster.member_pods("highgang")) == 1
    assert len(cluster.member_pods("lowgang")) == 1


def test_new_extended_resource_after_first_batch(sim):
    """Schema-cache correctness: a later gang introducing a resource name
    the cached lane schema has never seen forces a fresh collect (not a
    KeyError, not a silent drop) and the gang is correctly denied when no
    node exposes it."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "8", "pods": "20"})])
    cluster.create_group(make_sim_group("plain", 2))
    cluster.start()
    cluster.create_pods(make_member_pods("plain", 2, {"cpu": "1"}))
    assert cluster.wait_for(
        lambda: cluster.scheduler.stats["binds"] >= 2, timeout=30.0
    ), cluster.scheduler.stats

    # second gang needs an accelerator no node has — arrives after the
    # schema was collected and cached for the first batch
    cluster.create_group(make_sim_group("accel", 2))
    pods = make_member_pods("accel", 2, {"cpu": "1", "fake.com/npu": "1"})
    cluster.create_pods(pods)
    # positive denial signal (NOT a crash: a broken batch would requeue via
    # the cycle's exception path without counting an unschedulable denial)
    assert cluster.wait_for(
        lambda: cluster.scheduler.stats["unschedulable"] >= 2, timeout=20.0
    ), cluster.scheduler.stats
    bound = [p for p in cluster.member_pods("accel") if p.spec.node_name]
    assert bound == [], [p.metadata.name for p in bound]
    assert cluster.scheduler.stats["binds"] == 2

    # the scheduler is still alive after the schema rebuild: a third,
    # feasible gang binds normally
    cluster.create_group(make_sim_group("after", 2))
    cluster.create_pods(make_member_pods("after", 2, {"cpu": "1"}))
    assert cluster.wait_for(
        lambda: cluster.scheduler.stats["binds"] >= 4, timeout=30.0
    ), cluster.scheduler.stats


def test_prescheduling_gang_with_lost_bind_responses_recovers(sim):
    """A gang whose early binds committed but whose responses were lost
    (API outage) sits PreScheduling with live non-Pending members and an
    undercounted Status.Scheduled; the permit quorum is then unreachable
    for the remaining members. The controller must count members in
    PRE_SCHEDULING too (beyond the reference's Scheduling+ gate) so the
    quorum becomes reachable and the gang completes via the TTL abort
    retry. Found by the gateway-restart soak."""
    import time

    cluster = sim(
        scorer="oracle",
        max_schedule_minutes=0.05,  # 3s gang TTL: fast abort-retry cycles
        backoff_base=0.1,
        backoff_cap=0.5,
        kubelet_start_delay=0.01,
    )
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("lost", 4, creation_ts=time.time())
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()

    pods = make_member_pods("lost", 4, {"cpu": "1"})
    # two members "bind with lost responses": committed in the store
    # (and will go Running via the kubelet) but the scheduler never
    # saw success — no post_bind, no scheduled bump
    for p in pods[:2]:
        cluster.clientset.pods().create(p)
        cluster.clientset.pods().bind(p.metadata.name, "n1")
    # the gang is mid-admission from the scheduler's perspective
    op = cluster.runtime.operation
    assert cluster.wait_for(
        lambda: op.status_cache.get("default/lost") is not None,
        timeout=10.0,
    )
    pgs = op.status_cache.get("default/lost")
    from batch_scheduler_tpu.api import PodGroupPhase

    pgs.pod_group.status.phase = PodGroupPhase.PRE_SCHEDULING
    pgs.scheduled = True  # released

    # remaining two members arrive normally; quorum needs
    # min_member - scheduled = 4 - 0 = 4 while only 2 remain ->
    # unreachable until the controller corrects scheduled to 2
    cluster.create_pods(pods[2:])
    assert cluster.wait_for(
        lambda: all(
            cluster.clientset.pods().get(p.metadata.name).spec.node_name
            for p in pods
        ),
        timeout=30.0,
    ), (
        cluster.scheduler.stats,
        cluster.group("lost").status,
    )


def test_capacity_observatory_in_sim_verdict(sim, monkeypatch):
    """The capacity observatory end to end over a real sim (satellite of
    the capacity-observatory PR): the scorer's publish hook samples, the
    harness view answers like /debug/capacity would, tenant shares are
    attributed by namespace, and the exit-verdict line the CLI prints
    formats from the same summary."""
    monkeypatch.setenv("BST_CAPACITY", "1")
    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "1.0")
    cluster = sim(scorer="oracle")
    cluster.add_nodes(
        [make_sim_node(f"c{i}", {"cpu": "8", "pods": "64"}) for i in range(4)]
    )
    for t in range(2):
        cluster.create_group(
            make_sim_group(f"capg{t}", 2, namespace=f"team-{t}",
                           creation_ts=float(t))
        )
    cluster.start()
    for t in range(2):
        cluster.create_pods(
            make_member_pods(f"capg{t}", 2, {"cpu": "1"},
                             namespace=f"team-{t}")
        )
        assert cluster.wait_for(
            lambda t=t: sum(
                1
                for p in cluster.member_pods(f"capg{t}", f"team-{t}")
                if p.spec.node_name
            ) >= 2,
            timeout=30.0,
        )

    report = cluster.capacity()
    assert report["samples"] >= 1, report
    last = report["last"]
    assert last is not None
    assert last["placed"]["gangs"] >= 1
    tenants = {t["tenant"] for t in last["tenants"]}
    assert {"team-0", "team-1"} & tenants, tenants
    # shares conserve per lane (the bench-capacity acceptance, in-suite)
    sums = {}
    for t in last["tenants"]:
        for lane, share in t["shares"].items():
            sums[lane] = sums.get(lane, 0.0) + share
    assert all(v <= 1.000001 for v in sums.values()), sums

    from batch_scheduler_tpu.ops.capacity import (
        active_sampler,
        format_capacity_verdict,
    )

    sampler = active_sampler()
    line = format_capacity_verdict(sampler.last(), sampler.lane_names())
    assert line.startswith("capacity: frag ")
    assert "busiest lane cpu" in line
    # the decision records carry the tenant stamp (utils.tenancy)
    decisions = cluster.decisions("team-0/capg0")
    recs = decisions.get("team-0/capg0") or []
    assert recs and all(
        r.get("tenant") == "team-0" for r in recs
    ), recs[:2]
