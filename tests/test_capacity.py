"""Capacity observatory (ops.capacity + utils.timeseries + utils.tenancy
+ the health burn-rate model): kernel exactness against hand-computed
clusters, budget gating, audit-event replay identity, the downsampling
ring's bounds, tenant cardinality capping, the /debug/capacity endpoint,
and the multi-window burn-rate verdicts."""

import json
import time

import numpy as np
import pytest

from batch_scheduler_tpu.ops.capacity import (
    CapacitySampler,
    annotate_summary,
    capacity_budget_frac,
    capacity_debug_view,
    capacity_enabled,
    capacity_summary,
    format_capacity_verdict,
    set_active_sampler,
)
from batch_scheduler_tpu.ops.oracle import _BINS, execute_batch_host
from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot, GroupDemand
from batch_scheduler_tpu.sim.scenarios import make_sim_node
from batch_scheduler_tpu.utils import tenancy
from batch_scheduler_tpu.utils.timeseries import DownsamplingRing


@pytest.fixture(autouse=True)
def _clean_observatory_state():
    tenancy.reset_registry()
    tenancy.set_batch_tenant(None)
    yield
    set_active_sampler(None)
    tenancy.reset_registry()
    tenancy.set_batch_tenant(None)


def _snapshot(nodes_n=8, gangs=4, members=2, cpu="8", req_cpu=2000,
              tenants=2):
    nodes = [
        make_sim_node(f"n{i:03d}", {"cpu": cpu, "memory": "32Gi",
                                    "pods": "110"})
        for i in range(nodes_n)
    ]
    groups = [
        GroupDemand(
            f"team-{g % tenants}/gang-{g}", members,
            member_request={"cpu": req_cpu}, creation_ts=float(g),
        )
        for g in range(gangs)
    ]
    return nodes, groups, ClusterSnapshot(nodes, {}, groups)


def _summarize(snap, host):
    progress = snap.progress_args()
    return capacity_summary(
        snap.device_args(), host,
        group_names=snap.group_names,
        scheduled=progress[1], matched=progress[2],
    )


# ---------------------------------------------------------------------------
# tenancy
# ---------------------------------------------------------------------------


def test_tenant_label_caps_cardinality(monkeypatch):
    monkeypatch.setenv("BST_TENANT_LABEL_MAX", "2")
    assert tenancy.tenant_label("alpha") == "alpha"
    assert tenancy.tenant_label("beta") == "beta"
    # the cap is reached: every NEW namespace overflows into "other",
    # while already-registered labels stay stable
    assert tenancy.tenant_label("gamma") == tenancy.OTHER_TENANT
    assert tenancy.tenant_label("alpha") == "alpha"
    assert tenancy.tenant_label("") == ""


def test_tenant_cap_parse_guard(monkeypatch):
    monkeypatch.setenv("BST_TENANT_LABEL_MAX", "not-a-number")
    assert tenancy.tenant_cap() == 32
    monkeypatch.setenv("BST_TENANT_LABEL_MAX", "0")
    assert tenancy.tenant_cap() == 1


def test_batch_tenants_deterministic_and_padded(monkeypatch):
    monkeypatch.setenv("BST_TENANT_LABEL_MAX", "2")
    names = ["b/x", "a/y", "a/z", "c/w"]
    ids, labels = tenancy.batch_tenants(names, g_bucket=6)
    # ranked by (count desc, name asc): a(2), then b and c tie on count
    # -> b wins by name; c overflows; pads map to "other"
    assert labels == ["a", "b", "other"]
    assert ids.tolist() == [1, 0, 0, 2, 2, 2]
    ids2, labels2 = tenancy.batch_tenants(list(names), g_bucket=6)
    assert labels2 == labels and ids2.tolist() == ids.tolist()


# ---------------------------------------------------------------------------
# the downsampling ring
# ---------------------------------------------------------------------------


def test_ring_downsamples_and_stays_bounded():
    ring = DownsamplingRing(capacity=4, levels=3)
    for i in range(100):
        ring.append(float(i), {"v": float(i), "v_max": float(i)})
    stats = ring.stats()
    assert stats["appended"] == 100
    assert stats["retained"] <= 4 * 3
    series = ring.series()
    # chronological: coarse history first, raw tail last
    ts = [e["ts"] for e in series]
    assert ts == sorted(ts)
    # merged entries average plain numerics and keep *_max extrema
    merged = [e for e in series if e["merged"] > 1]
    assert merged, "no downsampled entries after 100 appends"
    for e in merged:
        assert e["data"]["v_max"] >= e["data"]["v"]
    # the newest raw sample survives verbatim
    assert ring.last()["data"]["v"] == 99.0
    assert len(ring.series(max_points=3)) == 3


def test_ring_drops_oldest_at_top_level():
    ring = DownsamplingRing(capacity=2, levels=2)
    for i in range(50):
        ring.append(float(i), {"v": 1.0})
    assert ring.stats()["dropped"] > 0
    assert ring.stats()["retained"] <= 4


# ---------------------------------------------------------------------------
# the analytics kernel
# ---------------------------------------------------------------------------


def test_summary_utilization_and_plan_accounting():
    """4 gangs x 2 members x 2000m on 8 x 8-core nodes: the plan's seats
    must show up as lane utilization, and the seat histogram must hold
    exactly the placed seats."""
    nodes, groups, snap = _snapshot()
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    assert np.asarray(host["placed"])[:4].all()
    s = _summarize(snap, host)
    assert s["placed"] == {"gangs": 4, "seats": 8}
    assert s["pending"] == {"gangs": 0, "seats": 0, "unplaceable_gangs": 0}
    cpu_lane = next(
        lane for i, lane in enumerate(s["lanes"])
        if list(snap.schema.names)[lane["lane"]] == "cpu"
    )
    # 8 seats x 2000m consumed of 8 nodes x 8000m allocatable
    assert cpu_lane["alloc"] == 8 * 8000
    assert cpu_lane["utilization"] == pytest.approx(
        (8 * 2000) / (8 * 8000), abs=1e-6
    )
    assert sum(s["seat_tightness_hist"]) == 8
    assert s["nodes"] == 8


def test_summary_pending_and_unplaceable():
    """A gang wider than the whole cluster is pending AND capacity-
    unplaceable; a merely-waiting gang is pending but placeable."""
    nodes, groups, snap = _snapshot(nodes_n=2, gangs=1, members=2)
    giant = GroupDemand("big/giant", 64, member_request={"cpu": 4000},
                       creation_ts=9.0)
    groups = groups + [giant]
    snap = ClusterSnapshot(nodes, {}, groups)
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    placed = np.asarray(host["placed"])
    assert placed[0] and not placed[1]
    s = _summarize(snap, host)
    assert s["pending"]["gangs"] == 1
    assert s["pending"]["seats"] == 64
    assert s["pending"]["unplaceable_gangs"] == 1
    # the pending tenant is attributed its waiting seats
    big = next(t for t in s["tenants"] if t["tenant"] == "big")
    assert big["pending_seats"] == 64


def test_summary_stranded_capacity():
    """Nodes with headroom that no pending shape can consume are
    stranded; with no pending work nothing is stranded by definition."""
    nodes, groups, snap = _snapshot(nodes_n=4, gangs=2, members=2,
                                    req_cpu=3000)
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    s = _summarize(snap, host)
    assert s["stranded"]["nodes"] == 0  # nothing pending
    # now add a pending gang whose members need more cpu than ANY node's
    # leftover: every node with headroom is stranded relative to it
    wide = GroupDemand("w/wide", 4, member_request={"cpu": 64000},
                       creation_ts=9.0)
    snap2 = ClusterSnapshot(nodes, {}, groups + [wide])
    host2, _ = execute_batch_host(
        snap2.device_args(), snap2.progress_args()
    )
    s2 = _summarize(snap2, host2)
    assert not np.asarray(host2["placed"])[2]
    assert s2["stranded"]["nodes"] == 4
    assert s2["pending"]["unplaceable_gangs"] == 1
    top = s2["stranded"]["top_lane"]
    assert s2["lanes"][top]["stranded_free"] > 0


def test_summary_headroom_hist_bucketing():
    """The per-lane spectrum uses the scan's min(cap, _BINS-1) clamp: a
    pending demand of 2000m against 8000m-free nodes puts every node in
    bucket 4 on the cpu lane."""
    nodes, groups, snap = _snapshot(nodes_n=4, gangs=1, members=1,
                                    req_cpu=2000)
    # keep the gang pending by demanding more members than one node holds
    pend = GroupDemand("p/pend", 64, member_request={"cpu": 2000},
                       creation_ts=9.0)
    snap = ClusterSnapshot(nodes, {}, [pend])
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    s = _summarize(snap, host)
    cpu_i = list(snap.schema.names).index("cpu")
    lane = next(l for l in s["lanes"] if l["lane"] == cpu_i)
    assert lane["ref_member_demand"] > 0
    hist = lane["headroom_hist"]
    assert len(hist) == _BINS
    cap_per_node = 8000 // lane["ref_member_demand"]
    assert hist[min(cap_per_node, _BINS - 1)] == 4
    assert sum(hist) == 4


def test_summary_tenant_shares_conserve():
    nodes, groups, snap = _snapshot(nodes_n=8, gangs=6, members=2,
                                    tenants=3)
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    s = _summarize(snap, host)
    assert {t["tenant"] for t in s["tenants"]} == {
        "team-0", "team-1", "team-2"
    }
    sums = {}
    for t in s["tenants"]:
        for lane, share in t["shares"].items():
            sums[lane] = sums.get(lane, 0.0) + share
    assert all(v <= 1.000001 for v in sums.values())
    assert s["top_tenant"].startswith("team-")


def test_summary_fragmentation_sweep():
    """Fragmentation: pooled capacity minus the largest single placeable
    unit. A pending gang that still fits whole keeps the index low; the
    largest-placeable figure matches a brute-force check."""
    nodes, groups, snap = _snapshot(nodes_n=4, gangs=1, members=1,
                                    req_cpu=2000)
    pend = GroupDemand("p/pend", 64, member_request={"cpu": 2000},
                       creation_ts=9.0)
    snap = ClusterSnapshot(nodes, {}, [pend])
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    s = _summarize(snap, host)
    # 4 nodes x 4 members of 2000m each = 16 pooled; the biggest
    # power-of-two gang with pooled >= size is 16
    assert s["largest_placeable_gang"] == 16
    assert s["largest_placeable_by_tier"][0] == 16
    assert 0.0 <= s["fragmentation_index"] <= 1.0


def test_annotate_and_verdict_line():
    nodes, groups, snap = _snapshot()
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    s = _summarize(snap, host)
    names = list(snap.schema.names)
    view = annotate_summary(s, names)
    assert view["lanes"][0]["name"] == names[0]
    line = format_capacity_verdict(s, names)
    assert line.startswith("capacity: frag ")
    assert "busiest lane" in line and "top tenant team-" in line
    # the canonical summary stays index-keyed (bit-compare contract)
    assert "name" not in s["lanes"][0]


# ---------------------------------------------------------------------------
# the sampler: budget gate, gauges, audit events
# ---------------------------------------------------------------------------


def test_sampler_budget_gates(monkeypatch):
    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "0.0001")
    nodes, groups, snap = _snapshot()
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    sampler = CapacitySampler(label="t")
    first = sampler.note_batch(
        snap.device_args(), host, group_names=snap.group_names
    )
    assert first is not None
    # at frac=1e-4 the next slot is kernel_s * 10_000 seconds away
    assert sampler.note_batch(
        snap.device_args(), host, group_names=snap.group_names
    ) is None
    assert sampler.samples == 1 and sampler.skipped == 1
    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "1.0")
    # frac >= 1 disarms the gate entirely after the next sample window
    sampler2 = CapacitySampler(label="t2")
    assert sampler2.note_batch(
        snap.device_args(), host, group_names=snap.group_names
    ) is not None
    assert sampler2.note_batch(
        snap.device_args(), host, group_names=snap.group_names
    ) is not None
    assert sampler2.samples == 2


def test_sampler_budget_frac_parse_guard(monkeypatch):
    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "junk")
    assert capacity_budget_frac() == 0.02
    monkeypatch.setenv("BST_CAPACITY", "junk-on")
    assert capacity_enabled() is True
    monkeypatch.setenv("BST_CAPACITY", "off")
    assert capacity_enabled() is False


def test_sampler_audit_event_replays_bit_identically(tmp_path,
                                                     monkeypatch):
    """The offline contract end to end at unit scale: a recorded batch +
    its capacity_sample event, recomputed through the same kernel from
    the reader's reconstruction, compares equal representation-for-
    representation."""
    from batch_scheduler_tpu.utils import audit as audit_mod
    from batch_scheduler_tpu.utils.audit import AuditLog, AuditReader

    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "1.0")
    nodes, groups, snap = _snapshot()
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    log = AuditLog(str(tmp_path))
    aid = audit_mod.new_audit_id()
    log.record_batch(
        batch_args=snap.device_args(), progress_args=snap.progress_args(),
        result=host, plan_digest=audit_mod.plan_digest(host),
        node_names=snap.node_names, group_names=snap.group_names,
        audit_id=aid,
    )
    sampler = CapacitySampler(label="t")
    progress = snap.progress_args()
    live = sampler.note_batch(
        snap.device_args(), host, group_names=snap.group_names,
        scheduled=progress[1], matched=progress[2],
        audit_log=log, audit_id=aid,
    )
    assert log.flush()
    log.stop()
    recorded = None
    batch = None
    for rec in AuditReader(str(tmp_path)).records():
        if rec.get("kind") == "event" and rec["event"] == "capacity_sample":
            recorded = rec["summary"]
        elif rec.get("kind") == "batch":
            batch = rec
    assert recorded is not None and batch is not None
    replayed = capacity_summary(
        batch["batch_args"], batch["result_arrays"],
        group_names=batch["names"]["groups"],
        scheduled=batch["progress_args"][1],
        matched=batch["progress_args"][2],
    )
    canon = json.loads(json.dumps(replayed, sort_keys=True))
    assert canon == recorded
    assert json.loads(json.dumps(live, sort_keys=True)) == recorded


def test_debug_capacity_endpoint(monkeypatch):
    import urllib.request

    from batch_scheduler_tpu.utils.metrics import serve_metrics

    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "1.0")
    nodes, groups, snap = _snapshot()
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    sampler = CapacitySampler(label="endpoint")
    sampler.note_batch(
        snap.device_args(), host, group_names=snap.group_names,
        lane_names=list(snap.schema.names),
    )
    set_active_sampler(sampler)
    srv = serve_metrics(port=0)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/capacity", timeout=10
        ) as r:
            assert r.status == 200
            payload = json.loads(r.read().decode())
        assert payload["samples"] >= 1
        assert payload["last"]["lanes"][0]["name"]  # annotated view
        assert payload["series"]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/capacity?points=1", timeout=10
        ) as r:
            trimmed = json.loads(r.read().decode())
        assert len(trimmed["series"]) == 1
        # malformed points answers 400, never a crash
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/capacity?points=junk",
                timeout=10,
            )
        assert exc.value.code == 400
    finally:
        srv.shutdown()
    # no sampler registered: self-describing 200 (the /debug/ index probe)
    set_active_sampler(None)
    payload, status = capacity_debug_view()
    assert status == 200 and payload["sampler"] is None


def test_scorer_publish_feeds_sampler(monkeypatch):
    """OracleScorer._publish runs the hook: a refresh on a live scorer
    lands a sample in the active sampler and stamps the scan counter
    with the dominant tenant."""
    from batch_scheduler_tpu.core.oracle_scorer import OracleScorer
    from batch_scheduler_tpu.ops.capacity import active_sampler
    from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY

    monkeypatch.setenv("BST_CAPACITY", "1")
    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "1.0")

    class _Cluster:
        def version(self):
            return 1

        def nodes(self):
            return [
                make_sim_node(f"s{i}", {"cpu": "8", "pods": "110"})
                for i in range(4)
            ]

        def node_requested(self, name):
            return {}

    class _Cache:
        def get(self, name):
            return None

    from batch_scheduler_tpu.core import oracle_scorer as osc

    def fake_read(cluster, cache):
        nodes = cluster.nodes()
        demands = [
            GroupDemand("acme/g0", 2, member_request={"cpu": 1000},
                        creation_ts=0.0)
        ]
        return nodes, {}, demands

    monkeypatch.setattr(osc, "read_cluster_inputs", fake_read)
    before = DEFAULT_REGISTRY.counter("bst_scan_batches_total").values()
    scorer = OracleScorer()
    assert active_sampler() is scorer._capacity
    scorer.refresh(_Cluster(), _Cache())
    assert scorer._capacity.samples == 1
    last = scorer._capacity.last()
    assert last["placed"]["gangs"] == 1
    after = DEFAULT_REGISTRY.counter("bst_scan_batches_total").values()
    tenant_keys = [
        dict(k).get("tenant") for k in set(after) - set(before)
    ] + [
        dict(k).get("tenant")
        for k in after
        if k in before and after[k] != before[k]
    ]
    assert "acme" in tenant_keys


# ---------------------------------------------------------------------------
# burn-rate model (utils.health)
# ---------------------------------------------------------------------------


def test_burn_rate_breach_and_recovery(monkeypatch):
    from batch_scheduler_tpu.utils.health import HealthModel
    from batch_scheduler_tpu.utils.metrics import LONG_OP_BUCKETS, Registry

    monkeypatch.setenv("BST_SLO_BATCH_P95_S", "0.01")
    monkeypatch.setenv("BST_SLO_WINDOW_S", "1")
    monkeypatch.setenv("BST_SLO_BURN_WINDOW_S", "120")
    reg = Registry()
    model = HealthModel(registry=reg)
    hist = reg.histogram(
        "bst_oracle_batch_seconds", "t", buckets=LONG_OP_BUCKETS
    )
    baseline = model.evaluate()
    assert baseline["signals"]["burn:batch"]["verdict"] == "ok"
    for _ in range(10):
        hist.observe(0.5)  # every observation violates the 10ms target
    storm = model.evaluate()
    sig = storm["signals"]["burn:batch"]
    assert sig["verdict"] == "breach"
    assert sig["burn_fast"] >= sig["fast_threshold"]
    assert "NOW" in sig["reason"]
    assert (
        reg.gauge("bst_slo_burn_rate").value(signal="batch", window="fast")
        == sig["burn_fast"]
    )
    assert reg.counter("bst_slo_breach_total").value(
        signal="burn:batch"
    ) == 1
    # recovery: the fast window slides past the storm; the slow window
    # still shows the spend — warn ("earlier"), never breach
    time.sleep(1.2)
    model.evaluate()  # records the boundary snapshot
    time.sleep(1.2)
    recovered = model.evaluate()
    sig = recovered["signals"]["burn:batch"]
    assert sig["verdict"] == "warn"
    assert "EARLIER" in sig["reason"]
    assert sig["burn_slow"] >= sig["slow_threshold"]


def test_burn_capacity_signal(monkeypatch):
    """A capacity sample with unplaceable pending demand burns the
    capacity budget; placeable samples do not."""
    from batch_scheduler_tpu.utils.health import HealthModel
    from batch_scheduler_tpu.utils.metrics import Registry

    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "1.0")
    monkeypatch.setenv("BST_SLO_WINDOW_S", "60")
    nodes, groups, snap = _snapshot(nodes_n=2, gangs=1, members=1)
    giant = GroupDemand("big/giant", 512, member_request={"cpu": 4000},
                       creation_ts=9.0)
    snap_bad = ClusterSnapshot(nodes, {}, groups + [giant])
    host_bad, _ = execute_batch_host(
        snap_bad.device_args(), snap_bad.progress_args()
    )
    sampler = CapacitySampler(label="burn")
    for _ in range(4):
        sampler.note_batch(
            snap_bad.device_args(), host_bad,
            group_names=snap_bad.group_names,
        )
    set_active_sampler(sampler)
    model = HealthModel(registry=Registry())
    verdictd = model.evaluate()
    sig = verdictd["signals"]["burn:capacity"]
    assert sig["verdict"] == "breach"
    assert sig["burn_fast"] >= sig["fast_threshold"]


def test_sidecar_capacity_rides_trace_info(monkeypatch):
    """A TRACED wire batch carries a compact sidecar capacity summary in
    the TRACE_INFO telemetry; an untraced batch never pays the sampler
    (no capacity key, no sample)."""
    from batch_scheduler_tpu.service import (
        OracleClient,
        protocol as proto,
        serve_background,
    )
    from batch_scheduler_tpu.service import server as server_mod
    from batch_scheduler_tpu.utils import trace as trace_mod

    monkeypatch.setenv("BST_CAPACITY", "1")
    monkeypatch.setenv("BST_CAPACITY_BUDGET_FRAC", "1.0")
    monkeypatch.setattr(server_mod, "_server_capacity", None)

    def _request(n=4, g=2, r=5, members=3):
        alloc = np.zeros((n, r), np.int32)
        alloc[:, 0] = 8000
        alloc[:, 3] = 20
        requested = np.zeros((n, r), np.int32)
        group_req = np.zeros((g, r), np.int32)
        group_req[:, 0] = 1000
        group_req[:, 3] = 1
        return proto.ScheduleRequest(
            alloc=alloc, requested=requested, group_req=group_req,
            remaining=np.full(g, members, np.int32),
            fit_mask=np.ones((1, n), bool),
            group_valid=np.ones(g, bool),
            order=np.arange(g, dtype=np.int32),
            min_member=np.full(g, members, np.int32),
            scheduled=np.zeros(g, np.int32),
            matched=np.zeros(g, np.int32),
            ineligible=np.zeros(g, bool),
            creation_rank=np.arange(g, dtype=np.int32),
        )

    srv = serve_background()
    # single-device sidecar shape: the conftest's 8-device virtual mesh
    # would route batches through shard placement, and the sidecar
    # sampler (correctly) skips mesh batches — this test exercises the
    # single-device deployment the TRACE_INFO summary is defined for
    srv.scan_mesh = None
    srv.executor.scan_mesh = None
    try:
        host, port = srv.address
        # untraced: the sampler must not run at all
        trace_mod.configure(enabled=False)
        plain = OracleClient(host, port)
        plain.schedule(_request())
        assert server_mod._server_capacity is None
        plain.close()

        trace_mod.configure(enabled=True)
        client = OracleClient(host, port)
        with trace_mod.start_trace("schedule_cycle"):
            resp = client.schedule(_request())
            assert resp.placed.all()
        tele = client.last_telemetry
        assert tele is not None and "capacity" in tele, tele
        cap = tele["capacity"]
        assert 0.0 <= cap["fragmentation_index"] <= 1.0
        assert cap["utilization"], cap
        assert cap["pending_unplaceable_gangs"] == 0
        client.close()
    finally:
        trace_mod.configure(enabled=False)
        srv.shutdown()
