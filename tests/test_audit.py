"""Black-box flight data: audit ring, deterministic replay, divergence
blame, identity audit, AUDIT_ID wire correlation, and the /debug/health
and /debug/buckets surfaces (docs/observability.md)."""

from __future__ import annotations

import glob
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from batch_scheduler_tpu.core.oracle_scorer import (
    OracleScorer,
    replay_audit_record,
    replay_batch,
)
from batch_scheduler_tpu.ops.oracle import execute_batch_host
from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot, GroupDemand
from batch_scheduler_tpu.sim.scenarios import make_sim_node
from batch_scheduler_tpu.utils import audit as audit_mod
from batch_scheduler_tpu.utils.audit import AuditLog, AuditReader
from batch_scheduler_tpu.utils.health import (
    DEFAULT_HEALTH,
    HealthModel,
    IdentityAuditor,
)
from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY, serve_metrics


def _make_snapshot(n=5, g=4, cpu_per_member=1000):
    nodes = [
        make_sim_node(f"n{i}", {"cpu": "8", "memory": "32Gi", "pods": "64"})
        for i in range(n)
    ]
    groups = [
        GroupDemand(
            f"default/g{i}", 3,
            member_request={"cpu": cpu_per_member},
            creation_ts=float(i),
        )
        for i in range(g)
    ]
    return ClusterSnapshot(nodes, {}, groups)


def _executed(snap):
    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    return host


def _record(log, snap, host, **kw):
    return log.record_batch(
        batch_args=snap.device_args(),
        progress_args=snap.progress_args(),
        result=host,
        plan_digest=audit_mod.plan_digest(host),
        node_names=snap.node_names,
        group_names=snap.group_names,
        **kw,
    )


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------


def test_roundtrip_bit_identical_through_deltas(tmp_path):
    """Keyframe + row-delta records reconstruct to exactly the recorded
    arrays, across churn that rewrites some rows between records."""
    log = AuditLog(str(tmp_path), keyframe_every=4)
    snaps, hosts = [], []
    requested = {}
    for i in range(6):
        # churn one node's requested row per record
        requested[f"n{i % 5}"] = {"cpu": 1000 * (i + 1), "pods": i + 1}
        nodes = [
            make_sim_node(f"n{j}", {"cpu": "8", "memory": "32Gi", "pods": "64"})
            for j in range(5)
        ]
        groups = [
            GroupDemand(f"default/g{j}", 3, member_request={"cpu": 1000},
                        creation_ts=float(j))
            for j in range(4)
        ]
        snap = ClusterSnapshot(nodes, dict(requested), groups)
        host = _executed(snap)
        _record(log, snap, host)
        snaps.append(snap)
        hosts.append(host)
    assert log.flush()
    batches, skipped = AuditReader(str(tmp_path)).batches()
    assert len(batches) == 6 and not skipped
    # both keyframe and delta records exist
    kinds = [rec["keyframe"] for rec in batches]
    assert True in kinds and False in kinds
    for rec, snap, host in zip(batches, snaps, hosts):
        for got, want in zip(rec["batch_args"], snap.device_args()):
            assert np.array_equal(np.asarray(got), np.asarray(want))
        for got, want in zip(rec["progress_args"], snap.progress_args()):
            assert np.array_equal(np.asarray(got), np.asarray(want))
        assert rec["plan_digest"] == audit_mod.plan_digest(host)
    log.stop()


def test_ring_rotation_respects_size_cap(tmp_path):
    """Oldest segments are deleted once the ring exceeds cap_bytes, and
    the survivors still read back."""
    snap = _make_snapshot()
    host = _executed(snap)
    # tiny segments + cap: every record is a keyframe (keyframe_every=1)
    # so any surviving segment is fully reconstructable
    log = AuditLog(str(tmp_path), cap_bytes=40_000, segment_bytes=8_000,
                   keyframe_every=1)
    for _ in range(40):
        _record(log, snap, host)
    assert log.flush()
    segments = glob.glob(os.path.join(str(tmp_path), "audit-*.jsonl"))
    total = sum(os.path.getsize(p) for p in segments)
    # the cap bounds all CLOSED segments; the live segment may overhang
    # by at most one segment's worth
    assert total <= 40_000 + 8_000 + 4096
    batches, skipped = AuditReader(str(tmp_path)).batches()
    assert batches, "rotation must leave readable records"
    assert not skipped  # keyframe-only ring: nothing depends on lost heads
    rep = replay_audit_record(batches[-1], against="steady")
    assert rep["identical"]
    log.stop()


def test_keyframe_recovery_after_truncation(tmp_path):
    """Deltas whose keyframe was rotated away are reported as
    unreconstructable (never a crash) and reconstruction resumes at the
    next keyframe — bit-exactly."""
    snap = _make_snapshot()
    host = _executed(snap)
    log = AuditLog(str(tmp_path), keyframe_every=3, segment_bytes=10**9)
    for _ in range(7):  # keyframes at seq 1 and 4 and 7
        _record(log, snap, host)
    assert log.flush()
    log.stop()
    # simulate ring truncation mid-chain: drop the single segment and
    # re-write it without the first 2 records (keyframe 1 + one delta) —
    # the file now STARTS with a dangling delta record
    (segment,) = glob.glob(os.path.join(str(tmp_path), "audit-*.jsonl"))
    with open(segment) as f:
        lines = f.readlines()
    with open(segment, "w") as f:
        f.writelines(lines[2:])
    batches, skipped = AuditReader(str(tmp_path)).batches()
    assert len(skipped) == 1  # the dangling delta at seq 3
    assert "keyframe" in skipped[0]["reason"]
    assert [rec["seq"] for rec in batches] == [4, 5, 6, 7]
    for rec in batches:
        for got, want in zip(rec["batch_args"], snap.device_args()):
            assert np.array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# audit format v2: event-stream records + reader-side re-fold
# ---------------------------------------------------------------------------


def _fold_ring(tmp_path, steps=6, keyframe_every=4, **log_kw):
    """A v2 ring recorded from a real fold sequence: one full pack, then
    ``steps`` pack_fold refreshes (node churn + group tail churn, one
    priority bump to exercise the queue-order resort on re-fold). Returns
    (log, snaps, hosts) with the log NOT yet stopped."""
    from batch_scheduler_tpu.ops.snapshot import DeltaSnapshotPacker, _demand_fp

    nodes = [
        make_sim_node(f"n{j}", {"cpu": "8", "memory": "32Gi", "pods": "64"})
        for j in range(5)
    ]
    groups = [
        GroupDemand(f"default/g{j}", 3, member_request={"cpu": 1000},
                    creation_ts=float(j))
        for j in range(4)
    ]
    node_req = {n.metadata.name: {} for n in nodes}
    packer = DeltaSnapshotPacker()
    log = AuditLog(str(tmp_path), fmt="v2", keyframe_every=keyframe_every,
                   **log_kw)

    def record(snap, ev):
        host = _executed(snap)
        lite_fps = getattr(snap, "lite_fps", None)
        _record(
            log, snap, host, event_fold=ev,
            refold=(snap.schema, lite_fps) if lite_fps is not None else None,
        )
        return host

    snaps, hosts = [], []
    snap = packer.pack(nodes, node_req, groups)
    hosts.append(record(snap, None))
    snaps.append(snap)
    for i in range(steps):
        nm = f"n{i % 5}"
        node_req[nm] = {"cpu": 1000 * (i + 1), "pods": i + 1}
        gi = i % 4
        g = groups[gi]
        g.scheduled = min(i, 2)
        if i == 3:
            g.priority = 5  # sort-key churn: the re-fold must resort too
        fsnap = packer.pack_fold([(nm, dict(node_req[nm]))], [g])
        assert fsnap is not None, f"fold step {i} unexpectedly bailed"
        ev = {"bumps": i + 1, "nodes": [(nm, dict(node_req[nm]))],
              "groups": [(g.full_name, _demand_fp(g))]}
        hosts.append(record(fsnap, ev))
        snaps.append(fsnap)
    return log, snaps, hosts


def test_v2_event_records_refold_bit_identical(tmp_path):
    """A churny fold sequence recorded in v2 reconstructs event_batch
    records by RE-FOLDING the recorded event stream — bit-identical
    inputs (input_digest checked per step) and bit-identical replay on
    the steady and cpu-ladder rungs."""
    log, snaps, hosts = _fold_ring(tmp_path)
    assert log.flush()
    batches, skipped = AuditReader(str(tmp_path)).batches()
    assert len(batches) == 7 and not skipped
    kinds = [rec.get("record_kind", "array") for rec in batches]
    assert kinds.count("event_batch") >= 4, kinds
    for rec, snap, host in zip(batches, snaps, hosts):
        for got, want in zip(
            rec["batch_args"] + rec["progress_args"],
            snap.device_args() + snap.progress_args(),
        ):
            assert np.array_equal(np.asarray(got), np.asarray(want))
        assert rec["plan_digest"] == audit_mod.plan_digest(host)
        if rec.get("record_kind") == "event_batch":
            assert rec["refold"]["input_digest_ok"]
            assert rec["refold"]["first_divergent_event"] is None
            # compact result: the digest still covers assignments, the
            # record body does not carry them
            assert "assignment_nodes" not in rec["result_arrays"]
    for rec in batches:
        for rung in ("steady", "cpu-ladder"):
            rep = replay_audit_record(rec, against=rung)
            assert rep["identical"], (rung, rep)
            if rec.get("record_kind") == "event_batch":
                assert rep.get("refolded")
    log.stop()


def test_v2_tampered_event_batch_blames_event(tmp_path):
    """A tampered event batch yields structured blame NAMING THE EVENT:
    the re-folded input digest diverges at the tampered record, replay
    diverges, and blame reports field=<event-stream> with the first
    divergent event's seq — on the tampered record and every later
    record of the same chain."""
    log, _snaps, _hosts = _fold_ring(tmp_path, keyframe_every=100)
    assert log.flush()
    log.stop()
    (segment,) = glob.glob(os.path.join(str(tmp_path), "audit-*.jsonl"))
    with open(segment) as f:
        lines = [json.loads(ln) for ln in f.read().splitlines() if ln]
    tampered_seq = None
    for rec in lines:
        if rec.get("kind") == "event_batch" and rec["events"]["groups"]:
            rec["events"]["groups"][0][1][1] -= 1  # min_member 3 -> 2
            tampered_seq = rec["seq"]
            break
    assert tampered_seq is not None
    with open(segment, "w") as f:
        f.writelines(json.dumps(rec, sort_keys=True) + "\n" for rec in lines)
    batches, skipped = AuditReader(str(tmp_path)).batches()
    assert not skipped  # tampering is a divergence, not a crash
    divergent = [
        rec for rec in batches
        if (rec.get("refold") or {}).get("first_divergent_event")
    ]
    assert divergent and divergent[0]["seq"] == tampered_seq
    for rec in divergent:
        assert rec["refold"]["first_divergent_event"]["seq"] == tampered_seq
    rep = replay_audit_record(divergent[0], against="steady")
    assert rep["identical"] is False
    blame = rep["blame"]
    assert blame["field"] == "<event-stream>"
    assert blame["first_divergent_event"]["seq"] == tampered_seq
    assert blame["fold"]["outcome"] == "input-divergence"


def test_v2_rotated_keyframe_reports_unreconstructable(tmp_path):
    """An event_batch record whose base keyframe rotated away reports
    unreconstructable with the fold-outcome reason — never a crash — and
    re-folding resumes bit-exactly at the next keyframe (the PR 5
    recovery discipline, v2 edition of the truncation case above)."""
    log, snaps, _hosts = _fold_ring(tmp_path, keyframe_every=3,
                                    segment_bytes=10**9)
    assert log.flush()
    log.stop()
    # seqs: 1=K, 2=E, 3=E, 4=K, 5=E, 6=E, 7=K; drop the keyframe and the
    # first event — the ring now STARTS with a dangling event record
    (segment,) = glob.glob(os.path.join(str(tmp_path), "audit-*.jsonl"))
    with open(segment) as f:
        lines = f.readlines()
    with open(segment, "w") as f:
        f.writelines(lines[2:])
    batches, skipped = AuditReader(str(tmp_path)).batches()
    assert len(skipped) == 1
    assert skipped[0]["seq"] == 3
    assert skipped[0]["fold_outcome"] == "no-base"
    assert "keyframe" in skipped[0]["reason"]
    assert [rec["seq"] for rec in batches] == [4, 5, 6, 7]
    for rec, snap in zip(batches, snaps[3:]):
        for got, want in zip(rec["batch_args"], snap.device_args()):
            assert np.array_equal(np.asarray(got), np.asarray(want))
        if rec.get("record_kind") == "event_batch":
            assert rec["refold"]["input_digest_ok"]


def test_v2_knobs_parse_guarded(monkeypatch, capsys):
    """BST_AUDIT_FORMAT / BST_AUDIT_KEYFRAME_EVERY are parse-guarded: a
    typo degrades to the default with a warn-once, never a crash."""
    monkeypatch.setattr(audit_mod, "_format_warned", [False])
    monkeypatch.setattr(audit_mod, "_keyframe_warned", [False])
    monkeypatch.setenv("BST_AUDIT_FORMAT", "v3-nope")
    monkeypatch.setenv("BST_AUDIT_KEYFRAME_EVERY", "sixteen")
    assert audit_mod.audit_format() == "array"
    assert audit_mod.audit_format() == "array"  # warns once, not twice
    assert audit_mod.audit_keyframe_every() == 16
    err = capsys.readouterr().err
    assert err.count("BST_AUDIT_FORMAT") == 1
    assert "BST_AUDIT_KEYFRAME_EVERY" in err
    monkeypatch.setenv("BST_AUDIT_FORMAT", "v2")
    monkeypatch.setenv("BST_AUDIT_KEYFRAME_EVERY", "7")
    assert audit_mod.audit_format() == "v2"
    assert audit_mod.audit_keyframe_every() == 7
    monkeypatch.setenv("BST_AUDIT_KEYFRAME_EVERY", "0")
    assert audit_mod.audit_keyframe_every() == 1  # clamped, not rejected
    monkeypatch.delenv("BST_AUDIT_FORMAT")
    monkeypatch.delenv("BST_AUDIT_KEYFRAME_EVERY")
    assert audit_mod.audit_format() == "array"
    assert audit_mod.audit_keyframe_every() == 16


def test_v2_ring_telemetry(tmp_path):
    """bst_audit_ring_bytes / bst_audit_records_total{kind} plus the
    bytes-per-record compression readout in /debug/perf."""
    log, _snaps, _hosts = _fold_ring(tmp_path)
    assert log.flush()
    segments = glob.glob(os.path.join(str(tmp_path), "audit-*.jsonl"))
    assert log.ring_bytes == sum(os.path.getsize(p) for p in segments) > 0
    gauge = DEFAULT_REGISTRY.get("bst_audit_ring_bytes")
    assert gauge is not None
    assert gauge.value(ring=str(tmp_path)) == float(log.ring_bytes)
    counter = DEFAULT_REGISTRY.get("bst_audit_records_total")
    kinds = {dict(k).get("kind") for k in counter.values()}
    assert "event_batch" in kinds and "batch" in kinds
    rings = audit_mod.ring_stats()
    mine = [r for r in rings if r["dir"] == str(tmp_path)]
    assert mine and mine[0]["format"] == "v2"
    by_kind = mine[0]["by_kind"]
    assert by_kind["event_batch"]["records"] >= 4
    # the compression claim, observable: event records are denser than
    # array keyframes even at this toy shape
    assert (by_kind["event_batch"]["bytes_per_record"]
            < by_kind["batch"]["bytes_per_record"])
    from batch_scheduler_tpu.utils.profiler import perf_report

    report = perf_report()
    assert any(r["dir"] == str(tmp_path) for r in report["audit"])
    log.stop()


def test_writer_failure_forces_keyframe(tmp_path):
    """A failed segment append drops the delta chain: the failed record
    never reached disk, so the next record must be a keyframe — diffing
    against the phantom record would make the reader reconstruct WRONG
    inputs for every row that churned in the lost record only."""
    def churned_snap(i):
        nodes = [
            make_sim_node(f"n{j}", {"cpu": "8", "memory": "32Gi", "pods": "64"})
            for j in range(5)
        ]
        groups = [
            GroupDemand(f"default/g{j}", 3, member_request={"cpu": 1000},
                        creation_ts=float(j))
            for j in range(4)
        ]
        return ClusterSnapshot(nodes, {"n0": {"cpu": 1000 * (i + 1)}}, groups)

    log = AuditLog(str(tmp_path), keyframe_every=100)
    s1 = churned_snap(0)
    _record(log, s1, _executed(s1))
    assert log.flush()
    # the second record's append fails (disk full); flush serializes the
    # monkeypatching against the async writer
    orig_append = log._append

    def failing_append(line):
        raise OSError("disk full")

    log._append = failing_append
    s2 = churned_snap(1)
    _record(log, s2, _executed(s2))
    assert log.flush()
    log._append = orig_append
    s3 = churned_snap(2)
    host3 = _executed(s3)
    _record(log, s3, host3)
    assert log.flush()
    assert log.write_errors == 1
    batches, skipped = AuditReader(str(tmp_path)).batches()
    assert [rec["seq"] for rec in batches] == [1, 3] and not skipped
    assert batches[1]["keyframe"], "post-failure record must be a keyframe"
    for got, want in zip(batches[1]["batch_args"], s3.device_args()):
        assert np.array_equal(np.asarray(got), np.asarray(want))
    assert replay_audit_record(batches[1], against="steady")["identical"]
    log.stop()


def test_seq_resumes_across_processes(tmp_path):
    """A restarted process appending to an existing ring continues the
    seq numbering — `replay --batch K` selects by seq, so duplicates
    would make it ambiguous."""
    snap = _make_snapshot()
    host = _executed(snap)
    log = AuditLog(str(tmp_path))
    _record(log, snap, host)
    _record(log, snap, host)
    assert log.flush()
    log.stop()
    log2 = AuditLog(str(tmp_path))  # "restart"
    _record(log2, snap, host)
    assert log2.flush()
    log2.stop()
    batches, _ = AuditReader(str(tmp_path)).batches()
    assert [rec["seq"] for rec in batches] == [1, 2, 3]


def test_queue_overflow_drops_never_blocks(tmp_path):
    snap = _make_snapshot()
    host = _executed(snap)
    log = AuditLog(str(tmp_path), queue_max=2)
    # stall the writer behind a slow sync item so the queue fills
    import threading

    gate = threading.Event()
    log._q.put({"kind": "_sync", "_event": gate})  # writer parks after it
    t0 = time.monotonic()
    for _ in range(20):
        _record(log, snap, host)
    assert time.monotonic() - t0 < 1.0, "hot path must never block"
    assert log.records_dropped > 0
    log.stop()


# ---------------------------------------------------------------------------
# replay + divergence blame
# ---------------------------------------------------------------------------


def test_replay_bit_identical_same_backend_and_across_rungs(tmp_path):
    log = AuditLog(str(tmp_path))
    snap = _make_snapshot()
    host = _executed(snap)
    _record(log, snap, host)
    assert log.flush()
    (rec,), _ = AuditReader(str(tmp_path)).batches()
    for rung in ("steady", "wavefront", "cpu-ladder"):
        rep = replay_audit_record(rec, against=rung)
        assert rep["identical"], (rung, rep)
        assert rep["replayed_digest"] == rec["plan_digest"]
    log.stop()


def test_replay_divergence_report_is_structured_not_a_crash(tmp_path):
    """A tampered record produces a populated blame report: field, first
    differing gang by NAME, config fingerprints on both sides, rung."""
    log = AuditLog(str(tmp_path))
    snap = _make_snapshot()
    host = _executed(snap)
    _record(log, snap, host)
    assert log.flush()
    (rec,), _ = AuditReader(str(tmp_path)).batches()
    rec["result_arrays"]["placed"] = 1 - rec["result_arrays"]["placed"]
    rec["plan_digest"] = "0" * 64
    rep = replay_audit_record(rec, against="cpu-ladder")
    assert not rep["identical"]
    blame = rep["blame"]
    assert blame["field"] == "placed"
    assert blame["gang"] == "default/g0"
    assert blame["recorded"] != blame["replayed"]
    assert blame["replay_config"]["backend"] == "cpu"
    assert "fallback_rung" in blame and "bucket" in blame
    log.stop()


def test_replay_input_divergence_blames_assignment(tmp_path):
    """Tampering the INPUTS (not the result) makes the replayed plan
    genuinely diverge — the report must localize the first differing
    field/gang rather than crash."""
    log = AuditLog(str(tmp_path))
    snap = _make_snapshot()
    host = _executed(snap)
    _record(log, snap, host)
    assert log.flush()
    (rec,), _ = AuditReader(str(tmp_path)).batches()
    alloc = rec["batch_args"][0].copy()
    alloc[: len(snap.node_names)] //= 4  # shrink every real node
    rec["batch_args"] = (alloc,) + tuple(rec["batch_args"][1:])
    rep = replay_audit_record(rec, against="steady")
    assert not rep["identical"]
    assert rep["blame"]["field"] in audit_mod.PLAN_FIELDS
    assert rep["blame"]["differing_elements"] > 0
    log.stop()


def test_replay_skips_degraded_records(tmp_path):
    """A conservative-fallback batch has no device plan: replaying the
    real oracle against it would be a guaranteed false divergence, so the
    replay reports it skipped instead (same rule as the identity audit)."""
    from batch_scheduler_tpu.core.oracle_scorer import conservative_cpu_batch

    log = AuditLog(str(tmp_path))
    snap = _make_snapshot()
    host, _ = conservative_cpu_batch(snap)
    _record(log, snap, host, degraded=True)
    assert log.flush()
    (rec,), _ = AuditReader(str(tmp_path)).batches()
    assert rec["degraded"]
    rep = replay_audit_record(rec, against="steady")
    assert rep["identical"] is None and "degraded" in rep["skipped"]
    log.stop()


def test_replay_reports_executed_rung(tmp_path):
    """The report always carries the rung that actually EXECUTED, so a
    pinned rung silently falling down the dispatch ladder is visible."""
    log = AuditLog(str(tmp_path))
    snap = _make_snapshot()
    _record(log, snap, _executed(snap))
    assert log.flush()
    (rec,), _ = AuditReader(str(tmp_path)).batches()
    rep = replay_audit_record(rec, against="wavefront")
    assert rep["identical"]
    assert rep["executed_rung"]["wave_width"] > 1
    assert "rung_fell_back" not in rep
    log.stop()


def test_replay_rung_pin_is_thread_local():
    """A pinned replay never flips the process-wide scan gates."""
    from batch_scheduler_tpu.ops import oracle as okern

    snap = _make_snapshot()
    before = dict(okern._pallas_enabled), okern._wave_enabled[0]
    replay_batch(snap.device_args(), snap.progress_args(),
                 against="wavefront")
    assert (dict(okern._pallas_enabled), okern._wave_enabled[0]) == before
    assert getattr(okern._rung_override, "value", None) is None


def test_replay_unknown_rung():
    snap = _make_snapshot()
    with pytest.raises(ValueError, match="unknown replay rung"):
        replay_batch(snap.device_args(), snap.progress_args(),
                     against="gpu-ladder")


# ---------------------------------------------------------------------------
# scorer integration + identity audit
# ---------------------------------------------------------------------------


class _FakeCluster:
    def __init__(self, nodes):
        self._nodes = nodes
        self._version = 0

    def version(self):
        return self._version

    def list_nodes(self):
        return self._nodes

    def node_requested(self, name):
        return {}


class _FakeStatusCache:
    def snapshot(self):
        return {}


def test_scorer_publish_records_audit(tmp_path):
    log = AuditLog(str(tmp_path))
    scorer = OracleScorer(audit_log=log)
    nodes = [
        make_sim_node(f"n{i}", {"cpu": "8", "memory": "32Gi", "pods": "64"})
        for i in range(4)
    ]
    scorer.refresh(_FakeCluster(nodes), _FakeStatusCache())
    assert log.flush()
    batches, _ = AuditReader(str(tmp_path)).batches()
    assert len(batches) == 1
    assert not batches[0]["speculative"] and not batches[0]["degraded"]
    rep = replay_audit_record(batches[0], against="steady")
    assert rep["identical"]
    log.stop()


def test_identity_audit_ok_and_mismatch(tmp_path):
    log = AuditLog(str(tmp_path))
    health = DEFAULT_HEALTH
    health.reset()
    snap = _make_snapshot()
    host = _executed(snap)
    digest = audit_mod.plan_digest(host)
    auditor = IdentityAuditor(every=1)
    # ok path: the served digest matches its CPU-rung replay
    auditor.note_batch(snap.device_args(), snap.progress_args(), digest,
                       "a" * 16, log)
    assert auditor.drain(60.0)
    assert auditor.audits == 1 and auditor.mismatches == 0
    assert health.evaluate()["signals"]["identity"]["verdict"] == "ok"
    # mismatch path: a wrong served digest breaches health, increments the
    # counter, and flags the audit ring
    breaches_before = DEFAULT_REGISTRY.counter(
        "bst_slo_breach_total"
    ).value(signal="identity")
    auditor.note_batch(snap.device_args(), snap.progress_args(), "f" * 64,
                       "b" * 16, log)
    assert auditor.drain(60.0)
    assert auditor.mismatches == 1
    verdicts = health.evaluate()
    assert verdicts["signals"]["identity"]["verdict"] == "breach"
    assert verdicts["verdict"] == "breach"
    assert DEFAULT_REGISTRY.counter("bst_slo_breach_total").value(
        signal="identity"
    ) == breaches_before + 1
    assert log.flush()
    events = [
        r for r in AuditReader(str(tmp_path)).records()
        if r.get("kind") == "event"
    ]
    assert events and events[0]["event"] == "identity_mismatch"
    assert events[0]["audit_id"] == "b" * 16
    health.reset()
    log.stop()


# ---------------------------------------------------------------------------
# health model
# ---------------------------------------------------------------------------


def test_health_breach_on_injected_latency(monkeypatch):
    health = HealthModel()
    hist = DEFAULT_REGISTRY.histogram("bst_oracle_batch_seconds")
    health.reset()  # baseline: prior observations out of the window
    assert health.evaluate()["signals"]["batch"]["verdict"] == "ok"
    monkeypatch.setenv("BST_SLO_BATCH_P95_S", "0.2")
    for _ in range(5):
        hist.observe(0.9)
    verdicts = health.evaluate()
    assert verdicts["signals"]["batch"]["verdict"] == "breach"
    assert verdicts["verdict"] == "breach"
    # warn band: p95 in (0.8*target, target]. The histogram interpolates
    # within its covering bucket, so 2.4s observations report p95 ~= 2.43
    # (the 1.0..2.5 bucket) — inside (2.08, 2.6] for a 2.6s target.
    health.reset()
    monkeypatch.setenv("BST_SLO_BATCH_P95_S", "2.6")
    for _ in range(5):
        hist.observe(2.4)
    assert health.evaluate()["signals"]["batch"]["verdict"] == "warn"


def test_health_no_traffic_is_ok():
    health = HealthModel()
    health.reset()
    out = health.evaluate()
    assert out["signals"]["pack"]["observations"] == 0
    assert out["signals"]["pack"]["verdict"] == "ok"


def test_health_first_touch_keeps_long_op_buckets():
    """Health evaluating BEFORE the first batch must not create the
    batch/device histograms with the default 10s-ceiling buckets — the
    registry ignores ``buckets`` for an existing metric, and a 10s
    ceiling would clamp cold-compile p95 below the 45s breach target
    forever."""
    from batch_scheduler_tpu.utils.metrics import LONG_OP_BUCKETS, Registry

    reg = Registry()
    model = HealthModel(registry=reg)
    model.reset()  # health touches the histograms first
    model.evaluate()
    for metric in ("bst_oracle_batch_seconds", "bst_oracle_device_seconds"):
        hist = reg.histogram(metric, buckets=LONG_OP_BUCKETS)
        assert hist.buckets == tuple(sorted(LONG_OP_BUCKETS)), metric


def test_health_folds_degraded_gauge():
    health = HealthModel()
    gauge = DEFAULT_REGISTRY.gauge("bst_oracle_degraded")
    gauge.set(1)
    try:
        out = health.evaluate()
        assert out["signals"]["degraded"]["verdict"] == "breach"
        assert out["verdict"] == "breach"
    finally:
        gauge.set(0)


# ---------------------------------------------------------------------------
# wire correlation + endpoints
# ---------------------------------------------------------------------------


def test_protocol_audit_id_roundtrip():
    from batch_scheduler_tpu.service import protocol as proto

    aid = audit_mod.new_audit_id()
    assert proto.unpack_audit_id(proto.pack_audit_id(aid)) == aid
    with pytest.raises(ValueError):
        proto.pack_audit_id("short")


def test_wire_audit_correlation(tmp_path):
    """A RemoteScorer with an audit log mints one AUDIT_ID per batch; the
    sidecar's own record carries the same ID (the cross-process evidence
    chain)."""
    from batch_scheduler_tpu.service.client import RemoteScorer, ResilientOracleClient
    from batch_scheduler_tpu.service.server import serve_background

    client_dir = tmp_path / "client"
    server_dir = tmp_path / "server"
    server_log = AuditLog(str(server_dir))
    srv = serve_background(audit_log=server_log)
    client = ResilientOracleClient(*srv.address, name="audit-test")
    scorer = RemoteScorer(client)
    client_log = AuditLog(str(client_dir))
    scorer.configure_audit(client_log)
    try:
        nodes = [
            make_sim_node(f"n{i}", {"cpu": "8", "memory": "32Gi", "pods": "64"})
            for i in range(4)
        ]
        scorer.refresh(_FakeCluster(nodes), _FakeStatusCache())
        assert client_log.flush() and server_log.flush()
        client_recs, _ = AuditReader(str(client_dir)).batches()
        server_recs, _ = AuditReader(str(server_dir)).batches()
        assert len(client_recs) == 1 and len(server_recs) == 1
        assert client_recs[0]["audit_id"] == server_recs[0]["audit_id"]
        assert server_recs[0]["side"] == "server"
        # both sides recorded the same computation: digests agree and both
        # replay bit-identically
        assert client_recs[0]["plan_digest"] == server_recs[0]["plan_digest"]
        assert replay_audit_record(server_recs[0])["identical"]
    finally:
        scorer.close()
        srv.shutdown()
        srv.server_close()
        client_log.stop()


def test_debug_health_and_buckets_endpoints(monkeypatch):
    monkeypatch.setenv("BST_BUCKET_COST", "1")
    from batch_scheduler_tpu.ops import oracle as okern

    snap = _make_snapshot()
    # force one analysis: clear the per-process registry for this shape
    with okern._bucket_cost_lock:
        okern._bucket_costs.clear()
        okern._bucket_cost_inflight.clear()
    okern._maybe_analyze_bucket(
        snap.device_args(), snap.progress_args(),
        use_pallas=False, pack=True, top_k=16, scan_wave=0,
    )
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not okern.bucket_cost_report():
        time.sleep(0.05)
    report = okern.bucket_cost_report()
    assert report, "bucket analysis never landed"
    (entry,) = report.values()
    assert "error" not in entry, entry
    assert "collectives" in entry  # HLO text counting always available

    srv = serve_metrics(port=0)
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/health", timeout=5
        ) as r:
            assert "application/json" in r.headers.get("Content-Type", "")
            health = json.loads(r.read().decode())
        assert health["verdict"] in ("ok", "warn", "breach")
        assert set(health["signals"]) >= {
            "pack", "batch", "device", "cycle", "degraded", "breaker",
            "identity",
        }
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/buckets", timeout=5
        ) as r:
            buckets = json.loads(r.read().decode())
        assert buckets == report
    finally:
        srv.shutdown()


def test_sim_cluster_end_to_end_audit(tmp_path):
    """The full harness path: SimCluster(audit_log=...) records every
    published batch; the ring replays bit-identically; health reports."""
    from batch_scheduler_tpu.sim import (
        SimCluster,
        make_member_pods,
        make_sim_group,
        make_sim_node,
    )

    log = AuditLog(str(tmp_path))
    cluster = SimCluster(audit_log=log, identity_audit_every=1)
    try:
        cluster.add_nodes(
            [make_sim_node(f"n{i}", {"cpu": "8", "pods": "64"}) for i in range(4)]
        )
        cluster.create_group(make_sim_group("auditable", 3))
        cluster.start()
        cluster.create_pods(make_member_pods("auditable", 3, {"cpu": "1"}))
        assert cluster.wait_for_bound("auditable", 3, timeout=60.0)
    finally:
        cluster.stop()
    oracle = cluster.runtime.operation.oracle
    oracle.drain_background()
    assert log.flush()
    batches, _ = AuditReader(str(tmp_path)).batches()
    assert batches
    for rec in batches:
        assert replay_audit_record(rec, against="steady")["identical"]
    health = cluster.health()
    assert health["signals"]["identity"]["verdict"] == "ok"
    assert oracle.stats().get("identity_mismatches") == 0
    log.stop()
