"""Multi-tenant oracle coalescer (service.coalescer, docs/multitenancy.md):
per-tenant bit-identity against dedicated sidecars (span + mega lowerings,
steady and wire-delta lanes mixed), DRF admission order under a whale,
saturation BUSY + client retry, chaos (mid-merge disconnect drops only that
tenant's span), tenant wire attribution, and the BST_LOCKCHECK-armed
submit storm over the new shared queue state."""

from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pytest

from batch_scheduler_tpu.service import protocol as proto
from batch_scheduler_tpu.service.coalescer import (
    CoalesceJob,
    CoalesceSaturated,
    OracleCoalescer,
    coalesce_depth,
    coalesce_enabled,
    coalesce_mode,
    coalesce_span_max,
)
from batch_scheduler_tpu.service.server import serve_background
from batch_scheduler_tpu.utils import audit as audit_mod
from batch_scheduler_tpu.utils.errors import OracleBusyError

from helpers import FakeCluster, make_group, make_node, make_pod, status_for


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def single_device_server(coalesce=False, **kw):
    """A sidecar pinned to one device (the coalescer's deployment shape —
    the conftest mesh forces 8 virtual devices, so the test forces the
    single-device path the way test_capacity does) with a live coalescer
    when asked."""
    srv = serve_background(**kw)
    srv.scan_mesh = None
    srv.executor.scan_mesh = None
    if coalesce and srv.coalescer is None:
        from batch_scheduler_tpu.service.server import _capacity_tenant_shares

        srv.coalescer = OracleCoalescer(
            srv.executor, weights_fn=_capacity_tenant_shares
        )
    return srv


def close_server(srv):
    srv.shutdown()
    srv.server_close()


def make_request(n=32, g=8, lanes=4, seed=0, per_group_mask=False):
    r = np.random.RandomState(seed)
    remaining = r.randint(1, 5, size=g).astype(np.int32)
    if per_group_mask:
        mask = r.rand(g, n) > 0.2
        mask[:, 0] = True  # every gang keeps at least one feasible node
    else:
        mask = np.ones((1, n), dtype=bool)
    return proto.ScheduleRequest(
        alloc=r.randint(4, 64, size=(n, lanes)).astype(np.int32),
        requested=r.randint(0, 4, size=(n, lanes)).astype(np.int32),
        group_req=r.randint(1, 4, size=(g, lanes)).astype(np.int32),
        remaining=remaining,
        fit_mask=mask,
        group_valid=np.ones(g, dtype=bool),
        order=r.permutation(g).astype(np.int32),
        min_member=remaining.copy(),
        scheduled=np.zeros(g, dtype=np.int32),
        matched=r.randint(0, 2, size=g).astype(np.int32),
        ineligible=np.zeros(g, dtype=bool),
        creation_rank=r.permutation(g).astype(np.int32),
    )


def response_digest(resp):
    return audit_mod.plan_digest(
        {
            "gang_feasible": np.asarray(resp.gang_feasible),
            "placed": np.asarray(resp.placed),
            "progress": np.asarray(resp.progress),
            "best": int(resp.best),
            "best_exists": bool(resp.best_exists),
            "assignment_nodes": np.asarray(resp.assignment_nodes),
            "assignment_counts": np.asarray(resp.assignment_counts),
        }
    )


class FakeExecJob:
    def __init__(self, host, batch, delay):
        self._host, self._batch, self._delay = host, batch, delay
        self.queue_wait = 0.0
        self.run_seconds = delay

    def wait(self, timeout=None):
        time.sleep(self._delay)
        return self._host, self._batch


class FakeExecutor:
    """Duck-typed DeviceExecutor for queue-dynamics tests: fixed service
    delay, records dispatch order."""

    def __init__(self, delay=0.01):
        self.delay = delay
        self.dispatched = []
        self._lock = threading.Lock()

    def _host(self, g):
        return {
            "gang_feasible": np.ones(g, bool),
            "placed": np.zeros(g, bool),
            "progress": np.zeros(g, np.int32),
            "best": 0,
            "best_exists": False,
            "assignment_nodes": np.zeros((g, 4), np.int32),
            "assignment_counts": np.zeros((g, 4), np.int32),
            "telemetry": {},
        }

    def submit_batch(self, batch_args, progress_args, donate=None,
                     tenant=None):
        with self._lock:
            self.dispatched.append(tenant)
        g = int(np.asarray(batch_args[2]).shape[0])
        return FakeExecJob(self._host(g), {"capacity": None}, self.delay)

    def run_batch(self, batch_args, progress_args, donate=None, tenant=None):
        job = self.submit_batch(batch_args, progress_args, donate, tenant)
        host, batch = job.wait()
        return host, batch, 0.0, self.delay

    def run(self, fn):
        return fn()


def make_job(tenant, n=8, g=4, seed=0):
    from batch_scheduler_tpu.ops.bucketing import pad_oracle_batch

    req = make_request(n=n, g=g, seed=seed)
    args, progress = pad_oracle_batch(
        alloc=req.alloc, requested=req.requested, group_req=req.group_req,
        remaining=req.remaining, fit_mask=req.fit_mask,
        group_valid=req.group_valid, order=req.order,
        min_member=req.min_member, scheduled=req.scheduled,
        matched=req.matched, ineligible=req.ineligible,
        creation_rank=req.creation_rank,
    )
    return CoalesceJob(
        tenant=tenant, n=n, g=g, r=int(req.alloc.shape[1]),
        padded_args=args, progress_args=progress,
        raw_fn=lambda req=req: (
            req.alloc, req.requested, req.group_req, req.remaining,
            req.fit_mask, req.group_valid, req.order, req.min_member,
            req.scheduled, req.matched, req.ineligible, req.creation_rank,
        ),
    )


# ---------------------------------------------------------------------------
# host-twin formula checks (the coupled-formula spine)
# ---------------------------------------------------------------------------


def test_find_max_group_host_matches_device():
    from batch_scheduler_tpu.ops.oracle import (
        find_max_group,
        find_max_group_host,
    )

    r = np.random.RandomState(7)
    for trial in range(20):
        g = int(r.randint(2, 40))
        min_member = r.randint(1, 9, size=g).astype(np.int32)
        scheduled = r.randint(0, 9, size=g).astype(np.int32)
        matched = r.randint(0, 9, size=g).astype(np.int32)
        ineligible = r.rand(g) < 0.3
        creation_rank = r.permutation(g).astype(np.int32)
        db, de, dp = find_max_group(
            min_member, scheduled, matched, ineligible, creation_rank
        )
        hb, he, hp = find_max_group_host(
            min_member, scheduled, matched, ineligible, creation_rank
        )
        assert (int(db), bool(de)) == (hb, he), trial
        np.testing.assert_array_equal(np.asarray(dp), hp)


def test_repack_assignment_span_reproduces_dedicated_topk():
    """The demux's backfill rule must equal lax.top_k's tie-break on the
    dedicated take vector — including the ascending zero-count tail."""
    import jax

    from batch_scheduler_tpu.ops.oracle import repack_assignment_span

    r = np.random.RandomState(3)
    for trial in range(10):
        nb, offset, k = 16, 32, 8
        local = np.zeros(nb, np.int32)
        for _ in range(int(r.randint(0, 5))):
            local[r.randint(nb)] = r.randint(1, 9)
        ded_counts, ded_nodes = jax.lax.top_k(local, k)
        # the mega row: the same takes embedded at `offset` in a wider
        # space whose other blocks hold zeros
        mega = np.zeros(96, np.int32)
        mega[offset:offset + nb] = local
        mega_counts, mega_nodes = jax.lax.top_k(mega, k)
        nodes, counts = repack_assignment_span(
            np.asarray(mega_nodes), np.asarray(mega_counts), offset, nb, k
        )
        np.testing.assert_array_equal(nodes, np.asarray(ded_nodes))
        np.testing.assert_array_equal(counts, np.asarray(ded_counts))


# ---------------------------------------------------------------------------
# bit-identity: coalescing sidecar vs dedicated sidecars
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["span", "mega"])
def test_wire_bit_identity_vs_dedicated(mode):
    """K tenants' streams through one coalescing sidecar produce the
    exact responses their dedicated-sidecar runs produce — per-group
    masks, permuted orders, and concurrent submission included."""
    coal_srv = single_device_server(coalesce=True)
    coal_srv.coalescer.mode = mode
    ded_srv = single_device_server()
    try:
        ch, cp = coal_srv.address
        dh, dp = ded_srv.address
        from batch_scheduler_tpu.service.client import OracleClient

        mismatches = []

        def run_tenant(i):
            c = OracleClient(ch, cp)
            d = OracleClient(dh, dp)
            try:
                for b in range(3):
                    req = make_request(
                        n=24 + 8 * i, g=4 + i, seed=i * 100 + b,
                        per_group_mask=(i % 2 == 0),
                    )
                    r_coal = c.schedule(req, tenant=f"t{i}")
                    r_ded = d.schedule(req)
                    if response_digest(r_coal) != response_digest(r_ded):
                        mismatches.append((i, b))
                    # row fetches demux back to the tenant's node space
                    row_c = c.row("capacity", 0, r_coal.batch_seq)
                    row_d = d.row("capacity", 0, r_ded.batch_seq)
                    if not np.array_equal(row_c, row_d):
                        mismatches.append((i, b, "row"))
            finally:
                c.close()
                d.close()

        threads = [
            threading.Thread(target=run_tenant, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not mismatches, mismatches
        stats = coal_srv.coalescer.stats()
        assert stats["groups_run"] >= 1
    finally:
        close_server(coal_srv)
        close_server(ded_srv)


def test_mega_demux_identity_direct():
    """Deterministic mega-group demux: every field of every tenant's
    result equals its own dedicated execute_batch_host — mixed shapes,
    mixed mask modes, forced into ONE block-diagonal mega-batch."""
    from batch_scheduler_tpu.ops.oracle import execute_batch_host
    from batch_scheduler_tpu.service.server import DeviceExecutor

    executor = DeviceExecutor(scan_mesh=None)
    coal = OracleCoalescer(executor, mode="mega", mega_cells=1 << 30)
    try:
        jobs = [
            make_job("alpha", n=16, g=4, seed=1),
            make_job("beta", n=40, g=7, seed=2),
            make_job("gamma", n=8, g=3, seed=3),
        ]
        coal._run_mega(jobs)
        for job in jobs:
            res = job.wait(timeout=60)
            ded_host, _ = execute_batch_host(
                job.padded_args, job.progress_args
            )
            g = job.g
            np.testing.assert_array_equal(
                np.asarray(res.host["gang_feasible"]),
                np.asarray(ded_host["gang_feasible"])[:g],
            )
            np.testing.assert_array_equal(
                np.asarray(res.host["placed"]),
                np.asarray(ded_host["placed"])[:g],
            )
            np.testing.assert_array_equal(
                np.asarray(res.host["progress"]),
                np.asarray(ded_host["progress"])[:g],
            )
            assert int(res.host["best"]) == int(ded_host["best"])
            assert bool(res.host["best_exists"]) == bool(
                ded_host["best_exists"]
            )
            np.testing.assert_array_equal(
                np.asarray(res.host["assignment_nodes"]),
                np.asarray(ded_host["assignment_nodes"])[:g],
            )
            np.testing.assert_array_equal(
                np.asarray(res.host["assignment_counts"]),
                np.asarray(ded_host["assignment_counts"])[:g],
            )
    finally:
        coal.stop()
        executor.stop()


def test_wire_delta_and_full_lanes_mixed():
    """A wire-delta RemoteScorer (device-resident mirror) and a
    full-snapshot RemoteScorer coalesce through one sidecar and stay
    bit-identical to the local scorer across churned refreshes — the
    'coalesced batch may mix delta-synced and keyframe tenants' claim."""
    from batch_scheduler_tpu.cache import PGStatusCache
    from batch_scheduler_tpu.core.oracle_scorer import OracleScorer
    from batch_scheduler_tpu.service.client import (
        RemoteScorer,
        ResilientOracleClient,
    )
    from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY

    srv = single_device_server(coalesce=True)
    host, port = srv.address
    delta_remote = RemoteScorer(
        ResilientOracleClient(host, port, timeout=60, window=2),
        tenant="team-delta",
    )
    full_remote = RemoteScorer(
        ResilientOracleClient(host, port, timeout=60, window=2),
        tenant="team-full",
    )
    full_remote._wire_delta_ok = False  # pinned to full snapshots
    local = OracleScorer(device_state=True)
    try:
        nodes = [
            make_node(f"n{i}", {"cpu": "8", "memory": "32Gi", "pods": "110"})
            for i in range(8)
        ]
        cluster = FakeCluster(nodes)
        cache = PGStatusCache()
        gang_names = []
        for i in range(4):
            name = f"gang{i}"
            pg = make_group(name, 3, creation_ts=float(i))
            members = [
                make_pod(f"{name}-{m}", group=name, requests={"cpu": "1"})
                for m in range(3)
            ]
            status_for(pg, cache, rep_pod=members[0])
            gang_names.append(f"default/{name}")
        counter = DEFAULT_REGISTRY.counter(
            "bst_oracle_wire_delta_batches_total"
        )
        deltas_before = counter.value(kind="delta")
        mismatches = []
        for rnd in range(3):
            for s in (delta_remote, full_remote, local):
                s.mark_dirty()
                s.ensure_fresh(cluster, cache, group=gang_names[0])
            for gname in gang_names:
                plans = [
                    (
                        s.placed(gname),
                        s.gang_feasible(gname),
                        tuple(sorted(s.assignment(gname).items())),
                    )
                    for s in (delta_remote, full_remote, local)
                ]
                if not plans[0] == plans[1] == plans[2]:
                    mismatches.append((rnd, gname, plans))
            cluster.bind(
                make_pod(f"filler-{rnd}", requests={"cpu": "2"}),
                nodes[rnd].metadata.name,
            )
        assert not mismatches, mismatches
        assert counter.value(kind="delta") - deltas_before >= 1
        assert srv.coalescer.stats()["groups_run"] >= 1
    finally:
        delta_remote.close()
        full_remote.close()
        close_server(srv)


# ---------------------------------------------------------------------------
# DRF fairness: a starved small tenant never waits behind the whale
# ---------------------------------------------------------------------------


def test_drf_whale_starvation_bound():
    executor = FakeExecutor(delay=0.01)
    coal = OracleCoalescer(
        executor, depth=256, span_max=2, mode="span"
    )
    try:
        # the whale floods 24 jobs; once they are queued, a small tenant
        # submits ONE — DRF must dequeue it within the next couple of
        # groups, not behind the whale's backlog
        whale_jobs = [make_job("whale", seed=s) for s in range(24)]
        small_job = make_job("small", seed=99)
        threads = [
            threading.Thread(target=coal.schedule, args=(j,))
            for j in whale_jobs
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5
        while coal.stats()["pending"] < 12 and time.monotonic() < deadline:
            time.sleep(0.002)
        small_thread = threading.Thread(
            target=coal.schedule, args=(small_job,)
        )
        small_thread.start()
        small_thread.join(timeout=30)
        assert small_job._done.is_set()
        for t in threads:
            t.join(timeout=30)
        order = executor.dispatched
        pos = order.index("small")
        whales_before_small = order[:pos].count("whale")
        # the small tenant jumped the whale's backlog: at submission time
        # >= 12 whale jobs were already queued, yet it dispatches with
        # span_max * 2 of the head (one in-flight group + the group that
        # admits it)
        assert whales_before_small <= 6, order
    finally:
        coal.stop()


def test_drf_uses_observatory_weights():
    """A tenant the capacity observatory says already holds the cluster
    (dominant share ~1) sorts behind a zero-share tenant even with no
    serviced-work history."""
    executor = FakeExecutor(delay=0.02)
    coal = OracleCoalescer(
        executor, depth=64, span_max=1, mode="span",
        weights_fn=lambda: {"hog": 0.9, "lean": 0.0},
    )
    try:
        # stall the worker with a filler so both contenders are queued
        # when selection happens
        filler = make_job("filler", seed=0)
        t0 = threading.Thread(target=coal.schedule, args=(filler,))
        t0.start()
        time.sleep(0.005)
        hog = make_job("hog", seed=1)
        lean = make_job("lean", seed=2)
        t1 = threading.Thread(target=coal.schedule, args=(hog,))
        t1.start()
        deadline = time.monotonic() + 2
        while coal.stats()["pending"] < 1 and time.monotonic() < deadline:
            time.sleep(0.001)
        t2 = threading.Thread(target=coal.schedule, args=(lean,))
        t2.start()
        for t in (t0, t1, t2):
            t.join(timeout=30)
        order = [t for t in executor.dispatched if t in ("hog", "lean")]
        assert order == ["lean", "hog"], executor.dispatched
    finally:
        coal.stop()


# ---------------------------------------------------------------------------
# admission control: BUSY + retry, never a silent hang
# ---------------------------------------------------------------------------


def test_saturation_raises_busy():
    executor = FakeExecutor(delay=0.2)
    coal = OracleCoalescer(executor, depth=1, span_max=1, mode="span")
    try:
        jobs = [make_job("a", seed=0), make_job("a", seed=1)]
        threads = [
            threading.Thread(target=coal.schedule, args=(j,)) for j in jobs
        ]
        for t in threads:
            t.start()
        # with depth=1 and a slow worker, a third submit must be refused
        deadline = time.monotonic() + 2
        saturated = None
        while time.monotonic() < deadline and saturated is None:
            try:
                coal.check_admission()
                time.sleep(0.005)
            except CoalesceSaturated as e:
                saturated = e
        assert saturated is not None
        assert 25 <= saturated.retry_after_ms <= 5000
        for t in threads:
            t.join(timeout=30)
    finally:
        coal.stop()


def test_busy_over_wire_and_resilient_retry():
    """A saturated coalescer answers BUSY in-band; the raw client raises
    OracleBusyError with the hint, the resilient client waits it out and
    succeeds — and the breaker never opens."""
    from batch_scheduler_tpu.service.client import (
        OracleClient,
        ResilientOracleClient,
    )

    srv = single_device_server(coalesce=True)
    # replace with a tiny-depth coalescer whose executor stalls briefly,
    # so concurrent submits saturate deterministically
    srv.coalescer.stop()

    class SlowExecutor:
        def __init__(self, inner):
            self._inner = inner

        def submit_batch(self, *a, **kw):
            time.sleep(0.3)
            return self._inner.submit_batch(*a, **kw)

        def run_batch(self, *a, **kw):
            time.sleep(0.3)
            return self._inner.run_batch(*a, **kw)

        def run(self, fn):
            return self._inner.run(fn)

    srv.coalescer = OracleCoalescer(
        SlowExecutor(srv.executor), depth=1, span_max=1, mode="span"
    )
    host, port = srv.address
    try:
        req = make_request(seed=5)
        busy_seen = []
        done = []

        def flood(i):
            c = OracleClient(host, port)
            try:
                for b in range(2):
                    try:
                        c.schedule(req)
                        done.append(i)
                    except OracleBusyError as e:
                        busy_seen.append(e.retry_after_ms)
            finally:
                c.close()

        threads = [
            threading.Thread(target=flood, args=(i,)) for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert busy_seen, "saturation never produced a BUSY answer"
        assert all(25 <= ms <= 5000 for ms in busy_seen)
        # the resilient client rides retry-after to a successful answer
        rc = ResilientOracleClient(host, port, timeout=60)
        resp = rc.schedule(req, tenant="retrier")
        assert resp.gang_feasible.shape[0] == 8
        assert rc.breaker.state == "closed"
        rc.close()
    finally:
        close_server(srv)


# ---------------------------------------------------------------------------
# chaos: a mid-merge disconnect drops only that tenant's span
# ---------------------------------------------------------------------------


def test_disconnect_mid_merge_drops_only_that_span():
    from batch_scheduler_tpu.service.client import OracleClient

    srv = single_device_server(coalesce=True)
    host, port = srv.address
    try:
        # tenant A ships a request and slams the connection shut before
        # reading the response — its span's result has nowhere to go
        dead = socket.create_connection((host, port), timeout=10)
        req_a = make_request(seed=11)
        proto.write_frame(
            dead, proto.MsgType.TENANT, proto.pack_tenant("vanisher")
        )
        proto.write_frame(
            dead, proto.MsgType.SCHEDULE_REQ,
            proto.pack_schedule_request(req_a),
        )
        dead.close()
        # tenant B's concurrent (possibly coalesced-with-A) batch must
        # complete and stay bit-identical to a dedicated run
        ded = single_device_server()
        try:
            c = OracleClient(host, port)
            d = OracleClient(*ded.address)
            req_b = make_request(seed=12)
            r_coal = c.schedule(req_b, tenant="survivor")
            r_ded = d.schedule(req_b)
            assert response_digest(r_coal) == response_digest(r_ded)
            # and the server keeps serving: another round works
            r2 = c.schedule(make_request(seed=13), tenant="survivor")
            assert r2.batch_seq == r_coal.batch_seq + 1
            c.close()
            d.close()
        finally:
            close_server(ded)
    finally:
        close_server(srv)


# ---------------------------------------------------------------------------
# tenant wire attribution
# ---------------------------------------------------------------------------


def test_tenant_annotation_attributes_scan_counter():
    from batch_scheduler_tpu.service.client import OracleClient
    from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY

    srv = single_device_server(coalesce=True)
    host, port = srv.address
    try:
        counter = DEFAULT_REGISTRY.counter("bst_scan_batches_total")
        before = counter.value(path="serial", tenant="acme")
        c = OracleClient(host, port)
        c.schedule(make_request(seed=21), tenant="acme")
        c.close()
        deadline = time.monotonic() + 5
        while (
            counter.value(path="serial", tenant="acme") <= before
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert counter.value(path="serial", tenant="acme") > before
    finally:
        close_server(srv)


def test_tenant_frame_roundtrip_and_bounds():
    assert proto.unpack_tenant(proto.pack_tenant("team-a")) == "team-a"
    with pytest.raises(ValueError):
        proto.pack_tenant("")
    # overlong labels truncate (attribution metadata must never crash the
    # schedule path), clipping a codepoint split at the byte cap cleanly
    assert proto.pack_tenant("x" * 65) == b"x" * 64
    # 3 + 2*40 bytes in, 64-byte cap: 30 whole é fit after "ns-", the
    # codepoint split across the boundary drops (61st byte is half an é)
    assert proto.unpack_tenant(proto.pack_tenant("ns-" + "é" * 40)) == (
        "ns-" + "é" * 30
    )
    ms, msg = proto.unpack_busy(proto.pack_busy(1234, "queue full"))
    assert (ms, msg) == (1234, "queue full")


# ---------------------------------------------------------------------------
# knobs: parse-guarded, typo'd values never crash
# ---------------------------------------------------------------------------


def test_knob_parse_guards(monkeypatch):
    monkeypatch.setenv("BST_COALESCE", "bananas")
    assert coalesce_enabled() is False
    monkeypatch.setenv("BST_COALESCE", "1")
    assert coalesce_enabled() is True
    monkeypatch.setenv("BST_COALESCE_DEPTH", "not-an-int")
    assert coalesce_depth() == 64
    monkeypatch.setenv("BST_COALESCE_SPAN_MAX", "9999")
    assert coalesce_span_max() == 64  # clamped
    monkeypatch.setenv("BST_COALESCE_MODE", "warp")
    assert coalesce_mode() == "auto"


# ---------------------------------------------------------------------------
# lock discipline: the submit storm under BST_LOCKCHECK
# ---------------------------------------------------------------------------


def test_lockcheck_armed_submit_storm(monkeypatch):
    """8 threads hammer schedule()/check_admission()/stats() against a
    live coalescer with BST_LOCKCHECK instrumentation installed — an
    unguarded read of the queue state raises LockDisciplineError with
    both stacks (docs/static_analysis.md)."""
    import os

    from batch_scheduler_tpu.analysis import lockcheck

    prev = os.environ.get("BST_LOCKCHECK")
    os.environ["BST_LOCKCHECK"] = "1"
    lockcheck.install()
    try:
        executor = FakeExecutor(delay=0.002)
        coal = OracleCoalescer(executor, depth=32, span_max=4, mode="span")
        errors = []

        def storm(i):
            try:
                for b in range(6):
                    try:
                        coal.schedule(make_job(f"t{i % 3}", seed=i * 10 + b))
                    except CoalesceSaturated:
                        time.sleep(0.005)
                    coal.stats()
            except BaseException as e:  # noqa: BLE001 — the assertion
                errors.append(e)

        threads = [
            threading.Thread(target=storm, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        coal.stop()
        assert not errors, errors
    finally:
        if prev is None:
            os.environ.pop("BST_LOCKCHECK", None)
        else:
            os.environ["BST_LOCKCHECK"] = prev
