"""Equivalence of the fused Pallas gang-placement kernel against the
lax.scan reference implementation (ops.oracle.assign_gangs)."""

import numpy as np
import pytest

from batch_scheduler_tpu.ops.oracle import assign_gangs
from batch_scheduler_tpu.ops.pallas_assign import assign_gangs_pallas


def _run_both(left, group_req, remaining, mask, order):
    a_ref, p_ref, l_ref = assign_gangs(left, group_req, remaining, mask, order)
    a_pal, p_pal, l_pal = assign_gangs_pallas(
        left, group_req, remaining, mask, order, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(a_ref), np.asarray(a_pal))
    np.testing.assert_array_equal(np.asarray(p_ref), np.asarray(p_pal))
    np.testing.assert_array_equal(np.asarray(l_ref), np.asarray(l_pal))
    return np.asarray(a_pal), np.asarray(p_pal), np.asarray(l_pal)


def test_pallas_matches_scan_race():
    left = np.array([[7100, 10**6, 10**6, 50]], dtype=np.int32)
    group_req = np.array([[1000, 0, 0, 1], [1000, 0, 0, 1]], dtype=np.int32)
    alloc, placed, _ = _run_both(
        left, group_req, np.array([5, 5], np.int32),
        np.ones((1, 1), bool), np.array([0, 1], np.int32),
    )
    assert placed.tolist() == [True, False]
    assert alloc.sum() == 5


def test_pallas_matches_scan_fuzz():
    rng = np.random.default_rng(7)
    for trial in range(8):
        n = int(rng.integers(1, 24))
        g = int(rng.integers(1, 12))
        r = int(rng.integers(1, 5))
        left = rng.integers(0, 40, size=(n, r)).astype(np.int32)
        group_req = rng.integers(0, 6, size=(g, r)).astype(np.int32)
        remaining = rng.integers(0, 10, size=g).astype(np.int32)
        order = rng.permutation(g).astype(np.int32)
        mask = np.ones((1, n), bool)
        mask[0, rng.integers(0, n)] = bool(rng.integers(0, 2))
        _run_both(left, group_req, remaining, mask, order)


def test_pallas_matches_scan_per_group_mask_fuzz():
    """The [G,N] selector-mask path: mask rows ride the chunked DMA like
    the request rows, pre-permuted into scan order."""
    rng = np.random.default_rng(11)
    for trial in range(8):
        n = int(rng.integers(1, 24))
        g = int(rng.integers(1, 12))
        r = int(rng.integers(1, 5))
        left = rng.integers(0, 40, size=(n, r)).astype(np.int32)
        group_req = rng.integers(0, 6, size=(g, r)).astype(np.int32)
        remaining = rng.integers(0, 10, size=g).astype(np.int32)
        order = rng.permutation(g).astype(np.int32)
        mask = rng.random((g, n)) < 0.7  # per-group node eligibility
        _run_both(left, group_req, remaining, mask, order)


def test_pallas_matches_scan_bucketed_shapes_and_edge_values():
    """Equivalence at BUCKETED shapes (the sizes production actually
    compiles — shape-dependent bugs are the class that bit GSPMD) with
    adversarial value patterns: saturated nodes, zero-remaining rows,
    values near the LANE_MAX domain bound. Fixed shape set keeps the
    interpret-mode compile count bounded."""
    rng = np.random.default_rng(23)
    for n, g, r in ((64, 16, 3), (128, 32, 5)):
        left = rng.integers(0, 40, size=(n, r)).astype(np.int32)
        left[: n // 4] = 0  # saturated nodes
        left[n // 4] = 2**29  # near the lane domain bound
        group_req = rng.integers(0, 6, size=(g, r)).astype(np.int32)
        group_req[0] = 0  # zero-demand gang
        remaining = rng.integers(0, 10, size=g).astype(np.int32)
        remaining[1] = 0  # nothing left to place
        order = rng.permutation(g).astype(np.int32)
        mask = rng.random((g, n)) < 0.7
        mask[2, :] = False  # fully masked-out gang
        _run_both(left, group_req, remaining, mask, order)


def test_pallas_per_group_mask_selector_semantics():
    """A gang selecting one zone places only on its nodes even when the
    other zone has more room (the fit-mask contract the [G,N] path owns)."""
    left = np.array([[4000, 10], [8000, 10]], dtype=np.int32)  # n0 east, n1 west
    group_req = np.array([[1000, 1], [1000, 1]], dtype=np.int32)
    remaining = np.array([3, 3], dtype=np.int32)
    mask = np.array([[True, False], [True, True]])  # g0 pinned to n0
    alloc, placed, _ = _run_both(
        left, group_req, remaining, mask, np.array([0, 1], np.int32)
    )
    assert placed.tolist() == [True, True]
    assert alloc[0, 1] == 0 and alloc[0, 0] == 3  # g0 never touches west


def test_pallas_rejects_mismatched_mask_rows():
    left = np.zeros((2, 2), np.int32)
    with pytest.raises(ValueError):
        assign_gangs_pallas(
            left, np.zeros((3, 2), np.int32), np.zeros(3, np.int32),
            np.ones((2, 2), bool),  # neither 1 nor G rows
            np.arange(3, dtype=np.int32),
        )


def _run_wave(left, group_req, remaining, mask, order, wave):
    a_ref, p_ref, l_ref = assign_gangs(left, group_req, remaining, mask, order)
    a_pal, p_pal, l_pal = assign_gangs_pallas(
        left, group_req, remaining, mask, order, interpret=True, wave=wave
    )
    np.testing.assert_array_equal(np.asarray(a_ref), np.asarray(a_pal))
    np.testing.assert_array_equal(np.asarray(p_ref), np.asarray(p_pal))
    np.testing.assert_array_equal(np.asarray(l_ref), np.asarray(l_pal))


def test_pallas_wavefront_matches_scan_fuzz():
    """The chunked-grid wavefront kernel variant (wave >= 2): bit-identity
    against the serial scan over both mask modes, mixed demand rows (the
    speculative/demotion paths) and identical demand rows (the uniform
    aggregate path). ONE fixed shape — interpret-mode kernel builds are
    seconds each, so value trials must ride the jit cache."""
    rng = np.random.default_rng(31)
    n, g, r = 12, 10, 3
    for trial in range(6):
        left = rng.integers(0, 200, size=(n, r)).astype(np.int32)
        if trial % 3 == 0:
            group_req = np.tile(
                rng.integers(0, 4, size=(1, r)).astype(np.int32), (g, 1)
            )
        else:
            group_req = rng.integers(0, 6, size=(g, r)).astype(np.int32)
        remaining = rng.integers(0, 40, size=g).astype(np.int32)
        order = rng.permutation(g).astype(np.int32)
        rows = 1 if trial % 2 == 0 else g
        mask = rng.random((rows, n)) > 0.2
        _run_wave(left, group_req, remaining, mask, order, 8)


def test_pallas_wavefront_contended_and_uniform_edges():
    """Adversarial wavefront cases: a tight node every gang wants
    (serial-replay demotion), an all-identical bulk gang submission with
    infeasible gangs mid-stream (uniform aggregate path), and the
    histogram clamp region (capacities > _BINS-1)."""
    # contended, non-uniform
    left = np.array([[10], [100]], np.int32)
    group_req = np.array([[1 + (i % 2)] for i in range(8)], np.int32)
    _run_wave(
        left, group_req, np.full(8, 3, np.int32), np.ones((1, 2), bool),
        np.arange(8, dtype=np.int32), 4,
    )
    # uniform with infeasible gangs and clamped capacities
    left = np.array([[500, 9], [500, 9], [500, 300], [500, 0]], np.int32)
    group_req = np.tile(np.array([[3, 1]], np.int32), (8, 1))
    remaining = np.array([4, 900, 4, 4, 900, 4, 4, 4], np.int32)
    _run_wave(
        left, group_req, remaining, np.ones((1, 4), bool),
        np.arange(8, dtype=np.int32), 8,
    )


def test_pallas_matches_scan_readback_tail_scenarios():
    """Interpret-mode equivalence at the compact-readback tail shapes
    (sim.scenarios.readback_tail_scenarios, the same scenarios the TPU
    smoke drives on hardware): a gang spanning hundreds of distinct nodes
    with remaining near 2^16, and a 66k-member single-node take."""
    from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot
    from batch_scheduler_tpu.sim.scenarios import readback_tail_scenarios

    for nodes, groups in readback_tail_scenarios():
        snap = ClusterSnapshot(nodes, {}, groups)
        left = snap.alloc - snap.requested
        _run_both(
            left, snap.group_req, snap.remaining, snap.fit_mask, snap.order
        )
