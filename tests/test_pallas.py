"""Equivalence of the fused Pallas gang-placement kernel against the
lax.scan reference implementation (ops.oracle.assign_gangs)."""

import numpy as np
import pytest

from batch_scheduler_tpu.ops.oracle import assign_gangs
from batch_scheduler_tpu.ops.pallas_assign import assign_gangs_pallas


def _run_both(left, group_req, remaining, mask, order):
    a_ref, p_ref, l_ref = assign_gangs(left, group_req, remaining, mask, order)
    a_pal, p_pal, l_pal = assign_gangs_pallas(
        left, group_req, remaining, mask, order, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(a_ref), np.asarray(a_pal))
    np.testing.assert_array_equal(np.asarray(p_ref), np.asarray(p_pal))
    np.testing.assert_array_equal(np.asarray(l_ref), np.asarray(l_pal))
    return np.asarray(a_pal), np.asarray(p_pal), np.asarray(l_pal)


def test_pallas_matches_scan_race():
    left = np.array([[7100, 10**6, 10**6, 50]], dtype=np.int32)
    group_req = np.array([[1000, 0, 0, 1], [1000, 0, 0, 1]], dtype=np.int32)
    alloc, placed, _ = _run_both(
        left, group_req, np.array([5, 5], np.int32),
        np.ones((1, 1), bool), np.array([0, 1], np.int32),
    )
    assert placed.tolist() == [True, False]
    assert alloc.sum() == 5


def test_pallas_matches_scan_fuzz():
    rng = np.random.default_rng(7)
    for trial in range(8):
        n = int(rng.integers(1, 24))
        g = int(rng.integers(1, 12))
        r = int(rng.integers(1, 5))
        left = rng.integers(0, 40, size=(n, r)).astype(np.int32)
        group_req = rng.integers(0, 6, size=(g, r)).astype(np.int32)
        remaining = rng.integers(0, 10, size=g).astype(np.int32)
        order = rng.permutation(g).astype(np.int32)
        mask = np.ones((1, n), bool)
        mask[0, rng.integers(0, n)] = bool(rng.integers(0, 2))
        _run_both(left, group_req, remaining, mask, order)


def test_pallas_rejects_full_mask():
    left = np.zeros((2, 2), np.int32)
    with pytest.raises(ValueError):
        assign_gangs_pallas(
            left, np.zeros((3, 2), np.int32), np.zeros(3, np.int32),
            np.ones((3, 2), bool), np.arange(3, dtype=np.int32),
        )
