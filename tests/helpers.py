"""Shared test fixtures: compact builders for pods, nodes and pod groups."""

from __future__ import annotations

from typing import Dict, List, Optional

from batch_scheduler_tpu.api import (
    Container,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodGroup,
    PodGroupSpec,
    PodSpec,
    new_uid,
    parse_resource_list,
)
from batch_scheduler_tpu.cache import PGStatusCache, PodGroupMatchStatus
from batch_scheduler_tpu.utils.labels import POD_GROUP_LABEL


def make_pod(
    name: str,
    group: str = "",
    requests: Optional[Dict] = None,
    limits: Optional[Dict] = None,
    namespace: str = "default",
    priority: int = 0,
    node_selector: Optional[Dict] = None,
    owner_refs: Optional[List[str]] = None,
    creation_ts: float = 0.0,
) -> Pod:
    labels = {POD_GROUP_LABEL: group} if group else {}
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=new_uid("pod"),
            labels=labels,
            owner_references=owner_refs or [],
            creation_timestamp=creation_ts,
        ),
        spec=PodSpec(
            containers=[Container.from_raw(requests=requests, limits=limits)],
            priority=priority,
            node_selector=node_selector or {},
        ),
    )


def make_node(
    name: str,
    allocatable: Optional[Dict] = None,
    labels: Optional[Dict] = None,
    unschedulable: bool = False,
) -> Node:
    alloc = parse_resource_list(allocatable or {"cpu": "8", "memory": "16Gi", "pods": 110}, floor=True)
    return Node(
        metadata=ObjectMeta(name=name, uid=new_uid("node"), labels=labels or {}),
        spec=NodeSpec(unschedulable=unschedulable),
        status=NodeStatus(allocatable=alloc, capacity=dict(alloc)),
    )


def make_group(
    name: str,
    min_member: int,
    namespace: str = "default",
    min_resources: Optional[Dict] = None,
    max_schedule_time: Optional[float] = None,
    creation_ts: float = 0.0,
) -> PodGroup:
    return PodGroup(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            uid=new_uid("pg"),
            creation_timestamp=creation_ts,
        ),
        spec=PodGroupSpec(
            min_member=min_member,
            min_resources=parse_resource_list(min_resources) if min_resources else None,
            max_schedule_time=max_schedule_time,
        ),
    )


class FakeCluster:
    """Minimal ClusterStateProvider over static nodes + bound-pod tracking."""

    def __init__(self, nodes):
        self.nodes = list(nodes)
        self.bound: Dict[str, List] = {n.metadata.name: [] for n in self.nodes}

    def list_nodes(self):
        return list(self.nodes)

    def node_requested(self, node_name: str) -> Dict[str, int]:
        from batch_scheduler_tpu.ops.snapshot import node_requested_from_pods

        return node_requested_from_pods(self.bound.get(node_name, []))

    def bind(self, pod, node_name: str) -> None:
        pod.spec.node_name = node_name
        self.bound[node_name].append(pod)


def status_for(
    pg: PodGroup,
    cache: PGStatusCache,
    rep_pod: Optional[Pod] = None,
    clock=None,
) -> PodGroupMatchStatus:
    from batch_scheduler_tpu.api import PodGroupPhase

    pgs = PodGroupMatchStatus(pg, clock=clock)
    if pg.status.phase == PodGroupPhase.EMPTY:
        # the controller normalises ""->Pending on first sync
        pg.status.phase = PodGroupPhase.PENDING
    if rep_pod is not None:
        pgs.pod = rep_pod
        if pg.spec.min_resources is None:
            pg.spec.min_resources = rep_pod.resource_require()
    cache.set(pg.full_name(), pgs)
    return pgs
