from batch_scheduler_tpu.utils.ttl_cache import TTLCache


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


def test_set_get_delete():
    c = TTLCache(clock=FakeClock())
    c.set("a", 1)
    assert c.get("a") == 1
    c.delete("a")
    assert c.get("a") is None


def test_expiry_is_lazy_and_purgeable():
    clk = FakeClock()
    c = TTLCache(default_ttl=10.0, clock=clk)
    c.set("a", 1)
    clk.advance(9.9)
    assert c.get("a") == 1
    clk.advance(0.2)
    assert c.get("a") is None
    assert "a" not in c.items()


def test_add_only_when_absent():
    clk = FakeClock()
    c = TTLCache(default_ttl=5.0, clock=clk)
    assert c.add("k", 1)
    assert not c.add("k", 2)
    assert c.get("k") == 1
    clk.advance(6)
    assert c.add("k", 3)  # expired entries can be re-added
    assert c.get("k") == 3


def test_on_evicted_fires_on_expiry_only():
    clk = FakeClock()
    c = TTLCache(default_ttl=10.0, clock=clk)
    evicted = []
    c.on_evicted(lambda k, v: evicted.append((k, v)))

    c.set("gone", "x")
    c.set("kept", "y", ttl=100.0)
    c.set("deleted", "z")
    c.delete("deleted")  # explicit delete must NOT fire the gang-abort hook

    clk.advance(11)
    n = c.purge_expired()
    assert n == 1
    assert evicted == [("gone", "x")]
    assert c.get("kept") == "y"


def test_flush_silent():
    clk = FakeClock()
    c = TTLCache(default_ttl=10.0, clock=clk)
    fired = []
    c.on_evicted(lambda k, v: fired.append(k))
    c.set("a", 1)
    c.flush()
    clk.advance(20)
    c.purge_expired()
    assert fired == []
    assert len(c) == 0


def test_per_entry_ttl_overrides_default():
    clk = FakeClock()
    c = TTLCache(default_ttl=10.0, clock=clk)
    c.set("short", 1, ttl=1.0)
    c.set("long", 2, ttl=100.0)
    clk.advance(2)
    assert c.get("short") is None
    assert c.get("long") == 2
