"""Host-side resource math tests — ports the reference's unit fixture
(reference pkg/scheduler/core/core_test.go:27-115) onto the new exact-dict
implementation."""

from batch_scheduler_tpu.api import Taint, Toleration
from batch_scheduler_tpu.core import resources as rmath
from batch_scheduler_tpu.ops.snapshot import node_requested_from_pods

from helpers import make_node, make_pod

GPU = "alpha.kubernetes.io/nvidia-gpu"
TIP = "tencent.cr/tencentip"


def _fixture():
    """The core_test.go fixture: 10 cpu / 10 gpu / 100 pods / 20 tencentip
    node with one 1cpu+1gpu+1ip pod already accounted."""
    node = make_node("n1", {"cpu": "10", GPU: "10", "pods": "100", TIP: "20"})
    pod = make_pod("p0", limits={"cpu": "1", GPU: "1", TIP: "1"},
                   requests={"cpu": "1", GPU: "1", TIP: "1"})
    requested = node_requested_from_pods([pod])
    return node, pod, requested


def test_single_node_resource_fits():
    node, pod, requested = _fixture()
    left = rmath.single_node_left(node, requested, pod)
    req = pod.resource_require()
    assert rmath.resource_satisfied(left, req)
    assert left["cpu"] == 9000 and left[GPU] == 9 and left["pods"] == 99


def test_single_node_resource_gpu_over_capacity():
    node, pod, requested = _fixture()
    over = make_pod("p1", limits={"cpu": "1", GPU: "101", TIP: "1"})
    left = rmath.single_node_left(node, requested, over)
    assert not rmath.resource_satisfied(left, over.resource_require())


def test_single_node_resource_extended_over_capacity():
    node, pod, requested = _fixture()
    over = make_pod("p2", limits={"cpu": "1", GPU: "1", TIP: "101"})
    left = rmath.single_node_left(node, requested, over)
    assert not rmath.resource_satisfied(left, over.resource_require())


def test_missing_lane_with_nonzero_request_fails():
    # reference compareResourceAndRequire: requesting a resource the node
    # lacks must fail (core.go:686-696)
    assert not rmath.resource_satisfied({"cpu": 1000}, {"cpu": 500, GPU: 1})
    assert rmath.resource_satisfied({"cpu": 1000}, {"cpu": 500, GPU: 0})


def test_limits_fall_back_to_requests():
    # reference getPodResourceRequire (core.go:761-772)
    p = make_pod("p", requests={"cpu": "2"})
    assert p.resource_require() == {"cpu": 2000}
    p2 = make_pod("p", requests={"cpu": "2"}, limits={"cpu": "3"})
    assert p2.resource_require() == {"cpu": 3000}


def test_percent_scaling_exact():
    scaled = rmath.scale_resources({"cpu": 8000, "memory": 999}, 7, 10)
    assert scaled == {"cpu": 5600, "memory": 699}


def test_check_fit_selector_and_taints():
    node = make_node("n", {"cpu": "4"}, labels={"zone": "a"})
    pod = make_pod("p", requests={"cpu": "1"}, node_selector={"zone": "a"})
    assert rmath.check_fit(pod, node)
    pod_bad = make_pod("p", requests={"cpu": "1"}, node_selector={"zone": "b"})
    assert not rmath.check_fit(pod_bad, node)

    node.spec.taints = [Taint(key="dedicated", value="batch", effect="NoSchedule")]
    assert not rmath.check_fit(pod, node)
    pod.spec.tolerations = [Toleration(key="dedicated", operator="Exists")]
    assert rmath.check_fit(pod, node)
    # PreferNoSchedule never blocks
    node.spec.taints = [Taint(key="x", effect="PreferNoSchedule")]
    assert rmath.check_fit(pod_bad.deepcopy(), node) or True
    assert rmath.check_fit(make_pod("q", requests={"cpu": "1"}), node)


def test_cluster_satisfies_early_exit_and_unschedulable():
    nodes = [make_node(f"n{i}", {"cpu": "4", "pods": "10"}) for i in range(4)]
    nodes[3].spec.unschedulable = True
    # 3 schedulable nodes x 4 cpu = 12 cpu
    assert rmath.cluster_satisfies(nodes, {}, None, {"cpu": 12000})
    assert not rmath.cluster_satisfies(nodes, {}, None, {"cpu": 12001})
