"""The analyzer suite's own gate (ISSUE 10): every checker must catch its
seeded-violation fixture, the clean tree must pass end-to-end (the wrapper
that folds `make analyze` into tier-1), and the BST_LOCKCHECK runtime mode
must reproduce a synthetic unguarded-access race deterministically."""

import os
import subprocess
import sys
import textwrap
import threading

import pytest

from batch_scheduler_tpu.analysis import annotations, coupling, guards, jit_purity
from batch_scheduler_tpu.analysis import knobs as knobs_mod
from batch_scheduler_tpu.analysis import lockcheck, runner, wire

FIXTURES = os.path.join(os.path.dirname(__file__), "analysis_fixtures")
REPO = runner.package_root()


def _fixture(name: str):
    path = os.path.join(FIXTURES, name)
    with open(path, "r", encoding="utf-8") as f:
        return path, f.read()


# -- checker 1: guarded-by ---------------------------------------------------


def test_guards_fixture_detects_each_seeded_violation():
    path, src = _fixture("unguarded_access.py")
    mod = annotations.scan_module(path, src)
    findings = guards.check_module(mod, src)
    msgs = [f.message for f in findings]
    assert any("bad_read" in m and "_items" in m for m in msgs), msgs
    assert any("bad_write" in m and "_count" in m for m in msgs), msgs
    assert any("bad_global" in m and "_GLOBAL_STATE" in m for m in msgs), msgs
    # locked, lock-held, and suppressed accesses stay quiet
    assert not any("good" in m or "helper" in m or "suppressed" in m for m in msgs)
    assert len(findings) == 3, findings


def test_guards_lock_held_annotation_and_suppression_parse():
    path, src = _fixture("unguarded_access.py")
    mod = annotations.scan_module(path, src)
    ca = mod.classes["Sharded"]
    assert ca.guarded == {"_items": "_lock", "_count": "_lock"}
    assert ca.lock_held == {"helper": {"_lock"}}
    assert mod.guarded_globals == {"_GLOBAL_STATE": "_GLOBAL_LOCK"}
    assert any(s.checker == "guarded-by" and s.reason for s in mod.suppressions)


# -- checker 2: lockcheck runtime mode --------------------------------------


def test_lockcheck_reproduces_unguarded_race_deterministically():
    lockcheck.install(modules=["batch_scheduler_tpu/framework/cluster.py"])
    from batch_scheduler_tpu.framework.cluster import ClusterState

    cs = ClusterState()
    t = threading.Thread(target=cs.version)
    t.start()
    t.join()
    # deterministic: the instance is provably shared, the lock is not held
    for _ in range(3):
        with pytest.raises(lockcheck.LockDisciplineError) as ei:
            _ = cs._nodes
        msg = str(ei.value)
        assert "this access" in msg and "lock NOT held" in msg
        assert "thread" in msg  # both stacks, attributed by thread id


def test_lockcheck_guarded_and_lock_held_paths_stay_quiet():
    lockcheck.install(
        modules=[
            "batch_scheduler_tpu/framework/cluster.py",
            "batch_scheduler_tpu/utils/ttl_cache.py",
        ]
    )
    from batch_scheduler_tpu.framework.cluster import ClusterState
    from batch_scheduler_tpu.utils.ttl_cache import TTLCache

    cs = ClusterState()
    errors = []

    def worker():
        try:
            for _ in range(100):
                cs.version()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    # explicit guarded access from a second thread is fine too
    with cs._lock:
        assert cs._nodes == {}

    # _get_locked is annotated lock-held and called under the RLock: the
    # frame walk must honor it across threads
    c = TTLCache()
    c.set("k", 41)

    def getter():
        try:
            assert c.get("k") == 41
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    t = threading.Thread(target=getter)
    t.start()
    t.join()
    assert not errors


# -- checker 3: jit-purity ---------------------------------------------------


def test_jit_purity_fixture_detects_each_seeded_violation():
    path, src = _fixture("impure_jit.py")
    findings = jit_purity.check_source(path, src)
    msgs = [f.message for f in findings]
    assert any("os.environ" in m and "impure_env" in m for m in msgs), msgs
    assert any("time." in m and "impure_clock" in m for m in msgs), msgs
    assert any("print" in m and "impure_clock" in m for m in msgs), msgs
    assert any("random" in m and "body" in m for m in msgs), msgs
    assert any("donated" in m and "reuse_donated" in m for m in msgs), msgs
    assert not any("pure_ok" in m for m in msgs), msgs


# -- checker 4: formula coupling ---------------------------------------------


def test_coupling_fixture_drifted_formula_fails_until_restamped(tmp_path):
    mod = tmp_path / "pair.py"
    mod.write_text(
        textwrap.dedent(
            """
            def side_a(x):
                return x * 3 + 1

            def side_b(x):
                return x * 3 + 1
            """
        )
    )
    groups = {"pair": ["pair.py::side_a", "pair.py::side_b"]}
    stamp_file = str(tmp_path / "stamps.json")
    coupling.stamp(str(tmp_path), stamp_file, groups)
    assert coupling.check(str(tmp_path), stamp_file, groups) == []

    # comment/docstring-only edits never trip the fingerprint
    mod.write_text(
        textwrap.dedent(
            '''
            def side_a(x):
                """Docstring added."""
                # comment added
                return x * 3 + 1

            def side_b(x):
                return x * 3 + 1
            '''
        )
    )
    assert coupling.check(str(tmp_path), stamp_file, groups) == []

    # a formula change on one side fails and names the pair
    mod.write_text(
        textwrap.dedent(
            """
            def side_a(x):
                return x * 4 + 1

            def side_b(x):
                return x * 3 + 1
            """
        )
    )
    findings = coupling.check(str(tmp_path), stamp_file, groups)
    assert len(findings) == 1
    assert "side_a" in findings[0].message and "side_b" in findings[0].message
    # re-stamping (the explicit acknowledgement) clears it
    coupling.stamp(str(tmp_path), stamp_file, groups)
    assert coupling.check(str(tmp_path), stamp_file, groups) == []

    # a deleted member is a registry error, not a silent pass
    mod.write_text("def side_b(x):\n    return x * 3 + 1\n")
    findings = coupling.check(str(tmp_path), stamp_file, groups)
    assert any("not found" in f.message for f in findings)


def test_coupling_clean_tree_stamps_match():
    assert coupling.check(REPO) == []


# -- checker 5: knob registry ------------------------------------------------


def test_knobs_fixture_detects_each_seeded_violation():
    path, src = _fixture("undocumented_knob.py")
    readme = "| `BST_FIXTURE_INT` | `BST_FIXTURE_FLOAT` | `BST_FIXTURE_FLAG` |"
    findings = knobs_mod.check_source(path, src, readme)
    msgs = [f.message for f in findings]
    assert any("BST_FIXTURE_MISSING" in m and "README" in m for m in msgs), msgs
    unguarded = [m for m in msgs if "unguarded" in m]
    assert any("BST_FIXTURE_INT" in m for m in unguarded), msgs
    assert any("BST_FIXTURE_FLOAT" in m for m in unguarded), msgs
    # try/except-guarded and flag-style reads stay quiet
    assert len(findings) == 3, findings


# -- checker 6: wire + metrics ----------------------------------------------


def test_wire_fixture_detects_unhandled_msgtype():
    path, src = _fixture("unhandled_msgtype.py")
    import ast

    tree = ast.parse(src)
    server_src = client_src = None
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            if node.targets[0].id == "SERVER_SRC":
                server_src = node.value.value
            elif node.targets[0].id == "CLIENT_SRC":
                client_src = node.value.value
    findings = wire.check_wire(
        path,
        src,
        [("server dispatch", "server.py", server_src),
         ("client annotation", "client.py", client_src)],
    )
    msgs = [(f.path, f.message) for f in findings]
    # NEW_FRAME: unhandled on both peers; PONG: explicitly waived on the
    # server, referenced nowhere on the client
    assert sum("NEW_FRAME" in m for _, m in msgs) == 2, msgs
    assert not any("PONG" in m and p == "server.py" for p, m in msgs), msgs


def test_metrics_fixture_detects_each_seeded_violation():
    path, src = _fixture("unregistered_metric.py")
    doc = "bst_fixture_documented_total and bst_fixture_conflict are listed"
    findings = wire.check_metrics([(path, src)], doc)
    msgs = [f.message for f in findings]
    assert any("fixture_unprefixed_total" in m and "bst_" in m for m in msgs)
    assert any("bst_fixture_undocumented_total" in m for m in msgs), msgs
    assert any("bst_fixture_conflict" in m and "kinds" in m for m in msgs), msgs
    assert any("non-constant" in m for m in msgs), msgs


# -- the gate itself ---------------------------------------------------------


def test_clean_tree_analyzer_exits_zero():
    """The wrapper that makes `make analyze` part of tier-1: the shipped
    tree must stay clean, with every suppression carrying a reason."""
    findings, supps = runner.run_all(REPO)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)
    assert all(s.reason for s in supps), supps


def test_analyzer_cli_exit_codes(tmp_path):
    """exit 0 on the clean repo, nonzero findings rendered file:line."""
    proc = subprocess.run(
        [sys.executable, "-m", "batch_scheduler_tpu.analysis"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stderr


def test_analyzer_cli_exits_one_on_seeded_violations(tmp_path):
    """`make analyze` semantics end-to-end: a tree seeded with a fixture
    violation makes the CLI exit 1 and render file:line findings."""
    pkg = tmp_path / "batch_scheduler_tpu"
    pkg.mkdir()
    src = os.path.join(FIXTURES, "unguarded_access.py")
    with open(src) as f:
        (pkg / "seeded.py").write_text(f.read())
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "batch_scheduler_tpu.analysis",
            "--check",
            "guarded-by",
            "--root",
            str(tmp_path),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "seeded.py" in proc.stdout and "[guarded-by]" in proc.stdout


def test_fixture_files_fail_the_checkers_not_the_gate():
    """The seeded fixtures live under tests/analysis_fixtures and must be
    excluded from the repo sweep — the gate stays green while the fixtures
    stay red."""
    path, src = _fixture("unguarded_access.py")
    mod = annotations.scan_module(path, src)
    assert guards.check_module(mod, src)  # red standalone
    findings, _ = runner.run_all(REPO)  # green swept
    assert findings == []
