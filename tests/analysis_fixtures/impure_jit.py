"""Seeded jit-purity violations. Parsed only, never imported/executed."""

import os
import random
import time
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def impure_env(x):
    flag = os.environ.get("BST_FIXTURE", "")  # VIOLATION: env read in trace
    return x + (1 if flag else 0)


@partial(jax.jit, static_argnames=("k",))
def impure_clock(x, k: int = 2):
    t = time.time()  # VIOLATION: trace-time constant clock
    print("tracing", k)  # VIOLATION: host I/O at trace time
    return x * k + t


def scanned(xs):
    def body(carry, x):
        carry = carry + x + random.random()  # VIOLATION: stdlib random
        return carry, carry

    return jax.lax.scan(body, 0.0, xs)


_blob_donated = jax.jit(lambda a, b: a + b, donate_argnums=(0, 1))


def reuse_donated(a, b):
    out = _blob_donated(a, b)
    return out + a  # VIOLATION: 'a' was donated to the dispatch


def pure_ok(x):
    # jnp and jax.random are fine inside traces
    key = jax.random.PRNGKey(0)
    return jnp.sum(x) + jax.random.uniform(key)
