"""Seeded wire-exhaustiveness fixture: a protocol with one more MsgType
than the peers handle. Parsed only, never imported."""


class MsgType:
    PING = 1
    PONG = 2
    DATA = 3
    NEW_FRAME = 4  # neither peer below mentions this one


SERVER_SRC = '''
class _H:
    def handle(self, t, payload):
        if t == MsgType.PING:
            return MsgType.PONG
        if t == MsgType.DATA:
            return self.process(payload)
        # msgtype-ignored: PONG server never receives its own reply frame
'''

CLIENT_SRC = '''
class _C:
    def request(self, payload):
        self.send(MsgType.PING)
        self.send(MsgType.DATA, payload)
        return self.recv()  # PONG
'''
