"""Seeded guarded-by violations — tests/test_analysis.py feeds this to the
static checker and asserts each marked line is caught. Never imported."""

import threading


class Sharded:
    def __init__(self):
        self._lock = threading.Lock()
        self._items: dict = {}  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock

    def good(self):
        with self._lock:
            self._count += 1
            return dict(self._items)

    def bad_read(self):
        return len(self._items)  # VIOLATION: no lock held

    def bad_write(self):
        self._count += 1  # VIOLATION: no lock held

    def helper(self):  # lock-held: _lock
        return self._items.get("k")  # ok: documented lock-held

    def suppressed(self):
        # analysis: allow(guarded-by) fixture-reviewed benign read
        return self._count


_GLOBAL_LOCK = threading.Lock()
_GLOBAL_STATE: list = []  # guarded-by: _GLOBAL_LOCK


def good_global():
    with _GLOBAL_LOCK:
        _GLOBAL_STATE.append(1)


def bad_global():
    _GLOBAL_STATE.clear()  # VIOLATION: module-global without its lock
