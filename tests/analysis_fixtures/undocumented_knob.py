"""Seeded knob-registry violations. Parsed only, never imported."""

import os


def undocumented():
    # BST_FIXTURE_MISSING is (by construction) absent from the fixture README
    return os.environ.get("BST_FIXTURE_MISSING", "")


def unguarded_parse():
    return int(os.environ.get("BST_FIXTURE_INT", "1"))  # VIOLATION: bare int()


def unguarded_via_name():
    raw = os.environ.get("BST_FIXTURE_FLOAT", "1.0")
    return float(raw)  # VIOLATION: bare float() through a local name


def guarded_ok():
    try:
        return int(os.environ.get("BST_FIXTURE_INT", "1"))
    except ValueError:
        return 1


def flag_ok():
    return os.environ.get("BST_FIXTURE_FLAG", "") == "1"
