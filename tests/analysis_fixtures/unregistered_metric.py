"""Seeded metric violations. Parsed only, never imported."""


def register(reg, series):
    reg.counter("fixture_unprefixed_total", "missing the bst_ prefix")  # VIOLATION
    reg.counter("bst_fixture_undocumented_total", "absent from the doc")  # VIOLATION
    reg.gauge("bst_fixture_conflict", "registered as a gauge here")
    reg.counter("bst_fixture_conflict", "and as a counter here")  # VIOLATION: kind conflict
    reg.histogram(series, "dynamic name, no suppression")  # VIOLATION
    reg.counter("bst_fixture_documented_total", "this one is in the doc")  # ok
