"""Client-side QPS/Burst flow control (VERDICT r2 missing #3; reference
caps its PodGroup clientset at QPS=10/Burst=20, batchscheduler.go:391-392).
"""

from __future__ import annotations

import threading
import time

from batch_scheduler_tpu.client.apiserver import APIServer
from batch_scheduler_tpu.client.http_apiserver import HTTPAPIServer
from batch_scheduler_tpu.client.http_gateway import serve_gateway
from batch_scheduler_tpu.utils.throttle import TokenBucket


def test_token_bucket_burst_then_qps():
    """Deterministic (injected clock): burst tokens go instantly, then the
    bucket paces to exactly qps."""
    now = [0.0]
    waits = []

    def clock():
        return now[0]

    def sleep(s):
        waits.append(s)
        now[0] += s

    tb = TokenBucket(qps=10.0, burst=5, clock=clock, sleep=sleep)
    for _ in range(25):
        tb.acquire()
    # 5 burst tokens free; the remaining 20 each wait 1/qps
    assert abs(sum(waits) - 20 * 0.1) < 1e-6, sum(waits)
    assert now[0] >= 2.0 - 1e-6


def test_token_bucket_refills_while_idle_and_caps_at_burst():
    now = [0.0]
    tb = TokenBucket(qps=10.0, burst=3, clock=lambda: now[0], sleep=lambda s: None)
    assert all(tb.try_acquire() for _ in range(3))
    assert not tb.try_acquire()  # empty
    now[0] += 100.0  # long idle: refill caps at burst, not qps*t
    assert all(tb.try_acquire() for _ in range(3))
    assert not tb.try_acquire()


def test_token_bucket_disabled():
    tb = TokenBucket(qps=0, burst=0, sleep=lambda s: (_ for _ in ()).throw(AssertionError))
    for _ in range(100):
        tb.acquire()
        assert tb.try_acquire()


def test_http_clientset_capped_under_resync_load():
    """Reference parity: many concurrent request verbs through the HTTP
    clientset cannot exceed burst + qps*t against the server."""
    backing = APIServer()
    server = serve_gateway(backing)
    host, port = server.server_address[:2]
    # tight limits so the test is fast: 20 qps / burst 5
    api = HTTPAPIServer(host, port, qps=20.0, burst=5)
    try:
        backing.create("PodGroup", {"metadata": {"name": "g", "namespace": "default"}})
        n_requests = 20
        t0 = time.monotonic()
        threads = [
            threading.Thread(target=lambda: api.get("PodGroup", "default", "g"))
            for _ in range(n_requests)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.monotonic() - t0
        # 5 burst + 15 paced at 20/s = at least ~0.75s; unthrottled this
        # loopback burst completes in well under 0.2s
        assert elapsed >= 0.6, elapsed
    finally:
        api.close()
        server.shutdown()
        server.server_close()


def test_token_bucket_rejects_unfillable_burst():
    import pytest

    with pytest.raises(ValueError):
        TokenBucket(qps=10.0, burst=0)
