"""Snapshot-lite, the event-sourced refresh, and device-derived columns
(ops.snapshot / ops.events / ops.device_state, docs/pipelining.md
"Snapshot-lite & event ingest"): the persistent-pack keyframe-reason
matrix, content-based churn detection (in-place GroupDemand mutation),
queue-order resorts, the EventLog producer/consumer contract, the
ClusterState emission invariant, pack_fold equivalence + idempotence,
and the scorer's fold-or-scan refresh with audit provenance — every
path held to bit-identity against the from-scratch construction."""

import numpy as np
import pytest

from batch_scheduler_tpu.framework.cluster import ClusterState
from batch_scheduler_tpu.ops.device_state import (
    DeviceStateHolder,
    device_derive_enabled,
)
from batch_scheduler_tpu.ops.events import (
    EventLog,
    event_fold_enabled,
    event_log_cap,
)
from batch_scheduler_tpu.ops.snapshot import (
    ClusterSnapshot,
    DeltaSnapshotPacker,
    GroupDemand,
    snapshot_lite_enabled,
)

from helpers import make_group, make_node, make_pod, status_for

_FIELDS = (
    "alloc", "requested", "group_req", "remaining", "min_member",
    "scheduled", "matched", "ineligible", "order", "creation_rank",
    "fit_mask", "group_valid", "node_valid",
)


def _world(n=8, g=4):
    nodes = [
        make_node(f"n{i:02d}", {"cpu": "16", "memory": "64Gi", "pods": "110"})
        for i in range(n)
    ]
    groups = [
        GroupDemand(
            full_name=f"default/gang-{i}",
            min_member=3,
            member_request={"cpu": 2000, "memory": 4 * 1024**3},
            creation_ts=float(i),
        )
        for i in range(g)
    ]
    node_req = {
        nd.metadata.name: {"cpu": 1000 * (i % 3), "pods": i % 4}
        for i, nd in enumerate(nodes)
    }
    return nodes, groups, node_req


def _assert_matches_full(snap, nodes, node_req, groups):
    """Every packed array bit-identical to a from-scratch construction."""
    fresh = ClusterSnapshot(nodes, node_req, groups)
    for f in _FIELDS:
        assert np.array_equal(
            np.asarray(getattr(snap, f)), np.asarray(getattr(fresh, f))
        ), f


# -- snapshot-lite pack paths ------------------------------------------------


def test_lite_zero_churn_pack_is_noop_delta():
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    snap = packer.pack(nodes, node_req, groups)
    assert snap.delta.kind == "keyframe"
    assert packer._lite is not None  # keyframe armed the lite state

    snap2 = packer.pack(nodes, node_req, groups)  # nothing changed
    assert snap2.delta.kind == "delta"
    assert snap2.delta.source == "scan"
    assert snap2.delta.node_rows.tolist() == []
    assert snap2.delta.group_rows.tolist() == []
    assert snap2.delta.meta_rows.tolist() == []
    assert packer.lite_packs == 1
    _assert_matches_full(snap2, nodes, node_req, groups)


def test_lite_keyframe_reason_matrix():
    """Every documented resync reason still fires under snapshot-lite,
    and each keyframe re-arms (or drops) the lite state coherently."""
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, node_req, groups)

    # group-set shrink: positional gang indices break
    shrunk = groups[:-1]
    snap = packer.pack(nodes, node_req, shrunk)
    assert (snap.delta.kind, snap.delta.reason) == ("keyframe", "group-set")
    assert packer._lite is not None
    _assert_matches_full(snap, nodes, node_req, shrunk)

    # node-list reorder: positional node indices break
    reordered = list(reversed(nodes))
    snap = packer.pack(reordered, node_req, shrunk)
    assert (snap.delta.kind, snap.delta.reason) == ("keyframe", "node-list")
    assert packer._lite is not None
    _assert_matches_full(snap, reordered, node_req, shrunk)

    # schema change on a churned node row: covers miss -> full resync
    node_req["n00"] = {"nvidia.com/gpu": 2}
    snap = packer.pack(reordered, node_req, shrunk)
    assert (snap.delta.kind, snap.delta.reason) == ("keyframe", "node-churn")
    _assert_matches_full(snap, reordered, node_req, shrunk)

    # schema change on a churned DEMAND row takes the same exit
    shrunk[0].member_request = {"example.com/widget": 1}
    snap = packer.pack(reordered, node_req, shrunk)
    assert (snap.delta.kind, snap.delta.reason) == ("keyframe", "node-churn")
    _assert_matches_full(snap, reordered, node_req, shrunk)


def test_lite_detects_in_place_group_mutation():
    """Regression: callers mutate GroupDemand objects IN PLACE between
    packs (the snapshot holds references, not copies) — churn detection
    must diff captured content, or the packed row goes silently stale."""
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, node_req, groups)

    groups[1].member_request = {"cpu": 3000}
    groups[2].scheduled = 2
    snap = packer.pack(nodes, node_req, groups)
    assert snap.delta.kind == "delta" and snap.delta.source == "scan"
    assert snap.delta.group_rows.tolist() == [1]
    _assert_matches_full(snap, nodes, node_req, groups)

    # the fingerprint advanced with the mutation: the next pack is a no-op
    snap2 = packer.pack(nodes, node_req, groups)
    assert snap2.delta.group_rows.tolist() == []
    _assert_matches_full(snap2, nodes, node_req, groups)


def test_lite_meta_churn_resorts_queue_order():
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, node_req, groups)
    resorts_before = packer.order_resorts

    groups[3].priority = 50  # jumps the queue
    groups[0].creation_ts = 99.5  # falls to the back of its band
    snap = packer.pack(nodes, node_req, groups)
    assert snap.delta.kind == "delta"
    assert sorted(snap.delta.meta_rows.tolist()) == [0, 3]
    assert packer.order_resorts == resorts_before + 1
    _assert_matches_full(snap, nodes, node_req, groups)

    # meta-quiet churn must NOT pay the resort
    groups[2].matched = 1
    packer.pack(nodes, node_req, groups)
    assert packer.order_resorts == resorts_before + 1


def test_lite_selector_appearance_drops_lite_and_stays_exact():
    """A selector breaks the uniform-fit invariant: the pack falls back
    to the full construction (rebuilding the per-group fit mask) and the
    lite state is dropped until the world is uniform again."""
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, node_req, groups)
    assert packer._lite is not None

    groups[0].node_selector = {"zone": "a"}
    nodes[0].metadata.labels = {"zone": "a"}
    snap = packer.pack(nodes, node_req, groups)
    assert packer._lite is None  # uniform-fit eligibility gone
    assert snap.fit_mask.shape[0] > 1  # per-group fit rows are back
    _assert_matches_full(snap, nodes, node_req, groups)


def test_lite_randomized_equivalence_sweep():
    """Mixed churn — node rows, demand rows (in-place), progress tails,
    sort keys — across rounds: every lite pack bit-identical to the
    from-scratch construction."""
    rng = np.random.RandomState(7)
    nodes, groups, node_req = _world(n=12, g=6)
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, node_req, groups)
    lite_rounds = 0
    for rnd in range(10):
        for _ in range(rng.randint(0, 3)):
            i = rng.randint(len(nodes))
            node_req[f"n{i:02d}"] = {
                "cpu": int(rng.randint(0, 8000)),
                "pods": int(rng.randint(0, 8)),
            }
        gi = rng.randint(len(groups))
        mode = rng.randint(4)
        if mode == 0:
            groups[gi].member_request = {"cpu": int(rng.randint(1, 5000))}
        elif mode == 1:
            groups[gi].scheduled = int(rng.randint(0, 3))
            groups[gi].matched = int(rng.randint(0, 2))
        elif mode == 2:
            groups[gi].priority = int(rng.randint(-5, 10))
        else:
            groups[gi].released = bool(rng.randint(2))
        snap = packer.pack(nodes, node_req, groups)
        _assert_matches_full(snap, nodes, node_req, groups)
        if snap.delta.kind == "delta":
            lite_rounds += 1
    assert lite_rounds == 10  # positionally-stable churn never keyframes


# -- pack_fold (the O(churn) event path) ------------------------------------


def test_pack_fold_matches_full_construction_and_is_idempotent():
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, node_req, groups)

    node_req["n05"] = {"cpu": 4321, "pods": 2}
    update = GroupDemand(
        full_name="default/gang-2",
        min_member=3,
        scheduled=1,
        member_request={"cpu": 2500},
        creation_ts=2.0,
    )
    groups2 = list(groups)
    groups2[2] = update
    snap = packer.pack_fold([("n05", node_req["n05"])], [update])
    assert snap is not None
    assert snap.delta.kind == "delta" and snap.delta.source == "events"
    assert snap.delta.node_rows.tolist() == [5]
    assert snap.delta.group_rows.tolist() == [2]
    _assert_matches_full(snap, nodes, node_req, groups2)

    # idempotent: updates carry current state, so a re-fold converges
    snap2 = packer.pack_fold([("n05", node_req["n05"])], [update])
    assert snap2 is not None
    assert snap2.delta.node_rows.tolist() == []
    assert snap2.delta.group_rows.tolist() == []
    _assert_matches_full(snap2, nodes, node_req, groups2)


def test_pack_fold_bails_to_none_never_guesses():
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()

    # no lite state yet: nothing to fold onto
    assert packer.pack_fold([("n00", {"cpu": 1})], []) is None
    packer.pack(nodes, node_req, groups)

    # unknown names cannot be folded positionally
    assert packer.pack_fold([("ghost", {"cpu": 1})], []) is None
    stranger = GroupDemand(
        full_name="default/stranger", min_member=1,
        member_request={"cpu": 1}, creation_ts=0.0,
    )
    assert packer.pack_fold([], [stranger]) is None

    # a row the cached schema cannot pack exactly forces the scan path
    assert packer.pack_fold([("n01", {"odd.io/lane": 3})], []) is None

    # every bail above was two-phase: the buffers are still exactly the
    # previous pack, so a follow-up scan pack emits a clean no-op delta
    snap = packer.pack(nodes, node_req, groups)
    assert snap.delta.kind == "delta"
    assert snap.delta.node_rows.tolist() == []
    _assert_matches_full(snap, nodes, node_req, groups)


def test_pack_fold_disabled_with_lite_off(monkeypatch):
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, node_req, groups)
    monkeypatch.setenv("BST_SNAPSHOT_LITE", "0")
    assert packer.pack_fold([("n00", {"cpu": 7})], []) is None


# -- EventLog ----------------------------------------------------------------


def test_event_log_coalesces_names_and_counts_bumps():
    log = EventLog(cap=64, label="t")
    for _ in range(3):
        log.note_bump("node-requested", ("n1",))
    log.note_bump("node-requested", ("n2",))
    log.note_group("default/g1")
    log.note_group("default/g1")
    assert log.depth() == 3  # n1, n2, default/g1 — coalesced

    batch = log.drain()
    assert batch.complete and not batch.empty
    assert batch.node_names == frozenset({"n1", "n2"})
    assert batch.group_names == frozenset({"default/g1"})
    assert batch.bumps == 4
    assert log.depth() == 0
    assert log.drain().empty  # drain resets everything


def test_event_log_blind_and_structural_break_completeness():
    log = EventLog(cap=64, label="t")
    log.note_blind()
    batch = log.drain()
    assert batch.blind and not batch.complete

    log.note_bump("node-object", ("n1",))
    batch = log.drain()
    assert batch.structural and not batch.complete
    assert batch.node_names == frozenset({"n1"})
    assert log.drain().complete  # flags cleared by the drain


def test_event_log_cap_overflow_degrades_to_scan():
    log = EventLog(cap=2, label="t")
    for i in range(4):
        log.note_bump("node-requested", (f"n{i}",))
    batch = log.drain()
    assert batch.overflow and not batch.complete
    assert len(batch.node_names) == 2  # bounded: the rest were dropped
    assert batch.bumps == 4  # bump accounting is NEVER dropped
    assert log.stats()["dropped"] >= 2
    assert log.drain().complete


def test_cluster_state_emission_invariant():
    """Every ClusterState version bump reaches subscribers as exactly one
    event — the equality the scorer's fold-completeness proof rests on."""
    cluster = ClusterState()
    log = EventLog(cap=256, label="t")
    cluster.subscribe_events(log.note_bump)

    base = cluster.version()
    n1 = make_node("e1", {"cpu": "8", "memory": "32Gi", "pods": "64"})
    n2 = make_node("e2", {"cpu": "8", "memory": "32Gi", "pods": "64"})
    cluster.add_node(n1)
    cluster.add_node(n2)
    p1 = make_pod("ep-1", requests={"cpu": "1"})
    p2 = make_pod("ep-2", requests={"cpu": "1"})
    cluster.assume(p1, "e1")
    cluster.assume_many([(p2, "e2")])
    cluster.forget(p1.metadata.uid)
    batch = log.drain()
    assert batch.bumps == cluster.version() - base
    assert batch.structural  # node adds moved the lane schema
    assert {"e1", "e2"} <= set(batch.node_names)

    # steady state: accounting-only churn keeps the batch fold-eligible
    base = cluster.version()
    cluster.assume(make_pod("ep-3", requests={"cpu": "2"}), "e1")
    batch = log.drain()
    assert batch.complete
    assert batch.bumps == cluster.version() - base
    assert batch.node_names == frozenset({"e1"})


# -- knobs -------------------------------------------------------------------


@pytest.mark.parametrize(
    "env,fn",
    [
        ("BST_SNAPSHOT_LITE", snapshot_lite_enabled),
        ("BST_EVENT_FOLD", event_fold_enabled),
        ("BST_DEVICE_DERIVE", device_derive_enabled),
    ],
)
def test_bool_knobs_parse_guard(monkeypatch, env, fn):
    monkeypatch.delenv(env, raising=False)
    assert fn() is True
    monkeypatch.setenv(env, "0")
    assert fn() is False
    monkeypatch.setenv(env, "off")
    assert fn() is False
    monkeypatch.setenv(env, "bananas")  # degrades to default, never raises
    assert fn() is True


def test_event_log_cap_knob_parse_guard(monkeypatch):
    monkeypatch.delenv("BST_EVENT_LOG_CAP", raising=False)
    assert event_log_cap() == 4096
    monkeypatch.setenv("BST_EVENT_LOG_CAP", "128")
    assert event_log_cap() == 128
    monkeypatch.setenv("BST_EVENT_LOG_CAP", "0")
    assert event_log_cap() == 1  # clamped: a zero cap would never fold
    monkeypatch.setenv("BST_EVENT_LOG_CAP", "lots")
    assert event_log_cap() == 4096


# -- device-derived columns --------------------------------------------------


def test_device_derive_off_matches_derived_columns(monkeypatch):
    """The device-derived fit/order columns must be byte-identical to the
    host-computed ones the derive-off path uploads — churn after churn."""
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    on_holder = DeviceStateHolder(label="derive-on")
    monkeypatch.setenv("BST_DEVICE_DERIVE", "0")
    off_holder = DeviceStateHolder(label="derive-off")
    monkeypatch.delenv("BST_DEVICE_DERIVE", raising=False)

    for rnd in range(3):
        node_req[f"n{rnd:02d}"] = {"cpu": 100 + rnd, "pods": 1}
        groups[rnd % len(groups)].priority = rnd  # forces order churn
        snap = packer.pack(nodes, node_req, groups)
        host_args = snap.device_args()
        on_args = on_holder.sync(snap)
        monkeypatch.setenv("BST_DEVICE_DERIVE", "0")
        off_args = off_holder.sync(snap)
        monkeypatch.delenv("BST_DEVICE_DERIVE", raising=False)
        for idx in (4, 6):  # fit_mask, order — the derived columns
            assert np.array_equal(
                np.asarray(on_args[idx]), np.asarray(host_args[idx])
            ), f"round {rnd} derived arg {idx} != host"
            assert np.array_equal(
                np.asarray(off_args[idx]), np.asarray(host_args[idx])
            ), f"round {rnd} uploaded arg {idx} != host"


# -- scorer integration: fold-or-scan refresh + audit provenance -------------


def _scorer_world():
    cluster = ClusterState()
    for i in range(10):
        cluster.add_node(
            make_node(f"s{i:02d}", {"cpu": "64", "memory": "256Gi",
                                    "pods": "110"})
        )
    from batch_scheduler_tpu.cache import PGStatusCache

    cache = PGStatusCache()
    for gi in range(6):
        pg = make_group(
            f"g{gi:02d}", 3, min_resources={"cpu": "2", "memory": "4Gi"},
            creation_ts=100.0 + gi,
        )
        status_for(pg, cache)
    return cluster, cache


def test_scorer_event_fold_refresh_end_to_end(tmp_path, monkeypatch):
    from batch_scheduler_tpu.core.oracle_scorer import OracleScorer
    from batch_scheduler_tpu.utils.audit import AuditLog, AuditReader
    from batch_scheduler_tpu.utils import audit as audit_mod

    cluster, cache = _scorer_world()
    log = AuditLog(str(tmp_path))
    scorer = OracleScorer(audit_log=log)
    scorer.ensure_fresh(cluster, cache)
    assert scorer.snapshot.delta.kind == "keyframe"

    # evented churn: the refresh must FOLD, not scan
    cluster.assume(
        make_pod("fx-0", group="g00", requests={"cpu": "2"}), "s03"
    )
    scorer.mark_dirty("default/g00")
    scorer.ensure_fresh(cluster, cache)
    snap = scorer.snapshot
    assert snap.delta.kind == "delta" and snap.delta.source == "events"
    assert snap.delta.node_rows.tolist() == [3]
    stats = scorer.stats()
    assert stats["fold_packs"] >= 1
    assert stats["event_log"]["drains"] >= 2

    # a blind mark forces the scan fallback on the next refresh
    cluster.assume(make_pod("fx-1", requests={"cpu": "4"}), "s05")
    scorer.mark_dirty()
    scorer.ensure_fresh(cluster, cache)
    assert scorer.snapshot.delta.source == "scan"

    # bit-compare contract: the folded scorer against a from-scratch
    # scorer with every stage-3 knob off (PR 11 behaviour)
    d_fold = audit_mod.plan_digest(scorer._state.result)
    monkeypatch.setenv("BST_SNAPSHOT_LITE", "0")
    monkeypatch.setenv("BST_EVENT_FOLD", "0")
    monkeypatch.setenv("BST_DEVICE_DERIVE", "0")
    legacy = OracleScorer()
    legacy.ensure_fresh(cluster, cache)
    assert audit_mod.plan_digest(legacy._state.result) == d_fold

    # audit provenance: replayable records name the refresh path
    assert log.flush()
    batches, skipped = AuditReader(str(tmp_path)).batches()
    assert not skipped and len(batches) >= 3
    refreshes = [rec.get("refresh") for rec in batches]
    assert refreshes[0] and refreshes[0]["kind"] == "keyframe"
    sources = {r["source"] for r in refreshes if r}
    assert "events" in sources and "scan" in sources
    for rec in batches:
        assert rec["refresh"]["generation"] >= 1
    log.stop()


def test_scorer_fold_falls_back_on_unhooked_mutation():
    """A version bump with no matching event (simulating a mutation that
    bypassed the hooks) must break the completeness equality and scan."""
    from batch_scheduler_tpu.core.oracle_scorer import OracleScorer

    cluster, cache = _scorer_world()
    scorer = OracleScorer()
    scorer.ensure_fresh(cluster, cache)
    # fold once so the version baseline is armed
    cluster.assume(make_pod("vx-0", requests={"cpu": "1"}), "s01")
    scorer.mark_dirty("default/g01")
    scorer.ensure_fresh(cluster, cache)
    assert scorer.snapshot.delta.source == "events"

    # skew: bump the version behind the log's back
    with cluster._lock:
        cluster._version += 1
    scorer.mark_dirty("default/g01")
    scorer.ensure_fresh(cluster, cache)
    assert scorer.snapshot.delta.source == "scan"  # never a stale fold
