"""Whole-gang fast lane (gang-granular release+bind): a gang whose batch
plan covers the quorum and whose members are all queued is admitted as ONE
transaction — no permit parking, one batched bind, one status patch.
Reference precedent for gang-unit choreography: StartBatchSchedule
(reference pkg/scheduler/batch/batchscheduler.go:254-344)."""

import pytest

from batch_scheduler_tpu.api import PodGroupPhase
from batch_scheduler_tpu.client.apiserver import APIServer, AlreadyExistsError
from batch_scheduler_tpu.client.clientset import Clientset
from batch_scheduler_tpu.framework.types import PodInfo
from batch_scheduler_tpu.sim import (
    SimCluster,
    make_member_pods,
    make_sim_group,
    make_sim_node,
)

from helpers import make_pod


@pytest.fixture
def sim(request):
    clusters = []

    def build(**kwargs):
        c = SimCluster(**kwargs)
        clusters.append(c)
        return c

    yield build
    for c in clusters:
        c.stop()


def test_whole_gang_admitted_without_permit_waits(sim):
    """A fully-queued gang rides the fast lane: all members bind, the gang
    reaches Scheduled, and NOTHING parks in a Permit wait."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes(
        [make_sim_node(f"n{i}", {"cpu": "16", "pods": "64"}) for i in range(3)]
    )
    pg = make_sim_group("fast", 6)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    cluster.create_pods(make_member_pods("fast", 6, {"cpu": "1"}))
    assert cluster.wait_for_bound("fast", 6, timeout=20.0), (
        cluster.scheduler.stats
    )
    assert cluster.wait_for_group_phase(
        "fast", (PodGroupPhase.SCHEDULED, PodGroupPhase.RUNNING), timeout=10.0
    )
    stats = cluster.scheduler.stats
    assert stats["permit_waits"] == 0, stats
    assert stats["binds"] == 6


def test_partial_arrival_falls_back_to_permit_waits(sim):
    """Members arriving over time park via Permit waits (per-pod path) and
    the gang still completes when the quorum lands — fast-lane eligibility
    must not break incremental arrival."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("slow", 4)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    first = make_member_pods("slow", 4, {"cpu": "1"})
    cluster.create_pods(first[:2])
    # the two early members must park (gang incomplete)
    assert cluster.wait_for(
        lambda: cluster.scheduler.stats["permit_waits"] >= 2, timeout=10.0
    ), cluster.scheduler.stats
    cluster.create_pods(first[2:])
    assert cluster.wait_for_bound("slow", 4, timeout=20.0), (
        cluster.scheduler.stats
    )


def test_gang_plan_eligibility_gating(sim):
    """gang_plan is None for: serial mode, unknown groups, released gangs,
    and gangs with matched members (waiting pods) — each falls back to the
    per-pod path."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("gate", 2)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    op = cluster.runtime.operation
    pod = make_member_pods("gate", 2, {"cpu": "1"})[0]

    # no plan stamped yet
    assert op.gang_plan(pod) is None
    # stamp a plan via pre_filter
    op.pre_filter(pod)
    plan = op.gang_plan(pod)
    assert plan is not None
    slots, needed = plan
    assert needed == 2 and sum(slots.values()) >= 2
    # a matched (waiting) member disqualifies the whole-gang transaction
    pgs = op.status_cache.get("default/gate")
    outcome = op.permit(pod, "n1")
    assert not outcome.ready
    assert op.gang_plan(pod) is None
    # released gangs are ineligible too
    pgs.matched_pod_nodes.flush()
    pgs.scheduled = True
    assert op.gang_plan(pod) is None

    # non-group pods never have a plan
    assert op.gang_plan(make_pod("solo")) is None


def test_post_bind_gang_single_patch_transitions_to_scheduled(sim):
    """post_bind_gang applies ONE status transition for the whole gang:
    scheduled count jumps by the quorum and the phase lands on Scheduled
    (partial counts land on Scheduling)."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("unit", 4)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    op = cluster.runtime.operation
    op.post_bind_gang("default/unit", 3)
    live = cluster.group("unit")
    assert live.status.scheduled == 3
    assert live.status.phase == PodGroupPhase.SCHEDULING
    op.post_bind_gang("default/unit", 1)
    live = cluster.group("unit")
    assert live.status.scheduled == 4
    assert live.status.phase == PodGroupPhase.SCHEDULED
    assert live.status.schedule_start_time > 0


def test_bind_many_skips_missing_and_binds_rest():
    api = APIServer()
    cs = Clientset(api)
    for name in ("a", "b"):
        cs.pods().create(make_pod(name))
    bound = cs.pods().bind_many([("a", "n1"), ("ghost", "n1"), ("b", "n2")])
    assert bound == ["a", "b"]
    assert cs.pods().get("a").spec.node_name == "n1"
    assert cs.pods().get("b").spec.node_name == "n2"


def test_create_many_all_or_nothing_on_existing_names():
    api = APIServer()
    cs = Clientset(api)
    cs.pods().create(make_pod("dup"))
    import batch_scheduler_tpu.api.types as t

    with pytest.raises(AlreadyExistsError):
        api.create_many(
            "Pod", [t.to_dict(make_pod("fresh")), t.to_dict(make_pod("dup"))]
        )
    # nothing from the failed batch committed
    import batch_scheduler_tpu.client.apiserver as a

    with pytest.raises(a.NotFoundError):
        api.get("Pod", "default", "fresh")
    assert api.create_many("Pod", [t.to_dict(make_pod("fresh"))]) == 1


def test_sort_key_orders_like_compare(sim):
    """The precomputed queue key must rank pods exactly as the Compare
    chain (reference core.go:368-411): priority desc, non-gang first,
    group creation asc, group name REVERSE-lex, timestamp asc."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    for name, ts in (("alpha", 5.0), ("beta", 5.0), ("gamma", 1.0)):
        pg = make_sim_group(name, 1, creation_ts=ts)
        pg.spec.min_resources = {"cpu": 1000}
        cluster.create_group(pg)
    cluster.start()
    op = cluster.runtime.operation

    def info_for(pod, ts):
        return PodInfo(pod=pod, timestamp=ts)

    hi = info_for(make_pod("hi", group="alpha", priority=9), 4.0)
    solo = info_for(make_pod("solo", priority=0), 3.0)
    early = info_for(make_pod("e", group="gamma"), 2.0)
    a_pod = info_for(make_pod("a", group="alpha"), 2.0)
    b_pod = info_for(make_pod("b", group="beta"), 1.0)
    a_late = info_for(make_pod("a2", group="alpha"), 9.0)

    # expected: hi (prio) < solo (non-gang) < early (created 1.0)
    #           < b (reverse-lex beta>alpha) < a < a_late (timestamp)
    expected = [hi, solo, early, b_pod, a_pod, a_late]
    keyed = sorted(expected[::-1], key=op.sort_key)
    assert [i.name for i in keyed] == [i.name for i in expected]
    # spot-check agreement with the comparator form on every ordered pair
    for x in expected:
        for y in expected:
            if x is y:
                continue
            lt = op.compare(x.pod, x.timestamp, y.pod, y.timestamp)
            if lt:
                assert op.sort_key(x) < op.sort_key(y), (x.name, y.name)


def test_creation_cache_invalidated_on_group_delete(sim):
    """A group deleted and recreated under the same name must sort by its
    NEW creation timestamp (the sort-key cache dies with the group)."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("reborn", 1, creation_ts=100.0)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    op = cluster.runtime.operation
    info = PodInfo(pod=make_pod("p1", group="reborn"), timestamp=0.0)
    assert op.sort_key(info)[2] == 100.0
    op.status_cache.delete("default/reborn")
    assert ("default", "reborn") not in op._creation_cache


def test_flush_rolls_back_to_queue_on_bind_transport_failure(sim):
    """A transport error during the commit flush must not lose the gang:
    assumed capacity releases and every member returns to the queue (the
    gateway-restart e2e's failure mode, unit form)."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("fragile", 3)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    sched = cluster.scheduler

    # break the bind path AFTER startup
    orig = cluster.api.bind_pods
    calls = {"n": 0}

    def broken(ns, pairs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionError("simulated outage")
        return orig(ns, pairs)

    cluster.api.bind_pods = broken
    cluster.create_pods(make_member_pods("fragile", 3, {"cpu": "1"}))
    # first flush fails -> rollback -> backoff retry; the gang is already
    # marked released, so recovery may ride either the fast lane or the
    # per-pod permit path — what matters is that every member binds
    assert cluster.wait_for_bound("fragile", 3, timeout=20.0), (
        cluster.scheduler.stats,
        calls,
    )
    assert calls["n"] >= 1
    # capacity accounting stayed square: one gang's worth charged
    req = cluster.cluster.node_requested("n1")
    assert req.get("pods", 0) == 3, req


def test_flush_partial_namespace_failure_policy():
    """ADVICE r4 (medium), unit form: a bind_many exception mid-flush is
    AMBIGUOUS (the request may have applied with only the response lost),
    so the failed namespace's members KEEP their assumed capacity —
    mirroring the per-pod bind worker — while namespaces never attempted
    roll back fully, and namespaces whose bind_many already returned go
    through the normal finish + post_bind_gangs path."""
    from batch_scheduler_tpu.framework.cluster import ClusterState
    from batch_scheduler_tpu.framework.scheduler import Scheduler
    from helpers import make_node

    api = APIServer()
    cs = Clientset(api)
    cluster = ClusterState()
    cluster.add_node(make_node("n1", {"cpu": "64", "pods": "64"}))

    class Plugin:
        def __init__(self):
            self.posted = []
            self.dirty = 0

        less = None

        def mark_dirty(self):
            self.dirty += 1

        def post_bind_gangs(self, items):
            self.posted.extend(items)

    plugin = Plugin()
    sched = Scheduler(cs, cluster, plugin=plugin)

    buf_entries = []
    for ns, gang in (("nsa", "ga"), ("nsb", "gb"), ("nsc", "gc")):
        assigned = []
        for i in range(2):
            p = make_pod(f"{gang}-{i}", group=gang, namespace=ns,
                         requests={"cpu": "1"})
            cs.pods(ns).create(p)
            cluster.assume(p, "n1")
            assigned.append((PodInfo(pod=p), p, "n1"))
        buf_entries.append((f"{ns}/{gang}", ns, assigned))
    sched._gang_buffer = list(buf_entries)

    orig = api.bind_pods

    def broken(ns, pairs):
        if ns == "nsb":
            raise ConnectionError("simulated outage")
        return orig(ns, pairs)

    api.bind_pods = broken
    sched._flush_gangs()

    # nsa (bound before the failure): members finished binding, capacity
    # charged as bound, gang went through post_bind_gangs
    assert ("nsa/ga", 2) in plugin.posted
    assert all(g != "nsb/gb" and g != "nsc/gc" for g, _ in plugin.posted)
    for _, p, _ in buf_entries[0][2]:
        assert not cluster.is_assumed(p.metadata.uid)  # promoted to bound
    assert cs.pods("nsa").get("ga-0").spec.node_name == "n1"

    # nsb (the ambiguous failure): assumes KEPT, members requeued
    for _, p, _ in buf_entries[1][2]:
        assert cluster.is_assumed(p.metadata.uid)
    # nsc (never attempted): assumes released, members requeued
    for _, p, _ in buf_entries[2][2]:
        assert not cluster.is_assumed(p.metadata.uid)
    assert plugin.dirty >= 1
    # all four non-bound members are back in the queue (backoff)
    assert len(sched.queue) == 4
    # total capacity charge: nsa bound (2 pods) + nsb kept assumes (2 pods)
    assert cluster.node_requested("n1").get("pods", 0) == 4


def test_kept_assume_does_not_livelock_tight_node(sim):
    """The ambiguous-failure keep-capacity policy must not let a gang
    livelock against its OWN ghost reservations: a gang that exactly
    fills a node fails its first flush (kept assumes saturate the node),
    and the retry must still bind — the fresh liveness read resolves the
    ambiguity and releases the stale assume before planning."""
    cluster = sim(scorer="oracle")
    # node sized EXACTLY for the gang: 3 cpu, 3 pod slots
    cluster.add_nodes([make_sim_node("n1", {"cpu": "3", "pods": "3"})])
    pg = make_sim_group("tight", 3)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()

    orig = cluster.api.bind_pods
    calls = {"n": 0}

    def broken(ns, pairs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionError("simulated outage")
        return orig(ns, pairs)

    cluster.api.bind_pods = broken
    cluster.create_pods(make_member_pods("tight", 3, {"cpu": "1"}))
    assert cluster.wait_for_bound("tight", 3, timeout=30.0), (
        cluster.scheduler.stats,
        calls,
        cluster.cluster.node_requested("n1"),
    )
    # accounting squared: exactly one gang's worth charged
    req = cluster.cluster.node_requested("n1")
    assert req.get("pods", 0) == 3, req


def test_duplicate_queue_entry_keeps_parked_pod_reservation(sim):
    """A watch-replay duplicate queue entry for a permit-PARKED pod (which
    is assumed — its reservation is live) must not release that charge:
    the ghost-release at pop time is gated on the ambiguous-failure
    marker, not on is_assumed alone."""
    import time as _time

    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("parked", 4)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    pods = make_member_pods("parked", 4, {"cpu": "1"})
    cluster.create_pods(pods[:2])
    assert cluster.wait_for(
        lambda: cluster.scheduler.stats["permit_waits"] >= 2, timeout=10.0
    ), cluster.scheduler.stats
    parked_uid = pods[0].metadata.uid
    assert cluster.cluster.is_assumed(parked_uid)
    # replayed ADDED event: duplicate entry for the parked (unbound) pod
    cluster.scheduler.queue.push(PodInfo(pod=pods[0]))
    _time.sleep(0.5)  # let the duplicate pop and run a cycle
    assert cluster.cluster.is_assumed(parked_uid), (
        "duplicate entry released a parked pod's live reservation"
    )
    # the gang still completes when the rest arrive
    cluster.create_pods(pods[2:])
    assert cluster.wait_for_bound("parked", 4, timeout=20.0), (
        cluster.scheduler.stats
    )


def test_raced_kept_marker_spares_parked_owner(sim):
    """A _kept_assumes marker that lands AFTER a duplicate entry re-parked
    the pod (bind-worker failure racing a watch replay) must not release
    the new owner's live reservation: the forget is gated on
    _assume_owned, not the marker alone."""
    import time as _time

    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("raced", 4)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    pods = make_member_pods("raced", 4, {"cpu": "1"})
    cluster.create_pods(pods[:2])
    assert cluster.wait_for(
        lambda: cluster.scheduler.stats["permit_waits"] >= 2, timeout=10.0
    ), cluster.scheduler.stats
    uid = pods[0].metadata.uid
    assert cluster.cluster.is_assumed(uid)
    assert cluster.scheduler.waiting.get(uid) is not None
    # simulate the race: a stale ambiguous-failure marker exists for a
    # pod whose assume is now owned by a parked WaitingPod
    cluster.scheduler._kept_assumes.add(uid)
    cluster.scheduler.queue.push(PodInfo(pod=pods[0]))
    _time.sleep(0.5)
    assert cluster.cluster.is_assumed(uid), (
        "raced marker released a parked owner's reservation"
    )
    cluster.create_pods(pods[2:])
    assert cluster.wait_for_bound("raced", 4, timeout=20.0), (
        cluster.scheduler.stats
    )


def test_gang_transaction_partial_bind_missing_pod(sim):
    """A member deleted between seat and flush: bind_many skips it, the
    gang lands partially (Scheduling), and the recreated member completes
    it through the per-pod path."""
    cluster = sim(scorer="oracle")
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("gappy", 3)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()

    pods = make_member_pods("gappy", 3, {"cpu": "1"})
    orig = cluster.api.bind_pods

    def drop_one(ns, pairs):
        cluster.api.bind_pods = orig
        # delete a seated member right before the bind commits
        try:
            cluster.clientset.pods().delete(pods[2].metadata.name)
        except Exception:
            pass
        return orig(ns, pairs)

    cluster.api.bind_pods = drop_one
    cluster.create_pods(pods)
    assert cluster.wait_for(
        lambda: cluster.scheduler.stats["binds"] >= 2, timeout=20.0
    ), cluster.scheduler.stats
    # recreate the missing member: the released gang admits it per-pod
    import dataclasses

    from batch_scheduler_tpu.api.types import new_uid

    replacement = make_member_pods("gappy", 3, {"cpu": "1"})[2]
    replacement.metadata.uid = new_uid("pod")
    cluster.create_pods([replacement])
    assert cluster.wait_for_bound("gappy", 3, timeout=20.0), (
        cluster.scheduler.stats
    )


def test_members_beyond_min_member_bind_after_fast_lane(sim):
    """A gang with MORE queued members than min_member: the fast lane
    seats the quorum and the extras must still bind (beyond-quorum
    members schedule like ordinary pods once the gang is released — the
    reference strands them in a park/TTL-abort loop instead,
    batchscheduler.go:258-262; fixed, not copied)."""
    cluster = sim(scorer="oracle", max_schedule_minutes=0.05)
    cluster.add_nodes([make_sim_node("n1", {"cpu": "16", "pods": "64"})])
    pg = make_sim_group("plus", 3)
    pg.spec.min_resources = {"cpu": 1000}
    cluster.create_group(pg)
    cluster.start()
    cluster.create_pods(make_member_pods("plus", 4, {"cpu": "1"}))
    assert cluster.wait_for_bound("plus", 4, timeout=20.0), (
        cluster.scheduler.stats,
        cluster.member_phase_counts("plus"),
    )
