"""Churn re-scoring tests (BASELINE config 5 semantics): bucketed jit-cache
stability across ticks, pinned lane schema, and backfill — freed capacity is
re-offered to previously infeasible gangs on the next tick."""

import numpy as np
import pytest

from batch_scheduler_tpu.ops.rescore import ChurnRescorer
from batch_scheduler_tpu.ops.snapshot import GroupDemand
from batch_scheduler_tpu.sim.scenarios import make_sim_node


def _nodes(n, cpu="8"):
    return [
        make_sim_node(f"n{i:03d}", {"cpu": cpu, "memory": "32Gi", "pods": "110"})
        for i in range(n)
    ]


def _gang(name, members, cpu_milli=1000, ts=0.0):
    return GroupDemand(
        full_name=f"default/{name}",
        min_member=members,
        member_request={"cpu": cpu_milli},
        creation_ts=ts,
        has_pod=True,
    )


def test_steady_churn_hits_one_bucket_shape():
    """Group counts varying inside one bucket never change padded shapes, so
    only the first tick can compile."""
    r = ChurnRescorer(_nodes(12))  # 12 nodes -> node bucket 16
    for tick_no, g in enumerate([3, 5, 8, 6, 4, 7, 2, 8]):  # all <= bucket 8
        groups = [_gang(f"g{tick_no}-{i}", 2, ts=float(i)) for i in range(g)]
        r.tick({}, groups)
    assert r.recompiles == 1, r.summary()
    assert len(r._shapes_seen) == 1


def test_bucket_boundary_crossing_is_counted():
    r = ChurnRescorer(_nodes(4))
    r.tick({}, [_gang("a", 2)])  # 1 group -> bucket 8
    r.tick({}, [_gang(f"g{i}", 2, ts=float(i)) for i in range(9)])  # -> bucket 16
    assert r.recompiles == 2


def test_pinned_schema_keeps_shape_when_resource_appears():
    """An extended resource declared up front doesn't change R when it shows
    up mid-loop; an undeclared one fails loudly instead of silently
    reshaping."""
    gpu = "nvidia.com/gpu"
    r = ChurnRescorer(_nodes(4), extra_resources=[gpu])
    t1 = r.tick({}, [_gang("plain", 2)])
    g = _gang("gpu-gang", 2)
    g.member_request[gpu] = 1
    t2 = r.tick({}, [g])
    assert t1.bucket_shape == t2.bucket_shape
    assert r.recompiles == 1

    bad = _gang("bad", 2)
    bad.member_request["vendor.example/fpga"] = 1
    with pytest.raises(KeyError):
        r.tick({}, [bad])


def test_backfill_after_capacity_freed():
    """Config-5 churn semantics: a gang denied for capacity gets placed on a
    later tick once a running gang completes and frees its nodes."""
    nodes = _nodes(4, cpu="4")  # 16 cpus total
    r = ChurnRescorer(nodes)

    running = [_gang("running", 12, ts=0.0)]  # 12 cpus committed
    requested = {n.metadata.name: {"cpu": 3000, "pods": 3} for n in nodes}

    # while `running` occupies the cluster, a 10-cpu gang cannot place
    waiting = _gang("waiting", 10, ts=1.0)
    out = r.tick(requested, [waiting])
    assert "default/waiting" not in out.placed_groups()

    # the running gang finishes -> its requested capacity is freed
    out2 = r.tick({}, [waiting])
    assert out2.placed_groups() == ["default/waiting"]
    # same bucket both ticks: the backfill came from data, not a recompile
    assert r.recompiles == 1


def test_dense_state_guards():
    """admit/release bookkeeping: snapshots don't alias the mutable
    occupancy array, and a nodes override can't silently drop it."""
    nodes = _nodes(8, cpu="4")  # power-of-two node count: no pad copy
    r = ChurnRescorer(nodes)
    gang = _gang("g", 4)
    out = r.tick(None, [gang])
    before = out.snapshot.requested.copy()
    r.admit(out, "default/g")
    assert (out.snapshot.requested == before).all()  # not corrupted by admit
    assert r.requested_lanes.sum() > 0
    r.release("default/g")
    assert r.requested_lanes.sum() == 0

    with pytest.raises(ValueError, match="node_requested"):
        r.tick(None, [gang], nodes=_nodes(6))


def test_latency_summary_shape():
    r = ChurnRescorer(_nodes(4))
    for i in range(5):
        r.tick({}, [_gang(f"g{i}", 2)])
    s = r.summary()
    assert s["ticks"] == 5
    assert s["p50_s"] > 0 and s["p95_s"] >= s["p50_s"]
    assert s["recompiles"] == 1
    assert s["p50_collect_s"] >= 0 and s["p50_dispatch_s"] >= 0


def test_pipelined_ticks_match_sync_ticks():
    """A one-tick-deep pipelined loop (dispatch, churn host state, collect)
    admits exactly what the synchronous loop admits when driven through the
    same state sequence — the result reflects occupancy AT DISPATCH, and
    releases between dispatch and collect only add slack."""
    spec = dict(cpu="4")

    def drive(pipelined: bool):
        r = ChurnRescorer(_nodes(4, **spec))  # 16 cpus
        filler = _gang("filler", 12, ts=0.0)
        out = r.tick(None, [filler])
        r.admit(out, "default/filler")
        admitted = []
        pending = [_gang("w1", 10, ts=1.0), _gang("w2", 2, ts=2.0)]
        inflight = list(pending)
        pend = r.tick_dispatch(None, inflight) if pipelined else None
        for _ in range(3):
            if pipelined:
                out = r.tick_collect(pend)
            else:
                inflight = list(pending)
                out = r.tick(None, inflight)
            placed = set(out.placed_groups())
            for g in inflight:
                if g.full_name in placed:
                    r.admit(out, g.full_name)
                    admitted.append(g.full_name)
            pending = [g for g in pending if g.full_name not in placed]
            # churn event: the filler finishes after the first tick
            if "default/filler" in r.running:
                r.release("default/filler")
            if pipelined:
                inflight = list(pending)
                pend = r.tick_dispatch(None, inflight)
        if pipelined:
            r.tick_collect(pend)
        return admitted

    sync_admitted = drive(pipelined=False)
    pipe_admitted = drive(pipelined=True)
    # w2 (2 cpus) fits immediately; w1 (10 cpus) fits only after the filler
    # releases — the pipelined loop sees that one tick later but admits the
    # same set overall
    assert set(sync_admitted) == set(pipe_admitted) == {
        "default/w1", "default/w2",
    }


def test_pipelined_stats_recorded_per_collect():
    r = ChurnRescorer(_nodes(4))
    pend = r.tick_dispatch(None, [_gang("a", 2)])
    assert r.latencies == []  # dispatch alone records nothing
    r.tick_collect(pend)
    assert len(r.latencies) == 1
    assert len(r.dispatch_times) == len(r.collect_times) == 1


def test_device_resident_occupancy_matches_reupload():
    """The delta-scatter device occupancy path must be indistinguishable
    from re-uploading the numpy mirror every tick, across admits, releases,
    and arrivals (the mirror is ground truth; the device copy is an
    optimization for the host link)."""
    nodes = _nodes(6, cpu="8")

    def drive(force_reupload):
        r = ChurnRescorer(nodes)
        placed_seq = []
        pending = [_gang(f"g{i}", 3, ts=float(i)) for i in range(6)]
        for t in range(6):
            if force_reupload:
                r._req_dev = None
                r._req_deltas.clear()  # mirror is ground truth
            out = r.tick(None, pending)
            placed = sorted(out.placed_groups())
            for g in list(pending):
                if g.full_name in placed:
                    r.admit(out, g.full_name)
                    pending.remove(g)
            placed_seq.append(placed)
            if t == 2 and r.running:
                r.release(sorted(r.running)[0])
                pending.append(_gang(f"h{t}", 2, ts=10.0 + t))
        return placed_seq, r.requested_lanes.copy()

    seq_dev, mirror_dev = drive(force_reupload=False)
    seq_up, mirror_up = drive(force_reupload=True)
    assert seq_dev == seq_up
    np.testing.assert_array_equal(mirror_dev, mirror_up)


def test_device_occupancy_resyncs_after_failure():
    """A failed delta application drops the device copy; the next tick
    re-uploads the mirror and still scores correctly."""
    nodes = _nodes(4, cpu="4")
    r = ChurnRescorer(nodes)
    out = r.tick(None, [_gang("a", 4)])
    r.admit(out, "default/a")
    # poison the queued delta so the scatter path raises
    r._req_deltas.append(("not-an-array", "nope"))
    with pytest.raises(Exception):
        r.tick(None, [_gang("b", 2, ts=1.0)])
    assert r._req_dev is None and r._req_deltas == []
    # recovery: mirror re-uploads; capacity math reflects the admit
    out2 = r.tick(None, [_gang("big", 16, ts=2.0), _gang("small", 2, ts=3.0)])
    placed = out2.placed_groups()
    assert "default/big" not in placed  # 16 cpus no longer free (4 admitted)
    assert "default/small" in placed


def test_sticky_buckets_pin_shapes_across_boundaries():
    """sticky_buckets=True: once a bucket is visited, smaller ticks keep the
    pinned (larger) padded shape — oscillating across a boundary compiles
    once, and results are unaffected by the extra padding."""
    r_sticky = ChurnRescorer(_nodes(4), sticky_buckets=True)
    r_plain = ChurnRescorer(_nodes(4))

    big = [_gang(f"b{i}", 1, ts=float(i)) for i in range(9)]  # bucket 16
    small = [_gang("s0", 2, ts=100.0)]  # bucket 8 unpinned

    for r in (r_sticky, r_plain):
        r.tick(None, list(big))
        r.tick(None, list(small))
        r.tick(None, list(big))

    # plain: 8-bucket and 16-bucket are distinct signatures
    assert r_plain.recompiles == 2
    # sticky: the small tick reuses the pinned 16-bucket shape
    assert r_sticky.recompiles == 1
    shapes = {s[0] for s in r_sticky._shapes_seen}
    assert shapes == {16}
    # same scheduling outcome regardless of padding mode
    out_sticky = r_sticky.tick(None, list(small))
    out_plain = r_plain.tick(None, list(small))
    assert out_sticky.placed_groups() == out_plain.placed_groups()


def test_admit_verified_depth2_contention():
    """Pipelines deeper than one tick break admit()'s capacity-only-grows
    contract: a newer in-flight batch predates the older one's admissions,
    so its plan can seat a gang on capacity that is now taken.
    admit_verified() is the host-side re-verify that restores safety:
    the stale overlapping placement is skipped with a clean rollback, a
    double-offered gang commits exactly once, and the skipped gang places
    on a fresh dispatch once capacity frees."""
    nodes = _nodes(4, cpu="4")  # 16 cpus
    r = ChurnRescorer(nodes)
    x, y = _gang("x", 10, ts=0.0), _gang("y", 10, ts=1.0)

    # two dispatches in flight against the SAME empty-cluster occupancy
    p1 = r.tick_dispatch(None, [x])
    p2 = r.tick_dispatch(None, [y])

    out1 = r.tick_collect(p1)
    assert r.admit_verified(out1, "default/x") is True
    assert r.admit_verified(out1, "default/x") is False  # dup offer: no-op

    out2 = r.tick_collect(p2)
    # the stale plan DID place y (10 free cpus at dispatch)...
    assert "default/y" in out2.placed_groups()
    # ...but only 6 remain now: any 10-cpu seating must oversubscribe
    before = r.requested_lanes.copy()
    assert r.admit_verified(out2, "default/y") is False
    assert (r.requested_lanes == before).all()  # rollback left no charge
    assert r.running == ["default/x"]

    # skipped gangs stay pending and place on a CURRENT-state dispatch
    r.release("default/x")
    out3 = r.tick(None, [y])
    assert r.admit_verified(out3, "default/y") is True
    assert r.running == ["default/y"]


def test_concurrent_dispatch_admit_consistency():
    """Pins the depth-k race the state lock closes: dispatches running on a
    helper thread while the loop thread admits/releases must never lose a
    queued occupancy delta (a delta appended between the drain's
    concatenate and clear() used to vanish, silently understating device
    occupancy forever after). Invariant checked: after every round, the
    occupancy mirror equals the sum of running gangs' charges, and a
    final fresh tick places against exactly that state."""
    from concurrent.futures import ThreadPoolExecutor

    nodes = _nodes(8, cpu="8")  # 64 cpus
    r = ChurnRescorer(nodes)
    rng = np.random.default_rng(7)
    with ThreadPoolExecutor(max_workers=1) as pool:
        out = r.tick(None, [_gang("seed", 2)])
        r.admit(out, "default/seed")
        for round_i in range(20):
            gangs = [
                _gang(f"r{round_i}-{j}", int(rng.integers(1, 4)), ts=float(j))
                for j in range(4)
            ]
            fut = pool.submit(r.tick_dispatch, None, gangs)
            # interleave with the in-flight dispatch's pack/drain window
            for g in list(r.running):
                if rng.random() < 0.3 and g != "default/seed":
                    r.release(g)
            out = r.tick_collect(fut.result())
            for g in gangs:
                if g.full_name in out.placed_groups():
                    r.admit_verified(out, g.full_name)
            with r._state_lock:  # the lockcheck sweep: read guarded state guarded
                lanes = r.requested_lanes.copy()
                running = dict(r._running)
            expect = np.zeros_like(lanes)
            for idx, update in running.values():
                np.add.at(expect, idx, update)
            assert (lanes == expect).all(), (
                f"occupancy mirror diverged from running charges at "
                f"round {round_i}"
            )
    # the device-resident copy saw every delta too: a fresh tick scored
    # against it must agree with a from-scratch pack of the mirror
    probe = _gang("probe", 60, ts=999.0)  # needs most of the cluster
    out_dev = r.tick(None, [probe])
    r2 = ChurnRescorer(nodes)
    with r._state_lock:  # guarded state, read guarded (lockcheck)
        lanes_snapshot = r.requested_lanes.copy()
    out_ref = r2.tick(
        {
            n.metadata.name: {
                res: int(v)
                for res, v in zip(r.schema.names, lanes_snapshot[i])
                if v
            }
            for i, n in enumerate(nodes)
        },
        [probe],
    )
    assert out_dev.placed_groups() == out_ref.placed_groups()


def test_pipeline_depth_hides_simulated_link_rtt(monkeypatch):
    """CPU-reproducible proof of the depth mechanism behind the r05 TPU
    churn miss (LADDER_r05_tpu config 5: ~200ms tunnel RTT vs a one-tick
    pipeline): with an injected 200ms dispatch->collect latency, a
    depth-1 loop blocks ~RTT-interval inside every tick and misses the
    100ms budget, while a depth-2 loop of the SAME code absorbs the link
    into two intervals and holds it. Runs the ladder config-5 loop shape
    in miniature: same-prefix windows, whole-batch verified admits."""
    import time

    import batch_scheduler_tpu.ops.rescore as rs

    RTT, INTERVAL, TICKS, WINDOW = 0.2, 0.1, 6, 8

    stamps = {}
    real_dispatch, real_collect = rs.dispatch_batch, rs.collect_batch

    def slow_dispatch(args, pargs):
        p = real_dispatch(args, pargs)
        stamps[id(p)] = time.perf_counter()
        return p

    def slow_collect(p):
        # the result "arrives" RTT after dispatch, however fast the
        # backend actually was — the tunnel's behavior, minus the tunnel
        dt = time.perf_counter() - stamps.pop(id(p))
        if dt < RTT:
            time.sleep(RTT - dt)
        return real_collect(p)

    monkeypatch.setattr(rs, "dispatch_batch", slow_dispatch)
    monkeypatch.setattr(rs, "collect_batch", slow_collect)

    def drive(depth):
        from batch_scheduler_tpu.ops.rescore import TickPipeline

        r = ChurnRescorer(_nodes(8, cpu="8"))
        r.warm([8, WINDOW * depth])
        r.clear_stats()
        pending = [_gang(f"d{depth}-{i}", 2, ts=float(i)) for i in range(24)]
        window = WINDOW * depth
        overruns = 0
        pipe = TickPipeline(r, depth)
        with pipe:
            for _ in range(depth):
                pipe.submit(pending[:window])
                time.sleep(INTERVAL)
            for _ in range(TICKS):
                t0 = time.perf_counter()
                out, tick_groups = pipe.collect()
                pipe.admit_all(out, tick_groups)
                pending = [
                    g for g in pending if g.full_name not in pipe.placed_ever
                ]
                pipe.submit(pending[:window])
                elapsed = time.perf_counter() - t0
                if elapsed > INTERVAL:
                    overruns += 1
                else:
                    time.sleep(INTERVAL - elapsed)
        return overruns, len(pipe.placed_ever)

    overruns_d1, placed_d1 = drive(1)
    overruns_d2, placed_d2 = drive(2)
    # depth 1: every collect waits ~RTT-INTERVAL=100ms past the boundary
    assert overruns_d1 >= TICKS - 1, (overruns_d1, "d1 should miss")
    # depth 2: the RTT rides two intervals; the loop never blocks on it.
    # Tolerance scales with the tick count (TICKS // 3 = 2 of 6): these
    # are real wall-clock sleeps, and a heavily oversubscribed CI host
    # can stall the loop twice without the mechanism being wrong — the
    # d1 assertion (>= TICKS - 1 misses) still separates the regimes.
    assert overruns_d2 <= TICKS // 3, (overruns_d2, "d2 should hold the budget")
    # both drain the same work (the mechanism changes latency, not outcome)
    assert placed_d1 > 0 and placed_d2 >= placed_d1
