"""ScheduleOperation behavioural tests: the gang semantics of the reference's
scheduling core (PreFilter/Permit/PostBind/Compare/preemption), run under both
the oracle and serial scorers."""

import pytest

from batch_scheduler_tpu.api import PodGroupPhase
from batch_scheduler_tpu.cache import PGStatusCache
from batch_scheduler_tpu.core import ScheduleOperation
from batch_scheduler_tpu.utils import errors as errs

from helpers import FakeCluster, make_group, make_node, make_pod, status_for


def build_race(scorer):
    """README race scenario: one node with ~7.1 free cpus, two gangs of
    minMember=5 x 1cpu pods."""
    node = make_node("n1", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    cluster = FakeCluster([node])
    # 0.9 cpu of system pods already bound
    sys_pod = make_pod("sys", requests={"cpu": "900m"})
    cluster.bind(sys_pod, "n1")

    cache = PGStatusCache()
    pods = {}
    for gname, ts in (("race1", 1.0), ("race2", 2.0)):
        pg = make_group(gname, 5, creation_ts=ts)
        members = [
            make_pod(f"{gname}-{i}", group=gname, requests={"cpu": "1"}, creation_ts=ts)
            for i in range(5)
        ]
        status_for(pg, cache, rep_pod=members[0])
        pods[gname] = members

    op = ScheduleOperation(cache, cluster, scorer=scorer)
    return op, cache, cluster, pods


@pytest.mark.parametrize("scorer", ["oracle", "serial"])
def test_race_scenario_one_group_wins(scorer):
    op, cache, cluster, pods = build_race(scorer)

    # Drive race1 through prefilter+permit to completion.
    ready_seen = False
    for pod in pods["race1"]:
        op.pre_filter(pod)
        out = op.permit(pod, "n1")
        ready_seen = ready_seen or out.ready
    assert ready_seen
    assert cache.get("default/race1").scheduled

    # Bind them (postbind updates counters; cluster tracks requested).
    for pod in pods["race1"]:
        cluster.bind(pod, "n1")
        op.post_bind(pod, "n1")
    pg1 = cache.get("default/race1").pod_group
    assert pg1.status.scheduled == 5
    assert pg1.status.phase == PodGroupPhase.SCHEDULED

    # race2 must now be denied: only ~2.1 cpus remain for a 5-cpu gang.
    with pytest.raises(errs.ResourceNotEnoughError):
        op.pre_filter(pods["race2"][0])
    # and the deny cache fast-fails the next attempt
    with pytest.raises(errs.DeniedError):
        op.pre_filter(pods["race2"][1])


def test_oracle_prefilter_reserves_for_priority_group():
    """With both gangs pending and capacity for only one, the oracle path
    admits exactly the first-ordered gang up front (no 0.7 heuristic)."""
    op, cache, cluster, pods = build_race("oracle")
    op.pre_filter(pods["race1"][0])  # earlier creation_ts -> first in order
    with pytest.raises(errs.ResourceNotEnoughError):
        op.pre_filter(pods["race2"][0])


@pytest.mark.parametrize("scorer", ["oracle", "serial"])
def test_permit_gang_accounting(scorer):
    op, cache, _, pods = build_race(scorer)
    group = pods["race1"]
    for i, pod in enumerate(group[:4]):
        out = op.permit(pod, "n1")
        assert not out.ready
        assert isinstance(out.error, errs.WaitingError)
        assert len(cache.get("default/race1").matched_pod_nodes.items()) == i + 1
    out = op.permit(group[4], "n1")
    assert out.ready and out.error is None
    # phase advanced to PreScheduling on first permit
    assert cache.get("default/race1").pod_group.status.phase == PodGroupPhase.PRE_SCHEDULING


def test_permit_same_pod_name_new_uid_replaces_stale_entry():
    op, cache, _, pods = build_race("oracle")
    pod = pods["race1"][0]
    op.permit(pod, "n1")
    recreated = make_pod(pod.metadata.name, group="race1", requests={"cpu": "1"})
    op.permit(recreated, "n1")
    pgs = cache.get("default/race1")
    matched = pgs.matched_pod_nodes.items()
    assert recreated.metadata.uid in matched
    assert pod.metadata.uid not in matched


def test_permit_non_group_pod_not_matched():
    op, _, _, _ = build_race("oracle")
    out = op.permit(make_pod("lonely", requests={"cpu": "1"}), "n1")
    assert out.ready and isinstance(out.error, errs.NotMatchedError)


def test_prefilter_unknown_group_fails():
    op, _, _, _ = build_race("oracle")
    stray = make_pod("stray", group="nope", requests={"cpu": "1"})
    with pytest.raises(errs.PodGroupNotFoundError):
        op.pre_filter(stray)


def test_prefilter_last_permitted_fast_path():
    op, _, _, pods = build_race("oracle")
    pod = pods["race1"][0]
    op.last_permitted_pod.set(pod.metadata.uid, "")
    op.pre_filter(pod)  # passes without consulting the oracle
    assert op.oracle.batches_run == 0


def test_occupied_by_fencing():
    op, cache, _, _ = build_race("oracle")
    owner_a = make_pod("a-0", group="race1", requests={"cpu": "1"}, owner_refs=["rs-a"])
    op.pre_filter(owner_a)
    assert cache.get("default/race1").pod_group.status.occupied_by == "rs-a"
    owner_b = make_pod("b-0", group="race1", requests={"cpu": "1"}, owner_refs=["rs-b"])
    with pytest.raises(errs.OccupiedError):
        op.pre_filter(owner_b)
    # same owner is fine
    op.pre_filter(make_pod("a-1", group="race1", requests={"cpu": "1"}, owner_refs=["rs-a"]))


def test_post_bind_phase_transitions():
    op, cache, cluster, pods = build_race("oracle")
    group = pods["race1"]
    for pod in group[:4]:
        op.post_bind(pod, "n1")
    pg = cache.get("default/race1").pod_group
    assert pg.status.phase == PodGroupPhase.SCHEDULING
    assert pg.status.scheduled == 4
    assert pg.status.schedule_start_time > 0
    op.post_bind(group[4], "n1")
    assert pg.status.phase == PodGroupPhase.SCHEDULED
    assert pg.status.scheduled == 5


def test_filter_oracle_rejects_full_node():
    node_small = make_node("small", {"cpu": "1", "pods": "10"})
    node_big = make_node("big", {"cpu": "8", "pods": "10"})
    cluster = FakeCluster([node_small, node_big])
    cache = PGStatusCache()
    pg = make_group("g", 2)
    members = [make_pod(f"g-{i}", group="g", requests={"cpu": "2"}) for i in range(2)]
    status_for(pg, cache, rep_pod=members[0])
    op = ScheduleOperation(cache, cluster, scorer="oracle")
    op.filter(members[0], "big")
    with pytest.raises(errs.ResourceNotEnoughError):
        op.filter(members[1], "small")


def test_preemption_policy():
    op, cache, _, pods = build_race("oracle")
    online = make_pod("web", requests={"cpu": "1"})
    online2 = make_pod("web2", requests={"cpu": "1"})
    offline1 = pods["race1"][0]
    offline2 = pods["race2"][0]

    # online preempts online: allowed
    op.preempt_remove_pod(online, online2)
    # offline preempts online: forbidden
    with pytest.raises(errs.SchedulingError):
        op.preempt_remove_pod(offline1, online)
    # online preempts offline in a Pending gang: allowed
    op.preempt_remove_pod(online, offline1)
    # same gang: forbidden
    with pytest.raises(errs.SchedulingError):
        op.preempt_remove_pod(offline1, pods["race1"][1])
    # offline preempts a different pending gang: allowed
    op.preempt_remove_pod(offline1, offline2)
    # victims of Scheduled/Running gangs are protected
    cache.get("default/race2").pod_group.status.phase = PodGroupPhase.SCHEDULED
    with pytest.raises(errs.SchedulingError):
        op.preempt_remove_pod(offline1, offline2)


def test_compare_queue_ordering():
    cache = PGStatusCache()
    cluster = FakeCluster([make_node("n", {"cpu": "8"})])
    pg_old = make_group("alpha", 2, creation_ts=10.0)
    pg_new = make_group("beta", 2, creation_ts=20.0)
    lister = {("default", "alpha"): pg_old, ("default", "beta"): pg_new}
    op = ScheduleOperation(
        cache, cluster, scorer="serial",
        pg_lister=lambda ns, name: lister.get((ns, name)),
    )
    pa = make_pod("pa", group="alpha", requests={"cpu": "1"})
    pb = make_pod("pb", group="beta", requests={"cpu": "1"})
    solo = make_pod("solo", requests={"cpu": "1"})
    hi = make_pod("hi", requests={"cpu": "1"}, priority=100)

    assert op.compare(hi, 5.0, pa, 1.0)          # priority wins
    assert op.compare(solo, 2.0, pa, 1.0)        # non-group beats group at equal prio
    assert not op.compare(pa, 1.0, solo, 2.0)
    assert op.compare(pa, 9.0, pb, 1.0)          # earlier group creation wins
    assert not op.compare(pb, 1.0, pa, 9.0)
    pa2 = make_pod("pa2", group="alpha", requests={"cpu": "1"})
    assert op.compare(pa, 1.0, pa2, 2.0)         # same group: queue timestamp


def test_background_refresh_serves_stale_then_recovers():
    """background_refresh=True: a dirty-but-servable batch answers from the
    old state immediately while a daemon thread re-batches; a missing group
    still blocks; a failed background batch surfaces in a later cycle."""
    import time as _time

    op, cache, cluster, pods = build_race("oracle")
    oracle = op.oracle
    oracle.background_refresh = True

    # first ensure_fresh: no state yet -> blocking refresh
    oracle.ensure_fresh(cluster, cache, group="default/race1")
    assert oracle.batches_run == 1

    # dirty + servable -> immediate return (stale answers), background batch
    oracle.mark_dirty()
    oracle.ensure_fresh(cluster, cache, group="default/race1")
    assert oracle.gang_feasible("default/race1")  # served without blocking
    deadline = _time.monotonic() + 5.0
    while oracle.batches_run < 2 and _time.monotonic() < deadline:
        _time.sleep(0.01)
    assert oracle.batches_run == 2  # the daemon thread re-batched

    # a group missing from the snapshot forces the blocking path
    pg = make_group("late", 1, creation_ts=9.0)
    status_for(pg, cache, rep_pod=make_pod("late-0", group="late", requests={"cpu": "1"}))
    oracle.mark_dirty()
    oracle.ensure_fresh(cluster, cache, group="default/late")
    assert oracle.batches_run == 3
    assert oracle.gang_feasible("default/late")

    # background failure -> recorded, then consumed by a blocking refresh
    oracle._bg_error = RuntimeError("link down")
    oracle.mark_dirty()
    oracle.ensure_fresh(cluster, cache, group="default/race1")  # blocking path
    assert oracle._bg_error is None
    assert oracle.batches_run == 4


def test_mark_dirty_during_refresh_survives():
    """Compare-and-clear: an invalidation landing while the batch is on the
    device (routine with background_refresh) must leave the batch stale —
    refresh() records the generation it observed BEFORE packing, not a
    blanket 'clean now'."""
    op, cache, cluster, pods = build_race("oracle")
    oracle = op.oracle
    real_execute = oracle._execute

    def execute_and_invalidate(snap):
        out = real_execute(snap)
        oracle.mark_dirty()  # a gang completed while the batch was in flight
        return out

    oracle._execute = execute_and_invalidate
    oracle.ensure_fresh(cluster, cache, group="default/race1")
    oracle._execute = real_execute
    assert oracle._stale(cluster)  # the mid-flight invalidation survived
    oracle.ensure_fresh(cluster, cache, group="default/race1")
    assert not oracle._stale(cluster)
    assert oracle.batches_run == 2


def test_credits_issued_during_refresh_die_with_old_batch():
    """A plan-covered assume landing while a (background) batch is packing /
    on-device credits the OLD batch; the NEW batch must not inherit the
    offset — its snapshot may predate the assume, so it re-batches instead
    of serving a divergent plan as fresh."""
    op, cache, cluster, pods = build_race("oracle")
    cluster.version_counter = 7
    cluster.version = lambda: cluster.version_counter
    oracle = op.oracle
    oracle.ensure_fresh(cluster, cache, group="default/race1")
    assert not oracle._stale(cluster)

    real_execute = oracle._execute

    def execute_with_interleaved_assume(snap):
        out = real_execute(snap)
        # while the batch is on the device: a member assumes through the
        # old batch's plan (version bump + matching credit)
        cluster.version_counter += 1
        oracle.credit_expected_change(1)
        return out

    oracle.mark_dirty()
    oracle._execute = execute_with_interleaved_assume
    oracle.ensure_fresh(cluster, cache, group="default/race1")
    oracle._execute = real_execute
    # the new batch's base predates the bump and the credit was discarded
    assert oracle._stale(cluster)
    oracle.ensure_fresh(cluster, cache, group="default/race1")
    assert not oracle._stale(cluster)


def test_background_refresh_refused_on_unsupporting_scorer():
    """A scorer instance that declares supports_background_refresh=False
    (RemoteScorer: single-connection transport) is left on the blocking
    path, with a warning."""
    import warnings

    from batch_scheduler_tpu.core.oracle_scorer import OracleScorer

    class SingleConn(OracleScorer):
        supports_background_refresh = False

    node = make_node("n1", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    scorer = SingleConn()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ScheduleOperation(
            PGStatusCache(), FakeCluster([node]), scorer=scorer,
            background_refresh=True,
        )
    assert scorer.background_refresh is False
    assert any("background_refresh" in str(x.message) for x in w)
