"""Direct unit tests for BatchSchedulingPlugin's gang release choreography —
the retry dance the reference performs between the permit signal and the
framework's waiting-pod cache (reference batchscheduler.go:219-344). The
e2e sims cover the happy path; these pin the edge semantics."""

from batch_scheduler_tpu.cache import PGStatusCache
from batch_scheduler_tpu.core import ScheduleOperation
from batch_scheduler_tpu.framework.types import StatusCode
from batch_scheduler_tpu.plugin.batch_plugin import BatchSchedulingPlugin

from helpers import FakeCluster, make_group, make_node, make_pod, status_for


class _StubWaiting:
    """Framework-handle stand-in: a dict of uid -> waiting pod."""

    def __init__(self):
        self.pods = {}

    def get_waiting_pod(self, uid):
        return self.pods.get(uid)

    def iterate_over_waiting_pods(self, fn):
        for wp in list(self.pods.values()):
            fn(wp)


class _StubWaitingPod:
    def __init__(self, pod, node_name="n1"):
        self.pod = pod
        self.node_name = node_name
        self.allowed = 0
        self.rejected = []

    def get_pod(self):
        return self.pod

    def allow(self, name):
        self.allowed += 1
        return True

    def reject(self, reason):
        self.rejected.append(reason)
        return True


def _build(members=2):
    node = make_node("n1", {"cpu": "32", "memory": "64Gi", "pods": "110"})
    cluster = FakeCluster([node])
    cache = PGStatusCache()
    pg = make_group("gang", members, creation_ts=1.0)
    pods = [
        make_pod(f"gang-{i}", group="gang", requests={"cpu": "1"})
        for i in range(members)
    ]
    status_for(pg, cache, rep_pod=pods[0])
    op = ScheduleOperation(cache, cluster, scorer="oracle")
    handle = _StubWaiting()
    plugin = BatchSchedulingPlugin(handle, op, pg_client=None)
    return plugin, handle, op, cache, pods


def _permit_all(plugin, op, pods):
    for p in pods:
        op.pre_filter(p)
        plugin.permit(p, "n1")


def test_release_allows_every_matched_waiting_pod():
    plugin, handle, op, cache, pods = _build()
    _permit_all(plugin, op, pods)
    wps = {}
    for p in pods:
        wps[p.metadata.uid] = _StubWaitingPod(p)
    handle.pods = wps

    plugin.start_batch_schedule("default/gang")
    assert all(wp.allowed == 1 for wp in wps.values())
    # pairs are consumed: a second release has nothing left to allow
    plugin.start_batch_schedule("default/gang")
    assert all(wp.allowed == 1 for wp in wps.values())


def test_release_drops_stale_pair_when_waiting_pod_never_appears():
    """The permit signal racing ahead of the framework cache: after the
    retries exhaust, the stale (uid, pair) is dropped instead of blocking
    the release loop forever (reference batchscheduler.go:316-323) — and
    the sweep CONTINUES past it. The reference returns, stranding every
    not-yet-allowed member in its Permit wait until the full timeout
    with no further release signal coming (the ~100s stragglers in the
    gateway-restart e2e); the pairs are independent, so the raced one is
    dropped and the rest are still allowed. Deviation, not copied."""
    plugin, handle, op, cache, pods = _build(members=3)
    _permit_all(plugin, op, pods)
    # pods 1 and 2 are parked in the framework's waiting cache; pod 0
    # never shows (its wait resolved before the sweep saw it)
    wp1 = _StubWaitingPod(pods[1])
    wp2 = _StubWaitingPod(pods[2])
    handle.pods = {
        pods[1].metadata.uid: wp1,
        pods[2].metadata.uid: wp2,
    }

    plugin.start_batch_schedule("default/gang")
    pairs = op.get_pod_node_pairs("default/gang")
    assert pairs.get(pods[0].metadata.uid) is None  # stale pair dropped
    # the remaining parked members were NOT abandoned
    assert wp1.allowed == 1 and wp2.allowed == 1, (wp1.allowed, wp2.allowed)
    assert pairs.get(pods[1].metadata.uid) is None
    assert pairs.get(pods[2].metadata.uid) is None


def test_release_grace_covers_late_waiting_pod_despite_dead_pair():
    """The retry grace is shared, not first-come-first-served: a pair
    whose WaitingPod materialises DURING the grace is allowed even when
    another pair is permanently dead — the dead pair must not exhaust
    the grace on the others' behalf (the hole a single-payment
    implementation had)."""
    plugin, handle, op, cache, pods = _build(members=3)
    _permit_all(plugin, op, pods)
    wp2 = _StubWaitingPod(pods[2])
    late = {"wp": None}

    class _LateHandle:
        def get_waiting_pod(self, uid):
            if uid == pods[2].metadata.uid:
                return wp2
            if uid == pods[1].metadata.uid:
                return late["wp"]  # materialises mid-grace
            return None  # pod 0: permanently dead

        def iterate_over_waiting_pods(self, fn):
            pass

    plugin.handle = _LateHandle()
    # wp1 appears only after the sweep's first pass has already missed it
    import threading

    wp1 = _StubWaitingPod(pods[1])
    # fires inside the first grace sleep (grace = 2 x 10ms), well before
    # the sweep's final re-check — immune to scheduler jitter
    t = threading.Timer(0.005, lambda: late.__setitem__("wp", wp1))
    t.start()
    plugin.start_batch_schedule("default/gang")
    t.cancel()
    assert wp2.allowed == 1
    assert wp1.allowed == 1, "late-materialising pod lost its grace"
    pairs = op.get_pod_node_pairs("default/gang")
    assert pairs.get(pods[0].metadata.uid) is None  # dead pair dropped


def test_update_batch_cache_evicts_replaced_uid():
    """A pod deleted and recreated under the same name carries a new uid;
    the old uid's matched entry must go (reference UpdateBatchCache,
    batchscheduler.go:219-251)."""
    plugin, handle, op, cache, pods = _build()
    _permit_all(plugin, op, pods)
    pairs = op.get_pod_node_pairs("default/gang")
    assert pairs.get(pods[0].metadata.uid) is not None

    reborn = make_pod("gang-0", group="gang", requests={"cpu": "1"})
    assert reborn.metadata.uid != pods[0].metadata.uid
    handle.pods = {reborn.metadata.uid: _StubWaitingPod(reborn)}
    plugin.update_batch_cache()
    assert pairs.get(pods[0].metadata.uid) is None  # old uid evicted


def test_permit_outcome_mapping():
    """Permit statuses map exactly: non-gang pod -> SUCCESS, gang member ->
    WAIT with the TTL+1s timeout, unknown group -> UNSCHEDULABLE."""
    plugin, handle, op, cache, pods = _build()
    loose = make_pod("loose", requests={"cpu": "1"})
    loose.metadata.labels = {}
    code, _ = plugin.permit(loose, "n1")
    assert code == StatusCode.SUCCESS

    op.pre_filter(pods[0])
    code, timeout = plugin.permit(pods[0], "n1")
    assert code == StatusCode.WAIT
    assert timeout > 1.0  # gang TTL + 1s margin

    stranger = make_pod("ghost-0", group="ghost", requests={"cpu": "1"})
    code, _ = plugin.permit(stranger, "n1")
    assert code == StatusCode.UNSCHEDULABLE


def test_reject_pod_is_noop_for_unknown_uid():
    plugin, handle, op, cache, pods = _build()
    plugin.reject_pod("no-such-uid")  # must not raise
    wp = _StubWaitingPod(pods[0])
    handle.pods = {pods[0].metadata.uid: wp}
    plugin.reject_pod(pods[0].metadata.uid)
    assert wp.rejected == ["Group failed"]


# -- serde round trips (api/serde.py: every API-server read rehydrates
# through these; a lossy field would corrupt silently) -----------------------


def test_serde_round_trips_preserve_all_fields():
    from batch_scheduler_tpu.api.serde import (
        node_from_dict,
        pod_from_dict,
        pod_group_from_dict,
    )
    from batch_scheduler_tpu.api.types import to_dict

    pg = make_group("rt", 5, creation_ts=12.5)
    pg.spec.min_resources = {"cpu": 2000, "nvidia.com/gpu": 1}
    pg.spec.max_schedule_time = 90.0
    pg.spec.priority_class_name = "high"
    pg.status.phase = pg.status.phase.__class__("Scheduling")
    pg.status.scheduled = 3
    pg.status.occupied_by = "default/owner"
    d = to_dict(pg)
    back = pod_group_from_dict(d)
    assert to_dict(back) == d

    pod = make_pod("rt-0", group="rt", requests={"cpu": "2", "memory": "1Gi"})
    pod.spec.node_selector = {"zone": "east"}
    pod.spec.priority = 7
    pod.spec.node_name = "n9"
    d = to_dict(pod)
    assert to_dict(pod_from_dict(d)) == d

    node = make_node("rt-n", {"cpu": "8", "memory": "16Gi", "pods": "110"},
                     labels={"zone": "east"})
    node.spec.unschedulable = True
    d = to_dict(node)
    assert to_dict(node_from_dict(d)) == d
