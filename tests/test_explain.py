"""Gang explainability + what-if observatory (ops/explain.py,
core/explain.py, docs/observability.md "Explain" / "What-if").

The invariants pinned here:

- the explain kernel's breakdown is exact against hand-computed tiny
  batches (deficits, binding lane, fit-mask vs policy-mask vs capacity
  exclusion, entry vs independent capacity);
- each counterfactual kind's forked what-if plan is bit-identical to a
  cluster that ACTUALLY applied the change and rescheduled;
- a copy-on-write device-state fork never perturbs the live holder —
  generation and next-batch plan digest stay bit-identical under a
  concurrent what-if storm interleaved with live delta scheduling
  (lockcheck-instrumented: the storm doubles as a race sweep);
- explain's blame for a denied gang byte-matches the flight recorder's
  recorded decision reason and feasible-node count (the cross-stamp);
- pending-gang aging: denials age into bst_gang_pending_* and the
  /debug/health "pending" signal warns past the target.
"""

import os
import threading

import numpy as np
import pytest

from batch_scheduler_tpu.core.explain import (
    WhatIfEngine,
    apply_counterfactual,
    explain_arrays,
    parse_counterfactual,
)
from batch_scheduler_tpu.ops.device_state import DeviceStateHolder
from batch_scheduler_tpu.ops.oracle import execute_batch_host
from batch_scheduler_tpu.ops.snapshot import (
    ClusterSnapshot,
    DeltaSnapshotPacker,
    GroupDemand,
)
from batch_scheduler_tpu.utils import audit as audit_mod

from helpers import make_node


@pytest.fixture(scope="module", autouse=True)
def _lockcheck():
    """The what-if storm below doubles as a race sweep over the fork /
    holder / engine guarded-by annotations (the chaos-suite pattern,
    docs/static_analysis.md)."""
    from batch_scheduler_tpu.analysis import lockcheck

    prev = os.environ.get("BST_LOCKCHECK")
    os.environ["BST_LOCKCHECK"] = "1"
    lockcheck.install()
    yield
    if prev is None:
        os.environ.pop("BST_LOCKCHECK", None)
    else:
        os.environ["BST_LOCKCHECK"] = prev


def _demand(name, members, cpu, prio=0, ts=0.0, **kw):
    return GroupDemand(
        name, members, member_request={"cpu": cpu}, priority=prio,
        creation_ts=ts, **kw,
    )


def _digest(host):
    return audit_mod.plan_digest(host)


# ---------------------------------------------------------------------------
# the explain kernel
# ---------------------------------------------------------------------------


class TestExplainKernel:
    def _snap(self):
        # n0: cordoned; n1: nearly full (1 cpu left); n2/n3: empty 8-cpu
        nodes = [
            make_node("n0", {"cpu": "8", "memory": "16Gi", "pods": 110}),
            make_node("n1", {"cpu": "8", "memory": "16Gi", "pods": 110}),
            make_node("n2", {"cpu": "8", "memory": "16Gi", "pods": 110}),
            make_node("n3", {"cpu": "8", "memory": "16Gi", "pods": 110}),
        ]
        nodes[0].spec.unschedulable = True
        node_req = {"n1": {"cpu": 7000, "pods": 1}}
        demands = [
            _demand("default/early", 2, 4000, ts=1.0),
            _demand("default/late", 5, 2000, ts=2.0),
        ]
        return ClusterSnapshot(nodes, node_req, demands)

    def test_breakdown_counts_and_binding_lane(self):
        snap = self._snap()
        out = explain_arrays(
            snap.device_args(), snap.group_index("default/late"),
            node_names=snap.node_names, lane_names=snap.schema.names,
        )
        # n0 is cordoned (fit mask); capacity exclusion is ENTRY-based:
        # n1 blocked on cpu (1000 left < 2000) plus n2, which the earlier
        # gang consumed before this gang's scan turn
        assert out["excluded"]["fit_mask"] == 1
        assert out["excluded"]["policy_mask"] == 0
        assert out["excluded"]["capacity"] == 2
        assert out["binding_lane"] == "cpu"
        assert out["blocked_by_lane"] == {"cpu": 2}
        # independent: n2+n3 hold 4 members a piece... 8//2 = 4 each = 8 >= 5
        assert out["nodes_indep"] == 2
        assert out["feasible_alone"] is True
        # early (prio-equal, earlier creation) takes 4000x2 first: one of
        # n2/n3 drops to 0 left... early fits both members on n2 (tightest
        # first: all equal -> node-index order), leaving n2 at 0
        assert out["nodes_entry"] == 1
        assert out["feasible_at_entry"] is False
        assert out["need"] == 5
        # near-miss deficits name the missing cpu on the blocked node
        by_node = {e["node"]: e for e in out["near_miss"]}
        assert by_node["n1"]["deficit"] == {"cpu": 1000}
        assert by_node["n1"]["capacity_entry"] == 0

    def test_verdict_matches_batch_result(self):
        snap = self._snap()
        host, _ = execute_batch_host(
            snap.device_args(), snap.progress_args()
        )
        g_late = snap.group_index("default/late")
        g_early = snap.group_index("default/early")
        assert bool(host["placed"][g_early])
        assert not bool(host["placed"][g_late])
        out = explain_arrays(
            snap.device_args(), g_late, node_names=snap.node_names,
            lane_names=snap.schema.names,
        )
        # the kernel's independent feasibility equals the batch's
        # gang_feasible and its entry verdict explains the denial
        assert out["feasible_alone"] == bool(host["gang_feasible"][g_late])
        assert out["feasible_at_entry"] is False

    def test_policy_hard_mask_counted_separately(self):
        from batch_scheduler_tpu.policy.terms import (
            DOMAIN_BUCKETS,
            HASH_LANES,
            label_hash,
        )

        snap = self._snap()
        g = snap.group_index("default/late")
        nb = snap.alloc.shape[0]
        gb = snap.group_req.shape[0]
        h = label_hash("team", "red")
        anti = np.zeros(gb, np.int32)
        anti[g] = h
        node_hash = np.zeros((nb, HASH_LANES), np.int32)
        node_hash[3, 0] = h  # n3 carries the anti-affinity target
        cols = (
            np.zeros(gb, np.int32), np.zeros(gb, np.int32), anti,
            np.zeros((gb, DOMAIN_BUCKETS), np.int32), node_hash,
            np.zeros(nb, np.int32),
        )
        out = explain_arrays(
            snap.device_args(), g, node_names=snap.node_names,
            lane_names=snap.schema.names,
            policy=(cols, ("anti-affinity",), (32, 8, 3)),
        )
        assert out["excluded"]["policy_mask"] == 1  # n3, hard-masked
        assert out["excluded"]["fit_mask"] == 1     # n0 still cordon
        assert out["nodes_indep"] == 1              # only n2 remains

    def test_offline_lane_names_degrade(self):
        snap = self._snap()
        out = explain_arrays(snap.device_args(), 0)
        assert any(k.startswith("lane") for k in out["headroom_entry"])


# ---------------------------------------------------------------------------
# counterfactual grammar
# ---------------------------------------------------------------------------


class TestCounterfactuals:
    def test_parse_grammar(self):
        assert parse_counterfactual({"drain": "n1"}) == {
            "kind": "drain", "node": "n1",
        }
        cf = parse_counterfactual({"add_nodes": "4", "node_cpu": "16"})
        assert cf["count"] == 4 and cf["shape"]["cpu"] == "16"
        cf = parse_counterfactual({"bump_gang": "d/g", "tier": "7"})
        assert cf == {"kind": "bump-gang", "gang": "d/g", "tier": 7}

    @pytest.mark.parametrize(
        "params",
        [
            {},  # nothing
            {"drain": "a", "cordon": "b"},  # two at once
            {"add_nodes": "zap"},  # non-integer
            {"add_nodes": "0"},  # out of range
            {"bump_gang": "d/g"},  # missing tier
        ],
    )
    def test_parse_rejects(self, params):
        with pytest.raises(ValueError):
            parse_counterfactual(params)

    def test_apply_unknown_targets(self):
        nodes = [make_node("n0")]
        demands = [_demand("default/g", 1, 1000)]
        for cf in (
            {"kind": "drain", "node": "ghost"},
            {"kind": "cordon", "node": "ghost"},
            {"kind": "bump-gang", "gang": "ghost", "tier": 1},
            {"kind": "remove-gang", "gang": "ghost"},
        ):
            with pytest.raises(ValueError):
                apply_counterfactual(nodes, {}, demands, cf)

    def test_cordon_never_mutates_live_node(self):
        nodes = [make_node("n0"), make_node("n1")]
        out_nodes, _, _ = apply_counterfactual(
            nodes, {}, [], {"kind": "cordon", "node": "n1"}
        )
        assert out_nodes[1].spec.unschedulable is True
        assert nodes[1].spec.unschedulable is False  # live object untouched


# ---------------------------------------------------------------------------
# what-if identity + fork isolation
# ---------------------------------------------------------------------------


def _inputs(n=12, g=6, seed=5):
    rng = np.random.default_rng(seed)
    nodes = [
        make_node(f"n{i:02d}", {"cpu": "16", "memory": "64Gi", "pods": 110})
        for i in range(n)
    ]
    node_req = {
        f"n{i:02d}": {"cpu": int(rng.integers(0, 8000)), "pods": 1}
        for i in range(n // 2)
    }
    demands = [
        _demand(
            f"default/gang-{i:02d}", 3, int(rng.integers(1000, 6000)),
            prio=int(rng.integers(0, 3)), ts=float(i),
        )
        for i in range(g)
    ]
    return nodes, node_req, demands


class TestWhatIfIdentity:
    @pytest.mark.parametrize(
        "kind",
        ["drain", "cordon", "add-nodes", "bump-gang", "remove-gang"],
    )
    def test_counterfactual_bit_identical_to_applied_cluster(self, kind):
        nodes, node_req, demands = _inputs()
        cf = {
            "drain": {"kind": "drain", "node": "n01"},
            "cordon": {"kind": "cordon", "node": "n02"},
            "add-nodes": {
                "kind": "add-nodes", "count": 2,
                "shape": {"cpu": "16", "memory": "64Gi", "pods": "110"},
            },
            "bump-gang": {
                "kind": "bump-gang", "gang": "default/gang-05", "tier": 9,
            },
            "remove-gang": {
                "kind": "remove-gang", "gang": "default/gang-00",
            },
        }[kind]
        engine = WhatIfEngine()
        res = engine.query_on(
            nodes, node_req, demands, cf, baseline_key="t"
        )
        applied = apply_counterfactual(nodes, node_req, demands, cf)
        direct = ClusterSnapshot(*applied)
        host, _ = execute_batch_host(
            direct.device_args(), direct.progress_args()
        )
        assert res["whatif"]["plan_digest"] == _digest(host)
        base = ClusterSnapshot(nodes, node_req, demands)
        bhost, _ = execute_batch_host(
            base.device_args(), base.progress_args()
        )
        assert res["base"]["plan_digest"] == _digest(bhost)

    def test_bump_gang_reorders_queue(self):
        # a starved low-priority gang jumps the queue when bumped: the
        # what-if reports it newly placeable (the capacity-planning use)
        nodes = [make_node(f"n{i}", {"cpu": "8", "memory": "32Gi",
                                     "pods": 110}) for i in range(2)]
        demands = [
            _demand("default/whale", 4, 4000, prio=5, ts=1.0),
            _demand("default/starved", 4, 4000, prio=0, ts=2.0),
        ]
        engine = WhatIfEngine()
        res = engine.query_on(
            nodes, {}, demands,
            {"kind": "bump-gang", "gang": "default/starved", "tier": 9},
        )
        assert "default/starved" in res["newly_placeable"]
        assert "default/whale" in res["no_longer_placeable"]

    def test_rung_rejected(self):
        nodes, node_req, demands = _inputs(4, 2)
        with pytest.raises(ValueError):
            WhatIfEngine().query_on(
                nodes, node_req, demands,
                {"kind": "drain", "node": "n01"}, rung="warp-speed",
            )


class TestForkIsolation:
    def test_fork_is_copy_on_write(self):
        nodes, node_req, demands = _inputs()
        packer = DeltaSnapshotPacker()
        holder = DeviceStateHolder(label="live-t")
        snap = packer.pack(nodes, node_req, demands)
        live_args = holder.sync(snap)
        gen0 = holder.current_generation()
        live_requested = np.asarray(live_args[1]).copy()

        fork = holder.fork()
        assert fork.current_generation() == gen0
        # mutate through the fork: scatter a changed row
        cf_nodes, cf_req, cf_dem = apply_counterfactual(
            nodes, node_req, demands, {"kind": "cordon", "node": "n01"}
        )
        cf_snap = ClusterSnapshot(
            cf_nodes, cf_req, cf_dem, schema=snap.schema
        )
        fork.apply_batch(cf_snap.device_args(), snap.device_args())
        # live holder untouched: same generation, same buffer contents
        assert holder.current_generation() == gen0
        assert holder.stats()["rows_scattered"] == 0
        np.testing.assert_array_equal(
            np.asarray(live_args[1]), live_requested
        )

    def test_fork_never_donates(self):
        holder = DeviceStateHolder(label="live-d")
        fork = holder.fork()
        assert fork._donate() is False

    def test_apply_batch_refused_on_live_holder(self):
        holder = DeviceStateHolder(label="live-r")
        with pytest.raises(RuntimeError):
            holder.apply_batch((None,) * 7, (None,) * 7)

    def test_storm_leaves_live_state_bit_identical(self):
        """The acceptance invariant: a what-if fork must leave the live
        holder's generation and next-batch plan digest bit-identical
        under CONCURRENT scheduling (live churn deltas keep landing while
        the storm runs) — lockcheck-instrumented via the module fixture."""
        nodes, node_req, demands = _inputs(16, 8, seed=9)
        packer = DeltaSnapshotPacker()
        holder = DeviceStateHolder(label="live-storm")
        control = DeltaSnapshotPacker()  # fork-free reference pipeline
        engine = WhatIfEngine(holder_source=lambda: holder)
        cfs = [
            {"kind": "drain", "node": "n03"},
            {"kind": "add-nodes", "count": 2,
             "shape": {"cpu": "16", "memory": "64Gi", "pods": "110"}},
            {"kind": "remove-gang", "gang": "default/gang-01"},
        ]
        errors = []
        stop = threading.Event()

        def storm(widx):
            try:
                i = 0
                while not stop.is_set() and i < 6:
                    engine.query_on(
                        nodes, node_req, demands, cfs[(widx + i) % 3],
                        baseline_key="storm",
                    )
                    i += 1
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(f"{type(e).__name__}: {e}")

        threads = [
            threading.Thread(target=storm, args=(w,), daemon=True)
            for w in range(3)
        ]
        for t in threads:
            t.start()
        # concurrent live scheduling: churn one node's requested row per
        # tick, sync the holder, execute from the resident buffers, and
        # bit-compare against a fork-free control pipeline
        live_req = dict(node_req)
        for tick in range(5):
            live_req["n08"] = {"cpu": 1000 + tick, "pods": 1}
            snap = packer.pack(nodes, live_req, demands)
            live_args = holder.sync(snap)
            host, _ = execute_batch_host(live_args, snap.progress_args())
            csnap = control.pack(nodes, live_req, demands)
            chost, _ = execute_batch_host(
                csnap.device_args(), csnap.progress_args()
            )
            assert _digest(host) == _digest(chost), f"tick {tick} diverged"
        stop.set()
        for t in threads:
            t.join(60)
        assert not errors, errors
        # the live holder advanced by exactly its own syncs
        assert holder.current_generation() == packer.generation


# ---------------------------------------------------------------------------
# the live observatory: cross-stamp + pending aging (SimCluster e2e)
# ---------------------------------------------------------------------------


class TestObservatoryE2E:
    def test_explain_byte_matches_recorded_blame_and_pending_ages(self):
        from batch_scheduler_tpu.core.explain import active_observatory
        from batch_scheduler_tpu.sim import SimCluster
        from batch_scheduler_tpu.sim.scenarios import (
            make_member_pods,
            make_sim_group,
            make_sim_node,
        )
        from batch_scheduler_tpu.utils.health import active_pending
        from batch_scheduler_tpu.utils.trace import DEFAULT_FLIGHT_RECORDER

        DEFAULT_FLIGHT_RECORDER.clear()
        cluster = SimCluster(scorer="oracle")
        cluster.add_nodes(
            [
                make_sim_node(
                    f"sim-node-{i}",
                    {"cpu": "8", "memory": "32Gi", "pods": "110"},
                )
                for i in range(2)
            ]
        )
        pods = []
        for name, members, cpu in (("fits", 2, "1"), ("too-big", 30, "4")):
            cluster.create_group(make_sim_group(name, members))
            pods += make_member_pods(name, members, {"cpu": cpu})
        cluster.start()
        try:
            cluster.create_pods(pods)
            assert cluster.wait_for_bound("fits", 2, timeout=60)
            assert cluster.wait_for(
                lambda: any(
                    r.get("phase") == "pre_filter"
                    and r.get("verdict") == "denied"
                    for r in cluster.decisions("too-big").get(
                        "default/too-big", []
                    )
                ),
                timeout=30,
            )
        finally:
            cluster.stop()

        recorded = next(
            r
            for r in reversed(
                cluster.decisions("too-big")["default/too-big"]
            )
            if r.get("phase") == "pre_filter"
        )
        obs = active_observatory()
        assert obs is not None
        exp = cluster.explain("too-big")
        # the cross-stamp: explain's blame byte-matches the recorded
        # decision reason AND feasible-node count
        assert exp["verdict"] == "denied"
        assert exp["deny_reason"] == recorded["reason"]
        assert "cannot fit gang (30 members)" in exp["deny_reason"]
        assert recorded.get("feasible_nodes") is not None
        assert exp["feasible_nodes"] == recorded["feasible_nodes"]
        assert exp["recorded_agrees"] is True
        assert exp["recorded"]["reason"] == recorded["reason"]
        # structural evidence is present and sane
        assert exp["need"] > 0
        assert exp["feasible_alone"] is False
        assert isinstance(exp["near_miss"], list) and exp["near_miss"]
        # a placed gang explains as placed, with its seats
        exp_fit = cluster.explain("fits")
        assert exp_fit["verdict"] == "placed"

        # pending-gang aging: the denied gang is aging, the placed one
        # resolved out of the tracker
        rep = active_pending().report()
        assert rep["pending_gangs"] >= 1
        assert rep["oldest_gang"] == "default/too-big"
        assert rep["oldest_age_s"] > 0
        assert rep["max_deny_streak"] >= 1
        health = cluster.health()
        assert "pending" in health["signals"]
        assert health["signals"]["pending"]["verdict"] == "ok"  # < target

    def test_pending_warns_past_target(self, monkeypatch):
        from batch_scheduler_tpu.utils.health import (
            HealthModel,
            PendingGangTracker,
            set_active_pending,
        )

        tracker = PendingGangTracker()
        set_active_pending(tracker)
        try:
            tracker.note_deny("default/starved")
            # a negative target makes ANY pending age a warn (the gang
            # was denied microseconds ago)
            monkeypatch.setenv("BST_SLO_PENDING_P95_S", "-1")
            health = HealthModel().evaluate()
            sig = health["signals"]["pending"]
            assert sig["verdict"] == "warn"
            assert "default/starved" in sig["reason"]
            # placement resolves it (and observes the age histogram)
            tracker.note_placed("default/starved")
            assert tracker.report()["pending_gangs"] == 0
            assert tracker.resolved == 1
            health = HealthModel().evaluate()
            assert health["signals"]["pending"]["verdict"] == "ok"
        finally:
            from batch_scheduler_tpu.utils.health import DEFAULT_PENDING

            set_active_pending(DEFAULT_PENDING)

    def test_baseline_key_tracks_demand_churn(self):
        """cluster.version() alone misses pod-group churn (a created
        gang never bumps it): the baseline-cache key must fingerprint
        the demands too, or a cached baseline diffs against fresher
        inputs and attributes cluster churn to the counterfactual."""
        from dataclasses import replace

        from batch_scheduler_tpu.core.explain import baseline_inputs_key

        nodes = [make_node("n0")]
        demands = [_demand("default/a", 2, 1000)]
        k0 = baseline_inputs_key(7, nodes, demands)
        assert baseline_inputs_key(7, nodes, demands) == k0  # stable
        assert baseline_inputs_key(8, nodes, demands) != k0  # version
        assert (
            baseline_inputs_key(7, nodes, demands + [_demand("default/b", 1, 1)])
            != k0
        )  # a NEW gang, invisible to the version counter
        assert (
            baseline_inputs_key(7, nodes, [replace(demands[0], priority=5)])
            != k0
        )  # a demand field changed

    def test_backoff_spam_never_rolls_the_blame_record_out(self):
        """The cross-stamp's lifeline: deny-backoff retries repeat one
        blame string every ~0.2-2s; coalesced, they bump ``repeats`` on
        the last record instead of appending — the authoritative
        pre_filter decision stays in the 32-deep ring for the gang's
        whole pending lifetime."""
        from batch_scheduler_tpu.utils.trace import FlightRecorder

        fr = FlightRecorder(per_gang=4)
        fr.record("g", phase="pre_filter", verdict="denied",
                  reason="real blame", coalesce=True, feasible_nodes=2)
        for i in range(100):
            fr.record("g", phase="cycle", verdict="denied",
                      reason="backing off", coalesce=True, batch=i)
        recs = fr.snapshot("g")["g"]
        assert len(recs) == 2
        assert recs[0]["reason"] == "real blame"
        assert recs[0]["feasible_nodes"] == 2
        assert recs[1]["repeats"] == 100
        assert recs[1]["batch"] == 99  # evidence refreshes to the newest
        # a DIFFERENT blame still appends (coalesce is exact-repeat only)
        fr.record("g", phase="cycle", verdict="denied",
                  reason="new blame", coalesce=True)
        assert len(fr.snapshot("g")["g"]) == 3

    def test_whatif_debug_view_grammar_errors(self):
        from batch_scheduler_tpu.core.explain import (
            explain_debug_view,
            whatif_debug_view,
        )

        # bare GETs are self-describing 200s (the /debug/ index probe
        # walks every endpoint parameterless)
        payload, status = explain_debug_view(None)
        assert status == 200 and "gang" in payload["usage"]
        payload, status = whatif_debug_view({})
        assert status == 200 and "kinds" in payload
        payload, status = whatif_debug_view(
            {"drain": "a", "cordon": "b"}
        )
        # an observatory may be live from the e2e above; either way a
        # malformed counterfactual answers 400 with the grammar...
        if "kinds" in payload:
            assert status == 400
        else:  # ...or the no-observatory explainer answers 200
            assert status == 200