"""The schedule-trace pipeline (utils.trace + the TRACE/TRACE_INFO wire
frames): span nesting and context propagation, ring bounds, Chrome-trace
export schema, the flight recorder, and the client+server stitch over the
real sidecar wire."""

import json
import threading

import numpy as np
import pytest

from batch_scheduler_tpu.service import (
    OracleClient,
    protocol as proto,
    serve_background,
)
from batch_scheduler_tpu.utils import trace as trace_mod
from batch_scheduler_tpu.utils.trace import FlightRecorder, TraceRecorder


@pytest.fixture(autouse=True)
def _clean_trace_state():
    trace_mod.DEFAULT_RECORDER.clear()
    trace_mod.DEFAULT_FLIGHT_RECORDER.clear()
    yield
    trace_mod.configure(enabled=False)
    trace_mod.DEFAULT_RECORDER.clear()
    trace_mod.DEFAULT_FLIGHT_RECORDER.clear()


def test_disabled_is_noop():
    trace_mod.configure(enabled=False)
    s = trace_mod.start_trace("root")
    assert s is trace_mod._NULL_SPAN
    with s:
        assert trace_mod.current_context() is None
        assert trace_mod.span("child") is trace_mod._NULL_SPAN
    assert trace_mod.DEFAULT_RECORDER.snapshot() == []


def test_span_nesting_and_context():
    trace_mod.configure(enabled=True)
    with trace_mod.start_trace("root", pod="p0") as root:
        tid, sid = trace_mod.current_context()
        assert tid == root.trace_id and sid == root.span_id
        with trace_mod.span("child") as child:
            assert child.trace_id == tid
            assert child.parent_id == root.span_id
            child.set(extra=7)
        # child popped: context back to the root span
        assert trace_mod.current_context() == (tid, root.span_id)
    assert trace_mod.current_context() is None
    events = trace_mod.DEFAULT_RECORDER.snapshot()
    assert [e["name"] for e in events] == ["child", "root"]  # close order
    child_ev, root_ev = events
    assert child_ev["args"]["parent_id"] == root_ev["args"]["span_id"]
    assert child_ev["args"]["trace_id"] == root_ev["args"]["trace_id"]
    assert child_ev["args"]["extra"] == 7
    assert root_ev["args"]["pod"] == "p0"


def test_child_span_without_root_records_nothing():
    trace_mod.configure(enabled=True)
    with trace_mod.span("orphan"):
        pass
    assert trace_mod.DEFAULT_RECORDER.snapshot() == []


def test_sampling_keeps_fraction():
    trace_mod.configure(enabled=True, sample=0.25)
    kept = 0
    for _ in range(100):
        with trace_mod.start_trace("r") as s:
            if s is not trace_mod._NULL_SPAN:
                kept += 1
    assert kept == 25
    trace_mod.configure(enabled=True, sample=0.0)
    assert trace_mod.start_trace("r") is trace_mod._NULL_SPAN


def test_recorder_ring_bounded_and_concurrent():
    rec = TraceRecorder(capacity=64)

    def writer(i):
        for j in range(100):
            rec.add({"name": f"w{i}-{j}", "ph": "X", "ts": 0, "pid": "p"})

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = rec.snapshot()
    assert len(events) == 64  # bounded, oldest dropped
    with rec._lock:  # the lockcheck sweep: guarded state, read guarded
        dropped = rec.dropped
    assert dropped == 8 * 100 - 64


def test_chrome_trace_export_schema(tmp_path):
    trace_mod.configure(enabled=True)
    with trace_mod.start_trace("root"):
        with trace_mod.span("child"):
            pass
    path = trace_mod.DEFAULT_RECORDER.export(str(tmp_path / "t.json"))
    doc = json.loads(open(path).read())
    events = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    # process-name metadata rows precede the spans
    meta = [e for e in events if e["ph"] == "M"]
    spans = [e for e in events if e["ph"] == "X"]
    assert meta and spans
    for e in spans:
        for field in ("name", "cat", "ph", "ts", "dur", "pid", "tid", "args"):
            assert field in e, (field, e)


def test_record_remote_spans_stitch_and_malformed():
    trace_mod.configure(enabled=True)
    trace_mod.record_remote_spans(
        [
            {"name": "oracle.device_batch", "ts": 123.0, "dur": 5.0,
             "args": {"trace_id": "a" * 16}},
            {"no_name": True},  # malformed: skipped, never raises
            "not-a-dict",
        ],
        pid="oracle-server",
    )
    events = trace_mod.DEFAULT_RECORDER.snapshot()
    assert len(events) == 1
    assert events[0]["pid"] == "oracle-server"
    assert events[0]["args"]["trace_id"] == "a" * 16


def test_flight_recorder_rings_and_lru():
    fr = FlightRecorder(per_gang=2, max_gangs=3)
    for i in range(5):
        fr.record(f"g{i}", phase="cycle", verdict="denied", reason="r")
    snap = fr.snapshot()
    assert set(snap) == {"g2", "g3", "g4"}  # LRU-bounded on gangs
    assert fr.dropped_gangs == 2
    for _ in range(5):
        fr.record("g4", phase="permit", verdict="placed")
    assert len(fr.snapshot("g4")["g4"]) == 2  # per-gang ring bounded
    assert fr.last("g4")["verdict"] == "placed"
    doc = json.loads(fr.to_json().decode())
    assert "decisions" in doc and doc["dropped_gangs"] == 2


def test_flight_recorder_stamps_trace_id():
    trace_mod.configure(enabled=True)
    fr = FlightRecorder()
    with trace_mod.start_trace("root") as s:
        fr.record("default/g", phase="cycle", verdict="placed")
    assert fr.last("default/g")["trace_id"] == s.trace_id


def test_trace_frame_roundtrip():
    tid = trace_mod.new_trace_id()
    payload = proto.pack_trace(tid, "abcd1234")
    assert proto.unpack_trace(payload) == (tid, "abcd1234")
    with pytest.raises(ValueError):
        proto.pack_trace("short")
    info = proto.pack_trace_info(tid, [{"name": "s", "ts": 1, "dur": 2}],
                                 {"device_seconds": 0.5})
    back = proto.unpack_trace_info(info)
    assert back["trace_id"] == tid and back["telemetry"]["device_seconds"] == 0.5
    assert proto.unpack_trace_info(b"\xff not json") == {}


def _request(n=4, g=2, r=5, members=3):
    alloc = np.zeros((n, r), np.int32)
    alloc[:, 0] = 8000
    alloc[:, 3] = 20
    requested = np.zeros((n, r), np.int32)
    group_req = np.zeros((g, r), np.int32)
    group_req[:, 0] = 1000
    group_req[:, 3] = 1
    return proto.ScheduleRequest(
        alloc=alloc,
        requested=requested,
        group_req=group_req,
        remaining=np.full(g, members, np.int32),
        fit_mask=np.ones((1, n), bool),
        group_valid=np.ones(g, bool),
        order=np.arange(g, dtype=np.int32),
        min_member=np.full(g, members, np.int32),
        scheduled=np.zeros(g, np.int32),
        matched=np.zeros(g, np.int32),
        ineligible=np.zeros(g, bool),
        creation_rank=np.arange(g, dtype=np.int32),
    )


def test_wire_stitch_over_real_sidecar():
    """A traced schedule request stitches: the server's spans come back in
    the TRACE_INFO frame under the client's trace ID, the device telemetry
    lands on the client, and an untraced client sees byte-identical
    behavior (no TRACE_INFO ever sent)."""
    srv = serve_background()
    try:
        host, port = srv.address
        # untraced first: pre-trace behavior intact
        trace_mod.configure(enabled=False)
        plain = OracleClient(host, port)
        resp = plain.schedule(_request())
        assert resp.placed.all()
        assert plain.last_telemetry is None
        plain.close()

        trace_mod.configure(enabled=True)
        client = OracleClient(host, port)
        with trace_mod.start_trace("schedule_cycle") as root:
            resp = client.schedule(_request())
            assert resp.placed.all()
        tele = client.last_telemetry
        assert tele is not None
        assert tele["n"] == 4 and tele["g"] == 2
        assert "device_seconds" in tele and "mask_mode" in tele
        server_spans = [
            e for e in trace_mod.DEFAULT_RECORDER.snapshot()
            if e["pid"] == "oracle-server"
        ]
        assert server_spans, "no server spans stitched into the local ring"
        assert {e["args"]["trace_id"] for e in server_spans} == {root.trace_id}
        names = {e["name"] for e in server_spans}
        assert "oracle.device_batch" in names and "oracle.schedule" in names
        # rows still work after the trace exchange (stream not desynced)
        row = client.row("capacity", 0, resp.batch_seq)
        assert row.shape[0] >= 4
        # an untraced (sampled-out) batch must NOT inherit the previous
        # traced batch's telemetry — last_telemetry is per-request
        client.schedule(_request())
        assert client.last_telemetry is None
        client.close()
    finally:
        srv.shutdown()


def test_batch_flight_record_nests_peer_telemetry():
    """The per-batch flight record nests the telemetry dict rather than
    splatting it: a version-skewed sidecar shipping a telemetry key that
    collides with record()'s own parameters (phase/verdict/batch/...)
    must not TypeError the refresh path into a cycle error."""
    from batch_scheduler_tpu.core.oracle_scorer import OracleScorer

    class _HostileScorer(OracleScorer):
        def _execute(self, snap):
            import numpy as np

            g = len(snap.group_names)
            host = {
                "gang_feasible": np.zeros(g, bool),
                "placed": np.zeros(g, bool),
                "progress": np.zeros(g, np.int32),
                "best": 0,
                "best_exists": False,
                "assignment_nodes": np.zeros((g, 1), np.int32),
                "assignment_counts": np.zeros((g, 1), np.int32),
                # reserved-name collisions straight off the wire
                "telemetry": {"phase": "evil", "verdict": "evil",
                              "batch": -1, "gang": "x", "reason": "x"},
            }
            return host, lambda kind, gi: np.zeros(1, np.int32)

    from helpers import FakeCluster, make_node  # noqa: F401
    from batch_scheduler_tpu.cache import PGStatusCache

    scorer = _HostileScorer()
    scorer.refresh(FakeCluster([make_node("n0", {"cpu": "8"})]), PGStatusCache())
    rec = trace_mod.DEFAULT_FLIGHT_RECORDER.last("_batch")
    assert rec["phase"] == "batch" and rec["verdict"] == "info"
    assert rec["telemetry"]["phase"] == "evil"  # nested, not splatted


def test_in_process_batch_telemetry_and_wave_metrics():
    """collect_batch attaches device telemetry to the host result and the
    wavefront stats flow to Prometheus from the SERVING path (not just
    benchmarks/scan_split.py)."""
    import os

    from batch_scheduler_tpu.ops.oracle import execute_batch_host
    from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot, GroupDemand
    from batch_scheduler_tpu.sim.scenarios import make_sim_node
    from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY

    nodes = [
        make_sim_node(f"n{i}", {"cpu": "8", "memory": "32Gi", "pods": "110"})
        for i in range(4)
    ]
    groups = [
        GroupDemand(f"default/g{i}", 2, member_request={"cpu": 1000})
        for i in range(6)
    ]
    snap = ClusterSnapshot(nodes, {}, groups)

    host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    tele = host["telemetry"]
    assert tele["wave_width"] == 0 and tele["n_bucket"] >= 4

    old = os.environ.get("BST_SCAN_WAVE")
    os.environ["BST_SCAN_WAVE"] = "4"
    try:
        demote_before = DEFAULT_REGISTRY.counter(
            "bst_scan_wave_demotions_total"
        ).value()
        host, _ = execute_batch_host(snap.device_args(), snap.progress_args())
        tele = host["telemetry"]
        assert tele["wave_width"] == 4
        assert tele["waves_per_batch"] >= 1
        assert tele["wave_demotions"] >= 0
        # the serving-path series moved
        assert DEFAULT_REGISTRY.histogram("bst_scan_waves_per_batch").count() > 0
        assert (
            DEFAULT_REGISTRY.counter("bst_scan_wave_demotions_total").value()
            >= demote_before
        )
        # wavefront result identical to the serial scan (bit-identical by
        # construction — re-assert through the telemetry-carrying path)
        os.environ["BST_SCAN_WAVE"] = "0"
        host_serial, _ = execute_batch_host(
            snap.device_args(), snap.progress_args()
        )
        np.testing.assert_array_equal(host["placed"], host_serial["placed"])
        np.testing.assert_array_equal(
            host["assignment_counts"], host_serial["assignment_counts"]
        )
    finally:
        if old is None:
            os.environ.pop("BST_SCAN_WAVE", None)
        else:
            os.environ["BST_SCAN_WAVE"] = old
