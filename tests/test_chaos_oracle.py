"""Chaos suite for the oracle transport (docs/resilience.md): every fault
class the sim.chaos proxy injects — connection reset, black-hole hang,
delayed frames, truncated frames, garbage frames — individually survived by
ResilientOracleClient; the circuit breaker's closed -> open -> half-open ->
closed lifecycle; server-side deadline enforcement (an in-band deadline
error within 2x the budget, distinct from transport failure); and the
conservative local-CPU fallback making only safe decisions during a full
outage, then recovering on its own once the sidecar returns."""

import time

import numpy as np
import pytest

from batch_scheduler_tpu.cache import PGStatusCache
from batch_scheduler_tpu.core import ScheduleOperation
from batch_scheduler_tpu.service import (
    OracleClient,
    RemoteScorer,
    ResilientOracleClient,
    protocol as proto,
    serve_background,
)
from batch_scheduler_tpu.sim.chaos import FAULT_KINDS, ChaosProxy
from batch_scheduler_tpu.utils import errors as errs
from batch_scheduler_tpu.utils.metrics import DEFAULT_REGISTRY, Registry
from batch_scheduler_tpu.utils.retry import CircuitBreaker, RetryPolicy

from helpers import FakeCluster, make_group, make_node, make_pod, status_for


@pytest.fixture(scope="module", autouse=True)
def _lockcheck():
    """BST_LOCKCHECK: this suite's thread storms (chaos proxy relays,
    breaker probes, fallback scorer, deadline-abandoned workers) double as
    a race detector over every guarded-by-annotated class
    (docs/static_analysis.md). Instrumentation is process-global and
    deliberately left installed: later suites keep running under it."""
    import os

    from batch_scheduler_tpu.analysis import lockcheck

    prev = os.environ.get("BST_LOCKCHECK")
    os.environ["BST_LOCKCHECK"] = "1"
    lockcheck.install()
    yield
    # restore the env so SUBPROCESSES spawned by later tests don't inherit
    # the knob (in-process instrumentation intentionally stays installed)
    if prev is None:
        os.environ.pop("BST_LOCKCHECK", None)
    else:
        os.environ["BST_LOCKCHECK"] = prev


def _request(n=4, g=2, r=5, members=3):
    alloc = np.zeros((n, r), np.int32)
    alloc[:, 0] = 8000
    alloc[:, 3] = 20
    requested = np.zeros((n, r), np.int32)
    group_req = np.zeros((g, r), np.int32)
    group_req[:, 0] = 1000
    group_req[:, 3] = 1
    return proto.ScheduleRequest(
        alloc=alloc,
        requested=requested,
        group_req=group_req,
        remaining=np.full(g, members, np.int32),
        fit_mask=np.ones((g, n), bool),
        group_valid=np.ones(g, bool),
        order=np.arange(g, dtype=np.int32),
        min_member=np.full(g, members, np.int32),
        scheduled=np.zeros(g, np.int32),
        matched=np.zeros(g, np.int32),
        ineligible=np.zeros(g, bool),
        creation_rank=np.arange(g, dtype=np.int32),
    )


@pytest.fixture(scope="module")
def server():
    srv = serve_background()
    # warm the jit cache through a direct connection so the chaos tests'
    # deliberately short socket timeouts never race a first compile
    warm = OracleClient(*srv.address)
    warm.schedule(_request())
    warm.close()
    yield srv
    srv.shutdown()


@pytest.fixture
def proxy(server):
    p = ChaosProxy(*server.address)
    yield p
    p.stop()


def _quick_client(proxy, registry, timeout=0.8, attempts=4, **breaker_kwargs):
    return ResilientOracleClient(
        *proxy.address,
        timeout=timeout,
        registry=registry,
        retry_policy=RetryPolicy(
            max_attempts=attempts, base_delay=0.01, max_delay=0.05
        ),
        breaker=CircuitBreaker(
            failure_threshold=breaker_kwargs.pop("failure_threshold", 8),
            reset_timeout=breaker_kwargs.pop("reset_timeout", 0.3),
        ),
    )


# -- fault classes, individually ------------------------------------------


@pytest.mark.parametrize("kind", FAULT_KINDS)
def test_client_survives_each_fault_class(proxy, kind):
    """One injected fault of each class: the request still completes (via
    retry + reconnect where needed), the breaker stays closed, and no
    transport error escapes to the caller."""
    reg = Registry()
    client = _quick_client(proxy, reg)
    label = "%s:%s" % proxy.address
    assert client.schedule(_request()).placed.all()  # healthy baseline

    proxy.set_fault(kind, probability=1.0, limit=1, delay_s=0.1)
    resp = client.schedule(_request())
    assert resp.placed.all()
    injected = proxy.injected_counts()
    assert injected[kind] == 1, injected
    assert client.breaker.state == "closed"
    retries = reg.counter("bst_oracle_retries_total").value(
        op="schedule", client=label
    )
    if kind == "delay":
        # a late frame is not a failure: no retry, no reconnect
        assert retries == 0
        assert reg.counter("bst_oracle_transport_failures_total").value(
            op="schedule", client=label
        ) == 0
    else:
        assert retries >= 1
    # the connection (possibly re-established) stays fully usable
    assert client.ping()
    client.close()


def test_reconnect_makes_old_batch_rows_stale(proxy):
    """After a mid-run reconnect the server's per-connection batch state is
    gone; a row fetch against the pre-fault batch must surface as
    StaleBatchError (conservative answer), not a transport error or a
    foreign batch's row."""
    reg = Registry()
    client = _quick_client(proxy, reg)
    resp = client.schedule(_request())
    proxy.set_fault("reset", probability=1.0, limit=1)
    assert client.ping()  # consumes the reset; client reconnects
    with pytest.raises(errs.StaleBatchError):
        client.row("capacity", 0, resp.batch_seq)
    client.close()


# -- circuit breaker lifecycle --------------------------------------------


def test_breaker_opens_fails_fast_and_recovers(proxy):
    reg = Registry()
    label = "%s:%s" % proxy.address
    client = ResilientOracleClient(
        *proxy.address,
        timeout=1.0,
        registry=reg,
        retry_policy=RetryPolicy(max_attempts=2, base_delay=0.01, max_delay=0.02),
        breaker=CircuitBreaker(failure_threshold=3, reset_timeout=0.3),
    )
    gauge = reg.gauge("bst_oracle_breaker_state")
    assert client.schedule(_request()).placed.all()
    assert gauge.value(client=label) == 0  # closed

    proxy.set_fault("reset", probability=1.0)  # sustained outage
    for _ in range(2):
        with pytest.raises(errs.OracleTransportError):
            client.schedule(_request())
    assert client.breaker.state == "open"
    assert gauge.value(client=label) == 1

    # open: refused WITHOUT touching the transport — instant, no new
    # transport failures recorded
    failures = reg.counter("bst_oracle_transport_failures_total").value(
        op="schedule", client=label
    )
    t0 = time.perf_counter()
    with pytest.raises(errs.CircuitOpenError):
        client.schedule(_request())
    assert time.perf_counter() - t0 < 0.05
    assert reg.counter("bst_oracle_transport_failures_total").value(
        op="schedule", client=label
    ) == failures

    # cooldown elapses while the fault persists: the half-open ping probe
    # fails and the breaker re-opens for a fresh cooldown
    time.sleep(0.35)
    with pytest.raises(errs.CircuitOpenError):
        client.schedule(_request())
    assert client.breaker.state == "open"

    # sidecar recovers: cooldown -> half-open probe succeeds -> closed
    proxy.clear_fault()
    time.sleep(0.35)
    assert client.schedule(_request()).placed.all()
    assert client.breaker.state == "closed"
    assert gauge.value(client=label) == 0
    client.close()


# -- deadline propagation --------------------------------------------------


def test_deadline_error_within_two_x_budget(server, monkeypatch):
    """A server-side stall longer than deadline_ms answers an in-band
    deadline error within 2x the deadline, surfaced as OracleDeadlineError
    — distinctly NOT a transport failure (no retry, breaker untouched)."""
    import batch_scheduler_tpu.service.server as server_mod

    reg = Registry()
    label = "%s:%s" % server.address
    client = ResilientOracleClient(
        *server.address,
        timeout=10.0,
        registry=reg,
        breaker=CircuitBreaker(failure_threshold=1, reset_timeout=60.0),
    )
    assert client.schedule(_request()).placed.all()

    # stall the device-executor's dispatch (the executor resolves the name
    # through the server module's globals, so this patches the pipeline's
    # single issuing thread — the post-executor analog of stalling
    # execute_batch_host under the old execute_lock)
    real = server_mod.dispatch_batch

    def stalled(*args, **kwargs):
        time.sleep(1.5)
        return real(*args, **kwargs)

    monkeypatch.setattr(server_mod, "dispatch_batch", stalled)
    t0 = time.perf_counter()
    with pytest.raises(errs.OracleDeadlineError):
        client.schedule(_request(), deadline_ms=300)
    elapsed = time.perf_counter() - t0
    assert elapsed <= 0.6, f"deadline answer took {elapsed:.3f}s (> 2x 300ms)"
    # distinct from transport: threshold-1 breaker would have opened on
    # any transport classification, and nothing was retried
    assert client.breaker.state == "closed"
    assert reg.counter("bst_oracle_retries_total").value(
        op="schedule", client=label
    ) == 0
    assert reg.counter("bst_oracle_deadline_errors_total").value(client=label) == 1

    # the abandoned batch keeps running server-side; a later request (the
    # stall undone) queues behind it and still completes
    monkeypatch.setattr(server_mod, "dispatch_batch", real)
    assert client.schedule(_request(), deadline_ms=30000).placed.all()
    client.close()


def test_deadline_generous_budget_is_a_noop(server):
    client = ResilientOracleClient(
        *server.address, timeout=10.0, registry=Registry(), deadline_ms=60000
    )
    resp = client.schedule(_request())
    assert resp.placed.all()
    # rows inherit the client-default deadline annotation too
    assert client.row("capacity", 0, resp.batch_seq).shape[0] >= 4
    client.close()


# -- conservative local-CPU fallback --------------------------------------


def _gang_fixture():
    node = make_node("n1", {"cpu": "8", "memory": "32Gi", "pods": "110"})
    cluster = FakeCluster([node])
    cache = PGStatusCache()
    ok_members = [
        make_pod(f"okgang-{i}", group="okgang", requests={"cpu": "1"})
        for i in range(2)
    ]
    status_for(make_group("okgang", 2, creation_ts=1.0), cache, rep_pod=ok_members[0])
    bad_members = [
        make_pod(f"badgang-{i}", group="badgang", requests={"cpu": "64"})
        for i in range(2)
    ]
    status_for(make_group("badgang", 2, creation_ts=2.0), cache, rep_pod=bad_members[0])
    return cluster, cache, ok_members, bad_members


def test_fallback_local_cpu_is_conservative_and_recovers(proxy):
    """Breaker open => the scorer serves the conservative CPU batch:
    feasible gangs pass PreFilter (no speculative plan, no deny-cache
    poisoning), provably-infeasible gangs get ResourceNotEnoughError,
    Filter/Score answer real capacities — and once the sidecar returns the
    scorer re-probes through the breaker on its own and resumes exact
    batch placement."""
    cluster, cache, ok_members, bad_members = _gang_fixture()
    client = ResilientOracleClient(
        *proxy.address,
        timeout=2.0,
        registry=Registry(),
        retry_policy=RetryPolicy(max_attempts=2, base_delay=0.01, max_delay=0.02),
        breaker=CircuitBreaker(failure_threshold=2, reset_timeout=0.4),
    )
    scorer = RemoteScorer(client, fallback="local-cpu")
    op = ScheduleOperation(cache, cluster, scorer=scorer)

    proxy.set_fault("reset", probability=1.0)  # total outage from the start
    decisions = DEFAULT_REGISTRY.counter("bst_oracle_fallback_decisions_total")
    passes0 = decisions.value(decision="pass")
    denies0 = decisions.value(decision="deny")

    op.pre_filter(ok_members[0])  # no exception: conservative pass
    assert scorer.degraded
    assert op.gang_plan(ok_members[0]) is None  # nothing speculative
    assert not op.last_denied_pg.contains("default/okgang")
    with pytest.raises(errs.ResourceNotEnoughError):
        op.pre_filter(bad_members[0])
    assert decisions.value(decision="pass") == passes0 + 1
    assert decisions.value(decision="deny") == denies0 + 1

    # Filter/Score still answer from real (host-computed) capacities
    op.filter(ok_members[0], "n1")
    assert op.score(ok_members[0], "n1") > 0

    # sidecar recovers; after the cooldown the next query re-probes
    # (degraded batches auto-expire via _stale) and exact answers return
    proxy.clear_fault()
    time.sleep(0.45)
    op.pre_filter(ok_members[1])
    assert not scorer.degraded
    assert scorer.placed("default/okgang")
    assert op.gang_plan(ok_members[1]) is not None  # real plan stamped
    scorer.close()


def test_fallback_deny_mode_surfaces_transport_error(proxy):
    """Default fallback ('deny'): the transport error reaches the caller
    (the scheduling cycle requeues with backoff) — never a silent deny."""
    cluster, cache, ok_members, _ = _gang_fixture()
    client = ResilientOracleClient(
        *proxy.address,
        timeout=1.0,
        registry=Registry(),
        retry_policy=RetryPolicy(max_attempts=2, base_delay=0.01, max_delay=0.02),
        breaker=CircuitBreaker(failure_threshold=3, reset_timeout=0.3),
    )
    scorer = RemoteScorer(client)  # fallback="deny"
    op = ScheduleOperation(cache, cluster, scorer=scorer)
    proxy.set_fault("reset", probability=1.0)
    with pytest.raises(errs.OracleTransportError):
        op.pre_filter(ok_members[0])
    assert not scorer.degraded
    scorer.close()


# -- device-resident state deltas under chaos ------------------------------


def _delta_world(n_nodes=6, n_gangs=4):
    """A small live cluster + reference scorer world for the wire-delta
    chaos cases."""
    from batch_scheduler_tpu.core.oracle_scorer import OracleScorer

    nodes = [
        make_node(f"n{i}", {"cpu": "8", "memory": "32Gi", "pods": "110"})
        for i in range(n_nodes)
    ]
    cluster = FakeCluster(nodes)
    cache = PGStatusCache()
    gang_names = []
    for i in range(n_gangs):
        name = f"gang{i}"
        pg = make_group(name, 3, creation_ts=float(i))
        members = [
            make_pod(f"{name}-{m}", group=name, requests={"cpu": "1"})
            for m in range(3)
        ]
        status_for(pg, cache, rep_pod=members[0])
        gang_names.append(f"default/{name}")
    reference = OracleScorer(device_state=False)
    return cluster, cache, gang_names, nodes, reference


def test_wire_delta_survives_dropped_and_duplicated_frames(server):
    """The delta-stream chaos case (docs/pipelining.md "Device-resident
    state"): the proxy drops one delta frame mid-stream, then duplicates
    one. Either way the sidecar must detect the generation gap and refuse
    to apply stale/duplicate rows (DELTA_RESYNC), the client must resync
    through a full keyframe, and every published plan must stay
    bit-identical to an independent full-repack scorer — a silently
    stale-row plan is the one forbidden outcome."""
    chaos = ChaosProxy(*server.address, c2s_frames=True)
    reg = Registry()
    client = ResilientOracleClient(
        *chaos.address,
        timeout=2.0,
        registry=reg,
        retry_policy=RetryPolicy(max_attempts=4, base_delay=0.01, max_delay=0.05),
        breaker=CircuitBreaker(failure_threshold=16, reset_timeout=0.3),
    )
    remote = RemoteScorer(client, fallback="deny")
    assert remote._wire_delta_ok  # resilient transport: delta path live
    # pin the wire layout to one frame per request: with tenant
    # announcements on, the TENANT annotation precedes the delta frame
    # and the proxy's single-frame faults land on IT — which is
    # harmlessly fire-and-forget (attribution only), so the drop this
    # case is about would never reach the delta stream
    remote._wire_tenant_ok = False
    cluster, cache, gang_names, nodes, reference = _delta_world()

    def refresh_and_compare():
        for s in (remote, reference):
            s.mark_dirty()
            s.ensure_fresh(cluster, cache, group=gang_names[0])
        for full_name in gang_names:
            assert remote.placed(full_name) == reference.placed(full_name)
            assert remote.gang_feasible(full_name) == reference.gang_feasible(
                full_name
            )
            assert remote.assignment(full_name) == reference.assignment(
                full_name
            )

    wire_kinds = DEFAULT_REGISTRY.counter(
        "bst_oracle_wire_delta_batches_total"
    )

    def kind_count(kind):
        return wire_kinds.value(kind=kind)

    resyncs = DEFAULT_REGISTRY.counter("bst_oracle_wire_delta_resyncs_total")

    # healthy baseline: keyframe installs the mirror, churn rides deltas
    refresh_and_compare()
    cluster.bind(make_pod("warm-filler", requests={"cpu": "2"}), "n0")
    deltas_before = kind_count("delta")
    refresh_and_compare()
    assert kind_count("delta") == deltas_before + 1

    # 1) DROPPED delta frame: the request vanishes, the socket read times
    # out, the resilient client retries on a fresh connection — where the
    # sidecar has no mirror and answers DELTA_RESYNC; the client resyncs
    # through a keyframe and the plan is still exact
    resyncs_before = resyncs.value()
    keyframes_before = kind_count("keyframe")
    chaos.set_fault("drop_c2s", probability=1.0, limit=1)
    cluster.bind(make_pod("drop-filler", requests={"cpu": "2"}), "n1")
    refresh_and_compare()
    assert chaos.injected_counts()["drop_c2s"] == 1
    assert resyncs.value() >= resyncs_before + 1
    assert kind_count("keyframe") >= keyframes_before + 1

    # steady state returns to deltas after the resync
    cluster.bind(make_pod("steady-filler", requests={"cpu": "2"}), "n2")
    deltas_before = kind_count("delta")
    refresh_and_compare()
    assert kind_count("delta") == deltas_before + 1

    # 2) DUPLICATED delta frame: the sidecar applies the first copy and
    # must REFUSE the second on the generation check (never scatter the
    # same delta twice); the stale DELTA_RESYNC left in the stream makes
    # the client drop the lane and keyframe — plans stay exact throughout
    chaos.set_fault("dup_c2s", probability=1.0, limit=1)
    cluster.bind(make_pod("dup-filler", requests={"cpu": "2"}), "n3")
    refresh_and_compare()
    assert chaos.injected_counts()["dup_c2s"] == 1
    cluster.bind(make_pod("post-dup-filler", requests={"cpu": "2"}), "n4")
    refresh_and_compare()
    cluster.bind(make_pod("tail-filler", requests={"cpu": "2"}), "n5")
    refresh_and_compare()

    remote.close()
    chaos.stop()


# -- warm-standby failover drills (docs/resilience.md "High availability") --


def _pooled(spec, registry, attempts=6, timeout=2.0, reset_timeout=5.0,
            name=None):
    """The HA drills' tuned pool client: the breaker trips on the second
    transport error, so a crash promotes within one schedule() call."""
    return ResilientOracleClient(
        spec,
        timeout=timeout,
        registry=registry,
        name=name,
        retry_policy=RetryPolicy(
            max_attempts=attempts, base_delay=0.01, max_delay=0.05
        ),
        breaker=CircuitBreaker(
            failure_threshold=2, reset_timeout=reset_timeout
        ),
    )


def test_kill_mid_delta_stream_fails_over_and_resyncs(server):
    """Primary killed mid-delta-stream: the pooled client must trip the
    breaker, promote to the standby, land the retried delta request on a
    sidecar with NO device mirror — which answers DELTA_RESYNC — resync
    through a full keyframe, and keep every published plan bit-identical
    to an independent full-repack scorer. The cursor survives failover
    by re-keyframing, never by silently applying deltas to the wrong
    mirror."""
    standby = serve_background()
    chaos = ChaosProxy(*server.address)
    reg = Registry()
    client = _pooled(
        "%s:%s,%s:%s" % (chaos.address + standby.address), reg
    )
    remote = RemoteScorer(client, fallback="deny")
    assert remote._wire_delta_ok
    cluster, cache, gang_names, nodes, reference = _delta_world()

    def refresh_and_compare():
        for s in (remote, reference):
            s.mark_dirty()
            s.ensure_fresh(cluster, cache, group=gang_names[0])
        for full_name in gang_names:
            assert remote.placed(full_name) == reference.placed(full_name)
            assert remote.gang_feasible(
                full_name
            ) == reference.gang_feasible(full_name)
            assert remote.assignment(full_name) == reference.assignment(
                full_name
            )

    resyncs = DEFAULT_REGISTRY.counter("bst_oracle_wire_delta_resyncs_total")
    kinds = DEFAULT_REGISTRY.counter("bst_oracle_wire_delta_batches_total")
    try:
        # healthy baseline on the primary: keyframe, then a delta
        refresh_and_compare()
        cluster.bind(make_pod("pre-kill-filler", requests={"cpu": "2"}), "n0")
        refresh_and_compare()
        primary_addr = client.active_address

        # the crash: every primary connection RSTs, new dials refused
        chaos.kill_endpoint()
        resyncs_before = resyncs.value()
        keyframes_before = kinds.value(kind="keyframe")
        cluster.bind(make_pod("kill-filler", requests={"cpu": "2"}), "n1")
        refresh_and_compare()

        # promoted, resynced through a keyframe, plans exact
        assert client.active_address != primary_addr
        assert client.active_address == standby.address
        assert resyncs.value() >= resyncs_before + 1
        assert kinds.value(kind="keyframe") >= keyframes_before + 1
        failovers = reg.counter("bst_oracle_failover_total")
        pool_label = "%s:%s,%s:%s" % (chaos.address + standby.address)
        assert failovers.value(reason="crash", client=pool_label) >= 1

        # steady state on the standby: churn rides deltas again
        cluster.bind(make_pod("post-kill-filler", requests={"cpu": "2"}), "n2")
        deltas_before = kinds.value(kind="delta")
        refresh_and_compare()
        assert kinds.value(kind="delta") == deltas_before + 1
    finally:
        remote.close()
        chaos.stop()
        standby.shutdown()
        standby.server_close()


def test_draining_during_coalesced_mega_batch():
    """DRAINING lands while a coalesced mega-batch is in flight: the
    admitted group must finish (drain waits out the in-flight window —
    zero client-visible errors), and every tenant's NEXT batch promotes
    to the standby. The coalescer is flushed as part of the drain's
    producer-before-join order, so no merged group is lost half-applied."""
    import threading

    from batch_scheduler_tpu.service.client import active_failover_report
    from batch_scheduler_tpu.service.coalescer import OracleCoalescer
    from batch_scheduler_tpu.service.server import _capacity_tenant_shares

    primary = serve_background(coalesce=True)
    primary.scan_mesh = None
    primary.executor.scan_mesh = None
    if primary.coalescer is None:
        primary.coalescer = OracleCoalescer(
            primary.executor, weights_fn=_capacity_tenant_shares
        )
    primary.coalescer.mode = "mega"
    standby = serve_background()
    spec = "%s:%s,%s:%s" % (primary.address + standby.address)
    reg = Registry()
    tenants = [f"ha-t{i}" for i in range(4)]
    clients = {
        t: _pooled(spec, reg, timeout=30.0, name=t) for t in tenants
    }
    results = {t: [] for t in tenants}
    errors = []
    barrier = threading.Barrier(len(tenants))
    drained = threading.Event()

    def run(tenant):
        try:
            for i in range(3):
                barrier.wait(timeout=30)
                if tenant == tenants[0] and i == 1 and not drained.is_set():
                    # fire the drain while every tenant's batch i=1 is
                    # in flight (or queued in the coalescer)
                    drained.set()
                    threading.Thread(
                        target=lambda: primary.drain(timeout=15.0),
                        daemon=True,
                    ).start()
                resp = clients[tenant].schedule(_request(), tenant=tenant)
                results[tenant].append(np.asarray(resp.placed).copy())
        except Exception as e:  # noqa: BLE001 — collected, asserted empty
            errors.append((tenant, repr(e)))

    threads = [
        threading.Thread(target=run, args=(t,), daemon=True)
        for t in tenants
    ]
    try:
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=120)
            assert not th.is_alive()
        # zero client-visible errors and zero lost batches
        assert errors == []
        for t in tenants:
            assert len(results[t]) == 3
            for placed in results[t]:
                assert placed.all()
        # every tenant promoted off the draining primary
        for t in tenants:
            assert clients[t].active_address == standby.address, t
        report = primary.drain()  # idempotent: returns the first report
        assert report["drained"] is True
        assert report["audit_flushed"] is True
        rows = {
            c["client"]: c
            for c in active_failover_report()["clients"]
        }
        for t in tenants:
            reasons = {p["reason"] for p in rows[t]["promotions"]}
            assert "drain" in reasons, (t, rows[t])
    finally:
        for c in clients.values():
            c.close()
        primary.shutdown()
        primary.server_close()
        standby.shutdown()
        standby.server_close()


def test_failover_races_half_open_probe(server):
    """Promotion interleaved with the breaker's half-open lifecycle: the
    client crashes off the primary, then — when the standby dies after
    the primary's cooldown has elapsed — promotes BACK onto the primary
    through its half-open probe slot. The successful probe closes the
    breaker; the request is served, not refused."""
    standby = serve_background()
    chaos_primary = ChaosProxy(*server.address)
    chaos_standby = ChaosProxy(*standby.address)
    reg = Registry()
    client = _pooled(
        "%s:%s,%s:%s" % (chaos_primary.address + chaos_standby.address),
        reg,
        reset_timeout=0.3,
    )
    primary_addr = tuple(chaos_primary.address)
    standby_addr = tuple(chaos_standby.address)
    try:
        assert client.schedule(_request()).placed.all()
        assert client.active_address == primary_addr

        # crash the primary: trip, promote, serve from the standby
        chaos_primary.kill_endpoint()
        assert client.schedule(_request()).placed.all()
        assert client.active_address == standby_addr
        assert client._breakers[0].state == "open"

        # primary heals; its cooldown elapses (half-open probe eligible)
        chaos_primary.restore_endpoint()
        time.sleep(0.35)

        # the standby dies exactly when the primary's breaker is waiting
        # on its half-open probe: promotion must route the request back
        # through that probe slot and close the breaker on success
        chaos_standby.kill_endpoint()
        assert client.schedule(_request()).placed.all()
        assert client.active_address == primary_addr
        assert client._breakers[0].state == "closed"
        assert client._breakers[1].state == "open"
        # both hops are in the promotion history, both as crashes
        hops = [(reason, to) for _ts, reason, to in client._promotions]
        assert hops == [("crash", 1), ("crash", 0)]
    finally:
        client.close()
        chaos_primary.stop()
        chaos_standby.stop()
        standby.shutdown()
        standby.server_close()
