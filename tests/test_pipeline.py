"""Overlapped-batch pipeline (docs/pipelining.md): delta snapshot packing
bit-identity, dispatch-ahead plan identity under concurrent mutation,
the pipelined sidecar device executor's deadline chaos case, the
compile-ahead bucket warmer, and the windowed client's slot pinning."""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from batch_scheduler_tpu.cache import PGStatusCache
from batch_scheduler_tpu.core.oracle_scorer import OracleScorer
from batch_scheduler_tpu.ops.snapshot import (
    ClusterSnapshot,
    DeltaSnapshotPacker,
    GroupDemand,
)

from helpers import FakeCluster, make_group, make_node, make_pod, status_for

_SNAP_ATTRS = (
    "alloc", "requested", "group_req", "remaining", "fit_mask",
    "group_valid", "order", "min_member", "scheduled", "matched",
    "ineligible", "creation_rank", "node_valid",
)


def _assert_snapshots_identical(a: ClusterSnapshot, b: ClusterSnapshot):
    for attr in _SNAP_ATTRS:
        np.testing.assert_array_equal(
            getattr(a, attr), getattr(b, attr), err_msg=attr
        )


def _nodes(n=12):
    return [
        make_node(f"n{i:03d}", {"cpu": "8", "memory": "32Gi", "pods": "110"})
        for i in range(n)
    ]


def _demands(g=4):
    return [
        GroupDemand(
            f"default/g{i}", 3, member_request={"cpu": 1000},
            creation_ts=float(i),
        )
        for i in range(g)
    ]


# -- delta snapshot packing -------------------------------------------------


def test_delta_pack_bit_identical_across_churn():
    nodes, groups = _nodes(), _demands()
    node_req = {"n003": {"cpu": 2000, "pods": 2}}
    packer = DeltaSnapshotPacker()

    _assert_snapshots_identical(
        ClusterSnapshot(nodes, node_req, groups),
        packer.pack(nodes, node_req, groups),
    )
    assert packer.full_repacks == 1

    # no churn: zero rows rewritten, still identical
    delta = packer.pack(nodes, node_req, groups)
    assert packer.delta_packs == 1 and packer.last_rows_rewritten == 0
    _assert_snapshots_identical(ClusterSnapshot(nodes, node_req, groups), delta)

    # churn one node's requested accounting: exactly one row rewritten
    node_req2 = dict(node_req)
    node_req2["n005"] = {"cpu": 4000, "pods": 4}
    delta = packer.pack(nodes, node_req2, groups)
    assert packer.last_rows_rewritten == 1 and packer.delta_packs == 2
    _assert_snapshots_identical(ClusterSnapshot(nodes, node_req2, groups), delta)

    # node-OBJECT churn (resource_version bump) full-repacks: the lane
    # shifts are sized from alloc peaks, so alloc churn must re-collect
    # the schema like the old per-batch reuse did
    nodes[7].status.allocatable["cpu"] = 16000
    nodes[7].metadata.resource_version = "rv-bumped"
    delta = packer.pack(nodes, node_req2, groups)
    assert packer.full_repacks == 2
    _assert_snapshots_identical(ClusterSnapshot(nodes, node_req2, groups), delta)

    # group membership churn rides the memo, no node rows rewritten
    groups2 = groups[1:] + [
        GroupDemand("default/new", 2, member_request={"cpu": 500})
    ]
    delta = packer.pack(nodes, node_req2, groups2)
    assert packer.last_rows_rewritten == 0
    _assert_snapshots_identical(
        ClusterSnapshot(nodes, node_req2, groups2), delta
    )


def test_delta_pack_schema_change_forces_full_repack():
    nodes, groups = _nodes(), _demands()
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, {}, groups)

    # a new resource NAME (extended resource) cannot pack under the cached
    # schema: full repack, still bit-identical to the from-scratch pack
    groups2 = groups + [
        GroupDemand(
            "default/gpu", 1,
            member_request={"cpu": 1000, "nvidia.com/gpu": 1},
        )
    ]
    delta = packer.pack(nodes, {}, groups2)
    assert packer.full_repacks == 2
    _assert_snapshots_identical(ClusterSnapshot(nodes, {}, groups2), delta)

    # node-list change (scale-up) also full-repacks and stays identical
    nodes2 = nodes + [make_node("n999", {"cpu": "8", "pods": "110"})]
    delta = packer.pack(nodes2, {}, groups2)
    assert packer.full_repacks == 3
    _assert_snapshots_identical(ClusterSnapshot(nodes2, {}, groups2), delta)


def test_delta_pack_schema_narrows_when_big_node_shrinks():
    """A node whose huge value forced a coarser lane shift later shrinking
    must NARROW the schema back (full repack on the node's version bump),
    not keep packing at the stale coarse granularity — review finding."""
    nodes = _nodes(4)
    # 2 TiB memory forces the memory lane to shift=1 (2 KiB units)
    nodes[0].status.allocatable["memory"] = 2 * 1024**4
    groups = _demands(2)
    packer = DeltaSnapshotPacker()
    first = packer.pack(nodes, {}, groups)
    assert packer.schema.shifts[packer.schema.index["memory"]] >= 1

    nodes[0].status.allocatable["memory"] = 32 * 1024**3
    nodes[0].metadata.resource_version = "shrunk"
    delta = packer.pack(nodes, {}, groups)
    assert packer.full_repacks == 2
    assert packer.schema.shifts[packer.schema.index["memory"]] == 0
    _assert_snapshots_identical(ClusterSnapshot(nodes, {}, groups), delta)
    del first


def test_delta_pack_snapshot_isolated_from_later_mutation():
    """A published snapshot must stay what was scored: later packs (which
    mutate the packer's persistent buffers) must not reach into it."""
    nodes, groups = _nodes(), _demands()
    packer = DeltaSnapshotPacker()
    first = packer.pack(nodes, {}, groups)
    before = first.alloc.copy()
    nodes[2].status.allocatable["cpu"] = 1000
    nodes[2].metadata.resource_version = "rv2"
    packer.pack(nodes, {}, groups)
    np.testing.assert_array_equal(first.alloc, before)


# -- dispatch-ahead ---------------------------------------------------------


def _gang_cluster(n_nodes=5, n_gangs=3):
    cluster = FakeCluster(_nodes(n_nodes))
    cache = PGStatusCache()
    names = []
    for i in range(n_gangs):
        name = f"gang{i}"
        pg = make_group(name, 3, creation_ts=float(i))
        members = [
            make_pod(f"{name}-{m}", group=name, requests={"cpu": "1"})
            for m in range(3)
        ]
        status_for(pg, cache, rep_pod=members[0])
        names.append(f"default/{name}")
    return cluster, cache, names


def _wait_for_spec(scorer, timeout=15.0):
    deadline = time.monotonic() + timeout

    def banked():
        # _spec travels under the refresh lock (guarded-by annotation);
        # polling takes it briefly each probe
        with scorer._refresh_lock:
            return scorer._spec is not None

    while not banked() and time.monotonic() < deadline:
        if scorer._spec_error is not None:
            raise AssertionError(scorer._spec_error)
        time.sleep(0.01)
    assert banked(), "speculative batch never banked"


def test_dispatch_ahead_bit_identical_under_concurrent_mutation():
    """The satellite invariant: dispatch-ahead plans are bit-identical to
    serial execution, and a mark_dirty landing mid-flight DISCARDS the
    speculative batch instead of serving it."""
    cluster, cache, names = _gang_cluster()
    serial = OracleScorer()
    ahead = OracleScorer(dispatch_ahead=True)
    try:
        for round_no in range(3):
            serial.mark_dirty()
            serial.ensure_fresh(cluster, cache, group=names[0])
            # let the speculative batch (packed BEFORE this round's
            # mutation) land, then invalidate it mid-flight
            if round_no:
                _wait_for_spec(ahead)
            ahead.mark_dirty()
            ahead.ensure_fresh(cluster, cache, group=names[0])
            for name in names:
                assert ahead.placed(name) == serial.placed(name), name
                assert ahead.gang_feasible(name) == serial.gang_feasible(name)
                assert ahead.assignment(name) == serial.assignment(name), name
            # mutate cluster state so the next round's plans differ
            cluster.bind(
                make_pod(f"filler-{round_no}", requests={"cpu": "4"}),
                f"n{round_no:03d}",
            )
        # every banked speculative batch predated a mark_dirty: all discarded
        assert ahead.spec_served == 0
        assert ahead.spec_discarded >= 1
    finally:
        assert ahead.drain_background()


def test_dispatch_ahead_serves_speculative_batch_when_state_unchanged():
    cluster, cache, names = _gang_cluster()
    ahead = OracleScorer(dispatch_ahead=True)
    try:
        ahead.ensure_fresh(cluster, cache, group=names[0])
        _wait_for_spec(ahead)
        # staleness whose cause PREDATES the speculative pack: clear the
        # banked spec, mark dirty, re-kick (packs at the new generation),
        # then consume — no blocking batch needed
        with ahead._refresh_lock:
            ahead._spec = None
        with ahead._spec_lock:  # guarded state, read guarded (lockcheck)
            spec_thread = ahead._spec_thread
        if spec_thread is not None:
            spec_thread.join(15.0)
        ahead.mark_dirty()
        ahead._kick_speculative(cluster, cache)
        _wait_for_spec(ahead)
        before = ahead.batches_run
        ahead.ensure_fresh(cluster, cache, group=names[0])
        assert ahead.spec_served == 1
        assert ahead.batches_run == before + 1
        stats = ahead.stats()
        assert stats["spec_served"] == 1
    finally:
        assert ahead.drain_background()


# -- pipelined sidecar executor ---------------------------------------------


def test_executor_deadline_on_inflight_batch_leaves_queued_batch_intact(
    monkeypatch,
):
    """Chaos case: the in-flight batch blows its DEADLINE while another
    connection's batch is queued behind it — the queued batch must come
    back complete and correct (the executor collects the abandoned batch
    instead of corrupting the pipeline)."""
    import batch_scheduler_tpu.service.server as server_mod
    from batch_scheduler_tpu.service import OracleClient, serve_background
    from batch_scheduler_tpu.utils import errors as errs
    from test_service import _request

    srv = serve_background()
    try:
        stall_started = threading.Event()
        stalled_once = []
        real = server_mod.dispatch_batch

        def stalling_dispatch(*args, **kwargs):
            if not stalled_once:
                stalled_once.append(1)
                stall_started.set()
                time.sleep(1.2)
            return real(*args, **kwargs)

        client_a = OracleClient(*srv.address)
        client_b = OracleClient(*srv.address)
        # warm the jit cache so the stall is the ONLY slow thing
        assert client_a.schedule(_request()).placed.all()
        monkeypatch.setattr(server_mod, "dispatch_batch", stalling_dispatch)

        b_result = {}

        def run_b():
            stall_started.wait(10.0)
            b_result["resp"] = client_b.schedule(_request())

        t = threading.Thread(target=run_b, daemon=True)
        t.start()
        from batch_scheduler_tpu.service import protocol as proto  # noqa: F401

        with pytest.raises(errs.OracleDeadlineError):
            # deadline client-side path: raw client honors server frame
            client_a._round_trip(
                proto.MsgType.SCHEDULE_REQ,
                proto.pack_schedule_request(_request()),
                deadline_ms=200,
            )
        t.join(30.0)
        assert not t.is_alive(), "queued batch never completed"
        resp = b_result["resp"]
        assert resp.placed.tolist() == [True, True]
        assert resp.gang_feasible.tolist() == [True, True]

        # connection A stays usable after its deadline miss
        monkeypatch.setattr(server_mod, "dispatch_batch", real)
        assert client_a.schedule(_request()).placed.all()
        client_a.close()
        client_b.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_executor_total_order_row_reads_across_pipelined_batches():
    """Row fetches issued while later batches pipeline through the
    executor answer from the right batch (per-connection state + executor
    total order)."""
    from batch_scheduler_tpu.service import OracleClient, serve_background
    from test_service import _request

    srv = serve_background()
    try:
        clients = [OracleClient(*srv.address) for _ in range(3)]
        resps = [c.schedule(_request()) for c in clients]
        rows = [
            c.row("capacity", 0, r.batch_seq)
            for c, r in zip(clients, resps)
        ]
        for row in rows:
            assert row[:4].min() >= 1
        for c in clients:
            c.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_executor_refuses_jobs_after_stop():
    """Submissions after stop() fail fast, and a job that raced past the
    check into the queue behind the stop sentinel is FAILED by the drain
    instead of blocking its waiter forever — review finding."""
    from batch_scheduler_tpu.service.server import (
        DeviceExecutor,
        _EXEC_STOP,
        _ExecJob,
    )

    ex = DeviceExecutor()
    assert ex.run(lambda: 41 + 1) == 42

    # hold the loop on a slow job so a straggler can be staged BEHIND the
    # sentinel (the exact interleaving of a submit racing stop())
    gate = threading.Event()
    slow = ex._submit(_ExecJob("call", fn=lambda: gate.wait(10.0)))
    ex._stopped = True
    ex._q.put(_EXEC_STOP)
    straggler = _ExecJob("call", fn=lambda: None)
    ex._q.put(straggler)
    gate.set()
    assert slow.wait(10.0) is True
    with pytest.raises(RuntimeError, match="stopped"):
        straggler.wait(10.0)
    with pytest.raises(RuntimeError, match="stopped"):
        ex.run(lambda: None)
    assert ex.stop()


# -- compile-ahead bucket warmer --------------------------------------------


def test_compile_warmer_precompiles_adjacent_shapes():
    from batch_scheduler_tpu.ops.bucketing import (
        CompileWarmer,
        adjacent_bucket_shapes,
        pad_oracle_batch,
    )
    from batch_scheduler_tpu.ops.oracle import collect_batch, dispatch_batch
    from batch_scheduler_tpu.utils.metrics import Registry

    assert adjacent_bucket_shapes(16, 32) == [
        (8, 32), (32, 32), (16, 16), (16, 64),
    ]
    assert adjacent_bucket_shapes(8, 8) == [(16, 8), (8, 16)]

    def args_for(g, n, r=2):
        return pad_oracle_batch(
            alloc=np.full((n, r), 32, np.int32),
            requested=np.zeros((n, r), np.int32),
            group_req=np.ones((g, r), np.int32),
            remaining=np.full(g, 2, np.int32),
            fit_mask=np.ones((1, n), bool),
            group_valid=np.ones(g, bool),
            order=np.arange(g, dtype=np.int32),
            min_member=np.full(g, 2, np.int32),
            scheduled=np.zeros(g, np.int32),
            matched=np.zeros(g, np.int32),
            ineligible=np.zeros(g, bool),
            creation_rank=np.arange(g, dtype=np.int32),
        )

    reg = Registry()
    warmer = CompileWarmer(registry=reg)
    try:
        base = args_for(8, 8)
        host, _ = collect_batch(dispatch_batch(*base))
        warmer.note_batch(base[0], base[1], host["telemetry"])
        deadline = time.monotonic() + 120.0
        while len(warmer.warmed_shapes()) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(warmer.warmed_shapes()) == 2

        # the bucket transition: a serving batch at the precompiled shape
        # hits the jit cache (compiled False) and counts as a warmer hit
        trans = args_for(16, 8)
        host, _ = collect_batch(dispatch_batch(*trans))
        assert host["telemetry"]["compiled"] is False
        warmer.note_batch(trans[0], trans[1], host["telemetry"])
        assert warmer.stats()["warmer_hits"] == 1
        # steady batches at the now-served shape are NOT further hits
        host, _ = collect_batch(dispatch_batch(*trans))
        warmer.note_batch(trans[0], trans[1], host["telemetry"])
        assert warmer.stats()["warmer_hits"] == 1
    finally:
        assert warmer.stop()


# -- windowed resilient client ----------------------------------------------


def test_windowed_client_slots_pin_batches_to_connections():
    from batch_scheduler_tpu.service import (
        RemoteScorer,
        ResilientOracleClient,
        serve_background,
    )
    from test_service import _request

    srv = serve_background()
    try:
        client = ResilientOracleClient(*srv.address, window=2)
        s0, s1 = client.slot(0), client.slot(1)
        r0 = s0.schedule(_request())
        r1 = s1.schedule(_request())
        # per-connection batch state: each slot's rows answer for ITS batch
        assert s0.row("capacity", 0, r0.batch_seq)[:4].min() >= 1
        assert s1.row("capacity", 0, r1.batch_seq)[:4].min() >= 1
        # a second batch on slot 1 must not invalidate slot 0's batch
        r1b = s1.schedule(_request())
        assert r1b.batch_seq != r0.batch_seq or True
        assert s0.row("capacity", 1, r0.batch_seq)[:4].min() >= 1

        # RemoteScorer picks up the two lanes from the window
        scorer = RemoteScorer(client)
        assert scorer.supports_background_refresh
        assert scorer.supports_dispatch_ahead
        client.close()

        single = ResilientOracleClient(*srv.address)
        scorer = RemoteScorer(single)
        assert not scorer.supports_dispatch_ahead
        with pytest.raises(IndexError):
            single.slot(1)
        single.close()
    finally:
        srv.shutdown()
        srv.server_close()
