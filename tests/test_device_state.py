"""Device-resident cluster state (ops.device_state, docs/pipelining.md
"Device-resident state"): the packer's churned-row delta records, the
holder's scatter-apply vs keyframe-resync transitions (bit-identity against
the host-packed snapshot at every step), the BST_DEVICE_STATE knob, the
wire delta protocol frames, and the RemoteScorer fallback matrix (old
peers, plain clients)."""

import numpy as np
import pytest

from batch_scheduler_tpu.ops.device_state import (
    DeviceStateHolder,
    device_state_enabled,
    device_state_report,
)
from batch_scheduler_tpu.ops.snapshot import DeltaSnapshotPacker, GroupDemand
from batch_scheduler_tpu.service import protocol as proto

from helpers import make_node


def _world(n=8, g=4):
    nodes = [
        make_node(f"n{i:02d}", {"cpu": "16", "memory": "64Gi", "pods": "110"})
        for i in range(n)
    ]
    groups = [
        GroupDemand(
            full_name=f"default/gang-{i}",
            min_member=3,
            member_request={"cpu": 2000, "memory": 4 * 1024**3},
            creation_ts=float(i),
        )
        for i in range(g)
    ]
    node_req = {
        nd.metadata.name: {"cpu": 1000 * (i % 3), "pods": i % 4}
        for i, nd in enumerate(nodes)
    }
    return nodes, groups, node_req


def _args_equal(device_args, snap):
    host = snap.device_args()
    return all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(device_args, host)
    )


# -- packer delta records ---------------------------------------------------


def test_packer_emits_keyframe_then_deltas():
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    snap = packer.pack(nodes, node_req, groups)
    assert snap.delta.kind == "keyframe"
    assert snap.delta.reason == "first"
    assert snap.delta.generation == 1

    node_req["n03"] = {"cpu": 9000, "pods": 3}
    snap2 = packer.pack(nodes, node_req, groups)
    assert snap2.delta.kind == "delta"
    assert snap2.delta.generation == 2
    assert snap2.delta.node_rows.tolist() == [3]
    assert snap2.delta.group_rows.tolist() == []

    # group demand churn: positional group row listed
    groups[1].member_request = {"cpu": 3000}
    snap3 = packer.pack(nodes, node_req, groups)
    assert snap3.delta.kind == "delta"
    assert snap3.delta.group_rows.tolist() == [1]


def test_packer_keyframe_reasons():
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    packer.pack(nodes, node_req, groups)

    # node OBJECT churn (resource_version bump) -> full repack -> keyframe
    nodes[2].metadata.resource_version = "bumped"
    snap = packer.pack(nodes, node_req, groups)
    assert (snap.delta.kind, snap.delta.reason) == ("keyframe", "node-churn")

    # group set change -> positional indices break -> keyframe
    groups.append(
        GroupDemand(
            full_name="default/late", min_member=1,
            member_request={"cpu": 100}, creation_ts=99.0,
        )
    )
    snap = packer.pack(nodes, node_req, groups)
    assert (snap.delta.kind, snap.delta.reason) == ("keyframe", "group-set")

    # node list change
    nodes2 = nodes[:-1]
    snap = packer.pack(nodes2, node_req, groups)
    assert (snap.delta.kind, snap.delta.reason) == ("keyframe", "node-list")


# -- holder transitions -----------------------------------------------------


def test_holder_scatter_matches_host_pack_bitwise():
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    holder = DeviceStateHolder(label="test")
    snap = packer.pack(nodes, node_req, groups)
    args = holder.sync(snap)
    assert _args_equal(args, snap)
    assert holder.stats()["keyframes"] == {"first": 1}

    for round_no in range(3):
        node_req[f"n{round_no:02d}"] = {"cpu": 500 + round_no, "pods": 1}
        groups[round_no % len(groups)].member_request = {
            "cpu": 1000 + round_no
        }
        snap = packer.pack(nodes, node_req, groups)
        args = holder.sync(snap)
        assert snap.delta.kind == "delta"
        assert _args_equal(args, snap), f"divergence at round {round_no}"
    stats = holder.stats()
    assert stats["deltas_applied"] == 3
    assert stats["rows_scattered"] >= 6  # one node + one group row per round
    assert stats["generation"] == snap.delta.generation


def test_holder_generation_gap_forces_keyframe():
    """A pack whose delta never reached the holder (the forbidden silent
    case) must resync from a keyframe — never scatter a later delta on top
    of a stale base."""
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    holder = DeviceStateHolder(label="test")
    holder.sync(packer.pack(nodes, node_req, groups))

    node_req["n01"] = {"cpu": 777}
    packer.pack(nodes, node_req, groups)  # delta NOT synced: the gap
    node_req["n02"] = {"cpu": 888}
    snap = packer.pack(nodes, node_req, groups)
    args = holder.sync(snap)
    assert _args_equal(args, snap)  # exact anyway — via keyframe
    assert holder.stats()["keyframes"].get("generation") == 1


def test_holder_apply_rows_refuses_stale_base():
    """The wire-mirror form of the same contract: apply_rows with a
    mismatched base generation returns None (the server answers
    DELTA_RESYNC on it), and a duplicate application is refused."""
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    holder = DeviceStateHolder(label="test")
    snap = packer.pack(nodes, node_req, groups)
    holder.keyframe(snap.device_args(), 7, "wire-keyframe")

    node_req["n04"] = {"cpu": 4242}
    snap2 = packer.pack(nodes, node_req, groups)
    idx = snap2.delta.node_rows
    update = (idx, np.asarray(snap2.requested)[idx])
    small = (snap2.remaining, snap2.fit_mask, snap2.group_valid, snap2.order)
    out = holder.apply_rows(7, 8, update, None, small)
    assert out is not None
    # the duplicate: same delta again — base 7 no longer matches mirror 8
    assert holder.apply_rows(7, 8, update, None, small) is None
    # and a gapped future delta is refused too
    assert holder.apply_rows(9, 10, update, None, small) is None


def test_holder_bucket_growth_keyframes():
    nodes, groups, node_req = _world(n=8)
    packer = DeltaSnapshotPacker()
    holder = DeviceStateHolder(label="test")
    holder.sync(packer.pack(nodes, node_req, groups))
    # enough new nodes to cross the padded node bucket -> shapes change
    big_nodes = nodes + [
        make_node(f"x{i}", {"cpu": "16", "memory": "64Gi", "pods": "110"})
        for i in range(32)
    ]
    snap = packer.pack(big_nodes, node_req, groups)
    args = holder.sync(snap)
    assert _args_equal(args, snap)
    assert snap.delta.reason == "node-list"
    assert holder.stats()["keyframes"].get("node-list") == 1


def test_holder_report_registry():
    holder = DeviceStateHolder(label="report-probe")
    labels = [h["label"] for h in device_state_report()]
    assert "report-probe" in labels
    del holder


# -- knob ------------------------------------------------------------------


def test_device_state_knob_parse_guard(monkeypatch):
    monkeypatch.delenv("BST_DEVICE_STATE", raising=False)
    assert device_state_enabled() is True
    monkeypatch.setenv("BST_DEVICE_STATE", "0")
    assert device_state_enabled() is False
    monkeypatch.setenv("BST_DEVICE_STATE", "off")
    assert device_state_enabled() is False
    # unparseable degrades to the default, never raises
    monkeypatch.setenv("BST_DEVICE_STATE", "bananas")
    assert device_state_enabled() is True


# -- wire frames ------------------------------------------------------------


def _delta_request(n=6, g=3, r=4):
    rng = np.random.RandomState(0)
    return proto.DeltaScheduleRequest(
        node_idx=np.array([1, 4], np.int32),
        node_rows=rng.randint(0, 99, (2, r)).astype(np.int32),
        group_idx=np.array([2], np.int32),
        group_rows=rng.randint(0, 99, (1, r)).astype(np.int32),
        remaining=rng.randint(0, 5, g).astype(np.int32),
        fit_mask=np.ones((1, n), bool),
        group_valid=np.ones(g, bool),
        order=np.arange(g, dtype=np.int32),
        min_member=np.full(g, 3, np.int32),
        scheduled=np.zeros(g, np.int32),
        matched=np.zeros(g, np.int32),
        ineligible=np.zeros(g, bool),
        creation_rank=np.arange(g, dtype=np.int32),
        n=n,
        g=g,
        r=r,
    )


def test_delta_rows_frame_roundtrip():
    d = _delta_request()
    payload = proto.pack_delta_rows(41, 42, d)
    kind, base_gen, new_gen, out = proto.unpack_delta_schedule_request(payload)
    assert (kind, base_gen, new_gen) == (proto.DELTA_ROWS, 41, 42)
    for field in (
        "node_idx", "node_rows", "group_idx", "group_rows", "remaining",
        "fit_mask", "group_valid", "order", "min_member", "scheduled",
        "matched", "ineligible", "creation_rank",
    ):
        assert np.array_equal(getattr(out, field), getattr(d, field)), field
    assert (out.n, out.g, out.r) == (d.n, d.g, d.r)


def test_delta_keyframe_frame_is_a_schedule_request():
    nodes, groups, node_req = _world()
    snap = DeltaSnapshotPacker().pack(nodes, node_req, groups)
    req = proto.ScheduleRequest(
        alloc=snap.alloc, requested=snap.requested, group_req=snap.group_req,
        remaining=snap.remaining, fit_mask=snap.fit_mask,
        group_valid=snap.group_valid, order=snap.order,
        min_member=snap.min_member, scheduled=snap.scheduled,
        matched=snap.matched, ineligible=snap.ineligible,
        creation_rank=snap.creation_rank,
    )
    payload = proto.pack_delta_keyframe(9, req)
    kind, _, new_gen, out = proto.unpack_delta_schedule_request(payload)
    assert (kind, new_gen) == (proto.DELTA_KEYFRAME, 9)
    assert np.array_equal(out.alloc, np.asarray(snap.alloc))
    assert np.array_equal(out.requested, np.asarray(snap.requested))


def test_delta_resync_roundtrip():
    reason = "generation gap: mirror at 3, delta base 1"
    assert proto.unpack_delta_resync(proto.pack_delta_resync(reason)) == reason


def test_delta_rows_frame_rejects_trailing_bytes():
    payload = proto.pack_delta_rows(1, 2, _delta_request()) + b"x"
    with pytest.raises(ValueError):
        proto.unpack_delta_schedule_request(payload)


# -- RemoteScorer fallback matrix ------------------------------------------


class _FakeResilient:
    """Just enough surface for RemoteScorer's wire-delta gating."""

    window = 1

    def would_attempt(self):
        return True

    def delta_schedule(self, *a, **k):
        raise RuntimeError("oracle server error: unknown message type 14")

    def schedule(self, *a, **k):
        raise AssertionError("not exercised here")

    def close(self):
        pass


def test_wire_delta_gating():
    from batch_scheduler_tpu.service.client import OracleClient, RemoteScorer

    # a resilient-shaped transport gets the delta path
    scorer = RemoteScorer(_FakeResilient())
    assert scorer._wire_delta_ok
    # a plain OracleClient (no reconnect: resync recovery needs re-dial)
    # stays on full snapshots
    plain = OracleClient.__new__(OracleClient)  # no real socket
    scorer2 = RemoteScorer(plain)
    assert not scorer2._wire_delta_ok


def test_old_peer_falls_back_to_full_snapshots(monkeypatch):
    """A peer without MsgType 14 answers an in-band unknown-message-type
    error: the scorer must permanently drop to full snapshots (bit-
    identical path) instead of erroring every batch."""
    from batch_scheduler_tpu.service.client import RemoteScorer

    sent = []

    class _OldPeer(_FakeResilient):
        def schedule(self, req, **k):
            sent.append("full")
            raise RuntimeError("stub transport: no real server")

    scorer = RemoteScorer(_OldPeer())
    nodes, groups, node_req = _world()
    snap = DeltaSnapshotPacker().pack(nodes, node_req, groups)
    scorer._note_pack(snap)
    with pytest.raises(RuntimeError, match="stub transport"):
        scorer._execute(snap)
    assert sent == ["full"]
    assert not scorer._wire_delta_ok


def test_apply_rows_refuses_negative_indices():
    """A negative scatter index would WRAP in .at[].set and corrupt an
    unrelated resident row — it must be refused (resync), like any other
    out-of-range index (review finding)."""
    nodes, groups, node_req = _world()
    packer = DeltaSnapshotPacker()
    holder = DeviceStateHolder(label="test")
    snap = packer.pack(nodes, node_req, groups)
    holder.keyframe(snap.device_args(), 1, "wire-keyframe")
    rows = np.asarray(snap.requested)[:1]
    small = (snap.remaining, snap.fit_mask, snap.group_valid, snap.order)
    bad = (np.array([-1], np.int32), rows)
    assert holder.apply_rows(1, 2, bad, None, small) is None
    assert holder.apply_rows(1, 2, None, bad, small) is None
    # the refusal must not have advanced the generation
    assert holder.current_generation() == 1


def test_wire_delta_rows_lane_domain_enforced():
    """The delta path must enforce the same LANE_MAX boundary the
    full-snapshot wire path enforces in pad_oracle_batch — an
    out-of-domain lane raises OverflowError instead of reaching
    _exact_floordiv (review finding)."""
    from batch_scheduler_tpu.service.server import _pad_delta_request

    d = _delta_request()
    small, progress = _pad_delta_request(d)  # in-domain: fine
    assert small[0].shape[0] >= d.g and len(progress) == 5
    d.node_rows = d.node_rows.copy()
    d.node_rows[0, 0] = 2**30 + 1
    with pytest.raises(OverflowError, match="LANE_MAX"):
        _pad_delta_request(d)
