"""Interpreter-tuning knobs (utils/runtime_tuning.py): env parsing and
restore discipline — the measured framework is the deployed framework,
so the knobs must apply and fail-safe exactly as documented."""

import gc

import pytest

from batch_scheduler_tpu.utils.runtime_tuning import (
    _DEFAULT,
    apply_gc_tuning,
    freeze_startup,
)


@pytest.fixture
def restore_gc():
    prev = gc.get_threshold()
    yield
    gc.set_threshold(*prev)
    gc.unfreeze()


def test_default_thresholds_applied(restore_gc, monkeypatch):
    monkeypatch.delenv("BST_GC_THRESHOLD", raising=False)
    apply_gc_tuning()
    assert gc.get_threshold() == _DEFAULT


def test_env_override_and_zero_disables(restore_gc, monkeypatch):
    monkeypatch.setenv("BST_GC_THRESHOLD", "1234,56,78")
    apply_gc_tuning()
    assert gc.get_threshold() == (1234, 56, 78)

    prev = gc.get_threshold()
    monkeypatch.setenv("BST_GC_THRESHOLD", "0")
    apply_gc_tuning()  # "0" keeps whatever is set — no change
    assert gc.get_threshold() == prev


@pytest.mark.parametrize("bad", ["nope", "1,2", "1,2,3,4", "-5,1,1", "0,0,0"])
def test_malformed_env_falls_back_to_default(restore_gc, monkeypatch, bad):
    monkeypatch.setenv("BST_GC_THRESHOLD", bad)
    apply_gc_tuning()
    assert gc.get_threshold() == _DEFAULT


def test_freeze_startup_moves_objects_out_of_gc(restore_gc):
    freeze_startup()
    try:
        assert gc.get_freeze_count() > 0
    finally:
        gc.unfreeze()
    assert gc.get_freeze_count() == 0
