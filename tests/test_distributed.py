"""Two-process jax.distributed bootstrap test (VERDICT r1 item 7): the
multi-host path of parallel.distributed actually executes — coordinator
handshake, global mesh over both processes' devices, one sharded oracle
batch with cross-process collectives — on the CPU backend, localhost."""

import os
import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_batch():
    # Platform-conditional skip: the workers pop JAX_PLATFORMS and resolve
    # their own backend (the utils.backend probe — NOT this test process,
    # which conftest pins to a virtual CPU mesh). On a CPU-only host the
    # run fails identically at seed HEAD with "Multiprocess computations
    # aren't implemented on the CPU backend" (CHANGES.md PR 5), so the
    # tier-1 output would carry a known-environmental F — skip with the
    # reason instead; the test runs for real on the next TPU tunnel.
    from batch_scheduler_tpu.utils.backend import resolve_platform

    platform, _ = resolve_platform()
    if platform == "cpu":
        pytest.skip(
            "two-process collectives need a non-CPU backend: this jax "
            "build fails with \"Multiprocess computations aren't "
            "implemented on the CPU backend\" (pre-existing at seed HEAD, "
            "CHANGES.md PR 5)"
        )
    port = _free_port()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo_root, "tests", "distributed_worker.py")

    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)  # worker sets its own platform
        # 4 virtual devices per process -> 8-device global mesh (override
        # whatever the test session's conftest exported)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env.update(
            BST_COORDINATOR=f"127.0.0.1:{port}",
            BST_NUM_PROCESSES="2",
            BST_PROCESS_ID=str(pid),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, worker],
                cwd=repo_root,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )

    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed workers hung")
        outs.append((p.returncode, out, err))

    for rc, out, err in outs:
        assert rc == 0, f"worker failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
    # process 0 prints the summary line
    assert any("DIST-OK processes=2" in out for _, out, _ in outs), outs
