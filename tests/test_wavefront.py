"""Wavefront assignment scan: bit-identity against the serial scan on
every workload class (the tentpole contract — assign_gangs_wavefront
commits a wave only after proving its batched takes equal the serial
ones, and demotes contended waves to a serial replay), plus the
BST_SCAN_WAVE knob plumbing (bucketing, env parse guard, fallback
ladder) and the multi-device blob integrity fix the wavefront rides on.
"""

import os

import numpy as np
import pytest

from batch_scheduler_tpu.ops import oracle as omod
from batch_scheduler_tpu.ops.bucketing import (
    pad_oracle_batch,
    wave_width_bucket,
)
from batch_scheduler_tpu.ops.oracle import (
    assign_gangs,
    assign_gangs_wavefront,
    dispatch_batch,
    execute_batch_host,
    schedule_batch,
)
from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot, GroupDemand

from helpers import make_node


def _assert_identical(args, wave, trial=""):
    ref = [np.asarray(x) for x in assign_gangs(*args)]
    got = assign_gangs_wavefront(*args, wave=wave)
    for a, b, name in zip(ref, got, ("alloc", "placed", "left_after")):
        np.testing.assert_array_equal(
            a, np.asarray(b), err_msg=f"{name} wave={wave} {trial}"
        )
    return ref


def test_wave_width_bucket():
    assert wave_width_bucket(0) == 0
    assert wave_width_bucket(1) == 0
    assert wave_width_bucket(-3) == 0
    assert wave_width_bucket(2) == 2
    assert wave_width_bucket(3) == 4
    assert wave_width_bucket(8) == 8
    assert wave_width_bucket(9) == 16
    assert wave_width_bucket(33) == 32
    assert wave_width_bucket(10**6) == 32


# ONE fixed fuzz shape for every randomized test in this file: distinct
# shapes would each recompile the three-branch wavefront scan (seconds per
# variant) and blow the tier-1 wall-clock budget; value randomization over
# a fixed shape exercises the same code paths off the jit cache.
_FN, _FG, _FR = 12, 12, 3


def test_wavefront_bit_identity_fuzz():
    """Random workloads over both mask modes and two wave widths: the
    wavefront outputs must be EXACTLY the serial scan's."""
    rng = np.random.default_rng(17)
    for trial in range(10):
        left0 = rng.integers(0, 50, size=(_FN, _FR)).astype(np.int32)
        group_req = rng.integers(0, 6, size=(_FG, _FR)).astype(np.int32)
        remaining = rng.integers(0, 16, size=_FG).astype(np.int32)
        order = rng.permutation(_FG).astype(np.int32)
        rows = 1 if trial % 2 == 0 else _FG
        fit_mask = rng.random((rows, _FN)) > 0.2
        for wave in (2, 8):
            _assert_identical(
                (left0, group_req, remaining, fit_mask, order),
                wave,
                f"trial={trial}",
            )


def test_wavefront_contended_workload_demotes_and_stays_identical():
    """Non-uniform gangs fighting for the same tight node: waves must
    demote to the serial replay, and the result must STILL be
    bit-identical (the conflict path IS the serial body)."""
    n, g, r = 2, 8, 1
    left0 = np.array([[10], [100]], np.int32)  # node 0 is the tight one
    # alternate request sizes so waves are NOT uniform (the identical-req
    # aggregate path would otherwise absorb the contention)
    group_req = np.array([[1 + (i % 2)] for i in range(g)], np.int32)
    remaining = np.full(g, 3, np.int32)
    order = np.arange(g, dtype=np.int32)
    mask = np.ones((1, n), bool)
    args = (left0, group_req, remaining, mask, order)
    _assert_identical(args, 4)
    *_, (conflicts, megas) = assign_gangs_wavefront(
        *args, wave=4, with_stats=True
    )
    assert np.asarray(conflicts).any(), (
        "contended waves should demote at least once"
    )
    assert not np.asarray(megas).any()


def test_wavefront_disjoint_masks_commit_conflict_free():
    """Gangs with disjoint feasible node sets (the provably-safe wave
    shape) commit on the speculative fast path: no wave demotes."""
    n, g, r = 8, 8, 1
    left0 = np.full((n, r), 10, np.int32)
    group_req = np.ones((g, r), np.int32)
    remaining = np.full(g, 5, np.int32)
    order = np.arange(g, dtype=np.int32)
    mask = np.zeros((g, n), bool)
    for i in range(g):
        mask[i, i] = True  # each gang sees only its own node
    args = (left0, group_req, remaining, mask, order)
    _assert_identical(args, 4)
    *_, (conflicts, _megas) = assign_gangs_wavefront(
        *args, wave=4, with_stats=True
    )
    assert not np.asarray(conflicts).any(), np.asarray(conflicts)


def test_wavefront_uniform_waves_use_aggregate_path():
    """A bulk submission of identical gangs (the north-star workload
    shape) commits all-feasible waves on the uniform aggregate path and
    stays bit-identical; a wave holding an infeasible gang demotes to
    the serial replay (the all-feasible boundary assumption fails) and
    STILL matches serial. Capacities above the histogram clamp included."""
    n, g, r = 6, 16, 2
    left0 = np.array(
        [[500, 9], [500, 9], [500, 3], [500, 200], [500, 200], [500, 0]],
        np.int32,
    )
    group_req = np.tile(np.array([[3, 1]], np.int32), (g, 1))
    # wave 0 carries gangs that need more than the whole cluster holds
    # (infeasible at their turn); wave 1 is all feasible
    remaining = np.array(
        [4, 4, 4, 900, 4, 4, 4, 900, 4, 4, 4, 4, 4, 4, 4, 4], np.int32
    )
    order = np.arange(g, dtype=np.int32)
    mask = np.ones((1, n), bool)
    args = (left0, group_req, remaining, mask, order)
    _assert_identical(args, 8)
    *_, (conflicts, megas) = assign_gangs_wavefront(
        *args, wave=8, with_stats=True
    )
    assert np.asarray(megas).all(), np.asarray(megas)
    # wave 0 demoted (infeasible gangs), wave 1 committed aggregate
    assert np.asarray(conflicts).tolist() == [True, False]


def test_wavefront_uniform_fuzz_vs_serial():
    """Randomized identical-req workloads (random caps, needs, masks,
    zero-req rows, bucket-clamp-sized capacities): the aggregate path
    must match the serial scan exactly. Fixed fuzz shape (jit cache)."""
    rng = np.random.default_rng(41)
    for trial in range(10):
        left0 = rng.integers(0, 400, size=(_FN, _FR)).astype(np.int32)
        one_req = rng.integers(0, 3, size=(1, _FR)).astype(np.int32)
        group_req = np.tile(one_req, (_FG, 1))
        remaining = rng.integers(0, 200, size=_FG).astype(np.int32)
        order = rng.permutation(_FG).astype(np.int32)
        mask = np.ones((1, _FN), bool)
        mask[0, rng.integers(0, _FN)] = bool(rng.integers(0, 2))
        _assert_identical(
            (left0, group_req, remaining, mask, order), 8, f"trial={trial}"
        )


def test_wavefront_selector_taint_mask_modes():
    """Per-group selector-style masks (partial overlap between gangs) —
    the mask rows ride the wave chunks pre-permuted. Fixed fuzz shape:
    shares the jit cache with the bit-identity fuzz."""
    rng = np.random.default_rng(29)
    for trial in range(5):
        left0 = rng.integers(0, 30, size=(_FN, _FR)).astype(np.int32)
        group_req = rng.integers(0, 4, size=(_FG, _FR)).astype(np.int32)
        remaining = rng.integers(1, 8, size=_FG).astype(np.int32)
        order = rng.permutation(_FG).astype(np.int32)
        mask = rng.random((_FG, _FN)) < 0.5
        for wave in (2, 8):
            _assert_identical(
                (left0, group_req, remaining, mask, order),
                wave,
                f"trial={trial}",
            )


def test_wavefront_padded_batch_and_edge_values():
    """Bucketed shapes with saturated/zero rows and values near the lane
    domain bound, through pad_oracle_batch (the production boundary)."""
    n, g, r = 5, 3, 2
    alloc = np.array(
        [[2**30, 4], [7, 4], [0, 0], [1, 1], [2**30, 2**30]], np.int32
    )
    requested = np.zeros((n, r), np.int32)
    group_req = np.array([[2**20, 1], [1, 0], [0, 0]], np.int32)
    remaining = np.array([4, 9, 0], np.int32)
    fit_mask = np.ones((1, n), bool)
    group_valid = np.ones(g, bool)
    order = np.array([2, 0, 1], np.int32)
    batch_args, _ = pad_oracle_batch(
        alloc, requested, group_req, remaining, fit_mask, group_valid, order,
        remaining, np.zeros(g, np.int32), np.zeros(g, np.int32),
        np.zeros(g, bool), np.arange(g, dtype=np.int32),
    )
    (p_alloc, p_req, p_gr, p_rem, p_mask, _, p_order) = batch_args
    left = p_alloc - p_req
    for wave in (2, 8):
        _assert_identical((left, p_gr, p_rem, p_mask, p_order), wave)


def test_schedule_batch_scan_wave_matches_serial():
    nodes = [
        make_node(f"n{i}", {"cpu": "16", "memory": "64Gi", "pods": "32"})
        for i in range(5)
    ]
    groups = [
        GroupDemand(f"default/g{i}", 3, member_request={"cpu": 1000})
        for i in range(4)
    ]
    snap = ClusterSnapshot(nodes, {}, groups)
    base = schedule_batch(*snap.device_args())
    wav = schedule_batch(*snap.device_args(), scan_wave=4)
    for key in ("placed", "assignment", "left_after", "gang_feasible"):
        np.testing.assert_array_equal(
            np.asarray(base[key]), np.asarray(wav[key]), err_msg=key
        )


def test_dispatch_batch_env_knob_and_parse_guard(monkeypatch):
    """BST_SCAN_WAVE plumbs through dispatch_batch bucketed; a typo'd
    value degrades to the serial scan (same guard idiom as
    BST_CHURN_PIPELINE_DEPTH) instead of failing the batch."""
    nodes = [make_node("n0", {"cpu": "8", "memory": "8Gi", "pods": "10"})]
    groups = [GroupDemand("default/g", 2, member_request={"cpu": 1000})]
    snap = ClusterSnapshot(nodes, {}, groups)

    monkeypatch.setenv("BST_SCAN_WAVE", "5")
    pend = dispatch_batch(snap.device_args(), snap.progress_args())
    assert pend.used_wave == 8  # bucketed up from 5
    host, _ = omod.collect_batch(pend)
    assert host["placed"][:1].tolist() == [True]

    monkeypatch.setenv("BST_SCAN_WAVE", "not-a-number")
    omod._wave_env_warned[0] = False
    pend = dispatch_batch(snap.device_args(), snap.progress_args())
    assert pend.used_wave == 0
    host, _ = omod.collect_batch(pend)
    assert host["placed"][:1].tolist() == [True]

    # the process-wide gate forces serial even with a valid knob
    monkeypatch.setenv("BST_SCAN_WAVE", "8")
    saved = omod._wave_enabled[0]
    try:
        omod._wave_enabled[0] = False
        pend = dispatch_batch(snap.device_args(), snap.progress_args())
        assert pend.used_wave == 0
    finally:
        omod._wave_enabled[0] = saved


def test_execute_batch_host_wave_equals_serial(monkeypatch):
    """The full blob path (the host-vector contract both the in-process
    scorer and the sidecar read) is byte-identical serial vs wavefront."""
    nodes = [
        make_node(f"n{i}", {"cpu": "32", "memory": "128Gi", "pods": "64"})
        for i in range(6)
    ]
    groups = [
        GroupDemand(
            f"default/g{i}", 4, member_request={"cpu": 2000}, creation_ts=float(i)
        )
        for i in range(5)
    ]
    snap = ClusterSnapshot(nodes, {}, groups)
    monkeypatch.delenv("BST_SCAN_WAVE", raising=False)
    host_s, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    monkeypatch.setenv("BST_SCAN_WAVE", "4")
    host_w, _ = execute_batch_host(snap.device_args(), snap.progress_args())
    for key in ("placed", "gang_feasible", "progress", "assignment_nodes",
                "assignment_counts"):
        np.testing.assert_array_equal(
            np.asarray(host_s[key]), np.asarray(host_w[key]), err_msg=key
        )
    assert host_s["best"] == host_w["best"]


def test_dispatch_fallback_blames_wave_not_pallas(monkeypatch):
    """A wavefront compile failure falls back to the serial scan and
    disables ONLY the wavefront gate — the pallas mask-mode gates are
    untouched (and vice versa the serial path keeps serving)."""
    nodes = [make_node("n0", {"cpu": "8", "memory": "8Gi", "pods": "10"})]
    groups = [GroupDemand("default/g", 2, member_request={"cpu": 1000})]
    snap = ClusterSnapshot(nodes, {}, groups)
    monkeypatch.setenv("BST_SCAN_WAVE", "4")

    real_blob = omod._batch_blob

    def boom_on_wave(*args, **kwargs):
        if kwargs.get("scan_wave"):
            raise RuntimeError("wavefront lowering exploded")
        return real_blob(*args, **kwargs)

    saved_wave = omod._wave_enabled[0]
    saved_pallas = dict(omod._pallas_enabled)
    monkeypatch.setattr(omod, "_batch_blob", boom_on_wave)
    try:
        with pytest.warns(UserWarning, match="wavefront"):
            pend = dispatch_batch(snap.device_args(), snap.progress_args())
        assert pend.used_wave == 0
        assert omod._wave_enabled[0] is False
        assert omod._pallas_enabled == saved_pallas
        host, _ = omod.collect_batch(pend)
        assert host["placed"][:1].tolist() == [True]
        # subsequent dispatches skip the wavefront without re-failing
        pend2 = dispatch_batch(snap.device_args(), snap.progress_args())
        assert pend2.used_wave == 0
    finally:
        omod._wave_enabled[0] = saved_wave
        omod._pallas_enabled.clear()
        omod._pallas_enabled.update(saved_pallas)
