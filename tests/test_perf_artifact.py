"""The unified bench envelope (benchmarks/artifact.py), the artifact
schema validator, and the perf-regression comparison logic
(benchmarks/perf_regress.py) — the host-side halves that need no
benchmark run."""

from __future__ import annotations

import json

from benchmarks import artifact
from benchmarks.perf_regress import _injections, _timed, compare, knob_diff
from benchmarks.validate_artifacts import GRANDFATHERED, validate_file


def test_envelope_is_additive_and_valid(tmp_path, monkeypatch):
    monkeypatch.setenv("BST_PERF_LEDGER", str(tmp_path / "ledger.jsonl"))
    monkeypatch.setenv("BST_TEST_KNOB", "42")
    result = {
        "metric": "probe_s",
        "value": 0.5,
        "unit": "s",
        "detail": {"platform": "cpu", "draws": 3, "note": "text"},
    }
    doc = artifact.envelope(result)
    # additive: every legacy key survives at the top level (capture-
    # script greps keep working)
    for k in result:
        assert doc[k] == result[k]
    assert doc["schema"] == artifact.SCHEMA
    assert doc["host"]["jax_backend"]
    assert doc["knobs"].get("BST_TEST_KNOB") == "42"
    # metrics default: the headline value + numeric detail entries only
    assert doc["metrics"] == {"probe_s": 0.5, "draws": 3}
    assert artifact.validate(doc) == []
    # the ledger append lands one parseable envelope line
    path = artifact.append_ledger(doc)
    assert path == str(tmp_path / "ledger.jsonl")
    line = json.loads((tmp_path / "ledger.jsonl").read_text())
    assert line["metric"] == "probe_s" and line["schema"] == artifact.SCHEMA
    # BST_PERF_LEDGER=off disables
    monkeypatch.setenv("BST_PERF_LEDGER", "off")
    assert artifact.ledger_path() is None


def test_envelope_validation_catches_drift():
    doc = artifact.envelope({"metric": "m", "value": 1.0, "unit": "s"})
    assert artifact.validate(doc) == []
    bad = dict(doc)
    bad["schema"] = "bst-bench-envelope/v999"
    assert any("schema" in e for e in artifact.validate(bad))
    bad = dict(doc)
    del bad["host"]
    assert any("host" in e for e in artifact.validate(bad))
    bad = dict(doc)
    bad["metrics"] = {"m": "not-a-number"}
    assert any("metrics" in e for e in artifact.validate(bad))
    assert artifact.validate([1, 2]) == ["document is not a JSON object"]


def test_validate_artifacts_grandfather_and_new_files(tmp_path):
    # a grandfathered legacy artifact passes as-is
    legacy = tmp_path / "BENCH_r01.json"
    legacy.write_text(json.dumps({"metric": "m", "value": 1, "unit": "s"}))
    assert "BENCH_r01.json" in GRANDFATHERED
    assert validate_file(str(legacy)) == []
    # a NEW capture without the envelope fails (no silent drift)
    new = tmp_path / "BENCH_r99.json"
    new.write_text(json.dumps({"metric": "m", "value": 1, "unit": "s"}))
    errors = validate_file(str(new))
    assert errors and "grandfather" in errors[0]
    # the same file with the envelope passes
    new.write_text(
        json.dumps(artifact.envelope({"metric": "m", "value": 1, "unit": "s"}))
    )
    assert validate_file(str(new)) == []
    # JSONL artifacts validate per line, with line-indexed blame
    jl = tmp_path / "LADDER_r99.json"
    good = artifact.envelope({"metric": "m", "value": 1, "unit": "s"})
    jl.write_text(json.dumps(good) + "\n" + json.dumps({"metric": "m"}) + "\n")
    errors = validate_file(str(jl))
    assert errors and errors[0].startswith("doc 2: ")
    # unparseable files are one clear error, not a crash
    broken = tmp_path / "SMASH_r01.json"
    broken.write_text("{not json")
    assert "unparseable" in validate_file(str(broken))[0]


def test_perf_regress_compare_blames_regressions():
    baseline = {
        "metrics": {"probe_a_s": 0.100, "probe_b_s": 0.200},
        "tolerances": {"probe_a_s": 1.5, "probe_b_s": 1.5},
        "knobs": {"BST_X": "1"},
    }
    observed = {"probe_a_s": 0.105, "probe_b_s": 0.500}
    regressions, comparisons = compare(baseline, observed)
    assert len(comparisons) == 2
    assert [r["metric"] for r in regressions] == ["probe_b_s"]
    blame = regressions[0]
    # structured blame: metric, baseline, observed, ratio, knob diff
    assert blame["baseline"] == 0.200 and blame["observed"] == 0.500
    assert blame["ratio"] == 2.5 and blame["tolerance"] == 1.5
    assert "knob_diff" in blame
    # the knob diff names what changed between the two envelopes
    diff = knob_diff({"BST_X": "1", "BST_Y": "a"}, {"BST_X": "2"})
    assert diff == {"BST_X": ["1", "2"], "BST_Y": ["a", None]}
    # a global tolerance override wins over per-metric ones
    regressions, _ = compare(baseline, observed, tolerance_override=3.0)
    assert regressions == []
    # unknown metrics in the baseline are skipped, never divide-by-zero
    regressions, comparisons = compare({"metrics": {"z": 0}}, {"z": 1.0})
    assert regressions == [] and comparisons == []


def test_perf_regress_injection_hook(monkeypatch):
    """BST_PERF_REGRESS_INJECT stretches the timed region itself — the
    observed slowdown is real wall-clock, which is what makes the gate's
    failure path honestly testable."""
    monkeypatch.setenv(
        "BST_PERF_REGRESS_INJECT", "probe_a_s=3.0,junk,bad=x"
    )
    inj = _injections()
    assert inj == {"probe_a_s": 3.0}

    med_plain, draws = _timed(lambda: None, repeats=3)
    assert len(draws) == 3
    base = 0.005
    med_inj, _ = _timed(
        lambda: __import__("time").sleep(base), repeats=3, inject_factor=3.0
    )
    assert med_inj >= base * 2.5  # ~3x the probe's own wall-clock
