"""Leader election over the API server: APILease CAS semantics and a
two-replica failover e2e (VERDICT r1 item 6 — the reference coordinates
replicas through the shared kube-scheduler EndpointsLock in kube-system,
reference batchscheduler.go:452-502)."""

import threading
import time

from batch_scheduler_tpu.client.apiserver import APIServer
from batch_scheduler_tpu.client.clientset import Clientset
from batch_scheduler_tpu.client.http_apiserver import HTTPAPIServer
from batch_scheduler_tpu.client.http_gateway import serve_gateway
from batch_scheduler_tpu.framework.cluster import ClusterState
from batch_scheduler_tpu.plugin.factory import PluginConfig, new_plugin_runtime
from batch_scheduler_tpu.plugin.leader import APILease

from helpers import make_group


class _FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def test_api_lease_cas_and_takeover():
    api = APIServer()
    clock = _FakeClock()
    lease_a = APILease(api, default_duration=10.0, clock=clock)
    lease_b = APILease(api, default_duration=10.0, clock=clock)

    assert lease_a.acquire("a")
    assert not lease_b.acquire("b")  # held and fresh
    assert lease_a.renew("a")
    assert not lease_b.renew("b")  # not the holder

    # holder re-acquire is a renew
    clock.now += 5.0
    assert lease_a.acquire("a")

    # expiry -> takeover
    clock.now += 11.0
    assert lease_b.acquire("b")
    assert not lease_a.acquire("a")
    rec = lease_a.get()
    assert rec.holder_identity == "b"

    # release clears; anyone may claim
    lease_b.release("b")
    assert lease_a.acquire("a")


def test_api_lease_race_single_winner():
    """Two replicas racing an expired lease: exactly one CAS wins."""
    api = APIServer()
    clock = _FakeClock()
    seed = APILease(api, default_duration=1.0, clock=clock)
    assert seed.acquire("old")
    clock.now += 5.0  # expired

    results = {}
    barrier = threading.Barrier(2)

    def claim(identity):
        lease = APILease(api, default_duration=10.0, clock=clock)
        barrier.wait()
        results[identity] = lease.acquire(identity)

    threads = [threading.Thread(target=claim, args=(i,)) for i in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results.values()) == [False, True], results
    holder = seed.get().holder_identity
    assert holder in ("a", "b")


def test_api_lease_over_http():
    backing = APIServer()
    server = serve_gateway(backing)
    host, port = server.server_address[:2]
    remote = HTTPAPIServer(host, port)
    try:
        lease_a = APILease(remote, default_duration=10.0)
        lease_b = APILease(remote, default_duration=10.0)
        assert lease_a.acquire("a")
        assert not lease_b.acquire("b")
        assert lease_a.renew("a")
        lease_a.release("a")
        assert lease_b.acquire("b")
    finally:
        remote.close()
        server.shutdown()
        server.server_close()


class _Handle:
    """Minimal framework handle for a controller-only runtime."""

    def __init__(self):
        self.cluster = ClusterState()

    def get_waiting_pod(self, uid):
        return None

    def iterate_over_waiting_pods(self, fn):
        pass


def _wait(predicate, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def test_two_replica_failover():
    """Leader dies -> follower's controller takes the lease, starts, and
    reconciles both the in-flight gang and new ones."""
    api = APIServer()
    cs = Clientset(api)

    def build(identity):
        config = PluginConfig(
            identity=identity,
            leader_poll_seconds=0.05,
            lease_renew_seconds=0.2,
            controller_resync_seconds=0.1,
        )
        lease = APILease(api, default_duration=1.0)
        return new_plugin_runtime(api, _Handle(), config, lease=lease)

    rt_a = build("replica-a")
    rt_b = build("replica-b")
    try:
        rt_a.start()
        # A claims first (B not started yet), its controller reconciles
        assert _wait(lambda: rt_a.lease.get() is not None)
        assert rt_a.lease.get().holder_identity == "replica-a"
        cs.podgroups().create(make_group("inflight", min_member=2))
        assert _wait(
            lambda: rt_a.operation.status_cache.get("default/inflight")
            is not None
        )
        assert _wait(
            lambda: cs.podgroups().get("inflight").status.phase.value == "Pending"
        )

        rt_b.start()
        time.sleep(0.5)
        # B must NOT have taken over while A is alive
        assert rt_a.lease.get().holder_identity == "replica-a"
        assert rt_b.operation.status_cache.get("default/inflight") is None

        # leader dies (no release — crash semantics; failover via expiry)
        rt_a.stop()
        assert _wait(
            lambda: rt_b.lease.get() is not None
            and rt_b.lease.get().holder_identity == "replica-b",
            timeout=10.0,
        ), rt_b.lease.get()

        # follower's controller warm-syncs the in-flight gang...
        assert _wait(
            lambda: rt_b.operation.status_cache.get("default/inflight")
            is not None,
            timeout=10.0,
        )
        # ...and keeps reconciling new ones
        cs.podgroups().create(make_group("post-failover", min_member=2))
        assert _wait(
            lambda: cs.podgroups().get("post-failover").status.phase.value
            == "Pending",
            timeout=10.0,
        )
    finally:
        rt_a.stop()
        rt_b.stop()
