"""Node-sharded wavefront scan (ops.oracle.assign_gangs_sharded): shard-count
invariance, padded-row safety, tie-break determinism, the dispatch ladder's
graceful demotion to the replicated rung, and the scan-only collective
budget. Runs on the 8-device virtual CPU mesh from conftest."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from batch_scheduler_tpu.ops import oracle as okern
from batch_scheduler_tpu.ops.oracle import (
    assign_gangs,
    assign_gangs_sharded,
    dispatch_batch,
    collect_batch,
    execute_batch_host,
    forced_scan_rung,
    schedule_batch,
)
from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot, GroupDemand
from batch_scheduler_tpu.parallel.mesh import (
    make_mesh,
    shard_snapshot_args,
    sharded_scan_collective_counts,
    sharded_schedule_batch,
)
from batch_scheduler_tpu.sim.scenarios import make_sim_node


def _scan_case(n=48, g=14, r=3, per_group=False, uniform=False, seed=7):
    """Raw assign_gangs inputs (unbucketed, so N can be shard-uneven)."""
    rng = np.random.RandomState(seed)
    left = jnp.asarray(rng.randint(0, 120, size=(n, r)), jnp.int32)
    if uniform:
        req = jnp.asarray(
            np.tile(rng.randint(1, 6, size=(1, r)), (g, 1)), jnp.int32
        )
    else:
        req = jnp.asarray(rng.randint(0, 6, size=(g, r)), jnp.int32)
    rem = jnp.asarray(rng.randint(0, 30, size=(g,)), jnp.int32)
    if per_group:
        mask = jnp.asarray(rng.randint(0, 2, size=(g, n)), jnp.int32)
    else:
        mask = jnp.ones((1, n), jnp.int32)
    order = jnp.asarray(rng.permutation(g), jnp.int32)
    return left, req, rem, mask, order


def _assert_identical(args, mesh, wave=4, want_demoted=None, want_mega=None):
    a0, p0, l0 = (np.asarray(x) for x in assign_gangs(*args))
    a1, p1, l1, (conf, megas) = assign_gangs_sharded(
        *args, mesh=mesh, wave=wave, with_stats=True
    )
    np.testing.assert_array_equal(a0, np.asarray(a1))
    np.testing.assert_array_equal(p0, np.asarray(p1))
    np.testing.assert_array_equal(l0, np.asarray(l1))
    if want_demoted is not None:
        assert bool(np.asarray(conf).sum() > 0) is want_demoted
    if want_mega is not None:
        assert bool(np.asarray(megas).sum() > 0) is want_mega


@pytest.mark.parametrize("n_devices", [1, 2, 4, 8])
def test_bit_identical_across_device_counts(n_devices):
    """The same batch must produce the same plan on 1/2/4/8 shards — the
    shard count is a layout choice, never a semantic one."""
    mesh = make_mesh(n_devices)
    _assert_identical(
        _scan_case(per_group=False, uniform=False, seed=3 + n_devices), mesh
    )


def test_contended_waves_demote_and_stay_identical():
    """Non-uniform contended gangs force the conflict psum to fire and the
    wave to replay gang-at-a-time — the demotion ladder's output must
    still be the serial plan."""
    _assert_identical(
        _scan_case(n=24, g=12, per_group=True, uniform=False, seed=11),
        make_mesh(4),
        want_demoted=True,
    )


def test_uniform_waves_take_mega_path():
    """Bulk-identical gangs (the north-star workload) commit whole waves
    through the aggregate member-stream path."""
    _assert_identical(
        _scan_case(n=64, g=16, per_group=False, uniform=True, seed=5),
        make_mesh(8),
        want_mega=True,
    )


@pytest.mark.parametrize("n", [37, 50, 61])
def test_uneven_node_counts_pad_rows_never_win(n):
    """N not divisible by the shard count pads the node axis internally;
    identity with the serial scan proves a padded row never wins a member,
    and the returned shapes stay in the caller's node space."""
    mesh = make_mesh(8)
    args = _scan_case(n=n, g=9, uniform=True, seed=n)
    _assert_identical(args, mesh)
    alloc, placed, left = assign_gangs_sharded(*args, mesh=mesh, wave=4)
    assert alloc.shape == (9, n)
    assert left.shape == (n, args[0].shape[1])


def test_tiebreak_is_global_node_index():
    """Equal-capacity nodes split across shards: the serial scan breaks
    ties by node index, so the winning members must sit on the lowest
    global indexes — not on whichever shard merged first."""
    n, g, r = 16, 2, 2
    left = jnp.full((n, r), 10, jnp.int32)  # every node identical
    req = jnp.full((g, r), 2, jnp.int32)
    rem = jnp.asarray([6, 6], jnp.int32)    # cap/node = 5 -> gang spans 2+
    mask = jnp.ones((1, n), jnp.int32)
    order = jnp.asarray([0, 1], jnp.int32)
    args = (left, req, rem, mask, order)
    _assert_identical(args, make_mesh(8))
    alloc, placed, _ = assign_gangs_sharded(*args, mesh=make_mesh(8), wave=2)
    alloc = np.asarray(alloc)
    taken_nodes = np.where(alloc.sum(axis=0) > 0)[0]
    # 12 members over capacity-5 nodes -> nodes 0,1,2 and nothing beyond
    assert taken_nodes.tolist() == [0, 1, 2]
    assert np.asarray(placed).all()


def _snapshot_args(num_nodes=48, num_groups=18):
    nodes = [
        make_sim_node(f"n{i:03d}", {"cpu": "16", "memory": "64Gi", "pods": "32"})
        for i in range(num_nodes)
    ]
    groups = [
        GroupDemand(
            full_name=f"default/g{x:03d}",
            min_member=4 + (x % 3),
            member_request={"cpu": 2000, "memory": 4 * 1024**3},
            creation_ts=float(x),
        )
        for x in range(num_groups)
    ]
    return ClusterSnapshot(nodes, {}, groups).device_args()


def test_full_batch_sharded_scan_matches_single_device():
    """The fused schedule_batch with the sharded-scan layout must agree
    with the single-device batch on every output field."""
    args = _snapshot_args()
    single = jax.device_get(schedule_batch(*args))
    mesh = make_mesh(8)
    sharded = jax.device_get(
        sharded_schedule_batch(mesh, args, sharded_scan=True)
    )
    for key in ("gang_feasible", "placed", "capacity", "assignment"):
        np.testing.assert_array_equal(
            np.asarray(single[key]), np.asarray(sharded[key]), err_msg=key
        )


def _progress_args(g):
    return (
        jnp.full((g,), 4, jnp.int32),   # min_member
        jnp.zeros((g,), jnp.int32),     # scheduled
        jnp.full((g,), 4, jnp.int32),   # matched
        jnp.zeros((g,), bool),          # ineligible
        jnp.arange(g, dtype=jnp.int32),  # creation_rank
    )


def test_dispatch_prefers_sharded_rung_on_mesh():
    args = _snapshot_args(num_nodes=24, num_groups=8)
    mesh = make_mesh(4)
    placed_args = shard_snapshot_args(mesh, args, flat_nodes=True)
    host, _ = execute_batch_host(
        placed_args, _progress_args(np.asarray(args[2]).shape[0]),
        scan_mesh=mesh,
    )
    tel = host["telemetry"]
    assert tel["scan_sharded"] is True
    assert tel["shard_count"] == 4
    assert tel["wave_width"] > 1
    assert "waves_per_batch" in tel


def test_dispatch_falls_back_to_replicated_without_flipping_gates(
    monkeypatch,
):
    """A sharded-rung failure must demote THIS batch to the replicated
    layout and disable only the sharded gate — never the wave or pallas
    gates (independent features must not poison each other). Uses a
    bucket shape no other test dispatches sharded, so the failure fires
    at trace time instead of hitting the jit cache."""
    args = _snapshot_args(num_nodes=40, num_groups=12)
    mesh = make_mesh(4)
    single, _ = execute_batch_host(
        args, _progress_args(np.asarray(args[2]).shape[0])
    )

    def boom(*a, **kw):
        raise RuntimeError("sharded lowering exploded")

    monkeypatch.setattr(okern, "assign_gangs_sharded", boom)
    wave_before = okern._wave_enabled[0]
    pallas_before = dict(okern._pallas_enabled)
    try:
        with pytest.warns(UserWarning, match="node-sharded assignment"):
            host, _ = execute_batch_host(
                args, _progress_args(np.asarray(args[2]).shape[0]),
                scan_mesh=mesh,
            )
        assert host["telemetry"]["scan_sharded"] is False
        assert okern._sharded_enabled[0] is False
        assert okern._wave_enabled[0] == wave_before
        assert okern._pallas_enabled == pallas_before
        np.testing.assert_array_equal(
            np.asarray(single["placed"]), np.asarray(host["placed"])
        )
    finally:
        okern._sharded_enabled[0] = True


def test_env_knob_pins_replicated_rung(monkeypatch):
    monkeypatch.setenv("BST_SCAN_SHARDED", "0")
    args = _snapshot_args(num_nodes=24, num_groups=8)
    mesh = make_mesh(4)
    host, _ = execute_batch_host(
        args, _progress_args(np.asarray(args[2]).shape[0]), scan_mesh=mesh
    )
    assert host["telemetry"]["scan_sharded"] is False


def test_forced_rung_pin_never_runs_sharded():
    """Replay/identity-audit pins name explicit (pallas, wave) rungs; a
    pinned thread on a mesh must not wander onto the sharded rung — its
    recorded batches are verified by cross-rung identity instead."""
    args = _snapshot_args(num_nodes=24, num_groups=8)
    mesh = make_mesh(4)
    with forced_scan_rung(False, 0):
        host, _ = execute_batch_host(
            args, _progress_args(np.asarray(args[2]).shape[0]),
            scan_mesh=mesh,
        )
    assert host["telemetry"]["scan_sharded"] is False
    assert host["telemetry"]["wave_width"] == 0


def test_scan_only_collective_budget():
    """The scan-only module's collectives are all summary-sized: no
    all-gather (or any other collective) of [N, R] node state ever rides
    inside the gang loop, and the instruction sites do not grow with G
    (the loop body compiles once regardless of gang count)."""
    mesh = make_mesh(8)
    small = sharded_scan_collective_counts(mesh, _snapshot_args(64, 8))
    big = sharded_scan_collective_counts(mesh, _snapshot_args(64, 32))
    assert small["counts"] == big["counts"], (small, big)
    assert big["waves"] > small["waves"]
    for rep in (small, big):
        # every collective in the module is summary-sized: the node-state
        # all-gather class (SHARDING_r05's 54 sites) cannot hide anywhere
        assert rep["max_collective_bytes"] <= rep["summary_bytes"], rep
        assert rep["counts"]["collective-permute"] == 0, rep
        assert rep["counts"]["all-gather"] + rep["counts"]["all-reduce"] > 0
