"""utils.backend: the probe-and-degrade guard for hung accelerator plugins."""

from __future__ import annotations

import subprocess

import pytest

from batch_scheduler_tpu.utils import backend


@pytest.fixture(autouse=True)
def _reset_cache(monkeypatch, tmp_path):
    saved = backend._resolved
    backend._resolved = None
    # isolate the cross-process verdict cache: default OFF so the probe
    # tests below exercise the live loop; cache tests re-enable per-case
    monkeypatch.setenv("BST_PROBE_CACHE_TTL_S", "0")
    monkeypatch.setenv(
        "BST_PROBE_CACHE_FILE", str(tmp_path / "probe_cache.json")
    )
    yield
    backend._resolved = saved


def test_pinned_cpu_skips_probe(monkeypatch):
    """With the platform already pinned to cpu (this test session), the
    subprocess probe must not run at all."""
    def boom(*a, **kw):
        raise AssertionError("probe subprocess must not be spawned")

    monkeypatch.setattr(subprocess, "run", boom)
    platform, err = backend.resolve_platform()
    assert (platform, err) == ("cpu", None)


def test_result_is_cached(monkeypatch):
    calls = []

    def fake_run(*a, **kw):
        calls.append(1)
        raise AssertionError("unexpected")

    monkeypatch.setattr(subprocess, "run", fake_run)
    backend.resolve_platform()
    backend.resolve_platform()
    assert calls == []  # pinned-cpu shortcut, and cached on repeat


def test_env_pin_wins_over_plugin_config_override(monkeypatch):
    """JAX_PLATFORMS=cpu in the ENV is honored even when a plugin registered
    at interpreter start rewrote the config to "axon,cpu" (this
    environment's sitecustomize): no probe, config forced back to cpu."""
    import jax

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setattr(
        type(jax.config), "jax_platforms", property(lambda self: "axon,cpu"),
        raising=False,
    )

    def boom(*a, **kw):
        raise AssertionError("probe subprocess must not be spawned")

    monkeypatch.setattr(subprocess, "run", boom)
    updates = []
    monkeypatch.setattr(
        jax.config, "update", lambda k, v: updates.append((k, v))
    )
    platform, err = backend.resolve_platform()
    assert (platform, err) == ("cpu", None)
    assert ("jax_platforms", "cpu") in updates


def test_hang_degrades_to_cpu(monkeypatch):
    """A probe that times out every attempt degrades to CPU with the error
    recorded (the hung-tunnel path, exercised for real this round)."""
    import jax

    # bypass the pinned-cpu shortcuts (config AND env) to reach the probe
    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(
        type(jax.config), "jax_platforms", property(lambda self: "axon"),
        raising=False,
    )

    def fake_run(*a, **kw):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=kw.get("timeout"))

    monkeypatch.setattr(subprocess, "run", fake_run)
    updates = []
    monkeypatch.setattr(
        jax.config, "update", lambda k, v: updates.append((k, v))
    )
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")

    platform, err = backend.resolve_platform(
        retries=2, probe_timeout_s=0.01, retry_delay_s=0.0
    )
    assert platform == "cpu"
    assert "hang" in err
    assert ("jax_platforms", "cpu") in updates


def test_deadline_mode_hangs_exit_after_two(monkeypatch):
    """deadline_s is a wall-clock budget, but two CONSECUTIVE full-timeout
    hangs end the probing immediately: a wedged tunnel does not heal
    inside one run, and the r5 postmortem measured ~12 x 75s of dead
    wall-clock per CPU-only bench run when every attempt hung. One
    backoff sleep separates the two attempts."""
    import jax

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(
        type(jax.config), "jax_platforms", property(lambda self: "axon"),
        raising=False,
    )
    calls = []

    def fake_run(*a, **kw):
        calls.append(kw.get("timeout"))
        raise subprocess.TimeoutExpired(cmd="probe", timeout=kw.get("timeout"))

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(jax.config, "update", lambda k, v: None)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")

    import time as _time

    sleeps = []
    monkeypatch.setattr(_time, "sleep", lambda s: sleeps.append(s))
    # deterministic clock: the budget must not race the real wall clock
    fake_now = [0.0]
    monkeypatch.setattr(_time, "monotonic", lambda: fake_now[0])

    platform, err = backend.resolve_platform(
        probe_timeout_s=0.0, retry_delay_s=0.01, deadline_s=1000.0
    )
    assert platform == "cpu" and "hang" in err
    # exactly two hung attempts despite the huge remaining budget, with
    # the first backoff sleep between them
    assert len(calls) == 2
    assert sleeps == [0.01]


def test_deadline_mode_deterministic_failure_exits_early(monkeypatch):
    """A fast, identically-repeating probe failure (broken plugin, not a
    hung tunnel) must NOT burn the whole deadline budget: three identical
    errors degrade immediately."""
    import jax

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(
        type(jax.config), "jax_platforms", property(lambda self: "axon"),
        raising=False,
    )
    calls = []

    class R:
        returncode = 1
        stdout = ""
        stderr = "RuntimeError: plugin exploded"

    def fake_run(*a, **kw):
        calls.append(1)
        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    monkeypatch.setattr(jax.config, "update", lambda k, v: None)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    import time as _time

    monkeypatch.setattr(_time, "sleep", lambda s: None)

    platform, err = backend.resolve_platform(
        probe_timeout_s=0.0, retry_delay_s=0.0, deadline_s=3600.0
    )
    assert platform == "cpu"
    assert "plugin exploded" in err
    assert len(calls) == 3  # bounded, despite the huge budget


def _unpin(monkeypatch):
    import jax

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setattr(
        type(jax.config), "jax_platforms", property(lambda self: "axon"),
        raising=False,
    )
    monkeypatch.setattr(jax.config, "update", lambda k, v: None)
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")


def test_probe_total_cap_bounds_deadline_budget(monkeypatch):
    """BST_PROBE_TOTAL_CAP_S caps probe wall-clock per invocation even
    under a huge deadline budget: a slow-failing (non-identical-error)
    probe loop stops at the cap instead of eating a capture stage's whole
    timeout window (the 12 x 75s BENCH_r05 postmortem)."""
    _unpin(monkeypatch)
    monkeypatch.setenv("BST_PROBE_TOTAL_CAP_S", "100")
    calls = []

    class R:
        returncode = 1
        stdout = ""

        @property
        def stderr(self):
            return f"transient error {len(calls)}"  # never identical

    def fake_run(*a, **kw):
        calls.append(1)
        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    import time as _time

    fake_now = [0.0]

    def fake_sleep(s):
        fake_now[0] += s

    monkeypatch.setattr(_time, "sleep", fake_sleep)
    monkeypatch.setattr(_time, "monotonic", lambda: fake_now[0])

    platform, err = backend.resolve_platform(
        probe_timeout_s=30.0, retry_delay_s=10.0, deadline_s=100000.0
    )
    assert platform == "cpu"
    # the cap ends the loop after ~4 probes (~70s fake wall-clock);
    # without it the 100000s deadline would admit dozens more
    assert len(calls) <= 4


def test_probe_verdict_cached_across_processes(monkeypatch, tmp_path):
    """A cached verdict (another stage of the same capture run) is reused
    without spawning a probe; an expired one is ignored."""
    import json
    import time as _time

    _unpin(monkeypatch)
    cache = tmp_path / "verdict.json"
    monkeypatch.setenv("BST_PROBE_CACHE_FILE", str(cache))
    monkeypatch.setenv("BST_PROBE_CACHE_TTL_S", "600")
    cache.write_text(json.dumps(
        {"platform": "cpu", "error": "probe hang", "ts": _time.time()}
    ))

    def boom(*a, **kw):
        raise AssertionError("probe must not run with a fresh cache")

    monkeypatch.setattr(subprocess, "run", boom)
    platform, err = backend.resolve_platform()
    assert platform == "cpu" and "hang" in err

    # expired cache: the probe runs again (and rewrites the verdict)
    backend._resolved = None
    cache.write_text(json.dumps(
        {"platform": "cpu", "error": "probe hang", "ts": _time.time() - 9999}
    ))

    def fake_run(*a, **kw):
        class R:
            returncode = 0
            stdout = "PLATFORM=cpu\n"
            stderr = ""

        return R()

    monkeypatch.setattr(subprocess, "run", fake_run)
    platform, err = backend.resolve_platform()
    assert (platform, err) == ("cpu", None)
    rec = json.loads(cache.read_text())
    assert rec["platform"] == "cpu" and rec["error"] is None
