"""Direct unit tests for the two-level gang scheduling queue: one heap
resident per (gang, priority) bucket with FIFO parking, lazy deletion via
pop_group, and promotion on resident pop (framework/queue.py)."""

import pytest

from batch_scheduler_tpu.framework.queue import SchedulingQueue
from batch_scheduler_tpu.framework.types import PodInfo

from helpers import make_pod


def _info(name, group="", priority=0, ts=0.0):
    return PodInfo(pod=make_pod(name, group=group, priority=priority), timestamp=ts)


def _gang_key(info):
    return f"{info.namespace}/{info.gang}" if info.gang else None


def _sort_key(info):
    # same shape as the production key (operation.sort_key): priority,
    # non-gang first, then a gang-level component BEFORE the timestamp —
    # what makes same-gang members mutually adjacent, the property the
    # bucket FIFO relies on
    return (
        -info.priority,
        0 if not info.gang else 1,
        info.gang,
        info.timestamp,
    )


@pytest.fixture
def queue_factory():
    queues = []

    def build(**kw):
        q = SchedulingQueue(group_key_fn=_gang_key, sort_key_fn=_sort_key, **kw)
        queues.append(q)
        return q

    yield build
    for q in queues:
        q.close()


def test_same_gang_members_park_in_fifo_and_pop_in_arrival_order(queue_factory):
    q = queue_factory()
    for i in range(4):
        q.push(_info(f"m{i}", group="g1", ts=float(i + 1)))
    assert q.group_size("default/g1") == 4
    assert len(q) == 4
    # only ONE heap entry exists; pops promote the FIFO in arrival order
    names = [q.pop(timeout=0.1).name for _ in range(4)]
    assert names == ["m0", "m1", "m2", "m3"]
    assert q.group_size("default/g1") == 0
    assert len(q) == 0


def test_pop_group_drains_fifo_members_without_heap_traffic(queue_factory):
    q = queue_factory()
    q.push(_info("lead", group="g1", ts=1.0))
    for i in range(3):
        q.push(_info(f"sib{i}", group="g1", ts=float(i + 2)))
    lead = q.pop(timeout=0.1)
    assert lead.name == "lead"
    drained = {i.name for i in q.pop_group("default/g1")}
    assert drained == {"sib0", "sib1", "sib2"}
    assert len(q) == 0
    # the promoted-but-dead residents are skipped transparently
    assert q.pop(timeout=0.05) is None


def test_dead_head_still_promotes_parked_straggler(queue_factory):
    """pop_group kills the whole bucket while one entry is heap-resident;
    a STRAGGLER pushed afterwards parks behind the dead head and must
    still surface once the dead head cycles through the heap."""
    q = queue_factory()
    q.push(_info("a", group="g1", ts=1.0))
    q.push(_info("b", group="g1", ts=2.0))
    assert {i.name for i in q.pop_group("default/g1")} == {"a", "b"}
    # straggler arrives while the dead resident is still in the heap
    q.push(_info("late", group="g1", ts=3.0))
    assert q.pop(timeout=0.2).name == "late"


def test_priority_splits_buckets_within_one_gang(queue_factory):
    """Members of one gang at different priorities occupy separate
    buckets, so a high-priority member is never hidden behind a
    low-priority resident."""
    q = queue_factory()
    q.push(_info("low", group="g1", priority=0, ts=1.0))
    q.push(_info("high", group="g1", priority=5, ts=2.0))
    assert q.pop(timeout=0.1).name == "high"
    assert q.pop(timeout=0.1).name == "low"
    # both were still indexed under the gang for pop_group
    q.push(_info("low2", group="g1", priority=0))
    q.push(_info("high2", group="g1", priority=5))
    assert {i.name for i in q.pop_group("default/g1")} == {"low2", "high2"}


def test_interleaved_gangs_order_by_sort_key(queue_factory):
    q = queue_factory()
    q.push(_info("b1", group="beta", ts=2.0))
    q.push(_info("a1", group="alpha", ts=1.0))
    q.push(_info("solo", ts=5.0))  # non-gang sorts first at equal priority
    q.push(_info("a2", group="alpha", ts=3.0))
    names = [q.pop(timeout=0.1).name for _ in range(4)]
    assert names == ["solo", "a1", "a2", "b1"]


def test_backoff_reentry_returns_to_bucket(queue_factory):
    q = queue_factory(backoff_base=0.01, backoff_cap=0.02)
    info = _info("retry", group="g1", ts=1.0)
    q.push(info)
    assert q.pop(timeout=0.1).name == "retry"
    q.push_backoff(info)
    assert len(q) == 1
    # promoted from backoff into the gang bucket and poppable again
    got = q.pop(timeout=2.0)
    assert got is not None and got.name == "retry"
    assert got.attempts == 1


def test_backoff_reentry_deviation_bounded_to_same_bucket(queue_factory):
    """Pins the two-level queue's ONE ordering deviation (queue.py FIFO
    parking note): a backoff RE-entry parks at its bucket's FIFO tail,
    so it pops AFTER same-bucket siblings whose timestamps it precedes —
    and pins the deviation's BOUND: cross-bucket Compare order (priority
    first, then the gang component, reference core.go:368-411 semantics)
    is never inverted, because the sort key orders buckets before the
    timestamp ever matters. A queue refactor that widens the deviation
    beyond same-(gang, priority) buckets fails this test."""
    import time as _time

    q = queue_factory(backoff_base=0.01, backoff_cap=0.02)
    a1 = _info("a1", group="alpha", ts=1.0)
    q.push(a1)
    q.push(_info("a2", group="alpha", ts=2.0))
    q.push(_info("a3", group="alpha", ts=3.0))
    assert q.pop(timeout=0.1).name == "a1"
    # re-entry: a1's ts=1.0 precedes a2/a3, but it re-parks at the tail
    q.push_backoff(a1)
    # cross-bucket competitors pushed AFTER the re-entry
    q.push(_info("b-hi", group="beta", priority=5, ts=9.0))
    q.push(_info("b1", group="beta", ts=0.5))
    _time.sleep(0.3)  # backoff flusher (≤0.02s) re-admits a1
    names = [q.pop(timeout=2.0).name for _ in range(5)]
    # priority bucket first (never inverted by the parking), then the
    # alpha bucket ahead of beta (gang component precedes timestamp in
    # the key), with the deviation visible ONLY inside alpha: a1 last
    assert names == ["b-hi", "a2", "a3", "a1", "b1"], names


def test_group_size_tracks_live_members_only(queue_factory):
    q = queue_factory()
    for i in range(3):
        q.push(_info(f"m{i}", group="g1", ts=float(i + 1)))
    assert q.group_size("default/g1") == 3
    q.pop(timeout=0.1)
    assert q.group_size("default/g1") == 2
    q.pop_group("default/g1")
    assert q.group_size("default/g1") == 0
    assert q.group_size("default/ghost") == 0


def test_len_counts_fifo_parked_and_backoff(queue_factory):
    q = queue_factory(backoff_base=5.0, backoff_cap=5.0)
    for i in range(5):
        q.push(_info(f"m{i}", group="g1", ts=float(i + 1)))
    q.push_backoff(_info("delayed", group="g1"))
    assert len(q) == 6  # 1 resident + 4 FIFO + 1 backoff


def test_raw_podinfo_scalars_and_lazy_pod():
    from batch_scheduler_tpu.api.types import to_dict

    pod = make_pod("rawpod", group="g9", priority=3)
    d = to_dict(pod)
    info = PodInfo(raw=d, timestamp=1.5)
    assert (info.namespace, info.name, info.gang, info.priority) == (
        "default",
        "rawpod",
        "g9",
        3,
    )
    assert info._pod is None  # not materialised yet
    typed = info.pod
    assert typed.metadata.name == "rawpod"
    assert typed.spec.priority == 3
    assert info.pod is typed  # cached

    with pytest.raises(ValueError):
        PodInfo()
