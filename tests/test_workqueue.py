from batch_scheduler_tpu.utils.workqueue import RateLimitingQueue


class FakeClock:
    def __init__(self):
        self.now = 0.0

    def __call__(self):
        return self.now


def test_add_get_done_dedup():
    q = RateLimitingQueue(clock=FakeClock())
    q.add("a")
    q.add("a")  # deduped
    q.add("b")
    assert q.get(0) == "a"
    assert q.get(0) == "b"
    assert q.get(0) is None
    q.done("a")
    q.done("b")


def test_readd_while_processing_defers():
    q = RateLimitingQueue(clock=FakeClock())
    q.add("k")
    assert q.get(0) == "k"
    q.add("k")  # while in-flight: marked dirty, not queued
    assert q.get(0) is None
    q.done("k")  # now the dirty key re-queues
    assert q.get(0) == "k"


def test_rate_limited_backoff_grows_and_forget_resets():
    clk = FakeClock()
    q = RateLimitingQueue(base_delay=1.0, max_delay=8.0, clock=clk)
    q.add_rate_limited("k")  # 1s
    assert q.get(0) is None
    clk.now = 1.01
    assert q.get(0) == "k"
    q.done("k")
    q.add_rate_limited("k")  # 2s
    clk.now = 2.0
    assert q.get(0) is None
    clk.now = 3.1
    assert q.get(0) == "k"
    q.done("k")
    q.forget("k")
    q.add_rate_limited("k")  # back to 1s
    clk.now = 4.2
    assert q.get(0) == "k"
    q.done("k")


def test_shutdown_unblocks():
    q = RateLimitingQueue(clock=FakeClock())
    q.shut_down()
    assert q.get(0) is None
    q.add("x")  # ignored after shutdown
    assert len(q) == 0
