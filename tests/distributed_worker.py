"""Worker process for the two-process jax.distributed test (run by
tests/test_distributed.py, one instance per process). Exercises the REAL
multi-host bootstrap path: jax.distributed.initialize from env, a global
(groups x nodes) mesh spanning both processes' devices, and one sharded
oracle batch with cross-process collectives."""

import os
import sys

# run as a script: the repo root (not tests/) must be importable; PYTHONPATH
# must stay unset in this environment (it breaks the axon TPU plugin)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# virtual multi-device CPU platform, forced the conftest way (sitecustomize
# registers the TPU plugin; the config update below is what wins)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=4"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from batch_scheduler_tpu.parallel.distributed import (  # noqa: E402
    global_mesh,
    init_distributed,
)
from batch_scheduler_tpu.parallel.mesh import sharded_schedule_batch  # noqa: E402


def build_snapshot():
    from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot, GroupDemand
    from batch_scheduler_tpu.sim.scenarios import make_sim_node

    nodes = [
        make_sim_node(f"n{i:03d}", {"cpu": "32", "memory": "128Gi", "pods": "110"})
        for i in range(16)
    ]
    groups = [
        GroupDemand(
            full_name=f"default/g{g}",
            min_member=4,
            member_request={"cpu": 2000, "memory": 4 * 1024**3},
            creation_ts=float(g),
        )
        for g in range(8)
    ]
    return ClusterSnapshot(nodes, {}, groups)


def main() -> None:
    assert init_distributed(), "BST_COORDINATOR env not picked up"
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, jax.devices()
    assert len(jax.local_devices()) == 4

    mesh = global_mesh()
    assert mesh.devices.size == 8

    snap = build_snapshot()
    out = sharded_schedule_batch(mesh, snap.device_args())

    from jax.experimental import multihost_utils

    placed = np.asarray(multihost_utils.process_allgather(out["placed"], tiled=True))
    feasible = np.asarray(
        multihost_utils.process_allgather(out["gang_feasible"], tiled=True)
    )
    assert placed[:8].all(), placed
    assert feasible[:8].all(), feasible
    if jax.process_index() == 0:
        print(
            f"DIST-OK processes={jax.process_count()} mesh={dict(mesh.shape)} "
            f"placed={int(placed.sum())}/8"
        )


if __name__ == "__main__":
    main()
    sys.exit(0)
