"""Oracle kernel tests: exactness of the device math and parity between the
batched TPU path and the serial reference-parity path."""

import numpy as np
import pytest

from batch_scheduler_tpu.cache import PGStatusCache
from batch_scheduler_tpu.core.resources import find_max_group_serial
from batch_scheduler_tpu.ops import (
    ClusterSnapshot,
    GroupDemand,
    LaneSchema,
    assign_gangs,
    bucket_size,
    find_max_group,
    gang_feasible,
    group_capacity,
    left_resources,
    schedule_batch,
)

from helpers import make_group, make_node, make_pod, status_for


def test_bucket_sizes():
    assert bucket_size(1) == 8
    assert bucket_size(8) == 8
    assert bucket_size(9) == 16
    assert bucket_size(5000) == 8192


def test_lane_schema_packing():
    schema = LaneSchema.collect([{"cpu": 1000, "nvidia.com/gpu": 2}])
    assert schema.names == ("cpu", "memory", "ephemeral-storage", "pods", "nvidia.com/gpu")
    vec = schema.pack({"cpu": 1500, "memory": 3 * 1024, "nvidia.com/gpu": 2})
    assert vec.tolist() == [1500, 3, 0, 0, 2]  # memory ceil'd to KiB
    cap = schema.pack({"memory": 1024 + 1}, capacity=True)
    assert cap[1] == 1  # capacity floors


def test_lane_schema_autoshift_big_nodes():
    """A >1 TiB-memory node must pack (shifted unit), not abort the batch
    (the reference carries int64 quantities with no cap)."""
    big = {"cpu": 64000, "memory": 2 * 1024**4}  # 2 TiB
    req = {"cpu": 1000, "memory": 8 * 1024**3}
    schema = LaneSchema.collect([big, req])
    mem = schema.index["memory"]
    assert schema.shifts[mem] == 2  # 2 TiB in KiB = 2**31 -> 4-KiB units
    cap = schema.pack(big, capacity=True)
    want = schema.pack(req)
    assert cap[mem] == 2 * 1024**3 // 4  # exact in 4-KiB units
    # capacity floors, request ceils in the shifted unit: fit math stays exact
    assert cap[mem] // want[mem] == (2 * 1024**4) // (8 * 1024**3)


def test_lane_schema_pinned_schema_clamps_safely():
    """With a pinned (stale) schema, an out-of-domain value saturates instead
    of raising — and a clamped request can never fit a clamped capacity."""
    import warnings as _w

    schema = LaneSchema.collect([{"cpu": 1000}])
    huge = {"memory": 4 * 1024**4}  # 4 TiB, beyond the unshifted KiB domain
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        cap = schema.pack(huge, capacity=True)
        req = schema.pack(huge)
    mem = schema.index["memory"]
    assert cap[mem] == 2**30 - 1  # conservative capacity underestimate
    assert req[mem] == 2**30  # strictly above any clamped capacity
    assert req[mem] > cap[mem]


def test_gang_bound_shrinks_with_node_bucket():
    """need * node_bucket must stay < 2**31 for the int32 cumsums; a gang at
    GANG_MAX with a 16k-node bucket must be rejected at the batch boundary."""
    import pytest

    from batch_scheduler_tpu.ops.bucketing import pad_oracle_batch
    from batch_scheduler_tpu.ops.oracle import GANG_MAX

    g, n, r = 1, 2**14, 4
    args = dict(
        alloc=np.zeros((n, r), np.int32),
        requested=np.zeros((n, r), np.int32),
        group_req=np.zeros((g, r), np.int32),
        remaining=np.full(g, GANG_MAX, np.int32),
        fit_mask=np.ones((1, n), bool),
        group_valid=np.ones(g, bool),
        order=np.arange(g, dtype=np.int32),
        min_member=np.full(g, GANG_MAX, np.int32),
        scheduled=np.zeros(g, np.int32),
        matched=np.zeros(g, np.int32),
        ineligible=np.zeros(g, bool),
        creation_rank=np.arange(g, dtype=np.int32),
    )
    with pytest.raises(OverflowError):
        pad_oracle_batch(**args)
    # the same gang on a small node bucket is fine
    args_small = dict(args)
    for k in ("alloc", "requested"):
        args_small[k] = np.zeros((8, r), np.int32)
    args_small["fit_mask"] = np.ones((1, 8), bool)
    pad_oracle_batch(**args_small)


def test_left_resources_percent_exact():
    alloc = np.array([[8000, 1000000, 0, 100]], dtype=np.int32)
    req = np.array([[900, 0, 0, 1]], dtype=np.int32)
    out = np.asarray(left_resources(alloc, req, 7, 10))
    # floor(0.7 * alloc) - requested, exactly
    assert out.tolist() == [[4700, 700000, 0, 69]]


def test_group_capacity_and_feasibility():
    # one node with 7 cpu free, group members need 1 cpu + 1 pod slot
    left = np.array([[7000, 10**6, 10**6, 50]], dtype=np.int32)
    group_req = np.array([[1000, 0, 0, 1], [2000, 0, 0, 1]], dtype=np.int32)
    fit = np.ones((2, 1), dtype=bool)
    cap = np.asarray(group_capacity(left, group_req, fit))
    assert cap.tolist() == [[7], [3]]
    ok = np.asarray(
        gang_feasible(cap, np.array([5, 4], np.int32), np.array([True, True]))
    )
    assert ok.tolist() == [True, False]


def test_gang_race_exactly_one_group_wins():
    """The README race scenario at the oracle level: ~7 free cpus, two
    5-member gangs of 1cpu pods — exactly one gang places."""
    left = np.array([[7100, 10**6, 10**6, 50]], dtype=np.int32)
    group_req = np.array([[1000, 0, 0, 1], [1000, 0, 0, 1]], dtype=np.int32)
    remaining = np.array([5, 5], dtype=np.int32)
    fit = np.ones((2, 1), dtype=bool)
    order = np.array([0, 1], dtype=np.int32)
    alloc, placed, left_after = assign_gangs(left, group_req, remaining, fit, order)
    assert np.asarray(placed).tolist() == [True, False]
    assert np.asarray(alloc).sum() == 5
    assert np.asarray(left_after)[0, 0] == 7100 - 5000


def test_assign_gangs_best_fit_prefers_tight_nodes():
    # two nodes: 2-cap and 10-cap; 2-member gang should pack the tight node
    left = np.array([[2000, 0, 0, 10], [10000, 0, 0, 10]], dtype=np.int32)
    group_req = np.array([[1000, 0, 0, 1]], dtype=np.int32)
    alloc, placed, _ = assign_gangs(
        left, group_req, np.array([2], np.int32),
        np.ones((1, 2), bool), np.array([0], np.int32),
    )
    assert np.asarray(placed).tolist() == [True]
    assert np.asarray(alloc).tolist() == [[2, 0]]


def test_assign_gangs_spills_across_nodes():
    left = np.array([[3000, 0, 0, 10], [3000, 0, 0, 10]], dtype=np.int32)
    group_req = np.array([[1000, 0, 0, 1]], dtype=np.int32)
    alloc, placed, _ = assign_gangs(
        left, group_req, np.array([5], np.int32),
        np.ones((1, 2), bool), np.array([0], np.int32),
    )
    assert np.asarray(placed).tolist() == [True]
    assert sorted(np.asarray(alloc)[0].tolist()) == [2, 3]


def test_priority_order_controls_reservation():
    # capacity 5; group B first in order takes it even though A is feasible alone
    left = np.array([[5000, 0, 0, 10]], dtype=np.int32)
    group_req = np.array([[1000, 0, 0, 1], [1000, 0, 0, 1]], dtype=np.int32)
    remaining = np.array([5, 5], dtype=np.int32)
    fit = np.ones((2, 1), bool)
    alloc, placed, _ = assign_gangs(
        left, group_req, remaining, fit, np.array([1, 0], np.int32)
    )
    assert np.asarray(placed).tolist() == [False, True]


def test_snapshot_padding_does_not_change_results():
    nodes = [make_node(f"n{i}", {"cpu": "4", "memory": "8Gi", "pods": "10"}) for i in range(3)]
    groups = [
        GroupDemand("default/g1", 5, member_request={"cpu": 1000}),
        GroupDemand("default/g2", 20, member_request={"cpu": 1000}),
    ]
    snap = ClusterSnapshot(nodes, {}, groups)
    assert snap.alloc.shape[0] == 8 and snap.group_req.shape[0] == 8  # bucketed
    out = schedule_batch(*snap.device_args())
    feasible = np.asarray(out["gang_feasible"])
    placed = np.asarray(out["placed"])
    # 12 cpu total: g1 (5) fits, g2 (20) cannot
    assert feasible[:2].tolist() == [True, False]
    assert placed[:2].tolist() == [True, False]
    # padded rows never report placement
    assert not placed[2:].any()
    assert not feasible[2:].any()


def test_snapshot_fit_mask_selector():
    nodes = [
        make_node("a", {"cpu": "4", "pods": "10"}, labels={"zone": "east"}),
        make_node("b", {"cpu": "4", "pods": "10"}, labels={"zone": "west"}),
    ]
    groups = [
        GroupDemand(
            "default/g", 2, member_request={"cpu": 1000},
            node_selector={"zone": "east"},
        )
    ]
    snap = ClusterSnapshot(nodes, {}, groups)
    assert snap.fit_mask[0, :2].tolist() == [True, False]
    out = schedule_batch(*snap.device_args())
    alloc = np.asarray(out["assignment"])
    assert alloc[0, 0] == 2 and alloc[0, 1] == 0


def test_collect_batch_fallback_policy():
    """The Pallas-blame policy at the collect sync point: a device failure on
    a pallas-dispatched batch re-runs once on the scan form; only if the
    scan succeeds is the kernel disabled for the process. A scan-path (or
    non-pallas) failure surfaces unchanged."""
    import warnings

    from batch_scheduler_tpu.ops import oracle as omod
    from batch_scheduler_tpu.ops.oracle import (
        PendingBatch,
        collect_batch,
        dispatch_batch,
    )

    nodes = [make_node("n0", {"cpu": "8", "memory": "8Gi", "pods": "10"})]
    groups = [GroupDemand("default/g", 2, member_request={"cpu": 1000})]
    snap = ClusterSnapshot(nodes, {}, groups)
    good = dispatch_batch(snap.device_args(), snap.progress_args())
    good_blob = good.blob
    good_out = good.out

    class Boom:
        def __array__(self, dtype=None):
            raise RuntimeError("device exploded")

    saved = dict(omod._pallas_enabled)
    try:
        # pallas batch fails at collect, scan rerun succeeds -> result comes
        # back, the FAILING VARIANT disabled (the other mode untouched),
        # warning emitted
        omod._pallas_enabled.update(broadcast=True, per_group=True)
        pend = PendingBatch(
            Boom(), good_out, good.pack, True,
            lambda up: (good_blob, good_out), mask_mode="broadcast",
        )
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            host, _ = collect_batch(pend)
        assert host["placed"][:1].tolist() == [True]
        assert omod._pallas_enabled["broadcast"] is False
        assert omod._pallas_enabled["per_group"] is True  # not poisoned
        assert any("pallas" in str(x.message) for x in w)

        # non-pallas batch failing surfaces directly, flags untouched
        omod._pallas_enabled.update(broadcast=True, per_group=True)
        pend2 = PendingBatch(Boom(), good_out, good.pack, False, None)
        with pytest.raises(RuntimeError, match="device exploded"):
            collect_batch(pend2)
        assert omod._pallas_enabled["broadcast"] is True

        # pallas batch fails AND the scan rerun fails -> the ORIGINAL error
        # surfaces and the kernel is NOT blamed
        def bad_rerun(up):
            raise ValueError("link down")

        pend3 = PendingBatch(
            Boom(), good_out, good.pack, True, bad_rerun,
            mask_mode="per_group",
        )
        with pytest.raises(RuntimeError, match="device exploded"):
            collect_batch(pend3)
        assert omod._pallas_enabled["per_group"] is True
    finally:
        omod._pallas_enabled.clear()
        omod._pallas_enabled.update(saved)


def test_find_max_group_matches_serial():
    cache = PGStatusCache()
    specs = [("g1", 10, 2), ("g2", 10, 7), ("g3", 4, 1)]
    for name, mm, scheduled in specs:
        pg = make_group(name, mm)
        pg.status.scheduled = scheduled
        status_for(pg, cache, rep_pod=make_pod(f"{name}-p", group=name, requests={"cpu": "1"}))

    serial_name, _, serial_progress = find_max_group_serial(cache.snapshot())
    assert serial_name == "default/g2"  # 700/1000 progress

    names = sorted(cache.snapshot())
    statuses = [cache.get(n) for n in names]
    min_member = np.array([s.pod_group.spec.min_member for s in statuses], np.int32)
    scheduled = np.array([s.pod_group.status.scheduled for s in statuses], np.int32)
    matched = np.zeros(len(names), np.int32)
    ineligible = np.zeros(len(names), bool)
    rank = np.arange(len(names), dtype=np.int32)
    best, exists, progress = find_max_group(min_member, scheduled, matched, ineligible, rank)
    assert bool(exists)
    assert names[int(best)] == "default/g2"
    assert int(np.asarray(progress)[int(best)]) == serial_progress


def test_find_max_group_skips_released_and_podless():
    min_member = np.array([4, 4], np.int32)
    scheduled = np.array([2, 1], np.int32)
    matched = np.zeros(2, np.int32)
    ineligible = np.array([True, False])  # g0 released
    best, exists, _ = find_max_group(
        min_member, scheduled, matched, ineligible, np.arange(2, dtype=np.int32)
    )
    assert bool(exists) and int(best) == 1

    none_eligible = np.array([True, True])
    _, exists, _ = find_max_group(
        min_member, scheduled, matched, none_eligible, np.arange(2, dtype=np.int32)
    )
    assert not bool(exists)


def test_exact_floordiv_adversarial():
    """The float32 reciprocal division must be bit-exact across the full
    LANE_MAX domain, including the values float32 cannot represent."""
    from batch_scheduler_tpu.ops.oracle import _exact_floordiv

    rng = np.random.default_rng(0)
    hard = [1, 2, 3, 5, 7, 127, 2**24 - 1, 2**24, 2**24 + 1, 2**30 - 1, 2**30]
    num = np.array(
        hard + list(rng.integers(0, 2**30 + 1, size=4096)), dtype=np.int64
    )
    den = np.array(
        hard + list(rng.integers(1, 2**30 + 1, size=4096)), dtype=np.int64
    )
    # all pairs on a coarse grid + elementwise on the random draw
    for d in hard:
        got = np.asarray(_exact_floordiv(num.astype(np.int32), np.full_like(num, d, dtype=np.int32)))
        assert (got == num // d).all(), f"den={d}"
    got = np.asarray(_exact_floordiv(num.astype(np.int32), den.astype(np.int32)))
    assert (got == num // den).all()


def test_gang_feasible_huge_caps_no_overflow():
    # sparse request: only cpu lane -> per-node capacity is huge; the
    # cluster sum must not wrap int32
    n = 4096
    left = np.tile(np.array([[10**6, 0, 0, 0]], np.int32), (n, 1))
    group_req = np.array([[1, 0, 0, 0]], np.int32)  # cap = 1e6 per node
    fit = np.ones((1, n), bool)
    cap = np.asarray(group_capacity(left, group_req, fit))
    assert cap[0, 0] == 10**6
    ok = np.asarray(gang_feasible(cap, np.array([5], np.int32), np.array([True])))
    assert ok.tolist() == [True]


def test_assign_gangs_huge_caps_and_wide_spill():
    # capacities above the ranking-bucket clamp still place correctly
    left = np.tile(np.array([[10**6, 0, 0, 0]], np.int32), (8, 1))
    group_req = np.array([[1, 0, 0, 0]], np.int32)
    alloc, placed, left_after = assign_gangs(
        left, group_req, np.array([300], np.int32),
        np.ones((1, 8), bool), np.array([0], np.int32),
    )
    assert np.asarray(placed).tolist() == [True]
    a = np.asarray(alloc)[0]
    assert a.sum() == 300 and (a >= 0).all()
    assert np.asarray(left_after)[:, 0].sum() == 8 * 10**6 - 300


def test_raw_lane_paths_reject_out_of_domain_values():
    """LaneSchema.pack guards dict packing; the raw-array batch boundary
    (churn fast path, sidecar wire path) must also reject lanes outside the
    exact-division domain rather than compute silently wrong capacities."""
    import pytest

    from batch_scheduler_tpu.ops.bucketing import pad_oracle_batch

    g, n, r = 1, 2, 4
    good = dict(
        alloc=np.zeros((n, r), np.int32),
        requested=np.zeros((n, r), np.int32),
        group_req=np.zeros((g, r), np.int32),
        remaining=np.zeros(g, np.int32),
        fit_mask=np.ones((g, n), bool),
        group_valid=np.ones(g, bool),
        order=np.arange(g, dtype=np.int32),
        min_member=np.ones(g, np.int32),
        scheduled=np.zeros(g, np.int32),
        matched=np.zeros(g, np.int32),
        ineligible=np.zeros(g, bool),
        creation_rank=np.arange(g, dtype=np.int32),
    )
    pad_oracle_batch(**good)  # in-domain passes
    bad = dict(good)
    bad["alloc"] = np.full((n, r), 2**30 + 1, np.int32)
    with pytest.raises(OverflowError):
        pad_oracle_batch(**bad)


def _assign_gangs_python(left0, group_req, remaining, fit_mask, order):
    """Independent pure-Python mirror of assign_gangs' documented greedy
    semantics (tightest-first histogram selection, priority order): the
    third implementation both device paths (lax.scan and the pallas
    kernel) are checked against, so a shared bug in the array math can't
    hide behind scan-vs-pallas equality."""
    BINS = 128
    left = left0.astype(np.int64).copy()  # [N, R]
    n = left.shape[0]
    g = group_req.shape[0]
    takes = np.zeros((g, n), dtype=np.int64)
    placed = np.zeros(g, dtype=bool)
    for s in range(g):
        gi = int(order[s])
        req = group_req[gi].astype(np.int64)
        need = int(remaining[gi])
        mask_row = fit_mask[0] if fit_mask.shape[0] == 1 else fit_mask[gi]
        cap = np.empty(n, dtype=np.int64)
        for i in range(n):
            per = [
                left[i, l] // req[l]
                for l in range(len(req))
                if req[l] > 0
            ]
            c = min(per) if per else 2**30
            cap[i] = max(0, min(c, 2**30)) if mask_row[i] else 0
        capc = np.minimum(cap, need)
        if capc.sum() < need:
            continue
        placed[gi] = True
        # tightest-first: ascending min(cap, BINS-1), then node index;
        # full capc from earlier nodes, remainder at the boundary
        key = np.minimum(cap, BINS - 1)
        taken = 0
        for i in sorted(range(n), key=lambda i: (key[i], i)):
            if taken >= need:
                break
            t = min(int(capc[i]), need - taken)
            takes[gi, i] = t
            taken += t
        left -= takes[gi][:, None] * req[None, :]
    return takes, placed, left


def test_assign_gangs_fuzz_vs_python_mirror():
    rng = np.random.default_rng(11)
    for trial in range(12):
        n = int(rng.integers(1, 20))
        g = int(rng.integers(1, 10))
        r = int(rng.integers(1, 4))
        left0 = rng.integers(0, 50, size=(n, r)).astype(np.int32)
        group_req = rng.integers(0, 6, size=(g, r)).astype(np.int32)
        remaining = rng.integers(0, 20, size=g).astype(np.int32)
        order = rng.permutation(g).astype(np.int32)
        # alternate broadcast [1,N] and per-group [G,N] masks, mostly-true
        rows = 1 if trial % 2 == 0 else g
        fit_mask = rng.random((rows, n)) > 0.2

        dev = assign_gangs(left0, group_req, remaining, fit_mask, order)
        takes_d, placed_d, left_d = [np.asarray(x) for x in dev]
        takes_p, placed_p, left_p = _assign_gangs_python(
            left0, group_req, remaining, fit_mask, order
        )
        np.testing.assert_array_equal(placed_d, placed_p, err_msg=f"trial {trial}")
        np.testing.assert_array_equal(takes_d, takes_p, err_msg=f"trial {trial}")
        np.testing.assert_array_equal(left_d, left_p, err_msg=f"trial {trial}")


def test_assign_gangs_invariants_hypothesis():
    """Property-based structural safety of the assignment scan, on fixed
    shapes (jit cache shared across examples) with hypothesis-driven
    values: takes respect the mask, placed gangs take exactly their need,
    unplaced gangs take nothing, and no node lane is ever driven below
    zero by a take (capacity can only be consumed where it exists)."""
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import given, settings
    from hypothesis import strategies as st
    from hypothesis.extra import numpy as hnp

    N, G, R = 8, 4, 3

    @settings(max_examples=60, deadline=None)
    @given(
        # negative starting lanes included: an over-committed node must
        # contribute zero capacity on that lane, not go MORE negative
        left0=hnp.arrays(np.int32, (N, R), elements=st.integers(-20, 60)),
        group_req=hnp.arrays(np.int32, (G, R), elements=st.integers(0, 7)),
        remaining=hnp.arrays(np.int32, (G,), elements=st.integers(0, 25)),
        order_seed=st.integers(0, 23),
        mask_bits=hnp.arrays(np.bool_, (G, N)),
        broadcast=st.booleans(),
    )
    def check(left0, group_req, remaining, order_seed, mask_bits, broadcast):
        import itertools

        orders = list(itertools.permutations(range(G)))
        order = np.array(orders[order_seed % len(orders)], dtype=np.int32)
        mask = mask_bits[:1] if broadcast else mask_bits

        takes, placed, left_after = (
            np.asarray(x)
            for x in assign_gangs(left0, group_req, remaining, mask, order)
        )
        full_mask = np.broadcast_to(mask, (G, N))
        # mask respected
        assert (takes[~full_mask] == 0).all()
        # placed gangs take exactly their need; unplaced take nothing
        sums = takes.sum(axis=1)
        assert (sums[placed] == remaining[placed]).all()
        assert (sums[~placed] == 0).all()
        # conservation: leftover = start - consumption
        consumed = (takes[:, :, None] * group_req[:, None, :]).sum(axis=0)
        np.testing.assert_array_equal(left_after, left0 - consumed)
        # no lane driven below zero by takes (started-nonnegative lanes)
        assert (left_after[left0 >= 0] >= 0).all()

    check()


def test_compact_readback_tails_wide_gang_and_saturation():
    """The smoke's readback-tail checks (benchmarks/tpu_smoke.py), CPU form
    over the SAME shared scenarios (sim.scenarios.readback_tail_scenarios):
    a gang spanning more distinct nodes than ASSIGNMENT_TOP_K truncates to
    the K largest (node,count) pairs that agree with the dense assignment;
    a per-node count above the packed halfword saturates ONLY the packed
    form (dense + unpacked counts stay exact)."""
    import jax
    import numpy as np

    from batch_scheduler_tpu.ops.oracle import ASSIGNMENT_TOP_K, schedule_batch
    from batch_scheduler_tpu.ops.snapshot import ClusterSnapshot
    from batch_scheduler_tpu.sim.scenarios import readback_tail_scenarios

    (wide_nodes, wide_groups), (big_nodes, big_groups) = (
        readback_tail_scenarios()
    )
    out = schedule_batch(
        *ClusterSnapshot(wide_nodes, {}, wide_groups).device_args(),
        use_pallas=False,
    )
    dense = np.asarray(jax.device_get(out["assignment"]))[0]
    an = np.asarray(out["assignment_nodes"])[0]
    ac = np.asarray(out["assignment_counts"])[0]
    assert bool(np.asarray(out["placed"])[0])
    assert int((dense > 0).sum()) > ASSIGNMENT_TOP_K  # truncation engaged
    assert all(dense[n] == c for n, c in zip(an, ac) if c > 0)
    assert ac.min() >= np.sort(dense)[-len(an)]  # the K largest
    ap = np.asarray(out["assignment_packed"])[0]
    assert np.array_equal(ap >> 16, an)
    assert np.array_equal(ap & 0xFFFF, np.minimum(ac, 2**16 - 1))

    out2 = schedule_batch(
        *ClusterSnapshot(big_nodes, {}, big_groups).device_args(),
        use_pallas=False,
    )
    dense2 = np.asarray(jax.device_get(out2["assignment"]))[0]
    ac2 = np.asarray(out2["assignment_counts"])[0]
    ap2 = np.asarray(out2["assignment_packed"])[0]
    assert dense2.max() == 66000 and ac2.max() == 66000  # exact above 2^16-1
    assert int(ap2[int(ac2.argmax())]) & 0xFFFF == 2**16 - 1  # packed saturates
