"""The /metrics exposition surface (VERDICT r2: A5 'wire and test').

The reference's only observability surface is the embedded kube-scheduler's
Prometheus /metrics endpoint (SURVEY §5); ours must actually serve the
bst_* series the stack records — scraped over HTTP here, not just rendered.
"""

from __future__ import annotations

import urllib.request

from batch_scheduler_tpu.utils.metrics import (
    DEFAULT_REGISTRY,
    Registry,
    serve_metrics,
)


def _scrape(port: int, path: str = "/metrics") -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def test_serve_metrics_scrape_roundtrip():
    reg = Registry()
    reg.counter("test_total", "help text").inc(3)
    reg.histogram("test_seconds", "h").observe(0.05)
    server = serve_metrics(reg, port=0)
    try:
        port = server.server_address[1]
        body = _scrape(port)
        assert "# TYPE test_total counter" in body
        assert "test_total 3" in body
        assert "test_seconds_count 1" in body
        assert '{le="+Inf"}' not in body or "test_seconds_bucket" in body
        assert _scrape(port, "/healthz").strip() == "ok"
    finally:
        server.shutdown()


def test_framework_series_render_after_a_run(tmp_path):
    """Drive the race scenario end-to-end, then scrape: the headline series
    (schedule cycle + oracle batch) must be present with nonzero counts."""
    from batch_scheduler_tpu.sim import SimCluster
    from batch_scheduler_tpu.sim.scenarios import race_scenario

    cluster = SimCluster(scorer="oracle")
    nodes, groups, pods_by_group = race_scenario()
    cluster.add_nodes(nodes)
    for pg in groups:
        cluster.create_group(pg)
    cluster.start()
    try:
        for pods in pods_by_group.values():
            cluster.create_pods(pods)
        assert cluster.wait_for(
            lambda: cluster.scheduler.stats["binds"] >= 5, timeout=60.0
        )
    finally:
        cluster.stop()

    server = serve_metrics(DEFAULT_REGISTRY, port=0)
    try:
        body = _scrape(server.server_address[1])
    finally:
        server.shutdown()
    for series in (
        "bst_schedule_cycle_seconds",
        "bst_oracle_batch_seconds",
        "bst_pods_bound_total",
        "bst_extension_point_seconds",
    ):
        assert f"{series}_count" in body or f"{series} " in body, series
    # counts are nonzero: the run above actually observed into them
    count_lines = {
        line.rsplit(" ", 1)[0]: float(line.rsplit(" ", 1)[1])
        for line in body.splitlines()
        if "_count" in line and not line.startswith("#")
    }
    assert count_lines.get("bst_schedule_cycle_seconds_count", 0) > 0
    assert count_lines.get("bst_oracle_batch_seconds_count", 0) > 0


def test_histogram_quantile_and_snapshot_window():
    reg = Registry()
    h = reg.histogram("q_seconds", "h", buckets=(0.01, 0.1, 1.0, 10.0))
    for _ in range(100):
        h.observe(0.05)
    snap = h.snapshot()
    for _ in range(100):
        h.observe(5.0)
    # overall p50 falls on the boundary between the two equal-sized
    # clusters (rank == cumulative count of the 0.05 bucket -> its bound);
    # windowed p50 is in the 5.0 bucket only
    assert 0.01 < h.quantile(0.5) <= 0.1
    windowed = h.quantile(0.5, since=snap)
    assert 1.0 < windowed <= 10.0
    # sum/count deltas
    _, total_sum, total_n = h.snapshot()
    assert total_n == 200 and abs(total_sum - (100 * 0.05 + 100 * 5.0)) < 1e-6


def test_histogram_quantile_window_edge_cases():
    """quantile(since=...) windowing: empty window, single-bucket window,
    and a ``since`` snapshot NEWER than the series (counter reuse after a
    registry swap) must all answer 0.0, never negative/garbage."""
    reg = Registry()
    h = reg.histogram("w_seconds", "h", buckets=(0.01, 0.1, 1.0))
    # empty series, no window
    assert h.quantile(0.5) == 0.0
    h.observe(0.05)
    # empty window: snapshot taken after the only observation
    snap = h.snapshot()
    assert h.quantile(0.5, since=snap) == 0.0
    # single-bucket window: all new observations in one bucket
    for _ in range(10):
        h.observe(0.5)
    q = h.quantile(0.5, since=snap)
    assert 0.1 < q <= 1.0
    # regression after counter reuse: a "since" snapshot with HIGHER
    # counts than the live series (the old registry's counters outlived a
    # swap) yields a negative window total — must clamp to 0.0
    h2 = reg.histogram("w2_seconds", "h", buckets=(0.01, 0.1, 1.0))
    h2.observe(0.05)
    stale_since = ([5, 5, 5], 99.0, 5)
    assert h2.quantile(0.5, since=stale_since) == 0.0
    # labels isolate windows
    h3 = reg.histogram("w3_seconds", "h", buckets=(0.01, 0.1, 1.0))
    h3.observe(0.05, op="a")
    snap_a = h3.snapshot(op="a")
    h3.observe(0.5, op="b")
    assert h3.quantile(0.5, since=snap_a, op="a") == 0.0
    assert h3.quantile(0.5, op="b") > 0.1


def test_long_op_buckets_cover_compile_times():
    """The compile/long-op preset must not saturate at 10s (XLA compiles
    and cold TPU batches are 20-40s): a 35s observation lands in a finite
    bucket and the quantile resolves above 10s."""
    from batch_scheduler_tpu.utils.metrics import LONG_OP_BUCKETS

    assert max(LONG_OP_BUCKETS) > 40.0
    reg = Registry()
    h = reg.histogram("c_seconds", "h", buckets=LONG_OP_BUCKETS)
    h.observe(35.0)
    assert 20.0 < h.quantile(0.5) <= 40.0
    # the default preset would have capped this at its 10s ceiling
    d = reg.histogram("d_seconds", "h")
    d.observe(35.0)
    assert d.quantile(0.5) == 10.0


def test_debug_trace_and_decisions_endpoints():
    """/debug/trace serves the span ring as Chrome-trace JSON and
    /debug/decisions serves the flight recorder — JSON content type,
    bounded size, and safe under concurrent writes."""
    import json
    import threading

    from batch_scheduler_tpu.utils import trace as trace_mod

    trace_mod.DEFAULT_RECORDER.clear()
    trace_mod.DEFAULT_FLIGHT_RECORDER.clear()
    trace_mod.configure(enabled=True)
    try:
        with trace_mod.start_trace("cycle"):
            with trace_mod.span("select_node"):
                pass
        trace_mod.DEFAULT_FLIGHT_RECORDER.record(
            "default/g0", phase="cycle", verdict="denied", reason="no fit"
        )
        server = serve_metrics(Registry(), port=0)
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                trace_mod.DEFAULT_FLIGHT_RECORDER.record(
                    f"default/h{i % 50}", phase="cycle", verdict="placed"
                )
                with trace_mod.start_trace("cycle"):
                    pass
                i += 1

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            port = server.server_address[1]
            for _ in range(5):  # scrape while writes hammer
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/trace", timeout=5
                ) as r:
                    assert "application/json" in r.headers["Content-Type"]
                    doc = json.loads(r.read().decode())
                events = doc["traceEvents"]
                assert len(events) <= trace_mod.DEFAULT_CAPACITY + 10
                assert any(e.get("name") == "select_node" for e in events)
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/decisions", timeout=5
                ) as r:
                    assert "application/json" in r.headers["Content-Type"]
                    decisions = json.loads(r.read().decode())["decisions"]
                assert decisions["default/g0"][0]["verdict"] == "denied"
                assert decisions["default/g0"][0]["reason"] == "no fit"
            # ?gang= scoping
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/decisions?gang=default/g0",
                timeout=5,
            ) as r:
                scoped = json.loads(r.read().decode())["decisions"]
            assert set(scoped) == {"default/g0"}
        finally:
            stop.set()
            t.join(timeout=5)
            server.shutdown()
    finally:
        trace_mod.configure(enabled=False)
        trace_mod.DEFAULT_RECORDER.clear()
        trace_mod.DEFAULT_FLIGHT_RECORDER.clear()


def test_label_value_escaping_in_exposition():
    """Prometheus text-format escaping: backslash, double quote, and
    newline in a label VALUE must render escaped — one bad node name
    must not corrupt the whole exposition for every scraper."""
    reg = Registry()
    g = reg.gauge("esc_gauge", "help")
    g.set(1.0, node='say "hi"', path="a\\b", reason="line1\nline2")
    body = g.render()
    assert 'node="say \\"hi\\""' in body
    assert 'path="a\\\\b"' in body
    assert 'reason="line1\\nline2"' in body
    # exactly one physical line per sample: the newline never leaks raw
    sample_lines = [
        line for line in body.splitlines() if not line.startswith("#")
    ]
    assert len(sample_lines) == 1
    # counters and histogram bucket labels share the same escaping path
    c = reg.counter("esc_total", "help")
    c.inc(op='x"y')
    assert 'op="x\\"y"' in c.render()
    h = reg.histogram("esc_seconds", "help", buckets=(1.0,))
    h.observe(0.5, op="p\\q")
    assert 'op="p\\\\q"' in h.render()


def test_help_line_escaping_and_type_lines():
    """HELP text with backslashes/newlines renders escaped; every metric
    renders exactly one HELP and one TYPE line of the declared kind."""
    reg = Registry()
    reg.counter("h_total", "first line\nsecond \\ line").inc()
    reg.gauge("h_gauge", "plain").set(2)
    reg.histogram("h_seconds", "hist help").observe(0.01)
    body = reg.render()
    assert "# HELP h_total first line\\nsecond \\\\ line" in body
    for name, kind in (
        ("h_total", "counter"), ("h_gauge", "gauge"), ("h_seconds", "histogram")
    ):
        assert body.count(f"# HELP {name} ") == 1
        assert body.count(f"# TYPE {name} {kind}") == 1
    # no raw newline from HELP text broke a line into a fake sample
    for line in body.splitlines():
        assert line.startswith(("#", "h_")), line


def test_metrics_content_type_and_index_endpoint():
    """/metrics answers the Prometheus text content type; /debug/ serves
    the machine-readable endpoint index and every indexed GET-able
    surface answers 200 JSON (profile capture excluded — the bare GET
    reports state only)."""
    import json
    import urllib.error

    from batch_scheduler_tpu.utils.metrics import DEBUG_ENDPOINTS

    reg = Registry()
    reg.counter("ct_total", "h").inc()
    server = serve_metrics(reg, port=0)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        ) as r:
            assert r.headers["Content-Type"] == "text/plain; version=0.0.4"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/", timeout=5
        ) as r:
            assert "application/json" in r.headers["Content-Type"]
            index = json.loads(r.read())["endpoints"]
        assert set(index) == set(DEBUG_ENDPOINTS)
        for path in index:
            if path in ("/metrics", "/healthz"):
                continue
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30
            ) as r:
                assert r.status == 200, path
                assert "application/json" in r.headers["Content-Type"], path
                json.loads(r.read())
        # unknown paths still 404
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/nope", timeout=5
            )
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        server.shutdown()


def test_metric_kind_stability_under_concurrent_writes():
    """The Registry must resolve a name to ONE kind no matter how many
    threads race the first registration: every same-kind caller gets the
    same instance, every wrong-kind caller gets TypeError (never a
    wrong-kind instance), and the rendered exposition carries a single
    TYPE line for the name."""
    import threading

    reg = Registry()
    results, errors = [], []
    start = threading.Event()

    def register(kind):
        start.wait(5)
        for i in range(50):
            try:
                m = getattr(reg, kind)(f"race_metric_{i % 10}", "h")
                if kind == "counter":
                    m.inc()
                else:
                    m.set(1.0)
                results.append((kind, i % 10, m))
            except TypeError as e:
                errors.append((kind, i % 10, e))

    threads = [
        threading.Thread(target=register, args=(kind,))
        for kind in ("counter", "gauge", "counter", "gauge")
    ]
    for t in threads:
        t.start()
    start.set()
    for t in threads:
        t.join(10)
    # per name: one winning kind, all same-kind instances identical, and
    # every cross-kind attempt raised (never returned the wrong class)
    for i in range(10):
        name = f"race_metric_{i}"
        winners = {id(m) for kind, j, m in results if j == i}
        kinds = {kind for kind, j, _ in results if j == i}
        assert len(winners) == 1, name
        assert len(kinds) == 1, name
        losing_kinds = {kind for kind, j, _ in errors if j == i}
        assert kinds.isdisjoint(losing_kinds)
        body = reg.render()
        assert body.count(f"# TYPE {name} ") == 1
    # and writes survived: the winner rendered with nonzero value
    assert "race_metric_0" in reg.render()


def test_cli_metrics_port_flag():
    """--metrics-port 0 on sim binds an ephemeral /metrics endpoint."""
    import argparse

    from batch_scheduler_tpu.cmd.main import _maybe_serve_metrics

    args = argparse.Namespace(metrics_port=0)
    server = _maybe_serve_metrics(args)
    try:
        assert server is not None
        body = _scrape(server.server_address[1])
        assert "# TYPE" in body
    finally:
        server.shutdown()
    assert _maybe_serve_metrics(argparse.Namespace(metrics_port=None)) is None
