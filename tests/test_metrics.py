"""The /metrics exposition surface (VERDICT r2: A5 'wire and test').

The reference's only observability surface is the embedded kube-scheduler's
Prometheus /metrics endpoint (SURVEY §5); ours must actually serve the
bst_* series the stack records — scraped over HTTP here, not just rendered.
"""

from __future__ import annotations

import urllib.request

from batch_scheduler_tpu.utils.metrics import (
    DEFAULT_REGISTRY,
    Registry,
    serve_metrics,
)


def _scrape(port: int, path: str = "/metrics") -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def test_serve_metrics_scrape_roundtrip():
    reg = Registry()
    reg.counter("test_total", "help text").inc(3)
    reg.histogram("test_seconds", "h").observe(0.05)
    server = serve_metrics(reg, port=0)
    try:
        port = server.server_address[1]
        body = _scrape(port)
        assert "# TYPE test_total counter" in body
        assert "test_total 3" in body
        assert "test_seconds_count 1" in body
        assert '{le="+Inf"}' not in body or "test_seconds_bucket" in body
        assert _scrape(port, "/healthz").strip() == "ok"
    finally:
        server.shutdown()


def test_framework_series_render_after_a_run(tmp_path):
    """Drive the race scenario end-to-end, then scrape: the headline series
    (schedule cycle + oracle batch) must be present with nonzero counts."""
    from batch_scheduler_tpu.sim import SimCluster
    from batch_scheduler_tpu.sim.scenarios import race_scenario

    cluster = SimCluster(scorer="oracle")
    nodes, groups, pods_by_group = race_scenario()
    cluster.add_nodes(nodes)
    for pg in groups:
        cluster.create_group(pg)
    cluster.start()
    try:
        for pods in pods_by_group.values():
            cluster.create_pods(pods)
        assert cluster.wait_for(
            lambda: cluster.scheduler.stats["binds"] >= 5, timeout=60.0
        )
    finally:
        cluster.stop()

    server = serve_metrics(DEFAULT_REGISTRY, port=0)
    try:
        body = _scrape(server.server_address[1])
    finally:
        server.shutdown()
    for series in (
        "bst_schedule_cycle_seconds",
        "bst_oracle_batch_seconds",
        "bst_pods_bound_total",
        "bst_extension_point_seconds",
    ):
        assert f"{series}_count" in body or f"{series} " in body, series
    # counts are nonzero: the run above actually observed into them
    count_lines = {
        line.rsplit(" ", 1)[0]: float(line.rsplit(" ", 1)[1])
        for line in body.splitlines()
        if "_count" in line and not line.startswith("#")
    }
    assert count_lines.get("bst_schedule_cycle_seconds_count", 0) > 0
    assert count_lines.get("bst_oracle_batch_seconds_count", 0) > 0


def test_histogram_quantile_and_snapshot_window():
    reg = Registry()
    h = reg.histogram("q_seconds", "h", buckets=(0.01, 0.1, 1.0, 10.0))
    for _ in range(100):
        h.observe(0.05)
    snap = h.snapshot()
    for _ in range(100):
        h.observe(5.0)
    # overall p50 falls on the boundary between the two equal-sized
    # clusters (rank == cumulative count of the 0.05 bucket -> its bound);
    # windowed p50 is in the 5.0 bucket only
    assert 0.01 < h.quantile(0.5) <= 0.1
    windowed = h.quantile(0.5, since=snap)
    assert 1.0 < windowed <= 10.0
    # sum/count deltas
    _, total_sum, total_n = h.snapshot()
    assert total_n == 200 and abs(total_sum - (100 * 0.05 + 100 * 5.0)) < 1e-6


def test_histogram_quantile_window_edge_cases():
    """quantile(since=...) windowing: empty window, single-bucket window,
    and a ``since`` snapshot NEWER than the series (counter reuse after a
    registry swap) must all answer 0.0, never negative/garbage."""
    reg = Registry()
    h = reg.histogram("w_seconds", "h", buckets=(0.01, 0.1, 1.0))
    # empty series, no window
    assert h.quantile(0.5) == 0.0
    h.observe(0.05)
    # empty window: snapshot taken after the only observation
    snap = h.snapshot()
    assert h.quantile(0.5, since=snap) == 0.0
    # single-bucket window: all new observations in one bucket
    for _ in range(10):
        h.observe(0.5)
    q = h.quantile(0.5, since=snap)
    assert 0.1 < q <= 1.0
    # regression after counter reuse: a "since" snapshot with HIGHER
    # counts than the live series (the old registry's counters outlived a
    # swap) yields a negative window total — must clamp to 0.0
    h2 = reg.histogram("w2_seconds", "h", buckets=(0.01, 0.1, 1.0))
    h2.observe(0.05)
    stale_since = ([5, 5, 5], 99.0, 5)
    assert h2.quantile(0.5, since=stale_since) == 0.0
    # labels isolate windows
    h3 = reg.histogram("w3_seconds", "h", buckets=(0.01, 0.1, 1.0))
    h3.observe(0.05, op="a")
    snap_a = h3.snapshot(op="a")
    h3.observe(0.5, op="b")
    assert h3.quantile(0.5, since=snap_a, op="a") == 0.0
    assert h3.quantile(0.5, op="b") > 0.1


def test_long_op_buckets_cover_compile_times():
    """The compile/long-op preset must not saturate at 10s (XLA compiles
    and cold TPU batches are 20-40s): a 35s observation lands in a finite
    bucket and the quantile resolves above 10s."""
    from batch_scheduler_tpu.utils.metrics import LONG_OP_BUCKETS

    assert max(LONG_OP_BUCKETS) > 40.0
    reg = Registry()
    h = reg.histogram("c_seconds", "h", buckets=LONG_OP_BUCKETS)
    h.observe(35.0)
    assert 20.0 < h.quantile(0.5) <= 40.0
    # the default preset would have capped this at its 10s ceiling
    d = reg.histogram("d_seconds", "h")
    d.observe(35.0)
    assert d.quantile(0.5) == 10.0


def test_debug_trace_and_decisions_endpoints():
    """/debug/trace serves the span ring as Chrome-trace JSON and
    /debug/decisions serves the flight recorder — JSON content type,
    bounded size, and safe under concurrent writes."""
    import json
    import threading

    from batch_scheduler_tpu.utils import trace as trace_mod

    trace_mod.DEFAULT_RECORDER.clear()
    trace_mod.DEFAULT_FLIGHT_RECORDER.clear()
    trace_mod.configure(enabled=True)
    try:
        with trace_mod.start_trace("cycle"):
            with trace_mod.span("select_node"):
                pass
        trace_mod.DEFAULT_FLIGHT_RECORDER.record(
            "default/g0", phase="cycle", verdict="denied", reason="no fit"
        )
        server = serve_metrics(Registry(), port=0)
        stop = threading.Event()

        def hammer():
            i = 0
            while not stop.is_set():
                trace_mod.DEFAULT_FLIGHT_RECORDER.record(
                    f"default/h{i % 50}", phase="cycle", verdict="placed"
                )
                with trace_mod.start_trace("cycle"):
                    pass
                i += 1

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            port = server.server_address[1]
            for _ in range(5):  # scrape while writes hammer
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/trace", timeout=5
                ) as r:
                    assert "application/json" in r.headers["Content-Type"]
                    doc = json.loads(r.read().decode())
                events = doc["traceEvents"]
                assert len(events) <= trace_mod.DEFAULT_CAPACITY + 10
                assert any(e.get("name") == "select_node" for e in events)
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/decisions", timeout=5
                ) as r:
                    assert "application/json" in r.headers["Content-Type"]
                    decisions = json.loads(r.read().decode())["decisions"]
                assert decisions["default/g0"][0]["verdict"] == "denied"
                assert decisions["default/g0"][0]["reason"] == "no fit"
            # ?gang= scoping
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/decisions?gang=default/g0",
                timeout=5,
            ) as r:
                scoped = json.loads(r.read().decode())["decisions"]
            assert set(scoped) == {"default/g0"}
        finally:
            stop.set()
            t.join(timeout=5)
            server.shutdown()
    finally:
        trace_mod.configure(enabled=False)
        trace_mod.DEFAULT_RECORDER.clear()
        trace_mod.DEFAULT_FLIGHT_RECORDER.clear()


def test_cli_metrics_port_flag():
    """--metrics-port 0 on sim binds an ephemeral /metrics endpoint."""
    import argparse

    from batch_scheduler_tpu.cmd.main import _maybe_serve_metrics

    args = argparse.Namespace(metrics_port=0)
    server = _maybe_serve_metrics(args)
    try:
        assert server is not None
        body = _scrape(server.server_address[1])
        assert "# TYPE" in body
    finally:
        server.shutdown()
    assert _maybe_serve_metrics(argparse.Namespace(metrics_port=None)) is None
