"""The /metrics exposition surface (VERDICT r2: A5 'wire and test').

The reference's only observability surface is the embedded kube-scheduler's
Prometheus /metrics endpoint (SURVEY §5); ours must actually serve the
bst_* series the stack records — scraped over HTTP here, not just rendered.
"""

from __future__ import annotations

import urllib.request

from batch_scheduler_tpu.utils.metrics import (
    DEFAULT_REGISTRY,
    Registry,
    serve_metrics,
)


def _scrape(port: int, path: str = "/metrics") -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.read().decode()


def test_serve_metrics_scrape_roundtrip():
    reg = Registry()
    reg.counter("test_total", "help text").inc(3)
    reg.histogram("test_seconds", "h").observe(0.05)
    server = serve_metrics(reg, port=0)
    try:
        port = server.server_address[1]
        body = _scrape(port)
        assert "# TYPE test_total counter" in body
        assert "test_total 3" in body
        assert "test_seconds_count 1" in body
        assert '{le="+Inf"}' not in body or "test_seconds_bucket" in body
        assert _scrape(port, "/healthz").strip() == "ok"
    finally:
        server.shutdown()


def test_framework_series_render_after_a_run(tmp_path):
    """Drive the race scenario end-to-end, then scrape: the headline series
    (schedule cycle + oracle batch) must be present with nonzero counts."""
    from batch_scheduler_tpu.sim import SimCluster
    from batch_scheduler_tpu.sim.scenarios import race_scenario

    cluster = SimCluster(scorer="oracle")
    nodes, groups, pods_by_group = race_scenario()
    cluster.add_nodes(nodes)
    for pg in groups:
        cluster.create_group(pg)
    cluster.start()
    try:
        for pods in pods_by_group.values():
            cluster.create_pods(pods)
        assert cluster.wait_for(
            lambda: cluster.scheduler.stats["binds"] >= 5, timeout=60.0
        )
    finally:
        cluster.stop()

    server = serve_metrics(DEFAULT_REGISTRY, port=0)
    try:
        body = _scrape(server.server_address[1])
    finally:
        server.shutdown()
    for series in (
        "bst_schedule_cycle_seconds",
        "bst_oracle_batch_seconds",
        "bst_pods_bound_total",
        "bst_extension_point_seconds",
    ):
        assert f"{series}_count" in body or f"{series} " in body, series
    # counts are nonzero: the run above actually observed into them
    count_lines = {
        line.rsplit(" ", 1)[0]: float(line.rsplit(" ", 1)[1])
        for line in body.splitlines()
        if "_count" in line and not line.startswith("#")
    }
    assert count_lines.get("bst_schedule_cycle_seconds_count", 0) > 0
    assert count_lines.get("bst_oracle_batch_seconds_count", 0) > 0


def test_histogram_quantile_and_snapshot_window():
    reg = Registry()
    h = reg.histogram("q_seconds", "h", buckets=(0.01, 0.1, 1.0, 10.0))
    for _ in range(100):
        h.observe(0.05)
    snap = h.snapshot()
    for _ in range(100):
        h.observe(5.0)
    # overall p50 falls on the boundary between the two equal-sized
    # clusters (rank == cumulative count of the 0.05 bucket -> its bound);
    # windowed p50 is in the 5.0 bucket only
    assert 0.01 < h.quantile(0.5) <= 0.1
    windowed = h.quantile(0.5, since=snap)
    assert 1.0 < windowed <= 10.0
    # sum/count deltas
    _, total_sum, total_n = h.snapshot()
    assert total_n == 200 and abs(total_sum - (100 * 0.05 + 100 * 5.0)) < 1e-6


def test_cli_metrics_port_flag():
    """--metrics-port 0 on sim binds an ephemeral /metrics endpoint."""
    import argparse

    from batch_scheduler_tpu.cmd.main import _maybe_serve_metrics

    args = argparse.Namespace(metrics_port=0)
    server = _maybe_serve_metrics(args)
    try:
        assert server is not None
        body = _scrape(server.server_address[1])
        assert "# TYPE" in body
    finally:
        server.shutdown()
    assert _maybe_serve_metrics(argparse.Namespace(metrics_port=None)) is None
