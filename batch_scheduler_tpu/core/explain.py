"""The gang observatory: explain ("why is my gang pending") and what-if
("what change would place it") over the live oracle.

Two product surfaces over machinery earlier PRs built (docs/observability.md
"Explain" / "What-if"):

- **Explain** (``Observatory.explain``, ``/debug/explain?gang=NS/NAME``,
  the ``explain`` CLI subcommand): runs the jit'd ``ops.explain``
  breakdown kernel on the CURRENT batch's packed inputs and assembles the
  human answer — denial verdict with the EXACT PreFilter blame string
  (core.operation's deny-reason builders, so explanation and recorded
  denial can never drift), per-lane deficits + the binding lane, hard-mask
  vs capacity exclusion counts, near-miss nodes with per-term policy
  penalties (policy.engine.PolicyEngine.explain), preemption candidacy
  (policy.preempt.PreemptionPlanner dry-run), all cross-stamped against
  the flight recorder's decision records (``recorded_agrees``).

- **What-if** (``Observatory.whatif``, ``/debug/whatif``, the ``whatif``
  CLI subcommand): forks the device-resident state copy-on-write
  (ops.device_state.DeviceStateHolder.fork — NEVER the live holder, which
  concurrent batches keep scoring), applies a counterfactual (drain /
  cordon node, add N nodes of a shape, bump a gang's priority tier,
  remove a gang) to a fresh read of the live cluster inputs, re-runs the
  EXACT scoring path on the forked state (the replay rung-pinning
  discipline: a non-steady rung runs under ops.oracle.forced_scan_rung,
  so a what-if can never flip a process gate or demote a serving
  feature), and returns a placement diff — newly-placeable gangs,
  displaced seats, per-lane headroom delta. Counterfactual correctness is
  gated by ``make bench-whatif``: applying C through the engine is
  bit-identical (plan digest) to a cluster that actually applied C and
  rescheduled, and the live holder's generation/digests are untouched.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "Observatory",
    "WhatIfEngine",
    "COUNTERFACTUAL_KINDS",
    "WHATIF_RUNGS",
    "parse_counterfactual",
    "apply_counterfactual",
    "explain_arrays",
    "baseline_inputs_key",
    "set_active_observatory",
    "active_observatory",
    "explain_debug_view",
    "whatif_debug_view",
]

COUNTERFACTUAL_KINDS = (
    "drain", "cordon", "add-nodes", "bump-gang", "remove-gang",
)

# The rungs a what-if may score on — REPLAY_RUNGS minus nothing: "steady"
# executes exactly what this process would dispatch now; the others are
# thread-locally pinned (forced_scan_rung), so a what-if failure never
# permanently demotes a serving feature.
WHATIF_RUNGS = ("steady", "wavefront", "cpu-ladder", "topk")


# ---------------------------------------------------------------------------
# counterfactuals
# ---------------------------------------------------------------------------


def parse_counterfactual(params: Dict[str, str]) -> dict:
    """Normalize the /debug/whatif query grammar (one counterfactual per
    query) into the canonical dict form; raises ValueError with the full
    grammar on anything malformed. Grammar (docs/observability.md):

      ?drain=NODE
      ?cordon=NODE
      ?add_nodes=N[&node_cpu=32][&node_memory=128Gi][&node_pods=110]
      ?bump_gang=NS/NAME&tier=T
      ?remove_gang=NS/NAME
    """
    present = [
        k for k in ("drain", "cordon", "add_nodes", "bump_gang",
                    "remove_gang")
        if params.get(k)
    ]
    if len(present) != 1:
        raise ValueError(
            "exactly one counterfactual per query: ?drain=NODE | "
            "?cordon=NODE | ?add_nodes=N[&node_cpu=..][&node_memory=..]"
            "[&node_pods=..] | ?bump_gang=NS/NAME&tier=T | "
            "?remove_gang=NS/NAME"
        )
    key = present[0]
    if key == "drain":
        return {"kind": "drain", "node": params["drain"]}
    if key == "cordon":
        return {"kind": "cordon", "node": params["cordon"]}
    if key == "add_nodes":
        try:
            count = int(params["add_nodes"])
        except ValueError:
            raise ValueError(
                f"add_nodes={params['add_nodes']!r} is not an integer"
            ) from None
        if not 0 < count <= 4096:
            raise ValueError("add_nodes must be in [1, 4096]")
        return {
            "kind": "add-nodes",
            "count": count,
            "shape": {
                "cpu": params.get("node_cpu", "32"),
                "memory": params.get("node_memory", "128Gi"),
                "pods": params.get("node_pods", "110"),
            },
        }
    if key == "bump_gang":
        try:
            tier = int(params.get("tier", ""))
        except ValueError:
            raise ValueError(
                "bump_gang requires &tier=T (an integer priority class)"
            ) from None
        return {"kind": "bump-gang", "gang": params["bump_gang"],
                "tier": tier}
    return {"kind": "remove-gang", "gang": params["remove_gang"]}


def apply_counterfactual(nodes: list, node_req: dict, demands: list,
                         cf: dict) -> Tuple[list, dict, list]:
    """Apply one counterfactual to host-side cluster inputs, returning
    NEW (nodes, node_requested, demands) — the live objects are never
    mutated (cordon deep-copies its node). This is deliberately the same
    surface a real cluster change flows through (the inputs
    ``core.oracle_scorer.read_cluster_inputs`` reads), which is what makes
    the what-if plan bit-identical to a cluster that actually applied the
    change: both feed the identical pack + scoring path."""
    kind = cf.get("kind")
    if kind == "drain":
        name = cf["node"]
        out = [n for n in nodes if n.metadata.name != name]
        if len(out) == len(nodes):
            raise ValueError(f"unknown node {name!r}")
        return out, {k: v for k, v in node_req.items() if k != name}, demands
    if kind == "cordon":
        name = cf["node"]
        out = []
        found = False
        for n in nodes:
            if n.metadata.name == name:
                n = n.deepcopy()
                n.spec.unschedulable = True
                found = True
            out.append(n)
        if not found:
            raise ValueError(f"unknown node {name!r}")
        return out, node_req, demands
    if kind == "add-nodes":
        from ..sim.scenarios import make_sim_node

        added = [
            make_sim_node(f"whatif-node-{i:04d}", dict(cf["shape"]))
            for i in range(int(cf["count"]))
        ]
        return list(nodes) + added, node_req, demands
    if kind == "bump-gang":
        gang = cf["gang"]
        out = [
            replace(d, priority=int(cf["tier"]))
            if d.full_name == gang else d
            for d in demands
        ]
        if all(d is demands[i] for i, d in enumerate(out)):
            raise ValueError(f"unknown gang {gang!r}")
        return nodes, node_req, out
    if kind == "remove-gang":
        gang = cf["gang"]
        out = [d for d in demands if d.full_name != gang]
        if len(out) == len(demands):
            raise ValueError(f"unknown gang {gang!r}")
        return nodes, node_req, out
    raise ValueError(
        f"unknown counterfactual kind {kind!r} (use one of "
        f"{COUNTERFACTUAL_KINDS})"
    )


# ---------------------------------------------------------------------------
# rung-pinned execution (the replay discipline applied to the future)
# ---------------------------------------------------------------------------


def _execute_rung(batch_args, progress_args, rung: str, policy=None):
    """Run one batch on ``rung`` and return the host result.

    ``steady`` dispatches exactly what this process would serve right now
    (device-resident fork args ride through untouched). Every other rung
    runs under the thread-local ``forced_scan_rung`` pin replay uses —
    never the process gates, never the disable-on-failure policy."""
    from ..ops.oracle import execute_batch_host, forced_scan_rung

    if rung == "steady":
        host, _ = execute_batch_host(batch_args, progress_args,
                                     policy=policy)
        return host
    if rung == "wavefront":
        from ..ops.bucketing import wave_width_bucket

        with forced_scan_rung(False, wave_width_bucket(8)):
            host, _ = execute_batch_host(batch_args, progress_args,
                                         policy=policy)
        return host
    if rung == "topk":
        from ..ops.bucketing import topk_bucket, wave_width_bucket

        with forced_scan_rung(False, wave_width_bucket(8),
                              topk_bucket(16)):
            host, _ = execute_batch_host(batch_args, progress_args,
                                         policy=policy)
        return host
    if rung == "cpu-ladder":
        import jax

        batch_args = tuple(np.asarray(a) for a in batch_args)
        progress_args = tuple(np.asarray(a) for a in progress_args)
        cpu = jax.local_devices(backend="cpu")[0]
        with forced_scan_rung(False, 0), jax.default_device(cpu):
            host, _ = execute_batch_host(batch_args, progress_args,
                                         policy=policy)
        return host
    raise ValueError(
        f"unknown what-if rung {rung!r} (use one of {WHATIF_RUNGS})"
    )


# ---------------------------------------------------------------------------
# what-if engine
# ---------------------------------------------------------------------------


def _placement_map(snap, host) -> Dict[str, Dict[str, int]]:
    """gang -> {node: seats} for the batch's placed gangs (compact top-K
    assignment; exact for gangs spanning <= K nodes — the same readback
    OracleScorer.assignment serves)."""
    out: Dict[str, Dict[str, int]] = {}
    names = snap.node_names
    placed = np.asarray(host["placed"])
    nodes_rows = np.asarray(host["assignment_nodes"])
    counts_rows = np.asarray(host["assignment_counts"])
    for gi, gang in enumerate(snap.group_names):
        if not bool(placed[gi]):
            continue
        seats: Dict[str, int] = {}
        for idx, cnt in zip(nodes_rows[gi], counts_rows[gi]):
            if cnt > 0 and int(idx) < len(names):
                seats[names[int(idx)]] = int(cnt)
        out[gang] = seats
    return out


def _feasible_set(snap, host) -> set:
    feas = np.asarray(host["gang_feasible"])
    return {
        gang for gi, gang in enumerate(snap.group_names) if bool(feas[gi])
    }


def baseline_inputs_key(version, nodes, demands) -> tuple:
    """The what-if baseline-cache key: a fingerprint of the INPUTS the
    baseline was packed from. ``cluster.version()`` alone is not enough —
    it bumps on node/pod capacity events but NOT on pod-group/demand
    churn (a created gang flows through ``mark_dirty``/ensure_fresh, not
    the version counter), and a baseline diffed against fresher demands
    would attribute cluster churn to the counterfactual. O(G·R) host
    hashing, trivia next to the batch it guards."""
    return (
        version,
        len(nodes),
        hash(
            tuple(
                (
                    d.full_name, d.priority, d.min_member, d.scheduled,
                    d.matched, d.released,
                    tuple(sorted(d.member_request.items())),
                )
                for d in demands
            )
        ),
    )


def _headroom_by_lane(snap) -> Dict[str, int]:
    """Per-lane schedulable headroom (device units): sum over valid nodes
    of clip(alloc - requested, 0)."""
    valid = np.asarray(snap.node_valid)
    left = np.clip(
        snap.alloc.astype(np.int64) - snap.requested.astype(np.int64),
        0, None,
    )
    return {
        name: int(left[valid, i].sum())
        for i, name in enumerate(snap.schema.names)
    }


class WhatIfEngine:
    """Counterfactual scorer over copy-on-write device-state forks.

    One query = pack the (baseline, counterfactual) snapshots from the
    SAME cluster read, install the baseline on a fork of the live
    device-resident holder (keyframe — the live holder is never written),
    apply the counterfactual to a fork-of-the-fork as row scatters
    (copy-on-write: shared buffers, fresh arrays), execute both on the
    requested rung, and diff. The baseline (snapshot + result + resident
    fork) is cached per ``baseline_key`` so a what-if storm against an
    unchanged cluster pays ONE extra batch per query — the <= 2x-steady
    latency bound ``make bench-whatif`` enforces.
    """

    def __init__(self, holder_source=None, policy_engine=None):
        # serializes queries end-to-end: the fork chain and baseline
        # cache are single-writer, and the endpoint is a debug surface
        self._lock = threading.Lock()
        # callable -> the live DeviceStateHolder (or None); resolved per
        # query so a scorer constructed later is still picked up
        self._holder_source = holder_source
        self.policy_engine = policy_engine
        # (key, snap, host, digest, fork, device_args) of the cached
        # baseline
        self._baseline: Optional[tuple] = None  # guarded-by: _lock
        self.queries = 0  # guarded-by: _lock

    def _fork(self):
        from ..ops.device_state import DeviceStateHolder

        live = self._holder_source() if self._holder_source else None
        if live is not None and live.mesh is None:
            return live.fork()
        # No live single-device holder: detached fork (keyframes
        # everything; same semantics, no shared state). Covers
        # BST_DEVICE_STATE=0, remote scorers (the device lives behind
        # the sidecar), and MESH holders — their resident buffers are
        # node-sharded for the sharded scan while the what-if executes
        # replicated single-device; plans are bit-identical across those
        # layouts by construction (docs/scan_parallelism.md), so nothing
        # is lost but the buffer sharing.
        return DeviceStateHolder(label="whatif").fork()

    def _pack(self, nodes, node_req, demands):
        from ..ops.snapshot import ClusterSnapshot

        engine = self.policy_engine
        if engine is not None and not engine.enabled:
            engine = None
        return ClusterSnapshot(
            nodes, node_req, demands, policy_engine=engine
        )

    def _digest(self, host) -> str:
        from ..utils import audit as audit_mod

        return audit_mod.plan_digest(host)

    def query_on(self, nodes, node_req, demands, cf: dict,
                 rung: str = "steady",
                 baseline_key=None) -> dict:
        """Score one counterfactual against explicit cluster inputs (the
        Observatory passes a live read; gates pass synthetic ones).
        Raises ValueError on a malformed counterfactual or unknown
        node/gang."""
        if rung not in WHATIF_RUNGS:
            raise ValueError(
                f"unknown what-if rung {rung!r} (use one of {WHATIF_RUNGS})"
            )
        t0 = time.perf_counter()
        cf_nodes, cf_req, cf_demands = apply_counterfactual(
            nodes, node_req, demands, cf
        )
        with self._lock:
            self.queries += 1
            cached = self._baseline
            use_cache = (
                cached is not None
                and baseline_key is not None
                and cached[0] == (baseline_key, rung)
            )
            if use_cache:
                _, base_snap, base_host, base_digest, fork, base_args = (
                    cached
                )
            else:
                base_snap = self._pack(nodes, node_req, demands)
                fork = self._fork()
                base_args = fork.keyframe(
                    base_snap.device_args(), 0, "whatif-base"
                )
                base_host = _execute_rung(
                    base_args, base_snap.progress_args(), rung,
                    policy=base_snap.policy_payload(),
                )
                base_digest = self._digest(base_host)
                if baseline_key is not None:
                    self._baseline = (
                        (baseline_key, rung), base_snap, base_host,
                        base_digest, fork, base_args,
                    )
            cf_snap = self._pack(cf_nodes, cf_req, cf_demands)
            cf_fork = fork.fork()
            cf_args = cf_fork.apply_batch(
                cf_snap.device_args(), base_snap.device_args()
            )
            cf_host = _execute_rung(
                cf_args, cf_snap.progress_args(), rung,
                policy=cf_snap.policy_payload(),
            )
            cf_digest = self._digest(cf_host)
        elapsed = time.perf_counter() - t0

        base_place = _placement_map(base_snap, base_host)
        cf_place = _placement_map(cf_snap, cf_host)
        base_feas = _feasible_set(base_snap, base_host)
        cf_feas = _feasible_set(cf_snap, cf_host)
        moved: Dict[str, Dict[str, int]] = {}
        displaced_seats = 0
        for gang in sorted(set(base_place) & set(cf_place)):
            b, c = base_place[gang], cf_place[gang]
            if b == c:
                continue
            delta = {
                node: c.get(node, 0) - b.get(node, 0)
                for node in sorted(set(b) | set(c))
                if c.get(node, 0) != b.get(node, 0)
            }
            moved[gang] = delta
            displaced_seats += sum(-v for v in delta.values() if v < 0)
        base_head = _headroom_by_lane(base_snap)
        cf_head = _headroom_by_lane(cf_snap)

        from ..utils.metrics import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.counter(
            "bst_whatif_queries_total",
            "What-if counterfactual queries by kind (/debug/whatif)",
        ).inc(kind=cf["kind"])
        DEFAULT_REGISTRY.histogram(
            "bst_whatif_query_seconds",
            "End-to-end what-if query time (pack + forked batch + diff)",
        ).observe(elapsed)
        return {
            "kind": cf["kind"],
            "counterfactual": dict(cf),
            "rung": rung,
            "elapsed_s": round(elapsed, 6),
            "baseline_cached": bool(use_cache),
            "base": {
                "plan_digest": base_digest,
                "groups": len(base_snap.group_names),
                "nodes": len(base_snap.node_names),
                "placed": len(base_place),
                "feasible": len(base_feas),
            },
            "whatif": {
                "plan_digest": cf_digest,
                "groups": len(cf_snap.group_names),
                "nodes": len(cf_snap.node_names),
                "placed": len(cf_place),
                "feasible": len(cf_feas),
            },
            "newly_placeable": sorted(set(cf_place) - set(base_place)),
            "no_longer_placeable": sorted(
                set(base_place) - set(cf_place)
            ),
            "feasibility_gained": sorted(cf_feas - base_feas),
            "feasibility_lost": sorted(base_feas - cf_feas),
            "displaced_seats": displaced_seats,
            "moved": moved,
            "headroom_delta": {
                lane: cf_head.get(lane, 0) - base_head.get(lane, 0)
                for lane in sorted(set(base_head) | set(cf_head))
                if cf_head.get(lane, 0) != base_head.get(lane, 0)
            },
        }


# ---------------------------------------------------------------------------
# explain assembly (shared by the live observatory and the offline CLI)
# ---------------------------------------------------------------------------


def explain_arrays(batch_args, g: int, node_names: Optional[List[str]] = None,
                   lane_names: Optional[List[str]] = None,
                   policy=None) -> dict:
    """Run the ops.explain kernel on one batch's packed inputs and fold
    the arrays into the structured host payload (names attached when
    known — the offline audit-record path has node/group names but no
    lane schema, so lanes fall back to ``lane<i>``)."""
    from ..ops.explain import explain_gang

    args = tuple(np.asarray(a) for a in batch_args)
    n_bucket = args[0].shape[0]
    lanes_n = args[0].shape[1]
    n_real = len(node_names) if node_names else n_bucket
    kwargs = {}
    if policy is not None:
        cols, terms, weights = policy
        kwargs = {
            "policy_cols": tuple(np.asarray(c) for c in cols),
            "policy_terms": tuple(terms),
            "policy_weights": tuple(weights),
        }
    res = explain_gang(
        *args, np.int32(g), np.int32(n_real), **kwargs
    )
    res = {k: np.asarray(v) for k, v in res.items()}
    lanes = (
        list(lane_names)
        if lane_names
        else [f"lane{r}" for r in range(lanes_n)]
    )

    def node_name(i: int) -> str:
        if node_names and 0 <= i < len(node_names):
            return node_names[i]
        return f"node{i}"

    binding = {
        lanes[r]: int(c)
        for r, c in enumerate(res["binding_counts"])
        if int(c) > 0
    }
    binding_lane = (
        max(binding, key=binding.get) if binding else None
    )
    near = []
    for j, idx in enumerate(res["near_idx"]):
        idx = int(idx)
        if idx >= n_real:
            continue
        deficit = {
            lanes[r]: int(v)
            for r, v in enumerate(res["near_deficit"][j])
            if int(v) > 0
        }
        entry = {
            "node": node_name(idx),
            "capacity_entry": int(res["near_cap"][j]),
            "capacity_alone": int(res["near_cap_indep"][j]),
            "deficit": deficit,
            "headroom": {
                lanes[r]: int(v)
                for r, v in enumerate(res["near_left"][j])
            },
        }
        if policy is not None:
            entry["policy_penalty"] = int(res["near_pen"][j])
        near.append(entry)
    return {
        "gang_index": int(g),
        "need": int(res["need"]),
        "feasible_alone": bool(res["feasible_indep"]),
        "feasible_at_entry": bool(res["feasible_entry"]),
        "nodes_indep": int(res["nodes_indep"]),
        "nodes_entry": int(res["nodes_entry"]),
        "excluded": {
            "fit_mask": int(res["masked_out"]),
            "policy_mask": int(res["policy_masked"]),
            "capacity": int(res["capacity_blocked"]),
        },
        "binding_lane": binding_lane,
        "blocked_by_lane": binding,
        "near_miss": near,
        "headroom_entry": {
            lanes[r]: round(float(v), 1)
            for r, v in enumerate(res["headroom_entry"])
        },
        "headroom_after_batch": {
            lanes[r]: round(float(v), 1)
            for r, v in enumerate(res["headroom_after"])
        },
    }


# ---------------------------------------------------------------------------
# the live observatory
# ---------------------------------------------------------------------------


class Observatory:
    """The per-process explain/what-if surface, constructed by
    ScheduleOperation in oracle mode and registered process-wide for the
    /debug endpoints (utils.metrics) and the SimCluster harness views."""

    def __init__(self, operation):
        self.operation = operation
        self.whatif_engine = WhatIfEngine(
            holder_source=lambda: getattr(
                operation.oracle, "_device_state", None
            ),
            policy_engine=operation.policy,
        )

    # -- explain ------------------------------------------------------------

    def explain(self, gang: str) -> dict:
        from ..utils.metrics import DEFAULT_REGISTRY
        from ..utils.trace import DEFAULT_FLIGHT_RECORDER
        from .operation import (
            deny_degraded_reason,
            deny_infeasible_reason,
            deny_reserved_reason,
        )

        DEFAULT_REGISTRY.counter(
            "bst_explain_queries_total",
            "Gang explain queries (/debug/explain + the explain "
            "subcommand)",
        ).inc()
        op = self.operation
        oracle = op.oracle
        if oracle is None:
            return {"error": "no oracle scorer in this process"}
        state = oracle._state
        if state is None:
            return {"error": "no oracle batch published yet"}
        snap = state.snapshot
        g = snap.group_index(gang)
        if g is None:
            return {
                "error": f"unknown gang {gang!r}",
                "known_gangs": len(snap.group_names),
            }
        out = explain_arrays(
            snap.device_args(), g, node_names=snap.node_names,
            lane_names=snap.schema.names, policy=snap.policy_payload(),
        )
        out["gang"] = gang
        out["batch"] = oracle.batches_run
        out["degraded"] = bool(getattr(oracle, "degraded", False))
        # refresh provenance (docs/pipelining.md "Snapshot-lite & event
        # ingest"): which path built the serving batch's inputs — a full
        # scan or an event fold — and at what pack generation. The
        # breakdown above reads the snapshot's HOST arrays (device_args),
        # which the device-derived fit/order columns equal byte-for-byte
        # by construction, so recorded_agrees below is unaffected by the
        # derivation path.
        delta = getattr(snap, "delta", None)
        if delta is not None:
            out["refresh"] = {
                "generation": int(delta.generation),
                "kind": delta.kind,
                "reason": delta.reason,
                "source": getattr(delta, "source", "scan"),
            }
        # the recorded-blame count: PreFilter's denial records carry the
        # capacity-row feasible-node count, which is the INDEPENDENT
        # count by construction (both read cap vs the batch-head leftover)
        out["feasible_nodes"] = out["nodes_indep"]

        placed = bool(state.result["placed"][g])
        feasible = bool(state.result["gang_feasible"][g])
        pgs = op.status_cache.get(gang)
        min_member = (
            pgs.pod_group.spec.min_member
            if pgs is not None
            else int(snap.groups[g].min_member)
        )
        if placed:
            verdict, reason = "placed", ""
            out["assignment"] = oracle.assignment(gang)
        elif out["degraded"]:
            # the conservative fallback batch denies ONLY provably-
            # infeasible gangs; a feasible gang PASSES to the per-pod
            # scan (docs/resilience.md) — explain must not fabricate a
            # "reserved" denial the degraded PreFilter can never emit
            if not feasible:
                verdict = "denied"
                reason = deny_degraded_reason(gang, min_member)
            else:
                verdict, reason = "pass", ""
                out["note"] = (
                    "degraded oracle: feasible gangs bypass PreFilter "
                    "and place through the per-pod scan"
                )
        elif feasible:
            verdict, reason = "denied", deny_reserved_reason(gang)
        else:
            verdict = "denied"
            reason = deny_infeasible_reason(gang, min_member)
        out["verdict"] = verdict
        out["deny_reason"] = reason

        # flight-recorder cross-stamp: the explanation must AGREE with
        # the recorded decision (same blame string, same feasible count)
        recs = DEFAULT_FLIGHT_RECORDER.snapshot(gang).get(gang, [])
        recorded = next(
            (r for r in reversed(recs) if r.get("phase") == "pre_filter"),
            None,
        )
        if recorded is not None:
            out["recorded"] = {
                "reason": recorded.get("reason"),
                "feasible_nodes": recorded.get("feasible_nodes"),
                "batch": recorded.get("batch"),
                "ts": recorded.get("ts"),
            }
            if verdict == "denied":
                out["recorded_agrees"] = (
                    recorded.get("reason") == reason
                    and (
                        recorded.get("feasible_nodes") is None
                        or recorded.get("feasible_nodes")
                        == out["feasible_nodes"]
                    )
                )
        if op.policy is not None and snap.policy_cols is not None:
            try:
                idx = [
                    snap.node_index(n["node"])
                    for n in out["near_miss"]
                    if snap.node_index(n["node"]) is not None
                ]
                terms = op.policy.explain(snap.policy_cols, g, idx)
                if terms:
                    out["policy_terms"] = terms
            except Exception:  # noqa: BLE001 — blame is evidence only
                pass
        if verdict == "denied":
            out["preemption"] = self._preempt_candidacy(
                gang, pgs, min_member
            )
        return out

    def _preempt_candidacy(self, gang: str, pgs, min_member: int) -> dict:
        """Would the vectorized preemption pass place this gang, and at
        whose expense — a DRY RUN of policy.preempt.PreemptionPlanner
        (no eviction, no counters beyond the planner's own)."""
        op = self.operation
        planner = op.preempt_planner
        if planner is None:
            return {
                "available": False,
                "reason": "policy preemption off (BST_POLICY without "
                          "'preempt')",
            }
        pod = pgs.pod if pgs is not None else None
        if pod is None:
            return {
                "available": False,
                "reason": "no representative pod observed yet",
            }
        if pod.spec.priority <= 0:
            return {
                "available": False,
                "reason": "tier-0 gangs never preempt (nothing is lower)",
            }
        try:
            need = max(
                min_member
                - pgs.pod_group.status.scheduled
                - len(pgs.matched_pod_nodes.items()),
                0,
            )
            plan = planner.plan(
                pod, op.cluster, op.status_cache, gang, need
            )
        except Exception as e:  # noqa: BLE001 — candidacy is evidence only
            return {"available": True, "error": f"{type(e).__name__}: {e}"}
        if plan is None:
            return {
                "available": True,
                "feasible": False,
                "reason": "no strictly-lower-tier victim set covers the "
                          "need",
            }
        return {
            "available": True,
            "feasible": True,
            "victim_gangs": list(plan.gangs),
            "evicted_pods": plan.evicted_pods,
            "pooled_after": plan.pooled_after,
        }

    # -- what-if ------------------------------------------------------------

    def whatif(self, cf: dict, rung: str = "steady") -> dict:
        from .oracle_scorer import read_cluster_inputs

        op = self.operation
        # version BEFORE the read (the _pack_current discipline): a
        # change landing mid-read leaves the cache keyed with the OLDER
        # version, so the next query at the new version rebuilds the
        # baseline instead of diffing fresh inputs against stale state.
        # The key also fingerprints the demands (baseline_inputs_key):
        # gang churn does not bump the version counter.
        version_fn = getattr(op.cluster, "version", None)
        version = version_fn() if callable(version_fn) else None
        nodes, node_req, demands = read_cluster_inputs(
            op.cluster, op.status_cache
        )
        return self.whatif_engine.query_on(
            nodes, node_req, demands, cf, rung=rung,
            baseline_key=baseline_inputs_key(version, nodes, demands),
        )


# ---------------------------------------------------------------------------
# process-wide registry (the /debug endpoints + CLI harness views)
# ---------------------------------------------------------------------------

_active: list = [None]


def set_active_observatory(obs: Optional[Observatory]) -> None:
    _active[0] = obs


def active_observatory() -> Optional[Observatory]:
    return _active[0]


def explain_debug_view(gang: Optional[str]) -> Tuple[dict, int]:
    """(payload, http status) for /debug/explain. A bare GET is
    self-describing (the /debug/profile precedent — the /debug/ index
    probe walks every endpoint parameterless)."""
    if not gang:
        return {
            "usage": "/debug/explain?gang=<namespace/name>",
            "serves": "structured denial breakdown for one gang "
                      "(docs/observability.md 'Explain')",
        }, 200
    obs = _active[0]
    if obs is None:
        return {
            "error": "no observatory in this process (explain serves the "
                     "oracle-mode scheduler; the sidecar has no gang "
                     "state)"
        }, 200
    try:
        return obs.explain(gang), 200
    except Exception as e:  # noqa: BLE001 — a debug surface never crashes
        return {"error": f"{type(e).__name__}: {e}"}, 500


def whatif_debug_view(params: Dict[str, str]) -> Tuple[dict, int]:
    """(payload, http status) for /debug/whatif. A bare GET answers the
    query grammar (200, self-describing); a malformed counterfactual
    answers 400."""
    if not any(
        params.get(k)
        for k in ("drain", "cordon", "add_nodes", "bump_gang",
                  "remove_gang")
    ):
        return {
            "usage": "?drain=NODE | ?cordon=NODE | ?add_nodes=N"
                     "[&node_cpu=..][&node_memory=..][&node_pods=..] | "
                     "?bump_gang=NS/NAME&tier=T | ?remove_gang=NS/NAME "
                     "[&rung=steady|wavefront|cpu-ladder|topk]",
            "kinds": list(COUNTERFACTUAL_KINDS),
            "serves": "placement diff of one counterfactual scored on a "
                      "forked device-state copy (docs/observability.md "
                      "'What-if')",
        }, 200
    obs = _active[0]
    if obs is None:
        return {
            "error": "no observatory in this process (what-if serves the "
                     "oracle-mode scheduler; the sidecar has no cluster "
                     "state)"
        }, 200
    rung = params.get("rung") or "steady"
    try:
        cf = parse_counterfactual(params)
        return obs.whatif(cf, rung=rung), 200
    except ValueError as e:
        return {"error": str(e), "kinds": list(COUNTERFACTUAL_KINDS)}, 400
    except Exception as e:  # noqa: BLE001 — a debug surface never crashes
        return {"error": f"{type(e).__name__}: {e}"}, 500
