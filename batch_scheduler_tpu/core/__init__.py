from .operation import (
    MAX_SCORE,
    ClusterStateProvider,
    PermitOutcome,
    ScheduleOperation,
)
from .oracle_scorer import OracleScorer, demand_from_status
from . import resources

__all__ = [
    "MAX_SCORE",
    "ClusterStateProvider",
    "PermitOutcome",
    "ScheduleOperation",
    "OracleScorer",
    "demand_from_status",
    "resources",
]
