"""OracleScorer: the TPU-backed batch scoring strategy.

Packs the live PodGroup status cache + cluster state into one
ClusterSnapshot, runs the fused ``schedule_batch`` oracle (one device
round-trip), and serves the per-group / per-node answers the scheduling
callbacks need from the cached numpy results.

This is the ``--scorer=tpu`` path of the north star: it subsumes the
reference's findMaxPG + compareClusterResourceAndRequire +
computeResourceSatisfied serial loops (reference pkg/scheduler/core/
core.go:514-632,701-739) with exact, stronger batch answers:

- gang feasibility is per-node-capacity based (fragmentation-aware), not a
  raw cluster resource sum;
- priority reservation comes from the greedy assignment scan processing
  groups in queue order, replacing the race-prone 0.7 reserve heuristic
  (reference core.go:161).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional

import jax
import numpy as np

from ..cache.pg_cache import PGStatusCache, PodGroupMatchStatus
from ..ops.oracle import execute_batch_host
from ..ops.snapshot import ClusterSnapshot, DeltaSnapshotPacker, GroupDemand
from ..utils.errors import StaleBatchError
from ..utils import trace as trace_mod

__all__ = [
    "OracleScorer",
    "demand_from_status",
    "conservative_cpu_batch",
    "read_cluster_inputs",
    "replay_batch",
    "replay_audit_record",
    "REPLAY_RUNGS",
]


def read_cluster_inputs(cluster, status_cache: PGStatusCache):
    """ONE consistent read of the oracle's cluster inputs: (nodes,
    node_requested, demands) — the exact projection every snapshot pack
    consumes. Shared by the refresh path (_pack_current) and the what-if
    observatory (core.explain), so a counterfactual scores the same
    inputs a real refresh would read."""
    statuses = status_cache.snapshot()
    demands: List[GroupDemand] = [
        demand_from_status(name, pgs)
        for name, pgs in sorted(statuses.items())
    ]
    nodes = cluster.list_nodes()
    node_req = {
        n.metadata.name: cluster.node_requested(n.metadata.name)
        for n in nodes
    }
    return nodes, node_req, demands


# ---------------------------------------------------------------------------
# deterministic replay (docs/observability.md "Audit log & replay")
# ---------------------------------------------------------------------------

# The rungs a recorded batch can be re-executed against:
#   steady    — exactly what this process would dispatch right now (its
#               live gates/env decide pallas/wavefront), on the default
#               backend: the same-backend bit-identity check.
#   wavefront — the wavefront assignment scan forced on (width 8 bucket),
#               pallas off: exercises the bit-identity-by-construction
#               claim of ops.oracle.assign_gangs_wavefront on real
#               recorded inputs.
#   cpu-ladder— the always-working fallback rung: serial lax.scan pinned
#               to a CPU device — what the in-production identity audit
#               (utils.health.IdentityAuditor) re-verifies against, and
#               the cross-backend divergence probe for TPU-recorded
#               audit logs.
#   topk      — the hierarchical top-K scan forced on (width-8 wave,
#               K=16 candidate bucket by default), pallas off: exercises
#               the demotion-backed bit-identity claim of
#               ops.oracle.assign_gangs_topk on real recorded inputs.
#
# The node-sharded mesh rungs (ops.oracle.assign_gangs_sharded and the
# sharded top-K variant) are deliberately NOT replay rungs: replays run
# single-process and a rung pin must never depend on mesh availability.
# Batches recorded on the sharded paths are instead verified by
# CROSS-rung identity — their audit records replay bit-identically on
# cpu-ladder (gated by benchmarks/replay_gate.py and make bench-xl),
# which is exactly the claim that matters: the sharded merges compute
# the same plan the serial scan would.
REPLAY_RUNGS = ("steady", "wavefront", "cpu-ladder", "topk")


def replay_batch(batch_args, progress_args, against: str = "steady",
                 scan_mesh=None, wave: int = 8, topk: int = 16,
                 policy=None):
    """Re-entry API for deterministic replay: re-execute one recorded
    oracle batch's EXACT packed inputs on the requested rung and return
    ``(host, device_result)`` like ``execute_batch_host``. The rung pin is
    thread-local (ops.oracle.forced_scan_rung), so replays — including the
    identity audit's daemon-thread re-verification — never change which
    rung concurrent serving batches run on, and a replay failure never
    permanently demotes a serving feature.

    ``policy`` is a recorded batch's ``(policy_cols, terms, weights)``
    payload: a policy batch ALWAYS re-executes the policy rung (the
    composite is part of its semantics — dispatch_batch demotes every
    other rung), so every ``against`` value degenerates to the policy
    scan on that rung's device placement. ``cpu-ladder`` therefore covers
    the policy rung's cross-backend identity (docs/policy.md)."""
    from ..ops.oracle import execute_batch_host, forced_scan_rung

    batch_args = tuple(np.asarray(a) for a in batch_args)
    progress_args = tuple(np.asarray(a) for a in progress_args)
    if policy is not None:
        cols, terms, weights = policy
        policy = (
            tuple(np.asarray(c) for c in cols), tuple(terms), tuple(weights),
        )
    if against == "steady":
        return execute_batch_host(batch_args, progress_args,
                                  scan_mesh=scan_mesh, policy=policy)
    if against == "wavefront":
        from ..ops.bucketing import wave_width_bucket

        with forced_scan_rung(False, wave_width_bucket(wave)):
            return execute_batch_host(batch_args, progress_args,
                                      scan_mesh=scan_mesh, policy=policy)
    if against == "cpu-ladder":
        cpu = jax.local_devices(backend="cpu")[0]
        with forced_scan_rung(False, 0), jax.default_device(cpu):
            return execute_batch_host(batch_args, progress_args,
                                      policy=policy)
    if against == "topk":
        from ..ops.bucketing import topk_bucket, wave_width_bucket

        with forced_scan_rung(
            False, wave_width_bucket(wave), topk_bucket(topk)
        ):
            return execute_batch_host(batch_args, progress_args,
                                      scan_mesh=scan_mesh, policy=policy)
    raise ValueError(
        f"unknown replay rung {against!r} (use one of {REPLAY_RUNGS})"
    )


def replay_audit_record(record: dict, against: str = "steady") -> dict:
    """Replay one reconstructed audit record (utils.audit.AuditReader) and
    bit-compare the resulting plan against the recorded digest. Returns a
    per-batch report; a divergence carries a structured blame dict —
    backend + config fingerprints on both sides, bucket shape, the
    fallback rung the replay actually ran, and the first differing plan
    field / gang / node (named when the record kept names).

    A record flagged ``degraded`` is SKIPPED, not replayed: the
    conservative fallback batch (conservative_cpu_batch) was a host-side
    answer with no device plan, so re-executing the real oracle against
    it would report a guaranteed — and meaningless — divergence (the
    identity auditor skips these for the same reason)."""
    from ..utils import audit as audit_mod

    if record.get("degraded"):
        return {
            "seq": record.get("seq"),
            "audit_id": record.get("audit_id"),
            "against": against,
            "identical": None,
            "skipped": "degraded conservative-fallback batch — no device "
                       "plan to re-execute",
        }
    refolded = record.get("record_kind") == "event_batch"
    host, _ = replay_batch(
        record["batch_args"], record["progress_args"], against=against,
        policy=record.get("policy_args"),
    )
    digest = audit_mod.plan_digest(host)
    identical = digest == record.get("plan_digest")
    exec_telemetry = host.get("telemetry") or {}
    out = {
        "seq": record.get("seq"),
        "audit_id": record.get("audit_id"),
        "against": against,
        "identical": identical,
        "recorded_digest": record.get("plan_digest"),
        "replayed_digest": digest,
        "shape": record.get("shape"),
        # the rung that actually EXECUTED — the dispatch ladder still
        # applies under a pin (a failing wavefront lowering falls back to
        # serial without flipping the process gates), and an "identical"
        # verdict for a rung that never ran would falsely validate it
        "executed_rung": {
            "used_pallas": exec_telemetry.get("used_pallas"),
            "wave_width": exec_telemetry.get("wave_width"),
            "scan_topk": exec_telemetry.get("scan_topk"),
            "scan_policy": exec_telemetry.get("scan_policy"),
        },
    }
    if against == "wavefront" and exec_telemetry.get("wave_width", 0) <= 1:
        out["rung_fell_back"] = True
    if against == "topk" and exec_telemetry.get("scan_topk", 0) <= 0:
        out["rung_fell_back"] = True
    if refolded:
        out["refolded"] = True
    if not identical:
        names = record.get("names") or {}
        telemetry = exec_telemetry
        shape = record.get("shape") or {}
        recorded_result = record["result_arrays"]
        if refolded:
            # event_batch records carry a compact result (assignment
            # arrays omitted — the digest still covers them): substitute
            # the replayed assignments so the field-by-field compare runs
            # over the fields the record actually kept
            recorded_result = dict(recorded_result)
            for k in ("assignment_nodes", "assignment_counts"):
                recorded_result.setdefault(k, host[k])
        blame = audit_mod.divergence_report(
            recorded_result,
            host,
            node_names=names.get("nodes"),
            group_names=names.get("groups"),
            context={
                "recorded_config": record.get("config"),
                "replay_config": audit_mod.config_fingerprint(),
                "bucket": [shape.get("g_bucket"), shape.get("n_bucket")],
                "fallback_rung": {
                    "used_pallas": telemetry.get("used_pallas"),
                    "wave_width": telemetry.get("wave_width"),
                },
            },
        )
        if refolded:
            refold = record.get("refold") or {}
            if blame is None:
                blame = {
                    "field": "<assignment>",
                    "reason": "digest mismatch confined to the assignment "
                              "arrays, which event_batch records omit — "
                              "re-execute against an array keyframe to "
                              "localize the slot",
                }
            # name the fold outcome and — when the re-folded input stream
            # itself diverged — the first differing event batch, so blame
            # points at the event, not just the downstream array field
            blame["fold"] = {
                "outcome": (
                    "refolded" if refold.get("input_digest_ok", True)
                    else "input-divergence"
                ),
                "refresh": record.get("refresh"),
            }
            if refold.get("first_divergent_event") is not None:
                blame["field"] = "<event-stream>"
                blame["first_divergent_event"] = (
                    refold["first_divergent_event"]
                )
        out["blame"] = blame or {
            "field": "<record>",
            "reason": "digest mismatch but every plan field matches — "
                      "the recorded digest (not the plan) is damaged",
        }
    return out


def conservative_cpu_batch(snap: ClusterSnapshot):
    """Degraded-mode batch: the conservative host-side answers a
    RemoteScorer serves while the sidecar is unreachable (breaker open /
    retries exhausted — docs/resilience.md).

    Semantics match the kube-scheduler rule that a scorer outage makes
    decisions conservative, never absent:

    - per-(group, node) member CAPACITY is computed exactly from the
      snapshot (lane-wise ``left // member_request`` under the fit mask),
      so Filter/Score keep answering with real numbers;
    - ``gang_feasible`` is exact INDEPENDENT feasibility (sum of per-node
      capacity >= remaining members): when it is False the gang provably
      cannot fit even alone, and PreFilter may deny it;
    - ``placed`` is all-False and no assignment exists: nothing is
      admitted speculatively through a whole-gang plan — members that do
      pass PreFilter go through the per-pod scan + Permit-quorum path,
      whose fit checks run against live cluster state.

    Returns the same ``(host, row_fetcher)`` pair as a real batch. Built
    lane-by-lane (R passes over a [G, N] array) so the degraded path
    never materialises the [G, N, R] broadcast cube.
    """
    left = np.maximum(
        snap.alloc.astype(np.int64) - snap.requested.astype(np.int64), 0
    )  # [N, R]
    group_req = snap.group_req.astype(np.int64)  # [G, R]
    g_count, n_count = group_req.shape[0], left.shape[0]
    cap = np.full((g_count, n_count), np.iinfo(np.int32).max, dtype=np.int64)
    for r in range(group_req.shape[1]):
        req_r = group_req[:, r]
        has = req_r > 0
        if not has.any():
            continue
        lane_cap = left[:, r][None, :] // np.maximum(req_r, 1)[:, None]
        cap = np.where(has[:, None], np.minimum(cap, lane_cap), cap)
    cap = np.where(snap.fit_mask, cap, 0)  # [1,N] broadcast or [G,N]
    cap = np.where(snap.node_valid[None, :], cap, 0)
    cap = np.clip(cap, 0, np.iinfo(np.int32).max).astype(np.int32)
    feasible = np.asarray(snap.group_valid) & (
        cap.sum(axis=1, dtype=np.int64) >= snap.remaining
    )
    host = {
        "gang_feasible": feasible,
        "placed": np.zeros(g_count, dtype=bool),
        "progress": np.zeros(g_count, dtype=np.int32),
        "best": 0,
        "best_exists": False,
        "assignment_nodes": np.zeros((g_count, 1), dtype=np.int32),
        "assignment_counts": np.zeros((g_count, 1), dtype=np.int32),
    }

    def row_fetcher(kind: str, g: int) -> np.ndarray:
        # capacity doubles as the score rank: more headroom, better seat
        return cap[g]

    return host, row_fetcher


def demand_from_status(full_name: str, pgs: PodGroupMatchStatus) -> GroupDemand:
    """Project a live PodGroupMatchStatus into the oracle's demand row.

    Policy columns (docs/policy.md) project from the representative pod's
    policy labels; the spread term additionally needs the gang's matched
    members per node (its domain occupancy) — read here so queue order,
    the priority term and the preemption planner all consume ONE field
    per concept. Pods without policy labels pay nothing: the extra work
    is guarded on label presence."""
    pg = pgs.pod_group
    member_req = dict(pg.spec.min_resources or {})
    if not member_req and pgs.pod is not None:
        member_req = pgs.pod.resource_require()
    affinity_hash = anti_hash = 0
    spread = False
    placed_nodes: Dict[str, int] = {}
    if pgs.pod is not None and pgs.pod.metadata.labels:
        from ..policy.terms import label_hash, parse_label_ref
        from ..utils.labels import (
            POLICY_AFFINITY_LABEL,
            POLICY_ANTI_AFFINITY_LABEL,
            POLICY_SPREAD_LABEL,
        )

        labels = pgs.pod.metadata.labels
        raw = labels.get(POLICY_AFFINITY_LABEL)
        if raw:
            k, v = parse_label_ref(raw)
            affinity_hash = label_hash(k, v) if k else 0
        raw = labels.get(POLICY_ANTI_AFFINITY_LABEL)
        if raw:
            k, v = parse_label_ref(raw)
            anti_hash = label_hash(k, v) if k else 0
        spread = bool(labels.get(POLICY_SPREAD_LABEL))
        if spread:
            for pair in pgs.matched_pod_nodes.items().values():
                placed_nodes[pair.node] = placed_nodes.get(pair.node, 0) + 1
    return GroupDemand(
        full_name=full_name,
        min_member=pg.spec.min_member,
        scheduled=pg.status.scheduled,
        matched=len(pgs.matched_pod_nodes.items()),
        priority=pgs.pod.spec.priority if pgs.pod is not None else 0,
        creation_ts=pg.metadata.creation_timestamp,
        member_request=member_req,
        node_selector=dict(pgs.pod.spec.node_selector) if pgs.pod else {},
        tolerations=list(pgs.pod.spec.tolerations) if pgs.pod else [],
        released=pgs.scheduled,
        has_pod=pgs.pod is not None,
        affinity_hash=affinity_hash,
        anti_hash=anti_hash,
        spread=spread,
        placed_nodes=placed_nodes,
    )


class _BatchState:
    """One immutable (snapshot, results) pair, swapped in atomically so
    concurrent readers never see a torn snapshot/result combination.

    ``result`` holds only the O(G) host vectors; the big (G,N) tensors stay
    behind ``row_fetcher`` (on device locally, or on the sidecar remotely)
    and individual group rows are fetched lazily (a row is KBs; the full
    tensor is ~100MB at 5k nodes and costs ~10x the batch time to pull over
    the host link)."""

    __slots__ = ("snapshot", "result", "max_group", "row_fetcher", "_rows", "_rows_lock")

    def __init__(
        self,
        snapshot: ClusterSnapshot,
        result: dict,
        max_group: str,
        row_fetcher,
    ):
        self.snapshot = snapshot
        self.result = result
        self.max_group = max_group
        self.row_fetcher = row_fetcher
        self._rows: Dict[tuple, np.ndarray] = {}  # guarded-by: _rows_lock
        self._rows_lock = threading.Lock()

    def row(self, kind: str, g: int) -> np.ndarray:
        """Fetch (and cache) one group's row of a (G,N) tensor."""
        key = (kind, g)
        with self._rows_lock:
            cached = self._rows.get(key)
        if cached is not None:
            return cached
        row = np.asarray(self.row_fetcher(kind, g))
        with self._rows_lock:
            self._rows[key] = row
        return row


class OracleScorer:
    """Caches one batch of oracle results; invalidated by ``mark_dirty``."""

    supports_background_refresh = True
    # In-process batches dispatch-ahead freely; RemoteScorer narrows this
    # to multi-lane transports (see service.client).
    supports_dispatch_ahead = True
    # True while the served batch came from a degraded (conservative
    # fallback) path — RemoteScorer flips it; the in-process scorer never
    # degrades. ScheduleOperation reads it to relax the deny-by-default
    # PreFilter rule to deny-only-provably-infeasible.
    degraded = False
    # Black-box flight data (utils.audit / docs/observability.md): class
    # defaults so subclasses constructed without audit wiring (RemoteScorer)
    # stay auditing-free until configure_audit is called on them.
    audit_log = None
    _identity = None

    def __init__(
        self,
        min_batch_interval: float = 0.0,
        scan_mesh=None,
        background_refresh: bool = False,
        dispatch_ahead: bool = False,
        compile_warmer: bool = False,
        audit_log=None,
        identity_audit_every: int = 0,
        policy_engine=None,
        device_state: Optional[bool] = None,
    ):
        # Dirty tracking is a GENERATION pair, not a bool: refresh() clears
        # staleness by recording the generation it observed BEFORE packing
        # its snapshot, so a mark_dirty landing while the batch is on the
        # device (routine once background_refresh runs batches concurrently
        # with scheduling cycles) advances the generation past the recorded
        # one and the batch stays stale — a plain `_dirty = False` at
        # completion would clobber that invalidation.
        self._dirty_gen = 1
        self._clean_gen = 0
        self._state: Optional[_BatchState] = None
        # Background refresh: a stale-but-servable batch (every queried group
        # known) re-batches on a daemon thread while callers keep reading the
        # old answers — the device round-trip leaves the scheduling cycle's
        # critical path. Staleness is bounded by one batch time, the same
        # class as min_batch_interval coalescing (denials are 20s-sticky
        # regardless). A missing group or a failed background batch still
        # forces the BLOCKING path so transport errors surface in a cycle
        # instead of decaying into an invisible all-deny.
        self.background_refresh = background_refresh
        self._bg_thread: Optional[threading.Thread] = None  # guarded-by: _bg_lock
        self._bg_lock = threading.Lock()
        self._bg_error: Optional[Exception] = None
        # Multi-chip layout: when set (parallel.global_mesh() on a >1-chip
        # deployment), batches shard the O(G*N*R) scoring over the mesh and
        # replicate the sequential gang scan's inputs (the measured layout
        # choice — ops.oracle.schedule_batch's scan_mesh, README scaling
        # note, benchmarks/sharding_scaling.py). None = single device.
        self.scan_mesh = scan_mesh
        self._refresh_lock = threading.Lock()
        self._cluster_version = None
        self.batches_run = 0
        # Gang-granular admission support: plan-covered cluster changes
        # (member assumes/binds the current batch already charged via its
        # gang placement) are *credited* rather than invalidating the batch,
        # so batches scale with gangs and cluster churn — not with pods.
        self._version_credits = 0  # guarded-by: _credits_lock
        self._credits_lock = threading.Lock()
        # Optional re-batch coalescing: when > 0, a dirty batch whose answers
        # can still be served (all queried groups known) is refreshed at most
        # once per interval. Denials are already 20s-sticky via the deny
        # cache (reference core.go:188), so bounded staleness here is well
        # inside existing semantics.
        self.min_batch_interval = min_batch_interval
        self._last_batch_t = 0.0
        # Persistent packed host buffers (ops.snapshot.DeltaSnapshotPacker):
        # low-churn refreshes rewrite only the node/group rows that changed
        # instead of re-walking every dict; subsumes the per-batch schema
        # reuse this class used to do inline (the packer enforces the same
        # covers/covers_names validity rules and full-repacks on schema
        # change). self._schema mirrors the packer's for compatibility.
        # An enabled policy engine (batch_scheduler_tpu.policy) rides the
        # packer so every snapshot carries packed policy columns and every
        # local batch runs the policy scan rung (docs/policy.md).
        self.policy_engine = policy_engine
        self._packer = DeltaSnapshotPacker(policy_engine=policy_engine)
        self._schema = None
        # Event-sourced refresh (ops.events, docs/pipelining.md
        # "Snapshot-lite & event ingest"): informer/bind/permit mutations
        # append entity NAMES to a bounded host event log (wired lazily
        # to the cluster's subscribe_events on first pack), and an
        # eligible refresh folds just the named entities into the
        # packer's persistent buffers (pack_fold) instead of re-reading
        # every node/group — steady-state refresh cost O(churn). The
        # wiring state below moves only under _refresh_lock (packs
        # serialize); the LOG REFERENCE itself is written once under that
        # lock and read WITHOUT it by producers (mark_dirty /
        # note_group_event run on scheduling threads and must never block
        # behind a refresh in flight) — benign: the EventLog is
        # internally locked, and a producer racing the wiring at worst
        # misses the log, which the version-bump accounting catches as a
        # skew (scan fallback), never a stale fold.
        self._event_log = None  # racy-read by design (see above)
        self._event_cluster_ref = None  # guarded-by: _refresh_lock
        self._event_cache_ref = None  # guarded-by: _refresh_lock
        # completeness baselines recorded at every pack: the cluster
        # version and status-cache mutation counter the NEXT fold must
        # reconcile against (None -> the fold cannot prove coverage)
        self._fold_version = None  # guarded-by: _refresh_lock
        self._fold_mut_base = None  # guarded-by: _refresh_lock
        # Device-resident cluster state (ops.device_state, docs/
        # pipelining.md "Device-resident state"): the packed [N,R]/[G,R]
        # buffers stay committed on device across batches and each pack's
        # churned rows apply as one jit'd scatter-update, so the refresh
        # path stops re-uploading a full snapshot per batch. BST_DEVICE_
        # STATE=0 (or device_state=False) restores the upload-per-batch
        # path. RemoteScorer nulls this out: its device lives behind the
        # sidecar, which keeps the mirror (wire deltas).
        if device_state is None:
            from ..ops.device_state import device_state_enabled

            device_state = device_state_enabled()
        self._device_state = None
        if device_state:
            from ..ops.device_state import DeviceStateHolder

            self._device_state = DeviceStateHolder(
                mesh=scan_mesh, label="scorer"
            )
        # Dispatch-ahead (docs/pipelining.md): after each published batch,
        # a daemon thread packs and dispatches the NEXT batch speculatively
        # so a later ensure_fresh can publish it without a blocking device
        # round-trip. The existing generation/version dirty-tracking
        # decides at consume time whether the speculative batch is
        # servable (nothing changed since it packed -> bit-identical to
        # the blocking refresh it replaces) or discarded (any mark_dirty
        # or uncredited version bump mid-flight).
        self.dispatch_ahead = dispatch_ahead
        self._spec_lock = threading.Lock()
        self._spec_thread: Optional[threading.Thread] = None  # guarded-by: _spec_lock
        # (snap, host, row_fetcher, gen, version, pack_s, batch_s) — the
        # banked speculative batch travels under the REFRESH lock (packed
        # and consumed inside it), not _spec_lock, which only serializes
        # thread lifecycle
        self._spec: Optional[tuple] = None  # guarded-by: _refresh_lock
        self._spec_error: Optional[Exception] = None
        self.spec_served = 0
        self.spec_discarded = 0
        # Compile-ahead bucket warmer (ops.bucketing.CompileWarmer):
        # precompiles the adjacent (G, N) bucket shapes around the live
        # working set on a daemon thread, so a bucket transition on the
        # serving path lands on a warm executable. Local batches only —
        # RemoteScorer batches compile on the sidecar (the server runs
        # its own warmer).
        self._warmer = None
        if compile_warmer:
            from ..ops.bucketing import maybe_compile_warmer

            self._warmer = maybe_compile_warmer(scan_mesh)
        # oracle-batch latency telemetry (SURVEY.md §5: schedule-cycle
        # latency is the headline metric; the reference has no equivalent
        # instrumentation, only klog verbosity)
        self.pack_seconds: list = []  # guarded-by: _stats_lock
        self.batch_seconds: list = []  # guarded-by: _stats_lock
        self._stats_lock = threading.Lock()
        # Capacity observatory (ops.capacity, docs/observability.md
        # "Capacity observatory"): a budget-gated analytics kernel run
        # against the published batch's committed inputs — per-lane
        # utilization/headroom spectra, fragmentation, stranded capacity,
        # tenant shares — feeding /debug/capacity, the Prometheus gauges,
        # and (via the audit ring) the offline `capacity` replay.
        self._capacity = None
        from ..ops.capacity import capacity_enabled, set_active_sampler

        if capacity_enabled():
            from ..ops.capacity import CapacitySampler

            self._capacity = CapacitySampler(label="scorer")
        # registered UNCONDITIONALLY (None when disabled): the newest
        # scorer owns the observatory, so a torn-down harness's ring can
        # never answer a later harness's /debug/capacity query or feed
        # its burn:capacity health verdict (the set_active_pending
        # pattern — a capacity-off scorer must CLEAR a predecessor's)
        set_active_sampler(self._capacity)
        self.configure_audit(audit_log, identity_audit_every)

    def configure_audit(self, audit_log=None,
                        identity_audit_every: int = 0) -> None:
        """Attach the black-box flight data layer: an ``utils.audit.AuditLog``
        recording every published batch (inputs + plan digest, off the hot
        path), and/or the sampled in-production identity audit — every Kth
        non-speculative batch re-verified bit-for-bit on the CPU fallback
        rung (utils.health.IdentityAuditor; a mismatch breaches /debug/health
        and flags the audit ring). Also how RemoteScorer instances get
        wired: the cmd layer constructs them before the config is known."""
        self.audit_log = audit_log
        if identity_audit_every and identity_audit_every > 0:
            from ..utils.health import IdentityAuditor

            self._identity = IdentityAuditor(identity_audit_every)
        else:
            self._identity = None

    def mark_dirty(self, group: Optional[str] = None) -> None:
        # GIL-level increment; a lost update between two racing markers
        # still leaves the generation ahead of _clean_gen, which is all
        # _stale needs
        self._dirty_gen += 1
        # event attribution (ops.events): a caller naming the gang whose
        # demand row changed keeps the next refresh fold-eligible; an
        # unattributed mark is a BLIND mark — the next refresh falls back
        # to the full scan, which is always correct. The unlocked read is
        # benign: the log reference only ever moves under _refresh_lock
        # and a mark racing the swap lands as a blind scan at worst.
        log = self._event_log
        if log is not None:
            if group:
                log.note_group(group)
            else:
                log.note_blind()

    def note_group_event(self, full_name: str) -> None:
        """Record that a gang's demand row (matched/scheduled progress)
        changed WITHOUT dirtying the batch — the plan-covered mutations
        the gang-granular credit path already accounts for. The pending
        event makes the next refresh (whenever something else triggers
        it) fold this gang's fresh state instead of scanning."""
        log = self._event_log
        if log is not None:
            log.note_group(full_name)

    def credit_expected_change(self, n: int = 1) -> None:
        """Record n cluster-version bumps as pre-accounted by the current
        batch (a planned gang member being assumed/bound): the batch stays
        fresh. Over- or under-crediting is safe — any mismatch makes
        ``_stale`` true, which only costs an extra re-batch."""
        with self._credits_lock:
            self._version_credits += n

    @property
    def snapshot(self) -> Optional[ClusterSnapshot]:
        state = self._state
        return state.snapshot if state is not None else None

    def refresh(self, cluster, status_cache: PGStatusCache) -> None:
        """Rebuild the snapshot and run one fused oracle batch."""
        with trace_mod.span("oracle.refresh", cat="oracle"):
            self._refresh_traced(cluster, status_cache)

    def _pack_current(self, cluster, status_cache: PGStatusCache):  # lock-held: _refresh_lock
        """Read cluster state and build one snapshot — the O(churn) event
        fold when the pending events prove complete coverage, else the
        full read + delta pack. Returns (snap, dirty_gen, version_base,
        pack_seconds).

        Credits, the dirty generation, and the version base are all taken
        BEFORE reading state: any change landing mid-pack leaves version()
        ahead of the base (or the generation ahead of the one recorded at
        completion) and re-batches conservatively. The mutation-counter
        baseline follows the same rule — a membership change landing
        mid-read skews the next fold's comparison and forces a scan."""
        t0 = time.perf_counter()
        dirty_gen = self._dirty_gen
        version_fn = getattr(cluster, "version", None)
        version_base = version_fn() if callable(version_fn) else None
        self._ensure_event_wiring(cluster, status_cache)
        log = self._event_log
        mut_base = status_cache.mutations() if log is not None else None
        snap = None
        if log is not None:
            snap = self._try_fold(
                cluster, status_cache, version_base, mut_base
            )
        if snap is None:
            nodes, node_req, demands = read_cluster_inputs(
                cluster, status_cache
            )
            with trace_mod.span("oracle.snapshot_pack", cat="oracle"):
                snap = self._packer.pack(nodes, node_req, demands)
        if log is not None:
            self._fold_version = version_base
            self._fold_mut_base = mut_base
        self._schema = self._packer.schema
        self._note_pack(snap)
        return snap, dirty_gen, version_base, time.perf_counter() - t0

    def _ensure_event_wiring(self, cluster, status_cache) -> None:  # lock-held: _refresh_lock
        """Lazily subscribe one EventLog to THIS (cluster, status_cache)
        pair. A provider without subscribe_events/version (FakeCluster,
        plain test providers) gets no log — every refresh scans, exactly
        the pre-event behaviour. Re-wiring on a provider change resets
        the completeness baselines: a fold must never reconcile version
        arithmetic across two different clusters."""
        if (
            self._event_log is not None
            and self._event_cluster_ref is not None
            and self._event_cluster_ref() is cluster
            and self._event_cache_ref is not None
            and self._event_cache_ref() is status_cache
        ):
            return
        self._event_log = None
        self._event_cluster_ref = None
        self._event_cache_ref = None
        self._fold_version = None
        self._fold_mut_base = None
        from ..ops.events import event_fold_enabled

        if not event_fold_enabled():
            return
        subscribe = getattr(cluster, "subscribe_events", None)
        version_fn = getattr(cluster, "version", None)
        if not callable(subscribe) or not callable(version_fn):
            return
        if not callable(getattr(status_cache, "mutations", None)):
            return
        from ..ops.events import EventLog

        log = EventLog(label="scorer")
        subscribe(log.note_bump)  # weakly held: dies with this scorer
        self._event_log = log
        self._event_cluster_ref = weakref.ref(cluster)
        self._event_cache_ref = weakref.ref(status_cache)

    def _try_fold(  # lock-held: _refresh_lock
        self, cluster, status_cache, version_base, mut_base
    ):
        """Attempt the O(churn) event-fold pack. The eligibility chain
        proves — never assumes — that the drained events cover EVERY
        oracle-visible change since the last pack:

        1. the batch is complete (no blind mark, no structural node
           mutation, no cap overflow);
        2. every cluster version bump since the last pack's base has a
           matching logged event (``version delta == drained bumps`` —
           a mutation that bypassed the hooks breaks the equality);
        3. the status cache's set/delete counter is unchanged (the gang
           SET cannot have churned without it);
        4. every named entity resolves against the packer's lite state
           (pack_fold re-checks and bails to None otherwise).

        Any failure returns None and the caller runs the full scan —
        correctness never depends on hook coverage. Outcomes are counted
        (bst_event_folds_total) so a fleet that silently stopped folding
        is visible."""
        from ..ops.events import event_fold_enabled

        batch = self._event_log.drain()
        snap = None
        if not event_fold_enabled():
            outcome = "disabled"
        elif self._fold_version is None or version_base is None:
            outcome = "no-base"
        elif not batch.complete:
            outcome = (
                "blind" if batch.blind
                else "structural" if batch.structural
                else "overflow"
            )
        elif version_base - self._fold_version != batch.bumps:
            outcome = "version-skew"
        elif mut_base is None or self._fold_mut_base != mut_base:
            outcome = "group-churn"
        else:
            node_updates = []
            group_updates = []
            unresolved = False
            for name in sorted(batch.node_names):
                node_updates.append((name, cluster.node_requested(name)))
            for full_name in sorted(batch.group_names):
                pgs = status_cache.get(full_name)
                if pgs is None:
                    unresolved = True
                    break
                group_updates.append(demand_from_status(full_name, pgs))
            if unresolved:
                outcome = "unknown-name"
            else:
                with trace_mod.span("oracle.event_fold", cat="oracle"):
                    snap = self._packer.pack_fold(
                        node_updates, group_updates
                    )
                outcome = "folded" if snap is not None else "packer-bail"
                if snap is not None:
                    from ..ops.snapshot import _demand_fp

                    # audit v2 (utils.audit): the exact drained,
                    # name-coalesced batch this pack consumed, stashed so
                    # the publish path can record an event_batch record
                    # the replayer re-folds. Node dicts are copied —
                    # cluster.node_requested returns live accounting
                    snap.event_fold = {
                        "bumps": int(batch.bumps),
                        "nodes": [
                            (name, dict(d)) for name, d in node_updates
                        ],
                        "groups": [
                            (g.full_name, _demand_fp(g))
                            for g in group_updates
                        ],
                    }
        from ..utils.metrics import DEFAULT_REGISTRY

        DEFAULT_REGISTRY.counter(
            "bst_event_folds_total",
            "Event-fold refresh attempts by outcome (folded = O(churn) "
            "pack served; every other outcome fell back to the full scan)",
        ).inc(outcome=outcome)
        return snap

    def _note_pack(self, snap) -> None:  # lock-held: _refresh_lock
        """Per-pack hook, under the refresh lock: bring the device-resident
        state up to this pack (EVERY pack, including dispatch-ahead packs
        whose batch is later discarded — the holder mirrors the PACKER's
        buffers, so generation contiguity survives a discarded batch).
        RemoteScorer overrides this to feed its wire-delta cursors."""
        if self._device_state is None:
            return
        with trace_mod.span("oracle.device_state_sync", cat="oracle"):
            snap.device_state_args = self._device_state.sync(snap)
            snap.device_state_policy_cols = (
                self._device_state.sync_policy_cols(snap)
            )

    def _refresh_traced(self, cluster, status_cache: PGStatusCache) -> None:
        snap, dirty_gen, version_base, pack_s = self._pack_current(
            cluster, status_cache
        )
        t1 = time.perf_counter()
        with trace_mod.span(
            "oracle.batch", cat="oracle",
            groups=len(snap.group_names), nodes=len(snap.node_names),
        ):
            host, row_fetcher = self._execute(snap)
        batch_s = time.perf_counter() - t1
        self._publish(
            snap, host, row_fetcher, dirty_gen, version_base, pack_s, batch_s
        )

    def _publish(
        self, snap, host, row_fetcher, dirty_gen, version_base,
        pack_s: float, batch_s: float, speculative: bool = False,
    ) -> None:
        """Install one executed batch as the served state — shared by the
        blocking refresh and the dispatch-ahead consume path."""
        max_group = (
            snap.group_names[int(host["best"])]
            if bool(host["best_exists"]) and int(host["best"]) < len(snap.group_names)
            else ""
        )
        # Degradedness is a property of the SERVED batch, applied only at
        # publication: a speculative batch that degraded (or recovered)
        # mid-flight must not change PreFilter semantics while the healthy
        # (or fallback) batch is still the one being served — and a
        # discarded speculative batch must not change them at all.
        degraded_marker = (
            host.pop("_degraded", None) if isinstance(host, dict) else None
        )
        # audit correlation id minted at dispatch time (RemoteScorer sends
        # it over the wire as the AUDIT_ID annotation so the sidecar's own
        # record correlates) — popped unconditionally so the served result
        # never carries transport-internal keys
        audit_id_marker = (
            host.pop("_audit_id", None) if isinstance(host, dict) else None
        )
        if degraded_marker is not None:
            self._set_degraded(bool(degraded_marker))
        self._state = _BatchState(snap, host, max_group, row_fetcher)
        self._cluster_version = version_base
        self._clean_gen = dirty_gen  # compare-and-clear: later marks survive
        self.batches_run += 1
        # Credits issued while this batch was packing/on-device offset the
        # OLD batch's staleness check and die with it: their version bumps
        # may or may not have made this snapshot (the assume could land
        # before or after the cluster read), so carrying them into the new
        # base could mark a snapshot that predates an assume as fresh — its
        # divergent plan would then serve until gang completion. The zero
        # comes AFTER the publication above: a credit landing mid-publish is
        # still an old-plan credit (on_assume matches plan_batch_seq against
        # batches_run) and must die; one landing after the zero can only be
        # against the new batch. Either race direction errs toward an extra
        # re-batch, never toward serving a divergent plan as fresh.
        with self._credits_lock:
            self._version_credits = 0
        self._last_batch_t = time.monotonic()
        with self._stats_lock:
            self.pack_seconds.append(pack_s)
            self.batch_seconds.append(batch_s)
            del self.pack_seconds[:-1000], self.batch_seconds[:-1000]
        from ..utils.metrics import DEFAULT_REGISTRY, LONG_OP_BUCKETS

        DEFAULT_REGISTRY.counter(
            "bst_oracle_batches_total", "Fused oracle batches executed"
        ).inc()
        # LONG_OP buckets: a cold batch includes the XLA compile (~20-40s
        # on the accelerator) — the default 10s ceiling would saturate
        DEFAULT_REGISTRY.histogram(
            "bst_oracle_batch_seconds",
            "Device time per fused oracle batch (compiles included)",
            buckets=LONG_OP_BUCKETS,
        ).observe(batch_s)
        DEFAULT_REGISTRY.histogram(
            "bst_oracle_pack_seconds", "Host snapshot-pack time per batch"
        ).observe(pack_s)
        # flight-recorder batch record: the device-side evidence (scan
        # path, wave stats, compile) later gang decisions rest on. The
        # telemetry dict is NESTED, never splatted: on the remote path it
        # arrives verbatim from the peer's TRACE_INFO JSON, and a
        # version-skewed sidecar's key colliding with record()'s own
        # parameters must not TypeError the refresh into a cycle error
        # (same contract as record_remote_spans: malformed peer data
        # never breaks the caller).
        telemetry = host.get("telemetry") if isinstance(host, dict) else None
        waves = (
            telemetry.get("waves_per_batch")
            if isinstance(telemetry, dict)
            else None
        )
        if (
            isinstance(waves, (int, float))
            and not isinstance(waves, bool)
            and waves > 0
            and "per_wave_device_seconds" not in telemetry
        ):
            # per-wave merge cost for the flight recorder: on the sharded
            # rung this is the summary all-gather + verify-reduce cadence
            # (the remote path computes the same field sidecar-side from
            # its own device clock and it arrives via TRACE_INFO)
            telemetry["per_wave_device_seconds"] = round(
                batch_s / waves, 6
            )
        if self._warmer is not None:
            try:
                # donate matches what _execute dispatched with, so the
                # warmer warms the SAME jit (donated and non-donated
                # variants keep separate caches)
                self._warmer.note_batch(
                    snap.device_args(), snap.progress_args(), telemetry or {},
                    donate=self._donate(),
                )
            except Exception:  # noqa: BLE001 — warm accounting never fatal
                pass
        trace_mod.DEFAULT_FLIGHT_RECORDER.record(
            "_batch",
            phase="batch",
            verdict="info",
            batch=self.batches_run,
            batch_ms=round(batch_s * 1000, 2),
            pack_ms=round(pack_s * 1000, 2),
            groups=len(snap.group_names),
            nodes=len(snap.node_names),
            degraded=bool(self.degraded),
            speculative=speculative,
            audit_id=audit_id_marker,
            telemetry=telemetry or {},
        )
        # one audit ID for the whole evidence chain: the audit record,
        # the identity audit, AND the capacity sample all correlate by it
        # (the offline `capacity` replay matches samples to records on it)
        aid = audit_id_marker
        if aid is None and (
            self.audit_log is not None
            or self._identity is not None
            or self._capacity is not None
        ):
            from ..utils import audit as audit_mod

            aid = audit_mod.new_audit_id()
        # lifecycle batch context (utils.lifecycle): every gang event the
        # scheduler notes until the NEXT publish stamps this audit id —
        # joining the gang's timeline to the audit/flight evidence chain —
        # and attributes the sidecar coalescer's queue wait (TRACE_INFO
        # lock_wait_seconds; absent when the client ran untraced) once
        # per (gang, batch)
        from ..utils.lifecycle import DEFAULT_LEDGER

        DEFAULT_LEDGER.note_batch_context(
            aid, telemetry if isinstance(telemetry, dict) else None
        )
        if self.audit_log is not None or self._identity is not None:
            self._audit_publish(snap, host, aid, speculative, telemetry)
        if self._capacity is not None:
            self._capacity_sample(snap, host, aid)

    @staticmethod
    def _snapshot_tenancy(snap) -> tuple:
        """One cached O(G) namespace pass per SNAPSHOT: ``(ns_counts,
        dominant_ns)``, shared by the dispatch path's dominant-tenant
        context and the audit record's tenant metadata — the hot paths
        must not each re-walk 2048 gang names per batch."""
        cached = getattr(snap, "_tenancy", None)
        if cached is not None:
            return cached
        from ..utils import tenancy

        ns_counts: Dict[str, int] = {}
        for name in snap.group_names:
            ns = tenancy.gang_namespace(name)
            if ns:
                ns_counts[ns] = ns_counts.get(ns, 0) + 1
        dominant = (
            min(ns_counts, key=lambda ns: (-ns_counts[ns], ns))
            if ns_counts
            else ""
        )
        snap._tenancy = (ns_counts, dominant)
        return snap._tenancy

    def dominant_tenant(self, snap) -> str:
        """The batch's dominant tenant LABEL (cardinality-capped through
        the process registry, utils.tenancy) — the one identity this
        batch carries everywhere: the local scan counter's tenant label,
        and (RemoteScorer) the TENANT wire annotation the sidecar's
        capacity/scan attribution and coalescer fairness key off. ""
        when the snapshot has no namespaced gangs."""
        from ..utils import tenancy

        _counts, dominant = self._snapshot_tenancy(snap)
        return tenancy.tenant_label(dominant) if dominant else ""

    def _capacity_sample(self, snap, host, audit_id) -> None:
        """Budget-gated capacity-observatory hook (ops.capacity): one
        analytics kernel over exactly the committed inputs this batch
        scored — the device-resident buffers when residency is live
        (single-device), so the big arrays never leave HBM. Evidence
        collection, never the decision path."""
        try:
            batch_args = None
            if self.scan_mesh is None:
                # mesh-sharded resident buffers would reshard under the
                # single-device analytics jit; the host arrays are the
                # bit-identical fallback there
                batch_args = getattr(snap, "device_state_args", None)
            if batch_args is None:
                batch_args = snap.device_args()
            progress = snap.progress_args()
            cols = snap.policy_cols
            self._capacity.note_batch(
                batch_args, host,
                group_names=snap.group_names,
                lane_names=list(snap.schema.names),
                scheduled=progress[1], matched=progress[2],
                policy_prio=cols[0] if cols is not None else None,
                audit_log=self.audit_log, audit_id=audit_id,
            )
        except Exception:  # noqa: BLE001 — analytics never fail publish
            pass

    def _audit_publish(
        self, snap, host, audit_id, speculative: bool, telemetry
    ) -> None:
        """Durable evidence for one PUBLISHED batch: the audit record (the
        exact padded inputs + plan digest, enqueued to the daemon writer)
        and the sampled identity audit. Evidence collection is never
        allowed to fail the decision path."""
        try:
            from ..utils import audit as audit_mod

            digest = audit_mod.plan_digest(host)
            aid = audit_id or audit_mod.new_audit_id()
            ctx = trace_mod.current_context()
            policy_payload = (
                snap.policy_payload()
                if hasattr(snap, "policy_payload")
                else None
            )
            if self.audit_log is not None:
                # cardinality-capped tenant attribution rides the record
                # metadata (the ROADMAP multi-tenant item's prep): gangs
                # per tenant label, derived from this batch's names
                from ..utils import tenancy

                # the snapshot's cached namespace counts, then one
                # registry hit per DISTINCT namespace — per-gang
                # tenant_label calls would take the process-wide
                # registry lock G times per audited batch
                ns_counts, _ = self._snapshot_tenancy(snap)
                tenants: Dict[str, int] = {}
                for ns, count in ns_counts.items():
                    label = tenancy.tenant_label(ns)
                    tenants[label] = tenants.get(label, 0) + count
                extra = {"tenants": tenants}
                # the event log itself rides the audit stream (the
                # keyframe+delta audit discipline applied to refreshes):
                # which rows this pack rewrote and which path produced it
                # — replay and the identity auditor keep bit-comparing
                # the recorded batch_args regardless of the path
                delta = getattr(snap, "delta", None)
                if delta is not None:
                    extra["refresh"] = {
                        "generation": int(delta.generation),
                        "kind": delta.kind,
                        "reason": delta.reason,
                        "source": delta.source,
                        "node_rows": [int(i) for i in delta.node_rows],
                        "group_rows": [int(i) for i in delta.group_rows],
                        "meta_rows": [int(i) for i in delta.meta_rows],
                    }
                # audit v2 payloads (no-ops under the array format): the
                # drained event batch a fold pack consumed, and the
                # snapshot-lite re-fold base a keyframe must carry
                lite_fps = getattr(snap, "lite_fps", None)
                self.audit_log.record_batch(
                    batch_args=snap.device_args(),
                    progress_args=snap.progress_args(),
                    result=host,
                    plan_digest=digest,
                    node_names=snap.node_names,
                    group_names=snap.group_names,
                    audit_id=aid,
                    trace_id=ctx[0] if ctx else None,
                    speculative=speculative,
                    degraded=bool(self.degraded),
                    telemetry=telemetry or {},
                    policy=policy_payload,
                    extra=extra,
                    event_fold=getattr(snap, "event_fold", None),
                    refold=(
                        (snap.schema, lite_fps)
                        if lite_fps is not None else None
                    ),
                )
            if (
                self._identity is not None
                and not speculative
                and not self.degraded
            ):
                # speculative batches are verified at publication anyway
                # (a served spec batch is bit-identical to the blocking
                # refresh by the consume-time generation check), and a
                # degraded conservative batch has no plan to verify
                self._identity.note_batch(
                    snap.device_args(), snap.progress_args(), digest,
                    aid, self.audit_log, policy=policy_payload,
                )
        except Exception:  # noqa: BLE001 — evidence, never the decision path
            pass

    def _donate(self) -> bool:
        """Donate the [N,R] input buffers to the batch (docs/pipelining.md):
        the scorer always dispatches from host numpy snapshots, so the
        donated buffer is fresh per batch; gated to the dispatch-ahead
        pipeline (where the warmer warms the matching donated signature)
        and to backends where donation buys anything. Always False while
        device-resident state is live: those dispatches run FROM the
        resident buffers, which donation would consume (the donation
        moved into the scatter-update; ops.device_state)."""
        from ..ops.oracle import donation_supported

        if self._device_state is not None:
            return False
        return self.dispatch_ahead and donation_supported()

    def _execute(self, snap: ClusterSnapshot):
        """Run one batch locally on the attached device. Returns the O(G)
        host result dict and a lazy (G,N)-row fetcher. RemoteScorer swaps
        this for the sidecar round-trip."""
        policy = snap.policy_payload()
        if policy is not None and self.policy_engine is not None:
            self.policy_engine.note_batch()
        # Device-resident path: dispatch from the resident buffers the
        # _note_pack sync produced for exactly this pack. donate=False is
        # load-bearing — a donated dispatch would consume the resident
        # state the next delta scatters into (the donation lives in the
        # scatter-update instead; ops.device_state module docstring).
        batch_args = getattr(snap, "device_state_args", None)
        donate = self._donate()
        if batch_args is None:
            batch_args = snap.device_args()
        else:
            donate = False
            if policy is not None:
                device_cols = getattr(
                    snap, "device_state_policy_cols", None
                )
                if device_cols is not None:
                    policy = (device_cols, policy[1], policy[2])
        # dominant-tenant context for the scan-path counter
        # (bst_scan_batches_total{tenant=...}): derived from this batch's
        # names, capped through the process registry (utils.tenancy) so
        # the label set stays bounded; cleared in the finally — the
        # dispatch-ahead thread must not leak its label into the next
        # foreground batch on a reused thread
        from ..utils import tenancy

        tenancy.set_batch_tenant(self.dominant_tenant(snap))
        try:
            host, device_result = execute_batch_host(
                batch_args, snap.progress_args(),
                scan_mesh=self.scan_mesh, donate=donate,
                policy=policy,
            )
        finally:
            tenancy.set_batch_tenant(None)

        def row_fetcher(kind: str, g: int) -> np.ndarray:
            return np.asarray(jax.device_get(device_result[kind][g]))

        return host, row_fetcher

    def _set_degraded(self, flag: bool) -> None:
        """Install the served batch's degradedness (see _publish).
        RemoteScorer mirrors the flip into its gauge/counter."""
        self.degraded = flag

    def _probe_due(self) -> bool:
        """Whether a degraded batch is worth re-attempting now (overridden
        by RemoteScorer to ask its client's breaker). Gating on the
        breaker keeps the degraded steady state cheap: while the cooldown
        runs, the fallback batch is served as an ordinary fresh batch."""
        return True

    def _stale(self, cluster) -> bool:
        if self._dirty_gen != self._clean_gen or self._state is None:
            return True
        if self.degraded and self._probe_due():
            # a conservative fallback batch auto-expires the moment the
            # transport is worth probing again, so recovery needs no
            # cluster change to trigger it
            return True
        version_fn = getattr(cluster, "version", None)
        if callable(version_fn):
            with self._credits_lock:
                credits = self._version_credits
            if version_fn() - credits != self._cluster_version:
                return True
        return False

    def _group_missing(self, group: Optional[str]) -> bool:
        return (
            group is not None
            and (
                self._state is None
                or self._state.snapshot.group_index(group) is None
            )
        )

    def ensure_fresh(
        self, cluster, status_cache: PGStatusCache, group: Optional[str] = None
    ) -> None:
        """Re-batch if dirty, the cluster changed, or ``group`` (a group the
        caller is about to query) is missing from the cached snapshot —
        newly created PodGroups must not be denied off a stale batch.

        With ``min_batch_interval`` > 0, a merely-stale batch (the queried
        group is known) is served as-is until the interval elapses, bounding
        re-batch rate under churn."""
        if not self._stale(cluster):
            if not self._group_missing(group):
                return
        elif not self._group_missing(group) and self._state is not None:
            if (
                self.min_batch_interval > 0
                and time.monotonic() - self._last_batch_t < self.min_batch_interval
            ):
                return
            if self.background_refresh and self._bg_error is None:
                self._kick_background_refresh(cluster, status_cache)
                return
        published = False
        with self._refresh_lock:
            if self._stale(cluster) or self._group_missing(group):
                # dispatch-ahead: a speculative batch packed from the
                # CURRENT cluster state replaces the blocking refresh
                # outright (taking the lock above also waited out an
                # in-flight speculative execution, so its device time
                # overlapped the caller's host work instead of this
                # cycle). A stale speculative batch is discarded and the
                # blocking path runs — bit-identical either way.
                if self._consume_speculative(cluster, group):
                    published = True
                else:
                    # a background/speculative failure is consumed here:
                    # this blocking refresh either succeeds (recovery) or
                    # raises into the caller's cycle (visible failure)
                    self._bg_error = None
                    self._spec_error = None
                    self.refresh(cluster, status_cache)
                    published = True
        if published and self.dispatch_ahead:
            self._kick_speculative(cluster, status_cache)

    def drain_background(self, timeout: float = 60.0) -> bool:
        """Wait out any in-flight background batch. MUST be called before
        process teardown when background_refresh is on: a daemon thread dying
        inside an XLA call while the runtime is being destroyed aborts the
        process. The flag flip and the thread read share _bg_lock with the
        kick path (which rechecks the flag under it), so no new thread can
        start after this returns.

        Returns True when no background batch remains in flight. The
        default timeout is sized to the known first-compile worst case
        (~20-40s on the accelerator); a False return means the join timed
        out and teardown would still race the XLA call — callers should
        treat it as "do not destroy the runtime yet" (ADVICE r3)."""
        with self._bg_lock:
            self.background_refresh = False  # no new kicks after drain
            t = self._bg_thread
        with self._spec_lock:
            self.dispatch_ahead = False  # no new speculative kicks either
            spec_t = self._spec_thread
        ok = True
        # the warmer stops FIRST: every warm precompile is a jit-cache
        # miss, and each miss spawns a bucket-cost-analysis telemetry
        # thread (ops.oracle) — stopping the producer before the
        # telemetry-thread join below is what makes that join final
        # (the --dispatch-ahead --compile-warmer exit-abort fix)
        if self._warmer is not None:
            ok = self._warmer.stop(timeout) and ok
        for name, th in (("background", t), ("dispatch-ahead", spec_t)):
            if th is not None and th.is_alive():
                th.join(timeout)
                if th.is_alive():
                    import sys

                    print(
                        f"drain_background: {name} batch still in flight "
                        f"after {timeout}s; teardown would race an XLA call",
                        file=sys.stderr,
                    )
                    ok = False
        if self._identity is not None:
            # the identity audit's re-verification is an XLA call on a
            # daemon thread — same teardown rule as the refresh threads
            ok = self._identity.drain(timeout) and ok
        # LAST, with every batch producer above quiesced: join the
        # telemetry daemon threads (bucket-cost analyses, coarse probes)
        # each compiled dispatch spawned — a daemon thread dying inside
        # an XLA compile at interpreter exit aborts the process
        from ..ops.oracle import drain_telemetry_threads

        ok = drain_telemetry_threads(timeout) and ok
        return ok

    # -- dispatch-ahead (docs/pipelining.md) --------------------------------

    def _consume_speculative(self, cluster, group: Optional[str]) -> bool:  # lock-held: _refresh_lock
        """Publish the speculative batch iff NOTHING changed since it was
        packed — the same generation + raw-version equality the staleness
        check uses, with no credit forgiveness (a credited bump means an
        assume the speculative snapshot may predate; serving its plan
        would risk divergence, so it is discarded). Caller holds
        ``_refresh_lock``. Returns True when the batch was published."""
        spec = self._spec
        if spec is None:
            return False
        self._spec = None  # consumed either way
        snap, host, row_fetcher, gen, version, pack_s, batch_s = spec
        version_fn = getattr(cluster, "version", None)
        current_version = version_fn() if callable(version_fn) else None
        from ..utils.metrics import DEFAULT_REGISTRY

        spec_counter = DEFAULT_REGISTRY.counter(
            "bst_oracle_spec_batches_total",
            "Dispatch-ahead speculative batches by outcome (served = "
            "published without a blocking device round-trip; discarded = "
            "invalidated by a mid-flight cluster change)",
        )
        if (
            gen != self._dirty_gen
            or current_version != version
            or (group is not None and snap.group_index(group) is None)
        ):
            self.spec_discarded += 1
            spec_counter.inc(outcome="discarded")
            return False
        self._publish(
            snap, host, row_fetcher, gen, version, pack_s, batch_s,
            speculative=True,
        )
        self.spec_served += 1
        spec_counter.inc(outcome="served")
        return True

    def _kick_speculative(self, cluster, status_cache: PGStatusCache) -> None:
        """Pack + execute the NEXT batch on a daemon thread so a later
        ensure_fresh can publish it without a blocking round-trip. At most
        one in flight; a failure parks the mode until the next successful
        blocking refresh (mirroring ``_bg_error``)."""
        with self._spec_lock:
            if not self.dispatch_ahead or self._spec_error is not None:
                return
            if self._spec_thread is not None and self._spec_thread.is_alive():
                return

            def _run() -> None:
                try:
                    with self._refresh_lock:
                        if self._spec is not None:
                            return  # an unconsumed batch is already banked
                        snap, gen, version, pack_s = self._pack_current(
                            cluster, status_cache
                        )
                        # invalidated while packing: consume would discard
                        # it anyway — skip the device round-trip (and the
                        # _refresh_lock hold) entirely. A change landing
                        # AFTER this check still discards at consume time;
                        # under sustained churn dispatch-ahead degrades to
                        # pack-and-discard, which is why it is opt-in and
                        # aimed at steady serving (docs/pipelining.md).
                        version_fn = getattr(cluster, "version", None)
                        if gen != self._dirty_gen or (
                            callable(version_fn) and version_fn() != version
                        ):
                            return
                        t1 = time.perf_counter()
                        with trace_mod.span(
                            "oracle.spec_batch", cat="oracle",
                            groups=len(snap.group_names),
                            nodes=len(snap.node_names),
                        ):
                            host, row_fetcher = self._execute(snap)
                        self._spec = (
                            snap, host, row_fetcher, gen, version, pack_s,
                            time.perf_counter() - t1,
                        )
                except Exception as e:  # noqa: BLE001 — surfaced via consume
                    self._spec_error = e

            self._spec_thread = threading.Thread(
                target=_run, name="oracle-dispatch-ahead", daemon=True
            )
            self._spec_thread.start()

    def _kick_background_refresh(self, cluster, status_cache: PGStatusCache) -> None:
        with self._bg_lock:
            # recheck under the lock: ensure_fresh's unlocked read can race
            # a concurrent drain_background, and spawning after the drain
            # would resurrect the teardown abort it exists to prevent
            if not self.background_refresh:
                return
            if self._bg_thread is not None and self._bg_thread.is_alive():
                return

            def _run() -> None:
                try:
                    with self._refresh_lock:
                        if self._stale(cluster):
                            self.refresh(cluster, status_cache)
                except Exception as e:  # noqa: BLE001 — surfaced via _bg_error
                    self._bg_error = e

            self._bg_thread = threading.Thread(
                target=_run, name="oracle-refresh", daemon=True
            )
            self._bg_thread.start()

    # -- query API (host-side, post-batch) ---------------------------------

    def stats(self) -> dict:
        """Batch-latency summary for the observability surface (the sim CLI
        prints it; the reference's only observability is CRD phase
        transitions + klog)."""
        with self._stats_lock:
            batches = list(self.batch_seconds)
            packs = list(self.pack_seconds)
        out = {"batches": self.batches_run}
        if batches:
            out["batch_p50_ms"] = round(float(np.median(batches)) * 1000, 2)
            out["batch_max_ms"] = round(float(max(batches)) * 1000, 2)
            out["pack_p50_ms"] = round(float(np.median(packs)) * 1000, 2)
        # delta-pack + pipelining evidence (docs/pipelining.md): how much
        # of the steady state rode the fast paths
        packer = self._packer
        if packer.delta_packs or packer.full_repacks:
            out["delta_packs"] = packer.delta_packs
            out["full_repacks"] = packer.full_repacks
            out["rows_rewritten_last"] = packer.last_rows_rewritten
        if packer.lite_packs or packer.fold_packs:
            out["lite_packs"] = packer.lite_packs
            out["fold_packs"] = packer.fold_packs
            out["order_resorts"] = packer.order_resorts
        if self._event_log is not None:
            out["event_log"] = self._event_log.stats()
        if self.dispatch_ahead or self.spec_served or self.spec_discarded:
            out["spec_served"] = self.spec_served
            out["spec_discarded"] = self.spec_discarded
        if self._device_state is not None:
            ds = self._device_state.stats()
            out["device_state_generation"] = ds["generation"]
            out["device_rows_scattered"] = ds["rows_scattered"]
            out["device_keyframes"] = ds["keyframes"]
            out["device_derived_batches"] = ds["derived_batches"]
        if self._warmer is not None:
            out.update(self._warmer.stats())
        if self.audit_log is not None:
            out.update(self.audit_log.stats())
        if self._identity is not None:
            out.update(self._identity.stats())
        return out

    def max_group(self) -> str:
        state = self._state
        return state.max_group if state is not None else ""

    def gang_feasible(self, full_name: str) -> bool:
        state = self._state
        g = state.snapshot.group_index(full_name) if state else None
        return bool(state.result["gang_feasible"][g]) if g is not None else False

    def placed(self, full_name: str) -> bool:
        state = self._state
        g = state.snapshot.group_index(full_name) if state else None
        return bool(state.result["placed"][g]) if g is not None else False

    def node_capacity(self, full_name: str, node_name: str) -> int:
        state = self._state
        if state is None:
            return 0
        g = state.snapshot.group_index(full_name)
        n = state.snapshot.node_index(node_name)
        if g is None or n is None:
            return 0
        try:
            return int(state.row("capacity", g)[n])
        except StaleBatchError:
            # the batch's rows no longer exist — raced by a newer batch,
            # or (remotely) lost with a re-established connection. Answer
            # conservatively NOW and invalidate, so the next ensure_fresh
            # re-batches: on a static cluster nothing else would, and the
            # rowless batch would serve capacity-0 denials forever (the
            # chaos-fuzz livelock). ONLY this error class is swallowed: a
            # dead transport turning into an invisible all-deny is
            # exactly the failure mode to avoid.
            self.mark_dirty()
            return 0

    def feasible_node_count(self, full_name: str) -> Optional[int]:
        """How many (real) nodes could hold at least one member of this
        gang, per the served batch's capacity row — the evidence count
        PreFilter denial records carry and /debug/explain re-derives
        (core.explain; both read capacity vs the batch-head leftover, so
        the two counts byte-match by construction). One lazy row fetch;
        None when the gang/batch is unknown or the row raced away."""
        state = self._state
        g = state.snapshot.group_index(full_name) if state else None
        if g is None:
            return None
        try:
            row = state.row("capacity", g)
        except StaleBatchError:
            self.mark_dirty()  # see node_capacity
            return None
        n_real = len(state.snapshot.node_names)
        return int((np.asarray(row)[:n_real] > 0).sum())

    def node_score(self, full_name: str, node_name: str) -> int:
        state = self._state
        if state is None:
            return -(2**30)
        g = state.snapshot.group_index(full_name)
        n = state.snapshot.node_index(node_name)
        if g is None or n is None:
            return -(2**30)
        try:
            return int(state.row("scores", g)[n])
        except StaleBatchError:
            self.mark_dirty()  # see node_capacity
            return -(2**30)

    def assignment(self, full_name: str) -> Dict[str, int]:
        """node name -> member count placed there for this gang's batch plan
        (from the compact top-K output; exact for gangs spanning <= K nodes)."""
        state = self._state
        g = state.snapshot.group_index(full_name) if state else None
        if g is None:
            return {}
        names = state.snapshot.node_names
        nodes_row = state.result["assignment_nodes"][g]
        counts_row = state.result["assignment_counts"][g]
        out: Dict[str, int] = {}
        for idx, count in zip(nodes_row, counts_row):
            if count <= 0:
                continue
            if idx < len(names):
                out[names[int(idx)]] = int(count)
        return out
