"""OracleScorer: the TPU-backed batch scoring strategy.

Packs the live PodGroup status cache + cluster state into one
ClusterSnapshot, runs the fused ``schedule_batch`` oracle (one device
round-trip), and serves the per-group / per-node answers the scheduling
callbacks need from the cached numpy results.

This is the ``--scorer=tpu`` path of the north star: it subsumes the
reference's findMaxPG + compareClusterResourceAndRequire +
computeResourceSatisfied serial loops (reference pkg/scheduler/core/
core.go:514-632,701-739) with exact, stronger batch answers:

- gang feasibility is per-node-capacity based (fragmentation-aware), not a
  raw cluster resource sum;
- priority reservation comes from the greedy assignment scan processing
  groups in queue order, replacing the race-prone 0.7 reserve heuristic
  (reference core.go:161).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import jax
import numpy as np

from ..cache.pg_cache import PGStatusCache, PodGroupMatchStatus
from ..ops.oracle import find_max_group, schedule_batch
from ..ops.snapshot import ClusterSnapshot, GroupDemand

__all__ = ["OracleScorer", "demand_from_status"]


def demand_from_status(full_name: str, pgs: PodGroupMatchStatus) -> GroupDemand:
    """Project a live PodGroupMatchStatus into the oracle's demand row."""
    pg = pgs.pod_group
    member_req = dict(pg.spec.min_resources or {})
    if not member_req and pgs.pod is not None:
        member_req = pgs.pod.resource_require()
    return GroupDemand(
        full_name=full_name,
        min_member=pg.spec.min_member,
        scheduled=pg.status.scheduled,
        matched=len(pgs.matched_pod_nodes.items()),
        priority=pgs.pod.spec.priority if pgs.pod is not None else 0,
        creation_ts=pg.metadata.creation_timestamp,
        member_request=member_req,
        node_selector=dict(pgs.pod.spec.node_selector) if pgs.pod else {},
        tolerations=list(pgs.pod.spec.tolerations) if pgs.pod else [],
        released=pgs.scheduled,
        has_pod=pgs.pod is not None,
    )


class _BatchState:
    """One immutable (snapshot, results) pair, swapped in atomically so
    concurrent readers never see a torn snapshot/result combination."""

    __slots__ = ("snapshot", "result", "max_group")

    def __init__(self, snapshot: ClusterSnapshot, result: dict, max_group: str):
        self.snapshot = snapshot
        self.result = result
        self.max_group = max_group


class OracleScorer:
    """Caches one batch of oracle results; invalidated by ``mark_dirty``."""

    def __init__(self):
        self._dirty = True
        self._state: Optional[_BatchState] = None
        self._refresh_lock = threading.Lock()
        self.batches_run = 0

    def mark_dirty(self) -> None:
        self._dirty = True

    @property
    def snapshot(self) -> Optional[ClusterSnapshot]:
        state = self._state
        return state.snapshot if state is not None else None

    def refresh(self, cluster, status_cache: PGStatusCache) -> None:
        """Rebuild the snapshot and run one fused oracle batch."""
        statuses = status_cache.snapshot()
        demands: List[GroupDemand] = [
            demand_from_status(name, pgs) for name, pgs in sorted(statuses.items())
        ]
        nodes = cluster.list_nodes()
        node_req = {
            n.metadata.name: cluster.node_requested(n.metadata.name) for n in nodes
        }
        snap = ClusterSnapshot(nodes, node_req, demands)
        out = schedule_batch(*snap.device_args())
        best, exists, progress = find_max_group(
            snap.min_member,
            snap.scheduled,
            snap.matched,
            snap.ineligible,
            snap.creation_rank,
        )
        host = jax.device_get(
            {
                "gang_feasible": out["gang_feasible"],
                "placed": out["placed"],
                "capacity": out["capacity"],
                "scores": out["scores"],
                "assignment": out["assignment"],
                "best": best,
                "best_exists": exists,
                "progress": progress,
            }
        )
        max_group = (
            snap.group_names[int(host["best"])]
            if bool(host["best_exists"]) and int(host["best"]) < len(snap.group_names)
            else ""
        )
        self._state = _BatchState(snap, host, max_group)
        self._dirty = False
        self.batches_run += 1

    def ensure_fresh(self, cluster, status_cache: PGStatusCache) -> None:
        if not self._dirty and self._state is not None:
            return
        with self._refresh_lock:
            if self._dirty or self._state is None:
                self.refresh(cluster, status_cache)

    # -- query API (host-side, post-batch) ---------------------------------

    def max_group(self) -> str:
        state = self._state
        return state.max_group if state is not None else ""

    def gang_feasible(self, full_name: str) -> bool:
        state = self._state
        g = state.snapshot.group_index(full_name) if state else None
        return bool(state.result["gang_feasible"][g]) if g is not None else False

    def placed(self, full_name: str) -> bool:
        state = self._state
        g = state.snapshot.group_index(full_name) if state else None
        return bool(state.result["placed"][g]) if g is not None else False

    def node_capacity(self, full_name: str, node_name: str) -> int:
        state = self._state
        if state is None:
            return 0
        g = state.snapshot.group_index(full_name)
        n = state.snapshot.node_index(node_name)
        if g is None or n is None:
            return 0
        return int(state.result["capacity"][g, n])

    def node_score(self, full_name: str, node_name: str) -> int:
        state = self._state
        if state is None:
            return -(2**30)
        g = state.snapshot.group_index(full_name)
        n = state.snapshot.node_index(node_name)
        if g is None or n is None:
            return -(2**30)
        return int(state.result["scores"][g, n])

    def assignment(self, full_name: str) -> Dict[str, int]:
        """node name -> member count placed there for this gang's batch plan."""
        state = self._state
        g = state.snapshot.group_index(full_name) if state else None
        if g is None:
            return {}
        row = state.result["assignment"][g]
        names = state.snapshot.node_names
        return {
            names[i]: int(row[i]) for i in np.nonzero(row[: len(names)])[0]
        }
