"""ScheduleOperation: all gang-scheduling semantics behind the framework's
extension points.

The behavioural equivalent of the reference's scheduling core
(reference pkg/scheduler/core/core.go:49-434): prefilter feasibility, per-node
fit, permit accounting, queue ordering, postbind status transitions,
preemption policy and the deny/permit fast-path caches — with the hot loops
swapped for the batched TPU oracle when ``scorer="oracle"`` (the
``--scorer=tpu`` gate of the north star; ``scorer="serial"`` is the
reference-parity host path).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from ..api.types import Pod, PodGroup, PodGroupPhase
from ..cache.pg_cache import PGStatusCache, PodGroupMatchStatus, PodNodePair
from ..utils import errors as errs
from ..utils.labels import get_wait_seconds, pod_group_name
from ..utils.metrics import DEFAULT_REGISTRY
from ..utils.patch import create_merge_patch
from ..utils.ttl_cache import TTLCache
from . import resources as rmath
from .oracle_scorer import OracleScorer

__all__ = [
    "ScheduleOperation",
    "PermitOutcome",
    "ClusterStateProvider",
    "MAX_SCORE",
    "deny_reserved_reason",
    "deny_infeasible_reason",
    "deny_degraded_reason",
]


# THE PreFilter denial blame strings — built here and ONLY here, shared
# by the denial raise sites below and by /debug/explain's re-derivation
# (core.explain), so the explanation and the recorded decision can never
# drift apart (the cross-stamp invariant tests/test_explain.py pins).


def deny_reserved_reason(full_name: str) -> str:
    """Feasible alone, but earlier gangs consume the space in this batch."""
    return f"{full_name}: cluster capacity reserved for earlier gangs"


def deny_infeasible_reason(full_name: str, min_member: int) -> str:
    """Provably cannot fit even alone (per-node-capacity feasibility)."""
    return f"{full_name}: cluster cannot fit gang ({min_member} members)"


def deny_degraded_reason(full_name: str, min_member: int) -> str:
    """The conservative fallback batch's only denial (docs/resilience.md)."""
    return (
        f"{full_name}: provably infeasible "
        f"({min_member} members; degraded oracle)"
    )

# Score stub ceiling (reference core.go:46).
MAX_SCORE = 2**31 - 1

# Deny/permit fast-path cache tuning (reference core.go:71-72,188,424).
DENY_TTL = 20.0
DENY_CACHE_DEFAULT_TTL = 30.0
DENY_CACHE_JANITOR = 3.0
PERMITTED_TTL = 2.0
PERMITTED_CACHE_DEFAULT_TTL = 3.0


class _RevStr:
    """String wrapper ordering REVERSE-lexicographically — the Compare
    chain's ``name1 > name2`` tiebreak (reference core.go:404) embedded in
    a sort-key tuple."""

    __slots__ = ("s",)

    def __init__(self, s: str):
        self.s = s

    def __lt__(self, other: "_RevStr") -> bool:
        return self.s > other.s

    def __eq__(self, other) -> bool:
        return isinstance(other, _RevStr) and self.s == other.s


class ClusterStateProvider(Protocol):
    """The slice of cluster state the scorers need (the reference reads this
    from the framework's SnapshotSharedLister, core.go:437,567)."""

    def list_nodes(self) -> list: ...

    def node_requested(self, node_name: str) -> Dict[str, int]: ...


@dataclass
class PermitOutcome:
    """Result triple of Permit (reference core.go:268-309 returns
    (ready, groupName, error))."""

    ready: bool
    pg_name: str
    error: Optional[Exception] = None


class ScheduleOperation:
    def __init__(
        self,
        status_cache: PGStatusCache,
        cluster: ClusterStateProvider,
        pg_client=None,
        max_schedule_seconds: Optional[float] = None,
        pg_lister: Optional[Callable[[str, str], Optional[PodGroup]]] = None,
        scorer: "str | OracleScorer" = "oracle",
        clock: Callable[[], float] = time.monotonic,
        min_batch_interval: float = 0.0,
        background_refresh: bool = False,
        dispatch_ahead: bool = False,
        compile_warmer: bool = False,
        audit_log=None,
        identity_audit_every: int = 0,
        policy=None,
    ):
        self.status_cache = status_cache
        self.cluster = cluster
        self.pg_client = pg_client
        self.max_schedule_seconds = max_schedule_seconds
        self.pg_lister = pg_lister
        # Policy engine (batch_scheduler_tpu.policy / docs/policy.md):
        # ``policy`` is a PolicyConfig, or None to read BST_POLICY from the
        # environment (empty = off: every path below runs the exact
        # pre-policy code). The engine scores batches through the local
        # oracle's policy scan rung; the preemption planner works with ANY
        # scorer transport (it runs its own local jit).
        from ..policy.engine import PolicyConfig, PolicyEngine
        from ..policy.preempt import PreemptionPlanner

        if policy is None:
            policy = PolicyConfig.from_env()
        self.policy = PolicyEngine(policy) if policy.enabled else None
        self.preempt_planner = (
            PreemptionPlanner(policy)
            if self.policy is not None and policy.preemption
            else None
        )
        if isinstance(scorer, str):
            if scorer not in ("oracle", "serial"):
                raise ValueError(
                    f"unknown scorer {scorer!r} (use 'oracle', 'serial', or an "
                    "OracleScorer-like instance, e.g. service.RemoteScorer)"
                )
            self.scorer_kind = scorer
            self.oracle = (
                OracleScorer(
                    min_batch_interval=min_batch_interval,
                    background_refresh=background_refresh,
                    dispatch_ahead=dispatch_ahead,
                    compile_warmer=compile_warmer,
                    audit_log=audit_log,
                    identity_audit_every=identity_audit_every,
                    policy_engine=self.policy,
                )
                if scorer == "oracle"
                else None
            )
        else:
            # a scorer instance (e.g. RemoteScorer backed by the sidecar);
            # apply requested batching behavior rather than silently
            # dropping it — but only when asked, so an instance configured
            # directly keeps its own settings. NOTE (ADVICE r3): when
            # min_batch_interval/background_refresh are passed here, the
            # caller-supplied instance IS mutated — do not share one scorer
            # across operations with conflicting batching settings.
            self.scorer_kind = "oracle"
            self.oracle = scorer
            if min_batch_interval:
                scorer.min_batch_interval = min_batch_interval
            if background_refresh:
                if getattr(scorer, "supports_background_refresh", True):
                    scorer.background_refresh = True
                else:
                    import warnings

                    warnings.warn(
                        "background_refresh requested but "
                        f"{type(scorer).__name__} does not support it "
                        "(single-connection transports would stall row "
                        "reads behind the background batch); running with "
                        "blocking refresh"
                    )
            if dispatch_ahead:
                if getattr(scorer, "supports_dispatch_ahead", True):
                    scorer.dispatch_ahead = True
                else:
                    import warnings

                    warnings.warn(
                        "dispatch_ahead requested but "
                        f"{type(scorer).__name__} does not support it "
                        "(a single-connection transport would stall row "
                        "reads behind the speculative batch; pass a "
                        "windowed client or a background_client); running "
                        "with blocking refresh"
                    )
            if audit_log is not None or identity_audit_every:
                # flight-data wiring for a caller-supplied instance
                # (RemoteScorer): audit records are recorded CLIENT-side
                # from the same padded snapshot the wire carried, and the
                # batch's AUDIT_ID annotation correlates the sidecar's own
                # record (service.protocol)
                scorer.configure_audit(audit_log, identity_audit_every)
            if self.policy is not None:
                # a remote sidecar is policy-UNAWARE (the policy scan runs
                # in-process only): stamp the client-side fingerprint so
                # the POLICY annotation rides the wire and a mismatched
                # peer is visible, never silent (docs/policy.md "Wire")
                scorer.policy_fingerprint = self.policy.config.fingerprint()[
                    "fingerprint"
                ]
        self.last_denied_pg = TTLCache(DENY_CACHE_DEFAULT_TTL, DENY_CACHE_JANITOR, clock=clock)
        self.last_permitted_pod = TTLCache(PERMITTED_CACHE_DEFAULT_TTL, DENY_CACHE_JANITOR, clock=clock)
        self._lock = threading.RLock()
        # sort_key's per-group creation-timestamp cache. A value is
        # immutable for a group's lifetime; entries die with the group via
        # the status-cache delete hook, so a recreated group under a
        # reused name re-reads its (new) creation stamp and the cache
        # stays bounded by the live group count.
        self._creation_cache: Dict[Tuple[str, str], float] = {}
        self._creation_tombstones: Dict[Tuple[str, str], float] = {}
        self._clock = clock
        status_cache.on_delete(self._forget_creation)
        # Cross-call max-progress group state used by the serial Filter path
        # (reference core.go:58-59,118-127).
        self.max_finished_pg: str = ""
        self.max_pg_status: Optional[PodGroupMatchStatus] = None
        # pending-gang aging (utils.health): per-operation so gangs from
        # a torn-down harness never age into a later harness's health
        # verdict; registered as the process's active tracker
        from ..utils.health import PendingGangTracker, set_active_pending

        self.pending_tracker = PendingGangTracker()
        set_active_pending(self.pending_tracker)
        # same isolation rule for the gang lifecycle ledger
        # (utils.lifecycle): a fresh operation starts a fresh story —
        # stale timelines from a torn-down harness must not feed this
        # run's TTP histograms, /debug/gangs, or the event stream
        from ..utils.lifecycle import DEFAULT_LEDGER

        DEFAULT_LEDGER.reset()
        # the explain/what-if observatory (core.explain): process-wide so
        # /debug/explain + /debug/whatif and the CLI harness views reach
        # the live operation without extra wiring. A non-oracle operation
        # registers None — a stale observatory answering from a torn-down
        # oracle harness would violate the same isolation the pending
        # tracker's re-registration above guarantees.
        from .explain import Observatory, set_active_observatory

        set_active_observatory(
            Observatory(self)
            if self.scorer_kind == "oracle" and self.oracle is not None
            else None
        )
        # same isolation rule for the capacity observatory (ops.capacity):
        # a non-oracle operation must CLEAR a predecessor scorer's sampler
        # or the dead harness's ring keeps answering /debug/capacity and
        # feeding the burn:capacity health signal (OracleScorer registers
        # its own — possibly None when BST_CAPACITY=0 — at construction)
        if self.scorer_kind != "oracle" or self.oracle is None:
            from ..ops.capacity import set_active_sampler

            set_active_sampler(None)

    # ------------------------------------------------------------------
    # scorer lifecycle
    # ------------------------------------------------------------------

    def mark_dirty(self, group: Optional[str] = None) -> None:
        """Invalidate the oracle batch (cluster or gang state changed).

        ``group`` attributes the invalidation to ONE gang's demand row so
        the scorer's event-fold refresh stays O(churn) (ops.events): the
        named row is re-read at the next pack instead of the whole
        cluster. ``None`` is a blind mark — the next refresh falls back
        to the full scan, which is always correct. Callers must pass a
        group ONLY when the gang row is the sole oracle-visible state
        they changed outside the evented cluster mutators."""
        if self.oracle is not None:
            self.oracle.mark_dirty(group)

    def _gang_event(self, full_name: str) -> None:
        """Note a gang-row change WITHOUT invalidating the batch — the
        plan-covered permit/bind paths pre-account their capacity
        (credit_expected_change), so the batch stays servable; but the
        next refresh, whenever something else triggers it, must re-read
        this gang's progress row rather than fold it as unchanged."""
        if self.oracle is not None:
            note = getattr(self.oracle, "note_group_event", None)
            if note is not None:
                note(full_name)

    def _oracle_fresh(self, group: Optional[str] = None) -> OracleScorer:
        self.oracle.ensure_fresh(self.cluster, self.status_cache, group)
        return self.oracle

    # ------------------------------------------------------------------
    # PreFilter (reference core.go:88-167)
    # ------------------------------------------------------------------

    def pre_filter(self, pod: Pod) -> None:
        """Raises a SchedulingError to reject the pod for this cycle."""
        pg_name, ok = pod_group_name(pod)
        if not ok:
            return  # non-group pods pass straight through (core.go:89-92)
        full_name = f"{pod.metadata.namespace}/{pg_name}"

        if self.last_permitted_pod.contains(pod.metadata.uid):
            return  # fast-pass: just permitted (core.go:95-98)

        pgs = self.status_cache.get(full_name)
        if pgs is None:
            raise errs.PodGroupNotFoundError(f"pod group not found: {full_name}")

        if self.last_denied_pg.contains(full_name):
            raise errs.DeniedError(
                f"pod group {full_name} denied recently, backing off"
            )

        self._fill_occupied(pgs, pod)

        if self.scorer_kind == "oracle":
            self._pre_filter_oracle(full_name, pgs)
        else:
            self._pre_filter_serial(full_name, pgs, pod)

    def _pre_filter_oracle(self, full_name: str, pgs: PodGroupMatchStatus) -> None:
        if pgs.scheduled:
            return  # gang already released; let its members through
        oracle = self._oracle_fresh(full_name)
        self.max_finished_pg = oracle.max_group()
        if oracle.placed(full_name):
            self._stamp_plan(full_name, pgs, oracle)
            return
        if getattr(oracle, "degraded", False):
            # conservative fallback (sidecar unreachable, serving the
            # local-CPU batch): no placement plan exists, so nothing is
            # admitted speculatively — but the deny-by-default rule above
            # would starve every gang for the outage's duration. Instead,
            # deny ONLY the provably infeasible (independent feasibility
            # is exact in the fallback batch); everything else proceeds
            # through the per-pod scan + Permit-quorum path, whose fit
            # checks run against live cluster state (docs/resilience.md).
            feasible = oracle.gang_feasible(full_name)
            DEFAULT_REGISTRY.counter(
                "bst_oracle_fallback_decisions_total",
                "PreFilter decisions made on the conservative CPU fallback",
            ).inc(decision="pass" if feasible else "deny")
            if feasible:
                return
            self.add_to_deny_cache(full_name)
            reason = deny_degraded_reason(
                full_name, pgs.pod_group.spec.min_member
            )
            self._record_denial(full_name, reason, oracle)
            raise errs.ResourceNotEnoughError(reason)
        self.add_to_deny_cache(full_name)
        if oracle.gang_feasible(full_name):
            # Feasible alone, but higher-priority gangs consume the space in
            # this batch — the exact form of the reference's 0.7 reserve
            # heuristic (core.go:157-165).
            reason = deny_reserved_reason(full_name)
            self._record_denial(full_name, reason, oracle)
            raise errs.ResourceNotEnoughError(reason)
        reason = deny_infeasible_reason(
            full_name, pgs.pod_group.spec.min_member
        )
        self._record_denial(full_name, reason, oracle)
        raise errs.ResourceNotEnoughError(reason)

    def _record_denial(
        self, full_name: str, reason: str, oracle: OracleScorer
    ) -> None:
        """One pre_filter flight record per oracle denial: the blame
        string PLUS the capacity-row feasible-node count — the evidence
        /debug/explain cross-stamps against (core.explain). Evidence
        only, never the decision path; the deny-cache fast path does NOT
        re-record, so the original blame stays the gang's last
        pre_filter record through the 20s backoff."""
        try:
            from ..utils.trace import DEFAULT_FLIGHT_RECORDER

            fields = {"batch": oracle.batches_run}
            count = oracle.feasible_node_count(full_name)
            if count is not None:
                fields["feasible_nodes"] = count
            DEFAULT_FLIGHT_RECORDER.record(
                full_name,
                phase="pre_filter",
                verdict="denied",
                reason=reason,
                coalesce=True,  # one record per distinct blame, not per retry
                **fields,
            )
        except Exception:  # noqa: BLE001 — evidence, never the decision
            pass

    def _pre_filter_serial(
        self, full_name: str, pgs: PodGroupMatchStatus, pod: Pod
    ) -> None:
        statuses = self.status_cache.snapshot()
        max_name, max_status, _ = rmath.find_max_group_serial(statuses)
        self.max_finished_pg = max_name
        self.max_pg_status = max_status
        if not max_name or max_status is None or max_status.pod_group is None:
            return

        nodes = self.cluster.list_nodes()
        node_req = {
            n.metadata.name: self.cluster.node_requested(n.metadata.name)
            for n in nodes
        }

        matched = len(max_status.matched_pod_nodes.items())
        if matched == 0:
            # First gang in flight becomes the max group (core.go:136-147).
            max_status = pgs
            prealloc = rmath.pre_allocated_resource(max_status, matched)
            if not rmath.cluster_satisfies(
                nodes, node_req, max_status.pod, prealloc, (1, 1)
            ):
                self.add_to_deny_cache(full_name)
                raise errs.ResourceNotEnoughError("cluster resource not enough")
            return

        if self.max_finished_pg == full_name:
            return  # the max-progress gang itself always passes (core.go:150-155)

        prealloc = rmath.pre_allocated_resource(max_status, matched)
        prealloc = rmath.add_resources(prealloc, pod.resource_require())
        if not rmath.cluster_satisfies(
            nodes, node_req, max_status.pod, prealloc, (7, 10)
        ):
            self.add_to_deny_cache(full_name)
            raise errs.ResourceNotEnoughError("cluster resource not enough")

    # ------------------------------------------------------------------
    # Gang-granular admission (no reference equivalent: the reference
    # re-runs its serial accounting per pod, core.go:268-309; here the
    # batch's whole-gang placement becomes a per-gang plan that member
    # pods ride without re-batching)
    # ------------------------------------------------------------------

    @staticmethod
    def _matched_per_node(pgs: PodGroupMatchStatus) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for pair in pgs.matched_pod_nodes.items().values():
            counts[pair.node] = counts.get(pair.node, 0) + 1
        return counts

    def _stamp_plan(
        self, full_name: str, pgs: PodGroupMatchStatus, oracle: OracleScorer
    ) -> None:
        """Stamp (or refresh) the gang's placement plan from the current
        batch. Idempotent per batch: the plan covers the members that were
        *remaining* when the batch ran, and the matched-per-node base lets
        slot consumption be derived from live matched counts."""
        seq = oracle.batches_run
        if pgs.plan_batch_seq == seq:
            return
        pgs.plan_base_matched = self._matched_per_node(pgs)
        pgs.placement_plan = oracle.assignment(full_name)
        pgs.plan_batch_seq = seq
        if self.policy is not None and pgs.placement_plan:
            # per-term score contributions at the chosen seats — the
            # flight recorder's policy blame (docs/policy.md): why THESE
            # nodes, in the terms' own units. Evidence only, never the
            # decision path.
            try:
                snap = oracle.snapshot
                if snap is not None and snap.policy_cols is not None:
                    g = snap.group_index(full_name)
                    idx = [
                        snap.node_index(n)
                        for n in pgs.placement_plan
                        if snap.node_index(n) is not None
                    ]
                    terms = self.policy.explain(snap.policy_cols, g, idx)
                    if terms:
                        from ..utils.trace import DEFAULT_FLIGHT_RECORDER

                        DEFAULT_FLIGHT_RECORDER.record(
                            full_name,
                            phase="policy",
                            verdict="info",
                            batch=seq,
                            nodes=len(idx),
                            terms=terms,
                        )
            except Exception:  # noqa: BLE001 — blame is evidence only
                pass

    def suggested_node(self, pod: Pod) -> Optional[str]:
        """The plan's next open slot for this pod's gang, or None (caller
        falls back to the full node scan). Served entirely host-side from
        the stamped plan — no oracle query, no re-batch."""
        if self.scorer_kind != "oracle":
            return None
        pg_name, ok = pod_group_name(pod)
        if not ok:
            return None
        pgs = self.status_cache.get(f"{pod.metadata.namespace}/{pg_name}")
        if pgs is None or not pgs.placement_plan:
            return None
        current = self._matched_per_node(pgs)
        base = pgs.plan_base_matched
        for node, planned in pgs.placement_plan.items():
            if planned > current.get(node, 0) - base.get(node, 0):
                return node
        return None

    def gang_plan(self, pod: Pod):
        """Whole-gang fast-lane eligibility (gang-granular release+bind;
        reference precedent for whole-gang choreography is
        StartBatchSchedule releasing a complete gang in one sweep,
        batchscheduler.go:254-344 — here admission, permit and bind are
        gang-granular too).

        Returns ``(slots, needed)`` — the current batch's placement plan
        ``{node: member_count}`` and the member quorum — when this pod's
        gang can be admitted as ONE transaction: oracle mode, a plan
        stamped by the live batch, and a completely fresh gang (nothing
        matched or waiting, nothing scheduled, not released). Anything
        else returns None and the caller takes the per-pod path."""
        if self.scorer_kind != "oracle" or self.oracle is None:
            return None
        pg_name, ok = pod_group_name(pod)
        if not ok:
            return None
        full_name = f"{pod.metadata.namespace}/{pg_name}"
        pgs = self.status_cache.get(full_name)
        if (
            pgs is None
            or pgs.scheduled
            or not pgs.placement_plan
            or pgs.plan_batch_seq != self.oracle.batches_run
            or pgs.pod_group.status.scheduled
            or pgs.matched_pod_nodes.items()
        ):
            return None
        needed = pgs.pod_group.spec.min_member
        if sum(pgs.placement_plan.values()) < needed:
            return None
        return dict(pgs.placement_plan), needed

    def permit_gang(self, full_name: str, members) -> bool:
        """Bulk Permit for a whole-gang transaction: one phase transition
        and one released-flag flip instead of per-member TTL bookkeeping.
        No waiting-pod entries are created — the caller binds
        synchronously, so the gang never parks and the TTL-eviction abort
        path has nothing to guard (the reference accumulates waiting pods
        only because its binds are asynchronous, core.go:268-309).

        ``members`` are (pod, node_name) pairs the caller already assumed.
        May raise OccupiedError (owner-reference fencing, per member like
        the per-pod path); returns False when the gang vanished mid-flight.
        Either way the caller rolls back its assumes."""
        pgs = self.status_cache.get(full_name)
        if pgs is None:
            return False
        for pod, _ in members:
            self._fill_occupied(pgs, pod)
        # under the operation lock like post_bind: the phase flip must not
        # race a controller worker swapping pgs.pod_group.status
        with self._lock:
            pg = pgs.pod_group
            if pg.status.phase == PodGroupPhase.PENDING:
                pg.status.phase = PodGroupPhase.PRE_SCHEDULING
            pgs.scheduled = True
        # every one of these assumes is capacity the batch pre-accounted
        # through the gang's plan (the bulk form of on_assume's credit)
        if self.oracle is not None:
            self.oracle.credit_expected_change(len(members))
        # the gang's progress row (phase, released flag) moved outside the
        # evented cluster mutators — note it for the next event fold
        self._gang_event(full_name)
        self.pending_tracker.note_placed(full_name)
        from ..utils.lifecycle import DEFAULT_LEDGER

        DEFAULT_LEDGER.note_permit(full_name)
        return True

    def post_bind_gang(self, full_name: str, bound: int) -> None:
        """One status transition for ``bound`` members bound as a unit:
        the per-gang equivalent of ``bound`` post_bind calls (reference
        PostBind runs per pod, core.go:312-362; at 10k pods the per-pod
        form was the single largest control-plane cost). Thin wrapper
        over :meth:`post_bind_gangs` so the transition state machine
        exists exactly once — including its commit-local-first patch
        semantics (the binds are already durable; the controller
        reconciles any missed patch from live member pods)."""
        self.post_bind_gangs([(full_name, bound)])

    def post_bind_gangs(self, items) -> None:
        """Flush form of :meth:`post_bind_gang` for a batch of gangs bound
        together (the scheduler's cross-gang commit buffer): ONE lock
        pass, ONE bulk status patch per namespace, ONE batch invalidation
        — instead of a lock + patch + re-batch per gang. ``items``:
        (full_name, bound_count) pairs.

        Unlike the per-gang form (which leaves local state unadvanced when
        its patch fails, so the next bind retries the transition), the
        flush commits local state first and patches best-effort: the binds
        are already durable at this point, and the controller re-derives
        any missed phase from live member pods (reference
        controller.go:201-222 crash recovery)."""
        patches_by_ns: Dict[str, list] = {}
        completed_any = False
        with self._lock:
            for full_name, bound in items:
                if bound <= 0:
                    continue
                pgs = self.status_cache.get(full_name)
                if pgs is None:
                    continue
                pg = pgs.pod_group
                pgs.binds_committed += bound
                new_scheduled = max(pg.status.scheduled, pgs.binds_committed)
                completed = new_scheduled >= pg.spec.min_member
                new_phase = (
                    PodGroupPhase.SCHEDULED
                    if completed
                    else PodGroupPhase.SCHEDULING
                )
                new_start = pg.status.schedule_start_time or time.time()
                # patch on scheduled-count advance too, not just phase
                # change: two partial flushes both landing in SCHEDULING
                # must still move the API server's count, or a crash in
                # that window loses more progress than the per-pod path
                # (whose bound-but-Pending members the controller cannot
                # see until kubelets start them)
                if self.pg_client is not None and (
                    new_phase != pg.status.phase
                    or new_scheduled > pg.status.scheduled
                ):
                    patches_by_ns.setdefault(
                        pg.metadata.namespace, []
                    ).append(
                        (
                            pg.metadata.name,
                            {
                                "status": {
                                    "phase": new_phase.value,
                                    "scheduled": new_scheduled,
                                    "schedule_start_time": new_start,
                                }
                            },
                        )
                    )
                pg.status.phase = new_phase
                pg.status.schedule_start_time = new_start
                pg.status.scheduled = new_scheduled
                pgs.placement_plan = None
                completed_any = completed_any or completed
        for ns, patches in patches_by_ns.items():
            try:
                self.pg_client.podgroups(ns).patch_many(patches)
            except Exception:
                pass  # controller reconciliation recovers the phase
        # every touched gang's progress row moved (binds_committed /
        # scheduled / phase / dropped plan) — name them all so the next
        # event fold re-reads exactly these rows, then invalidate once
        # per flush (not per gang) when any gang completed
        touched = [full_name for full_name, bound in items if bound > 0]
        for full_name in touched:
            self._gang_event(full_name)
        if completed_any:
            self.mark_dirty(group=touched[0] if touched else None)

    def on_assume(
        self, pod: Pod, node_name: str, from_plan: bool = False
    ) -> None:
        """Called after the framework assumes a pod onto a node. A gang
        member SEATED THROUGH the plan (``from_plan``, the scheduler's O(1)
        hint path) whose plan was stamped by the CURRENT batch is exactly
        the capacity charge that batch already accounted — credit the
        version bump instead of invalidating. Everything else — non-gang
        pods, planless gangs, scan fallbacks (even onto a planned node:
        the slot bookkeeping may not match), and placements against a
        superseded batch's plan — dirties the batch, since its per-node
        rows now diverge from reality (ADVICE r2)."""
        pg_name, ok = pod_group_name(pod)
        full_name = f"{pod.metadata.namespace}/{pg_name}" if ok else None
        if (
            self.scorer_kind == "oracle"
            and self.oracle is not None
            and from_plan
            and ok
        ):
            pgs = self.status_cache.get(full_name)
            if (
                pgs is not None
                and pgs.placement_plan is not None
                and node_name in pgs.placement_plan
                and pgs.plan_batch_seq == self.oracle.batches_run
            ):
                self.oracle.credit_expected_change(1)
                return
        # the node-row change itself is already evented by the cluster
        # mutator (ClusterState.assume); a known gang name keeps the
        # conservative invalidation attributed so the next refresh can
        # still fold instead of scanning. Non-gang pods stay blind.
        self.mark_dirty(group=full_name)

    # ------------------------------------------------------------------
    # Filter (reference core.go:170-191,514-564)
    # ------------------------------------------------------------------

    def filter(self, pod: Pod, node_name: str) -> None:
        pg_name, ok = pod_group_name(pod)
        if not ok:
            return
        full_name = f"{pod.metadata.namespace}/{pg_name}"
        pgs = self.status_cache.get(full_name)
        if pgs is None:
            raise errs.PodGroupNotFoundError(f"pod group not found: {full_name}")
        try:
            if self.scorer_kind == "oracle":
                self._filter_oracle(full_name, pgs, pod, node_name)
            else:
                self._filter_serial(full_name, pgs, pod, node_name)
        except errs.SchedulingError:
            self.add_to_deny_cache(full_name)
            raise
        self.last_permitted_pod.set(pod.metadata.uid, "", PERMITTED_TTL)

    def _filter_oracle(
        self, full_name: str, pgs: PodGroupMatchStatus, pod: Pod, node_name: str
    ) -> None:
        oracle = self._oracle_fresh(full_name)
        if oracle.node_capacity(full_name, node_name) > 0:
            return
        raise errs.ResourceNotEnoughError(
            f"{full_name}: node {node_name} cannot fit a member"
        )

    def _filter_serial(
        self, full_name: str, pgs: PodGroupMatchStatus, pod: Pod, node_name: str
    ) -> None:
        # case1: the max-progress group itself always passes (core.go:531-535)
        if self.max_finished_pg == full_name:
            return
        max_status = self.max_pg_status
        if max_status is None or not max_status.pod_group.spec.min_resources:
            return  # nothing to reserve against (core.go:542-544)
        max_single = dict(max_status.pod_group.spec.min_resources)

        node = next(
            (
                n
                for n in self.cluster.list_nodes()
                if n.metadata.name == node_name
            ),
            None,
        )
        if node is None:
            raise errs.SchedulingError("node snapshot not initialized")
        left = rmath.single_node_left(
            node, self.cluster.node_requested(node_name), None, (1, 1)
        )

        # case2: node fits this pod plus one member of the max group
        combined = rmath.add_resources(pod.resource_require(), max_single)
        if rmath.resource_satisfied(left, combined):
            return
        # case3: node can't host the max group's member anyway — don't hold
        # this node hostage for it (core.go:557-561)
        if not rmath.resource_satisfied(left, max_single):
            return
        raise errs.ResourceNotEnoughError(
            f"node {node_name} reserved for max group {self.max_finished_pg}"
        )

    # ------------------------------------------------------------------
    # Preemption (reference core.go:194-260)
    # ------------------------------------------------------------------

    def preempt_add_pod(self, pod_to_add: Pod, node_name: str) -> None:
        return None

    def preempt_victim_plan(self, pod: Pod):
        """Dry-run a vectorized victim plan for a denied gang pod
        (policy.preempt, docs/policy.md "Preemption pass"): the tier-
        eligible victim gangs whose whole-gang eviction frees enough
        capacity, minimal-by-construction. Returns a VictimPlan or None
        (policy preemption off / pod not a gang member / nothing
        evictable / infeasible even with full eviction). The commit half
        lives in the framework (Scheduler._evict_gang_plan) behind a live
        host-side re-verification."""
        if self.preempt_planner is None:
            return None
        pg_name, ok = pod_group_name(pod)
        if not ok or pod.spec.priority <= 0:
            return None  # tier-0 gangs never preempt (nothing is lower)
        full_name = f"{pod.metadata.namespace}/{pg_name}"
        pgs = self.status_cache.get(full_name)
        if pgs is None:
            return None
        pg = pgs.pod_group
        need = max(
            pg.spec.min_member
            - pg.status.scheduled
            - len(pgs.matched_pod_nodes.items()),
            0,
        )
        plan = self.preempt_planner.plan(
            pod, self.cluster, self.status_cache, full_name, need
        )
        if self.policy is not None:
            self.policy.note_plan(plan is not None)
        if plan is None:
            return None
        # legality gate: every victim must individually pass the existing
        # preempt hook ("applies through the existing preempt hooks") —
        # one forbidden victim invalidates the whole plan, because the
        # device's minimal set is minimal only as a unit
        for victim in plan.victims():
            try:
                self.preempt_remove_pod(pod, victim)
            except errs.SchedulingError:
                return None
        return plan

    def note_gang_evicted(self, full_name: str) -> None:
        """Reset a victim gang's local schedule state after a policy
        eviction: its members were deleted (and recreated Pending by the
        requeue), so the gang re-enters the queue as a fresh unit — phase
        back to PENDING, scheduled count zeroed, plan dropped. The status
        patch is best-effort (the controller re-derives phase from live
        member pods, the same crash-recovery contract post_bind_gangs
        relies on)."""
        with self._lock:
            pgs = self.status_cache.get(full_name)
            if pgs is None:
                return
            pg = pgs.pod_group
            pg.status.phase = PodGroupPhase.PENDING
            pg.status.scheduled = 0
            pg.status.schedule_start_time = None
            pgs.binds_committed = 0
            pgs.scheduled = False
            pgs.placement_plan = None
            for uid in list(pgs.matched_pod_nodes.items()):
                pgs.matched_pod_nodes.delete(uid)
            ns, name = pg.metadata.namespace, pg.metadata.name
        if self.pg_client is not None:
            try:
                self.pg_client.podgroups(ns).patch(
                    name,
                    {
                        "status": {
                            "phase": PodGroupPhase.PENDING.value,
                            "scheduled": 0,
                            "schedule_start_time": None,
                        }
                    },
                )
            except Exception:  # noqa: BLE001 — controller reconciles
                pass
        # the eviction does NOT reset the gang's pending clock: the
        # original first-seen is re-armed so pending age (and TTP, via
        # the lifecycle ledger's preserved arrival anchor) include the
        # preemption churn the gang is about to re-queue through
        self.pending_tracker.note_evicted(full_name)
        # the member deletions rode the evented cluster mutators; the
        # gang-row reset above is the only out-of-band change — name it
        self.mark_dirty(group=full_name)

    def forget_denied(self, full_name: str) -> None:
        """Drop a gang's deny-cache entry (a successful preemption freed
        the capacity the denial was about; the 20s stickiness would
        otherwise idle the freed capacity for its whole TTL)."""
        self.last_denied_pg.delete(full_name)

    def preempt_remove_pod(self, pod_to_schedule: Pod, pod_to_remove: Pod) -> None:
        """Raises SchedulingError when the preemption is forbidden.

        Policy (reference core.go:198-260): online↔online free; offline may
        never preempt online; nobody preempts members of Scheduled/Running
        gangs; a gang never preempts itself. ("offline" = carries the group
        label.)

        With the policy engine's preemption term enabled the phase rule is
        replaced by PRIORITY TIERS (docs/policy.md): a victim is legal iff
        its priority class is strictly below the preemptor's — including
        members of released (Scheduled/Running) gangs unless
        ``protect_running`` restores the reference behavior. The
        offline-may-not-preempt-online and no-self-preemption rules are
        kept as-is.
        """
        if self.policy is not None and self.policy.preemption:
            self._preempt_remove_tiered(pod_to_schedule, pod_to_remove)
            return
        remove_group, remove_offline = pod_group_name(pod_to_remove)
        schedule_group, schedule_offline = pod_group_name(pod_to_schedule)

        if not schedule_offline and not remove_offline:
            return

        if schedule_offline and not remove_offline:
            raise errs.SchedulingError(
                f"offline pod {pod_to_schedule.metadata.name} may not preempt "
                f"online pod {pod_to_remove.metadata.name}"
            )

        def check_victim() -> Tuple[str, Optional[Exception]]:
            full = f"{pod_to_remove.metadata.namespace}/{remove_group}"
            pgs = self.status_cache.get(full)
            if pgs is None:
                return "", errs.PodGroupNotFoundError(f"pod group not found: {full}")
            phase = pgs.pod_group.status.phase
            if phase in (PodGroupPhase.SCHEDULED, PodGroupPhase.RUNNING):
                return "", errs.SchedulingError(
                    "members of Scheduled/Running pod groups may not be preempted"
                )
            return full, None

        victim_full, err = check_victim()

        if not schedule_offline and remove_offline:
            if err is not None:
                raise err
            return

        # offline preempts offline
        schedule_full = f"{pod_to_schedule.metadata.namespace}/{schedule_group}"
        if victim_full == schedule_full:
            raise errs.SchedulingError(
                "pod group may not preempt its own members"
            )
        if err is not None:
            raise err

    def _preempt_remove_tiered(
        self, pod_to_schedule: Pod, pod_to_remove: Pod
    ) -> None:
        """Priority-tier legality (the policy engine's preemption
        eligibility term): strictly-lower tier only, no self-preemption,
        offline still may not preempt online, and the reference's phase
        protection applies only under ``protect_running``."""
        remove_group, remove_offline = pod_group_name(pod_to_remove)
        schedule_group, schedule_offline = pod_group_name(pod_to_schedule)
        if schedule_offline and not remove_offline:
            raise errs.SchedulingError(
                f"offline pod {pod_to_schedule.metadata.name} may not "
                f"preempt online pod {pod_to_remove.metadata.name}"
            )
        if pod_to_remove.spec.priority >= pod_to_schedule.spec.priority:
            raise errs.SchedulingError(
                f"victim {pod_to_remove.metadata.name} (tier "
                f"{pod_to_remove.spec.priority}) is not strictly below "
                f"preemptor tier {pod_to_schedule.spec.priority}"
            )
        if remove_offline:
            victim_full = (
                f"{pod_to_remove.metadata.namespace}/{remove_group}"
            )
            if schedule_offline:
                schedule_full = (
                    f"{pod_to_schedule.metadata.namespace}/{schedule_group}"
                )
                if victim_full == schedule_full:
                    raise errs.SchedulingError(
                        "pod group may not preempt its own members"
                    )
            if self.policy.config.protect_running:
                pgs = self.status_cache.get(victim_full)
                if pgs is not None and pgs.pod_group.status.phase in (
                    PodGroupPhase.SCHEDULED,
                    PodGroupPhase.RUNNING,
                ):
                    raise errs.SchedulingError(
                        "members of Scheduled/Running pod groups may not "
                        "be preempted (protect_running)"
                    )

    # ------------------------------------------------------------------
    # Score (reference stub core.go:263-265 — real ranks in oracle mode)
    # ------------------------------------------------------------------

    def score(self, pod: Pod, node_name: str) -> int:
        pg_name, ok = pod_group_name(pod)
        if not ok or self.scorer_kind != "oracle":
            return MAX_SCORE
        full_name = f"{pod.metadata.namespace}/{pg_name}"
        return self._oracle_fresh().node_score(full_name, node_name)

    # ------------------------------------------------------------------
    # Permit (reference core.go:268-309)
    # ------------------------------------------------------------------

    def permit(self, pod: Pod, node_name: str) -> PermitOutcome:
        pg_name, ok = pod_group_name(pod)
        if not ok:
            return PermitOutcome(True, pg_name, errs.NotMatchedError())
        full_name = f"{pod.metadata.namespace}/{pg_name}"
        pgs = self.status_cache.get(full_name)
        if pgs is None:
            return PermitOutcome(
                False, pg_name, errs.PodGroupNotFoundError(full_name)
            )
        pg = pgs.pod_group
        if (
            pgs.scheduled
            and pg.status.scheduled >= pg.spec.min_member
        ):
            # Quorum met AND released: members beyond the minimum schedule
            # like ordinary pods. The reference instead parks them in a
            # Permit wait whose release signal StartBatchSchedule ignores
            # for SCHEDULED gangs (batchscheduler.go:258-262), stranding
            # every late/extra member in a park -> TTL-abort loop forever
            # — a wart fixed, not copied (found by review repro: a
            # min_member=3 gang with 4 members never binds the 4th).
            return PermitOutcome(True, pg_name, errs.NotMatchedError())
        if pg.status.phase == PodGroupPhase.PENDING:
            pg.status.phase = PodGroupPhase.PRE_SCHEDULING

        pod_key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        wait = get_wait_seconds(pg, self.max_schedule_seconds)
        pgs.matched_pod_nodes.set(
            pod.metadata.uid, PodNodePair(pod_key, node_name), wait
        )
        old_uid = pgs.pod_name_uids.get(pod_key)
        if old_uid is not None and old_uid != pod.metadata.uid:
            # the pod was re-created; drop the stale permit (core.go:293-296)
            pgs.matched_pod_nodes.delete(old_uid)
        pgs.pod_name_uids.set(pod_key, pod.metadata.uid, wait)
        if self.scorer_kind != "oracle" or pgs.placement_plan is None:
            # Plan-covered gangs skip the per-pod invalidation: the batch's
            # assignment already placed every remaining member, so a member
            # matching only *reduces* future demand (conservative to serve
            # from the existing batch).
            self.mark_dirty(group=full_name)
        else:
            # plan-covered: no invalidation, but the matched count moved —
            # the next fold must re-read this gang's progress row
            self._gang_event(full_name)

        matched = len(pgs.matched_pod_nodes.items())
        if matched >= pg.spec.min_member - pg.status.scheduled:
            pgs.scheduled = True
            self.pending_tracker.note_placed(full_name)
            from ..utils.lifecycle import DEFAULT_LEDGER

            DEFAULT_LEDGER.note_permit(full_name)
            return PermitOutcome(True, pg_name, None)
        return PermitOutcome(False, pg_name, errs.WaitingError())

    # ------------------------------------------------------------------
    # PostBind (reference core.go:312-362)
    # ------------------------------------------------------------------

    def post_bind(self, pod: Pod, node_name: str) -> None:
        pg_name, ok = pod_group_name(pod)
        if not ok:
            return
        full_name = f"{pod.metadata.namespace}/{pg_name}"
        with self._lock:
            pgs = self.status_cache.get(full_name)
            if pgs is None:
                return
            pg = pgs.pod_group
            # max-of-lower-bounds, not addition: commutes with the
            # controller's live member count (see pg_cache.binds_committed)
            pgs.binds_committed += 1
            new_scheduled = max(pg.status.scheduled, pgs.binds_committed)
            if new_scheduled >= pg.spec.min_member:
                new_phase = PodGroupPhase.SCHEDULED
                new_start = pg.status.schedule_start_time
            else:
                new_phase = PodGroupPhase.SCHEDULING
                new_start = pg.status.schedule_start_time or time.time()

            if new_phase != pg.status.phase and self.pg_client is not None:
                # Slow path — once per phase transition (≤2 per gang). A
                # targeted status merge patch sets exactly the fields this
                # transition owns: no live GET, no object copy, no full
                # serialisation — the earlier GET+diff+deepcopy form held
                # this lock for milliseconds and serialized every bind
                # worker behind it (the postBind histogram showed 5.4ms/pod,
                # almost all lock wait).
                try:
                    updated = self.pg_client.podgroups(
                        pg.metadata.namespace
                    ).patch(
                        pg.metadata.name,
                        {
                            "status": {
                                "phase": new_phase.value,
                                "scheduled": new_scheduled,
                                "schedule_start_time": new_start,
                            }
                        },
                    )
                    pg.status.phase = updated.status.phase
                except Exception:
                    return
            else:
                pg.status.phase = new_phase
                pg.status.schedule_start_time = new_start

            pg.status.scheduled = new_scheduled
            completed = new_scheduled >= pg.spec.min_member
        # Plan-covered member binds are pre-accounted; re-batch once per
        # gang completion (progress/max-group freshness), not per pod.
        if (
            completed
            or self.scorer_kind != "oracle"
            or pgs.placement_plan is None
        ):
            self.mark_dirty(group=full_name)
        else:
            # plan-covered, quorum not yet met: binds_committed/scheduled
            # advanced — name the row for the next event fold
            self._gang_event(full_name)

    # ------------------------------------------------------------------
    # Queue ordering (reference core.go:368-411)
    # ------------------------------------------------------------------

    def compare(self, pod1: Pod, ts1: float, pod2: Pod, ts2: float) -> bool:
        """True iff pod1 should be scheduled before pod2: priority, then
        PodGroup creation time, then (reverse) group name, then pod queue
        timestamp — reference Compare semantics, including its
        reverse-lexicographic name tiebreak (core.go:404)."""
        prio1, prio2 = pod1.spec.priority, pod2.spec.priority
        name1, _ = pod_group_name(pod1)
        name2, _ = pod_group_name(pod2)

        if prio1 > prio2:
            return True
        if prio1 == prio2:
            if not name1 and not name2:
                return ts1 < ts2
            if not name1:
                return True
            if not name2:
                return False
        if self.pg_lister is None:
            return False
        pg1 = self.pg_lister(pod1.metadata.namespace, name1)
        pg2 = self.pg_lister(pod2.metadata.namespace, name2)
        if pg1 is None or pg2 is None:
            return False
        c1, c2 = pg1.metadata.creation_timestamp, pg2.metadata.creation_timestamp
        if prio1 == prio2 and c1 < c2:
            return True
        if prio1 == prio2 and c1 == c2 and name1 > name2:
            return True
        return prio1 == prio2 and c1 == c2 and name1 == name2 and ts1 < ts2

    # After a group's cache entry dies, its name is TOMBSTONED for this
    # long: sort_key keeps answering from the (possibly lagging) lister
    # but does NOT re-cache, so a recreated group cannot get pinned to its
    # predecessor's creation timestamp read off a stale informer doc.
    CREATION_TOMBSTONE_S = 5.0

    def _forget_creation(self, full_name: str) -> None:
        ns, _, name = full_name.partition("/")
        self._creation_cache.pop((ns, name), None)
        self._creation_tombstones[(ns, name)] = (
            self._clock() + self.CREATION_TOMBSTONE_S
        )
        # a deleted gang is no longer pending; its age never resolves
        # into the placement histogram (utils.health)
        self.pending_tracker.forget(full_name)
        from ..utils.lifecycle import DEFAULT_LEDGER

        DEFAULT_LEDGER.note_delete(full_name)

    def sort_key(self, info) -> tuple:
        """Total-order queue key equivalent to :meth:`compare` (reference
        Compare, core.go:368-411): priority desc → non-gang before gang →
        group creation asc → group name REVERSE-lex → queue timestamp asc.
        Computed once per push from the entry's scalar fields, so heap
        operations are tuple compares instead of O(log n) Less() chains.

        One documented deviation: a gang pod whose PodGroup the lister has
        not yet observed gets creation=+inf (sorts after known gangs of
        equal priority); the comparator form answers "incomparable" there
        (reference returns false both ways) and falls to insertion order.
        Both resolve the same way once the group is observed — such pods
        fail PreFilter with PodGroupNotFound until then."""
        if not info.gang:
            return (-info.priority, 0, 0.0, _RevStr(""), info.timestamp)
        # creation timestamps are immutable: cache per group so a push
        # costs a dict hit, not a lister lookup contending with the
        # watch-dispatch thread's informer lock
        cache_key = (info.namespace, info.gang)
        created = self._creation_cache.get(cache_key)
        if created is None:
            created = float("inf")
            if self.pg_lister is not None:
                pg = self.pg_lister(info.namespace, info.gang)
                if pg is not None:
                    created = pg.metadata.creation_timestamp
            tomb = self._creation_tombstones.get(cache_key)
            if tomb is not None and self._clock() < tomb:
                pass  # recently deleted: the lister may still be stale
            elif created != float("inf"):
                if tomb is not None:
                    del self._creation_tombstones[cache_key]
                self._creation_cache[cache_key] = created
        return (
            -info.priority,
            1,
            created,
            _RevStr(info.gang),
            info.timestamp,
        )

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------

    def add_to_deny_cache(self, full_name: str) -> None:
        self.last_denied_pg.add(full_name, "", DENY_TTL)
        # pending-gang aging (utils.health): every denial extends the
        # gang's pending window and its deny streak; placement resolves it
        self.pending_tracker.note_deny(full_name)

    def get_pod_node_pairs(self, full_name: str) -> Optional[TTLCache]:
        pgs = self.status_cache.get(full_name)
        return pgs.matched_pod_nodes if pgs is not None else None

    def get_pod_name_uids(self, full_name: str) -> Optional[TTLCache]:
        pgs = self.status_cache.get(full_name)
        return pgs.pod_name_uids if pgs is not None else None

    def _fill_occupied(self, pgs: PodGroupMatchStatus, pod: Pod) -> None:
        """Owner-reference fencing: a PodGroup belongs to the first workload
        whose pods claim it (reference fillOccupiedObj, core.go:477-512)."""
        if pgs is None or pgs.pod_group is None:
            raise errs.SchedulingError("pod group match status is nil")
        refs = sorted(str(r) for r in pod.metadata.owner_references)
        if pgs.pod is None:
            pgs.pod = pod
            # The demand row only *changes* if the pod carries placement
            # constraints the spec didn't (priority/selector/tolerations) or
            # fixes the member shape below; a plain first pod of a
            # min_resources gang leaves the row identical (has_pod only
            # gates max-progress eligibility) — don't burn a re-batch on it.
            if (
                pod.spec.priority
                or pod.spec.node_selector
                or pod.spec.tolerations
                or pgs.pod_group.spec.min_resources is None
            ):
                self.mark_dirty(group=pgs.pod_group.full_name())
        if pgs.pod_group.spec.min_resources is None:
            pgs.pod_group.spec.min_resources = pod.resource_require()
            self.mark_dirty(group=pgs.pod_group.full_name())
        occupied = pgs.pod_group.status.occupied_by
        if not occupied:
            if refs:
                pgs.pod_group.status.occupied_by = ",".join(refs)
            return
        if not refs or ",".join(refs) != occupied:
            raise errs.OccupiedError(
                f"pod group {pgs.pod_group.full_name()} occupied by {occupied}"
            )
