"""Host-side exact resource math — the reference-parity serial path.

This is the direct semantic equivalent of the reference's resource helpers
(reference pkg/scheduler/core/core.go:436-475,566-699,741-793), kept as the
``--scorer=serial`` fallback and as the measured baseline the TPU oracle must
beat. Dict-based exact integer arithmetic; the reference's float32
percent-truncation is replaced by exact ``floor(a·num/den)``.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Sequence, Tuple

from ..api.fit import selector_matches, tolerates_all
from ..api.types import Node, Pod
from ..cache.pg_cache import PodGroupMatchStatus

__all__ = [
    "add_resources",
    "scale_resources",
    "resource_satisfied",
    "check_fit",
    "single_node_left",
    "cluster_left",
    "cluster_satisfies",
    "pre_allocated_resource",
    "find_max_group_serial",
]

def add_resources(a: Dict[str, int], b: Dict[str, int]) -> Dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def scale_resources(r: Dict[str, int], num: int, den: int) -> Dict[str, int]:
    """Exact floor(v·num/den) per lane (the reserve-percent scaling,
    reference core.go:656-667)."""
    if num == den:
        return dict(r)
    return {k: (v * num) // den for k, v in r.items()}


def resource_satisfied(left: Dict[str, int], req: Dict[str, int]) -> bool:
    """Element-wise left >= req; a nonzero requirement for a lane the left
    side lacks fails (reference compareResourceAndRequire, core.go:672-699)."""
    for k, v in req.items():
        if v > left.get(k, 0):
            return False
    return True


def check_fit(pod: Pod, node: Node) -> bool:
    """Node selector + taint toleration placement fit
    (reference checkFit, core.go:741-759)."""
    return selector_matches(
        pod.spec.node_selector, node.metadata.labels
    ) and tolerates_all(pod.spec.tolerations, node.spec.taints)


def single_node_left(
    node: Node,
    requested: Dict[str, int],
    pod: Optional[Pod],
    percent: Tuple[int, int] = (1, 1),
) -> Dict[str, int]:
    """Per-node leftover = floor(alloc·percent) − requested, zeroed when the
    pod cannot be placed there at all (reference singleNodeResource,
    core.go:634-670)."""
    if pod is not None and not check_fit(pod, node):
        return {}
    scaled = scale_resources(node.status.allocatable, *percent)
    left = dict(scaled)
    for k, v in requested.items():
        left[k] = left.get(k, 0) - v
    return left


def cluster_left(
    nodes: Sequence[Node],
    node_requested: Dict[str, Dict[str, int]],
    pod: Optional[Pod],
    percent: Tuple[int, int] = (1, 1),
) -> Dict[str, int]:
    """Sum of per-node leftovers over schedulable nodes
    (reference computeClusterResource, core.go:566-593)."""
    total: Dict[str, int] = {}
    for node in nodes:
        if node.spec.unschedulable:
            continue
        left = single_node_left(
            node, node_requested.get(node.metadata.name, {}), pod, percent
        )
        total = add_resources(total, left)
    return total


def cluster_satisfies(
    nodes: Sequence[Node],
    node_requested: Dict[str, Dict[str, int]],
    pod: Optional[Pod],
    required: Dict[str, int],
    percent: Tuple[int, int] = (1, 1),
) -> bool:
    """Running-sum cluster feasibility with early exit — the serial hot loop
    the oracle replaces (reference compareClusterResourceAndRequire,
    core.go:595-632)."""
    running: Dict[str, int] = {}
    for node in nodes:
        if node.spec.unschedulable:
            continue
        left = single_node_left(
            node, node_requested.get(node.metadata.name, {}), pod, percent
        )
        running = add_resources(running, left)
        if resource_satisfied(running, required):
            return True
    return False


def pre_allocated_resource(pgs: PodGroupMatchStatus, matched: int) -> Dict[str, int]:
    """Resources to reserve for the max-progress group's unfinished members
    (reference getPreAllocatedResource, core.go:774-793)."""
    pg = pgs.pod_group
    if matched != 0:
        not_finished = pg.spec.min_member - matched
    else:
        not_finished = pg.spec.min_member - pg.status.scheduled
    total: Dict[str, int] = {}
    if pg.spec.min_resources:
        for _ in range(max(not_finished, 0)):
            total = add_resources(total, pg.spec.min_resources)
    if total.get("pods", 0) == 0:
        total["pods"] = pg.spec.min_member + 1
    return total


def find_max_group_serial(
    statuses: Dict[str, PodGroupMatchStatus],
) -> Tuple[str, Optional[PodGroupMatchStatus], int]:
    """Serial max-progress group selection (reference findMaxPG,
    core.go:701-739), with deterministic iteration (sorted by name) in place
    of Go's randomised map order."""
    max_name, max_status, max_finished = "", None, 0
    for name in sorted(statuses):
        pgs = statuses[name]
        if pgs.scheduled or pgs.pod is None:
            continue
        pg = pgs.pod_group
        if pg.spec.min_member - pg.status.scheduled <= 0:
            finished = 0
        else:
            finished = (
                (len(pgs.matched_pod_nodes.items()) + pg.status.scheduled)
                * 1000
                // max(pg.spec.min_member, 1)
            )
        if finished > max_finished:
            max_finished, max_name, max_status = finished, name, pgs
        elif finished == max_finished:
            if max_status is None or (
                max_status.pod_group.status.scheduled
                >= max_status.pod_group.spec.min_member
                and pg.status.scheduled == 0
            ):
                max_finished, max_name, max_status = finished, name, pgs
    return max_name, max_status, max_finished
