from .controller import GC_HORIZON_SECONDS, PodGroupController

__all__ = ["GC_HORIZON_SECONDS", "PodGroupController"]
