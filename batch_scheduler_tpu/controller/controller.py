"""PodGroupController: the informer-driven phase-machine reconciler.

Behavioural port of the reference controller
(reference pkg/scheduler/controller/controller.go:48-335): creates the
per-group match-status cache entries (wiring TTL eviction to the gang-abort
callback), normalises ""->Pending, recovers crash state by listing member
pods, drives Pending -> PreScheduling -> Scheduling -> Scheduled -> Running
-> Finished/Failed from live member pod phases, and persists every status
delta as a merge patch.
"""

from __future__ import annotations

import threading
import time
from dataclasses import replace
from typing import Callable, List, Optional

from ..api.types import (
    PodGroup,
    PodGroupPhase,
    PodGroupStatus,
    PodPhase,
    to_dict,
)
from ..cache.pg_cache import PGStatusCache, PodGroupMatchStatus
from ..client.apiserver import NotFoundError
from ..client.clientset import Clientset
from ..client.informers import SharedInformer
from ..utils.labels import POD_GROUP_LABEL, get_wait_seconds
from ..utils.patch import create_merge_patch
from ..utils.workqueue import RateLimitingQueue

__all__ = ["PodGroupController"]

# Re-enqueue guard: groups stuck past this horizon are left alone because
# their pods may have been garbage collected (reference controller.go:122-125).
GC_HORIZON_SECONDS = 48 * 3600.0


class PodGroupController:
    def __init__(
        self,
        client: Clientset,
        pg_informer: SharedInformer,
        pg_cache: PGStatusCache,
        reject_pod: Callable[[str], None],
        add_to_backoff: Callable[[str], None],
        rate_limiter_base: float = 1.0,
        rate_limiter_cap: float = 10.0,
        max_schedule_seconds: Optional[float] = None,
        resync_seconds: float = 0.5,
        clock: Callable[[], float] = time.monotonic,
        pod_informer=None,
    ):
        self.client = client
        # optional SharedInformer("Pod"): member-pod scans read uid/phase
        # from its raw store (label-indexed, no copies) instead of a
        # deep-copying API list per sync — the client-go lister pattern the
        # reference controller uses (controller.go:148-176)
        self._pod_informer = pod_informer
        self.pg_cache = pg_cache
        self.reject_pod = reject_pod
        self.add_to_backoff = add_to_backoff
        self.max_schedule_seconds = max_schedule_seconds
        self.resync_seconds = resync_seconds
        self._clock = clock
        self._limiter_args = (rate_limiter_base, rate_limiter_cap, clock)
        self.queue = RateLimitingQueue(*self._limiter_args)
        self._informer = pg_informer
        pg_informer.add_event_handler(
            on_add=self._pg_added_raw,
            on_update=self._pg_updated_raw,
            on_delete=self._pg_deleted_raw,
            raw=True,
        )
        self._stop = threading.Event()
        self._workers: List[threading.Thread] = []

    # -- informer handlers (reference controller.go:111-145) ---------------

    # raw-dict handlers: the watch stream delivers a handful of events per
    # gang (create + every status patch); the enqueue decision needs five
    # scalar fields, not a typed rehydration per event. The restart path
    # (run) feeds the same predicate from the informer's raw store, so the
    # GC/phase-skip rule exists exactly once.
    def _pg_added_raw(self, d: dict) -> None:
        status = d.get("status") or {}
        phase = status.get("phase") or ""
        if phase in (PodGroupPhase.FINISHED.value, PodGroupPhase.FAILED.value):
            return
        meta = d.get("metadata") or {}
        spec = d.get("spec") or {}
        if (
            status.get("scheduled", 0) == spec.get("min_member", 0)
            and status.get("running", 0) == 0
            and (status.get("schedule_start_time") or 0.0)
            - (meta.get("creation_timestamp") or 0.0)
            > GC_HORIZON_SECONDS
        ):
            return
        self.queue.add(
            f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
        )

    def _pg_updated_raw(self, old: Optional[dict], new: dict) -> None:
        self._pg_added_raw(new)

    def _pg_deleted_raw(self, d: dict) -> None:
        meta = d.get("metadata") or {}
        self.pg_cache.delete(
            f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
        )

    # -- run loop (reference controller.go:93-108) -------------------------

    def run(self, workers: int, stop_event: Optional[threading.Event] = None) -> None:
        self._stop = stop_event or threading.Event()
        if self.queue.is_shut_down():
            # restart after a lease loss: the old queue is dead; re-enqueue
            # every known group so reconciliation resumes cleanly
            self.queue = RateLimitingQueue(*self._limiter_args)
            for d in self._informer.list_raw():
                self._pg_added_raw(d)
        self._informer.wait_for_sync()
        for i in range(workers):
            t = threading.Thread(
                target=self._worker, name=f"pg-controller-{i}", daemon=True
            )
            t.start()
            self._workers.append(t)

    def stop(self) -> None:
        self._stop.set()
        self.queue.shut_down()

    def _worker(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.2)
            if key is None:
                continue
            try:
                self._sync(key)
            except Exception:
                # a failing sync retries with backoff; never kill the worker
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)

    # -- sync (reference controller.go:148-176) ----------------------------

    def _sync(self, key: str) -> None:
        namespace, _, name = key.partition("/")
        # shared read-only typed view (one materialisation per store
        # update, not one per sync — the deep-copying ``get`` was the
        # controller workers' top cost at 10k-pod scale). _sync_handler
        # never mutates it: every write goes through replace() copies, and
        # the cache entry takes a private copy at init.
        pg = self._informer.get_typed(namespace, name)
        if pg is None:
            try:
                pg = self.client.podgroups(namespace).get(name)
            except NotFoundError:
                self.pg_cache.delete(key)
                return
        self._sync_handler(pg, key)

    # -- the phase machine (reference controller.go:179-311) ---------------

    def _sync_handler(self, pg: PodGroup, key: str) -> None:
        # terminal groups never resync: no cache resurrection, no pod lists,
        # no dead rows in the oracle batch
        if pg.status.phase in (PodGroupPhase.FINISHED, PodGroupPhase.FAILED):
            self.pg_cache.delete(key)
            return

        pgs = self.pg_cache.get(key)
        if pgs is None:
            pgs = self._init_match_status(pg, key)
            self.pg_cache.set(key, pgs)

        # pgs.pod_group may alias pg (cache holds the informer object); diff
        # against an immutable snapshot so cache syncs don't mask the patch.
        # Only status is ever mutated here and its fields are all scalars,
        # so a shallow field copy is a true snapshot — the full-object
        # deepcopy this replaces was ~half the controller's sync cost.
        original = replace(pg.status)
        pg_copy = replace(pg, status=replace(pg.status))
        if pg_copy.status.phase == PodGroupPhase.EMPTY:
            pg_copy.status.phase = PodGroupPhase.PENDING
        elif (
            pg_copy.status.phase == PodGroupPhase.PENDING
            and pg_copy.status.schedule_start_time != 0
        ):
            # crash recovery: re-derive Scheduled from live member pods
            # (reference controller.go:201-222)
            pg_copy.status.scheduled = len(self._member_phases(pg_copy))
            if pg_copy.status.scheduled > 0:
                self._patch_if_changed(original, pg_copy)

        # Refresh the cached group's status from the API view — but never
        # regress locally-advanced scheduling progress: Permit/PostBind
        # advance phase and the scheduled counter in the cache first and
        # persist only on transitions (core semantics), and the gang release
        # gate reads the cache, so a clobber here could strand a complete
        # gang. (The reference clobbers, controller.go:225, and tolerates
        # the race by timing; we close it.) Controller-derived Running/
        # Failed/Finished always win.
        rank = {
            PodGroupPhase.EMPTY: 0,
            PodGroupPhase.PENDING: 1,
            PodGroupPhase.PRE_SCHEDULING: 2,
            PodGroupPhase.SCHEDULING: 3,
            PodGroupPhase.SCHEDULED: 4,
        }
        local = pgs.pod_group.status
        if (
            local.phase in rank
            and pg_copy.status.phase in rank
            and rank[local.phase] > rank[pg_copy.status.phase]
        ):
            pg_copy.status.phase = local.phase
        if local.scheduled > pg_copy.status.scheduled:
            pg_copy.status.scheduled = local.scheduled
        pgs.pod_group.status = pg_copy.status
        self.pg_cache.set(key, pgs)

        if (
            pg_copy.status.scheduled == pg_copy.spec.min_member
            and pg_copy.status.running == 0
            and pg_copy.status.schedule_start_time
            - pg_copy.metadata.creation_timestamp
            > GC_HORIZON_SECONDS
        ):
            return

        if pg_copy.status.phase in (
            PodGroupPhase.SCHEDULED,
            PodGroupPhase.RUNNING,
            PodGroupPhase.SCHEDULING,
            # PRE_SCHEDULING is beyond the reference's gate
            # (controller.go:235: Scheduling+), but bound members CAN
            # exist here — a bind whose API response was lost, or a
            # scheduler crash between bind and PostBind, leaves the gang
            # pre-scheduling with live non-Pending members and an
            # undercounted Status.Scheduled. Without this row the permit
            # quorum (minMember - scheduled) stays unreachable and the
            # gang loops park -> TTL abort -> park forever (found by the
            # gateway-restart soak at seed run 4: 7 members parked
            # needing 9, with 3 bound-but-uncounted siblings).
            PodGroupPhase.PRE_SCHEDULING,
        ):
            members = self._member_phases(pg_copy)
            with pgs.count_lock:
                not_pending = 0
                running = 0
                for uid, phase in members:
                    if phase == PodPhase.RUNNING.value:
                        running += 1
                    elif phase == PodPhase.SUCCEEDED.value:
                        pgs.succeed[uid] = ""
                    elif phase == PodPhase.FAILED.value:
                        pgs.failed[uid] = ""
                    if phase != PodPhase.PENDING.value:
                        not_pending += 1
                pg_copy.status.failed = len(pgs.failed)
                pg_copy.status.succeeded = len(pgs.succeed)
                pg_copy.status.running = running
                if not_pending > pg_copy.status.scheduled:
                    pg_copy.status.scheduled = not_pending

            # demote when members went missing (reference :276-279)
            if 0 != not_pending < pg_copy.spec.min_member:
                pg_copy.status.scheduled = not_pending
                pg_copy.status.phase = PodGroupPhase.SCHEDULING

            if pg_copy.status.succeeded + pg_copy.status.running >= pg.spec.min_member:
                pg_copy.status.phase = PodGroupPhase.RUNNING
            if (
                pg_copy.status.failed != 0
                and pg_copy.status.failed
                + pg_copy.status.running
                + pg_copy.status.succeeded
                >= pg.spec.min_member
            ):
                pg_copy.status.phase = PodGroupPhase.FAILED
            if pg_copy.status.succeeded >= pg.spec.min_member:
                pg_copy.status.phase = PodGroupPhase.FINISHED

        updated = self._patch_if_changed(original, pg_copy)
        terminal = False
        if updated is not None:
            if updated.status.phase in (PodGroupPhase.FINISHED, PodGroupPhase.FAILED):
                self.pg_cache.delete(key)
                terminal = True
            else:
                pgs.pod_group.status = updated.status
            self.queue.forget(key)
        if not terminal:
            # periodic resync keeps pod-count-driven transitions flowing
            # (reference re-enqueues unconditionally, controller.go:310)
            self.queue.add_after(key, self.resync_seconds)

    # -- helpers -----------------------------------------------------------

    def _member_phases(self, pg: PodGroup) -> list:
        """(uid, phase-string) per member pod — the only fields the phase
        machine reads. Informer-backed when available (raw dicts, no copy);
        API list otherwise — including while the informer is still replaying
        its initial list (controller start / leader failover), when a
        partial store would under-count members and demote healthy gangs."""
        if self._pod_informer is not None and self._pod_informer.has_synced():
            return [
                (
                    (d.get("metadata") or {}).get("uid", ""),
                    (d.get("status") or {}).get("phase", PodPhase.PENDING.value),
                )
                for d in self._pod_informer.list_raw_by_label(
                    pg.metadata.namespace,
                    {POD_GROUP_LABEL: pg.metadata.name},
                )
            ]
        return [
            (p.metadata.uid, p.status.phase.value)
            for p in self.client.pods(pg.metadata.namespace).list(
                label_selector={POD_GROUP_LABEL: pg.metadata.name}
            )
        ]

    def _patch_if_changed(self, original_status: PodGroupStatus, pg_copy: PodGroup):
        """Status-only merge patch: the sync handler never mutates metadata
        or spec, so the diff (and the serialisation cost) is confined to the
        handful of scalar status fields."""
        status_patch = create_merge_patch(
            to_dict(original_status), to_dict(pg_copy.status)
        )
        if not status_patch:
            return None
        try:
            return self.client.podgroups(pg_copy.metadata.namespace).patch(
                pg_copy.metadata.name, {"status": status_patch}
            )
        except NotFoundError:
            return None

    def _init_match_status(self, pg: PodGroup, key: str) -> PodGroupMatchStatus:
        """Create the live gang bookkeeping entry; TTL expiry of the
        pod-name->UID cache aborts the whole gang
        (reference initPodGroupMatchStatus + OnEvicted,
        controller.go:314-335)."""
        ttl = get_wait_seconds(pg, self.max_schedule_seconds)
        # private copy: the cache entry's group is mutated in place by
        # Permit/PostBind/_fill_occupied (status fields, spec.min_resources)
        # and must never alias the informer's shared typed view
        pg = replace(pg, spec=replace(pg.spec), status=replace(pg.status))
        pgs = PodGroupMatchStatus(pg, match_ttl=ttl, clock=self._clock)

        def on_evicted(_key: str, _value) -> None:
            for pod_uid in list(pgs.matched_pod_nodes.items()):
                self.reject_pod(pod_uid)
                pgs.matched_pod_nodes.delete(pod_uid)
            pgs.pod_name_uids.flush()
            self.add_to_backoff(key)

        pgs.pod_name_uids.on_evicted(on_evicted)
        return pgs
