"""Build/version stamping — the analog of the reference's ldflags injection
of gitVersion/gitCommit/buildDate into the binary (reference version.sh:3-38,
Makefile:23-26). Python has no link step, so the stamp is resolved lazily
from git with static fallbacks.
"""

from __future__ import annotations

import datetime
import os
import subprocess
from typing import Dict

VERSION = "0.1.0"
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _git(*args: str) -> str:
    try:
        out = subprocess.run(
            ["git", "-C", _REPO_ROOT, *args],
            capture_output=True,
            text=True,
            timeout=5,
        )
        return out.stdout.strip() if out.returncode == 0 else ""
    except Exception:
        return ""


def version_info() -> Dict[str, str]:
    commit = _git("rev-parse", "HEAD")
    dirty = bool(_git("status", "--porcelain"))
    return {
        "version": VERSION,
        "gitCommit": commit or "unknown",
        "gitTreeState": "dirty" if dirty else "clean",
        "buildDate": datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
    }


def version_string() -> str:
    info = version_info()
    return (
        f"batch-scheduler-tpu v{info['version']} "
        f"({info['gitCommit'][:14]}, {info['gitTreeState']}) {info['buildDate']}"
    )
