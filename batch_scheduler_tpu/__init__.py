"""batch_scheduler_tpu — a TPU-native gang/batch scheduling framework.

A ground-up rebuild of the capabilities of ``tenstack/batch-scheduler`` (a
Kubernetes scheduler-framework plugin providing all-or-nothing PodGroup gang
scheduling; surveyed in SURVEY.md) re-centred on a pure, batched, jit-compiled
JAX bin-packing oracle: instead of serial per-pod O(groups)+O(nodes) Go loops
(reference ``pkg/scheduler/core/core.go:595-739``), all pending PodGroups ×
all cluster nodes are scored in one XLA computation on TPU, data-parallel
across chips over ICI via ``jax.sharding``/``shard_map``.

Layout (mirrors the reference's component inventory, SURVEY.md §2):

- ``api``        PodGroup/Pod/Node data model, phases, quantities, lanes (C2)
- ``client``     in-memory API server, typed clientset, informers, fake (C3-C5)
- ``cache``      PodGroup status cache + TTL match caches (C6)
- ``core``       gang scheduling semantics: PreFilter/Filter/Permit/... (C7)
- ``ops``        the jitted oracle kernels — the TPU hot path (C7a)
- ``parallel``   device mesh, shardings, multi-chip collectives
- ``framework``  embedded mini scheduling framework (queue, cycles, waiting)
- ``plugin``     framework plugin adapter + reconcile + leader gate (C8, C10)
- ``controller`` PodGroup phase-machine reconciler (C9)
- ``service``    sidecar oracle service with a packed-array data plane
- ``sim``        KWOK-style simulated clusters and scenario harness
- ``models``     synthetic cluster/workload model zoo for sim + bench
- ``utils``      merge patch, labels, TTL cache, errors (C11)
"""

__version__ = "0.1.0"

# BST_LOCKCHECK=1 arms the runtime lock-discipline checker (the `go test
# -race` analog, docs/static_analysis.md): every class annotated
# `# guarded-by:` is instrumented so unguarded cross-thread access raises
# with both stacks. A no-op (one env probe) when the knob is unset.
from .analysis.lockcheck import maybe_install as _lockcheck_maybe_install

_lockcheck_maybe_install()
del _lockcheck_maybe_install
