"""BatchSchedulingPlugin: the framework-extension-point adapter.

Behavioural port of the reference plugin
(reference pkg/scheduler/batch/batchscheduler.go:60-374): maps
QueueSort/PreFilter/Filter/Score/Permit/PostBind onto the ScheduleOperation,
owns the start-signal channel and the gang release/abort choreography
(UpdateBatchCache + StartBatchSchedule + rejectPod), and runs the
ReconcileStatus loop thread.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional, Tuple

from ..api.types import Pod, PodGroupPhase, to_dict
from ..cache.pg_cache import PodGroupMatchStatus
from ..client.apiserver import NotFoundError
from ..core.operation import ScheduleOperation
from ..framework.types import StatusCode
from ..utils import errors as errs
from ..utils.labels import DEFAULT_WAIT_SECONDS, get_wait_seconds, pod_group_name
from ..utils.metrics import DEFAULT_REGISTRY, Registry
from ..utils.patch import create_merge_patch

__all__ = ["BatchSchedulingPlugin", "PLUGIN_NAME"]

PLUGIN_NAME = "batch-scheduler"

# Retry tuning for the waiting-pod race between the permit signal and the
# framework's waiting-pod registration (reference batchscheduler.go:85-89).
GET_WAIT_POD_RETRIES = 3
GET_WAIT_POD_SLEEP = 0.01


class BatchSchedulingPlugin:
    name = PLUGIN_NAME

    def __init__(
        self,
        handle,
        operation: ScheduleOperation,
        pg_client,
        max_schedule_seconds: Optional[float] = None,
        registry: Optional[Registry] = None,
    ):
        self.handle = handle
        self.operation = operation
        self.pg_client = pg_client
        self.max_schedule_seconds = max_schedule_seconds
        self.start_chan: "queue.Queue[str]" = queue.Queue()
        self._stop = threading.Event()
        self._reconcile_thread: Optional[threading.Thread] = None
        # per-extension-point latency (SURVEY.md §5 build note: the
        # reference has no instrumentation of its own)
        registry = registry or DEFAULT_REGISTRY
        self._ext_seconds = registry.histogram(
            "bst_extension_point_seconds",
            "Wall-clock seconds spent in each plugin extension point",
        )
        self._gang_releases = registry.counter(
            "bst_gang_releases_total", "Gangs released to bind"
        )

    # ------------------------------------------------------------------
    # framework extension points
    # ------------------------------------------------------------------

    def less(self, info1, info2) -> bool:
        return self.operation.compare(
            info1.pod, info1.timestamp, info2.pod, info2.timestamp
        )

    def sort_key(self, info) -> tuple:
        """Precomputed queue key equivalent to ``less`` — see
        ScheduleOperation.sort_key."""
        return self.operation.sort_key(info)

    def pre_filter(self, pod: Pod) -> None:
        with self._ext_seconds.time(point="preFilter"):
            self.operation.pre_filter(pod)

    def filter(self, pod: Pod, node_name: str) -> None:
        with self._ext_seconds.time(point="filter"):
            self.operation.filter(pod, node_name)

    def score(self, pod: Pod, node_name: str) -> int:
        with self._ext_seconds.time(point="score"):
            return self.operation.score(pod, node_name)

    def permit(self, pod: Pod, node_name: str) -> Tuple[StatusCode, float]:
        """Returns (status, wait timeout). Gang pods always Wait; the wait
        timeout is the gang TTL + 1s so cache eviction (gang abort) fires
        before the framework's own timeout (reference batchscheduler.go:
        165-202, the +1s at :180-182)."""
        with self._ext_seconds.time(point="permit"):
            outcome = self.operation.permit(pod, node_name)
        wait = DEFAULT_WAIT_SECONDS
        if outcome.pg_name:
            full_name = f"{pod.metadata.namespace}/{outcome.pg_name}"
            pgs = self.operation.status_cache.get(full_name)
            if pgs is not None:
                wait = get_wait_seconds(pgs.pod_group, self.max_schedule_seconds)
        wait += 1.0

        if outcome.error is not None:
            if isinstance(outcome.error, errs.WaitingError):
                return StatusCode.WAIT, wait
            if isinstance(outcome.error, errs.NotMatchedError):
                return StatusCode.SUCCESS, 0.0
            return StatusCode.UNSCHEDULABLE, DEFAULT_WAIT_SECONDS

        if outcome.ready:
            self._gang_releases.inc()
            # non-blocking put on an unbounded queue; no thread needed
            self.send_start_schedule_signal(
                f"{pod.metadata.namespace}/{outcome.pg_name}"
            )
        return StatusCode.WAIT, wait

    def post_bind(self, pod: Pod, node_name: str) -> None:
        with self._ext_seconds.time(point="postBind"):
            self.operation.post_bind(pod, node_name)

    # PreFilterExtensions (reference batchscheduler.go:116-144): the
    # preemption dry-run's add/remove hooks
    def preempt_add_pod(self, pod_to_add: Pod, node_name: str) -> None:
        self.operation.preempt_add_pod(pod_to_add, node_name)

    def preempt_remove_pod(self, pod_to_schedule: Pod, pod_to_remove: Pod) -> None:
        self.operation.preempt_remove_pod(pod_to_schedule, pod_to_remove)

    # Vectorized policy preemption (batch_scheduler_tpu.policy /
    # docs/policy.md): the dry-run victim plan for a denied gang, and the
    # post-eviction gang reset. The framework drives the transaction
    # (Scheduler._evict_gang_plan: verify → evict → requeue).
    def preempt_victim_plan(self, pod: Pod):
        with self._ext_seconds.time(point="preemptPlan"):
            return self.operation.preempt_victim_plan(pod)

    def note_gang_evicted(self, full_name: str) -> None:
        self.operation.note_gang_evicted(full_name)

    def forget_denied(self, full_name: str) -> None:
        self.operation.forget_denied(full_name)

    def mark_dirty(self) -> None:
        self.operation.mark_dirty()

    # Whole-gang fast lane (gang-granular release+bind; reference
    # precedent: StartBatchSchedule's one-sweep gang release,
    # batchscheduler.go:254-344)
    def gang_plan(self, pod: Pod):
        return self.operation.gang_plan(pod)

    def permit_gang(self, full_name: str, members) -> bool:
        with self._ext_seconds.time(point="permit"):
            ok = self.operation.permit_gang(full_name, members)
        if ok:
            self._gang_releases.inc()
        return ok

    def post_bind_gang(self, full_name: str, bound: int) -> None:
        with self._ext_seconds.time(point="postBind"):
            self.operation.post_bind_gang(full_name, bound)

    def post_bind_gangs(self, items) -> None:
        with self._ext_seconds.time(point="postBind"):
            self.operation.post_bind_gangs(items)

    def suggested_node(self, pod: Pod) -> Optional[str]:
        """Gang-granular admission: the batch plan's next open slot for this
        pod, letting the framework skip the full node scan."""
        return self.operation.suggested_node(pod)

    def on_assume(self, pod: Pod, node_name: str, from_plan: bool = False) -> None:
        self.operation.on_assume(pod, node_name, from_plan)

    # ------------------------------------------------------------------
    # gang release choreography (the batchScheduler interface,
    # reference batchscheduler.go:53-58)
    # ------------------------------------------------------------------

    def update_batch_cache(self) -> None:
        """Reconcile waiting-pod UIDs into the per-group caches
        (reference UpdateBatchCache, batchscheduler.go:219-251)."""

        def visit(waiting_pod) -> None:
            pod = waiting_pod.get_pod()
            group, ok = pod_group_name(pod)
            if not ok:
                return
            full_name = f"{pod.metadata.namespace}/{group}"
            pgs = self.operation.status_cache.get(full_name)
            if pgs is None:
                return
            pod_key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            old_uid = pgs.pod_name_uids.get(pod_key)
            if old_uid is not None and old_uid != pod.metadata.uid:
                pgs.matched_pod_nodes.delete(old_uid)
                pgs.pod_name_uids.delete(pod_key)

        self.handle.iterate_over_waiting_pods(visit)

    def start_batch_schedule(self, full_name: str) -> None:
        """Release a complete gang: stamp ScheduleStartTime, then Allow every
        matched waiting pod (reference StartBatchSchedule,
        batchscheduler.go:254-344)."""
        pgs = self.operation.status_cache.get(full_name)
        if pgs is None:
            return
        phase = pgs.pod_group.status.phase
        if phase not in (PodGroupPhase.PRE_SCHEDULING, PodGroupPhase.SCHEDULING):
            return

        if (
            pgs.pod_group.status.scheduled >= pgs.pod_group.spec.min_member
            and self.pg_client is not None
        ):
            # re-stamp schedule start to survive abnormal exit during bind
            # (reference batchscheduler.go:263-288)
            try:
                ns = pgs.pod_group.metadata.namespace
                live = self.pg_client.podgroups(ns).get(pgs.pod_group.metadata.name)
                live_copy = live.deepcopy()
                live_copy.status.schedule_start_time = time.time()
                patch = create_merge_patch(to_dict(live), to_dict(live_copy))
                self.pg_client.podgroups(ns).patch(live.metadata.name, patch)
            except NotFoundError:
                self.start_chan.put(full_name)
                return

        pending = self.operation.get_pod_node_pairs(full_name)
        pending_ids = self.operation.get_pod_name_uids(full_name)
        if pending is None or pending_ids is None:
            return
        pending_map = pending.items()
        needed = pgs.pod_group.spec.min_member - pgs.pod_group.status.scheduled
        if len(pending_map) < needed:
            return

        # Two-pass sweep. Pass 1 allows every pair whose waiting pod is
        # already visible (no sleeping). Pass 2 gives ALL the misses one
        # shared retry grace — each pair's WaitingPod materialises
        # independently in the permit-signal/park gap, so every pair gets
        # the full grace, while the sweep's total sleep stays one grace
        # period regardless of gang size (a mostly-stale big gang must
        # not serially stall the single reconcile thread per member).
        # Pairs still missing after the grace are dropped — but the sweep
        # CONTINUES. The reference RETURNS on the first miss
        # (batchscheduler.go:316-323), abandoning every not-yet-allowed
        # member to its full Permit timeout with no further release
        # signal coming (the quorum event already happened — found as the
        # ~100s stragglers in the gateway-restart e2e). The pairs are
        # independent TTL entries; one raced pod says nothing about the
        # rest. Deviation, not copied.
        def consume(uid, pair, waiting_pod) -> None:
            # allow() returning False means the wait already resolved
            # (timeout/reject) — that is permanent, so never retry;
            # either way the pair is consumed
            waiting_pod.allow(self.name)
            pending.delete(uid)
            pending_ids.delete(pair.pod_name)

        missing = []
        for uid, pair in pending_map.items():
            waiting_pod = self.handle.get_waiting_pod(uid)
            if waiting_pod is None:
                missing.append((uid, pair))
            else:
                consume(uid, pair, waiting_pod)
        for attempt in range(GET_WAIT_POD_RETRIES - 1):
            if not missing:
                break
            time.sleep(GET_WAIT_POD_SLEEP)
            still = []
            for uid, pair in missing:
                waiting_pod = self.handle.get_waiting_pod(uid)
                if waiting_pod is None:
                    still.append((uid, pair))
                else:
                    consume(uid, pair, waiting_pod)
            missing = still
        for uid, pair in missing:
            # raced ahead of the framework cache for the whole grace:
            # drop the stale pair (reference batchscheduler.go:316-323)
            pending.delete(uid)
            pending_ids.delete(pair.pod_name)

    def reject_pod(self, uid: str) -> None:
        """Abort one waiting pod (reference rejectPod,
        batchscheduler.go:347-354)."""
        waiting_pod = self.handle.get_waiting_pod(uid)
        if waiting_pod is None:
            return
        waiting_pod.reject("Group failed")

    # ------------------------------------------------------------------
    # reconcile loop (reference ReconcileStatus, batchscheduler.go:357-368)
    # ------------------------------------------------------------------

    def send_start_schedule_signal(self, full_name: str) -> None:
        self.start_chan.put(full_name)

    # release-signal retry bound: ~10s of 0.5s-spaced attempts rides out
    # an API-server outage; a persistently-failing signal is then dropped
    # and the gang recovers via its TTL abort (reference behavior drops
    # immediately, batchscheduler.go:263-288 returns on patch error)
    RELEASE_RETRIES = 20

    def reconcile_status(self) -> None:
        while not self._stop.is_set():
            try:
                item = self.start_chan.get(timeout=0.2)
            except queue.Empty:
                continue
            full_name, attempt = (
                item if isinstance(item, tuple) else (item, 0)
            )
            try:
                self.update_batch_cache()
                self.start_batch_schedule(full_name)
            except Exception:
                # the reconcile loop must survive any single release — and
                # the SIGNAL must survive a transient failure too (an API
                # outage during the ScheduleStartTime stamp would strand a
                # complete gang in Permit waits until its TTL abort). The
                # re-enqueue is DELAYED on a timer, never blocking this
                # consumer thread, and bounded so a poisoned signal cannot
                # starve other gangs' releases forever.
                if attempt < self.RELEASE_RETRIES:
                    timer = threading.Timer(
                        0.5,
                        self.start_chan.put,
                        args=((full_name, attempt + 1),),
                    )
                    timer.daemon = True
                    timer.start()

    def start(self) -> None:
        self._reconcile_thread = threading.Thread(
            target=self.reconcile_status, name="reconcile-status", daemon=True
        )
        self._reconcile_thread.start()

    def stop(self) -> None:
        self._stop.set()
