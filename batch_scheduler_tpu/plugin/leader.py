"""Leader-election lease and the leader-gated controller runner.

Equivalent of the reference's EndpointsLock polling loop
(reference pkg/scheduler/batch/batchscheduler.go:450-502): the PodGroup
controller runs only on the replica currently holding the scheduler lease,
starts when the lease is observed held by us and fresh, and stops on loss.

The lease itself is an abstraction: ``InMemoryLease`` for single-process /
simulated deployments, ``FileLease`` for multi-process single-host
deployments (atomic O_EXCL claim files), and ``APILease`` — the deployment-
grade one — a Lease object living *in the API server* (the analog of the
reference's EndpointsLock in kube-system, batchscheduler.go:458-464), so any
number of scheduler replicas against one API server coordinate through the
same durable object, with optimistic-concurrency updates making claims
race-free.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

__all__ = [
    "LeaseRecord",
    "InMemoryLease",
    "FileLease",
    "APILease",
    "try_run_controller",
]


@dataclass
class LeaseRecord:
    holder_identity: str = ""
    renew_time: float = 0.0
    lease_duration_seconds: float = 15.0


class InMemoryLease:
    def __init__(self, clock: Callable[[], float] = time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._record = LeaseRecord()  # guarded-by: _lock

    def get(self) -> Optional[LeaseRecord]:
        with self._lock:
            return LeaseRecord(**vars(self._record))

    def acquire(self, identity: str, duration: float = 15.0) -> bool:
        with self._lock:
            rec = self._record
            now = self._clock()
            expired = now - rec.renew_time > rec.lease_duration_seconds
            if rec.holder_identity in ("", identity) or expired:
                self._record = LeaseRecord(identity, now, duration)
                return True
            return False

    def renew(self, identity: str) -> bool:
        with self._lock:
            if self._record.holder_identity != identity:
                return False
            self._record.renew_time = self._clock()
            return True

    def release(self, identity: str) -> None:
        with self._lock:
            if self._record.holder_identity == identity:
                self._record = LeaseRecord()


class FileLease:
    """Lease in a JSON file. Claims run read-check-write under an flock'd
    sidecar lock file, so two processes racing an expired lease cannot both
    win (the split-brain the lease exists to prevent)."""

    def __init__(self, path: str, clock: Callable[[], float] = time.time):
        self._path = path
        self._clock = clock

    def _locked(self):
        import fcntl
        from contextlib import contextmanager

        @contextmanager
        def guard():
            with open(f"{self._path}.lock", "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                try:
                    yield
                finally:
                    fcntl.flock(lk, fcntl.LOCK_UN)

        return guard()

    def _read(self) -> Optional[LeaseRecord]:
        try:
            with open(self._path) as f:
                d = json.load(f)
            return LeaseRecord(
                d.get("holder_identity", ""),
                d.get("renew_time", 0.0),
                d.get("lease_duration_seconds", 15.0),
            )
        except (OSError, ValueError):
            return None

    def get(self) -> Optional[LeaseRecord]:
        return self._read()

    def _write(self, rec: LeaseRecord) -> None:
        tmp = f"{self._path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(vars(rec), f)
        os.replace(tmp, self._path)

    def acquire(self, identity: str, duration: float = 15.0) -> bool:
        with self._locked():
            rec = self._read()
            now = self._clock()
            if (
                rec is None
                or rec.holder_identity in ("", identity)
                or now - rec.renew_time > rec.lease_duration_seconds
            ):
                self._write(LeaseRecord(identity, now, duration))
                return True
            return False

    def renew(self, identity: str) -> bool:
        with self._locked():
            rec = self._read()
            if rec is None or rec.holder_identity != identity:
                return False
            rec.renew_time = self._clock()
            self._write(rec)
            return True

    def release(self, identity: str) -> None:
        with self._locked():
            rec = self._read()
            if rec is not None and rec.holder_identity == identity:
                try:
                    os.unlink(self._path)
                except OSError:
                    pass


class APILease:
    """Lease object stored in the API server (namespace ``kube-system``,
    like the reference's EndpointsLock, batchscheduler.go:458-464).

    Claims are compare-and-swap: the update carries the read
    ``resource_version``, so two replicas racing an expired lease cannot
    both win — the loser's update raises ConflictError and its ``acquire``
    returns False. Works over the in-memory APIServer and the HTTP adapter
    alike (both speak the same interface)."""

    KIND = "Lease"

    def __init__(
        self,
        api,
        name: str = "batch-scheduler",
        namespace: str = "kube-system",
        default_duration: float = 15.0,
        clock: Callable[[], float] = time.time,
    ):
        self._api = api
        self._name = name
        self._ns = namespace
        self._default_duration = default_duration
        self._clock = clock

    @staticmethod
    def _record(d: dict) -> LeaseRecord:
        spec = d.get("spec") or {}
        return LeaseRecord(
            spec.get("holder_identity", ""),
            spec.get("renew_time", 0.0),
            spec.get("lease_duration_seconds", 15.0),
        )

    def get(self) -> Optional[LeaseRecord]:
        from ..client.apiserver import NotFoundError

        try:
            return self._record(self._api.get(self.KIND, self._ns, self._name))
        except NotFoundError:
            return None

    def _spec(self, identity: str, duration: float) -> dict:
        return {
            "holder_identity": identity,
            "renew_time": self._clock(),
            "lease_duration_seconds": duration,
        }

    def acquire(self, identity: str, duration: Optional[float] = None) -> bool:
        from ..client.apiserver import (
            AlreadyExistsError,
            ConflictError,
            NotFoundError,
        )

        duration = self._default_duration if duration is None else duration
        try:
            d = self._api.get(self.KIND, self._ns, self._name)
        except NotFoundError:
            try:
                self._api.create(
                    self.KIND,
                    {
                        "metadata": {"namespace": self._ns, "name": self._name},
                        "spec": self._spec(identity, duration),
                    },
                )
                return True
            except AlreadyExistsError:
                return False  # raced another replica's create; retry next poll
        rec = self._record(d)
        now = self._clock()
        expired = now - rec.renew_time > rec.lease_duration_seconds
        if rec.holder_identity not in ("", identity) and not expired:
            return False
        d["spec"] = self._spec(identity, duration)
        try:
            self._api.update(self.KIND, d)  # CAS on resource_version
            return True
        except (ConflictError, NotFoundError):
            return False

    def renew(self, identity: str) -> bool:
        from ..client.apiserver import ConflictError, NotFoundError

        try:
            d = self._api.get(self.KIND, self._ns, self._name)
        except NotFoundError:
            return False
        rec = self._record(d)
        if rec.holder_identity != identity:
            return False
        d["spec"]["renew_time"] = self._clock()
        try:
            self._api.update(self.KIND, d)
            return True
        except (ConflictError, NotFoundError):
            return False

    def release(self, identity: str) -> None:
        from ..client.apiserver import NotFoundError

        try:
            d = self._api.get(self.KIND, self._ns, self._name)
        except NotFoundError:
            return
        if self._record(d).holder_identity == identity:
            try:
                self._api.delete(self.KIND, self._ns, self._name)
            except NotFoundError:
                pass


def try_run_controller(
    lease,
    identity: str,
    controller,
    workers: int,
    stop_event: threading.Event,
    poll_seconds: float = 1.0,
    clock: Callable[[], float] = time.time,
) -> None:
    """Poll the lease; run the controller only while we hold it
    (reference tryRunController, batchscheduler.go:452-502)."""
    started = False
    controller_stop: Optional[threading.Event] = None
    while not stop_event.wait(poll_seconds):
        record = lease.get()
        if record is None:
            continue
        held = identity and identity in record.holder_identity
        fresh = clock() - record.renew_time < record.lease_duration_seconds
        if held and fresh:
            if not started:
                controller_stop = threading.Event()
                controller.run(workers, controller_stop)
                started = True
        elif started:
            started = False
            controller_stop.set()
            controller.stop()
    if started and controller_stop is not None:
        controller_stop.set()
        controller.stop()
