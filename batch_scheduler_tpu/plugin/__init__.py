from .batch_plugin import PLUGIN_NAME, BatchSchedulingPlugin
from .factory import PluginConfig, PluginRuntime, new_plugin_runtime
from .leader import FileLease, InMemoryLease, LeaseRecord, try_run_controller

__all__ = [
    "PLUGIN_NAME",
    "BatchSchedulingPlugin",
    "PluginConfig",
    "PluginRuntime",
    "new_plugin_runtime",
    "FileLease",
    "InMemoryLease",
    "LeaseRecord",
    "try_run_controller",
]
