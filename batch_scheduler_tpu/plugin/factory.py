"""Plugin factory: assembles the whole control plane around a framework
handle — the equivalent of the reference's ``New`` registration entry point
(reference pkg/scheduler/batch/batchscheduler.go:377-448 and
cmd/scheduler/main.go:28-36).

Wiring order mirrors the reference: clientset -> informers -> status cache
-> ScheduleOperation (with the ``scorer`` gate, the north star's
``--scorer=tpu`` flag) -> CRD auto-create -> ReconcileStatus thread ->
controller -> leader-gated controller runner.
"""

from __future__ import annotations

import socket
import threading
from dataclasses import dataclass, field
from typing import Optional

from ..api import register
from ..cache.pg_cache import PGStatusCache
from ..client.apiserver import APIServer
from ..client.clientset import Clientset
from ..client.informers import SharedInformerFactory
from ..controller.controller import PodGroupController
from ..core.operation import ScheduleOperation
from .batch_plugin import BatchSchedulingPlugin
from .gate import ALL_EXTENSION_POINTS, ExtensionPointGate
from .leader import InMemoryLease, try_run_controller

__all__ = ["PluginConfig", "PluginRuntime", "new_plugin_runtime"]


@dataclass
class PluginConfig:
    """Plugin args (reference Configuration, batchscheduler.go:71-75).
    ``max_schedule_minutes`` keeps the reference's minutes interpretation
    (batchscheduler.go:406)."""

    max_schedule_minutes: Optional[float] = None
    # "oracle" = the TPU-batched scorer (the --scorer=tpu gate);
    # "serial" = the reference-parity in-process path.
    scorer: str = "oracle"
    # Re-batch coalescing window for the oracle scorer (0 = re-batch on
    # every invalidation; >0 bounds batch rate under churn — denials are
    # already 20s-sticky so bounded staleness is inside existing semantics).
    min_batch_interval_seconds: float = 0.0
    # Re-batch on a daemon thread while serving the stale (but
    # known-complete) batch — takes the device round-trip off the
    # scheduling cycle's critical path (see OracleScorer.background_refresh).
    oracle_background_refresh: bool = False
    # Dispatch-ahead: speculatively pack + execute batch N+1 while the
    # control plane works against batch N; a later refresh publishes it
    # without a blocking device round-trip iff nothing changed since it
    # packed — bit-identical plans either way (docs/pipelining.md).
    oracle_dispatch_ahead: bool = False
    # Compile-ahead bucket warmer: precompile the adjacent (G, N) bucket
    # shapes around the live working set on a daemon thread so a bucket
    # transition never pays the cold XLA compile on the serving path.
    oracle_compile_warmer: bool = False
    # Black-box flight data (utils.audit / docs/observability.md): an
    # AuditLog instance recording every published oracle batch — packed
    # inputs + plan digest — to a bounded on-disk ring for deterministic
    # replay (`python -m batch_scheduler_tpu replay`). None = off.
    oracle_audit_log: Optional[object] = None
    # Sampled in-production identity audit: every Kth non-speculative
    # published batch re-verified bit-for-bit on the CPU fallback rung
    # (utils.health.IdentityAuditor; mismatch => /debug/health breach).
    # 0 = off.
    oracle_identity_audit_every: int = 0
    # Policy engine config (batch_scheduler_tpu.policy.PolicyConfig /
    # docs/policy.md): priority-tiered preemption, affinity / spread
    # scoring terms. None = read BST_POLICY from the environment (empty =
    # policies off, the exact pre-policy paths).
    policy: Optional[object] = None
    controller_workers: int = 10
    leader_poll_seconds: float = 1.0
    lease_renew_seconds: float = 3.0
    # Extension points the plugin is enabled at (config-file surface,
    # reference batch_scheduler_config.json:7-36). Default: all — a superset
    # of the reference's shipped four (it omits filter/score; we keep score
    # on so node selection reads oracle ranks).
    enabled_points: frozenset = ALL_EXTENSION_POINTS
    controller_resync_seconds: float = 0.5
    identity: str = field(default_factory=socket.gethostname)

    @property
    def max_schedule_seconds(self) -> Optional[float]:
        if self.max_schedule_minutes is None:
            return None
        return self.max_schedule_minutes * 60.0


class PluginRuntime:
    """Everything the factory assembled; owns background thread lifecycle."""

    def __init__(self, plugin, controller, lease, config, informers, operation):
        self.plugin = plugin
        self.controller = controller
        self.lease = lease
        self.config = config
        self.informers = informers
        self.operation = operation
        self._stop = threading.Event()
        self._leader_thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self.informers.start()
        self.plugin.start()
        # leader-election heartbeat: keep trying to hold (or take over) the
        # lease — the role upstream kube-scheduler's election loop plays for
        # the reference
        self._renew_thread = threading.Thread(
            target=self._renew_loop, name="lease-renew", daemon=True
        )
        self._renew_thread.start()
        self._leader_thread = threading.Thread(
            target=try_run_controller,
            args=(
                self.lease,
                self.config.identity,
                self.controller,
                self.config.controller_workers,
                self._stop,
                self.config.leader_poll_seconds,
            ),
            name="leader-gate",
            daemon=True,
        )
        self._leader_thread.start()

    def _renew_loop(self) -> None:
        while not self._stop.wait(self.config.lease_renew_seconds):
            try:
                self.lease.acquire(self.config.identity)
            except Exception:
                pass

    def stop(self) -> None:
        self._stop.set()
        self.plugin.stop()
        self.controller.stop()
        self.informers.stop()
        oracle = getattr(self.operation, "oracle", None)
        if oracle is not None:
            # let any in-flight background batch finish before the process
            # (and with it the XLA runtime) can go away; a timed-out join
            # means teardown would still race the XLA call, so keep
            # waiting with escalating patience before giving up loudly
            drain = getattr(oracle, "drain_background", None)
            if drain is not None:
                for timeout in (60.0, 120.0, 120.0):
                    if drain(timeout) is not False:
                        break


def new_plugin_runtime(
    api: APIServer,
    handle,
    config: Optional[PluginConfig] = None,
    lease=None,
    clock=None,
    informers: Optional[SharedInformerFactory] = None,
) -> PluginRuntime:
    """Build plugin + controller + leader gate over an API server and a
    framework handle. ``handle.cluster`` is the snapshot provider.

    Pass ``informers`` to share one factory (and thus ONE watch stream +
    typed rehydration per event per kind) with the embedding framework —
    a second factory doubles every pod event's dispatch cost."""
    config = config or PluginConfig()
    pg_client = Clientset(api)

    if informers is None:
        informers = SharedInformerFactory(api)
    pg_informer = informers.pod_groups()
    lister = informers.pod_group_lister()

    pg_cache = PGStatusCache()

    kwargs = {} if clock is None else {"clock": clock}
    operation = ScheduleOperation(
        status_cache=pg_cache,
        cluster=handle.cluster,
        pg_client=pg_client,
        max_schedule_seconds=config.max_schedule_seconds,
        # compare runs per heap comparison — use the informer's cached
        # typed view (read-only) instead of rebuilding objects per call
        pg_lister=pg_informer.get_typed,
        scorer=config.scorer,
        min_batch_interval=config.min_batch_interval_seconds,
        background_refresh=config.oracle_background_refresh,
        dispatch_ahead=config.oracle_dispatch_ahead,
        compile_warmer=config.oracle_compile_warmer,
        audit_log=config.oracle_audit_log,
        identity_audit_every=config.oracle_identity_audit_every,
        policy=config.policy,
        **kwargs,
    )

    plugin = BatchSchedulingPlugin(
        handle=handle,
        operation=operation,
        pg_client=pg_client,
        max_schedule_seconds=config.max_schedule_seconds,
    )
    if frozenset(config.enabled_points) != ALL_EXTENSION_POINTS:
        plugin = ExtensionPointGate(plugin, config.enabled_points)

    # CRD auto-create, ignoring AlreadyExists (reference :416-436)
    api.ensure_crd(
        register.CRD_NAME,
        {
            "group": register.GROUP_NAME,
            "version": register.VERSION,
            "kind": register.KIND_POD_GROUP,
            "plural": register.PLURAL_POD_GROUPS,
            "short_names": list(register.SHORT_NAMES),
            "scope": "Namespaced",
        },
    )

    controller = PodGroupController(
        client=pg_client,
        pg_informer=pg_informer,
        pod_informer=informers.informer("Pod"),
        pg_cache=pg_cache,
        reject_pod=plugin.reject_pod,
        add_to_backoff=operation.add_to_deny_cache,
        max_schedule_seconds=config.max_schedule_seconds,
        resync_seconds=config.controller_resync_seconds,
        **kwargs,
    )

    if lease is None:
        lease = InMemoryLease()
        lease.acquire(config.identity)  # single-replica default: we lead

    return PluginRuntime(plugin, controller, lease, config, informers, operation)
