"""Extension-point gate: enable/disable plugin callbacks per config.

The reference ships a ``KubeSchedulerConfiguration`` enabling the plugin at
exactly four extension points — ``preFilter``, ``permit``, ``postBind``,
``queueSort`` — while its implemented ``Filter`` is deliberately NOT enabled
(reference deploy/scheduler/config/batch_scheduler_config.json:7-36 vs
pkg/scheduler/batch/batchscheduler.go:151-157). This wrapper reproduces that
configuration surface: it delegates only the enabled points and no-ops the
rest, so the shipped-config behavior (and any other combination) is testable.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, Tuple

from ..framework.types import StatusCode

__all__ = ["ExtensionPointGate", "ALL_EXTENSION_POINTS", "DEFAULT_ENABLED"]

ALL_EXTENSION_POINTS = frozenset(
    {"queueSort", "preFilter", "filter", "score", "permit", "postBind"}
)
# The reference's shipped config (batch_scheduler_config.json:7-36).
DEFAULT_ENABLED = frozenset({"queueSort", "preFilter", "permit", "postBind"})


class ExtensionPointGate:
    """Delegates enabled extension points to a BatchSchedulingPlugin, no-ops
    the rest. Lifecycle and cache-maintenance calls always pass through."""

    def __init__(self, plugin, enabled: Iterable[str] = DEFAULT_ENABLED):
        enabled = frozenset(enabled)
        unknown = enabled - ALL_EXTENSION_POINTS
        if unknown:
            raise ValueError(f"unknown extension points: {sorted(unknown)}")
        self.plugin = plugin
        self.enabled: FrozenSet[str] = enabled

    # -- gated extension points -------------------------------------------

    def less(self, info1, info2) -> bool:
        if "queueSort" in self.enabled:
            return self.plugin.less(info1, info2)
        return info1.timestamp < info2.timestamp

    def pre_filter(self, pod) -> None:
        if "preFilter" in self.enabled:
            self.plugin.pre_filter(pod)

    def filter(self, pod, node_name: str) -> None:
        if "filter" in self.enabled:
            self.plugin.filter(pod, node_name)

    def score(self, pod, node_name: str) -> int:
        if "score" in self.enabled:
            return self.plugin.score(pod, node_name)
        return 0

    def permit(self, pod, node_name: str) -> Tuple[StatusCode, float]:
        if "permit" in self.enabled:
            return self.plugin.permit(pod, node_name)
        return (StatusCode.SUCCESS, 0.0)

    def post_bind(self, pod, node_name: str) -> None:
        if "postBind" in self.enabled:
            self.plugin.post_bind(pod, node_name)

    # -- always pass through ----------------------------------------------

    def __getattr__(self, name):
        return getattr(self.plugin, name)
