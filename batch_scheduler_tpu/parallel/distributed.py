"""Multi-host bootstrap for the oracle's device mesh.

The reference's only distributed machinery is control-plane: an
Endpoints-lease leader poll plus API-server watches (reference
pkg/scheduler/batch/batchscheduler.go:452-502; SURVEY.md §5 "Distributed
communication backend"). The TPU build's data plane scales differently: the
same fused batch runs ``pjit``-sharded over a ``jax.sharding.Mesh``, and on
a multi-host slice the mesh simply spans all hosts' devices — XLA's
collectives over ICI/DCN are the communication backend; there is no NCCL/MPI
analog to port.

``init_distributed`` wires ``jax.distributed`` from standard environment
variables so the same service binary works single-host (no-op) and
multi-host (each host runs one process; the coordinator address is the only
required config). ``global_mesh`` then builds the (groups × nodes) mesh over
every device in the job.
"""

from __future__ import annotations

import os
import warnings
from typing import Optional

import jax

from .mesh import make_mesh

__all__ = ["init_distributed", "global_mesh"]

_initialized = False


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize ``jax.distributed`` for a multi-host oracle service.

    Reads ``BST_COORDINATOR`` / ``BST_NUM_PROCESSES`` / ``BST_PROCESS_ID``
    when arguments are omitted (matching the one-process-per-host model of
    ``jax.distributed.initialize``). Returns True if a multi-host runtime
    was initialized; False for the single-process no-op (no coordinator
    configured — the common case, and the only one exercised in CI).
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get("BST_COORDINATOR")
    if not coordinator_address:
        return False
    # parse-guarded (the BST_SCAN_WAVE idiom): a typo'd knob degrades to
    # the single-process topology instead of crashing bootstrap — but only
    # when the value is absent/garbage, never silently renumbering a host
    try:
        num_processes = num_processes or int(
            os.environ.get("BST_NUM_PROCESSES", "1")
        )
    except ValueError:
        warnings.warn(
            "ignoring unparseable BST_NUM_PROCESSES; assuming 1 process"
        )
        num_processes = 1
    try:
        process_id = (
            process_id
            if process_id is not None
            else int(os.environ.get("BST_PROCESS_ID", "0"))
        )
    except ValueError:
        warnings.warn("ignoring unparseable BST_PROCESS_ID; assuming id 0")
        process_id = 0
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def global_mesh():
    """The (groups × nodes) mesh over every device in the job — all local
    devices single-host, or the full slice after ``init_distributed``."""
    return make_mesh(devices=jax.devices())
