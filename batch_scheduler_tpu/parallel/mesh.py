"""Device mesh + sharded oracle execution.

Scaling model (SURVEY.md §2 "Parallelism strategies"): the scaling axis of
this domain is cluster size, not sequence length — the (groups × nodes)
feasibility/score tensors are sharded over a 2-D ``("groups", "nodes")``
mesh, with XLA inserting the ICI collectives (psum for node-axis reductions,
all-gathers for the assignment scan) under GSPMD. TP/PP/SP/EP/ring-attention
are intentionally out of scope: no sequence dimension exists (SURVEY.md §5
"Long-context").

On one host this runs over the virtual CPU device mesh in tests and the
single TPU chip in prod; on a v5e pod slice the same code spans chips over
ICI — ``jax.sharding.Mesh`` is the only multi-chip abstraction used.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import oracle as okern

__all__ = [
    "make_mesh",
    "shard_snapshot_args",
    "sharded_schedule_batch",
    "sharded_collective_counts",
    "count_collective_instructions",
    "compiled_cost_summary",
    "COLLECTIVES",
]

# collective op mnemonics as they appear in compiled HLO instruction lines
# (single shared tuple — benchmarks/sharding_scaling.py counts with the
# same heuristic through count_collective_instructions below)
COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)


def count_collective_instructions(hlo_text: str) -> dict:
    """Per-op counts of collective INSTRUCTION sites in compiled HLO.
    Line-based on instruction forms (``%x = ... all-gather(...)`` and the
    async ``-start`` variant), not incidental metadata mentions."""
    counts = {}
    for op in COLLECTIVES:
        counts[op] = sum(
            1
            for line in hlo_text.splitlines()
            if f" {op}(" in line or f"{op}-start(" in line
        )
    return counts


def compiled_cost_summary(compiled) -> dict:
    """Guarded cost/memory/collective summary of one compiled executable
    (a ``jax.stages.Compiled``): ``cost_analysis()`` (flops, bytes
    accessed), ``memory_analysis()`` (argument/output/temp/code bytes),
    and the collective instruction counts from the HLO text
    (``count_collective_instructions`` — the same heuristic the sharding
    benchmark gates on). Every probe is independently guarded: not all
    backends expose all three analyses (TPU exposes memory_analysis, CPU
    often only cost_analysis), and a missing analysis yields a smaller
    dict, never an error — the consumer is telemetry
    (ops.oracle bucket cost registry, /debug/buckets, TRACE_INFO)."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        # older jax returns a per-device list; newer a flat dict
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            for src, dst in (
                ("flops", "flops"),
                ("bytes accessed", "bytes_accessed"),
                ("transcendentals", "transcendentals"),
                ("utilization", "utilization"),
            ):
                v = ca.get(src)
                if isinstance(v, (int, float)):
                    out[dst] = float(v)
    except Exception:  # noqa: BLE001 — backend-dependent, telemetry only
        pass
    try:
        ma = compiled.memory_analysis()
        for attr in (
            "generated_code_size_in_bytes",
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "temp_size_in_bytes",
        ):
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)):
                out[attr] = int(v)
    except Exception:  # noqa: BLE001
        pass
    try:
        out["collectives"] = count_collective_instructions(compiled.as_text())
    except Exception:  # noqa: BLE001
        pass
    return out


def _factor_devices(n: int) -> tuple:
    """Split n devices into a (groups, nodes) grid, nodes-major — node-axis
    parallelism carries the heavy lanes (N is the big dimension)."""
    g = int(math.isqrt(n))
    while g > 1 and n % g != 0:
        g -= 1
    return (g, n // g)


def make_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    grid = _factor_devices(len(devs))
    return Mesh(np.asarray(devs).reshape(grid), axis_names=("groups", "nodes"))


def shard_snapshot_args(mesh: Mesh, args: tuple) -> tuple:
    """Place ClusterSnapshot.device_args() onto the mesh.

    Layout: node-major arrays split over "nodes"; group-major over "groups";
    the (G, N) fit mask over both; the scan order replicated.
    """
    (alloc, requested, group_req, remaining, fit_mask, group_valid, order) = args
    # A broadcast [1,N] fit mask (uniform-feasibility fast path) has no
    # group extent to split — shard its node axis only.
    mask_spec = (
        P(None, "nodes") if fit_mask.shape[0] == 1 else P("groups", "nodes")
    )
    spec = {
        "alloc": P("nodes", None),
        "requested": P("nodes", None),
        "group_req": P("groups", None),
        "remaining": P("groups"),
        "fit_mask": mask_spec,
        "group_valid": P("groups"),
        "order": P(),
    }
    named = dict(
        alloc=alloc,
        requested=requested,
        group_req=group_req,
        remaining=remaining,
        fit_mask=fit_mask,
        group_valid=group_valid,
        order=order,
    )
    multiprocess = jax.process_count() > 1

    def _place(v, sharding):
        v = np.asarray(v)
        if multiprocess:
            # every host holds the full array; each process contributes its
            # addressable shards (jax.device_put cannot target devices on
            # other hosts)
            return jax.make_array_from_callback(
                v.shape, sharding, lambda idx: v[idx]
            )
        return jax.device_put(v, sharding)

    placed = {
        k: _place(v, NamedSharding(mesh, spec[k])) for k, v in named.items()
    }
    return (
        placed["alloc"],
        placed["requested"],
        placed["group_req"],
        placed["remaining"],
        placed["fit_mask"],
        placed["group_valid"],
        placed["order"],
    )


def sharded_schedule_batch(mesh: Mesh, args: tuple, replicated_scan: bool = True):
    """One fused oracle batch with inputs sharded over the mesh; XLA/GSPMD
    partitions the kernels and inserts the cross-chip collectives.

    ``replicated_scan`` (default, the production layout): the O(G·N·R)
    scoring runs sharded, then the sequential gang scan's inputs are
    replicated up front so its G steps run collective-free on every chip —
    the measured compiled module carries 5 one-time collectives total,
    versus ~50 collective sites INSIDE the scan loop (executed per step)
    when the scan state is partitioned, which ran 6x slower than a single
    device on the 8-way virtual mesh (benchmarks/sharding_scaling.py,
    SHARDING_r03.json; virtual-mesh caveats in the README scaling note).
    Pass False to measure the naive fully-partitioned layout."""
    sharded = shard_snapshot_args(mesh, args)
    return okern.schedule_batch(
        *sharded, scan_mesh=mesh if replicated_scan else None
    )


def sharded_collective_counts(
    mesh: Mesh, args: tuple, replicated_scan: bool = True
) -> dict:
    """Collective INSTRUCTIONS in the compiled sharded module, by op.

    The replicated-scan layout's contract is a one-time handful of
    collectives for the whole batch (scoring all-gathers + the scan-input
    replication), not per-scan-step traffic — GSPMD partitioning bugs at
    large/uneven shard shapes typically show up as op-count explosions
    here before they show up as wrong numbers."""
    sharded = shard_snapshot_args(mesh, args)
    hlo = (
        okern.schedule_batch.lower(
            *sharded, scan_mesh=mesh if replicated_scan else None
        )
        .compile()
        .as_text()
    )
    return count_collective_instructions(hlo)
