"""Device mesh + sharded oracle execution.

Scaling model (SURVEY.md §2 "Parallelism strategies"): the scaling axis of
this domain is cluster size, not sequence length — the (groups × nodes)
feasibility/score tensors are sharded over a 2-D ``("groups", "nodes")``
mesh, with XLA inserting the ICI collectives (psum for node-axis reductions,
all-gathers for the assignment scan) under GSPMD. TP/PP/SP/EP/ring-attention
are intentionally out of scope: no sequence dimension exists (SURVEY.md §5
"Long-context").

On one host this runs over the virtual CPU device mesh in tests and the
single TPU chip in prod; on a v5e pod slice the same code spans chips over
ICI — ``jax.sharding.Mesh`` is the only multi-chip abstraction used.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops import oracle as okern

__all__ = [
    "make_mesh",
    "snapshot_shardings",
    "shard_snapshot_args",
    "sharded_schedule_batch",
    "sharded_collective_counts",
    "sharded_scan_collective_counts",
    "count_collective_instructions",
    "collective_instruction_bytes",
    "compiled_cost_summary",
    "COLLECTIVES",
]

# collective op mnemonics as they appear in compiled HLO instruction lines
# (single shared tuple — benchmarks/sharding_scaling.py counts with the
# same heuristic through count_collective_instructions below)
COLLECTIVES = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)


def count_collective_instructions(hlo_text: str) -> dict:
    """Per-op counts of collective INSTRUCTION sites in compiled HLO.
    Line-based on instruction forms (``%x = ... all-gather(...)`` and the
    async ``-start`` variant), not incidental metadata mentions."""
    counts = {}
    for op in COLLECTIVES:
        counts[op] = sum(
            1
            for line in hlo_text.splitlines()
            if f" {op}(" in line or f"{op}-start(" in line
        )
    return counts


def compiled_cost_summary(compiled) -> dict:
    """Guarded cost/memory/collective summary of one compiled executable
    (a ``jax.stages.Compiled``): ``cost_analysis()`` (flops, bytes
    accessed), ``memory_analysis()`` (argument/output/temp/code bytes),
    and the collective instruction counts from the HLO text
    (``count_collective_instructions`` — the same heuristic the sharding
    benchmark gates on). Every probe is independently guarded: not all
    backends expose all three analyses (TPU exposes memory_analysis, CPU
    often only cost_analysis), and a missing analysis yields a smaller
    dict, never an error — the consumer is telemetry
    (ops.oracle bucket cost registry, /debug/buckets, TRACE_INFO)."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
        # older jax returns a per-device list; newer a flat dict
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            for src, dst in (
                ("flops", "flops"),
                ("bytes accessed", "bytes_accessed"),
                ("transcendentals", "transcendentals"),
                ("utilization", "utilization"),
            ):
                v = ca.get(src)
                if isinstance(v, (int, float)):
                    out[dst] = float(v)
    except Exception:  # noqa: BLE001 — backend-dependent, telemetry only
        pass
    try:
        ma = compiled.memory_analysis()
        for attr in (
            "generated_code_size_in_bytes",
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "temp_size_in_bytes",
        ):
            v = getattr(ma, attr, None)
            if isinstance(v, (int, float)):
                out[attr] = int(v)
    except Exception:  # noqa: BLE001
        pass
    try:
        out["collectives"] = count_collective_instructions(compiled.as_text())
    except Exception:  # noqa: BLE001
        pass
    return out


# HLO shape tokens like "s32[8,8,128]{2,1,0}" ahead of a collective op name
_SHAPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8,
}
_SHAPE_RE = None  # compiled lazily (re import kept local)


def collective_instruction_bytes(hlo_text: str) -> list:
    """``(op, bytes)`` for every collective INSTRUCTION site in compiled
    HLO, sized as the LARGEST shape on the line's left-hand side — async
    forms (``<op>-start``) put a tuple of (aliased operand, result) there,
    so summing would double-count; the max is the buffer the collective
    actually materializes. The budget signal for the node-sharded scan:
    every collective it issues moves an [S, W, BINS] summary (a few KB),
    never the [N, R] node state — a node-state-sized entry here is the
    partitioned-scan regression (SHARDING_r05's 54 all-gathers) coming
    back."""
    import re

    global _SHAPE_RE
    if _SHAPE_RE is None:
        _SHAPE_RE = re.compile(r"\b(\w+)\[([0-9,]*)\]")
    out = []
    for line in hlo_text.splitlines():
        for op in COLLECTIVES:
            token = f" {op}(" if f" {op}(" in line else (
                f"{op}-start(" if f"{op}-start(" in line else None
            )
            if token is None:
                continue
            lhs = line.split(token, 1)[0]
            largest = 0
            for dtype, dims in _SHAPE_RE.findall(lhs):
                unit = _SHAPE_BYTES.get(dtype)
                if unit is None:
                    continue
                count = 1
                for d in filter(None, dims.split(",")):
                    count *= int(d)
                largest = max(largest, unit * count)
            out.append((op, largest))
            break
    return out


def _factor_devices(n: int) -> tuple:
    """Split n devices into a (groups, nodes) grid, nodes-major — node-axis
    parallelism carries the heavy lanes (N is the big dimension)."""
    g = int(math.isqrt(n))
    while g > 1 and n % g != 0:
        g -= 1
    return (g, n // g)


def make_mesh(
    n_devices: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    grid = _factor_devices(len(devs))
    return Mesh(np.asarray(devs).reshape(grid), axis_names=("groups", "nodes"))


def snapshot_specs(
    mesh: Mesh, broadcast_mask: bool, flat_nodes: bool = False
) -> dict:
    """The canonical per-array PartitionSpecs of one oracle batch — THE
    single source for ``shard_snapshot_args`` and the device-resident
    state holder (ops.device_state), so a resident buffer scattered in
    place keeps exactly the layout a freshly placed snapshot would get."""
    nodes_axes = tuple(mesh.axis_names) if flat_nodes else "nodes"
    # A broadcast [1,N] fit mask (uniform-feasibility fast path) has no
    # group extent to split — shard its node axis only.
    if broadcast_mask:
        mask_spec = P(None, nodes_axes)
    else:
        mask_spec = (
            P(None, nodes_axes) if flat_nodes else P("groups", "nodes")
        )
    return {
        "alloc": P(nodes_axes, None),
        "requested": P(nodes_axes, None),
        "group_req": P("groups", None),
        "remaining": P("groups"),
        "fit_mask": mask_spec,
        "group_valid": P("groups"),
        "order": P(),
    }


def snapshot_shardings(
    mesh: Mesh, broadcast_mask: bool, flat_nodes: bool = False
) -> dict:
    """``snapshot_specs`` resolved to NamedShardings on ``mesh``."""
    return {
        k: NamedSharding(mesh, s)
        for k, s in snapshot_specs(
            mesh, broadcast_mask, flat_nodes=flat_nodes
        ).items()
    }


def shard_snapshot_args(
    mesh: Mesh, args: tuple, flat_nodes: bool = False
) -> tuple:
    """Place ClusterSnapshot.device_args() onto the mesh.

    Layout: node-major arrays split over "nodes"; group-major over "groups";
    the (G, N) fit mask over both; the scan order replicated.

    ``flat_nodes`` (the node-sharded scan layout, ops.oracle
    ``assign_gangs_sharded``): the node axis of every node-major array is
    split over ALL mesh devices — the scan has no group parallelism to
    spend, so its inputs stay node-sharded end-to-end instead of being
    replicated across the group axis, and the shard_map entry needs no
    resharding collective for the leftover lanes.
    """
    (alloc, requested, group_req, remaining, fit_mask, group_valid, order) = args
    spec = snapshot_specs(
        mesh, broadcast_mask=fit_mask.shape[0] == 1, flat_nodes=flat_nodes
    )
    named = dict(
        alloc=alloc,
        requested=requested,
        group_req=group_req,
        remaining=remaining,
        fit_mask=fit_mask,
        group_valid=group_valid,
        order=order,
    )
    multiprocess = jax.process_count() > 1

    def _place(v, sharding):
        v = np.asarray(v)
        if multiprocess:
            # every host holds the full array; each process contributes its
            # addressable shards (jax.device_put cannot target devices on
            # other hosts)
            return jax.make_array_from_callback(
                v.shape, sharding, lambda idx: v[idx]
            )
        return jax.device_put(v, sharding)

    placed = {
        k: _place(v, NamedSharding(mesh, spec[k])) for k, v in named.items()
    }
    return (
        placed["alloc"],
        placed["requested"],
        placed["group_req"],
        placed["remaining"],
        placed["fit_mask"],
        placed["group_valid"],
        placed["order"],
    )


def sharded_schedule_batch(mesh: Mesh, args: tuple,
                           replicated_scan: bool = True,
                           sharded_scan: bool = False,
                           scan_wave: int = 0,
                           scan_topk: int = 0):
    """One fused oracle batch with inputs sharded over the mesh; XLA/GSPMD
    partitions the kernels and inserts the cross-chip collectives.

    Scan layouts, most- to least-partitioned:

    - ``sharded_scan=True`` — the node-sharded wavefront merge
      (ops.oracle.assign_gangs_sharded): every shard keeps only its node
      slice of the leftover lanes end-to-end and each wave merges an
      [S, W, BINS] summary with one all-gather + one reduce — the layout
      that makes "add chips" mean "go faster" (SHARDING_r06).
    - ``replicated_scan`` (default without ``sharded_scan``; also the
      fallback rung the dispatch ladder demotes to): scoring runs sharded,
      then the scan's inputs are replicated up front so its G steps run
      collective-free on every chip — a one-time handful of collectives
      (5 in the measured module) versus ~50 collective sites INSIDE the
      scan loop when GSPMD partitions the scan state, which ran 6x slower
      than a single device on the 8-way virtual mesh
      (benchmarks/sharding_scaling.py, SHARDING_r03.json; virtual-mesh
      caveats in the README scaling note).
    - Both False — the naive fully-partitioned GSPMD layout, kept
      measurable as the cautionary baseline.

    ``scan_topk`` > 0 selects the hierarchical top-K scan on whichever
    layout is live (the XL-tier rung, docs/scan_parallelism.md
    "Hierarchical top-K"): with ``sharded_scan`` each shard coarse-ranks
    only its node slice and the per-wave merge moves candidate summaries
    instead of histograms."""
    sharded = shard_snapshot_args(mesh, args, flat_nodes=sharded_scan)
    return okern.schedule_batch(
        *sharded,
        scan_mesh=mesh if (replicated_scan or sharded_scan) else None,
        scan_shard=sharded_scan,
        scan_wave=scan_wave,
        scan_topk=scan_topk,
    )


def sharded_scan_collective_counts(
    mesh: Mesh, args: tuple, wave: int = 8, topk: int = 0
) -> dict:
    """Collective budget of the node-sharded assignment SCAN alone.

    ``sharded_collective_counts`` compiles the whole fused batch, so the
    scoring phase's one-time collectives drown the signal the scan's
    budget gate actually needs. This lowers ONLY ``left_resources`` + the
    sharded scan (the exact computation the gang loop runs) and reports:

    - ``counts`` — per-op collective instruction sites in the compiled
      module (static sites: the scan body compiles once; the demotion
      replay contributes its gang-at-a-time sites whether or not a batch
      ever demotes);
    - ``max_collective_bytes`` — the largest result any collective site
      moves. The budget contract: every site is summary-sized
      (≤ ``summary_bytes`` ≈ S·W·BINS ints, plus slop for stacked wave
      outputs), never ``node_state_bytes`` (N·R lanes) — the dynamic
      fast-path cost is ≤ 2 collectives per wave (one summary all-gather,
      one verify reduce) by construction;
    - ``waves`` — sequential steps per batch at this (G, wave).

    ``topk`` > 0 lowers the hierarchical top-K sharded scan instead
    (ops.oracle.assign_gangs_topk_sharded): the per-wave summary is then
    the merged candidate payload (composites + clipped capacities +
    pooled scalars; the gang-at-a-time replay adds a [_BINS] histogram
    per gang), still never node state — same ≤2-per-wave fast-path
    budget.
    """
    (alloc, requested, group_req, remaining, fit_mask, _gv, order) = tuple(
        np.asarray(a) for a in args
    )

    def scan_only(alloc, requested, group_req, remaining, fit_mask, order):
        left = okern.left_resources(alloc, requested)
        if topk > 0:
            return okern.assign_gangs_topk_sharded(
                left, group_req, remaining, fit_mask, order, mesh=mesh,
                wave=wave, k=topk, with_stats=True,
            )
        return okern.assign_gangs_sharded(
            left, group_req, remaining, fit_mask, order, mesh=mesh,
            wave=wave, with_stats=True,
        )

    hlo = (
        jax.jit(scan_only)
        .lower(alloc, requested, group_req, remaining, fit_mask, order)
        .compile()
        .as_text()
    )
    sizes = collective_instruction_bytes(hlo)
    s = int(mesh.devices.size)
    w = max(int(wave), 2)
    g = int(group_req.shape[0])
    if topk > 0:
        n_pad = -(-int(alloc.shape[0]) // s) * s
        kk_l = max(1, min(int(topk), n_pad // s))
        # largest per-wave payload across the three paths: speculative
        # [S, W, 2K_l+1], mega [S, 1, 3K_l+W], replay [S, 1, 2K_l+1+_BINS]
        payload = max(
            w * (2 * kk_l + 1), 3 * kk_l + w, 2 * kk_l + 1 + okern._BINS
        )
        summary_bytes = s * payload * 4
    else:
        summary_bytes = s * w * okern._BINS * 4
    return {
        "counts": count_collective_instructions(hlo),
        "max_collective_bytes": max((b for _, b in sizes), default=0),
        "summary_bytes": summary_bytes,
        "node_state_bytes": int(alloc.shape[0]) * int(alloc.shape[1]) * 4,
        "waves": -(-g // w),
        "fastpath_collectives_per_wave": 2,
    }


def sharded_collective_counts(
    mesh: Mesh, args: tuple, replicated_scan: bool = True
) -> dict:
    """Collective INSTRUCTIONS in the compiled sharded module, by op.

    The replicated-scan layout's contract is a one-time handful of
    collectives for the whole batch (scoring all-gathers + the scan-input
    replication), not per-scan-step traffic — GSPMD partitioning bugs at
    large/uneven shard shapes typically show up as op-count explosions
    here before they show up as wrong numbers."""
    sharded = shard_snapshot_args(mesh, args)
    hlo = (
        okern.schedule_batch.lower(
            *sharded, scan_mesh=mesh if replicated_scan else None
        )
        .compile()
        .as_text()
    )
    return count_collective_instructions(hlo)
