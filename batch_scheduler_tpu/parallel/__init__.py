from .distributed import global_mesh, init_distributed
from .mesh import (
    make_mesh,
    shard_snapshot_args,
    sharded_collective_counts,
    sharded_schedule_batch,
)

__all__ = [
    "global_mesh",
    "init_distributed",
    "make_mesh",
    "shard_snapshot_args",
    "sharded_schedule_batch",
    "sharded_collective_counts",
]
