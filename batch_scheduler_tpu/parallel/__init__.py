from .mesh import make_mesh, shard_snapshot_args, sharded_schedule_batch

__all__ = ["make_mesh", "shard_snapshot_args", "sharded_schedule_batch"]
