"""Checker ``knobs`` — the BST_* env-knob registry, kept mechanical.

Two invariants, both shipped-bug classes:

1. **Parse-guard discipline** (the BST_SCAN_WAVE idiom, ops/oracle.py): a
   typo'd knob must degrade to the working default, never crash a batch.
   Mechanized as: any ``int(...)``/``float(...)`` conversion of a
   ``BST_*`` env read (direct, or through one local name) must sit inside
   a ``try`` whose handlers catch ``ValueError`` (or ``TypeError`` /
   ``Exception`` / bare).  Flag-style string comparisons need no guard.

2. **Documentation**: every knob read anywhere in the tree (package,
   benchmarks, bench.py, __graft_entry__.py) must appear in README.md's
   env-knob tables. Dynamically-built names (f-strings like
   ``BST_SLO_{sig}_P95_S``) are checked as a family by their literal
   prefix, which the README documents with the ``BST_SLO_<SIGNAL>``
   spelling.

Writes (``os.environ["BST_X"] = ...``) configure child code and are
exempt. Suppress one line with ``# analysis: allow(knobs) <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .annotations import comment_map, is_suppressed, suppressions_at
from .findings import Finding

CHECKER = "knobs"

_CATCH_OK = {"ValueError", "TypeError", "Exception", None}  # None = bare except


def _env_read_key(node: ast.AST) -> Optional[ast.AST]:
    """The key expression of an env read, or None.

    Matches ``os.environ.get(K, ...)``, ``os.getenv(K, ...)``,
    ``os.environ[K]`` (Load ctx only — subscript stores are writes).
    """
    if isinstance(node, ast.Call):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "get"
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "environ"
        ) or (isinstance(f, ast.Attribute) and f.attr == "getenv") or (
            isinstance(f, ast.Name) and f.id == "getenv"
        ):
            return node.args[0] if node.args else None
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        v = node.value
        if isinstance(v, ast.Attribute) and v.attr == "environ":
            key = node.slice
            if isinstance(key, ast.Index):  # py<3.9 compat
                key = key.value
            return key
    return None


def _knob_name(key: ast.AST) -> Optional[Tuple[str, bool]]:
    """(name-or-prefix, is_family) if the key is a BST_* knob."""
    if isinstance(key, ast.Constant) and isinstance(key.value, str):
        if key.value.startswith("BST_"):
            return key.value, False
        return None
    if isinstance(key, ast.JoinedStr) and key.values:
        head = key.values[0]
        if (
            isinstance(head, ast.Constant)
            and isinstance(head.value, str)
            and head.value.startswith("BST_")
        ):
            return head.value, True
    if isinstance(key, ast.Name):
        # module-level constant like _WAVE_ENV = "BST_SCAN_WAVE" — resolved
        # by the caller against the file's constant bindings
        return key.id, None  # type: ignore[return-value]
    return None


def _module_str_constants(tree: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Constant):
            if isinstance(node.value.value, str):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = node.value.value
    return out


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _guarded_by_try(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> bool:
    prev = node
    cur = node
    while cur in parents:
        prev, cur = cur, parents[cur]
        if isinstance(cur, ast.Try):
            # only the try BODY is guarded — a parse inside an except
            # handler / else / finally raises past this Try
            if not any(prev is stmt for stmt in cur.body):
                continue
            for h in cur.handlers:
                names: Set[Optional[str]] = set()
                t = h.type
                if t is None:
                    names.add(None)
                elif isinstance(t, ast.Tuple):
                    names |= {
                        e.id if isinstance(e, ast.Name) else getattr(e, "attr", "")
                        for e in t.elts
                    }
                elif isinstance(t, ast.Name):
                    names.add(t.id)
                elif isinstance(t, ast.Attribute):
                    names.add(t.attr)
                if names & _CATCH_OK:
                    return True
    return False


class _KnobScan(ast.NodeVisitor):
    """One file: collect (knob, line, node) reads and parse sites."""

    def __init__(self, consts: Dict[str, str]):
        self.consts = consts
        self.reads: List[Tuple[str, bool, ast.AST]] = []

    def visit_Call(self, node: ast.Call) -> None:
        self._note(node)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        self._note(node)
        self.generic_visit(node)

    def _note(self, node: ast.AST) -> None:
        key = _env_read_key(node)
        if key is None:
            return
        got = _knob_name(key)
        if got is None:
            return
        name, family = got
        if family is None:  # Name indirection — resolve via constants
            resolved = self.consts.get(name, "")
            if not resolved.startswith("BST_"):
                return
            name, family = resolved, False
        self.reads.append((name, bool(family), node))


def check_source(path: str, source: str, readme_text: str) -> List[Finding]:
    findings: List[Finding] = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return findings
    supp = suppressions_at(comment_map(source), path)
    consts = _module_str_constants(tree)
    scan = _KnobScan(consts)
    scan.visit(tree)
    if not scan.reads:
        return findings
    parents = _parent_map(tree)

    def _enclosing_fn(n: ast.AST) -> Optional[ast.AST]:
        cur = n
        while cur in parents:
            cur = parents[cur]
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
        return None

    # map: (enclosing function, name assigned from an env read) -> read
    # node. Scoped per function: a parameter named `raw` in one function
    # must not be tainted by an env-read local of the same name elsewhere
    env_named: Dict[Tuple[Optional[ast.AST], str], ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                for _, _, read in scan.reads:
                    # the read (or a strip()/or-chain around it) is the value
                    if _contains(node.value, read):
                        env_named[(_enclosing_fn(node), t.id)] = read

    for name, family, read in scan.reads:
        line = getattr(read, "lineno", 0)
        if is_suppressed(supp, line, CHECKER):
            continue
        # 1) documentation
        if name not in readme_text:
            label = f"{name}* (family)" if family else name
            findings.append(
                Finding(
                    CHECKER,
                    path,
                    line,
                    f"knob {label} is read here but missing from README.md's "
                    "env-knob table — document it (value grammar + default) "
                    "or the knob is invisible to operators",
                )
            )
        # 2) parse-guard: direct int()/float() around the read, including
        # the map(int, env.split(",")) spelling
        cur = read
        while cur in parents:
            parent = parents[cur]
            if isinstance(parent, ast.Call) and isinstance(parent.func, ast.Name):
                is_parse = parent.func.id in ("int", "float") or (
                    parent.func.id == "map"
                    and parent.args
                    and isinstance(parent.args[0], ast.Name)
                    and parent.args[0].id in ("int", "float")
                )
                if is_parse and not _guarded_by_try(parent, parents):
                    findings.append(_parse_finding(path, parent, name))
                    break
            cur = parent

    # 2b) parse-guard through one local name, same function only:
    # raw = os.environ.get(...); int(raw) outside try
    if env_named:
        knob_of_read = {id(read): name for name, _, read in scan.reads}
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id in ("int", "float")
                and node.args
                and isinstance(node.args[0], ast.Name)
            ):
                key = (_enclosing_fn(node), node.args[0].id)
                if key not in env_named:
                    continue
                line = getattr(node, "lineno", 0)
                if is_suppressed(supp, line, CHECKER):
                    continue
                if not _guarded_by_try(node, parents):
                    knob = knob_of_read.get(id(env_named[key]), "BST_*")
                    findings.append(_parse_finding(path, node, knob))
    return findings


def _parse_finding(path: str, node: ast.AST, knob: str) -> Finding:
    return Finding(
        CHECKER,
        path,
        getattr(node, "lineno", 0),
        f"unguarded {getattr(node.func, 'id', 'parse')}() of knob {knob} — a "
        "typo'd value raises ValueError in the serving path; wrap in "
        "try/except and degrade to the default (the BST_SCAN_WAVE "
        "parse-guard idiom, ops/oracle.py)",
    )


def _contains(haystack: ast.AST, needle: ast.AST) -> bool:
    return any(n is needle for n in ast.walk(haystack))
