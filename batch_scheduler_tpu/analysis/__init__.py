"""In-repo static analyzer suite + runtime lock-discipline checker.

``python -m batch_scheduler_tpu.analysis`` (``make analyze``) runs the six
static checkers; ``BST_LOCKCHECK=1`` arms the runtime race detector
(lockcheck.maybe_install, called from the package __init__). See
docs/static_analysis.md for the annotation grammar and checker catalog.

Pure stdlib on purpose: the analyzers parse the tree, they never import
it, so `make analyze` needs no jax and stays fast and side-effect free.

Exports resolve lazily (PEP 562): the package __init__'s lockcheck hook
must cost one env probe on every ``import batch_scheduler_tpu``, not the
import of the whole checker suite — only ``lockcheck`` loads eagerly
(os/sys/threading), the rest on first attribute access.
"""

from .lockcheck import LockDisciplineError, lockcheck_enabled, maybe_install  # noqa: F401

_LAZY = {
    "Finding": ("findings", "Finding"),
    "CHECKS": ("runner", "CHECKS"),
    "main": ("runner", "main"),
    "run_all": ("runner", "run_all"),
}

__all__ = [
    "LockDisciplineError",
    "lockcheck_enabled",
    "maybe_install",
    *_LAZY,
]


def __getattr__(name):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    module = importlib.import_module(f".{entry[0]}", __name__)
    value = getattr(module, entry[1])
    globals()[name] = value
    return value
