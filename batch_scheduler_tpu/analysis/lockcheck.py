"""BST_LOCKCHECK=1 — the runtime half of the lock-discipline checker.

The static ``guarded-by`` checker (guards.py) is lexical: a closure
defined under ``with self._lock:`` but executed later, or a caller that
ignores a ``# lock-held:`` contract, passes statically and still races.
This module is the ``go test -race`` analog for exactly those holes:
with ``BST_LOCKCHECK=1``, every class carrying ``# guarded-by:``
annotations is instrumented so that an access to a guarded attribute
without its lock held — on an instance that another thread has provably
touched — raises ``LockDisciplineError`` carrying BOTH stacks: the
offending access and the most recent access from the other thread.

Detection is by lock ownership, not timing, so violations reproduce
deterministically: thread A touches the attribute (guarded or not),
thread B touches it without the lock → B raises, every run. Single
-threaded phases (construction, one-shot scripts) never trip it because
the "another thread has touched this instance" predicate stays false.

Wired into the chaos suite (tests/test_chaos_oracle.py) and the gateway
fuzz (tests/test_fuzz_e2e.py), which turns their thread storms into a
race detector for the annotated modules. Cost: one dict probe per
attribute access on instrumented classes plus a bounded stack capture
per guarded access — opt-in only, never on in production paths.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Set

ENV = "BST_LOCKCHECK"

_STACK_LIMIT = 12


def lockcheck_enabled() -> bool:
    """Parse-guarded BST_LOCKCHECK read: only the literal "1" enables."""
    return os.environ.get(ENV, "") == "1"


class LockDisciplineError(RuntimeError):
    """An annotated attribute was accessed without its guard lock while the
    instance was demonstrably shared across threads."""


def _is_lock_like(value) -> bool:
    return hasattr(value, "acquire") and hasattr(value, "release")


class _TrackedLock:
    """Ownership-tracking proxy around Lock/RLock/Condition.

    RLock and Condition expose ``_is_owned`` (used when present); plain
    Lock has no owner concept, so the proxy records the acquiring thread.
    Everything else forwards, so timeouts/waits/notifies behave verbatim.
    """

    def __init__(self, inner):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_owners", set())

    def acquire(self, *args, **kwargs):
        got = self._inner.acquire(*args, **kwargs)
        if got:
            self._owners.add(threading.get_ident())
        return got

    def release(self, *args, **kwargs):
        self._owners.discard(threading.get_ident())
        return self._inner.release(*args, **kwargs)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held_by_current(self) -> bool:
        is_owned = getattr(self._inner, "_is_owned", None)
        if is_owned is not None:
            try:
                return bool(is_owned())
            except Exception:
                pass
        return threading.get_ident() in self._owners

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __setattr__(self, name, value):
        setattr(self._inner, name, value)


def _capture_frames(frame) -> tuple:
    """Cheap stack capture: (filename, lineno, funcname) tuples, no source
    lookup, no string formatting — that cost runs on EVERY guarded access,
    so it must stay at raw-frame-walk speed (~1µs); rendering happens only
    on a violation (_render_frames)."""
    out = []
    depth = 0
    while frame is not None and depth < _STACK_LIMIT:
        out.append((frame.f_code.co_filename, frame.f_lineno, frame.f_code.co_name))
        frame = frame.f_back
        depth += 1
    out.reverse()
    return tuple(out)


def _render_frames(frames: tuple) -> str:
    import linecache

    lines = []
    for filename, lineno, funcname in frames:
        lines.append(f'  File "{filename}", line {lineno}, in {funcname}\n')
        src = linecache.getline(filename, lineno).strip()
        if src:
            lines.append(f"    {src}\n")
    return "".join(lines)


def _lock_held_by_frames(obj, cls, lockname: str) -> bool:
    """True if a ``# lock-held: <lockname>`` method of obj's class is on the
    current call stack — the static contract's runtime honoring."""
    lock_held: Dict[str, Set[str]] = getattr(cls, "_lockcheck_lock_held", {})
    if not lock_held:
        return False
    frame = sys._getframe(2)
    depth = 0
    while frame is not None and depth < 30:
        name = frame.f_code.co_name
        locks = lock_held.get(name)
        if locks and lockname in locks and frame.f_locals.get("self") is obj:
            return True
        frame = frame.f_back
        depth += 1
    return False


# side table for __slots__ classes (no per-instance __dict__ to stash the
# access record in); weak keys so instances die normally
_SLOT_STATE: "weakref.WeakKeyDictionary" = None  # type: ignore[assignment]


def _tracking_state(obj) -> Optional[dict]:
    try:
        return object.__getattribute__(obj, "__dict__")
    except AttributeError:
        pass
    global _SLOT_STATE
    if _SLOT_STATE is None:
        import weakref

        _SLOT_STATE = weakref.WeakKeyDictionary()
    try:
        state = _SLOT_STATE.get(obj)
        if state is None:
            state = {}
            _SLOT_STATE[obj] = state
        return state
    except TypeError:
        # slotted AND not weakref-able: nowhere safe to keep history —
        # ownership is still checked below, sharing detection is not
        return None


def _check(obj, cls, attr: str, lockname: str, op: str) -> None:
    d = _tracking_state(obj)
    if d is None:
        return
    try:
        lock = object.__getattribute__(obj, lockname)
    except AttributeError:
        lock = None
    held = False
    if isinstance(lock, _TrackedLock):
        held = lock.held_by_current()
    elif lock is not None and _is_lock_like(lock):
        # pre-instrumentation lock object: best-effort ownership
        is_owned = getattr(lock, "_is_owned", None)
        if is_owned is not None:
            try:
                held = bool(is_owned())
            except Exception:
                held = False
        else:
            held = lock.locked()
    tid = threading.get_ident()
    table = d.get("_lockcheck_access")
    if table is None:
        table = {}
        d["_lockcheck_access"] = table
    threads = d.get("_lockcheck_threads")
    if threads is None:
        threads = set()
        d["_lockcheck_threads"] = threads
    per_attr = table.setdefault(attr, {})
    # the instance is "shared" once any guarded attribute has been touched
    # from a second thread — from then on, EVERY guarded access must hold
    # the lock (the declared contract), not just accesses that happen to
    # collide on one attribute. Deterministic: no timing window involved.
    if not held and any(t != tid for t in threads):
        if not _lock_held_by_frames(obj, cls, lockname) and not _access_suppressed():
            other = next(
                ((t, v) for t, v in per_attr.items() if t != tid), None
            )
            if other is None:
                # another thread touched a different guarded attr; find its
                # most recent record for the report
                for recs in table.values():
                    other = next(
                        ((t, v) for t, v in recs.items() if t != tid), None
                    )
                    if other is not None:
                        break
            here = _render_frames(_capture_frames(sys._getframe(2)))
            other_txt = (
                f"--- most recent guarded-state access by thread "
                f"{other[0]} ({other[1][0]}) ---\n"
                f"{_render_frames(other[1][1])}"
                if other is not None
                else "--- no recorded stack for the other thread ---\n"
            )
            raise LockDisciplineError(
                f"unguarded {op} of {cls.__name__}.{attr} "
                f"(guarded-by {lockname}) on thread {tid} while the "
                f"instance is shared across threads\n"
                f"--- this access (thread {tid}, lock NOT held) ---\n{here}"
                f"{other_txt}"
            )
    threads.add(tid)
    per_attr[tid] = (op, _capture_frames(sys._getframe(2)))


def _instrument_class(cls, guarded: Dict[str, str], lock_held) -> None:
    if cls.__dict__.get("_lockcheck_instrumented"):
        return
    locknames = set(guarded.values())
    orig_setattr = cls.__setattr__
    orig_getattribute = cls.__getattribute__

    def __setattr__(self, name, value):
        if (
            name in locknames
            and _is_lock_like(value)
            and not isinstance(value, _TrackedLock)
        ):
            value = _TrackedLock(value)
        elif name in guarded:
            _check(self, cls, name, guarded[name], "write")
        orig_setattr(self, name, value)

    def __getattribute__(self, name):
        if name in guarded:
            _check(self, cls, name, guarded[name], "read")
        return orig_getattribute(self, name)

    cls.__setattr__ = __setattr__
    cls.__getattribute__ = __getattribute__
    cls._lockcheck_instrumented = True
    cls._lockcheck_lock_held = dict(lock_held)


# abs filename -> line numbers carrying an `analysis: allow(guarded-by)`
# suppression; the runtime checker honors the same escapes the static one
# does (the lock-free cancellation paths are suppressed, not special-cased)
_SUPPRESSED: Dict[str, Set[int]] = {}


def _access_suppressed() -> bool:
    frame = sys._getframe(3)  # the user frame performing the access
    lines = _SUPPRESSED.get(frame.f_code.co_filename)
    if not lines:
        return False
    # trailing on the access line, or standalone on the line above
    return frame.f_lineno in lines or (frame.f_lineno - 1) in lines


_installed = [False]


def install(root: Optional[str] = None, modules: Optional[List[str]] = None) -> List[str]:
    """Instrument every annotated class in the package. Returns the list of
    instrumented ``module:Class`` names. Idempotent."""
    import importlib

    from . import annotations as ann
    from .runner import annotated_sources, package_root

    root = root or package_root()
    instrumented: List[str] = []
    for relpath, source in annotated_sources(root, modules):
        mod_ann = ann.scan_module(relpath, source)
        for s in mod_ann.suppressions:
            if s.checker == "guarded-by":
                _SUPPRESSED.setdefault(
                    os.path.abspath(relpath), set()
                ).add(s.line)
        if not mod_ann.classes:
            continue
        modname = (
            relpath.replace(os.sep, "/")
            .rsplit(".py", 1)[0]
            .replace("/", ".")
        )
        # relpath is rooted at the repo; the import name starts at the package
        idx = modname.find("batch_scheduler_tpu")
        if idx < 0:
            continue
        modname = modname[idx:]
        try:
            module = importlib.import_module(modname)
        except Exception:
            continue
        for clsname, ca in mod_ann.classes.items():
            if not ca.guarded:
                continue
            cls = getattr(module, clsname, None)
            if cls is None:  # nested / underscore class: search module dict
                cls = next(
                    (
                        v
                        for v in vars(module).values()
                        if isinstance(v, type) and v.__name__ == clsname
                    ),
                    None,
                )
            if cls is None:
                continue
            _instrument_class(cls, ca.guarded, ca.lock_held)
            instrumented.append((cls, set(ca.guarded.values())))
    _wrap_existing_instances(instrumented)
    return [f"{cls.__module__}:{cls.__name__}" for cls, _ in instrumented]


def _wrap_existing_instances(instrumented) -> None:
    """Wrap guard locks on instances created BEFORE instrumentation
    (module singletons like trace.DEFAULT_RECORDER): without this, their
    raw locks fall back to ``lock.locked()`` ownership — true when ANY
    thread holds the lock, so a bare access racing a lock-holding writer
    (the true race moment) would be judged held. One gc sweep at install
    time; best-effort (a lock held across the swap loses its owner record
    until the next acquire, which is why install runs at session start)."""
    import gc

    by_cls = tuple(instrumented)
    if not by_cls:
        return
    classes = tuple(c for c, _ in by_cls)
    locknames = {c: names for c, names in by_cls}
    for obj in gc.get_objects():
        try:
            if not isinstance(obj, classes):
                continue
        except Exception:
            continue
        names = next(
            (locknames[c] for c in type(obj).__mro__ if c in locknames), ()
        )
        for ln in names:
            try:
                lock = object.__getattribute__(obj, ln)
            except AttributeError:
                continue
            if _is_lock_like(lock) and not isinstance(lock, _TrackedLock):
                object.__setattr__(obj, ln, _TrackedLock(lock))


def maybe_install() -> List[str]:
    """Install iff BST_LOCKCHECK=1; called from the package __init__ so one
    env var arms the race detector for any entry point (tests, sims, the
    capture script's lockcheck cycle)."""
    if not lockcheck_enabled() or _installed[0]:
        return []
    _installed[0] = True
    return install()
