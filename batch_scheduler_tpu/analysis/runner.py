"""`make analyze` — the in-repo analyzer suite's entry point.

The Python analog of the reference Makefile's ``go vet`` line, specialized
to this codebase's stated invariants (ISSUE: the contracts PRs 1-8 wrote
as prose). Six checkers, all pure stdlib AST/tokenize — no imports of the
checked modules, no jax, so the whole sweep runs in well under the 30s CI
budget:

  guarded-by   static lock discipline over annotated shared attributes
  jit-purity   host effects + donation discipline inside traced functions
  coupling     AST fingerprints over declared change-together formulas
  knobs        BST_* parse-guard discipline + README knob-table coverage
  wire         MsgType exhaustiveness on both peer dispatch paths
  metrics      bst_ namespace, single-kind, documented in observability.md

Exit 0 with no findings; exit 1 with findings rendered one per line
(file:line: [checker] message). ``--stamp-coupling`` regenerates the
coupling stamp file after an intentional coupled change. The BST_LOCKCHECK
runtime mode lives in lockcheck.py, armed by env var, not by this runner.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Iterable, List, Optional, Tuple

from . import annotations as ann
from . import coupling, guards, jit_purity, knobs, wire
from .findings import Finding, render_all

# files/dirs never scanned: seeded-violation fixtures and generated trees
_EXCLUDE_PARTS = ("analysis_fixtures", "__pycache__", ".git", "native")

# jit-purity scope: packages whose functions run under trace
_JIT_SCOPED = ("ops", "parallel", "policy")


def package_root() -> str:
    """The repo root: analysis/ -> batch_scheduler_tpu/ -> root."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _iter_py(root: str, subdirs: Iterable[str]) -> Iterable[str]:
    for sub in subdirs:
        base = os.path.join(root, sub)
        if os.path.isfile(base):
            yield base
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_PARTS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def _read(path: str) -> str:
    with open(path, "r", encoding="utf-8") as f:
        return f.read()


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, root)


def annotated_sources(
    root: str, modules: Optional[List[str]] = None
) -> List[Tuple[str, str]]:
    """(path, source) for every package file (lockcheck.install reuses this)."""
    if modules:
        paths = [os.path.join(root, m) for m in modules]
    else:
        paths = list(_iter_py(root, ["batch_scheduler_tpu"]))
    return [(p, _read(p)) for p in paths]


def run_guards(root: str) -> List[Finding]:
    findings: List[Finding] = []
    for path, source in annotated_sources(root):
        mod = ann.scan_module(path, source)
        if mod.classes or mod.guarded_globals:
            for f in guards.check_module(mod, source):
                f.path = _rel(root, f.path)
                findings.append(f)
    return findings


def run_jit_purity(root: str) -> List[Finding]:
    findings: List[Finding] = []
    subdirs = [os.path.join("batch_scheduler_tpu", d) for d in _JIT_SCOPED]
    for path in _iter_py(root, subdirs):
        for f in jit_purity.check_source(path, _read(path)):
            f.path = _rel(root, f.path)
            findings.append(f)
    return findings


def run_coupling(root: str) -> List[Finding]:
    return coupling.check(root)


def run_knobs(root: str) -> List[Finding]:
    readme = _read(os.path.join(root, "README.md"))
    findings: List[Finding] = []
    targets = list(
        _iter_py(
            root,
            ["batch_scheduler_tpu", "benchmarks", "bench.py", "__graft_entry__.py"],
        )
    )
    for path in targets:
        for f in knobs.check_source(path, _read(path), readme):
            f.path = _rel(root, f.path)
            findings.append(f)
    return findings


def run_wire(root: str) -> List[Finding]:
    svc = os.path.join(root, "batch_scheduler_tpu", "service")
    protocol_path = os.path.join(svc, "protocol.py")
    peers = [
        ("server dispatch", os.path.join(svc, "server.py")),
        ("client annotation", os.path.join(svc, "client.py")),
    ]
    findings = wire.check_wire(
        _rel(root, protocol_path),
        _read(protocol_path),
        [(role, _rel(root, p), _read(p)) for role, p in peers],
    )
    return findings


def run_metrics(root: str) -> List[Finding]:
    obs = _read(os.path.join(root, "docs", "observability.md"))
    files = [
        (_rel(root, p), _read(p))
        for p in _iter_py(root, ["batch_scheduler_tpu"])
        # the metrics module itself is the registry implementation: its
        # counter()/gauge()/histogram() defs and internal calls are plumbing
        if os.path.basename(p) != "metrics.py"
    ]
    return wire.check_metrics(files, obs)


CHECKS = {
    "guarded-by": run_guards,
    "jit-purity": run_jit_purity,
    "coupling": run_coupling,
    "knobs": run_knobs,
    "wire": run_wire,
    "metrics": run_metrics,
}


def suppression_inventory(root: str) -> Tuple[List[ann.Suppression], List[Finding]]:
    """Every allow() suppression in the scanned tree; reasonless ones are
    findings — the gate lands with zero unreviewed escapes."""
    supps: List[ann.Suppression] = []
    findings: List[Finding] = []
    scoped = ["batch_scheduler_tpu", "benchmarks", "bench.py", "__graft_entry__.py"]
    for path in _iter_py(root, scoped):
        source = _read(path)
        mod_supps = ann.suppressions_at(ann.comment_map(source), path)
        for s in mod_supps.values():
            s.path = _rel(root, s.path)
            supps.append(s)
            if not s.reason:
                findings.append(
                    Finding(
                        "suppressions",
                        s.path,
                        s.line,
                        f"allow({s.checker}) without a reason — every "
                        "suppression must say why (docs/static_analysis.md)",
                    )
                )
    return supps, findings


def run_all(root: Optional[str] = None, checks: Optional[List[str]] = None) -> Tuple[List[Finding], List[ann.Suppression]]:
    root = root or package_root()
    findings: List[Finding] = []
    for name, fn in CHECKS.items():
        if checks and name not in checks:
            continue
        findings.extend(fn(root))
    supps, supp_findings = suppression_inventory(root)
    findings.extend(supp_findings)
    return findings, supps


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m batch_scheduler_tpu.analysis",
        description="in-repo invariant analyzer suite (make analyze)",
    )
    parser.add_argument("--root", default=None, help="repo root to scan")
    parser.add_argument(
        "--check",
        action="append",
        choices=sorted(CHECKS),
        help="run only the named checker(s)",
    )
    parser.add_argument(
        "--stamp-coupling",
        action="store_true",
        help="regenerate coupling_stamps.json from the current tree "
        "(after verifying the group via the bit-identity gates)",
    )
    args = parser.parse_args(argv)
    root = args.root or package_root()

    if args.stamp_coupling:
        stamps = coupling.stamp(root)
        n = sum(len(v) for v in stamps.values())
        print(f"stamped {n} coupled members across {len(stamps)} groups "
              f"-> {coupling.STAMP_FILE}")
        return 0

    t0 = time.monotonic()
    findings, supps = run_all(root, args.check)
    dt = time.monotonic() - t0
    if supps:
        print(f"# {len(supps)} reviewed suppression(s):", file=sys.stderr)
        for s in supps:
            print(
                f"#   {s.path}:{s.line}: allow({s.checker}) {s.reason}",
                file=sys.stderr,
            )
    if findings:
        print(render_all(findings))
        print(
            f"analyze: {len(findings)} finding(s) in {dt:.2f}s",
            file=sys.stderr,
        )
        return 1
    print(f"analyze: clean ({dt:.2f}s)", file=sys.stderr)
    return 0
