"""Checker ``jit-purity`` — host effects and donation discipline in traced code.

Inside functions that jax traces (jit/shard_map/scan bodies/pallas kernels),
host-side effects execute once at trace time and silently never again —
the class of bug where an ``os.environ`` read or ``time.time()`` call gets
baked into a compiled executable and the knob stops responding. This
checker forbids, lexically inside any traced function in the scoped
packages (ops/, parallel/, policy/):

  * ``os.environ`` / ``os.getenv`` / ``os.putenv`` reads
  * ``time.*`` calls (trace-time constants masquerading as clocks)
  * the stdlib ``random`` module (``jax.random`` / ``np.asarray`` are fine)
  * ``print(...)`` (host I/O at trace time; use ``jax.debug.print``)
  * ``open(...)`` and ``global`` mutation (host state from traced code)

Donation discipline (PR 4): callables jitted with ``donate_argnums`` consume
their donated operands — the buffer behind the handle is gone after
dispatch. The checker scans each file for ``jax.jit(..., donate_argnums=…)``
bindings and flags any later lexical *use* of a name that was passed in a
donated position of a direct call to such a binding (the PR 4 donation
misfire class: reusing ``alloc`` after ``_batch_blob_donated(alloc, …)``).

Traced-context discovery (lexical, per file):
  * ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)`` decorated defs
  * ``name = jax.jit(fn, ...)`` bindings mark ``fn``'s def
  * functions passed to ``lax.scan`` / ``shard_map`` / ``pl.pallas_call``
    / ``jax.vmap`` / ``lax.cond`` / ``lax.while_loop``

Suppress one line with ``# analysis: allow(jit-purity) <reason>``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .annotations import comment_map, is_suppressed, suppressions_at
from .findings import Finding

CHECKER = "jit-purity"

_TRACING_CALLS = {
    "scan",
    "shard_map",
    "pallas_call",
    "vmap",
    "pmap",
    "cond",
    "while_loop",
    "fori_loop",
    "switch",
    "checkpoint",
    "remat",
    "custom_vjp",
}

_BANNED_MODULES = {"time", "random"}


def _callee_name(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """True for ``jax.jit``/``jit`` or ``partial(jax.jit, ...)`` shapes."""
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        if _callee_name(fn) in ("partial", "wraps"):
            return any(_is_jit_expr(a) for a in node.args)
        return _is_jit_expr(fn)
    return False


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """donate_argnums of a ``jax.jit(...)`` call, if statically visible."""
    if not _is_jit_expr(call.func) and not _is_jit_expr(call):
        return None
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Tuple):
                out = []
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                        out.append(elt.value)
                return tuple(out)
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
    return None


def _collect_traced_names(tree: ast.AST) -> Tuple[Set[str], Dict[str, Tuple[int, ...]]]:
    """Names of functions that end up traced + donating jit bindings."""
    traced: Set[str] = set()
    donors: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            callee = _callee_name(node.func)
            if callee in _TRACING_CALLS or _is_jit_expr(node.func):
                for a in node.args[:1] if callee != "pallas_call" else node.args:
                    if isinstance(a, ast.Name):
                        traced.add(a.id)
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donated_positions(node.value)
            if pos:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donors[t.id] = pos
                # the wrapped fn is traced too
                for a in node.value.args:
                    if isinstance(a, ast.Name):
                        traced.add(a.id)
    # alias propagation: `fn = _donated if cond else _plain` (the dispatch
    # ladder's spelling, ops/oracle.py) — calls through the alias MAY
    # donate, so reuse after them is flagged conservatively
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.IfExp):
            branches = (node.value.body, node.value.orelse)
            hit = [
                donors[b.id]
                for b in branches
                if isinstance(b, ast.Name) and b.id in donors
            ]
            if hit:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donors[t.id] = hit[0]
    return traced, donors


def _is_traced_def(fn: ast.AST, traced_names: Set[str]) -> bool:
    if fn.name in traced_names:
        return True
    for dec in fn.decorator_list:
        if _is_jit_expr(dec):
            return True
        if isinstance(dec, ast.Call) and _callee_name(dec.func) in _TRACING_CALLS:
            return True
    return False


class _PurityVisitor(ast.NodeVisitor):
    def __init__(self, path: str, findings: List[Finding], supp, context: str):
        self.path = path
        self.findings = findings
        self.supp = supp
        self.context = context

    def _flag(self, node: ast.AST, msg: str) -> None:
        line = getattr(node, "lineno", 0)
        if is_suppressed(self.supp, line, CHECKER):
            return
        self.findings.append(
            Finding(CHECKER, self.path, line, f"{self.context}: {msg}")
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.value, ast.Name):
            root = node.value.id
            if root == "os" and node.attr in ("environ", "getenv", "putenv"):
                self._flag(node, f"os.{node.attr} inside a traced function "
                                 "(baked in at trace time)")
            elif root in _BANNED_MODULES:
                self._flag(
                    node,
                    f"host module '{root}.{node.attr}' inside a traced "
                    "function (trace-time constant, not a runtime effect)",
                )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Name) and node.func.id in ("print", "open"):
            self._flag(
                node,
                f"'{node.func.id}(...)' inside a traced function "
                "(host I/O at trace time; use jax.debug.print)",
            )
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        self._flag(
            node,
            f"global mutation of {', '.join(node.names)} inside a traced "
            "function (host state from traced code)",
        )


class _DonationVisitor(ast.NodeVisitor):
    """Within one function: flag lexical reuse of donated operands."""

    def __init__(self, path, findings, supp, donors: Dict[str, Tuple[int, ...]],
                 context: str):
        self.path = path
        self.findings = findings
        self.supp = supp
        self.donors = donors
        self.context = context
        # donated name -> (donating call line, donor fn name)
        self.consumed: Dict[str, Tuple[int, str]] = {}

    def visit_Call(self, node: ast.Call) -> None:
        callee = _callee_name(node.func)
        if callee in self.donors:
            # consumed from the call's LAST line: the donating call's own
            # argument Names (which may sit on later lines of a multi-line
            # call) must not trip the reuse flag
            end = getattr(node, "end_lineno", node.lineno) or node.lineno
            for pos in self.donors[callee]:
                if pos < len(node.args) and isinstance(node.args[pos], ast.Name):
                    name = node.args[pos].id
                    self.consumed[name] = (end, callee)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Store):
            # rebinding a name makes it safe again
            self.consumed.pop(node.id, None)
            return
        hit = self.consumed.get(node.id)
        if hit and node.lineno > hit[0]:
            line = node.lineno
            if not is_suppressed(self.supp, line, CHECKER):
                self.findings.append(
                    Finding(
                        CHECKER,
                        self.path,
                        line,
                        f"{self.context}: '{node.id}' used after being "
                        f"donated to {hit[1]} (line {hit[0]}) — the buffer "
                        "is consumed by dispatch (PR 4 donation discipline)",
                    )
                )
                # report once per name
                self.consumed.pop(node.id, None)


def check_source(path: str, source: str) -> List[Finding]:
    findings: List[Finding] = []
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return findings
    supp = suppressions_at(comment_map(source), path)
    traced_names, donors = _collect_traced_names(tree)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _is_traced_def(node, traced_names):
                v = _PurityVisitor(path, findings, supp, node.name)
                for stmt in node.body:
                    v.visit(stmt)
            if donors:
                dv = _DonationVisitor(path, findings, supp, donors, node.name)
                for stmt in node.body:
                    dv.visit(stmt)
    # nested defs are reachable both standalone (ast.walk) and through
    # their parent's visitor — dedupe identical findings
    seen = set()
    out: List[Finding] = []
    for f in findings:
        key = (f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out
