"""Finding/report plumbing shared by every checker in the suite."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List


@dataclass
class Finding:
    checker: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


def render_all(findings: List[Finding]) -> str:
    return "\n".join(f.render() for f in findings)
