"""Checkers ``wire`` + ``metrics`` — protocol and telemetry exhaustiveness.

**wire**: every ``MsgType`` member declared in service/protocol.py must be
either referenced (handled) in the server dispatch file AND the client
annotation-path file, or explicitly waived in that file with a

    # msgtype-ignored: <NAME> <reason>

comment. The POLICY_INFO frame (PR 8) shipped exactly this way — a new
frame type added to one peer with the other peer's handling hand-audited;
this makes adding MsgType 14 fail the gate until both paths say something.

**metrics**: every metric registered anywhere in the package must be
``bst_``-prefixed, documented in docs/observability.md, and registered
under a single metric kind (counter/gauge/histogram) — the Registry
raises TypeError on kind conflicts only at runtime, on whichever path
loses the race. Registration sites with a non-constant name must carry
``# analysis: allow(metrics) <reason>``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .annotations import comment_map, is_suppressed, suppressions_at
from .findings import Finding

WIRE = "wire"
METRICS = "metrics"

MSG_IGNORED_RE = re.compile(r"#\s*msgtype-ignored:\s*([A-Z_0-9]+)\s+(\S.*)")

_METRIC_METHODS = ("counter", "gauge", "histogram")


def msgtype_members(protocol_source: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    tree = ast.parse(protocol_source)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "MsgType":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Constant
                ):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = stmt.lineno
    return out


def _referenced_msgtypes(source: str) -> Set[str]:
    refs: Set[str] = set()
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return refs
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            v = node.value
            if (isinstance(v, ast.Name) and v.id == "MsgType") or (
                isinstance(v, ast.Attribute) and v.attr == "MsgType"
            ):
                refs.add(node.attr)
    return refs


def _ignored_msgtypes(source: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for text in comment_map(source).values():
        m = MSG_IGNORED_RE.search(text)
        if m:
            out[m.group(1)] = m.group(2).strip()
    return out


def check_wire(
    protocol_path: str,
    protocol_source: str,
    peers: List[Tuple[str, str, str]],
) -> List[Finding]:
    """peers: (role, path, source) for the server and client files."""
    findings: List[Finding] = []
    members = msgtype_members(protocol_source)
    if not members:
        findings.append(
            Finding(WIRE, protocol_path, 0, "no MsgType class found in protocol")
        )
        return findings
    for role, path, source in peers:
        refs = _referenced_msgtypes(source)
        ignored = _ignored_msgtypes(source)
        for name, line in sorted(members.items()):
            if name in refs or name in ignored:
                continue
            findings.append(
                Finding(
                    WIRE,
                    path,
                    0,
                    f"MsgType.{name} (protocol.py:{line}) is neither handled "
                    f"nor explicitly waived on the {role} path — handle it or "
                    f"add '# msgtype-ignored: {name} <reason>' (both peers "
                    "must stay exhaustive; the POLICY_INFO lesson)",
                )
            )
    return findings


def collect_metric_registrations(
    path: str, source: str
) -> Tuple[List[Tuple[str, str, int, int]], List[Tuple[int, int]]]:
    """([(name, kind, line, end_line)], [(line, end_line) non-constant])."""
    out: List[Tuple[str, str, int, int]] = []
    dynamic: List[Tuple[int, int]] = []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return out, dynamic
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_METHODS
            and node.args
        ):
            end = getattr(node, "end_lineno", node.lineno) or node.lineno
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                out.append((first.value, node.func.attr, node.lineno, end))
            else:
                dynamic.append((node.lineno, end))
    return out, dynamic


def check_metrics(
    files: List[Tuple[str, str]], observability_text: str
) -> List[Finding]:
    findings: List[Finding] = []
    kinds: Dict[str, Set[str]] = {}
    sites: Dict[str, List[Tuple[str, int, str]]] = {}
    for path, source in files:
        supp = suppressions_at(comment_map(source), path)
        regs, dynamic = collect_metric_registrations(path, source)

        def _span_suppressed(line: int, end: int) -> bool:
            # trailing allow() comments may sit on any line the call spans
            return any(
                is_suppressed(supp, l, METRICS) for l in range(line, end + 1)
            )

        for line, end in dynamic:
            if not _span_suppressed(line, end):
                findings.append(
                    Finding(
                        METRICS,
                        path,
                        line,
                        "metric registered under a non-constant name — the "
                        "registry can't be audited statically; add "
                        "'# analysis: allow(metrics) <reason>' naming where "
                        "the names are enumerated",
                    )
                )
        for name, kind, line, end in regs:
            if _span_suppressed(line, end):
                continue
            kinds.setdefault(name, set()).add(kind)
            sites.setdefault(name, []).append((path, line, kind))
            if not name.startswith("bst_"):
                findings.append(
                    Finding(
                        METRICS,
                        path,
                        line,
                        f"metric '{name}' is not bst_-prefixed — every metric "
                        "this codebase exports shares the bst_ namespace",
                    )
                )
            if name not in observability_text:
                findings.append(
                    Finding(
                        METRICS,
                        path,
                        line,
                        f"metric '{name}' is not documented in "
                        "docs/observability.md — add it to the metrics "
                        "catalog (name, kind, meaning)",
                    )
                )
    for name, ks in sorted(kinds.items()):
        if len(ks) > 1:
            path, line, _ = sites[name][0]
            findings.append(
                Finding(
                    METRICS,
                    path,
                    line,
                    f"metric '{name}' is registered as multiple kinds "
                    f"({', '.join(sorted(ks))}) — the Registry raises "
                    "TypeError at runtime on whichever path registers second",
                )
            )
    return findings
