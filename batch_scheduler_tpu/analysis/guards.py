"""Checker ``guarded-by`` — static lock-discipline verification.

For every attribute carrying a ``# guarded-by: <lock>`` annotation
(annotations.scan_module), every lexical read/write of ``self.<attr>``
must occur inside ``with self.<lock>:`` (or inside a method whose ``def``
line documents ``# lock-held: <lock>``). Module-level guarded globals are
checked the same way against module-level ``with <lock>:`` blocks.

The check is lexical, the same approximation clang's thread-safety
analysis makes: a closure defined under a ``with`` is treated as guarded
even though it may run later. The BST_LOCKCHECK runtime mode (lockcheck.py)
closes that gap dynamically, which is why both exist.

Exemptions baked into the discipline (documented in
docs/static_analysis.md):
  * ``__init__``/``__del__`` bodies — construction and finalization are
    single-threaded by contract.
  * methods annotated ``# lock-held: <lock>`` hold that lock throughout.
  * ``# analysis: allow(guarded-by) <reason>`` suppresses one line.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from .annotations import (
    ModuleAnnotations,
    comment_map,
    is_suppressed,
    suppressions_at,
)
from .findings import Finding

CHECKER = "guarded-by"

# methods whose body runs before/after the instance is shared
_SINGLE_THREADED = {"__init__", "__del__", "__post_init__"}


def _with_locks(node: ast.With, *, self_scope: bool) -> Set[str]:
    """Lock names a ``with`` statement acquires (self.X or bare globals)."""
    out: Set[str] = set()
    for item in node.items:
        ctx = item.context_expr
        # unwrap common acquire forms: with self._lock, with LOCK,
        # with self._cond (Condition is lock-like)
        if isinstance(ctx, ast.Call):
            # e.g. with self._lock.acquire_timeout(...): not a guard we track
            continue
        if self_scope and isinstance(ctx, ast.Attribute):
            if isinstance(ctx.value, ast.Name) and ctx.value.id == "self":
                out.add(ctx.attr)
        if isinstance(ctx, ast.Name):
            out.add(ctx.id)
    return out


class _MethodChecker(ast.NodeVisitor):
    """Walks one function body tracking the lexically-held lock set."""

    def __init__(
        self,
        guarded: Dict[str, str],
        held: Set[str],
        findings: List[Finding],
        path: str,
        supp,
        *,
        self_scope: bool,
        context: str,
    ):
        self.guarded = guarded
        self.held = set(held)
        self.findings = findings
        self.path = path
        self.supp = supp
        self.self_scope = self_scope
        self.context = context

    def visit_With(self, node: ast.With) -> None:
        acquired = _with_locks(node, self_scope=self.self_scope)
        # the context expressions themselves are evaluated unguarded
        for item in node.items:
            self.visit(item.context_expr)
        before = set(self.held)
        self.held |= acquired
        for stmt in node.body:
            self.visit(stmt)
        self.held = before

    visit_AsyncWith = visit_With

    def _flag(self, node: ast.AST, attr: str, lock: str) -> None:
        line = getattr(node, "lineno", 0)
        if is_suppressed(self.supp, line, CHECKER):
            return
        self.findings.append(
            Finding(
                CHECKER,
                self.path,
                line,
                f"{self.context}: access to '{attr}' (guarded-by {lock}) "
                f"outside 'with {'self.' if self.self_scope else ''}{lock}'",
            )
        )

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            self.self_scope
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.guarded
        ):
            lock = self.guarded[node.attr]
            if lock not in self.held:
                self._flag(node, f"self.{node.attr}", lock)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if not self.self_scope and node.id in self.guarded:
            lock = self.guarded[node.id]
            if lock not in self.held:
                self._flag(node, node.id, lock)
        self.generic_visit(node)


def _check_function(
    fn: ast.AST,
    guarded: Dict[str, str],
    lock_held: Dict[str, Set[str]],
    findings: List[Finding],
    path: str,
    supp,
    *,
    self_scope: bool,
    owner: str,
) -> None:
    name = fn.name
    if self_scope and name in _SINGLE_THREADED:
        return
    held = set(lock_held.get(name, ()))
    checker = _MethodChecker(
        guarded,
        held,
        findings,
        path,
        supp,
        self_scope=self_scope,
        context=f"{owner}.{name}" if owner else name,
    )
    for stmt in fn.body:
        checker.visit(stmt)


def check_module(mod: ModuleAnnotations, source: str) -> List[Finding]:
    findings: List[Finding] = []
    if mod.tree is None:
        return findings
    supp = suppressions_at(comment_map(source), mod.path)

    # class-scope: guarded self attributes
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name in mod.classes:
            ca = mod.classes[node.name]
            if not ca.guarded:
                continue
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    _check_function(
                        sub,
                        ca.guarded,
                        ca.lock_held,
                        findings,
                        mod.path,
                        supp,
                        self_scope=True,
                        owner=node.name,
                    )

    # module-scope: guarded globals, checked across every top-level function
    # and class method in the file (globals are reachable from anywhere).
    # Only outermost defs are seeded — the visitor descends into closures
    # itself, so nested functions are not double-reported.
    if mod.guarded_globals:
        tops: List[ast.AST] = []
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                tops.append(node)
            elif isinstance(node, ast.ClassDef):
                tops.extend(
                    sub
                    for sub in node.body
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
                )
        for node in tops:
            _check_function(
                node,
                mod.guarded_globals,
                mod.lock_held_funcs,
                findings,
                mod.path,
                supp,
                self_scope=False,
                owner="",
            )
    return findings
