"""Machine-readable invariant annotations — the grammar every checker shares.

PRs 1-8 stated their concurrency and coupling contracts in prose comments
("guarded by _state_lock", "change all of them together"). This module is
the first half of mechanizing them: a tokenize+AST scanner that turns
trailing comments into a structured registry the static checkers (and the
BST_LOCKCHECK runtime mode) consume.

Grammar (docs/static_analysis.md has the full catalog):

``# guarded-by: <lock>``
    Trailing comment on a ``self.<attr> = ...`` assignment (class scope) or
    a module-level ``NAME = ...`` assignment. Declares that every read or
    write of the attribute/global must happen while ``self.<lock>`` (or the
    module-level ``<lock>``) is held — lexically inside ``with <lock>:`` for
    the static checker, dynamically owned for the runtime checker.

``# lock-held: <lock>[, <lock2>]``
    Trailing comment on a ``def`` line. The method documents that its
    CALLERS hold the named lock(s); its body is checked as if the locks
    were held. The runtime checker verifies the claim by walking the call
    stack.

``# analysis: allow(<checker>) <reason>``
    Suppression, trailing on the flagged line. A reason is mandatory —
    the runner inventories every suppression and fails on reasonless ones,
    so the gate lands with zero unreviewed escapes.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# the marker may open the comment or follow prose ("# heap; guarded-by: x")
GUARDED_BY_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z_0-9]*)")
LOCK_HELD_RE = re.compile(
    r"lock-held:\s*([A-Za-z_][A-Za-z_0-9]*(?:\s*,\s*[A-Za-z_][A-Za-z_0-9]*)*)"
)
ALLOW_RE = re.compile(r"#\s*analysis:\s*allow\(([a-z0-9_-]+)\)\s*(.*)")


@dataclass
class Suppression:
    path: str
    line: int
    checker: str
    reason: str


@dataclass
class ClassAnnotations:
    """Annotations for one class: attr name -> guard lock attr name."""

    module: str
    name: str
    guarded: Dict[str, str] = field(default_factory=dict)
    # method name -> set of lock attr names the caller holds
    lock_held: Dict[str, Set[str]] = field(default_factory=dict)
    lines: Dict[str, int] = field(default_factory=dict)  # attr -> decl line


@dataclass
class ModuleAnnotations:
    """One scanned file: class annotations plus module-global guards."""

    path: str
    classes: Dict[str, ClassAnnotations] = field(default_factory=dict)
    # module-level global name -> module-level lock global name
    guarded_globals: Dict[str, str] = field(default_factory=dict)
    global_lines: Dict[str, int] = field(default_factory=dict)
    # module-level function name -> lock globals the caller holds
    lock_held_funcs: Dict[str, Set[str]] = field(default_factory=dict)
    suppressions: List[Suppression] = field(default_factory=list)
    tree: Optional[ast.AST] = None


def comment_map(source: str) -> Dict[int, str]:
    """line number -> comment text for every comment token in the file."""
    out: Dict[int, str] = {}
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except tokenize.TokenError:
        pass
    return out


def suppressions_at(comments: Dict[int, str], path: str) -> Dict[Tuple[int, str], Suppression]:
    """(line, checker) -> Suppression for every allow() comment."""
    out: Dict[Tuple[int, str], Suppression] = {}
    for line, text in comments.items():
        m = ALLOW_RE.search(text)
        if m:
            out[(line, m.group(1))] = Suppression(
                path=path, line=line, checker=m.group(1), reason=m.group(2).strip()
            )
    return out


def is_suppressed(
    supp: Dict[Tuple[int, str], Suppression], line: int, checker: str
) -> bool:
    # trailing on the flagged line, or standalone on the line above
    return (line, checker) in supp or (line - 1, checker) in supp


def _assign_target_lines(node: ast.stmt):
    """Yield (kind, name, line) for annotatable assignment targets.

    kind is "self" for ``self.X = ...`` targets, "global" for module-level
    ``NAME = ...`` targets.
    """
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    for t in targets:
        if (
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
        ):
            yield "self", t.attr, node.lineno
        elif isinstance(t, ast.Name):
            yield "global", t.id, node.lineno


def scan_module(path: str, source: Optional[str] = None) -> ModuleAnnotations:
    """Parse one file's annotations into a ModuleAnnotations registry."""
    if source is None:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
    mod = ModuleAnnotations(path=path)
    comments = comment_map(source)
    mod.suppressions = list(suppressions_at(comments, path).values())
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return mod
    mod.tree = tree

    def matching_comment(node: ast.stmt, regex) -> Optional[re.Match]:
        # trailing annotations attach to any line the statement spans — a
        # multi-line call keeps its annotation next to the closing paren.
        # EVERY comment in the span is searched: an unrelated inline
        # comment on an earlier line must not shadow the marker
        end = getattr(node, "end_lineno", node.lineno) or node.lineno
        for line in range(node.lineno, end + 1):
            text = comments.get(line)
            if text:
                m = regex.search(text)
                if m:
                    return m
        return None

    # module-level guarded globals + lock-held functions
    for node in tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            m = matching_comment(node, GUARDED_BY_RE)
            if m:
                for kind, name, line in _assign_target_lines(node):
                    if kind == "global":
                        mod.guarded_globals[name] = m.group(1)
                        mod.global_lines[name] = line
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            text = comments.get(node.lineno)
            if text:
                m = LOCK_HELD_RE.search(text)
                if m:
                    mod.lock_held_funcs[node.name] = {
                        s.strip() for s in m.group(1).split(",")
                    }

    # class-scope annotations: guarded attrs declared anywhere inside the
    # class body (typically __init__), lock-held methods on def lines
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ca = ClassAnnotations(module=path, name=node.name)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                m = matching_comment(sub, GUARDED_BY_RE)
                if m:
                    for kind, name, line in _assign_target_lines(sub):
                        if kind == "self":
                            ca.guarded[name] = m.group(1)
                            ca.lines[name] = line
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                text = comments.get(sub.lineno)
                if text:
                    m = LOCK_HELD_RE.search(text)
                    if m:
                        ca.lock_held[sub.name] = {
                            s.strip() for s in m.group(1).split(",")
                        }
        if ca.guarded or ca.lock_held:
            mod.classes[node.name] = ca
    return mod
